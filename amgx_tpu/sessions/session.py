"""Streaming solve sessions: transient PDEs as a first-class serve
workload.

AmgX's dominant production pattern is time stepping: the same sparsity
pattern solved every step with new coefficients (implicit CFD, heat,
reservoir).  The one-shot serve path already amortizes setup across
requests *of one instant*; a session amortizes it across *time* — a
client registers a sparsity fingerprint once and then streams
``(values, b)`` pairs:

  open_session(A) ── registers (ro, ci, n, fingerprint) once
       │
       ▼
  step(values_k, b_k)            per step, per session:
       │ 1. prestage  — host-side resetup prep (value array coercion,
       │               finite validation) runs WHILE the previous
       │               step-group is still solving on the device —
       │               this is the resetup/solve overlap, measured by
       │               ``resetup_overlap_s``;
       │ 2. resolve   — the previous step's result arrives through the
       │               group's ONE shared host sync; its x becomes the
       │               warm start (masked: a non-converged step's x is
       │               never reused — zeros instead);
       │ 3. submit    — the values-only fast path into the serve layer
       │               (``_host`` tuple: no per-step pattern hashing),
       │               x0 = warm start, dispatched without a fetch.
       ▼
  SessionManager.step_all(...)   B sessions sharing a fingerprint step
                                 in lockstep: their steps form ONE
                                 bucketed vmapped group — one hierarchy,
                                 one compiled program, one host sync per
                                 flushed step-group.

The hierarchy itself rides the existing serve machinery: one setup per
(fingerprint, config) in the hierarchy cache, per-step coefficients
flowing through the traced batch-params rebuild (RAP-plan re-execution
+ ``replace_values`` gather maps inside the compiled program).  Every
``resetup_every`` steps the session additionally refreshes the CACHED
template solver through :meth:`BatchedSolveService.resetup_entry` so
quarantine retries, store exports, and the PR 8 spectral-bound cache
(``reestimate_eigs`` cadence) track the streamed values instead of the
step-0 coefficients.

Persistence: :meth:`SolveSession.save` writes a small manifest (step
counter, warm-start x, status, the registered pattern) into the
:class:`~amgx_tpu.store.store.ArtifactStore`; the hierarchy is the
serve layer's existing warm-boot export.  A drained worker's sessions
therefore survive a restart: ``warm_boot()`` + :meth:`SessionManager
.restore` resume the stream at the saved step with ZERO coarsening
calls and a bitwise-identical hierarchy (tests/test_sessions.py).

Observability: the manager registers a ``sessions`` telemetry source
(``amgx_session_*`` families), every sampled step records a
``session_step`` root span with ``resetup`` → ``pad`` → ``dispatch``
→ ``device`` → ``fetch`` children in the shared trace ring, and every
resolved step lands a ``path="session_step"`` flight record.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Optional

import numpy as np

from amgx_tpu.core.errors import StoreError
from amgx_tpu.serve.service import (
    BatchedSolveService,
    _host_csr,
    _resolve_dtype,
)
from amgx_tpu.telemetry import get_registry, telemetry_enabled, tracing

SESSION_KIND = "solve_session"
# sessions are keyed in the store without a dtype axis (the real dtype
# lives in the manifest); this constant fills entry_key's dtype slot
_SESSION_KEY_DTYPE = "session"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class StepTicket:
    """Handle for one streamed step.  ``result()`` resolves through
    the owning session so warm-start state updates exactly once no
    matter who asks first (the session's next ``step`` or the
    client)."""

    __slots__ = ("session", "step", "ticket", "resetup_s", "_trace",
                 "_t0", "_res", "_err")

    def __init__(self, session: "SolveSession", step: int, ticket,
                 resetup_s: float, trace, t0: float):
        self.session = session
        self.step = step
        self.ticket = ticket
        self.resetup_s = resetup_s
        self._trace = trace
        self._t0 = t0
        self._res = None
        self._err = None

    def done(self) -> bool:
        return (
            self._res is not None
            or self._err is not None
            or self.ticket.done()
        )

    def result(self):
        self.session._resolve_ticket(self)
        if self._err is not None:
            raise self._err
        return self._res

    def _service_ticket(self):
        """The underlying serve SolveTicket (unwraps a gateway
        ticket), for the overlap probe."""
        return getattr(self.ticket, "_ticket", self.ticket)


class SolveSession:
    """One streamed transient-PDE solve: a registered sparsity pattern
    plus per-step warm-start state.  Created by
    :meth:`SessionManager.open` / :meth:`SessionManager.restore` (or
    ``gateway.open_session``), never directly."""

    def __init__(self, manager: "SessionManager", session_id: str,
                 host: tuple, dtype, tenant: str, lane: str,
                 deadline_s: Optional[float] = None):
        self.manager = manager
        self.session_id = session_id
        # (row_offsets, col_indices, n, raw fingerprint): the one-time
        # registration that makes every step a values-only submit
        ro, ci, n, raw_fp = host
        self._ro = np.asarray(ro)
        self._ci = np.asarray(ci)
        self.n = int(n)
        self.nnz = int(self._ci.shape[0])
        self.fingerprint = raw_fp
        self.dtype, self._dtype_s = _resolve_dtype(dtype)
        self.tenant = tenant
        self.lane = lane
        self.deadline_s = deadline_s
        self.step_idx = 0  # steps RESOLVED so far
        self.closed = False
        self._last_x: Optional[np.ndarray] = None
        self._last_status: Optional[int] = None
        self._last_iters: Optional[int] = None
        self._pending: Optional[StepTicket] = None
        self._staged = None  # (values, b, t0, resetup_s, ctx)
        # padded fingerprint memo (the hierarchy-cache key); resolved
        # on first use through the service's pattern cache
        self._padded_fp: Optional[str] = None

    # -- warm-start state ----------------------------------------------

    def _x0_for_next(self):
        """(x0, warm): the previous step's solution when it CONVERGED,
        else zeros — a diverged step's x must never poison the next
        step's initial guess."""
        if self._last_x is not None and self._last_status == 0:
            return self._last_x, True
        return None, False

    @property
    def placement_device(self) -> Optional[str]:
        """Label of the device the serve placement policy holds this
        session's hierarchy on (cache-affinity routing, PR 10): every
        step of the session — one fingerprint — routes there, so a
        streamed hierarchy never migrates between chips mid-stream.
        None before the first step lands, or under a non-routing
        policy (single-device, mesh)."""
        fp = self._padded_fp
        if fp is None:
            return None
        return self.manager.service.placement.device_for(fp)

    @property
    def last_x(self) -> Optional[np.ndarray]:
        """The last resolved step's solution (converged or not) —
        the implicit-Euler client's state vector.  Warm-start MASKING
        is separate: ``_x0_for_next`` only reuses a CONVERGED x."""
        return self._last_x

    @property
    def last_status(self) -> Optional[int]:
        return self._last_status

    @property
    def last_iterations(self) -> Optional[int]:
        return self._last_iters

    # -- the three step phases -----------------------------------------

    def _coerce_b(self, b) -> np.ndarray:
        b = np.ascontiguousarray(
            np.asarray(b, dtype=self.dtype).reshape(-1)
        )
        if b.shape[0] != self.n:
            raise ValueError(
                f"session {self.session_id}: expected length-{self.n} "
                f"rhs, got {b.shape[0]}"
            )
        return b

    def prestage(self, values, b=None):
        """Phase 1 — host-side resetup prep for the NEXT step, safe to
        run (and designed to run) while the previous step-group is
        still solving on the device.  Coerces the coefficient/rhs
        arrays and pre-validates them; the time spent here while the
        previous group is dispatched-but-unfetched is the measured
        resetup/solve overlap.

        ``b`` may be deferred to :meth:`commit` — or passed as a
        CALLABLE of the session, evaluated at commit time AFTER the
        previous step resolves.  That is the implicit-Euler shape:
        ``b_k`` depends on ``x_{k-1}``, but the coefficient resetup
        does not, so the values prep still overlaps the in-flight
        solve (``sess.prestage(vals, lambda s: s.last_x)``)."""
        if self.closed:
            raise RuntimeError(f"session {self.session_id} is closed")
        if self._staged is not None:
            raise RuntimeError(
                "prestage called twice without a commit; a session "
                "pipelines at depth one (x0 depends on the previous x)"
            )
        t0 = time.perf_counter()
        ctx = tracing.new_trace()
        overlapped = self._previous_in_flight()
        values = np.ascontiguousarray(
            np.asarray(values, dtype=self.dtype).reshape(-1)
        )
        if values.shape[0] != self.nnz:
            raise ValueError(
                f"session {self.session_id}: expected {self.nnz} "
                f"coefficients, got {values.shape[0]}"
            )
        if b is not None and not callable(b):
            b = self._coerce_b(b)
        resetup_s = time.perf_counter() - t0
        if ctx is not None:
            tracing.record_span("resetup", t0, t0 + resetup_s, ctx)
        self.manager._account_resetup(resetup_s, overlapped)
        self._staged = (values, b, t0, resetup_s, ctx)
        return self

    def _previous_in_flight(self) -> bool:
        """Is the previous step dispatched but not yet fetched?  True
        means host work done NOW overlaps device execution."""
        p = self._pending
        if p is None or p._res is not None or p._err is not None:
            return False
        t = p._service_ticket()
        batch = getattr(t, "_batch", None)
        return batch is not None and not batch.fetched()

    def commit(self, b=None) -> StepTicket:
        """Phases 2+3 — resolve the previous step (its group's one
        shared host sync; updates warm-start state) and submit the
        prestaged step with the masked warm start.  ``b`` (array or
        callable of the session) overrides a prestaged rhs; callables
        evaluate AFTER the previous step resolves, so ``last_x`` is
        the just-finished step's solution."""
        if self._staged is None:
            raise RuntimeError("commit without a prestage")
        # consume the staged step UP FRONT: any failure below (a
        # previous step's deadline/drain error surfacing in the
        # resolve, a raising rhs callable, an admission shed) must
        # leave the session retryable with a fresh prestage, not
        # wedged on "prestage called twice"
        (values, b0, t0, resetup_s, ctx), self._staged = (
            self._staged, None,
        )
        if b is None:
            b = b0
        try:
            if self._pending is not None:
                self._resolve_ticket(self._pending)
            if callable(b):
                b = b(self)
            if b is None:
                raise ValueError(
                    "no rhs: pass b to prestage or commit"
                )
            b = self._coerce_b(b)
            x0, warm = self._x0_for_next()
            step_idx = self.step_idx
            mgr = self.manager
            ticket = mgr._submit(
                self, values, b, x0, _trace=ctx,
            )
        except BaseException as e:
            if ctx is not None:
                # close the sampled root: the 'resetup' child (and a
                # gateway shed's non-root 'submit' span) already
                # parent onto this root id — without this the export
                # would carry dangling parent_ids (the PR 7 shed-path
                # contract, upheld for failed session steps too)
                tracing.record_span(
                    "session_step", t0, time.perf_counter(), ctx,
                    args={"session": self.session_id,
                          "step": self.step_idx,
                          "error": type(e).__name__},
                    root=True,
                )
            raise
        mgr._count("steps_total")
        mgr._count("warm_starts_total" if warm else "cold_starts_total")
        st = StepTicket(self, step_idx, ticket, resetup_s, ctx, t0)
        self._pending = st
        if ctx is not None:
            # the step's root span: prestage through submit; children
            # (resetup/submit/admission/pad/dispatch/device/fetch)
            # parent onto it, so one session-labeled chain per step
            tracing.record_span(
                "session_step", t0, time.perf_counter(), ctx,
                args={"session": self.session_id, "step": step_idx,
                      "lane": self.lane, "tenant": self.tenant,
                      "warm": warm},
                root=True,
            )
        mgr._maybe_entry_resetup(self, values)
        return st

    def step(self, values, b) -> StepTicket:
        """Stream one time step: ``prestage`` + ``commit`` in one
        call.  For the fully pipelined lockstep form over many
        sessions use :meth:`SessionManager.step_all`, which prestages
        EVERY member before the group's single sync."""
        self.prestage(values, b)
        return self.commit()

    def _abandon_stage(self, err=None):
        """Drop a prestaged step WITHOUT submitting it (a lockstep
        peer's failure aborts the whole group): clears the stage so
        the session stays retryable and closes the sampled trace root
        so the already-recorded ``resetup`` span does not dangle."""
        if self._staged is None:
            return
        (_values, _b, t0, _rs, ctx), self._staged = self._staged, None
        if ctx is not None:
            tracing.record_span(
                "session_step", t0, time.perf_counter(), ctx,
                args={"session": self.session_id,
                      "step": self.step_idx,
                      "error": (
                          type(err).__name__ if err is not None
                          else "abandoned"
                      )},
                root=True,
            )

    def finish(self):
        """Resolve the in-flight step, if any; returns the session's
        last solution (``last_x``, or None before any resolved step).
        Errors of the pending step are swallowed into the session
        state (``last_status`` None) — ``finish`` is the drain/save
        path, which must not raise."""
        # a prestaged-but-uncommitted step is dropped, closing its
        # sampled trace root (same contract as the step_all unwind)
        self._abandon_stage()
        p = self._pending
        if p is not None:
            try:
                self._resolve_ticket(p)
            except Exception:  # noqa: BLE001 — the failure is already
                # captured in the session state; Ctrl-C propagates
                pass
        return None if self._last_x is None else self._last_x

    def _resolve_ticket(self, st: StepTicket):
        """Idempotently settle one step ticket and fold its outcome
        into the warm-start state + telemetry."""
        if st._res is not None or st._err is not None:
            if st._err is not None:
                raise st._err
            return
        try:
            res = st.ticket.result()
        except BaseException as e:
            st._err = e
            if self._pending is st:
                self._pending = None
                self._last_status = None  # never warm-start off an error
                self.step_idx = st.step + 1
            self.manager._count("step_failures_total")
            raise
        st._res = res
        if self._pending is st:
            self._pending = None
            self._last_x = np.asarray(res.x)
            self._last_status = int(res.status)
            self._last_iters = int(res.iters)
            self.step_idx = st.step + 1
            self.manager._record_step(self, st, res)
            self.manager._maybe_checkpoint(self)

    # -- persistence ---------------------------------------------------

    def save(self, store=None) -> bool:
        """Persist this session's manifest (step counter, warm-start
        x, status, registered pattern) to the store.  The hierarchy
        itself persists through the serve layer's entry export; this
        is only the per-session streaming state.  Returns False
        (counted) on failure — persistence never raises into a
        stream."""
        return self.manager.save_session(self, store=store)

    def close(self):
        """Finish and deregister (the session stops counting as
        open; its hierarchy stays cached for other sessions)."""
        self.finish()
        self.closed = True
        self.manager._discard(self)


class SessionManager:
    """Owns the streaming sessions of one serve front (a
    :class:`BatchedSolveService` or a
    :class:`~amgx_tpu.serve.gateway.SolveGateway`).

    Parameters
    ----------
    front: the service or gateway every step submits through.  With a
        gateway, each streamed step is admitted as ONE ticket — lanes,
        tenant quotas, deadline shedding and the concurrency budget
        all apply per step.
    store: overrides the service's artifact store for session
        manifests (default: the service's own store).
    resetup_every: every N streamed steps touching a fingerprint's
        hierarchy entry, refresh the CACHED entry via
        :meth:`BatchedSolveService.resetup_entry` so quarantine
        retries / store exports / spectral-bound re-estimation
        (``reestimate_eigs``) track the streamed values.  0 disables.
        Env default: ``AMGX_TPU_SESSION_RESETUP_EVERY`` (64).
    checkpoint_every: persist each session's manifest (step counter,
        warm-start x, status) to the artifact store every N RESOLVED
        steps — the failure-domain contract: when the session's
        device is lost mid-stream, :meth:`recover` resumes from the
        last checkpoint losing at most N steps (and the replacement
        steps re-pin through the placement router, whose warm set
        forgot the tripped chip).  0 disables.  Env default:
        ``AMGX_TPU_SESSION_CHECKPOINT_EVERY`` (16).
    """

    def __init__(self, front, store=None,
                 resetup_every: Optional[int] = None,
                 checkpoint_every: Optional[int] = None):
        from amgx_tpu.serve.gateway import SolveGateway

        if isinstance(front, SolveGateway):
            self.gateway: Optional[SolveGateway] = front
            self.service: BatchedSolveService = front.service
        else:
            self.gateway = None
            self.service = front
        self.store = store if store is not None else self.service.store
        if isinstance(self.store, str):
            from amgx_tpu.store.store import ArtifactStore

            self.store = ArtifactStore(self.store)
        self.resetup_every = (
            _env_int("AMGX_TPU_SESSION_RESETUP_EVERY", 64)
            if resetup_every is None
            else int(resetup_every)
        )
        self.checkpoint_every = (
            _env_int("AMGX_TPU_SESSION_CHECKPOINT_EVERY", 16)
            if checkpoint_every is None
            else int(checkpoint_every)
        )
        self._lock = threading.Lock()
        self._sessions: dict = {}
        self._counters: dict = {}
        self._times: dict = {"resetup_seconds_total": 0.0,
                             "resetup_overlap_seconds_total": 0.0}
        # per-fingerprint step counter driving the entry-refresh
        # cadence: B lockstep sessions share ONE hierarchy entry, so
        # the refresh rate must follow entry traffic, not per-session
        # step counts (B sessions on per-session cadence N would
        # refresh the same entry B/N times per step-group)
        self._fp_steps: dict = {}
        self.telemetry_name = get_registry().register("sessions", self)

    # -- counters / telemetry ------------------------------------------

    def _count(self, name: str, by: int = 1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def _account_resetup(self, seconds: float, overlapped: bool):
        with self._lock:
            self._times["resetup_seconds_total"] += seconds
            if overlapped:
                self._times["resetup_overlap_seconds_total"] += seconds

    def telemetry_snapshot(self) -> dict:
        """Registry source (kind="sessions"): the ``amgx_session_*``
        families."""
        with self._lock:
            out = dict(self._counters)
            out.update(self._times)
            out["open"] = len(self._sessions)
        return out

    @property
    def resetup_overlap_s(self) -> float:
        with self._lock:
            return self._times["resetup_overlap_seconds_total"]

    @property
    def resetup_s(self) -> float:
        with self._lock:
            return self._times["resetup_seconds_total"]

    def _record_step(self, sess: SolveSession, st: StepTicket, res):
        """Flight-record one resolved step (path="session_step") —
        same degrade contract as every telemetry hook."""
        if not telemetry_enabled():
            return
        self.service._flight_record(
            fingerprint=sess._padded_fp or sess.fingerprint,
            config=self.service.cfg_key,
            lane=sess.lane,
            tenant=sess.tenant,
            iterations=int(res.iters),
            final_residual=float(np.max(np.asarray(res.final_norm))),
            status=int(res.status),
            stages={"resetup": st.resetup_s,
                    "step": max(time.perf_counter() - st._t0, 0.0)},
            path="session_step",
            trace_id=(
                st._trace.trace_id if st._trace is not None else None
            ),
        )

    # -- lifecycle -----------------------------------------------------

    def open(self, A, *, session_id: Optional[str] = None,
             tenant: str = "default", lane: str = "interactive",
             dtype=None, deadline_s: Optional[float] = None,
             x0=None) -> SolveSession:
        """Register a sparsity fingerprint and return its streaming
        session.  ``A`` (SparseMatrix or scipy CSR) contributes ONLY
        structure + dtype default; per-step coefficients arrive via
        ``step``.  ``x0`` seeds the first step's warm start."""
        ro, ci, vals, n, raw_fp = _host_csr(A)
        if session_id is None:
            session_id = f"sess-{uuid.uuid4().hex[:12]}"
        sess = SolveSession(
            self, session_id,
            (ro, ci, n, raw_fp),
            dtype if dtype is not None else vals.dtype,
            tenant, lane, deadline_s=deadline_s,
        )
        if x0 is not None:
            sess._last_x = np.asarray(x0, dtype=sess.dtype).reshape(-1)
            sess._last_status = 0
        with self._lock:
            self._sessions[session_id] = sess
        self._count("opens_total")
        return sess

    def _discard(self, sess: SolveSession):
        with self._lock:
            if self._sessions.get(sess.session_id) is sess:
                del self._sessions[sess.session_id]

    def sessions(self) -> list:
        with self._lock:
            return list(self._sessions.values())

    def get(self, session_id: str) -> Optional[SolveSession]:
        with self._lock:
            return self._sessions.get(session_id)

    # -- stepping ------------------------------------------------------

    def _submit(self, sess: SolveSession, values, b, x0, _trace):
        """One step into the serve layer via the values-only fast
        path: the registered (ro, ci, n, fingerprint) tuple goes in as
        ``_host`` so no per-step pattern extraction or hashing runs."""
        host = (sess._ro, sess._ci, values, sess.n, sess.fingerprint)
        front = self.gateway if self.gateway is not None else self.service
        ticket = front.submit(
            None, b, x0,
            tenant=sess.tenant, lane=sess.lane,
            deadline_s=sess.deadline_s,
            _host=host, _trace=_trace,
        )
        if sess._padded_fp is None:
            pat = self.service._patterns.get(sess.fingerprint)
            if pat is not None:
                sess._padded_fp = pat.fingerprint
        return ticket

    def step_all(self, steps) -> list:
        """Lockstep pipelined step over many sessions: ``steps`` is a
        list of ``(session, values, b)``.  Prestages EVERY member
        first (all of that host resetup work overlaps the in-flight
        previous group), then commits (ONE shared host sync resolves
        every previous ticket, then all submits land in one batch
        group), then flushes — the step-group dispatches with exactly
        one host sync outstanding for its eventual fetch.  Returns the
        StepTickets in order."""
        staged = []
        try:
            for sess, values, b in steps:
                sess.prestage(values, b)
                staged.append(sess)
            tickets = [sess.commit() for sess, _v, _b in steps]
        except BaseException as e:
            # one member's failure — bad input at prestage, or a
            # typed admission shed at commit — must not wedge its
            # lockstep peers: unwind every stage still pending so a
            # retry of the whole group prestages cleanly.  (Members
            # that already committed keep their in-flight tickets;
            # their results resolve on the next step or finish().)
            for sess in staged:
                sess._abandon_stage(e)
            raise
        self.flush()
        self._count("step_groups_total")
        return tickets

    def flush(self):
        (self.gateway or self.service).flush()

    def _maybe_entry_resetup(self, sess: SolveSession, values):
        """The ``resetup_every`` cadence: refresh the cached template
        hierarchy through the public values-only resetup API so the
        entry (quarantine retries, exports, spectral bounds /
        ``reestimate_eigs``) tracks the stream instead of the step-0
        coefficients.  Counted per FINGERPRINT — every N submitted
        steps touching the entry, whichever session lands on the
        boundary — and best-effort: a missing entry (nothing built
        yet) or a resetup failure never fails the step."""
        n = self.resetup_every
        if n <= 0:
            return
        with self._lock:
            c = self._fp_steps.get(sess.fingerprint, 0) + 1
            self._fp_steps[sess.fingerprint] = c
        if c % n:
            return
        fp = sess._padded_fp or sess.fingerprint
        try:
            self.service.resetup_entry(fp, values, sess.dtype)
            self._count("entry_resetups_total")
        except KeyError:
            pass  # no entry yet (first group still building)
        except Exception:  # noqa: BLE001 — cadence refresh is an
            # optimization; the batched path re-derives per step anyway
            self._count("entry_resetup_failures_total")

    # -- persistence ---------------------------------------------------

    def _session_key(self, session_id: str, store=None):
        """The ONE place session store keys derive (save and restore
        must never diverge)."""
        st = store if store is not None else self.store
        if st is None:
            raise StoreError("SessionManager has no artifact store")
        return st.entry_key(
            session_id, self.service.cfg_key, _SESSION_KEY_DTYPE,
            kind=SESSION_KIND,
        )

    def save_session(self, sess: SolveSession, store=None) -> bool:
        """Persist one session's streaming state (manifest + arrays).
        Returns False (counted) instead of raising on any failure."""
        st = store if store is not None else self.store
        if isinstance(st, str):
            from amgx_tpu.store.store import ArtifactStore

            st = ArtifactStore(st)
        if st is None:
            self._count("save_failures_total")
            return False
        try:
            arrays = {
                "row_offsets": np.asarray(sess._ro),
                "col_indices": np.asarray(sess._ci),
            }
            if sess._last_x is not None:
                arrays["x"] = np.asarray(sess._last_x)
            manifest = {
                "kind": SESSION_KIND,
                "session_id": sess.session_id,
                "raw_fingerprint": sess.fingerprint,
                "padded_fingerprint": sess._padded_fp,
                "cfg_key": self.service.cfg_key,
                "dtype": sess._dtype_s,
                "n": sess.n,
                "nnz": sess.nnz,
                "step": sess.step_idx,
                "last_status": sess._last_status,
                "last_iterations": sess._last_iters,
                "tenant": sess.tenant,
                "lane": sess.lane,
                "deadline_s": sess.deadline_s,
            }
            key = self._session_key(sess.session_id, store=st)
            ok = st.put(key, arrays, manifest)
        except Exception:  # noqa: BLE001 — persistence never raises
            ok = False
        self._count("saves_total" if ok else "save_failures_total")
        return ok

    def _maybe_checkpoint(self, sess: SolveSession):
        """The ``checkpoint_every`` cadence: persist the session's
        manifest after every Nth RESOLVED step so a device loss costs
        at most N steps of stream progress.  Best-effort like every
        persistence path — a failed checkpoint counts
        (``checkpoint_failures_total``) and never fails the step.

        Each checkpoint rewrites the FULL payload including the
        immutable pattern arrays: the store holds ONE atomically
        overwritten entry per session, so ``restore`` must find
        ``row_offsets``/``col_indices`` in whatever write is current
        — dropping them from periodic saves would require a second
        pattern-only key and cross-key atomicity.  Size the cadence
        accordingly for huge patterns on slow stores."""
        n = self.checkpoint_every
        if n <= 0 or self.store is None:
            return
        if sess.step_idx % n:
            return
        if self.save_session(sess):
            self._count("checkpoints_total")
            try:
                self.service.metrics.inc("resilience_checkpoints")
            except Exception:  # noqa: BLE001 — telemetry degrade
                pass
        else:
            self._count("checkpoint_failures_total")

    def recover(self, session_id: str, **kw) -> SolveSession:
        """Device-loss recovery for one streaming session: discard
        the live (wedged) session object — its in-flight step died
        with its device — and resume from the last persisted
        checkpoint via :meth:`restore`.  The resumed session's first
        step re-pins through the placement router, whose warm set
        forgot the tripped chip, so the stream continues on a healthy
        device losing at most ``checkpoint_every`` steps.  Raises
        :class:`StoreError` when no checkpoint exists — the live
        session is then left UNTOUCHED (restore runs first), so a
        caller can still read its state or restart the stream."""
        live = self.get(session_id)
        # restore FIRST: with no checkpoint (cadence disabled, store
        # missing, loss before the first cadence multiple) this raises
        # StoreError while the live session — the only state left —
        # survives intact.  On success restore() already replaced the
        # _sessions entry; the wedged live object is then retired.
        sess = self.restore(session_id, **kw)
        if live is not None:
            live._abandon_stage()
            # do NOT resolve the pending ticket: it belongs to the
            # lost device and may already be settled typed — the
            # checkpointed state is the authoritative resume point
            live._pending = None
            live.closed = True
        self._count("recoveries_total")
        return sess

    def save_all(self) -> int:
        """Finish and persist every open session (the drain
        protocol); returns the number persisted."""
        saved = 0
        for sess in self.sessions():
            sess.finish()
            if self.save_session(sess):
                saved += 1
        return saved

    def restore(self, session_id: str, *, tenant: Optional[str] = None,
                lane: Optional[str] = None,
                deadline_s: Optional[float] = None) -> SolveSession:
        """Resume a persisted session: the manifest restores the step
        counter, warm-start x and registered pattern; the hierarchy is
        expected in the hierarchy cache already (``warm_boot()`` the
        service first) so the resumed stream runs with zero coarsening
        calls.  Raises :class:`StoreError` when the manifest is
        missing/corrupt or was written under another config."""
        if self.store is None:
            raise StoreError("SessionManager has no artifact store")
        got = self.store.get(self._session_key(session_id))
        if got is None:
            self._count("restore_failures_total")
            raise StoreError(
                f"no persisted session {session_id!r} for this "
                "service's config"
            )
        manifest, arrays = got
        try:
            if manifest.get("kind") != SESSION_KIND:
                raise StoreError(
                    f"payload kind {manifest.get('kind')!r} is not a "
                    "solve session"
                )
            if manifest.get("cfg_key") != self.service.cfg_key:
                raise StoreError(
                    "session was streamed under a different solver "
                    "configuration"
                )
            host = (
                np.asarray(arrays["row_offsets"]),
                np.asarray(arrays["col_indices"]),
                int(manifest["n"]),
                str(manifest["raw_fingerprint"]),
            )
            if deadline_s is None:
                dl = manifest.get("deadline_s")
                deadline_s = None if dl is None else float(dl)
            sess = SolveSession(
                self, session_id, host, manifest.get("dtype"),
                tenant if tenant is not None
                else str(manifest.get("tenant", "default")),
                lane if lane is not None
                else str(manifest.get("lane", "interactive")),
                deadline_s=deadline_s,
            )
            sess.step_idx = int(manifest.get("step", 0))
            sess._padded_fp = manifest.get("padded_fingerprint")
            if "x" in arrays:
                sess._last_x = np.array(arrays["x"])
                ls = manifest.get("last_status")
                sess._last_status = None if ls is None else int(ls)
            li = manifest.get("last_iterations")
            sess._last_iters = None if li is None else int(li)
        except StoreError:
            self._count("restore_failures_total")
            raise
        except Exception as e:
            self._count("restore_failures_total")
            raise StoreError(
                f"malformed session manifest for {session_id!r}: {e}"
            ) from e
        with self._lock:
            self._sessions[session_id] = sess
        self._count("restores_total")
        try:
            self.service.metrics.inc("resilience_restores")
        except Exception:  # noqa: BLE001 — telemetry degrade
            pass
        return sess

    def drain(self) -> dict:
        """Session-level graceful handoff over a bare service: flush,
        finish every stream, persist manifests AND the hierarchy
        cache.  (Gateway-fronted managers normally go through
        ``gateway.drain()``, which calls :meth:`save_all` as part of
        its protocol.)"""
        self.flush()
        saved = self.save_all()
        exported = self.service.export_all_entries()
        return {"sessions_saved": saved, "entries_exported": exported}
