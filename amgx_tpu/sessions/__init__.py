"""Streaming solve sessions (transient-PDE serve workload).

A session registers a sparsity fingerprint once and then streams
``(values, b)`` pairs — the serve-level generalization of
``AMGX_matrix_replace_coefficients`` + ``AMGX_solver_resetup`` for
time-stepping workloads: values-only resetup through the hierarchy
cache, resetup of step k+1 pipelined against the in-flight solve of
step k, masked warm starts (previous x as x0), lockstep batching of
concurrent sessions sharing a fingerprint, and drain/warm-boot
persistence of the per-session streaming state.

Entry points::

    from amgx_tpu.serve import SolveGateway
    gw = SolveGateway(store="/var/amgx").start()
    sess = gw.open_session(A, tenant="cfd", lane="batch")
    for k in range(steps):
        t = sess.step(values_k, b_k)     # admitted as one ticket
    x_final = t.result().x

    # lockstep over B concurrent sessions sharing the fingerprint:
    from amgx_tpu.sessions import SessionManager
    mgr = SessionManager(service)
    sessions = [mgr.open(A_i, session_id=f"s{i}") for i in range(B)]
    tickets = mgr.step_all([(s, vals, b) for s ...])  # ONE vmapped
                                                     # group, one sync
"""

from amgx_tpu.sessions.session import (
    SESSION_KIND,
    SessionManager,
    SolveSession,
    StepTicket,
)

__all__ = [
    "SessionManager",
    "SolveSession",
    "StepTicket",
    "SESSION_KIND",
]
