"""s-step (communication-avoiding) PCG.

``SSTEP_PCG`` advances ``s`` conjugate-gradient steps per outer
iteration while paying the global-reduction bill ONCE: the outer body
runs ``s`` back-to-back SpMV + preconditioner applies to build the
s-dimensional Krylov block, forms EVERY inner product of those s steps
as one fused Gram-block reduction (:func:`amgx_tpu.ops.blas.gram_block`
— one ``psum`` on a sharded mesh), and recurs the CG scalars from the
Gram matrix with tiny s×s host-free linear algebra.  Reductions per s
steps drop from ~3s (classic monitored PCG: 2 dots + 1 norm per step)
to 2 (Gram + monitor norm).

Algorithm: the block/s-step CG of Chronopoulos & Gear (1989) in its
preconditioned form — the formulation the s-step AMG/CG literature
(arxiv 2512.09642) builds on.  Per outer iteration, with current
residual r and previous direction block P (s rows):

1. Z-basis:  z_0 = M^-1 r,  z_{i+1} = M^-1 (A z_i)  — s SpMVs, s
   preconditioner applies, with A z_i retained (AZ block).
2. ONE Gram reduction:  G = [Z; P; r] @ [AZ; AP; r]^H — all inner
   products the s steps need (Z^T A Z, Z^T A P, P^T A P, Z^T r,
   P^T r, and ||r||^2 for free).
3. Scalar recurrences: A-orthogonalize the new block against the
   previous one (C = -(P^T A P)^-1 P^T A Z), P_new = Z + C P, then
   one block step x += P_new^T a with (P_new^T A P_new) a = P_new^T r
   — in exact arithmetic exactly s classic CG steps.

Numerical knobs:

* ``sstep_basis = SCALED`` (default) renormalizes the monomial basis
  columns by their A-norms read off the Gram diagonal — a pure
  column-scaling of the s×s systems, no extra reduction — which keeps
  the Gram conditioning flat in s; ``MONOMIAL`` keeps raw powers.
* ``sstep_replace_every = N`` arms the residual-replacement guard:
  every N outer iterations the recurred residual is replaced by the
  true residual b - A x (one extra SpMV, no extra reduction),
  bounding the drift between the recurred and true residuals that
  s-step recurrences accumulate on ill-conditioned operators.

``s_step = 1`` degenerates to classic PCG *exactly*: init/iterate are
inherited from :class:`~amgx_tpu.solvers.krylov.PCGSolver` unchanged
(bitwise iteration-for-iteration parity, tests/test_sstep.py).

Monitoring: one outer iteration = s inner steps, so ``max_iters``
(an inner-step budget, like PCG) maps to ``ceil(max_iters / s)``
outer iterations and ``SolveResult.iters`` counts OUTER iterations;
``iterations_scale`` (= s) converts back to CG-step equivalents —
telemetry and the benches report inner steps so iteration counts stay
comparable across solvers.  Convergence is checked once per outer
iteration (the standard s-step overshoot of up to s-1 steps).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from amgx_tpu.ops.blas import gram_block
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.krylov import PCGSolver
from amgx_tpu.solvers.registry import register_solver


def _guarded_solve(W, rhs):
    """Solve W x = rhs for a tiny (s, s) SPD-ish Gram system with a
    relative ridge: near-breakdown (W -> 0 as r -> 0) yields x -> 0 —
    the s-step analogue of PCG's ``where(pq != 0, rho/pq, 0)`` guard —
    and any non-finite fallout is clamped to the no-op update."""
    s = W.shape[0]
    rdt = jnp.zeros((), W.dtype).real.dtype
    diag = jnp.abs(jnp.diagonal(W).real)
    eps = jnp.finfo(rdt).eps
    delta = jnp.max(diag) * eps * 4.0 + jnp.finfo(rdt).tiny
    sol = jnp.linalg.solve(
        W + delta * jnp.eye(s, dtype=W.dtype), rhs
    )
    return jnp.where(jnp.isfinite(sol), sol, jnp.zeros_like(sol))


@register_solver("SSTEP_PCG")
class SStepPCGSolver(PCGSolver):
    """Communication-avoiding PCG (module docstring).  Inherits the
    whole PCG surface — preconditioner resolution, values-only
    resetup, setup persistence, ``make_batch_params`` (so vmapped
    serve groups batch it like any Krylov solver) — and replaces only
    the iteration protocol."""

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.s = max(int(cfg.get("s_step", scope)), 1)
        self.basis = str(cfg.get("sstep_basis", scope)).upper()
        self.replace_every = max(
            int(cfg.get("sstep_replace_every", scope)), 0
        )
        # max_iters is an INNER-step budget (config parity with PCG);
        # the monitored loop counts outer iterations
        if self.s > 1:
            self.max_iters = -(-self.max_iters // self.s)

    @property
    def iterations_scale(self) -> int:
        """Inner CG steps per reported iteration (= s)."""
        return self.s

    # -- iteration protocol --------------------------------------------
    # extra = (r, P, AP, k): the residual, the previous direction
    # block and its A-image (s, n) — zero on entry, which makes the
    # first outer iteration's A-orthogonalization a no-op exactly —
    # and the outer-iteration counter for the replacement cadence.

    def _make_init(self):
        if self.s == 1:
            return super()._make_init()
        s = self.s

        def init(params, b, x):
            A, Mp = params
            r = b - spmv(A, x)
            P = jnp.zeros((s,) + r.shape, r.dtype)
            return (r, P, jnp.zeros_like(P), jnp.zeros((), jnp.int32))

        return init

    def _make_iter(self):
        if self.s == 1:
            return super()._make_iter()
        M = self._make_M()
        s = self.s
        scaled = self.basis == "SCALED"
        replace_every = self.replace_every

        def iterate(params, b, x, extra):
            A, Mp = params
            r, Pr, APr, k = extra

            # -- 1. the s-step Krylov block: s SpMVs, s applies ------
            z = M(Mp, r)
            z_rows, az_rows = [z], []
            for _ in range(s - 1):
                az = spmv(A, z_rows[-1])
                az_rows.append(az)
                z_rows.append(M(Mp, az))
            az_rows.append(spmv(A, z_rows[-1]))
            Z = jnp.stack(z_rows)
            AZ = jnp.stack(az_rows)

            # -- 2. ONE fused reduction: every inner product ---------
            L = jnp.concatenate([Z, Pr, r[None]], axis=0)
            Rt = jnp.concatenate([AZ, APr, r[None]], axis=0)
            G = gram_block(L, Rt)  # (2s+1, 2s+1)

            if scaled:
                # column-normalize the monomial basis by its A-norms,
                # read off the Gram diagonal — pure rescaling of the
                # tiny scalar systems + s axpy-scales, no reduction
                rdt = jnp.zeros((), G.dtype).real.dtype
                d = jnp.sqrt(jnp.maximum(
                    jnp.abs(jnp.diagonal(G)[:s].real),
                    jnp.finfo(rdt).tiny,
                )).astype(rdt)
                inv = (1.0 / d).astype(G.dtype)
                sl = jnp.concatenate(
                    [inv, jnp.ones((s + 1,), G.dtype)]
                )
                G = G * sl[:, None] * sl[None, :]
                Z = Z * inv[:, None]
                AZ = AZ * inv[:, None]

            G_ZAZ = G[:s, :s]           # <z_i, A z_j>
            G_ZAP = G[:s, s:2 * s]      # <z_i, A p_j>
            G_Zr = G[:s, -1]            # <z_i, r>
            G_PAZ = G[s:2 * s, :s]      # <p_i, A z_j>
            W_prev = G[s:2 * s, s:2 * s]  # <p_i, A p_j>
            G_Pr = G[s:2 * s, -1]       # <p_i, r>

            # -- 3. scalar recurrences off the Gram matrix -----------
            # A-orthogonalize the new block against the previous one:
            # <p_l, A p_new_i> = 0  =>  C = -(W_prev^-1 G_PAZ)^T
            C = -_guarded_solve(W_prev, G_PAZ).T
            P_new = Z + C @ Pr
            AP_new = AZ + C @ APr
            Cc = jnp.conj(C)
            # W_new = <P_new, A P_new> assembled from Gram blocks (the
            # G_PAZ + W_prev C^T term is ~0 by construction; keeping it
            # preserves the float cancellation structure)
            W_new = (
                G_ZAZ
                + G_ZAP @ C.T
                + Cc @ (G_PAZ + W_prev @ C.T)
            )
            g = G_Zr + Cc @ G_Pr  # <P_new_i, r>
            a = _guarded_solve(W_new, g)

            x = x + jnp.tensordot(a, P_new, axes=1)
            r_new = r - jnp.tensordot(a, AP_new, axes=1)
            k = k + 1

            if replace_every > 0:
                # residual-replacement guard: periodically discard the
                # recurred residual for the true one (SpMV only — the
                # monitor norm that follows is the same reduction
                # either way)
                r_new = jax.lax.cond(
                    k % replace_every == 0,
                    lambda op: op[0] - spmv(A, op[1]),
                    lambda op: op[2],
                    (b, x, r_new),
                )

            return x, (r_new, P_new, AP_new, k)

        return iterate
