"""Krylov solvers: CG, PCG, PCGF, BiCGStab, PBiCGStab.

Reference parity: cg_solver.cu, pcg_solver.cu, pcgf_solver.cu,
bicgstab_solver.cu, pbicgstab_solver.cu.  Each iteration is a pure
function over (params, b, x, extra); the generic monitored loop in
``Solver`` drives convergence/history.  Preconditioners are embedded as
pure apply functions whose arrays ride in ``params[1]`` — so a PCG with
an AMG preconditioner is ONE jitted program.

The NOSOLVER name disables preconditioning (reference
pcg_solver.cu:21-29).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from amgx_tpu.ops.blas import dot, fused_dots
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import (
    SolverRegistry,
    make_nested,
    register_solver,
)


def resolve_preconditioner(cfg, scope):
    """Allocate the preconditioner named in config, or None for NOSOLVER."""
    name, pscope = cfg.get_scoped("preconditioner", scope)
    if name == "NOSOLVER":
        return None
    return make_nested(SolverRegistry.get(name)(cfg, pscope))


class KrylovSolver(Solver):
    uses_preconditioner = True

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.precond = (
            resolve_preconditioner(cfg, scope)
            if self.uses_preconditioner
            else None
        )

    def _setup_impl(self, A):
        if self.precond is not None:
            self.precond.setup(A)
            self._params = (A, self.precond.apply_params())
        else:
            self._params = (A, None)

    def _resetup_impl(self, A):
        """Values-only refresh: delegate to the preconditioner (which
        falls back to its own full setup when it has no fast path)."""
        if self.precond is not None:
            self.precond.resetup(A)
            self._params = (A, self.precond.apply_params())
        else:
            self._params = (A, None)
        return True

    def _export_impl(self):
        # persistence (amgx_tpu.store): the preconditioner's setup is
        # the expensive part (AMG hierarchies); recurse into it so a
        # restored PCG+AMG skips coarsening entirely
        if self.precond is None:
            return None
        return {"precond": self.precond._export_setup()}

    def _import_impl(self, impl):
        if self.precond is None or not impl \
                or impl.get("precond") is None:
            return self._setup_impl(self.A)
        self.precond._import_setup(impl["precond"])
        self._params = (self.A, self.precond.apply_params())

    def _make_M(self):
        """Pure fn(Mp, r) -> z; identity when unpreconditioned."""
        if self.precond is None:
            return lambda Mp, r: r
        return self.precond.make_apply()

    def make_batch_params(self):
        A0 = self._params[0]
        if self.precond is None:
            return A0, lambda t, v: (t.replace_values(v), None)
        sub = self.precond.make_batch_params()
        if sub is None:
            return None
        ptmpl, pfn = sub

        def fn(t, v):
            At, pt = t
            return At.replace_values(v), pfn(pt, v)

        return (A0, ptmpl), fn

    # -- iteration protocol (subclasses) --------------------------------
    # extra is solver state; extra[0] must be the current residual r.

    def _make_init(self):
        raise NotImplementedError

    def _make_iter(self):
        raise NotImplementedError

    def make_solve(self):
        init = self._make_init()
        iterate = self._make_iter()
        norm_of = self.make_norm()
        monitored = self.monitor_residual

        def solve(params, b, x0):
            extra0 = init(params, b, x0)
            if not monitored:
                def fori_body(i, c):
                    x, extra = c
                    return iterate(params, b, x, extra)

                x, _ = jax.lax.fori_loop(
                    0, self.max_iters, fori_body, (x0, extra0)
                )
                return self._fixed_result(x, b, self.max_iters)

            nrm0 = norm_of(extra0[0])

            def body(c):
                it, x, extra, nrm, ini, mx, hist, st = c
                x, extra = iterate(params, b, x, extra)
                nrm = norm_of(extra[0])
                return self._monitor_update(
                    it + 1, x, extra, nrm, ini, mx, hist, st
                )

            return self._monitored_loop(nrm0, body, b, x0, extra0)

        return solve

    def make_apply(self):
        """Fixed-iteration zero-guess run (nested-solver usage)."""
        init = self._make_init()
        iterate = self._make_iter()
        iters = max(self.max_iters, 1)

        def apply(params, r):
            x = jnp.zeros_like(r)
            extra = init(params, r, x)

            def fori_body(i, c):
                x, extra = c
                return iterate(params, r, x, extra)

            x, _ = jax.lax.fori_loop(0, iters, fori_body, (x, extra))
            return x

        return apply

    def make_smooth(self):
        init = self._make_init()
        iterate = self._make_iter()

        def smooth(params, b, x, sweeps):
            extra = init(params, b, x)
            for _ in range(sweeps):
                x, extra = iterate(params, b, x, extra)
            return x

        return smooth


@register_solver("PCG")
class PCGSolver(KrylovSolver):
    """Preconditioned conjugate gradient (reference pcg_solver.cu)."""

    def _make_init(self):
        M = self._make_M()

        def init(params, b, x):
            A, Mp = params
            r = b - spmv(A, x)
            z = M(Mp, r)
            p = z
            rho = dot(r, z)
            return (r, p, rho)

        return init

    def _make_iter(self):
        M = self._make_M()

        def iterate(params, b, x, extra):
            A, Mp = params
            r, p, rho = extra
            q = spmv(A, p)
            pq = dot(p, q)
            # guards: exact breakdown (converged mid-fixed-iteration run)
            # must yield a no-op, not 0/0 = NaN
            alpha = jnp.where(pq != 0, rho / pq, 0.0)
            x = x + alpha * p
            r = r - alpha * q
            z = M(Mp, r)
            rho_new = dot(r, z)
            beta = jnp.where(rho != 0, rho_new / rho, 0.0)
            p = z + beta * p
            return x, (r, p, rho_new)

        return iterate


@register_solver("CG")
class CGSolver(PCGSolver):
    """Unpreconditioned CG (reference cg_solver.cu)."""

    uses_preconditioner = False


@register_solver("PCGF")
class PCGFSolver(KrylovSolver):
    """Flexible PCG (reference pcgf_solver.cu): Polak-Ribiere beta
    <z_new, r_new - r_old> / rho tolerates a changing preconditioner."""

    def _make_init(self):
        M = self._make_M()

        def init(params, b, x):
            A, Mp = params
            r = b - spmv(A, x)
            z = M(Mp, r)
            p = z
            rho = dot(r, z)
            return (r, p, rho)

        return init

    def _make_iter(self):
        M = self._make_M()

        def iterate(params, b, x, extra):
            A, Mp = params
            r, p, rho = extra
            q = spmv(A, p)
            pq = dot(p, q)
            alpha = jnp.where(pq != 0, rho / pq, 0.0)
            x = x + alpha * p
            r_new = r - alpha * q
            z = M(Mp, r_new)
            # the Polak-Ribiere arm needs <r_new, z> AND <z, r_new - r>
            # at the same point, and both share operands: ONE stacked
            # reduction instead of two (ops/blas.fused_dots)
            rho_new, zdr = fused_dots(((r_new, z), (z, r_new - r)))
            beta = jnp.where(
                rho != 0,
                zdr / jnp.where(rho != 0, rho, 1.0),
                0.0,
            )
            p = z + beta * p
            return x, (r_new, p, rho_new)

        return iterate


@register_solver("PBICGSTAB")
class PBiCGStabSolver(KrylovSolver):
    """Preconditioned BiCGStab (reference pbicgstab_solver.cu)."""

    def _make_init(self):
        def init(params, b, x):
            A, Mp = params
            r = b - spmv(A, x)
            one = jnp.ones((), r.dtype)
            zeros = jnp.zeros_like(r)
            # (r, r0hat, p, v, rho, alpha, omega)
            return (r, r, zeros, zeros, one, one, one)

        return init

    def _make_iter(self):
        M = self._make_M()

        def iterate(params, b, x, extra):
            A, Mp = params
            r, r0, p, v, rho, alpha, omega = extra
            rho1 = dot(r0, r)
            # guard each factor separately: the PRODUCT rho*omega can
            # underflow while both ratios remain well-defined
            ok = (rho != 0) & (omega != 0)
            beta = jnp.where(
                ok,
                (rho1 / jnp.where(rho != 0, rho, 1.0))
                * (alpha / jnp.where(omega != 0, omega, 1.0)),
                0.0,
            )
            p = r + beta * (p - omega * v)
            phat = M(Mp, p)
            v = spmv(A, phat)
            r0v = dot(r0, v)
            alpha = jnp.where(r0v != 0, rho1 / r0v, 0.0)
            s = r - alpha * v
            shat = M(Mp, s)
            t = spmv(A, shat)
            # <t, t> and <t, s> share t: one stacked reduction
            tt, ts = fused_dots(((t, t), (t, s)))
            omega = jnp.where(tt != 0, ts / tt, 0.0)
            x = x + alpha * phat + omega * shat
            r = s - omega * t
            return x, (r, r0, p, v, rho1, alpha, omega)

        return iterate


@register_solver("BICGSTAB")
class BiCGStabSolver(PBiCGStabSolver):
    """Unpreconditioned BiCGStab (reference bicgstab_solver.cu)."""

    uses_preconditioner = False
