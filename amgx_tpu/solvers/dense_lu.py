"""Dense LU coarse solver (reference dense_lu_solver.cu: cuSOLVER
getrf/getrs on the densified coarse matrix).

TPU form: densify at setup (host), LU-factorize once with
``jax.scipy.linalg.lu_factor`` (batched MXU-friendly), apply is a pair of
triangular solves inside the jitted cycle.  Size guards
dense_lu_num_rows/dense_lu_max_rows live in the AMG driver (amg.cu:76-85).

Zero-pivot guardrail: ``jax.scipy.linalg.lu_factor`` does not signal
singularity — a zero pivot silently propagates NaN into every coarse
correction (and from there into the whole V-cycle).  Setup therefore
checks the U diagonal on host; per ``dense_lu_zero_pivot`` policy a
singular factorization either raises :class:`SingularDiagonalError`
(RAISE) or switches the coarse solve to the pseudoinverse
(REGULARIZE): the correction becomes the least-squares solution,
exact on the range of the coarse operator and zero on its null space
— a degraded-but-convergent coarse solve, justified by inexact-
coarse-solver analysis (the outer iteration absorbs a bounded
coarse-solve perturbation, unlike a ridge whose 1/delta null-space
response would blow the cycle up)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from amgx_tpu.core import faults
from amgx_tpu.core.errors import SingularDiagonalError
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import register_solver


def _bad_pivots(lu) -> bool:
    """Host check of the factorization's U diagonal: exact zeros, NaNs
    (LAPACK writes NaN past a breakdown), or pivots tiny enough that
    back-substitution amplifies into overflow."""
    d = np.abs(np.diag(np.asarray(lu)))
    if d.size == 0:
        return False
    if not np.all(np.isfinite(np.asarray(lu))):
        return True
    dmax = float(d.max())
    if dmax == 0.0:
        return True
    tiny = np.finfo(d.dtype).eps * d.shape[0] * dmax
    return bool(np.any(d <= tiny))


@register_solver("DENSE_LU_SOLVER")
class DenseLUSolver(Solver):
    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.zero_pivot_policy = str(
            cfg.get("dense_lu_zero_pivot", scope)
        ).upper()
        self._pinv_mode = False

    def _setup_impl(self, A):
        dense = np.asarray(A.to_dense())
        if faults.should_fire("coarse_lu_zero_pivot"):
            # injected singularity: zero the last row/column so the
            # factorization hits an exact zero pivot deterministically
            dense = dense.copy()
            dense[-1, :] = 0.0
            dense[:, -1] = 0.0
        if dense.dtype.itemsize < 4:
            # sub-f32 hierarchies (hierarchy_dtype=BFLOAT16): LAPACK
            # has no bf16/f16 factorization — factor in f32; the cycle
            # casts the correction back to the level dtype
            dense = dense.astype(np.float32)
        self._pinv_mode = False
        lu, piv = jax.scipy.linalg.lu_factor(jnp.asarray(dense))
        if _bad_pivots(lu):
            if self.zero_pivot_policy == "RAISE":
                raise SingularDiagonalError(
                    f"DENSE_LU: singular coarse matrix "
                    f"({A.n_rows} rows): zero/tiny pivot in LU"
                )
            # REGULARIZE: least-squares coarse solve via the
            # pseudoinverse (exact on the range, zero on the null
            # space); the apply becomes one dense matvec
            import warnings

            warnings.warn(
                f"DENSE_LU: singular coarse matrix ({A.n_rows} rows); "
                "switching to pseudoinverse coarse solve "
                "(dense_lu_zero_pivot=REGULARIZE)"
            )
            self._pinv_mode = True
            pinv = np.linalg.pinv(dense)
            if not np.all(np.isfinite(pinv)):
                raise SingularDiagonalError(
                    f"DENSE_LU: pseudoinverse of the coarse matrix "
                    f"({A.n_rows} rows) is non-finite"
                )
            self._params = (A, jnp.asarray(pinv), piv)
            return
        self._params = (A, lu, piv)

    # ------------------------------------------------------------------
    # setup persistence (amgx_tpu.store): the factors ARE this solver's
    # setup — persisting them makes restore skip the O(n^3)
    # refactorization (and makes the dense-factor store bytes the
    # coarse_solver=INEXACT comparison measures explicit).

    def _export_impl(self):
        _, fac, piv = self._params
        return {"fac": fac, "piv": piv, "pinv": bool(self._pinv_mode)}

    def _import_impl(self, impl):
        if not impl or impl.get("fac") is None:
            return self._setup_impl(self.A)
        self._pinv_mode = bool(impl.get("pinv"))
        self._params = (self.A, impl["fac"], impl["piv"])

    def make_batch_params(self):
        if self._pinv_mode:
            # the traced rebuild refactorizes with plain LU, which is
            # exactly what just failed — no batch fast path
            return None
        A0 = self._params[0]
        if A0.block_size != 1:
            return None

        def fn(t, v):
            A = t.replace_values(v)
            if A.has_dense:
                dense = A.dense
            else:
                dense = (
                    jnp.zeros((A.n_rows, A.n_cols), A.values.dtype)
                    .at[A.row_ids, A.col_indices]
                    .add(A.values)
                )
            if dense.dtype.itemsize < 4:
                # same sub-f32 upcast as _setup_impl (no bf16 LAPACK)
                dense = dense.astype(jnp.float32)
            lu, piv = jax.scipy.linalg.lu_factor(dense)
            return A, lu, piv

        return A0, fn

    def make_apply(self):
        if self._pinv_mode:

            def apply_pinv(params, r):
                _, pinv, _ = params
                return pinv @ r

            return apply_pinv

        def apply(params, r):
            _, lu, piv = params
            return jax.scipy.linalg.lu_solve((lu, piv), r)

        return apply

    def make_smooth(self):
        apply = self.make_apply()

        def smooth(params, b, x, sweeps):
            # direct solve: the result does not depend on x or sweeps
            return apply(params, b)

        return smooth

    def make_solve(self):
        apply = self.make_apply()

        def solve(params, b, x0):
            x = apply(params, b)
            return self._fixed_result(x, b, 1)

        return solve


@register_solver("DENSE_LU")
class DenseLUAlias(DenseLUSolver):
    pass
