"""Dense LU coarse solver (reference dense_lu_solver.cu: cuSOLVER
getrf/getrs on the densified coarse matrix).

TPU form: densify at setup (host), LU-factorize once with
``jax.scipy.linalg.lu_factor`` (batched MXU-friendly), apply is a pair of
triangular solves inside the jitted cycle.  Size guards
dense_lu_num_rows/dense_lu_max_rows live in the AMG driver (amg.cu:76-85).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import register_solver


@register_solver("DENSE_LU_SOLVER")
class DenseLUSolver(Solver):
    def _setup_impl(self, A):
        dense = jnp.asarray(A.to_dense())
        lu, piv = jax.scipy.linalg.lu_factor(dense)
        self._params = (A, lu, piv)

    def make_batch_params(self):
        A0 = self._params[0]
        if A0.block_size != 1:
            return None

        def fn(t, v):
            A = t.replace_values(v)
            if A.has_dense:
                dense = A.dense
            else:
                dense = (
                    jnp.zeros((A.n_rows, A.n_cols), A.values.dtype)
                    .at[A.row_ids, A.col_indices]
                    .add(A.values)
                )
            lu, piv = jax.scipy.linalg.lu_factor(dense)
            return A, lu, piv

        return A0, fn

    def make_apply(self):
        def apply(params, r):
            _, lu, piv = params
            return jax.scipy.linalg.lu_solve((lu, piv), r)

        return apply

    def make_smooth(self):
        apply = self.make_apply()

        def smooth(params, b, x, sweeps):
            # direct solve: the result does not depend on x or sweeps
            return apply(params, b)

        return smooth

    def make_solve(self):
        apply = self.make_apply()

        def solve(params, b, x0):
            x = apply(params, b)
            return self._fixed_result(x, b, 1)

        return solve


@register_solver("DENSE_LU")
class DenseLUAlias(DenseLUSolver):
    pass
