"""NOSOLVER (reference dummy_solver.cu) and user-solver hook
(user_solver.cu)."""

from __future__ import annotations

import jax.numpy as jnp

from amgx_tpu.solvers.base import IdentitySolverMixin, Solver
from amgx_tpu.solvers.registry import register_solver


@register_solver("NOSOLVER")
class DummySolver(IdentitySolverMixin, Solver):
    """Does nothing (reference zeroes x on zero guess and returns).  Outer
    solvers special-case the name NOSOLVER and skip preconditioning
    entirely (reference pcg_solver.cu:21-29); when invoked anyway the
    apply is the zero map, matching the reference."""

    def _setup_impl(self, A):
        self._params = A

    def make_step(self):
        return lambda params, b, x: x

    def make_apply(self):
        return lambda params, r: jnp.zeros_like(r)

    def make_solve(self):
        def solve(params, b, x0):
            return self._fixed_result(x0, b, 0)

        return solve
