"""Gauss-Seidel smoothers via multicolor sweeps.

Reference parity: gauss_seidel_solver.cu, multicolor_gauss_seidel_solver.cu
(the reference's GPU GS is also color-parallel: one kernel per color after
matrix coloring).  TPU form: for each color c the update

    x_i <- (1-w) x_i + w * (b_i - sum_{j != i} a_ij x_j) / a_ii,  i in c

is a masked full-vector update driven by one SpMV; colors are a static
Python loop so XLA sees ``num_colors`` fused SpMV+select stages.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from amgx_tpu.ops.coloring import color_matrix
from amgx_tpu.ops.diagonal import invert_diag, scalarized
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import register_solver


@register_solver("MULTICOLOR_GS")
class MulticolorGSSolver(Solver):
    symmetric_default = False

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.symmetric = bool(cfg.get("symmetric_GS", scope)) or \
            self.symmetric_default
        self.scheme = str(cfg.get("matrix_coloring_scheme", scope))
        self.deterministic = bool(cfg.get("determinism_flag", scope))

    def _setup_impl(self, A):
        A = scalarized(A, "MULTICOLOR_GS")
        colors = color_matrix(A, self.scheme, self.deterministic)
        self.num_colors = int(colors.max()) + 1
        self._params = (A, invert_diag(A), jnp.asarray(colors))

    def make_step(self):
        omega = self.relaxation_factor
        ncol = self.num_colors
        order = list(range(ncol))
        if self.symmetric:
            order = order + order[::-1]

        def step(params, b, x):
            A, dinv, colors = params
            for c in order:
                ax = spmv(A, x)
                # remove the diagonal contribution to get sum_{j!=i} a_ij x_j
                gs = dinv * (b - ax) + x
                x = jnp.where(colors == c, (1 - omega) * x + omega * gs, x)
            return x

        return step


@register_solver("GS")
class GSSolver(MulticolorGSSolver):
    """Plain GS maps onto the multicolor implementation (the reference GPU
    path does the same, gauss_seidel_solver.cu)."""


@register_solver("FIXCOLOR_GS")
class FixcolorGSSolver(MulticolorGSSolver):
    """Fixed 2-coloring variant (reference fixcolor_gauss_seidel_solver.cu);
    uses the generic coloring here."""
