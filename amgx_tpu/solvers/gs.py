"""Gauss-Seidel smoothers via multicolor sweeps.

Reference parity: gauss_seidel_solver.cu, multicolor_gauss_seidel_solver.cu
(the reference's GPU GS is also color-parallel: one kernel per color after
matrix coloring; each stored entry is touched once per sweep).  TPU form:
rows are sliced PER COLOR at setup into compact ELL slices, so for color c

    x_i <- (1-w) x_i + w * (b_i - sum_{j != i} a_ij x_j) / a_ii,  i in c

is a compact gather + row-sum over color-c rows only and a scatter of the
color-c updates — one application costs O(nnz) total, not
O(num_colors * nnz) as a masked full-matrix sweep would.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from amgx_tpu.ops.coloring import color_matrix
from amgx_tpu.ops.diagonal import invert_diag, scalarized
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import register_solver


@register_solver("MULTICOLOR_GS")
class MulticolorGSSolver(Solver):
    symmetric_default = False

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.symmetric = bool(cfg.get("symmetric_GS", scope)) or \
            self.symmetric_default
        self.scheme = str(cfg.get("matrix_coloring_scheme", scope))
        self.deterministic = bool(cfg.get("determinism_flag", scope))

    def _setup_impl(self, A):
        from amgx_tpu.solvers.dilu import (
            _color_ell_slices,
            _fori_sweep_wanted,
            _stack_color_slices,
        )

        A = scalarized(A, "MULTICOLOR_GS")
        colors = color_matrix(A, self.scheme, self.deterministic,
                              cfg=self.cfg, scope=self.scope)
        self.num_colors = nc = int(colors.max()) + 1
        rows_by_color = [np.nonzero(colors == c)[0] for c in range(nc)]
        Asp = A.to_scipy().tocsr()
        slices = _color_ell_slices(Asp, rows_by_color)
        dinv = np.asarray(invert_diag(A))
        n = A.n_rows
        self._fori = _fori_sweep_wanted(nc, rows_by_color, slices)
        if self._fori:
            # stacked spill-padded slices -> one fori body (see
            # dilu._FORI_MIN_COLORS: many-color deep hierarchies
            # explode XLA compile time when unrolled)
            rows_s, cols_s, vals_s = _stack_color_slices(
                slices, rows_by_color, n
            )
            dinv_s = np.zeros(rows_s.shape, dtype=dinv.dtype)
            for c, rows_c in enumerate(rows_by_color):
                dinv_s[c, : len(rows_c)] = dinv[rows_c]
            self._params = (
                A,
                (
                    jnp.asarray(rows_s), jnp.asarray(cols_s),
                    jnp.asarray(vals_s), jnp.asarray(dinv_s),
                ),
            )
            return
        # params = (A, per-color slices): A first so the base monitored
        # loop's operator_of/spmv residual path keeps working
        self._params = (
            A,
            tuple(
                (
                    jnp.asarray(rows_c),
                    jnp.asarray(cols),
                    jnp.asarray(vals),
                    jnp.asarray(dinv[rows_c]),
                )
                for rows_c, (cols, vals) in zip(rows_by_color, slices)
            ),
        )

    def make_step(self):
        import jax

        omega = self.relaxation_factor
        nc = self.num_colors
        symmetric = self.symmetric
        if getattr(self, "_fori", False):
            total = 2 * nc if symmetric else nc

            def step(params, b, x):
                rows_s, cols_s, vals_s, dinv_s = params[1]
                n = x.shape[0]
                x_ext = jnp.concatenate(
                    [x, jnp.zeros((1,), x.dtype)]
                )
                b_ext = jnp.concatenate(
                    [b, jnp.zeros((1,), b.dtype)]
                )

                def body(k, xe):
                    c = jnp.where(k < nc, k, 2 * nc - 1 - k)
                    rows_c = rows_s[c]
                    # row sums include the diagonal term; dinv*(b-ax)+x
                    # cancels it: dinv*(b-off-d*x)+x = dinv*(b-off)
                    ax_c = jnp.sum(vals_s[c] * xe[cols_s[c]], axis=-1)
                    gs = (
                        dinv_s[c] * (b_ext[rows_c] - ax_c)
                        + xe[rows_c]
                    )
                    return xe.at[rows_c].set(
                        (1 - omega) * xe[rows_c] + omega * gs
                    )

                x_ext = jax.lax.fori_loop(0, total, body, x_ext)
                return x_ext[:n]

            return step
        order = list(range(nc))
        if symmetric:
            order = order + order[::-1]

        def step(params, b, x):
            for c in order:
                rows_c, cols, vals, dinv_c = params[1][c]
                # row sums include the diagonal term; dinv*(b-ax)+x
                # cancels it: dinv*(b - off - d*x) + x = dinv*(b - off)
                ax_c = jnp.sum(vals * x[cols], axis=-1)
                gs = dinv_c * (b[rows_c] - ax_c) + x[rows_c]
                x = x.at[rows_c].set(
                    (1 - omega) * x[rows_c] + omega * gs
                )
            return x

        return step


@register_solver("GS")
class GSSolver(MulticolorGSSolver):
    """Plain GS maps onto the multicolor implementation (the reference GPU
    path does the same, gauss_seidel_solver.cu)."""


@register_solver("FIXCOLOR_GS")
class FixcolorGSSolver(MulticolorGSSolver):
    """Fixed 2-coloring variant (reference fixcolor_gauss_seidel_solver.cu);
    uses the generic coloring here."""
