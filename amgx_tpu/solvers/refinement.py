"""Iterative refinement to rtol 1e-8+ without f64 hardware.

Reference mapping: the dDFI mixed mode's intent (f64 vectors over an
f32 matrix, basic_types.h:92-117) — on TPU there is no f64 ALU, so the
solution is carried as a float-float pair (ops/ff.py) and refined:

    loop: r = b - A x          (ff accumulation — exact to ~2^-49)
          solve A dx = r       (any f32 inner solver, loose tolerance)
          x = x (+ff) dx

Plain f32 Krylov stagnates near rtol 1e-5 at >=16M DOF because neither
x nor the residual can be resolved in one f32 working precision
(BENCHMARKS.md round 1); refinement restores full convergence at f32
bandwidth cost — the residual pass moves the same HBM bytes.

Config: ``solver=ITERATIVE_REFINEMENT`` with the inner solver under
``preconditioner`` (e.g. PCG+AMG); ``tolerance``/``convergence`` are
the outer criteria, ``max_iters`` the outer sweep cap.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from amgx_tpu.ops import ff as ffm
from amgx_tpu.ops.norms import norm as _norm
from amgx_tpu.solvers.base import (
    NOT_CONVERGED,
    SUCCESS,
    SolveResult,
    Solver,
)
from amgx_tpu.solvers.registry import register_solver


@register_solver("ITERATIVE_REFINEMENT")
class IterativeRefinementSolver(Solver):
    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        from amgx_tpu.solvers.krylov import resolve_preconditioner

        self.inner = (
            resolve_preconditioner(cfg, scope)
            if cfg.has("preconditioner", scope)
            else None
        )
        if self.inner is None:
            raise ValueError(
                "ITERATIVE_REFINEMENT needs an inner solver under "
                "'preconditioner' (NOSOLVER is not one)"
            )
        # cheap-preconditioner accuracy envelope (ROADMAP items 3/4):
        # when the inner solver runs a reduced-precision hierarchy
        # (hierarchy_dtype != SAME anywhere in the config), a tripped
        # guardrail — non-SUCCESS status, or more outer corrections
        # than refine_iteration_guard — re-solves once at full
        # precision.  Counted in precision_fallbacks; ci gates the
        # trip-and-recover path (ci/precision_bench.py).
        self.precision_fallback = bool(
            cfg.get("precision_fallback", scope)
        )
        self.iteration_guard = int(
            cfg.get("refine_iteration_guard", scope)
        )
        self.precision_fallbacks = 0
        self._fallback_solver = None
        # retired inner iterations (inner-step equivalents) of the
        # LAST solve() — the parity currency of ci/precision_bench.py
        self.last_inner_iters = 0

    def _setup_impl(self, A):
        self.inner.setup(A)
        self._params = (A, self.inner.apply_params())

    def _resetup_impl(self, A) -> bool:
        """Values-only refresh: delegate to the inner solver (which
        falls back to its own full setup when it has no fast path)."""
        self.inner.resetup(A)
        self._params = (A, self.inner.apply_params())
        return True

    def _export_impl(self):
        # persistence (amgx_tpu.store): recurse into the inner solver
        return {"inner": self.inner._export_setup()}

    def _import_impl(self, impl):
        if not impl or impl.get("inner") is None:
            return self._setup_impl(self.A)
        self.inner._import_setup(impl["inner"])
        self._params = (self.A, self.inner.apply_params())

    def make_solve(self):
        """Jit-composable form: x collapsed to working precision (the
        pair-preserving entry is :meth:`solve`, which combines hi+lo in
        f64 on host — the value of refinement is lost if the output is
        rounded back to one f32)."""
        pair = self._make_solve_pair()

        def solve(params, b, x0):
            res, xl, _inner = pair(params, b, x0)
            return dataclasses.replace(res, x=res.x + xl)

        return solve

    # -- iteration protocol (serve batching) ----------------------------
    # One "iteration" = one outer correction: float-float residual,
    # inner solve of the correction, error-free accumulate.  Exposing
    # it lets the vmapped serve loop (serve/batched._instance_protocol)
    # batch refinement-wrapped cheap configs like any Krylov solver —
    # extra = (residual estimate, low part of x).

    def _make_init(self):
        def init(params, b, x0):
            A, _ip = params
            xl = jnp.zeros_like(x0)
            rh, rl = ffm.ff_residual(A, ffm.ff(b), (x0, xl))
            return (rh + rl, xl)

        return init

    def _make_iter(self):
        inner_solve = self.inner.make_solve()

        def iterate(params, b, x, extra):
            A, ip = params
            _r, xl = extra
            b_ff = ffm.ff(b)
            rh, _rl = ffm.ff_residual(A, b_ff, (x, xl))
            d = inner_solve(ip, rh, jnp.zeros_like(rh))
            xh, xl = ffm.ff_add((x, xl), ffm.ff(d.x))
            r2h, r2l = ffm.ff_residual(A, b_ff, (xh, xl))
            return xh, (r2h + r2l, xl)

        return iterate

    def _make_solve_pair(self):
        inner_solve = self.inner.make_solve()
        conv_check = self._conv_check
        max_outer = max(self.max_iters, 1)
        nt = self.norm_type

        def solve(params, b, x0):
            A, inner_params = params
            b_ff = ffm.ff(b)
            rdt = jnp.real(b).dtype
            hist = jnp.full((max_outer + 1, 1), jnp.nan, rdt)

            def residual_norm(xh, xl):
                r = ffm.ff_residual(A, b_ff, (xh, xl))
                return r, jnp.atleast_1d(_norm(r[0] + r[1], nt))

            x0h = jnp.asarray(b, rdt) * 0 + x0
            r0, nrm0 = residual_norm(x0h, jnp.zeros_like(x0h))
            hist = hist.at[0, 0].set(nrm0[0])
            done0 = conv_check(nrm0, nrm0, nrm0) | jnp.all(nrm0 == 0)

            def body(c):
                it, xh, xl, nrm, mx, hist, done, inner_tot = c
                # NOTE: the residual is recomputed here rather than
                # carried from the previous iteration's norm pass —
                # carrying the pair through the while_loop carry lets
                # XLA simplify the error-free transformations across
                # the loop boundary (observed: refinement degrades to
                # plain-f32 stagnation at eps*||b||), and the extra
                # bandwidth-bound pass is cheap next to the inner solve.
                rh, _rl = ffm.ff_residual(A, b_ff, (xh, xl))
                res = inner_solve(inner_params, rh, jnp.zeros_like(rh))
                xh, xl = ffm.ff_add((xh, xl), ffm.ff(res.x))
                _r2, nrm = residual_norm(xh, xl)
                mx = jnp.maximum(mx, nrm)
                hist = hist.at[it + 1, 0].set(nrm[0])
                done = conv_check(nrm, nrm0, mx) | jnp.all(nrm == 0)
                # retired-iteration accounting: the sum of the inner
                # solver's iteration counts is the parity currency the
                # cheap-preconditioner CI gate compares against the
                # f64 baseline's monitored iterations
                inner_tot = inner_tot + res.iters
                return (it + 1, xh, xl, nrm, mx, hist, done, inner_tot)

            def cond(c):
                it, done = c[0], c[6]
                return (it < max_outer) & ~done

            c0 = (
                jnp.int32(0), x0h, jnp.zeros_like(x0h), nrm0, nrm0,
                hist, done0, jnp.int32(0),
            )
            (
                it, xh, xl, nrm, _mx, hist, done, inner_tot
            ) = jax.lax.while_loop(cond, body, c0)
            return (
                SolveResult(
                    x=xh,
                    iters=it,
                    status=jnp.where(
                        done, jnp.int32(SUCCESS), jnp.int32(NOT_CONVERGED)
                    ),
                    final_norm=nrm,
                    initial_norm=nrm0,
                    history=hist,
                ),
                xl,
                inner_tot,
            )

        return solve

    def solve(self, b, x0=None, zero_initial_guess=False, block=True):
        """Pair-preserving solve: the hi/lo parts are combined in f64
        on HOST, so the returned x carries the refined accuracy even
        when the device works in f32.  Mirrors the base solve's
        scaling/stats handling (base.py Solver.solve).  ``block`` is
        accepted for interface parity with the base async mode but
        ignored: the host-side hi/lo combine forces a sync anyway.

        Precision guardrail (cheap-preconditioner envelope): with a
        reduced-precision inner hierarchy, a non-SUCCESS status — or
        more outer corrections than ``refine_iteration_guard`` —
        re-solves once on an ``hierarchy_dtype=SAME`` fallback solver
        (``precision_fallbacks`` counts the trips)."""
        if self.A is None:
            raise RuntimeError("solve() before setup()")
        raw_b, raw_x0 = b, x0
        b = jnp.asarray(b)
        x0 = (
            jnp.zeros_like(b)
            if (x0 is None or zero_initial_guess)
            else jnp.asarray(x0)
        )
        if self._scale_vecs is not None:
            r_s, c_s = self._scale_vecs
            b = r_s * b
            x0 = x0 / jnp.where(c_s != 0, c_s, 1.0)
        key = (b.shape, b.dtype.name, "pair")
        fn = self._jit_cache.get(key)
        if fn is None:
            fn = jax.jit(self._make_solve_pair())
            self._jit_cache[key] = fn
        t0 = time.perf_counter()
        res, xl, inner_tot = fn(self.apply_params(), b, x0)
        scale = getattr(self.inner, "iterations_scale", 1)
        self.last_inner_iters = int(inner_tot) * int(scale)
        if self._guardrail_tripped(res):
            return self._solve_f64_fallback(
                raw_b, raw_x0, zero_initial_guess, t0
            )
        x64 = np.asarray(res.x, np.float64) + np.asarray(xl, np.float64)
        if self._scale_vecs is not None:
            x64 = x64 * np.asarray(self._scale_vecs[1], np.float64)
        res = dataclasses.replace(res, x=x64)
        self.solve_time = time.perf_counter() - t0
        if self.print_solve_stats:
            self._print_stats(res)
        return res

    # ------------------------------------------------------------------
    # precision-fallback guardrail

    def _reduced_precision_config(self) -> bool:
        """Does the SET-UP inner solver actually hold hierarchy values
        at a different (reduced) dtype than the operator?  Checked
        against the built levels, not the config spelling — an
        explicit ``hierarchy_dtype=FLOAT64`` on an f64 operator (or
        F32 on an f32-native one) is a no-op cast, and a fallback
        re-solve on a bitwise-equivalent twin would just double setup
        time and memory.  The guardrail is inert in those cases."""
        if self.A is None:
            return False
        base = np.dtype(self.A.values.dtype)
        stack, seen = [self.inner], set()
        while stack:
            s = stack.pop()
            if s is None or id(s) in seen:
                continue
            seen.add(id(s))
            stack.append(getattr(s, "precond", None))
            stack.append(getattr(s, "inner", None))
            for lvl in getattr(s, "levels", ()):
                for m in (lvl.A, lvl.P, lvl.R):
                    if m is not None and np.dtype(
                        m.values.dtype
                    ) != base:
                        return True
        return False

    def _guardrail_tripped(self, res) -> bool:
        if not self.precision_fallback:
            return False
        if not self._reduced_precision_config():
            return False
        if int(res.status) != SUCCESS:
            return True
        return (
            self.iteration_guard > 0
            and int(res.iters) > self.iteration_guard
        )

    def _make_fallback_solver(self):
        """Same config, hierarchy_dtype forced to SAME in every scope
        that sets it — the full-precision twin the guardrail re-solves
        on.  Set up ONCE on this solver's (already scaled/reordered)
        operator; the solve-boundary vectors are shared so b/x0 take
        the same path they took here."""
        from amgx_tpu.config.amg_config import AMGConfig

        cfg2 = AMGConfig.from_state(self.cfg.to_state())
        for (scope, name) in list(cfg2.items()):
            if name == "hierarchy_dtype":
                cfg2.set("hierarchy_dtype", "SAME", scope)
            if name == "precision_fallback":
                cfg2.set("precision_fallback", 0, scope)
        cfg2.set("precision_fallback", 0)
        fb = type(self)(cfg2, self.scope)
        fb.scaling = "NONE"
        fb.reordering = "NONE"
        fb.setup(self.A)
        fb._scale_vecs = self._scale_vecs
        fb._reorder = self._reorder
        return fb

    def _solve_f64_fallback(self, raw_b, raw_x0, zero_guess, t0):
        self.precision_fallbacks += 1
        if self._fallback_solver is None:
            self._fallback_solver = self._make_fallback_solver()
        res = self._fallback_solver.solve(
            raw_b, x0=raw_x0, zero_initial_guess=zero_guess
        )
        self.last_inner_iters = self._fallback_solver.last_inner_iters
        self.solve_time = time.perf_counter() - t0
        if self.print_solve_stats:
            self._print_stats(res)
        return res

    def make_apply(self):
        solve = self.make_solve()

        def apply(params, r):
            return solve(params, r, jnp.zeros_like(r)).x

        return apply

    def make_batch_params(self):
        """Traced values-only rebuild: the operator swaps values and
        the inner solver rebuilds through its own batch params — so a
        refinement-wrapped cheap config rides the vmapped serve path
        (with the iteration protocol above) instead of the sequential
        fallback."""
        if self.A is None or self.A.block_size != 1:
            return None
        sub = self.inner.make_batch_params()
        if sub is None:
            return None
        itmpl, ifn = sub
        A0 = self._params[0]

        def fn(t, v):
            At, it = t
            return At.replace_values(v), ifn(it, v)

        return (A0, itmpl), fn
