"""Iterative refinement to rtol 1e-8+ without f64 hardware.

Reference mapping: the dDFI mixed mode's intent (f64 vectors over an
f32 matrix, basic_types.h:92-117) — on TPU there is no f64 ALU, so the
solution is carried as a float-float pair (ops/ff.py) and refined:

    loop: r = b - A x          (ff accumulation — exact to ~2^-49)
          solve A dx = r       (any f32 inner solver, loose tolerance)
          x = x (+ff) dx

Plain f32 Krylov stagnates near rtol 1e-5 at >=16M DOF because neither
x nor the residual can be resolved in one f32 working precision
(BENCHMARKS.md round 1); refinement restores full convergence at f32
bandwidth cost — the residual pass moves the same HBM bytes.

Config: ``solver=ITERATIVE_REFINEMENT`` with the inner solver under
``preconditioner`` (e.g. PCG+AMG); ``tolerance``/``convergence`` are
the outer criteria, ``max_iters`` the outer sweep cap.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from amgx_tpu.ops import ff as ffm
from amgx_tpu.ops.norms import norm as _norm
from amgx_tpu.solvers.base import (
    NOT_CONVERGED,
    SUCCESS,
    SolveResult,
    Solver,
)
from amgx_tpu.solvers.registry import register_solver


@register_solver("ITERATIVE_REFINEMENT")
class IterativeRefinementSolver(Solver):
    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        from amgx_tpu.solvers.krylov import resolve_preconditioner

        self.inner = (
            resolve_preconditioner(cfg, scope)
            if cfg.has("preconditioner", scope)
            else None
        )
        if self.inner is None:
            raise ValueError(
                "ITERATIVE_REFINEMENT needs an inner solver under "
                "'preconditioner' (NOSOLVER is not one)"
            )

    def _setup_impl(self, A):
        self.inner.setup(A)
        self._params = (A, self.inner.apply_params())

    def _export_impl(self):
        # persistence (amgx_tpu.store): recurse into the inner solver
        return {"inner": self.inner._export_setup()}

    def _import_impl(self, impl):
        if not impl or impl.get("inner") is None:
            return self._setup_impl(self.A)
        self.inner._import_setup(impl["inner"])
        self._params = (self.A, self.inner.apply_params())

    def make_solve(self):
        """Jit-composable form: x collapsed to working precision (the
        pair-preserving entry is :meth:`solve`, which combines hi+lo in
        f64 on host — the value of refinement is lost if the output is
        rounded back to one f32)."""
        pair = self._make_solve_pair()

        def solve(params, b, x0):
            res, xl = pair(params, b, x0)
            return dataclasses.replace(res, x=res.x + xl)

        return solve

    def _make_solve_pair(self):
        inner_solve = self.inner.make_solve()
        conv_check = self._conv_check
        max_outer = max(self.max_iters, 1)
        nt = self.norm_type

        def solve(params, b, x0):
            A, inner_params = params
            b_ff = ffm.ff(b)
            rdt = jnp.real(b).dtype
            hist = jnp.full((max_outer + 1, 1), jnp.nan, rdt)

            def residual_norm(xh, xl):
                r = ffm.ff_residual(A, b_ff, (xh, xl))
                return r, jnp.atleast_1d(_norm(r[0] + r[1], nt))

            x0h = jnp.asarray(b, rdt) * 0 + x0
            r0, nrm0 = residual_norm(x0h, jnp.zeros_like(x0h))
            hist = hist.at[0, 0].set(nrm0[0])
            done0 = conv_check(nrm0, nrm0, nrm0) | jnp.all(nrm0 == 0)

            def body(c):
                it, xh, xl, nrm, mx, hist, done = c
                # NOTE: the residual is recomputed here rather than
                # carried from the previous iteration's norm pass —
                # carrying the pair through the while_loop carry lets
                # XLA simplify the error-free transformations across
                # the loop boundary (observed: refinement degrades to
                # plain-f32 stagnation at eps*||b||), and the extra
                # bandwidth-bound pass is cheap next to the inner solve.
                rh, _rl = ffm.ff_residual(A, b_ff, (xh, xl))
                res = inner_solve(inner_params, rh, jnp.zeros_like(rh))
                xh, xl = ffm.ff_add((xh, xl), ffm.ff(res.x))
                _r2, nrm = residual_norm(xh, xl)
                mx = jnp.maximum(mx, nrm)
                hist = hist.at[it + 1, 0].set(nrm[0])
                done = conv_check(nrm, nrm0, mx) | jnp.all(nrm == 0)
                return (it + 1, xh, xl, nrm, mx, hist, done)

            def cond(c):
                it, done = c[0], c[6]
                return (it < max_outer) & ~done

            c0 = (
                jnp.int32(0), x0h, jnp.zeros_like(x0h), nrm0, nrm0,
                hist, done0,
            )
            it, xh, xl, nrm, _mx, hist, done = jax.lax.while_loop(
                cond, body, c0
            )
            return (
                SolveResult(
                    x=xh,
                    iters=it,
                    status=jnp.where(
                        done, jnp.int32(SUCCESS), jnp.int32(NOT_CONVERGED)
                    ),
                    final_norm=nrm,
                    initial_norm=nrm0,
                    history=hist,
                ),
                xl,
            )

        return solve

    def solve(self, b, x0=None, zero_initial_guess=False, block=True):
        """Pair-preserving solve: the hi/lo parts are combined in f64
        on HOST, so the returned x carries the refined accuracy even
        when the device works in f32.  Mirrors the base solve's
        scaling/stats handling (base.py Solver.solve).  ``block`` is
        accepted for interface parity with the base async mode but
        ignored: the host-side hi/lo combine forces a sync anyway."""
        if self.A is None:
            raise RuntimeError("solve() before setup()")
        b = jnp.asarray(b)
        x0 = (
            jnp.zeros_like(b)
            if (x0 is None or zero_initial_guess)
            else jnp.asarray(x0)
        )
        if self._scale_vecs is not None:
            r_s, c_s = self._scale_vecs
            b = r_s * b
            x0 = x0 / jnp.where(c_s != 0, c_s, 1.0)
        key = (b.shape, b.dtype.name, "pair")
        fn = self._jit_cache.get(key)
        if fn is None:
            fn = jax.jit(self._make_solve_pair())
            self._jit_cache[key] = fn
        t0 = time.perf_counter()
        res, xl = fn(self.apply_params(), b, x0)
        x64 = np.asarray(res.x, np.float64) + np.asarray(xl, np.float64)
        if self._scale_vecs is not None:
            x64 = x64 * np.asarray(self._scale_vecs[1], np.float64)
        res = dataclasses.replace(res, x=x64)
        self.solve_time = time.perf_counter() - t0
        if self.print_solve_stats:
            self._print_stats(res)
        return res

    def make_apply(self):
        solve = self.make_solve()

        def apply(params, r):
            return solve(params, r, jnp.zeros_like(r)).x

        return apply
