"""Multicolor DILU and true multicolor ILU(k) smoothers.

Reference parity: multicolor_dilu_solver.cu (4259 LoC, block sizes
1-10 — the reference's workhorse preconditioner) and
multicolor_ilu_solver.cu (2222 LoC, ILU(0)/ILU(1) with fill via
csr_sparsity).

DILU math: with coloring-induced ordering and E the DILU diagonal,

    E_i = a_ii - sum_{j in N(i), color(j) < color(i)} a_ij E_j^{-1} a_ji
    M   = (E + L) E^{-1} (E + U)

Apply M^{-1} r: forward color sweep solves (E+L) y = r, backward sweep
solves (E+U) z = E y.

TPU form: rows are sliced PER COLOR at setup into compact ELL slices,
so one application costs O(nnz) total (each stored entry is touched by
exactly one forward and one backward stage) — not the
O(num_colors * nnz) of a masked full-matrix sweep.  Blocks are native:
E is a batched b×b inverse and sweep updates are einsum block
mat-vecs, matching the reference's block-specialized kernels instead
of scalar expansion.

ILU(k): exact multicolor ILU(k) factors on the level-k fill pattern of
the BLOCK graph (pattern of A^(k+1) sums, the reference csr_sparsity
product for ILU1).  Block rows of one color are structurally
independent in the fill pattern (the pattern graph is what gets
colored), so the numeric factorization vectorizes over color pairs
with b×b pivot-block elimination:

    for color c ascending, for earlier color c2 ascending:
        L_blk = Rc[:, cols_c2] @ blockdiag(U_kk^{-1})
        Rc    = Rc - (L_blk @ U[rows_c2]) restricted to the pattern
        Rc[:, cols_c2] = L_blk

Apply M^{-1} r = U^{-1} L^{-1} r by the same per-color ELL sweeps
(L forward with identity pivot blocks, U backward with inverted
pivot blocks).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import scipy.sparse as sps

from amgx_tpu.core.matrix import SparseMatrix
from amgx_tpu.ops.coloring import color_matrix
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import register_solver


# at or above this color count the sweep runs as a lax.fori_loop over
# spill-padded stacked slices instead of an unrolled per-color trace:
# deep hierarchies of many-color smoothers otherwise explode XLA
# compile time (observed: 64^3 serial DILU "Very slow compile", 217 s
# end to end -> 14 s with the loop) while the padded loop compiles one
# body per level
_FORI_MIN_COLORS = 6
# ... but padding costs nc*rc_max*w work per sweep; with unbalanced
# color sizes that can exceed the compact O(nnz) contract, so the loop
# only engages while padded work stays within this factor of compact
_FORI_MAX_WASTE = 4.0


def _fori_sweep_wanted(nc, rows_by_color, slices) -> bool:
    """Gate for the stacked fori sweep: enough colors to matter AND
    bounded padding waste (the O(nnz)-per-sweep contract holds to a
    constant factor)."""
    if nc < _FORI_MIN_COLORS:
        return False
    rc_max = max(max(len(r) for r in rows_by_color), 1)
    w = max(max(s[0].shape[1] for s in slices), 1)
    compact = sum(
        max(len(r), 1) * s[0].shape[1]
        for r, s in zip(rows_by_color, slices)
    )
    return nc * rc_max * w <= _FORI_MAX_WASTE * max(compact, 1)


def _stack_color_slices(slices, rows_by_color, n):
    """Stack per-color compact ELL slices [nc_i, w_i(, b, b)] into
    uniform spill-padded arrays (rows pad -> n, cols pad -> n, vals
    pad -> 0) for the fori sweep; the spill slot collects only zero
    updates.  Handles scalar and block (trailing b x b) value slices."""
    nc = len(slices)
    rc_max = max(max(len(r) for r in rows_by_color), 1)
    w = max(max(s[0].shape[1] for s in slices), 1)
    extra = slices[0][1].shape[2:]  # () scalar | (b, b) block
    rows_s = np.full((nc, rc_max), n, dtype=np.int64)
    cols_s = np.full((nc, rc_max, w), n, dtype=np.int32)
    vals_s = np.zeros(
        (nc, rc_max, w, *extra), dtype=slices[0][1].dtype
    )
    for c, (rows_c, (cols, vals)) in enumerate(
        zip(rows_by_color, slices)
    ):
        k = len(rows_c)
        rows_s[c, :k] = rows_c
        cols_s[c, :k, : cols.shape[1]] = cols
        vals_s[c, :k, : vals.shape[1]] = vals
    return rows_s, cols_s, vals_s


def _color_ell_slices(Asp: sps.csr_matrix, rows_by_color, block=None):
    """Per-color compact ELL slices of a (masked) host CSR matrix.

    Returns list of (cols[nc, w], vals[nc, w] or [nc, w, b, b]); colors
    with no stored entries get width-1 zero slices so the traced sweep
    structure is uniform.
    """
    out = []
    for rows_c in rows_by_color:
        sub = Asp[rows_c].tocsr()
        lens = np.diff(sub.indptr)
        w = max(int(lens.max()) if lens.size else 0, 1)
        cols = np.zeros((len(rows_c), w), dtype=np.int32)
        if block is None:
            vals = np.zeros((len(rows_c), w), dtype=sub.data.dtype)
        else:
            vals = np.zeros(
                (len(rows_c), w, block, block), dtype=sub.data.dtype
            )
        rid = np.repeat(np.arange(len(rows_c)), lens)
        pos = np.arange(sub.indices.shape[0]) - sub.indptr[rid].astype(
            np.int64
        )
        cols[rid, pos] = sub.indices
        vals[rid, pos] = sub.data
        out.append((cols, vals))
    return out


class _ColorSweepSmoother(Solver):
    """Shared stationary-step shell for the per-color sweep smoothers:
    subclasses provide _apply_M_inv(params, r)."""

    def make_residual_step(self):
        omega = self.relaxation_factor

        def rstep(params, b, x, r):
            return x + omega * self._apply_M_inv(params, r)

        return rstep

    def make_apply(self):
        omega = self.relaxation_factor
        step = self.make_step()
        iters = max(self.max_iters, 1)

        def apply(params, r):
            z = omega * self._apply_M_inv(params, r)
            for _ in range(iters - 1):
                z = step(params, r, z)
            return z

        return apply


@register_solver("MULTICOLOR_DILU")
class MulticolorDILUSolver(_ColorSweepSmoother):
    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.scheme = str(cfg.get("matrix_coloring_scheme", scope))
        self.deterministic = bool(cfg.get("determinism_flag", scope))

    def _setup_impl(self, A: SparseMatrix):
        b = A.block_size
        colors = color_matrix(A, self.scheme, self.deterministic,
                              cfg=self.cfg, scope=self.scope)
        self.num_colors = nc = int(colors.max()) + 1
        rows_by_color = [np.nonzero(colors == c)[0] for c in range(nc)]
        self._rows_by_color = rows_by_color

        # copies: jax device buffers are read-only; scipy mutates
        indptr = np.array(A.row_offsets)
        indices = np.array(A.col_indices)
        vals = np.array(A.values)
        n = A.n_rows
        row_ids = np.asarray(A.row_ids)
        lower = colors[indices] < colors[row_ids]
        upper = colors[indices] > colors[row_ids]
        diag = np.asarray(A.diag)

        # ---- E factors (block-native) -------------------------------
        if b == 1:
            Asp = sps.csr_matrix((vals, indices, indptr), shape=(n, n))
            W = Asp.multiply(Asp.T).tocsr()  # w_ij = a_ij * a_ji
            E = diag.astype(vals.dtype).copy()
            for c in range(1, nc):
                rows_c = rows_by_color[c]
                if rows_c.size == 0:
                    continue
                with np.errstate(divide="ignore", invalid="ignore"):
                    einv = np.where(
                        (E != 0) & (colors < c), 1.0 / E, 0.0
                    )
                E[rows_c] = diag[rows_c] - (W[rows_c] @ einv)
            E = np.where(E == 0, 1.0, E)
            einv_full = (1.0 / E).astype(vals.dtype)
        else:
            # block E: E_i = a_ii - sum_lower a_ij Einv_j a_ji
            # map (i,j) -> slot of (j,i) if present — one global
            # lexsorted searchsorted (the per-row loop was O(n) Python)
            order = np.lexsort((indices, row_ids))
            key_s = (row_ids[order].astype(np.int64) * (n + 1)
                     + indices[order])
            tkey = (indices.astype(np.int64) * (n + 1) + row_ids)
            pos = np.searchsorted(key_s, tkey)
            ok = (pos < key_s.shape[0]) & (
                key_s[np.minimum(pos, len(key_s) - 1)] == tkey
            )
            trans_slot = np.where(
                ok, order[np.minimum(pos, len(order) - 1)], -1
            )
            Einv = np.zeros((n, b, b), dtype=vals.dtype)
            E = diag.astype(vals.dtype).copy()
            eye = np.eye(b, dtype=vals.dtype)
            col_of_entry = colors[indices]
            row_of_entry = colors[row_ids]
            for c in range(nc):
                rows_c = rows_by_color[c]
                if rows_c.size == 0:
                    continue
                if c > 0:
                    # batched correction (one einsum per color — the
                    # per-row Python loop made 64^3 block setups take
                    # minutes): entries of color-c rows whose column
                    # color is lower and whose transpose entry exists
                    in_c = (
                        (row_of_entry == c)
                        & (col_of_entry < c)
                        & (trans_slot >= 0)
                        & (indices != row_ids)
                    )
                    if in_c.any():
                        ei = row_ids[in_c]
                        prod = np.einsum(
                            "nij,njk,nkl->nil",
                            vals[in_c],
                            Einv[indices[in_c]],
                            vals[np.maximum(trans_slot[in_c], 0)],
                        )
                        E[rows_c] = diag[rows_c]
                        np.add.at(E, ei, -prod)
                # invert (guarded)
                blk = E[rows_c]
                dets_ok = np.abs(np.linalg.det(blk)) > 1e-300
                safe = np.where(dets_ok[:, None, None], blk, eye)
                Einv[rows_c] = np.linalg.inv(safe)
            einv_full = Einv

        # ---- per-color ELL slices of L and U ------------------------
        if b == 1:
            # independent index copies: eliminate_zeros() compacts
            # indices/indptr in place and the two matrices must not
            # share them
            L = sps.csr_matrix(
                (np.where(lower, vals, 0.0), indices.copy(),
                 indptr.copy()), (n, n)
            )
            U = sps.csr_matrix(
                (np.where(upper, vals, 0.0), indices.copy(),
                 indptr.copy()), (n, n)
            )
            L.eliminate_zeros()
            U.eliminate_zeros()
            Ls = _color_ell_slices(L.tocsr(), rows_by_color)
            Us = _color_ell_slices(U.tocsr(), rows_by_color)
        else:
            Ls = _block_color_slices(
                indptr, indices, np.where(lower[:, None, None], vals, 0),
                rows_by_color, b,
            )
            Us = _block_color_slices(
                indptr, indices, np.where(upper[:, None, None], vals, 0),
                rows_by_color, b,
            )

        dev = jnp.asarray
        self._fori = _fori_sweep_wanted(nc, rows_by_color, Ls)
        if self._fori:
            # stacked spill-padded slices: one fori body per level
            # instead of nc unrolled color stages (compile-time fix;
            # round 5 extends it to block b > 1, VERDICT r4 #5)
            Lr, Lc_s, Lv_s = _stack_color_slices(Ls, rows_by_color, n)
            _, Uc_s, Uv_s = _stack_color_slices(Us, rows_by_color, n)
            if b == 1:
                einv_ext = np.concatenate(
                    [einv_full, np.zeros((1,), einv_full.dtype)]
                )
            else:
                einv_ext = np.concatenate(
                    [einv_full, np.zeros((1, b, b), einv_full.dtype)]
                )
            self._params = (
                A,
                (dev(Lc_s), dev(Lv_s)),
                (dev(Uc_s), dev(Uv_s)),
                dev(Lr),
                dev(einv_ext),
            )
            self._block = b
            return
        # params[0] is the operator (base Solver convention)
        self._params = (
            A,
            tuple((dev(c), dev(v)) for c, v in Ls),
            tuple((dev(c), dev(v)) for c, v in Us),
            tuple(dev(r) for r in rows_by_color),
            dev(einv_full),
        )
        self._block = b

    # ------------------------------------------------------------------

    def _apply_M_inv(self, params, r):
        _A, Ls, Us, rows, einv = params
        b = self._block
        if getattr(self, "_fori", False):
            import jax

            (Lc_s, Lv_s), (Uc_s, Uv_s) = Ls, Us
            rows_s, einv_ext = rows, einv
            ncol = rows_s.shape[0]
            if b > 1:
                # block fori sweep: vectors live as (n_blk + 1, b)
                # spill-padded block rows; per-color updates are
                # batched b x b einsums (same arithmetic as the
                # unrolled block path)
                r2 = r.reshape(-1, b)
                nblk = r2.shape[0]
                r_ext = jnp.concatenate(
                    [r2, jnp.zeros((1, b), r.dtype)]
                )

                def fwdb(c, y):
                    rows_c = rows_s[c]
                    s = jnp.einsum(
                        "nwij,nwj->ni", Lv_s[c], y[Lc_s[c]]
                    )
                    rc = r_ext[rows_c] - s
                    return y.at[rows_c].set(
                        jnp.einsum("nij,nj->ni", einv_ext[rows_c], rc)
                    )

                y = jax.lax.fori_loop(
                    0, ncol, fwdb, jnp.zeros((nblk + 1, b), r.dtype)
                )

                def bwdb(k, z):
                    c = ncol - 1 - k
                    rows_c = rows_s[c]
                    s = jnp.einsum(
                        "nwij,nwj->ni", Uv_s[c], z[Uc_s[c]]
                    )
                    corr = jnp.einsum(
                        "nij,nj->ni", einv_ext[rows_c], s
                    )
                    return z.at[rows_c].set(y[rows_c] - corr)

                z = jax.lax.fori_loop(0, ncol, bwdb, y)
                return z[:nblk].reshape(-1)
            n = r.shape[0]
            r_ext = jnp.concatenate([r, jnp.zeros((1,), r.dtype)])

            def fwd(c, y):
                rows_c = rows_s[c]
                s = jnp.sum(Lv_s[c] * y[Lc_s[c]], axis=1)
                return y.at[rows_c].set(
                    (r_ext[rows_c] - s) * einv_ext[rows_c]
                )

            y = jax.lax.fori_loop(
                0, ncol, fwd, jnp.zeros((n + 1,), r.dtype)
            )

            def bwd(k, z):
                c = ncol - 1 - k
                rows_c = rows_s[c]
                s = jnp.sum(Uv_s[c] * z[Uc_s[c]], axis=1)
                return z.at[rows_c].set(
                    y[rows_c] - einv_ext[rows_c] * s
                )

            z = jax.lax.fori_loop(0, ncol, bwd, y)
            return z[:n]
        ncol = len(rows)
        if b == 1:
            y = jnp.zeros_like(r)
            for c in range(ncol):
                Lc, Lv = Ls[c]
                s = jnp.sum(Lv * y[Lc], axis=1)
                y = y.at[rows[c]].set((r[rows[c]] - s) * einv[rows[c]])
            z = y
            for c in range(ncol - 1, -1, -1):
                Uc, Uv = Us[c]
                s = jnp.sum(Uv * z[Uc], axis=1)
                z = z.at[rows[c]].set(y[rows[c]] - einv[rows[c]] * s)
            return z
        r2 = r.reshape(-1, b)
        y = jnp.zeros_like(r2)
        for c in range(ncol):
            Lc, Lv = Ls[c]
            s = jnp.einsum("nwij,nwj->ni", Lv, y[Lc])
            rc = r2[rows[c]] - s
            y = y.at[rows[c]].set(
                jnp.einsum("nij,nj->ni", einv[rows[c]], rc)
            )
        z = y
        for c in range(ncol - 1, -1, -1):
            Uc, Uv = Us[c]
            s = jnp.einsum("nwij,nwj->ni", Uv, z[Uc])
            corr = jnp.einsum("nij,nj->ni", einv[rows[c]], s)
            z = z.at[rows[c]].set(y[rows[c]] - corr)
        return z.reshape(-1)



def _block_color_slices(indptr, indices, vals, rows_by_color, b):
    """Per-color ELL slices for block CSR (vals (nnz, b, b))."""
    out = []
    n = indptr.shape[0] - 1
    lens_all = np.diff(indptr)
    for rows_c in rows_by_color:
        w = max(int(lens_all[rows_c].max()) if rows_c.size else 0, 1)
        cols = np.zeros((len(rows_c), w), dtype=np.int32)
        vv = np.zeros((len(rows_c), w, b, b), dtype=vals.dtype)
        for li, i in enumerate(rows_c):
            s0, s1 = indptr[i], indptr[i + 1]
            cols[li, : s1 - s0] = indices[s0:s1]
            vv[li, : s1 - s0] = vals[s0:s1]
        out.append((cols, vv))
    return out


@register_solver("MULTICOLOR_ILU")
class MulticolorILUSolver(_ColorSweepSmoother):
    """True multicolor ILU(k), block-native (reference
    multicolor_ilu_solver.cu): exact LU factors on the level-k fill
    pattern of the BLOCK graph, with b×b diagonal-block pivots.

    The factorization runs on the scalar expansion (scipy CSR) but
    eliminates whole block columns at a time — ``Lb = B @ Dinv`` with
    ``Dinv`` the block-diagonal inverse of the factored color's pivot
    blocks — so the factors are exactly the reference's block ILU, not
    scalar ILU on an expanded matrix.  L has identity diagonal blocks;
    U's diagonal blocks are stored inverted for the backward sweep.
    Scalar matrices are the b == 1 case of the same path.
    """

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.scheme = str(cfg.get("matrix_coloring_scheme", scope))
        self.deterministic = bool(cfg.get("determinism_flag", scope))
        self.fill_level = int(cfg.get("ilu_sparsity_level", scope))

    def _setup_impl(self, A: SparseMatrix):
        b = A.block_size
        n = A.n_rows  # block rows
        Asp = A.to_scipy().tocsr()  # scalar expansion (N = n*b)
        Asp.sort_indices()

        # level-k fill pattern on the BLOCK graph (reference
        # csr_sparsity for ILU1)
        nnzb = A.col_indices.shape[0]
        Sb = sps.csr_matrix(
            (np.ones(nnzb, np.int8), np.array(A.col_indices),
             np.array(A.row_offsets)),
            shape=(n, n),
        )
        patt = Sb.copy()
        for _ in range(max(self.fill_level, 0)):
            patt = ((patt @ Sb + patt) != 0).astype(np.int8).tocsr()
        patt.setdiag(1)
        patt.sort_indices()

        # color the PATTERN graph: same-color block rows are
        # structurally independent in the fill pattern
        patt_mat = SparseMatrix.from_csr(
            patt.indptr, patt.indices,
            patt.data.astype(np.asarray(A.values).dtype),
            build_ell=False,
        )
        colors = color_matrix(patt_mat, self.scheme, self.deterministic,
                              cfg=self.cfg, scope=self.scope)
        self.num_colors = ncol = int(colors.max()) + 1
        rows_by_color = [np.nonzero(colors == c)[0] for c in range(ncol)]
        # scalar row/column ids of each color's block rows
        srows_by_color = [
            (r[:, None] * b + np.arange(b)[None, :]).reshape(-1)
            for r in rows_by_color
        ]
        ones_bb = np.ones((b, b), np.int8)

        # numeric factorization by color pairs (module docstring); fill
        # slots materialize through the pattern-projected subtraction
        work = Asp
        dtype = work.dtype
        rows_store = [None] * ncol
        u_store = [None] * ncol  # U-part (block cols with color >= c)
        udinv = np.zeros((n, b, b), dtype=dtype)
        eye = np.eye(b, dtype=dtype)
        pattb = patt.astype(bool)
        N = n * b
        for ci, rows_c in enumerate(rows_by_color):
            sr = srows_by_color[ci]
            Rc = work[sr].tocsr()
            maskc = pattb[rows_c]
            if b > 1:
                maskc = sps.kron(maskc, ones_bb, format="csr")
            for c2 in range(ci):
                rows_c2 = rows_by_color[c2]
                sc2 = srows_by_color[c2]
                B = Rc[:, sc2].tocsr()
                if B.nnz == 0:
                    continue
                # block-column elimination: scale by the factored
                # color's INVERTED pivot blocks (b x b), not scalar
                # reciprocals — this is what makes the factors block-ILU
                Dinv = sps.block_diag(udinv[rows_c2], format="csr")
                Lb = (B @ Dinv).tocsr()
                # elimination uses ONLY the U-part of the factored
                # rows: their L-values are factor entries, not
                # residual matrix values
                upd = (Lb @ u_store[c2]).multiply(maskc)
                Rc = (Rc - upd).tocsr()
                # replace the eliminated block columns with l_ik
                lcoo = Lb.tocoo()
                emb = sps.csr_matrix(
                    (lcoo.data, (lcoo.row, sc2[lcoo.col])),
                    shape=Rc.shape,
                )
                sel = np.zeros(N, dtype=bool)
                sel[sc2] = True
                coo = Rc.tocoo()
                keep = ~sel[coo.col]
                Rc = sps.csr_matrix(
                    (coo.data[keep], (coo.row[keep], coo.col[keep])),
                    shape=Rc.shape,
                ) + emb
                Rc = Rc.tocsr()
            # pivot blocks of this color: entries of Rc in each row's
            # own diagonal block
            sc = srows_by_color[ci]
            cooD = Rc[:, sc].tocoo()
            on = (cooD.row // b) == (cooD.col // b)
            D = np.zeros((len(rows_c), b, b), dtype=dtype)
            D[cooD.row[on] // b, cooD.row[on] % b, cooD.col[on] % b] = (
                cooD.data[on]
            )
            ok = np.abs(np.linalg.det(D)) > 1e-300
            D = np.where(ok[:, None, None], D, eye)
            udinv[rows_c] = np.linalg.inv(D)
            rows_store[ci] = Rc
            ucols = colors >= ci
            coo_u = Rc.tocoo()
            ukeep = ucols[coo_u.col // b]
            u_store[ci] = sps.csr_matrix(
                (coo_u.data[ukeep],
                 (coo_u.row[ukeep], coo_u.col[ukeep])),
                shape=Rc.shape,
            )
        # assemble factored matrix rows in original order
        full = sps.vstack(
            [rows_store[c] for c in range(ncol)], format="csr"
        )
        order = np.concatenate(srows_by_color)
        inv_order = np.argsort(order)
        fact = full[inv_order].tocsr()

        # split: unit-block-L (block colors <) and strict U (block
        # colors >); each row's own pivot block lives in udinv
        coo = fact.tocoo()
        bc_row = colors[coo.row // b]
        bc_col = colors[coo.col // b]
        L = sps.csr_matrix(
            (coo.data * (bc_col < bc_row), (coo.row, coo.col)),
            shape=(N, N),
        )
        U = sps.csr_matrix(
            (coo.data * (bc_col > bc_row), (coo.row, coo.col)),
            shape=(N, N),
        )
        L.eliminate_zeros()
        U.eliminate_zeros()
        Ls = _color_ell_slices(L.tocsr(), srows_by_color)
        Us = _color_ell_slices(U.tocsr(), srows_by_color)

        dev = jnp.asarray
        self._block = b
        self._fori = _fori_sweep_wanted(ncol, srows_by_color, Ls)
        if self._fori:
            # stacked spill-padded fori sweep (round 5, VERDICT r4 #5:
            # the 217 s -> 14 s many-color compile fix now covers ILU)
            sr_s, Lc_s, Lv_s = _stack_color_slices(
                Ls, srows_by_color, N)
            _, Uc_s, Uv_s = _stack_color_slices(Us, srows_by_color, N)
            rc_b_max = max(max(len(r) for r in rows_by_color), 1)
            ud_s = np.zeros((ncol, rc_b_max, b, b), dtype=udinv.dtype)
            for c, rows_c in enumerate(rows_by_color):
                ud_s[c, : len(rows_c)] = udinv[rows_c]
            self._params = (
                A,
                (dev(Lc_s), dev(Lv_s)),
                (dev(Uc_s), dev(Uv_s)),
                dev(sr_s),
                dev(ud_s),
            )
            return
        # params[0] is the operator (base Solver convention)
        self._params = (
            A,
            tuple((dev(c), dev(v)) for c, v in Ls),
            tuple((dev(c), dev(v)) for c, v in Us),
            tuple(dev(r) for r in srows_by_color),
            tuple(dev(udinv[r]) for r in rows_by_color),
        )

    def _apply_M_inv(self, params, r):
        _A, Ls, Us, srows, udinv = params
        b = self._block
        if getattr(self, "_fori", False):
            import jax

            (Lc_s, Lv_s), (Uc_s, Uv_s) = Ls, Us
            sr_s, ud_s = srows, udinv
            N = r.shape[0]
            ncol = sr_s.shape[0]
            r_ext = jnp.concatenate([r, jnp.zeros((1,), r.dtype)])

            def fwd(c, y):
                sr = sr_s[c]
                s = jnp.sum(Lv_s[c] * y[Lc_s[c]], axis=1)
                return y.at[sr].set(r_ext[sr] - s)

            y = jax.lax.fori_loop(
                0, ncol, fwd, jnp.zeros((N + 1,), r.dtype)
            )

            def bwd(k, z):
                c = ncol - 1 - k
                sr = sr_s[c]
                s = jnp.sum(Uv_s[c] * z[Uc_s[c]], axis=1)
                t = y[sr] - s
                zc = jnp.einsum(
                    "nij,nj->ni", ud_s[c], t.reshape(-1, b)
                ).reshape(-1)
                return z.at[sr].set(zc)

            z = jax.lax.fori_loop(
                0, ncol, bwd, jnp.zeros((N + 1,), r.dtype)
            )
            return z[:N]
        ncol = len(srows)
        # forward: L y = r (identity diagonal blocks)
        y = jnp.zeros_like(r)
        for c in range(ncol):
            Lc, Lv = Ls[c]
            s = jnp.sum(Lv * y[Lc], axis=1)
            y = y.at[srows[c]].set(r[srows[c]] - s)
        # backward: U z = y with inverted pivot blocks
        z = jnp.zeros_like(r)
        for c in range(ncol - 1, -1, -1):
            Uc, Uv = Us[c]
            s = jnp.sum(Uv * z[Uc], axis=1)
            t = y[srows[c]] - s
            if b == 1:
                zc = udinv[c].reshape(-1) * t
            else:
                zc = jnp.einsum(
                    "nij,nj->ni", udinv[c], t.reshape(-1, b)
                ).reshape(-1)
            z = z.at[srows[c]].set(zc)
        return z

