"""Multicolor DILU (diagonal-ILU(0)) smoother — the reference's workhorse
preconditioner (multicolor_dilu_solver.cu, 4259 LoC of block-size
specialized CUDA).

Math: with coloring-induced ordering and E the DILU diagonal,

    E_i = a_ii - sum_{j in N(i), color(j) < color(i)} a_ij E_j^{-1} a_ji
    M   = (E + L) E^{-1} (E + U)

where L/U are the strictly lower/upper (by color order) parts of A.
Apply M^{-1} r: forward color sweep solves (E+L) y = r, backward sweep
solves (E+U) z = E y.

TPU form: E is computed at setup with a host loop over colors (vectorized
scipy per color — the analogue of the reference's per-color setup
kernels); L/U are the same CSR structure with masked values, so each
sweep stage is one masked SpMV + select, ``2 * num_colors`` stages per
application, all fused under jit.  Scalar (block_size 1) for now.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from amgx_tpu.core.matrix import SparseMatrix
from amgx_tpu.ops.coloring import color_matrix
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import register_solver


@register_solver("MULTICOLOR_DILU")
class MulticolorDILUSolver(Solver):
    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.scheme = str(cfg.get("matrix_coloring_scheme", scope))
        self.deterministic = bool(cfg.get("determinism_flag", scope))

    def _setup_impl(self, A: SparseMatrix):
        from amgx_tpu.ops.diagonal import scalarized

        A = scalarized(A, "MULTICOLOR_DILU")
        colors = color_matrix(A, self.scheme, self.deterministic)
        self.num_colors = int(colors.max()) + 1

        indptr = np.asarray(A.row_offsets)
        indices = np.asarray(A.col_indices)
        vals = np.asarray(A.values)
        n = A.n_rows
        row_ids = np.asarray(A.row_ids)

        lower = colors[indices] < colors[row_ids]
        upper = colors[indices] > colors[row_ids]

        # E via W = A .* A^T on the intersected sparsity (host scipy)
        import scipy.sparse as sps

        Asp = sps.csr_matrix((vals, indices, indptr), shape=(n, n))
        W = Asp.multiply(Asp.T).tocsr()  # w_ij = a_ij * a_ji
        W.sort_indices()
        E = np.array(np.asarray(A.diag), copy=True)
        for c in range(1, self.num_colors):
            rows_c = np.nonzero(colors == c)[0]
            if rows_c.size == 0:
                continue
            with np.errstate(divide="ignore", invalid="ignore"):
                einv = np.where(
                    (E != 0) & (colors < c), 1.0 / E, 0.0
                )
            corr = W[rows_c] @ einv
            E[rows_c] = np.asarray(A.diag)[rows_c] - corr
        E = np.where(E == 0, 1.0, E)  # zero-pivot guard

        A_L = SparseMatrix.from_csr(
            indptr, indices, np.where(lower, vals, 0.0),
            n_cols=A.n_cols, build_ell=A.has_ell,
        )
        A_U = SparseMatrix.from_csr(
            indptr, indices, np.where(upper, vals, 0.0),
            n_cols=A.n_cols, build_ell=A.has_ell,
        )
        einv = (1.0 / E).astype(vals.dtype)
        self._params = (A, A_L, A_U, jnp.asarray(einv), jnp.asarray(colors))

    def _apply_M_inv(self, params, r):
        A, A_L, A_U, einv, colors = params
        ncol = self.num_colors
        # forward: (E+L) y = r
        y = jnp.zeros_like(r)
        for c in range(ncol):
            cand = (r - spmv(A_L, y)) * einv
            y = jnp.where(colors == c, cand, y)
        # backward: (E+U) z = E y  ->  z = y - Einv (U z)
        z = y
        for c in range(ncol - 1, -1, -1):
            cand = y - einv * spmv(A_U, z)
            z = jnp.where(colors == c, cand, z)
        return z

    def make_residual_step(self):
        omega = self.relaxation_factor

        def rstep(params, b, x, r):
            return x + omega * self._apply_M_inv(params, r)

        return rstep

    def make_apply(self):
        omega = self.relaxation_factor
        step = self.make_step()
        iters = max(self.max_iters, 1)

        def apply(params, r):
            z = omega * self._apply_M_inv(params, r)
            for _ in range(iters - 1):
                z = step(params, r, z)
            return z

        return apply


@register_solver("MULTICOLOR_ILU")
class MulticolorILUSolver(MulticolorDILUSolver):
    """ILU(0) approximation: the reference multicolor_ilu_solver.cu keeps
    full L/U factors; DILU is its diagonal variant and a good stand-in
    until the factorized version lands (ilu_sparsity_level=0 only)."""
