"""Solver algorithms and the name->factory registry.

Importing this package registers all built-in solvers (the analogue of
registerClasses at amgx::initialize, reference core.cu:552-688).

Registered here: PCG, CG, PCGF, SSTEP_PCG, PBICGSTAB, BICGSTAB, FGMRES,
GMRES, IDR, IDRMSYNC, BLOCK_JACOBI, JACOBI_L1, GS, MULTICOLOR_GS,
FIXCOLOR_GS, MULTICOLOR_DILU, MULTICOLOR_ILU, CHEBYSHEV, CHEBYSHEV_POLY,
POLYNOMIAL, KPZ_POLYNOMIAL, OPT_POLYNOMIAL, KACZMARZ, CF_JACOBI,
DENSE_LU_SOLVER, NOSOLVER.
The AMG solver registers when amgx_tpu.amg is imported (amgx_tpu.initialize
does both).
"""

from amgx_tpu.solvers.registry import (
    SolverRegistry,
    register_solver,
    create_solver,
)
from amgx_tpu.solvers.base import Solver, SolveResult

# registration side effects
from amgx_tpu.solvers import (  # noqa: F401
    cf_jacobi,
    chebyshev,
    dense_lu,
    dilu,
    dummy,
    gmres,
    gs,
    idr,
    inexact,
    jacobi,
    kaczmarz,
    krylov,
    polynomial,
    refinement,
    sstep,
)

__all__ = [
    "SolverRegistry",
    "register_solver",
    "create_solver",
    "Solver",
    "SolveResult",
]
