"""Solver algorithms and the name->factory registry.

Importing this package registers all built-in solvers (the analogue of
registerClasses at amgx::initialize, reference core.cu:552-688).
"""

from amgx_tpu.solvers.registry import (
    SolverRegistry,
    register_solver,
    create_solver,
)

__all__ = ["SolverRegistry", "register_solver", "create_solver"]
