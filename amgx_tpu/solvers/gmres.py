"""GMRES / FGMRES with restarts (reference gmres_solver.cu,
fgmres_solver.cu).

Structure: restart cycles of Arnoldi with modified Gram-Schmidt and Givens
rotations.  The reference runs Givens on host (fgmres_solver.cu:233-250);
here the whole solve — outer restart ``while_loop``, inner Arnoldi
``while_loop`` with masked MGS over the static Krylov dimension, and the
masked triangular solve — is one jitted program, so nothing syncs with the
host per iteration.

GMRES is left-preconditioned (Krylov space of M A); FGMRES is flexible
right-preconditioned, storing the preconditioned vectors Z_j so the
preconditioner may change between iterations.  Complex modes (dZ*/dC*,
reference amgx_config.h:103-121) use conjugated MGS projections and the
unitary Givens scheme; real dtypes recover the classical formulas
exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import (
    DIVERGED,
    FAILED,
    NOT_CONVERGED,
    SUCCESS,
    SolveResult,
)
from amgx_tpu.solvers.krylov import KrylovSolver
from amgx_tpu.solvers.registry import register_solver


@register_solver("FGMRES")
class FGMRESSolver(KrylovSolver):
    flexible = True

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.restart = int(cfg.get("gmres_n_restart", scope))
        # reference fgmres_solver.cu:235-241: gmres_krylov_dim > 0 caps
        # the Krylov basis below the restart length
        kdim = int(cfg.get("gmres_krylov_dim", scope))
        if kdim > 0:
            self.restart = min(self.restart, kdim)

    def make_solve(self):
        return self._build_solve(self.max_iters, self.monitor_residual)

    def _build_solve(self, max_iters, monitored):
        M = self._make_M()
        m = self.restart
        flexible = self.flexible
        conv_check = (
            self._conv_check
            if monitored
            else (lambda *a: jnp.asarray(False))
        )
        rel_div = self.rel_div_tolerance

        def solve(params, b, x0):
            A, Mp = params
            n = b.shape[0]
            dt = b.dtype

            def precond_resid(x):
                r = b - spmv(A, x)
                return r if flexible else M(Mp, r)

            def arnoldi_step(c):
                (j, V, Z, H, g, cs, sn, it, hist, status, ini, mx) = c
                v = V[j]
                if flexible:
                    z = M(Mp, v)
                    w = spmv(A, z)
                    Z = Z.at[j].set(z)
                else:
                    w = M(Mp, spmv(A, v))
                # masked modified Gram-Schmidt over the static dimension
                hcol = jnp.zeros(m + 1, dt)

                def mgs(i, wc):
                    w, hcol = wc
                    # conjugated projection (complex modes dZ*/dC*):
                    # vdot conjugates V[i]; identical to dot for reals
                    h = jnp.where(i <= j, jnp.vdot(V[i], w), 0.0)
                    w = w - h * V[i]
                    return (w, hcol.at[i].set(h))

                w, hcol = jax.lax.fori_loop(0, m, mgs, (w, hcol))
                hlast = jnp.sqrt(jnp.real(jnp.vdot(w, w)))
                hcol = hcol.at[j + 1].set(hlast)
                V = V.at[j + 1].set(w / jnp.where(hlast > 0, hlast, 1.0))

                # apply existing Givens rotations to the new column
                def rot(i, hc):
                    # unitary Givens: [[c, s], [-conj(s), conj(c)]]
                    # (reduces to the real rotation when dt is real)
                    t = cs[i] * hc[i] + sn[i] * hc[i + 1]
                    u = (-jnp.conj(sn[i]) * hc[i]
                         + jnp.conj(cs[i]) * hc[i + 1])
                    do = i < j
                    return hc.at[i].set(jnp.where(do, t, hc[i])).at[
                        i + 1
                    ].set(jnp.where(do, u, hc[i + 1]))

                hcol = jax.lax.fori_loop(0, m, rot, hcol)
                hj, hj1 = hcol[j], hcol[j + 1]
                denom = jnp.sqrt(
                    jnp.real(hj * jnp.conj(hj))
                    + jnp.real(hj1 * jnp.conj(hj1))
                )
                denom = jnp.where(denom > 0, denom, 1.0)
                # G = [[conj(hj), conj(hj1)], [-hj1, hj]] / denom is
                # unitary and maps (hj, hj1) -> (denom, 0); real dtypes
                # recover the classical (c, s) = (hj, hj1)/denom
                c_new = jnp.conj(hj) / denom
                s_new = jnp.conj(hj1) / denom
                hcol = hcol.at[j].set(denom).at[j + 1].set(0.0)
                cs = cs.at[j].set(c_new)
                sn = sn.at[j].set(s_new)
                gj = g[j]
                g = g.at[j].set(c_new * gj).at[j + 1].set(
                    -jnp.conj(s_new) * gj)
                H = H.at[:, j].set(hcol)

                res_est = jnp.abs(g[j + 1])
                it = it + 1
                hist = hist.at[it, 0].set(res_est)
                nrm = jnp.atleast_1d(res_est)
                mx = jnp.maximum(mx, nrm)
                done = conv_check(nrm, ini, mx)
                status = jnp.where(
                    done, jnp.int32(SUCCESS), jnp.int32(NOT_CONVERGED)
                )
                if rel_div > 0:
                    status = jnp.where(
                        jnp.any(nrm > rel_div * ini),
                        jnp.int32(DIVERGED),
                        status,
                    )
                status = jnp.where(
                    ~jnp.isfinite(res_est), jnp.int32(FAILED), status
                )
                return (j + 1, V, Z, H, g, cs, sn, it, hist, status, ini, mx)

            def arnoldi_cond(c):
                j, it, status = c[0], c[7], c[9]
                return (
                    (j < m) & (status == NOT_CONVERGED) & (it < max_iters)
                )

            def restart_body(c):
                x, it, hist, status, ini, mx = c
                r = precond_resid(x)
                beta = jnp.sqrt(jnp.real(jnp.vdot(r, r)))
                V = jnp.zeros((m + 1, n), dt)
                V = V.at[0].set(r / jnp.where(beta > 0, beta, 1.0))
                Z = jnp.zeros((m if flexible else 1, n), dt)
                H = jnp.zeros((m + 1, m), dt)
                g = jnp.zeros(m + 1, dt).at[0].set(beta)
                cs = jnp.ones(m, dt)
                sn = jnp.zeros(m, dt)
                inner0 = (
                    jnp.int32(0), V, Z, H, g, cs, sn, it, hist, status,
                    ini, mx,
                )
                (
                    j, V, Z, H, g, cs, sn, it, hist, status, ini, mx
                ) = jax.lax.while_loop(arnoldi_cond, arnoldi_step, inner0)

                # masked upper-triangular solve H[:m,:m] y = g[:m]
                idx = jnp.arange(m)
                diag_fix = jnp.where(idx >= j, 1.0, 0.0)
                R = H[:m, :m] + jnp.diag(diag_fix)
                gm = jnp.where(idx < j, g[:m], 0.0)
                y = jax.scipy.linalg.solve_triangular(R, gm, lower=False)
                basis = Z if flexible else V[:m]
                x = x + basis.T @ y
                return (x, it, hist, status, ini, mx)

            def outer_cond(c):
                it, status = c[1], c[3]
                return (status == NOT_CONVERGED) & (it < max_iters)

            rdt = jnp.zeros((), dt).real.dtype
            hist = jnp.full((max_iters + 1, 1), jnp.nan, rdt)
            r0 = precond_resid(x0)
            nrm0 = jnp.atleast_1d(jnp.sqrt(jnp.real(jnp.vdot(r0, r0))))
            hist = hist.at[0].set(nrm0)
            status0 = jnp.where(
                conv_check(nrm0, nrm0, nrm0) & monitored,
                jnp.int32(SUCCESS),
                jnp.int32(NOT_CONVERGED),
            )
            c0 = (x0, jnp.int32(0), hist, status0, nrm0, nrm0)
            x, it, hist, status, ini, mx = jax.lax.while_loop(
                outer_cond, restart_body, c0
            )
            final = hist[jnp.minimum(it, max_iters)]
            if not monitored:
                status = jnp.int32(SUCCESS)
            return SolveResult(
                x=x,
                iters=it,
                status=status,
                final_norm=final,
                initial_norm=ini,
                history=hist,
            )

        return solve

    def make_apply(self):
        """Nested-solver usage: fixed max_iters iterations, unmonitored."""
        solve = self._build_solve(max(self.max_iters, 1), monitored=False)

        def apply(params, r):
            return solve(params, r, jnp.zeros_like(r)).x

        return apply

    def make_smooth(self):
        """sweeps GMRES iterations (restarting as needed), unmonitored —
        honors the base contract fn(params, b, x, sweeps)."""
        cache = {}

        def smooth(params, b, x, sweeps):
            if sweeps not in cache:
                cache[sweeps] = self._build_solve(sweeps, monitored=False)
            return cache[sweeps](params, b, x).x

        return smooth


@register_solver("GMRES")
class GMRESSolver(FGMRESSolver):
    flexible = False
