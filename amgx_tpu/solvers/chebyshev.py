"""Chebyshev iteration (reference cheb_solver.cu, chebyshev_poly.cu).

One step applies an order-k Chebyshev polynomial in the Jacobi-
preconditioned operator D^{-1}A over the interval [lmin, lmax].  Interval:
user-provided (chebyshev_lambda_estimate_mode=1: cheby_min/max_lambda) or
estimated at setup by power iteration on D^{-1}A (mode 0), with
lmin = cheby_min_lambda * lmax (the reference default ratio 0.125).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from amgx_tpu.ops.diagonal import invert_diag
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import register_solver


def estimate_lambda_max(A, dinv, iters=20, seed=0):
    """Power iteration on D^{-1}A (host loop over device ops; setup-time)."""
    rng = np.random.default_rng(seed)
    v = jnp.asarray(rng.standard_normal(A.n_rows * A.block_size).astype(
        np.asarray(A.values).real.dtype
    ))
    lam = 1.0
    for _ in range(iters):
        w = dinv * spmv(A, v)
        lam = float(jnp.linalg.norm(w))
        v = w / jnp.maximum(lam, 1e-30)
    return lam


@register_solver("CHEBYSHEV")
class ChebyshevSolver(Solver):
    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.order = int(cfg.get("chebyshev_polynomial_order", scope))
        self.lambda_mode = int(
            cfg.get("chebyshev_lambda_estimate_mode", scope)
        )
        self.user_max = float(cfg.get("cheby_max_lambda", scope))
        self.user_min = float(cfg.get("cheby_min_lambda", scope))

    def _setup_impl(self, A):
        if A.block_size != 1:
            raise NotImplementedError("Chebyshev block matrices TBD")
        dinv = invert_diag(A)
        if self.lambda_mode == 0:
            lmax = 1.1 * estimate_lambda_max(A, dinv)
            lmin = self.user_min * lmax  # ratio semantics, default 0.125
        else:
            lmax, lmin = self.user_max, self.user_min
        self.lmax, self.lmin = float(lmax), float(lmin)
        self._params = (A, dinv)

    def make_step(self):
        k = max(self.order, 1)
        theta = (self.lmax + self.lmin) / 2.0
        delta = (self.lmax - self.lmin) / 2.0
        sigma = theta / delta

        def step(params, b, x):
            A, dinv = params
            rho_old = 1.0 / sigma
            r = b - spmv(A, x)
            d = dinv * r / theta
            x = x + d
            for _ in range(k - 1):
                rho = 1.0 / (2.0 * sigma - rho_old)
                r = b - spmv(A, x)
                d = rho * rho_old * d + (2.0 * rho / delta) * (dinv * r)
                x = x + d
                rho_old = rho
            return x

        return step


@register_solver("CHEBYSHEV_POLY")
class ChebyshevPolySolver(ChebyshevSolver):
    """Polynomial-smoother registration alias (reference chebyshev_poly.cu)."""
