"""Chebyshev iteration (reference cheb_solver.cu, chebyshev_poly.cu).

One step applies an order-k Chebyshev polynomial in the preconditioned
operator M^{-1}A over the eigenvalue interval [lmin, lmax].  The
preconditioner is the nested 'preconditioner' solver when configured
(e.g. JACOBI_L1 in AMG_CLASSICAL_AGGRESSIVE_CHEB_L1_TRUNC.json),
otherwise plain Jacobi D^{-1}.

Interval: chebyshev_lambda_estimate_mode == 3 takes the user's
cheby_min/max_lambda verbatim (reference cheb_solver.cu:209-211); modes
0-2 estimate lmax by power iteration
on M^{-1}A at setup (the reference's estimate modes differ only in GPU
implementation strategy), with lmin = cheby_min_lambda * lmax (reference
default ratio 0.125).

Spectral-bound caching (PR 8): the power iteration is the expensive
part of this setup, and on a values-only ``resetup`` (same sparsity
pattern, new coefficients — the streaming-PDE workload) the spectral
window moves only marginally while the 1.1 safety factor already
absorbs small shifts.  ``_resetup_impl`` therefore REUSES the cached
``lmax``/``lmin`` (previously every resetup fell back to a full setup
and re-ran the 20-step power iteration), bumping ``bound_staleness``;
the ``reestimate_eigs`` config knob re-runs the estimate every Nth
resetup (0 = never).  The cache rides the AMG hierarchy too: AMG's
``_finalize_setup`` resetups surviving level smoothers in place on
values-only refreshes instead of rebuilding them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from amgx_tpu.ops.diagonal import invert_diag, scalarized
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import register_solver


@register_solver("CHEBYSHEV")
class ChebyshevSolver(Solver):
    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.order = int(cfg.get("chebyshev_polynomial_order", scope))
        self.lambda_mode = int(
            cfg.get("chebyshev_lambda_estimate_mode", scope)
        )
        self.user_max = float(cfg.get("cheby_max_lambda", scope))
        self.user_min = float(cfg.get("cheby_min_lambda", scope))
        # spectral-bound cache bookkeeping: resetups served off the
        # cached window since the last power iteration, and the knob
        # that forces a re-estimate every Nth resetup (0 = never)
        self.reestimate_eigs = int(cfg.get("reestimate_eigs", scope))
        self.bound_staleness = 0
        self._resetups_since_estimate = 0
        from amgx_tpu.solvers.krylov import resolve_preconditioner

        # NOSOLVER (or nothing configured in scope) -> Jacobi default
        name, _ = cfg.get_scoped("preconditioner", scope)
        self.precond = (
            resolve_preconditioner(cfg, scope)
            if cfg.has("preconditioner", scope) and name != "NOSOLVER"
            else None
        )

    def _make_M(self):
        if self.precond is None:
            return lambda Mp, r: Mp * r  # Mp is dinv
        return self.precond.make_apply()

    def _setup_impl(self, A):
        if self.precond is not None:
            self.precond.setup(A)
            Mp = self.precond.apply_params()
        else:
            A = scalarized(A, "CHEBYSHEV")
            Mp = invert_diag(A)
        M = self._make_M()
        # reference cheb_solver.cu:153-216: mode 3 takes the user's
        # cheby_max/min_lambda verbatim; the other modes estimate lmax
        if self.lambda_mode == 3:
            lmax, lmin = self.user_max, self.user_min
        else:
            lmax = 1.1 * self._estimate_lambda_max(A, M, Mp)
            lmin = self.user_min * lmax  # ratio semantics, default 0.125
        self.lmax, self.lmin = float(lmax), float(lmin)
        self.bound_staleness = 0
        self._resetups_since_estimate = 0
        self._params = (A, Mp)

    def _resetup_impl(self, A):
        """Values-only refresh with the cached spectral window
        (module docstring): rebuild the cheap preconditioner state,
        re-run the power iteration only on the ``reestimate_eigs``
        cadence."""
        if self.precond is not None:
            self.precond.resetup(A)
            A2, Mp = A, self.precond.apply_params()
        else:
            A2 = scalarized(A, self.registry_name)
            A0 = self._params[0]
            if A2.n_rows != A0.n_rows or A2.nnz != A0.nnz:
                return False
            Mp = invert_diag(A2)
        if self.lambda_mode != 3:
            self._resetups_since_estimate += 1
            if (
                self.reestimate_eigs > 0
                and self._resetups_since_estimate
                >= self.reestimate_eigs
            ):
                lmax = 1.1 * self._estimate_lambda_max(
                    A2, self._make_M(), Mp
                )
                self.lmax = float(lmax)
                self.lmin = float(self.user_min * lmax)
                self.bound_staleness = 0
                self._resetups_since_estimate = 0
            else:
                self.bound_staleness += 1
        self._params = (A2, Mp)
        return True

    def make_batch_params(self):
        """Traced values-only rebuild for vmapped serve groups: the
        operator and diagonal preconditioner re-derive per instance;
        the spectral window stays the CACHED setup-time bounds —
        pattern-level state shared across the group, exactly like the
        resetup cache above (the 1.1 safety factor absorbs the
        group's coefficient jitter)."""
        if self.precond is not None:
            sub = self.precond.make_batch_params()
            if sub is None:
                return None
            ptmpl, pfn = sub
            A0 = self._params[0]

            def fn(t, v):
                At, pt = t
                return At.replace_values(v), pfn(pt, v)

            return (A0, ptmpl), fn
        A0 = self._params[0]
        if A0 is not self.A:
            # block input was scalar-expanded at setup: the incoming
            # values array no longer maps 1:1 onto the operator
            return None
        from amgx_tpu.ops.diagonal import invert_diag_jnp

        def fn(t, v):
            A2 = t.replace_values(v)
            return A2, invert_diag_jnp(A2)

        return A0, fn

    def _export_impl(self):
        # persistence (amgx_tpu.store): keep the estimated spectrum
        # bounds (the power iteration is the non-trivial part of this
        # setup) and recurse into the preconditioner if one exists
        state = {"lmax": float(self.lmax), "lmin": float(self.lmin)}
        if self.precond is not None:
            state["precond"] = self.precond._export_setup()
        return state

    def _import_impl(self, impl):
        if not impl or "lmax" not in impl:
            return self._setup_impl(self.A)
        if self.precond is not None:
            if impl.get("precond") is None:
                return self._setup_impl(self.A)
            self.precond._import_setup(impl["precond"])
            A, Mp = self.A, self.precond.apply_params()
        else:
            A = scalarized(self.A, "CHEBYSHEV")
            Mp = invert_diag(A)
        self.lmax = float(impl["lmax"])
        self.lmin = float(impl["lmin"])
        self._params = (A, Mp)

    def _estimate_lambda_max(self, A, M, Mp, iters=20, seed=0):
        """Power iteration on M^{-1}A (setup-time, jitted step)."""
        rng = np.random.default_rng(seed)
        rdt = np.zeros((), A.values.dtype).real.dtype
        v = jnp.asarray(
            rng.standard_normal(A.n_rows * A.block_size).astype(rdt)
        )

        @jax.jit
        def step(v):
            w = M(Mp, spmv(A, v))
            lam = jnp.linalg.norm(w)
            return w / jnp.maximum(lam, 1e-30), lam

        lam = 1.0
        for _ in range(iters):
            v, lam_j = step(v)
            lam = float(lam_j)
        return max(lam, 1e-12)

    def make_step(self):
        k = max(self.order, 1)
        theta = (self.lmax + self.lmin) / 2.0
        delta = max((self.lmax - self.lmin) / 2.0, 1e-30)
        sigma = theta / delta
        M = self._make_M()

        def step(params, b, x):
            A, Mp = params
            rho_old = 1.0 / sigma
            r = b - spmv(A, x)
            d = M(Mp, r) / theta
            x = x + d
            for _ in range(k - 1):
                rho = 1.0 / (2.0 * sigma - rho_old)
                r = b - spmv(A, x)
                d = rho * rho_old * d + (2.0 * rho / delta) * M(Mp, r)
                x = x + d
                rho_old = rho
            return x

        return step


@register_solver("CHEBYSHEV_POLY")
class ChebyshevPolySolver(ChebyshevSolver):
    """Polynomial-smoother registration alias (reference chebyshev_poly.cu)."""
