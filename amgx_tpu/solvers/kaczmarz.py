"""Multicolor Kaczmarz row-projection smoother (reference
kaczmarz_solver.cu).

Update for row i:  x += a_i^T (b_i - a_i x) / ||a_i||^2, executed one
color at a time so same-color rows (structurally orthogonal) update in
parallel:  delta_c = mask_c * r / rownorm2;  x += A^T delta_c.
A^T is prebuilt at setup; the sweep is num_colors SpMV(A^T) stages.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from amgx_tpu.core.matrix import SparseMatrix
from amgx_tpu.ops.coloring import color_matrix
from amgx_tpu.ops.diagonal import scalarized
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import register_solver


@register_solver("KACZMARZ")
class KaczmarzSolver(Solver):
    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.scheme = str(cfg.get("matrix_coloring_scheme", scope))
        self.deterministic = bool(cfg.get("determinism_flag", scope))
        self.coloring_needed = bool(
            cfg.get("kaczmarz_coloring_needed", scope)
        )

    def _setup_impl(self, A: SparseMatrix):
        A = scalarized(A, "KACZMARZ")
        sp = A.to_scipy()
        At = SparseMatrix.from_scipy(sp.T.tocsr().astype(sp.dtype))
        rownorm2 = np.asarray(sp.multiply(sp).sum(axis=1)).ravel()
        rownorm2 = np.where(rownorm2 > 0, rownorm2, 1.0)
        if self.coloring_needed:
            colors = color_matrix(A, self.scheme, self.deterministic,
                              cfg=self.cfg, scope=self.scope)
        else:
            colors = np.zeros(A.n_rows, dtype=np.int32)
        self.num_colors = int(colors.max()) + 1
        self._params = (
            A,
            At,
            jnp.asarray(1.0 / rownorm2),
            jnp.asarray(colors),
        )

    def make_step(self):
        omega = self.relaxation_factor
        ncol = self.num_colors

        def step(params, b, x):
            A, At, inv_rn2, colors = params
            for c in range(ncol):
                r = b - spmv(A, x)
                delta = jnp.where(colors == c, r * inv_rn2, 0.0)
                x = x + omega * spmv(At, delta)
            return x

        return step
