"""Equation scalers (reference src/scalers/: BINORMALIZATION,
NBINORMALIZATION, DIAGONAL_SYMMETRIC; hooked in Solver::setup/solve,
solver.cu:667-676).

A scaler computes row/col scaling vectors at setup, the solver then works
on As = Dr A Dc; rhs is scaled before the solve (b -> Dr b) and the
solution unscaled after (x -> Dc x).  For symmetric scalings Dr == Dc.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sps


class Scaler:
    """Computes (left, right) positive scaling vectors."""

    def compute(self, Asp: sps.csr_matrix):
        raise NotImplementedError


class DiagonalSymmetricScaler(Scaler):
    """As = D^{-1/2} A D^{-1/2} (reference diagonal_symmetric_scaler.cu)."""

    def compute(self, Asp):
        d = np.abs(Asp.diagonal())
        s = 1.0 / np.sqrt(np.where(d > 0, d, 1.0))
        return s, s


class BinormalizationScaler(Scaler):
    """Iterative binormalization (reference binormalization scalers,
    Livne-Golub): find u > 0 with u_i (B u)_i = 1 for B = |A|.^2, then
    D = diag(sqrt(u)) gives unit row/col 2-norms of D A D.  The damped
    (Knight-Ruiz style) symmetric iteration u <- sqrt(u / (B u)) is used —
    a SYMMETRIC scaling, so SPD systems stay SPD (alternating row/col
    Sinkhorn would produce r != c and break CG)."""

    def __init__(self, iters: int = 50):
        self.iters = iters

    def compute(self, Asp):
        B = Asp.copy().tocsr()
        B.data = np.abs(B.data) ** 2
        # symmetrize the weight graph so the iteration is well-defined for
        # mildly nonsymmetric A as well
        B = ((B + B.T) * 0.5).tocsr()
        n = B.shape[0]
        u = 1.0 / np.maximum(np.asarray(B.sum(axis=1)).ravel(), 1e-300)
        for _ in range(self.iters):
            Bu = B @ u
            u = np.sqrt(u / np.where(Bu > 0, Bu, 1.0))
        s = np.sqrt(u)
        return s, s


class NBinormalizationScaler(Scaler):
    """Nonsymmetric binormalization (reference nbinormalization.cu):
    with B = A.^2, alternately solve x = cols ./ (B y) and
    y = rows ./ (B' x); the scaling is Dr = diag(sqrt|x|),
    Dc = diag(sqrt|y|), equalizing row and column 2-norms of Dr A Dc.
    Unlike BINORMALIZATION the left and right scalings differ — the
    right choice for nonsymmetric systems (GMRES/BiCGStab), while SPD
    solvers should keep the symmetric variant."""

    def __init__(self, iters: int = 50, tolerance: float = 1e-10):
        self.iters = iters
        self.tolerance = tolerance

    def compute(self, Asp):
        B = Asp.copy().tocsr()
        B.data = B.data.astype(np.float64) ** 2
        rows, cols = B.shape
        Bt = B.T.tocsr()
        x = np.ones(rows)
        y = np.ones(cols)
        sum1, sum2 = float(cols), float(rows)
        beta = B @ y

        def _rms(resid, denom):
            return np.sqrt(np.mean(resid**2)) / denom

        for _ in range(self.iters):
            x = sum1 / np.where(beta > 0, beta, 1.0)
            gamma = Bt @ x
            # residuals measured against FRESH products of the other
            # side's stale iterate (structurally-zero rows/cols count
            # as satisfied — they cannot be equalized)
            std2 = _rms(
                np.where(gamma > 0, y * gamma - sum2, 0.0), sum2
            )
            y = sum2 / np.where(gamma > 0, gamma, 1.0)
            beta = B @ y
            std1 = _rms(
                np.where(beta > 0, x * beta - sum1, 0.0), sum1
            )
            if np.hypot(std1, std2) < self.tolerance:
                break
        return np.sqrt(np.abs(x)), np.sqrt(np.abs(y))


_SCALERS = {
    "DIAGONAL_SYMMETRIC": DiagonalSymmetricScaler,
    "BINORMALIZATION": BinormalizationScaler,
    "NBINORMALIZATION": NBinormalizationScaler,
}


def create_scaler(name: str):
    name = name.upper()
    if name in ("", "NONE"):
        return None
    try:
        return _SCALERS[name]()
    except KeyError:
        raise KeyError(
            f"unknown scaler {name!r}; known: {sorted(_SCALERS)}"
        ) from None
