"""Equation scalers (reference src/scalers/: BINORMALIZATION,
NBINORMALIZATION, DIAGONAL_SYMMETRIC; hooked in Solver::setup/solve,
solver.cu:667-676).

A scaler computes row/col scaling vectors at setup, the solver then works
on As = Dr A Dc; rhs is scaled before the solve (b -> Dr b) and the
solution unscaled after (x -> Dc x).  For symmetric scalings Dr == Dc.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sps


class Scaler:
    """Computes (left, right) positive scaling vectors."""

    def compute(self, Asp: sps.csr_matrix):
        raise NotImplementedError


class DiagonalSymmetricScaler(Scaler):
    """As = D^{-1/2} A D^{-1/2} (reference diagonal_symmetric_scaler.cu)."""

    def compute(self, Asp):
        d = np.abs(Asp.diagonal())
        s = 1.0 / np.sqrt(np.where(d > 0, d, 1.0))
        return s, s


class BinormalizationScaler(Scaler):
    """Iterative binormalization (reference binormalization scalers,
    Livne-Golub): find u > 0 with u_i (B u)_i = 1 for B = |A|.^2, then
    D = diag(sqrt(u)) gives unit row/col 2-norms of D A D.  The damped
    (Knight-Ruiz style) symmetric iteration u <- sqrt(u / (B u)) is used —
    a SYMMETRIC scaling, so SPD systems stay SPD (alternating row/col
    Sinkhorn would produce r != c and break CG)."""

    def __init__(self, iters: int = 50):
        self.iters = iters

    def compute(self, Asp):
        B = Asp.copy().tocsr()
        B.data = np.abs(B.data) ** 2
        # symmetrize the weight graph so the iteration is well-defined for
        # mildly nonsymmetric A as well
        B = ((B + B.T) * 0.5).tocsr()
        n = B.shape[0]
        u = 1.0 / np.maximum(np.asarray(B.sum(axis=1)).ravel(), 1e-300)
        for _ in range(self.iters):
            Bu = B @ u
            u = np.sqrt(u / np.where(Bu > 0, Bu, 1.0))
        s = np.sqrt(u)
        return s, s


_SCALERS = {
    "DIAGONAL_SYMMETRIC": DiagonalSymmetricScaler,
    "BINORMALIZATION": BinormalizationScaler,
    "NBINORMALIZATION": BinormalizationScaler,
}


def create_scaler(name: str):
    name = name.upper()
    if name in ("", "NONE"):
        return None
    try:
        return _SCALERS[name]()
    except KeyError:
        raise KeyError(
            f"unknown scaler {name!r}; known: {sorted(_SCALERS)}"
        ) from None
