"""Convergence criteria (reference src/convergence/, convergence.h:64-108).

Registered types: ABSOLUTE, RELATIVE_INI[_CORE], RELATIVE_MAX[_CORE],
COMBINED_REL_INI_ABS.  Each becomes a pure jit-safe predicate
``check(nrm, nrm_ini, nrm_max) -> bool`` built once per solver from static
config; block norms (nrm a vector) must converge in every component.
The divergence check (rel_div_tolerance, CHANGELOG:26) is layered on in
the solve loop, not here.
"""

from __future__ import annotations

import jax.numpy as jnp


def make_convergence_check(conv: str, tolerance: float, alt_rel_tol: float):
    conv = conv.upper()

    if conv == "ABSOLUTE":
        raw = lambda nrm, nrm_ini, nrm_max: jnp.all(nrm < tolerance)
    elif conv in ("RELATIVE_INI", "RELATIVE_INI_CORE"):
        raw = lambda nrm, nrm_ini, nrm_max: jnp.all(
            nrm < tolerance * nrm_ini
        )
    elif conv in ("RELATIVE_MAX", "RELATIVE_MAX_CORE"):
        raw = lambda nrm, nrm_ini, nrm_max: jnp.all(
            nrm < tolerance * nrm_max
        )
    elif conv == "COMBINED_REL_INI_ABS":
        raw = lambda nrm, nrm_ini, nrm_max: jnp.all(
            (nrm < tolerance) | (nrm < alt_rel_tol * nrm_ini)
        )
    else:
        raise ValueError(f"unknown convergence criterion {conv!r}")

    # an exactly-zero residual is always converged (relative criteria with
    # nrm_ini == 0, e.g. b == 0 and x0 == 0, would otherwise never stop)
    return lambda nrm, nrm_ini, nrm_max: raw(nrm, nrm_ini, nrm_max) | jnp.all(
        nrm == 0
    )
