"""IDR(s) — induced dimension reduction (reference idr_solver.cu,
idrmsync_solver.cu; van Gijzen & Sonneveld biortho variant).

The shadow space dimension s (subspace_dim_s, default 8) is static, so
the inner k-loop unrolls with static shapes; the whole solve is one
jitted while_loop over outer cycles.  IDRMSYNC differs from IDR only in
GPU synchronization strategy — meaningless under XLA — so it aliases.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from amgx_tpu.ops.blas import dot
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import (
    DIVERGED,
    FAILED,
    NOT_CONVERGED,
    SUCCESS,
    SolveResult,
)
from amgx_tpu.solvers.krylov import KrylovSolver
from amgx_tpu.solvers.registry import register_solver


@register_solver("IDR")
class IDRSolver(KrylovSolver):
    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.s = int(cfg.get("subspace_dim_s", scope))

    def make_solve(self):
        return self._build_solve(self.max_iters, self.monitor_residual)

    def _build_solve(self, max_iters, monitored):
        M = self._make_M()
        # shadow space cannot exceed the system size
        s = min(self.s, self.A.n_rows * self.A.block_size)
        norm_of = self.make_norm()
        rel_div = self.rel_div_tolerance
        conv_check = (
            self._conv_check
            if monitored
            else (lambda *a: jnp.asarray(False))
        )

        def solve(params, b, x0):
            A, Mp = params
            n = b.shape[0]
            dt = b.dtype
            # deterministic orthonormal shadow space
            rng = np.random.default_rng(42)
            Phost = rng.standard_normal((n, s))
            Phost, _ = np.linalg.qr(Phost)
            P = jnp.asarray(Phost.T.astype(dt))  # (s, n)

            r0 = b - spmv(A, x0)
            nrm0 = norm_of(r0)

            def outer(c):
                (it, x, r, G, U, Mm, om, nrm_max, hist, status) = c
                f = jnp.conj(P) @ r if jnp.iscomplexobj(r) else P @ r
                # inner: s dimension-reduction steps (static unroll)
                for k in range(s):
                    Mkk = Mm[k:, k:]
                    # guard exact-zero pivots (residual hit zero mid-loop:
                    # f is zero there, so the unit pivot is inert)
                    dsafe = jnp.where(jnp.diag(Mkk) == 0, 1.0, 0.0)
                    ck = jax.scipy.linalg.solve_triangular(
                        Mkk + jnp.diag(dsafe), f[k:], lower=True
                    )
                    v = r - ck @ G[k:]
                    v = M(Mp, v)
                    u = om * v + ck @ U[k:]
                    g = spmv(A, u)
                    for i in range(k):
                        mii = jnp.where(Mm[i, i] != 0, Mm[i, i], 1.0)
                        alpha = dot(P[i], g) / mii
                        g = g - alpha * G[i]
                        u = u - alpha * U[i]
                    col = jnp.conj(P[k:]) @ g if jnp.iscomplexobj(g) else P[k:] @ g
                    Mm = Mm.at[k:, k].set(col)
                    beta = f[k] / jnp.where(Mm[k, k] != 0, Mm[k, k], 1.0)
                    r = r - beta * g
                    x = x + beta * u
                    f = f.at[k:].add(-beta * Mm[k:, k])
                    G = G.at[k].set(g)
                    U = U.at[k].set(u)
                # dimension reduction step
                v = M(Mp, r)
                t = spmv(A, v)
                tt = dot(t, t)
                om = jnp.where(jnp.real(tt) > 0, dot(t, r) / tt, om)
                x = x + om * v
                r = r - om * t
                it = it + 1
                nrm = norm_of(r)
                nrm_max = jnp.maximum(nrm_max, nrm)
                hist = hist.at[it].set(nrm)
                done = conv_check(nrm, nrm0, nrm_max)
                status = jnp.where(
                    done, jnp.int32(SUCCESS), jnp.int32(NOT_CONVERGED)
                )
                if rel_div > 0:
                    status = jnp.where(
                        jnp.any(nrm > rel_div * nrm0),
                        jnp.int32(DIVERGED),
                        status,
                    )
                status = jnp.where(
                    ~jnp.all(jnp.isfinite(nrm)), jnp.int32(FAILED), status
                )
                return (it, x, r, G, U, Mm, om, nrm_max, hist, status)

            def cond(c):
                return (c[9] == NOT_CONVERGED) & (c[0] < max_iters)

            rdt = jnp.zeros((), dt).real.dtype
            ncomp = self.norm_components
            hist = jnp.full((max_iters + 1, ncomp), jnp.nan, rdt)
            hist = hist.at[0].set(nrm0)
            G = jnp.zeros((s, n), dt)
            U = jnp.zeros((s, n), dt)
            Mm = jnp.eye(s, dtype=dt)
            status0 = jnp.where(
                conv_check(nrm0, nrm0, nrm0) & monitored,
                jnp.int32(SUCCESS),
                jnp.int32(NOT_CONVERGED),
            )
            c0 = (
                jnp.int32(0), x0, r0, G, U, Mm, jnp.ones((), dt), nrm0,
                hist, status0,
            )
            c = jax.lax.while_loop(cond, outer, c0)
            it, x = c[0], c[1]
            hist = c[8]
            status = c[9] if monitored else jnp.int32(SUCCESS)
            final = hist[jnp.minimum(it, max_iters)]
            return SolveResult(
                x=x,
                iters=it,
                status=status,
                final_norm=final,
                initial_norm=nrm0,
                history=hist,
            )

        return solve

    def make_apply(self):
        solve = self._build_solve(max(self.max_iters, 1), monitored=False)

        def apply(params, r):
            return solve(params, r, jnp.zeros_like(r)).x

        return apply

    def make_smooth(self):
        cache = {}

        def smooth(params, b, x, sweeps):
            if sweeps not in cache:
                cache[sweeps] = self._build_solve(sweeps, monitored=False)
            return cache[sweeps](params, b, x).x

        return smooth


@register_solver("IDRMSYNC")
class IDRMSyncSolver(IDRSolver):
    """Reduced-synchronization IDR(s) (reference idrmsync_solver.cu) —
    identical math under XLA."""
