"""Jacobi-family smoothers.

Reference parity: block_jacobi_solver.cu (BLOCK_JACOBI, the default
smoother, core.cu:385), jacobi_l1_solver.cu (JACOBI_L1).  TPU form: the
sweep is one SpMV + elementwise update — bandwidth-bound, XLA fuses the
update chain; block-diagonal inverses are precomputed at setup with
vectorized ``jnp.linalg.inv`` over the (n, b, b) diagonal blocks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from amgx_tpu.ops.diagonal import apply_dinv, invert_diag, scalarized
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import register_solver


class _DiagSmootherBase(Solver):
    """Shared x += omega * Dinv r machinery; subclasses build Dinv."""

    def make_residual_step(self):
        omega = self.relaxation_factor
        # block size of the OPERATOR matrix in params (JACOBI_L1
        # scalarizes at setup, so self.A.block_size may differ)
        b_sz = self._params[0].block_size

        def rstep(params, b, x, r):
            _, dinv = params
            return x + omega * apply_dinv(dinv, r, b_sz)

        return rstep

    def make_apply(self):
        # zero-guess first sweep simplifies to omega*Dinv b; subsequent
        # sweeps use full steps (reference smooth_with_0_initial_guess)
        step = self.make_step()
        omega = self.relaxation_factor
        b_sz = self._params[0].block_size
        iters = max(self.max_iters, 1)

        def apply(params, r):
            _, dinv = params
            z = omega * apply_dinv(dinv, r, b_sz)
            if iters - 1 <= self._UNROLL_LIMIT:
                for _ in range(iters - 1):
                    z = step(params, r, z)
                return z
            return jax.lax.fori_loop(
                0, iters - 1, lambda i, z: step(params, r, z), z
            )

        return apply


@register_solver("BLOCK_JACOBI")
class BlockJacobiSolver(_DiagSmootherBase):
    """x += omega * D^{-1} (b - A x); D = (block) diagonal."""

    def _setup_impl(self, A):
        self._params = (A, invert_diag(A))

    def make_batch_params(self):
        from amgx_tpu.ops.diagonal import invert_diag_jnp

        def fn(t, v):
            A = t.replace_values(v)
            return A, invert_diag_jnp(A)

        return self._params[0], fn


@register_solver("JACOBI_L1")
class JacobiL1Solver(_DiagSmootherBase):
    """L1-Jacobi: d_i = |a_ii| + sum_{j != i} |a_ij| guarantees convergence
    for any symmetric A (reference jacobi_l1_solver.cu)."""

    def _setup_impl(self, A):
        A = scalarized(A, "JACOBI_L1")
        vals = np.asarray(A.values)
        row_ids = np.asarray(A.row_ids)
        cols = np.asarray(A.col_indices)
        offdiag = np.zeros(A.n_rows, dtype=np.abs(vals).dtype)
        np.add.at(offdiag, row_ids, np.abs(vals) * (cols != row_ids))
        d = np.abs(np.asarray(A.diag)) + offdiag
        with np.errstate(divide="ignore"):
            dinv = np.where(d != 0, 1.0 / d, 1.0)
        self._params = (A, jnp.asarray(dinv.astype(vals.dtype)))

    def make_batch_params(self):
        A0 = self._params[0]
        if A0 is not self.A:
            # block input was scalar-expanded at setup: the incoming
            # values array no longer maps 1:1 onto the operator
            return None

        def fn(t, v):
            A = t.replace_values(v)
            av = jnp.abs(A.values)
            offd = jax.ops.segment_sum(
                av * (A.col_indices != A.row_ids),
                A.row_ids,
                num_segments=A.n_rows,
                indices_are_sorted=True,
            )
            d = jnp.abs(A.diag) + offd
            dinv = jnp.where(d != 0, 1.0 / jnp.where(d != 0, d, 1.0), 1.0)
            return A, dinv

        return A0, fn
