"""Inexact coarse solver: kill the DenseLU bottom of the hierarchy.

Every AMG hierarchy here used to bottom out in DENSE_LU: setup pays an
O(n^3) factorization on the coarsest operator, the artifact store pays
the dense-factor bytes, and under mesh/domain sharding the dense solve
is the serialization point.  The inexact-coarse-solver analysis for
AMG and s-step CG (arxiv 2512.09642; SParSH-AMG, arxiv 2007.00056,
makes the same move for reduced-precision hierarchies) shows the
V-cycle tolerates a bounded coarse-solve perturbation: a few fixed
iterations of a polynomial smoother or (s-step) PCG preserve the cycle
convergence rate, so the exact factorization buys almost nothing.

``coarse_solver=INEXACT`` replaces the factorization with a
fixed-sweep run of ``inexact_coarse_solver`` (default
``OPT_POLYNOMIAL`` — the communication-free optimal-weight fourth-kind
Chebyshev chain, PR 8; ``SSTEP_PCG`` for a Krylov coarse solve whose
reductions amortize s-fold).  The sweep budget is linked to the cycle
depth — each additional level's smoothing absorbs more coarse-solve
error, and the coarse problem the budget must reduce gets easier the
deeper the hierarchy coarsens — and capped by ``max_coarse_iters``:

    sweeps = min(max_coarse_iters, 4 + 2 * cycle_depth)

(the AMG driver sets ``cycle_depth`` = level count before setup).
ci/precision_bench.py gates iteration parity (+10% inner-step
equivalents vs the DenseLU baseline at unchanged final tolerance) and
the measured coarse-setup-time / store-bytes reductions.

The class is a thin delegation shell: the inner solver owns params,
application, values-only resetup, setup persistence, and the vmapped
serve rebuild (``make_batch_params``), so INEXACT coarse hierarchies
batch, persist, and mesh-place exactly like any other config.
"""

from __future__ import annotations

from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import (
    SolverRegistry,
    make_nested,
    register_solver,
)


@register_solver("INEXACT")
class InexactCoarseSolver(Solver):
    """Fixed-budget iterative coarse solve (module docstring)."""

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        method, mscope = cfg.get_scoped("inexact_coarse_solver", scope)
        self.method = str(method).upper()
        self.inner = make_nested(
            SolverRegistry.get(self.method)(cfg, mscope)
        )
        from amgx_tpu.solvers.krylov import KrylovSolver

        # A Krylov inner whose preconditioner resolution falls through
        # to the registry default ("AMG") — or to a default/outer-scope
        # key (the flat-config layout, where "preconditioner" names the
        # OUTER solver's AMG) — would build hierarchies on the coarsest
        # level without bound.  Only a preconditioner set in a
        # DEDICATED inner scope (inexact_coarse_solver given as a
        # nested dict with its own scope) is honored; everything else
        # gets the unpreconditioned coarse iteration.
        explicit_precond = (
            mscope not in (scope, "default")
            and (mscope, "preconditioner") in cfg.items()
        )
        if (
            isinstance(self.inner, KrylovSolver)
            and self.inner.precond is not None
            and not explicit_precond
        ):
            self.inner.precond = None
        self.max_coarse_iters = max(
            int(cfg.get("max_coarse_iters", scope)), 1
        )
        # hierarchy depth the sweep budget is linked to; the AMG
        # driver (_new_coarse_solver) overwrites it before setup
        self.cycle_depth = 1

    # ------------------------------------------------------------------
    # sweep budget

    def sweep_budget(self) -> int:
        """Inner-step budget for one coarse solve: grows with cycle
        depth (deeper hierarchies coarsen the bottom problem further
        and smooth away more coarse-solve error), capped by
        ``max_coarse_iters``."""
        return min(self.max_coarse_iters, 4 + 2 * max(self.cycle_depth, 1))

    def _apply_budget(self):
        """Write the budget into the inner solver's iteration count.
        ``max_iters`` is an INNER-step budget for every solver family
        (SSTEP_PCG counts outer iterations of ``iterations_scale``
        steps each, so the budget rounds up to whole outers)."""
        scale = max(int(self.inner.iterations_scale), 1)
        self.inner.max_iters = max(-(-self.sweep_budget() // scale), 1)

    # ------------------------------------------------------------------
    # setup / resetup / persistence — delegation

    def _setup_impl(self, A):
        self._apply_budget()
        self.inner.setup(A)
        self._params = self.inner.apply_params()

    def _resetup_impl(self, A) -> bool:
        self.inner.resetup(A)
        self._params = self.inner.apply_params()
        return True

    def _export_impl(self):
        # persistence (amgx_tpu.store): the inner's setup state
        # (spectral bounds, preconditioner diagonals) rides along so a
        # restore re-derives nothing
        try:
            return {"inner": self.inner._export_setup()}
        except Exception:  # noqa: BLE001 — re-derive at import
            return None

    def _import_impl(self, impl):
        self._apply_budget()
        if not impl or impl.get("inner") is None:
            return self._setup_impl(self.A)
        self.inner._import_setup(impl["inner"])
        self._params = self.inner.apply_params()

    # ------------------------------------------------------------------
    # application — delegation (params are the inner's, kept in sync)

    def operator_of(self, params):
        return self.inner.operator_of(params)

    def make_apply(self):
        return self.inner.make_apply()

    def make_smooth(self):
        return self.inner.make_smooth()

    def make_step(self):
        return self.inner.make_step()

    def make_residual_step(self):
        return self.inner.make_residual_step()

    def make_solve(self):
        return self.inner.make_solve()

    def make_batch_params(self):
        """Traced values-only rebuild = the inner's (one pytree, one
        trace), so INEXACT coarse hierarchies ride the vmapped serve
        path unchanged."""
        return self.inner.make_batch_params()
