"""Solver framework (reference Solver<TConfig>, solver.h:21-278, solver.cu).

The reference contract — setup / solve_init / solve_iteration /
solve_finalize with a monitored outer loop (solver.cu:586-860) — maps to a
jit-first design:

  * ``setup(A)`` is host-side: builds preconditioner state (inverted
    diagonals, hierarchies, colorings) as pytrees of device arrays.
  * ``solve(b, x0)`` runs ONE fully-jitted function containing the entire
    iteration loop (``lax.while_loop``), residual monitoring, convergence
    and divergence checks, and residual-history recording.  One compile
    per (structure, shape) signature, cached.
  * Solvers used as preconditioners/smoothers expose pure functions:
      - ``make_apply()``  -> fn(params, r) -> z        (zero initial guess)
      - ``make_smooth()`` -> fn(params, b, x, sweeps) -> x
    with all arrays flowing through ``params`` (= ``apply_params()``), so
    outer solvers can embed them in their own jitted loops.

Stationary solvers (Jacobi/GS/DILU/Chebyshev-poly...) implement
``make_step`` and inherit the generic monitored loop; Krylov solvers
override ``make_solve`` wholesale.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from amgx_tpu.core import faults
from amgx_tpu.core.matrix import SparseMatrix
from amgx_tpu.core.printing import emit
from amgx_tpu.core.types import NormType

from amgx_tpu.ops.spmv import spmv
from amgx_tpu.ops.norms import norm as _norm, block_norm as _block_norm
from amgx_tpu.solvers.convergence import make_convergence_check


def device_memory_stats():
    """(bytes_in_use, peak_bytes_in_use) from the default device's
    runtime allocator (the TPU HBM counters behind the reference's
    MemoryInfo / "Mem Usage" column, include/memory_info.h:9-33), or
    None when the backend exposes no stats (CPU)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    used = stats.get("bytes_in_use")
    peak = stats.get("peak_bytes_in_use", used)
    if used is None:
        return None
    return used, peak


# AMGX_SOLVE_* status codes (reference amgx_c.h:75-80)
SUCCESS = 0
FAILED = 1  # hard failure (NaN/Inf residual)
DIVERGED = 2  # rel_div_tolerance exceeded
NOT_CONVERGED = 3


def donation_enabled() -> bool:
    """Buffer-donation default for jitted solve entry points.  ON for
    accelerator backends — donating x0 lets XLA alias the solution
    output onto it, saving an HBM buffer per solve.  OFF on CPU, where
    donation measurably serializes the otherwise-async XLA dispatch
    (~2ms blocking call vs ~0.3ms, see doc/SERVING.md) and buys
    nothing.  ``AMGX_TPU_DONATE=1/0`` overrides either way."""
    import os

    v = os.environ.get("AMGX_TPU_DONATE")
    if v is not None:
        return v != "0"
    return jax.default_backend() != "cpu"


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SolveResult:
    x: jnp.ndarray
    iters: jnp.ndarray  # i32 scalar
    status: jnp.ndarray  # i32: SUCCESS/FAILED/DIVERGED/NOT_CONVERGED
    final_norm: jnp.ndarray  # (ncomp,) real
    initial_norm: jnp.ndarray  # (ncomp,) real
    history: jnp.ndarray  # (max_iters+1, ncomp) real, NaN-padded

    @property
    def converged(self):
        return self.status == SUCCESS


class Solver:
    """Base solver. Subclasses register via @register_solver(NAME)."""

    registry_name = "?"
    # True if this solver ignores its operator (e.g. NOSOLVER)
    is_identity = False
    # inner steps per reported iteration (s-step solvers override: one
    # SSTEP_PCG outer iteration = s CG steps); telemetry and benches
    # multiply SolveResult.iters by this for cross-solver comparisons
    iterations_scale = 1

    def __init__(self, cfg, scope: str = "default"):
        self.cfg = cfg
        self.scope = scope
        g = lambda k: cfg.get(k, scope)
        self.max_iters = int(g("max_iters"))
        self.tolerance = float(g("tolerance"))
        self.conv_type = str(g("convergence"))
        self.norm_type = NormType(str(g("norm")))
        self.monitor_residual = bool(g("monitor_residual"))
        self.store_res_history = bool(g("store_res_history"))
        self.use_scalar_norm = bool(g("use_scalar_norm"))
        self.relaxation_factor = float(g("relaxation_factor"))
        self.print_solve_stats = bool(g("print_solve_stats"))
        self.obtain_timings = bool(g("obtain_timings"))
        # reference solver.cu:34,541-830: verbosity_level gates all
        # solve/grid printouts (>2 = full tables, 1-2 = summary line,
        # 0 = silent); solver_verbose=1 dumps the solver settings at
        # setup (solver.cu:349)
        self.verbosity = int(g("verbosity_level"))
        self.solver_verbose = bool(g("solver_verbose"))
        # reference convergence_analysis.cu: when > 0, print a
        # convergence-rate analysis over the final N iterations
        self.convergence_analysis = int(g("convergence_analysis"))
        self.rel_div_tolerance = float(g("rel_div_tolerance"))
        self.alt_rel_tolerance = float(g("alt_rel_tolerance"))
        # guardrails (core/errors.py taxonomy): stagnation detection
        # window and the retry-once-with-safer-config recovery hook
        self.stagnation_window = int(g("stagnation_window"))
        self.solve_retries = int(g("solve_retries"))
        self.solve_retries_used = 0
        self.scaling = str(g("scaling"))
        # overwritten to NONE by make_nested: only the outermost solve()
        # boundary may renumber unknowns
        self.reordering = str(g("matrix_reordering"))
        self._conv_check = make_convergence_check(
            self.conv_type, self.tolerance, self.alt_rel_tolerance
        )
        self.A: Optional[SparseMatrix] = None
        self._params: Any = None
        self._jit_cache: dict = {}
        self.setup_time = 0.0
        # seconds spent restoring a persisted setup (store.load_setup);
        # setup_time stays 0 on a restore — the pair is the
        # skipped-setup assertion surface of tests/test_store.py
        self.restore_time = 0.0
        self.solve_time = 0.0
        # compile-vs-execute split (PR 3): lifetime compile seconds and
        # the compile cost of the LAST solve() call (0 on warm calls)
        self.compile_time = 0.0
        self.last_compile_s = 0.0

    # ------------------------------------------------------------------
    # overridables

    def _setup_impl(self, A: SparseMatrix):
        """Host-side setup; must set self._params (pytree of arrays)."""
        self._params = A

    def make_step(self) -> Callable:
        """Pure fn(params, b, x) -> x : one relaxation sweep."""
        rstep = self.make_residual_step()
        if rstep is None:
            raise NotImplementedError(
                f"{type(self).__name__} provides no stationary step"
            )

        def step(params, b, x):
            A = self.operator_of(params)
            return rstep(params, b, x, b - spmv(A, x))

        return step

    def make_residual_step(self) -> Optional[Callable]:
        """Pure fn(params, b, x, r) -> x consuming the precomputed residual
        r = b - A x.  Solvers that can use it (Jacobi, DILU) return it so
        the monitored loop shares one SpMV per iteration between the step
        and the norm; others return None."""
        return None

    def make_solve(self) -> Callable:
        """Pure fn(params, b, x0) -> SolveResult. Default: monitored
        stationary iteration of make_step (reference solver.cu:795-855)."""
        norm_of = self.make_norm()

        if not self.monitor_residual:
            smooth = self.make_smooth()
            iters = self.max_iters

            def solve_plain(params, b, x0):
                x = smooth(params, b, x0, iters)
                return self._fixed_result(x, b, iters)

            return solve_plain

        rstep = self.make_residual_step()
        if rstep is not None:
            # residual-carrying loop: ONE SpMV per iteration shared between
            # the step and the norm
            def solve_r(params, b, x0):
                A = self.operator_of(params)
                r0 = b - spmv(A, x0)

                def body(c):
                    it, x, (r,), nrm, ini, mx, hist, st = c
                    x = faults.corrupt_nan(
                        "smoother_nan", rstep(params, b, x, r)
                    )
                    r = b - spmv(A, x)
                    nrm = norm_of(r)
                    return self._monitor_update(
                        it + 1, x, (r,), nrm, ini, mx, hist, st
                    )

                return self._monitored_loop(
                    norm_of(r0), body, b, x0, (r0,)
                )

            return solve_r

        step = self.make_step()

        def solve(params, b, x0):
            A = self.operator_of(params)

            def compute_nrm(x):
                return norm_of(b - spmv(A, x))

            def body(c):
                it, x, extra, nrm, ini, mx, hist, st = c
                x = faults.corrupt_nan("smoother_nan", step(params, b, x))
                nrm = compute_nrm(x)
                it = it + 1
                return self._monitor_update(
                    it, x, extra, nrm, ini, mx, hist, st
                )

            return self._monitored_loop(compute_nrm(x0), body, b, x0, ())

        return solve

    def make_apply(self) -> Callable:
        """Pure fn(params, r) -> z, preconditioner application with zero
        initial guess; default = max_iters unmonitored sweeps."""
        smooth = self.make_smooth()
        iters = max(self.max_iters, 1)

        def apply(params, r):
            z = jnp.zeros_like(r)
            return smooth(params, r, z, iters)

        return apply

    # few-sweep loops unroll (cycle smoothers, sweeps 1-4); longer ones use
    # fori_loop to bound trace size
    _UNROLL_LIMIT = 8

    def make_smooth(self) -> Callable:
        """Pure fn(params, b, x, sweeps) -> x (sweeps is static)."""
        step = self.make_step()

        def smooth(params, b, x, sweeps):
            if sweeps <= self._UNROLL_LIMIT:
                for _ in range(sweeps):
                    x = step(params, b, x)
                return faults.corrupt_nan("smoother_nan", x)
            x = jax.lax.fori_loop(
                0, sweeps, lambda i, x: step(params, b, x), x
            )
            return faults.corrupt_nan("smoother_nan", x)

        return smooth

    # ------------------------------------------------------------------
    # shared machinery

    def operator_of(self, params):
        """By convention params is the matrix or a tuple starting with it."""
        return params[0] if isinstance(params, tuple) else params

    @property
    def norm_components(self) -> int:
        if (
            self.A is not None
            and self.A.block_size > 1
            and not self.use_scalar_norm
        ):
            return self.A.block_size
        return 1

    def make_norm(self):
        nt = self.norm_type
        ncomp = self.norm_components
        if ncomp > 1:
            b = self.A.block_size
            return lambda r: _block_norm(r, b, nt)
        return lambda r: jnp.atleast_1d(_norm(r, nt))

    def _monitor_update(
        self, it, x, extra, nrm, nrm_ini, nrm_max, hist, status
    ):
        """Common tail of a monitored loop body: record history, update
        max-norm, derive status."""
        nrm_max = jnp.maximum(nrm_max, nrm)
        hist = hist.at[it].set(nrm)
        done_ok = self._conv_check(nrm, nrm_ini, nrm_max)
        bad = ~jnp.all(jnp.isfinite(nrm))
        status = jnp.where(
            done_ok, jnp.int32(SUCCESS), jnp.int32(NOT_CONVERGED)
        )
        if self.rel_div_tolerance > 0:
            div = jnp.any(nrm > self.rel_div_tolerance * nrm_ini)
            status = jnp.where(div, jnp.int32(DIVERGED), status)
        if self.stagnation_window > 0:
            # stagnation guardrail: the current residual is no better
            # than the BEST of the previous w iterations (min over the
            # window — robust to non-monotone Krylov residuals) —
            # reported as DIVERGED (the nearest reference status) so
            # the solve stops early and the retry hook can act
            w = min(self.stagnation_window, self.max_iters + 1)
            window = jax.lax.dynamic_slice_in_dim(
                hist, jnp.maximum(it - w, 0), w, axis=0
            )
            best = jnp.min(window, axis=0)
            stalled = (it >= w) & jnp.all(nrm >= best)
            status = jnp.where(
                stalled & (status == NOT_CONVERGED),
                jnp.int32(DIVERGED),
                status,
            )
        status = jnp.where(bad, jnp.int32(FAILED), status)
        return (it, x, extra, nrm, nrm_ini, nrm_max, hist, status)

    def _fixed_result(self, x, b, iters) -> SolveResult:
        """Result shell for unmonitored fixed-iteration solves.  Even
        unmonitored solves must never return NaN as SUCCESS (guardrail
        invariant): one cheap all-finite check derives the status."""
        rdt = jnp.real(b).dtype
        ncomp = self.norm_components
        zero = jnp.zeros((ncomp,), rdt)
        status = jnp.where(
            jnp.all(jnp.isfinite(x)),
            jnp.int32(SUCCESS),
            jnp.int32(FAILED),
        )
        return SolveResult(
            x=x,
            iters=jnp.int32(iters),
            status=status,
            final_norm=zero,
            initial_norm=zero,
            history=jnp.full((self.max_iters + 1, ncomp), jnp.nan, rdt),
        )

    def _monitored_loop(self, nrm0, body, b, x0, extra0):
        """Generic monitored while_loop (reference solver.cu:586-860).

        carry = (it, x, extra, nrm, nrm_ini, nrm_max, hist, status); body
        must end with _monitor_update.  ``extra0`` is solver-specific loop
        state (Krylov vectors etc.).
        """
        rdt = jnp.real(b).dtype
        ncomp = self.norm_components
        hist = jnp.full((self.max_iters + 1, ncomp), jnp.nan, rdt)
        hist = hist.at[0].set(nrm0)
        done0 = self._conv_check(nrm0, nrm0, nrm0)
        status0 = jnp.where(
            done0, jnp.int32(SUCCESS), jnp.int32(NOT_CONVERGED)
        )

        def cond(c):
            it, status = c[0], c[7]
            return (status == NOT_CONVERGED) & (it < self.max_iters)

        c0 = (jnp.int32(0), x0, extra0, nrm0, nrm0, nrm0, hist, status0)
        it, x, _, nrm, ini, mx, hist, status = jax.lax.while_loop(
            cond, body, c0
        )
        return SolveResult(
            x=x,
            iters=it,
            status=status,
            final_norm=nrm,
            initial_norm=ini,
            history=hist,
        )

    # ------------------------------------------------------------------
    # public API (reference Solver::setup / solve, solver.cu:333,586)

    def setup(self, A: SparseMatrix):
        t0 = time.perf_counter()
        from amgx_tpu.core import errors as _errors

        if _errors.validation_enabled():
            # typed setup guardrail: NaN/Inf coefficients fail HERE
            # with SetupError, not as a NaN status many layers later
            _errors.validate_operator(
                A, where=f"{self.registry_name} setup"
            )
        if self.solver_verbose:
            # reference solver.cu:349: dump the solver settings
            emit(
                f"{self.registry_name} solver settings (scope "
                f"{self.scope!r}): max_iters={self.max_iters} "
                f"tolerance={self.tolerance} norm={self.norm_type.value} "
                f"convergence={self.conv_type} "
                f"relaxation_factor={self.relaxation_factor}"
            )
        self._scale_vecs = None
        self._reorder = None
        if self.scaling.upper() not in ("", "NONE"):
            # scale the system at setup (reference Scaler::setup hook,
            # solver.cu:667-676): work on As = Dr A Dc
            from amgx_tpu.solvers.scalers import create_scaler
            import scipy.sparse as sps

            scaler = create_scaler(self.scaling)
            sp = A.to_scipy()
            r, c = scaler.compute(sp)
            sp = sps.diags_array(r) @ sp @ sps.diags_array(c)
            A = SparseMatrix.from_scipy(
                sp.tocsr().astype(np.dtype(A.values.dtype)),
                block_size=A.block_size,
            )
            self._scale_vecs = (jnp.asarray(r.astype(sp.dtype)),
                                jnp.asarray(c.astype(sp.dtype)))
        reorder_mode = self.reordering
        if reorder_mode.upper() != "NONE":
            # RCM renumbering at the solve boundary (same hook as the
            # scaler): unlocks the windowed gather kernel on TPU
            from amgx_tpu.ops.reorder import maybe_reorder

            A2, perm = maybe_reorder(A, reorder_mode)
            if perm is not None:
                iperm = np.argsort(perm)
                self._reorder = (jnp.asarray(perm), jnp.asarray(iperm))
                A = A2
        self.A = A
        self._setup_impl(A)
        self._jit_cache.clear()
        self.setup_time = time.perf_counter() - t0
        return self

    def resetup(self, A: SparseMatrix):
        """Refresh for a matrix whose VALUES changed but whose structure
        is intact (reference AMGX_solver_resetup / structure_reuse).
        Subclasses take fast paths via ``_resetup_impl``; anything that
        can't falls back to a full setup."""
        if (
            self.A is None
            or self._scale_vecs is not None
            or self._reorder is not None
            or A.n_rows != self.A.n_rows
            or A.nnz != self.A.nnz
            or A.block_size != self.A.block_size
        ):
            return self.setup(A)
        t0 = time.perf_counter()
        if not self._resetup_impl(A):
            return self.setup(A)
        self.A = A
        self._jit_cache.clear()
        self.setup_time = time.perf_counter() - t0
        return self

    def _resetup_impl(self, A: SparseMatrix) -> bool:
        """Attempt a values-only refresh; False -> caller runs setup."""
        return False

    # ------------------------------------------------------------------
    # setup persistence (amgx_tpu.store)

    def _export_setup(self) -> dict:
        """Serializable setup-state tree (leaves limited to what
        :func:`amgx_tpu.store.serialize.flatten` handles).  The base
        shape covers every solver: the set-up operator plus the
        solve-boundary scale/reorder vectors, with a solver-specific
        ``impl`` payload from :meth:`_export_impl`."""
        from amgx_tpu.core.errors import StoreError

        if self.A is None:
            raise StoreError(
                f"{self.registry_name}: save_setup before setup()"
            )
        return {
            "A": self.A,
            "scale": getattr(self, "_scale_vecs", None),
            "reorder": getattr(self, "_reorder", None),
            "impl": self._export_impl(),
        }

    def _import_setup(self, state: dict):
        """Restore from :meth:`_export_setup` WITHOUT re-running the
        expensive setup path.  The default re-derives params from the
        restored operator via ``_setup_impl`` — deterministic and
        cheap for every non-hierarchical solver; AMG overrides it to
        rebuild the level chain from the payload instead of
        re-coarsening."""
        self.A = state["A"]
        self._scale_vecs = state.get("scale")
        self._reorder = state.get("reorder")
        self._import_impl(state.get("impl"))
        self._jit_cache.clear()

    def _export_impl(self):
        """Solver-specific setup state beyond the operator; None when
        params re-derive from A (the default _import_impl path)."""
        return None

    def _import_impl(self, impl):
        self._setup_impl(self.A)

    def save_setup(self, path) -> dict:
        """Persist this solver's completed setup to ``path`` (one
        ``.npz`` payload with embedded JSON manifest) so a later
        process can :meth:`load_setup` it without re-running setup —
        the durable analogue of ``AMGX_write_system`` extended to the
        whole hierarchy.  Returns the manifest."""
        from amgx_tpu.store import serialize

        return serialize.save_setup(self, path)

    @classmethod
    def load_setup(cls, path, cfg=None, expect_dtype=None):
        """Restore a solver persisted with :meth:`save_setup`.

        The payload records the solver class and full config; pass
        ``cfg`` to assert config compatibility instead (content-hash
        mismatch raises :class:`~amgx_tpu.core.errors.StoreError`),
        and ``expect_dtype`` to refuse a payload of another operator
        dtype before anything ships to the device.  The restored
        solver solves with iteration counts identical to the original
        — setup (for AMG: coarsening + Galerkin) is skipped, not
        re-run (``setup_time`` stays 0; ``restore_time`` holds the
        import cost)."""
        from amgx_tpu.store import serialize

        return serialize.load_setup(
            path, cfg=cfg, expect_dtype=expect_dtype
        )

    def reductions_per_iteration(self):
        """Global reductions (dots + norms — the cross-chip ``psum``
        sync points of a sharded solve) one monitored iteration of
        this solver's compiled loop body executes, counted by tracing
        the iteration protocol under
        :func:`amgx_tpu.ops.blas.reduction_counter`.  ``None`` when
        the solver exposes no step/iterate protocol (GMRES/IDR
        override ``make_solve`` wholesale).  Cached per setup (the
        ``_jit_cache`` clears on setup/resetup); the number behind the
        ``amgx_solver_reductions_total`` telemetry family and the
        ci/smoother_bench.py reductions-per-s-steps gate."""
        key = "__reductions_per_iteration__"
        if key in self._jit_cache:
            return self._jit_cache[key]
        try:
            val = self._count_iteration_reductions()
        except Exception:  # noqa: BLE001 — accounting must never fail
            val = None
        self._jit_cache[key] = val
        return val

    def cycle_passes_per_iteration(self):
        """Fine-grid operator passes one iteration of this solver
        executes (trace-time count under
        :data:`amgx_tpu.ops.spmv.op_pass_counter`).  ``None`` for
        solvers without a cycle notion — the AMG hierarchy overrides
        this; the number feeds ``amgx_solver_cycle_passes_total``."""
        return None

    def _count_iteration_reductions(self):
        """Trace one monitored-loop body (iterate + residual-norm
        monitor) and count the reduction sites."""
        from amgx_tpu.ops import blas

        if self.A is None:
            return None
        params = self.apply_params()
        spec = jax.ShapeDtypeStruct(
            (self.A.n_rows * self.A.block_size,),
            jnp.zeros((), self.A.values.dtype).dtype,
        )
        norm_of = self.make_norm() if self.monitor_residual else None

        if hasattr(self, "_make_init"):
            try:
                init_fn, iter_fn = self._make_init(), self._make_iter()
            except NotImplementedError:
                init_fn = None
            if init_fn is not None:
                extra = jax.eval_shape(init_fn, params, spec, spec)

                def body(p, b, x, e):
                    x, e = iter_fn(p, b, x, e)
                    return norm_of(e[0]) if norm_of is not None else x

                with blas.reduction_counter() as c:
                    jax.eval_shape(body, params, spec, spec, extra)
                return c.count

        rstep = self.make_residual_step()
        if rstep is not None:
            def body_r(p, b, x, r):
                x = rstep(p, b, x, r)
                r = b - spmv(self.operator_of(p), x)
                return norm_of(r) if norm_of is not None else x

            with blas.reduction_counter() as c:
                jax.eval_shape(body_r, params, spec, spec, spec)
            return c.count

        step = self.make_step()

        def body_s(p, b, x):
            x = step(p, b, x)
            if norm_of is not None:
                return norm_of(b - spmv(self.operator_of(p), x))
            return x

        with blas.reduction_counter() as c:
            jax.eval_shape(body_s, params, spec, spec)
        return c.count

    def make_batch_params(self):
        """Traced values-only params rebuild, for batched group solves
        (:mod:`amgx_tpu.serve`).

        Returns ``(template, fn)`` where ``fn(template, values) ->
        params`` is a pure jit/vmap-safe function rebuilding this
        solver's ``apply_params()`` pytree for a coefficient set
        ``values`` on the SAME sparsity pattern as the setup matrix —
        the traced analogue of :meth:`resetup`.  ``template`` is a
        pytree of device arrays holding everything pattern-specific
        (index structures, transfer operators, SpGEMM plans); it is
        passed to ``fn`` as an ARGUMENT so the serve layer can hand it
        to one jit-compiled program per shape bucket instead of baking
        the pattern into the compiled code.

        Returns None when the solver has no traced values-only rebuild
        (callers fall back to sequential resetup + solve).  The default
        covers solvers whose params ARE the matrix.
        """
        if self._params is None or self._params is not self.A:
            return None
        return self.A, lambda t, v: t.replace_values(v)

    def apply_params(self):
        return self._params

    def collect_setup_profile(self) -> dict:
        """Merged setup-phase profile (``AMGSolver.setup_profile``
        keys: strength/cf_split/aggregation/interp/rap_plan/
        rap_execute/transfer/finalize/... ) of this solver and any
        nested preconditioner — the dict behind ``obtain_timings``'s
        ``setup:<phase>`` lines and bench.py's setup split."""
        prof = dict(getattr(self, "setup_profile", None) or {})
        inner = getattr(self, "precond", None)
        if inner is not None and inner is not self:
            for k, v in inner.collect_setup_profile().items():
                prof[k] = prof.get(k, 0) + v
        return prof

    def solve(self, b, x0=None, zero_initial_guess=False,
              block=True) -> SolveResult:
        """Monitored solve.  ``block=False`` is the async mode (PR 3):
        the call returns right after the device dispatch with a
        SolveResult backed by on-device arrays — status / iterations /
        history materialize lazily when first read — and performs no
        host sync of its own.  Sync-requiring features (solve stats
        printing, obtain_timings, convergence analysis; a triggered
        retry) still synchronize even with ``block=False``.

        Buffer donation: when this call OWNS the initial-guess buffer
        (x0 omitted, zero_initial_guess, a host array, or a
        scaled/reordered copy), the jitted solve donates it
        (``donate_argnums``) so XLA writes the solution in place.  A
        caller-owned device x0 is never donated — that aliasing caveat
        is the one documented in doc/SERVING.md."""
        if self.A is None:
            raise RuntimeError("solve() before setup()")
        b = jnp.asarray(b)
        donate = (
            x0 is None
            or zero_initial_guess
            or not isinstance(x0, jax.Array)
        ) and donation_enabled()
        if x0 is None or zero_initial_guess:
            x0 = jnp.zeros_like(b)
        else:
            x0 = jnp.asarray(x0)
        if self._scale_vecs is not None:
            r_s, c_s = self._scale_vecs
            b = r_s * b
            x0 = x0 / jnp.where(c_s != 0, c_s, 1.0)
            # the scaled x0 is a fresh array we own
            donate = donation_enabled()
        if self._reorder is not None:
            perm, _ = self._reorder
            b = b[perm]
            x0 = x0[perm]
            donate = donation_enabled()  # likewise the permuted copy
        key = (b.shape, b.dtype.name, x0.dtype.name, donate)
        fn = self._jit_cache.get(key)
        if fn is None:
            fn = self._compile_solve(key, b, x0, donate)
        else:
            self.last_compile_s = 0.0
        t0 = time.perf_counter()
        self.solve_retries_used = 0
        res = fn(self.apply_params(), b, x0)
        if self.solve_retries > 0:
            res = self._retry_if_failed(res, key, b)
        if self._reorder is not None:
            res = dataclasses.replace(res, x=res.x[self._reorder[1]])
        if self._scale_vecs is not None:
            res = dataclasses.replace(res, x=self._scale_vecs[1] * res.x)
        # async mode skips the device sync unless a reporting feature
        # needs concrete numbers anyway
        if (
            block
            or self.print_solve_stats
            or self.obtain_timings
            or self.convergence_analysis > 0
        ):
            res.x.block_until_ready()
        self.solve_time = time.perf_counter() - t0
        if self.print_solve_stats and self.verbosity > 2:
            self._print_stats(res)
        elif self.print_solve_stats and self.verbosity in (1, 2):
            # reduced one-line summary (reference solver.cu:760,830)
            emit(
                f"         Total Iterations: {int(res.iters)}  "
                f"status: {int(res.status)}"
            )
        if self.convergence_analysis > 0 and res.history is not None:
            self._print_convergence_analysis(res)
        if self.obtain_timings:
            # compile reported SEPARATELY from solve: the first call's
            # jit tracing/compilation is a one-off cost and folding it
            # into solve seconds misstates per-iteration cost (warm
            # calls report compile: 0)
            emit(
                f"Total Time: {self.setup_time + self.last_compile_s + self.solve_time:10.6f}\n"
                f"    setup: {self.setup_time:10.6f} s\n"
                f"    compile: {self.last_compile_s:10.6f} s\n"
                f"    solve: {self.solve_time:10.6f} s\n"
                f"    solve(per iteration): "
                f"{self.solve_time / max(1, int(res.iters)):10.6f} s"
            )
            setup_prof = self.collect_setup_profile()
            if setup_prof:
                # setup-phase anatomy (PR 5): the cold-setup cost
                # broken down the way compile:/solve: split the solve
                # side — doc/PERFORMANCE.md "Setup-phase anatomy"
                lines = []
                for k in sorted(setup_prof):
                    v = setup_prof[k]
                    if isinstance(v, float):
                        lines.append(f"    setup:{k}: {v:10.6f} s")
                    else:
                        lines.append(f"    setup:{k}: {v}")
                emit("\n".join(lines))
            mem = device_memory_stats()
            if mem is not None:
                # reference "Mem Usage" column (memory_info.h:9-33);
                # on TPU this is live/peak HBM from the runtime
                emit(
                    f"    Mem Usage: {mem[0] / 2**30:10.4f} GB in use, "
                    f"peak {mem[1] / 2**30:10.4f} GB"
                )
            # re-emit the same timing lines through the telemetry
            # registry (amgx_solver_* / amgx_setup_phase_* metrics) and
            # drop a flight record for the direct-API solve — this
            # branch already synchronized, so reading iters/status
            # costs nothing extra.  Telemetry must never fail a solve.
            self._telemetry_observe(res, setup_prof)
        return res

    def _telemetry_observe(self, res: SolveResult, setup_prof: dict):
        """Fold one timed solve into the process telemetry registry
        (obtain_timings re-emission) and the default flight-record
        path (``path="direct"``).  Best-effort: any failure —
        including the ``telemetry_export`` injected fault — is
        swallowed; the solve result is already computed."""
        try:
            from amgx_tpu import telemetry

            if not telemetry.telemetry_enabled():
                return
            reg = telemetry.get_registry()
            # iterations are reported in INNER-step equivalents
            # (iterations_scale: one s-step outer = s CG steps) so
            # histograms compare across solver families; reductions
            # multiply the per-loop-body count by loop-body
            # executions (= SolveResult.iters), making the
            # communication win observable: reductions/iterations
            # ~ 3 for classic monitored PCG, ~ 2/s for SSTEP_PCG
            red = self.reductions_per_iteration()
            cp = self.cycle_passes_per_iteration()
            reg.record_solver(
                self.registry_name,
                setup_s=self.setup_time,
                compile_s=self.last_compile_s,
                solve_s=self.solve_time,
                iterations=int(res.iters) * int(self.iterations_scale),
                reductions=(red or 0) * int(res.iters),
                cycle_passes=(cp or 0) * int(res.iters),
                setup_phases={
                    k: v for k, v in (setup_prof or {}).items()
                    if isinstance(v, float)
                },
            )
            from amgx_tpu.telemetry.registry import default_recorder

            default_recorder().record(
                fingerprint=(
                    self.A.fingerprint() if self.A is not None else ""
                ),
                config=self.cfg.content_hash(),
                lane="direct",
                tenant="-",
                iterations=int(res.iters),
                final_residual=float(np.max(np.asarray(res.final_norm))),
                status=int(res.status),
                stages={
                    "setup": self.setup_time,
                    "compile": self.last_compile_s,
                    "solve": self.solve_time,
                },
                path="direct",
            )
        except Exception:
            # observability is free to fail; the solve is not —
            # but KeyboardInterrupt/SystemExit must still propagate
            pass

    def _compile_solve(self, key, b, x0, donate):
        """AOT-compile the jitted solve for this signature, timing the
        compile separately from execution (``last_compile_s`` /
        ``compile_time``); falls back to the tracing jit wrapper when
        AOT rejects the params pytree."""
        t0 = time.perf_counter()
        jitted = jax.jit(
            self.make_solve(),
            donate_argnums=(2,) if donate else (),
        )
        try:
            fn = jitted.lower(self.apply_params(), b, x0).compile()
        except Exception:
            fn = jitted
        self._jit_cache[key] = fn
        self.last_compile_s = time.perf_counter() - t0
        self.compile_time += self.last_compile_s
        return fn

    # result-status preference order for the retry hook: a retry's
    # outcome replaces the original only when strictly better
    _STATUS_RANK = {FAILED: 0, DIVERGED: 1, NOT_CONVERGED: 2, SUCCESS: 3}

    def _retry_if_failed(self, res: SolveResult, key, b) -> SolveResult:
        """Retry-with-safer-config recovery hook (``solve_retries``).

        A FAILED/DIVERGED solve retries up to ``solve_retries`` times,
        each attempt evicting the possibly-defective MAIN executable (a
        fresh trace escapes spent fault injections and any trace-level
        corruption) and restarting from a zero initial guess.  The
        first retry keeps the configuration — it targets transient/
        trace corruption; further retries halve the relaxation factor
        each time (under-relaxation is the classic safer setting for
        stationary/smoothed iterations) — they target genuine
        divergence.  Retry executables are cached under their own
        (key, attempt) slot: the first failing solve traces them fresh
        (that's the corruption escape), repeated failing solves reuse
        the clean trace instead of paying a recompile per retry.  The
        best result by status wins; healthy solves pay only one scalar
        status sync."""
        attempt = 0
        while (
            attempt < self.solve_retries
            and int(res.status) in (FAILED, DIVERGED)
        ):
            attempt += 1
            self.solve_retries_used = attempt
            self._jit_cache.pop(key, None)
            rkey = ("retry", key, attempt)
            fn = self._jit_cache.get(rkey)
            if fn is None:
                old_omega = self.relaxation_factor
                self.relaxation_factor = old_omega * 0.5 ** (attempt - 1)
                try:
                    fn = jax.jit(self.make_solve())
                finally:
                    self.relaxation_factor = old_omega
                self._jit_cache[rkey] = fn
            retry = fn(self.apply_params(), b, jnp.zeros_like(b))
            if self._STATUS_RANK.get(int(retry.status), 0) > \
                    self._STATUS_RANK.get(int(res.status), 0):
                res = retry
        return res

    def _print_stats(self, res: SolveResult):
        """Residual table in the reference output format (README.md:118-131)."""
        import numpy as np

        hist = np.asarray(res.history)
        iters = int(res.iters)
        lines = ["           iter      residual           rate",
                 "         --------------------------------------"]
        for i in range(min(iters, self.max_iters) + 1):
            row = hist[i]
            if np.all(np.isnan(row)):
                continue
            r = float(np.max(row))
            if i == 0:
                lines.append(f"            Ini {r:18.6e}")
            else:
                prev = float(np.max(hist[i - 1]))
                rate = r / prev if prev > 0 else 0.0
                lines.append(f"            {i:3d} {r:18.6e} {rate:14.4f}")
        st = int(res.status)
        label = {
            SUCCESS: "success",
            FAILED: "failed (nan/inf)",
            DIVERGED: "diverged",
            NOT_CONVERGED: "not converged",
        }.get(st, f"unknown ({st})")
        lines.append("         --------------------------------------")
        emit("\n".join(lines))
        emit(
            f"         Total Iterations: {iters}\n"
            f"         Avg Convergence Rate: "
            f"{self._avg_rate(hist, iters):18.4f}\n"
            f"         Final Residual: {float(np.max(hist[iters])):18.6e}\n"
            f"         Residual reduction: "
            f"{float(np.max(hist[iters]) / max(np.max(hist[0]), 1e-300)):18.6e}\n"
            f"         Solve status: {label}"
        )

    def _print_convergence_analysis(self, res: SolveResult):
        """Reference convergence_analysis.cu: geometric-mean rate and
        per-iteration rates over the last ``convergence_analysis``
        iterations."""
        import numpy as np

        hist = np.asarray(res.history)
        iters = int(res.iters)
        k = min(self.convergence_analysis, iters)
        if k < 1:
            return
        rows = []
        for i in range(iters - k + 1, iters + 1):
            prev = float(np.max(hist[i - 1]))
            cur = float(np.max(hist[i]))
            rows.append(
                f"           iter {i:3d}: rate "
                f"{(cur / prev if prev > 0 else 0.0):10.4f}"
            )
        r0 = float(np.max(hist[iters - k]))
        rn = float(np.max(hist[iters]))
        geo = (rn / r0) ** (1.0 / k) if r0 > 0 else 0.0
        emit(
            "         Convergence analysis (last %d iterations):\n" % k
            + "\n".join(rows)
            + f"\n           geometric-mean rate: {geo:10.4f}"
        )

    @staticmethod
    def _avg_rate(hist, iters):
        import numpy as np

        if iters < 1:
            return 0.0
        r0, rn = np.max(hist[0]), np.max(hist[iters])
        if r0 <= 0:
            return 0.0
        return float((rn / r0) ** (1.0 / iters))


class IdentitySolverMixin:
    """For NOSOLVER-style solvers: apply is identity, smooth is no-op."""

    is_identity = True
