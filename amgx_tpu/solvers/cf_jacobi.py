"""CF-Jacobi smoother (reference cf_jacobi_solver.cu): Jacobi sweeps
ordered by a coarse/fine splitting — C points then F points (or the
reverse), per cf_smoothing_mode:

  0: CF for pre-smoothing order (C then F)
  1: FC (F then C)

Splitting source: the reference reads the owning AMG level's C/F
splitting.  Here the smoother computes its OWN splitting at setup (PMIS
on AHAT strength using the parameters of the smoother's config scope) —
set strength_threshold/max_row_sum in the smoother scope to match the
AMG scope if exact reference parity of the ordering matters.  Wiring the
level's actual splitting through smoother setup is future work."""

from __future__ import annotations

import jax.numpy as jnp

from amgx_tpu.ops.diagonal import invert_diag, scalarized
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import register_solver


@register_solver("CF_JACOBI")
class CFJacobiSolver(Solver):
    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.mode = int(cfg.get("cf_smoothing_mode", scope))
        self.theta = float(cfg.get("strength_threshold", scope))
        self.max_row_sum = float(cfg.get("max_row_sum", scope))

    def _setup_impl(self, A):
        A = scalarized(A, "CF_JACOBI")
        from amgx_tpu.amg.classical import pmis_select, strength_ahat

        sp = A.to_scipy()
        S = strength_ahat(sp, self.theta, self.max_row_sum)
        cf = pmis_select(S)
        self._params = (A, invert_diag(A), jnp.asarray(cf == 1))

    def make_step(self):
        omega = self.relaxation_factor
        first_coarse = self.mode == 0

        def half_sweep(params, b, x, mask):
            A, dinv, _ = params
            r = b - spmv(A, x)
            return jnp.where(mask, x + omega * dinv * r, x)

        def step(params, b, x):
            _, _, is_c = params
            m1, m2 = (is_c, ~is_c) if first_coarse else (~is_c, is_c)
            x = half_sweep(params, b, x, m1)
            x = half_sweep(params, b, x, m2)
            return x

        return step
