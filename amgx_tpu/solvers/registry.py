"""Factory registry (reference SolverFactory, solver.h:281-310).

Maps registry names (the strings appearing in config files, e.g. "PCG",
"BLOCK_JACOBI") to solver classes.  ``create_solver`` resolves a scoped
config parameter naming a solver and instantiates it, mirroring
SolverFactory::allocate's (config, scope) contract.
"""

from __future__ import annotations

from typing import Callable, Dict

_SOLVERS: Dict[str, Callable] = {}


class SolverRegistry:
    @staticmethod
    def register(name: str, cls):
        _SOLVERS[name] = cls

    @staticmethod
    def get(name: str):
        try:
            return _SOLVERS[name]
        except KeyError:
            raise KeyError(
                f"unregistered solver {name!r}; known: {sorted(_SOLVERS)}"
            ) from None

    @staticmethod
    def names():
        return sorted(_SOLVERS)


def register_solver(name: str):
    """Class decorator: @register_solver("PCG")."""

    def deco(cls):
        SolverRegistry.register(name, cls)
        cls.registry_name = name
        return cls

    return deco


def create_solver(cfg, scope: str = "default", param: str = "solver"):
    """Allocate the solver named by cfg param in scope
    (reference SolverFactory::allocate, solver.h:281-310)."""
    if param == "solver" and scope == "default" \
            and bool(cfg.get("print_config", scope)):
        # reference amg_config printAmgConfig: dump the effective
        # config once at top-level solver creation
        from amgx_tpu.core.printing import emit

        lines = ["         AMG Configuration:"]
        for (sc, name_), v in sorted(cfg.items().items()):
            lines.append(f"           {sc}:{name_} = {v!r}")
        emit("\n".join(lines))
    name, new_scope = cfg.get_scoped(param, scope)
    cls = SolverRegistry.get(name)
    return cls(cfg, new_scope)


def make_nested(solver):
    """Mark a solver as nested (preconditioner / smoother / coarse / inner
    eigensolver solver).  Nested solvers never re-scale (the outer solver
    already works on the scaled operator — reference 'scaled' guard,
    solver.cu:452-467) and never re-order: their make_apply/make_smooth
    pure functions receive vectors in the OUTER ordering, which only the
    outer solve() boundary permutes.  Single enforcement point for both
    invariants."""
    solver.scaling = "NONE"
    solver.reordering = "NONE"
    return solver
