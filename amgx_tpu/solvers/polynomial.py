"""Polynomial smoothers (reference polynomial_solver.cu,
kpz_polynomial_solver.cu).

POLYNOMIAL: truncated Neumann-series smoother in the Jacobi-preconditioned
operator:  z = sum_{k<order} (I - D^{-1}A)^k D^{-1} r.
KPZ_POLYNOMIAL: the Kraus-Pillwein-Zikatanov Chebyshev-type smoother
(reference kpz_polynomial_solver.cu:154-219): a three-term recurrence
over the spectral window [smax/mu, smax] with smax = ||A||_inf
estimated from column sums at setup; ``kpz_mu`` sets the window width.
Both are gather-free chains of SpMV + AXPY — TPU-friendly.
"""

from __future__ import annotations

import numpy as np

from amgx_tpu.ops.diagonal import invert_diag, scalarized
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import register_solver


@register_solver("POLYNOMIAL")
class PolynomialSolver(Solver):
    order_param = "kpz_order"

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.order = max(int(cfg.get(self.order_param, scope)), 1)

    def _setup_impl(self, A):
        A = scalarized(A, "POLYNOMIAL")
        self._params = (A, invert_diag(A))

    def make_residual_step(self):
        order = self.order
        omega = self.relaxation_factor

        def rstep(params, b, x, r):
            A, dinv = params
            # z_m = sum_{k<=m} (I - Dinv A)^k Dinv r, built incrementally
            z = dinv * r
            for _ in range(order - 1):
                z = z - dinv * spmv(A, z) + dinv * r
            return x + omega * z

        return rstep


@register_solver("KPZ_POLYNOMIAL")
class KPZPolynomialSolver(PolynomialSolver):
    """KPZ smoother (reference kpz_polynomial_solver.cu).  The scalar
    coefficients (delta, beta, chi) derive from smax = ||A||_inf and
    smin = smax / kpz_mu at setup; each application runs the reference's
    three-term recurrence smooth_1x1 (:154-219) up to ``kpz_order``."""

    order_param = "kpz_order"

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.mu = max(int(cfg.get("kpz_mu", scope)), 2)

    def _setup_impl(self, A):
        import jax.numpy as jnp

        A = scalarized(A, "KPZ_POLYNOMIAL")
        # ||A||_inf via column abs-sums (reference transposes and takes
        # the max row sum, kpz_polynomial_solver.cu:100-111)
        sp = A.to_scipy()
        smax = float(np.abs(sp).sum(axis=0).max())
        smax = smax if smax > 0 else 1.0
        smin = smax / self.mu
        smu0, smu1 = 1.0 / smax, 1.0 / smin
        skappa = np.sqrt(smax / smin)
        delta = (skappa - 1.0) / (skappa + 1.0)
        beta = (np.sqrt(smu0) + np.sqrt(smu1)) ** 2
        chi = 4.0 * smu0 * smu1 / beta
        dt = A.values.dtype
        coef = tuple(jnp.asarray(v, dt) for v in
                     (smu0, smu1, delta, beta, chi))
        self._params = (A, coef)

    def make_residual_step(self):
        order = max(self.order, 1)

        def rstep(params, b, x, r):
            A, (smu0, smu1, delta, beta, chi) = params
            # reference smooth_1x1: v0 = (smu0+smu1)/2 * r;
            # v = beta/2 * r - smu0*smu1 * A r; then the recurrence
            v0 = (smu0 + smu1) * 0.5 * r
            v = beta * 0.5 * r - smu0 * smu1 * spmv(A, r)
            for _ in range(2, order + 1):
                sn = chi * (r - spmv(A, v)) + delta * delta * (v - v0)
                v0 = v
                v = v + sn
            return x + v

        return rstep
