"""Polynomial smoothers (reference polynomial_solver.cu,
kpz_polynomial_solver.cu; OPT_POLYNOMIAL from the optimal-smoother
literature, arxiv 2407.09848).

POLYNOMIAL: truncated Neumann-series smoother in the Jacobi-preconditioned
operator:  z = sum_{k<order} (I - D^{-1}A)^k D^{-1} r.
KPZ_POLYNOMIAL: the Kraus-Pillwein-Zikatanov Chebyshev-type smoother
(reference kpz_polynomial_solver.cu:154-219): a three-term recurrence
over the spectral window [smax/mu, smax] with smax = ||A||_inf
estimated from column sums at setup; ``kpz_mu`` sets the window width.
OPT_POLYNOMIAL: the optimal-weight fourth-kind Chebyshev smoother
(Lottes, "Optimal polynomial smoothers for multigrid V-cycles",
arxiv 2202.08830; extended to parallel AMG in arxiv 2407.09848): the
degree-k fourth-kind Chebyshev recurrence over [0, lmax] with the
paper's optimized accumulation weights beta_k.  Unlike first-kind
Chebyshev it needs only the UPPER spectral bound (no lmin guess), and
unlike GS/DILU it needs no coloring and no triangular solves — a pure
SpMV chain that vmaps and shards trivially, which is why it is the
recommended serve/mesh smoother (doc/PERFORMANCE.md).
All three are gather-free chains of SpMV + AXPY — TPU-friendly.
"""

from __future__ import annotations

import numpy as np

from amgx_tpu.ops.diagonal import invert_diag, scalarized
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.chebyshev import ChebyshevSolver
from amgx_tpu.solvers.registry import register_solver


@register_solver("POLYNOMIAL")
class PolynomialSolver(Solver):
    order_param = "kpz_order"

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.order = max(int(cfg.get(self.order_param, scope)), 1)

    def _setup_impl(self, A):
        A = scalarized(A, "POLYNOMIAL")
        self._params = (A, invert_diag(A))

    def make_batch_params(self):
        """Traced values-only rebuild for vmapped serve groups
        (operator + Jacobi diagonal re-derive per instance)."""
        A0 = self._params[0]
        if A0 is not self.A:
            # block input was scalar-expanded at setup: the incoming
            # values array no longer maps 1:1 onto the operator
            return None
        from amgx_tpu.ops.diagonal import invert_diag_jnp

        def fn(t, v):
            A = t.replace_values(v)
            return A, invert_diag_jnp(A)

        return A0, fn

    def make_residual_step(self):
        order = self.order
        omega = self.relaxation_factor

        def rstep(params, b, x, r):
            A, dinv = params
            # z_m = sum_{k<=m} (I - Dinv A)^k Dinv r, built incrementally
            z = dinv * r
            for _ in range(order - 1):
                z = z - dinv * spmv(A, z) + dinv * r
            return x + omega * z

        return rstep


@register_solver("KPZ_POLYNOMIAL")
class KPZPolynomialSolver(PolynomialSolver):
    """KPZ smoother (reference kpz_polynomial_solver.cu).  The scalar
    coefficients (delta, beta, chi) derive from smax = ||A||_inf and
    smin = smax / kpz_mu at setup; each application runs the reference's
    three-term recurrence smooth_1x1 (:154-219) up to ``kpz_order``."""

    order_param = "kpz_order"

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.mu = max(int(cfg.get("kpz_mu", scope)), 2)

    def _setup_impl(self, A):
        import jax.numpy as jnp

        A = scalarized(A, "KPZ_POLYNOMIAL")
        # ||A||_inf via column abs-sums (reference transposes and takes
        # the max row sum, kpz_polynomial_solver.cu:100-111)
        sp = A.to_scipy()
        smax = float(np.abs(sp).sum(axis=0).max())
        smax = smax if smax > 0 else 1.0
        smin = smax / self.mu
        smu0, smu1 = 1.0 / smax, 1.0 / smin
        skappa = np.sqrt(smax / smin)
        delta = (skappa - 1.0) / (skappa + 1.0)
        beta = (np.sqrt(smu0) + np.sqrt(smu1)) ** 2
        chi = 4.0 * smu0 * smu1 / beta
        dt = A.values.dtype
        coef = tuple(jnp.asarray(v, dt) for v in
                     (smu0, smu1, delta, beta, chi))
        self._params = (A, coef)

    def make_batch_params(self):
        """Traced values-only rebuild: the smax = ||A||_inf column
        abs-sum estimate (host numpy at setup) re-derives on device
        per instance via a segment-sum over the column indices, so
        each vmapped instance gets its own spectral window."""
        import jax

        import jax.numpy as jnp

        A0 = self._params[0]
        if A0 is not self.A:
            return None
        mu = self.mu

        def fn(t, v):
            A = t.replace_values(v)
            colsum = jax.ops.segment_sum(
                jnp.abs(A.values), A.col_indices,
                num_segments=A.n_rows,
            )
            smax = jnp.max(colsum)
            smax = jnp.where(smax > 0, smax, 1.0)
            smin = smax / mu
            smu0, smu1 = 1.0 / smax, 1.0 / smin
            skappa = jnp.sqrt(smax / smin)
            delta = (skappa - 1.0) / (skappa + 1.0)
            beta = (jnp.sqrt(smu0) + jnp.sqrt(smu1)) ** 2
            chi = 4.0 * smu0 * smu1 / beta
            dt = A.values.dtype
            coef = tuple(
                jnp.asarray(c).astype(dt)
                for c in (smu0, smu1, delta, beta, chi)
            )
            return A, coef

        return A0, fn

    def make_residual_step(self):
        order = max(self.order, 1)

        def rstep(params, b, x, r):
            A, (smu0, smu1, delta, beta, chi) = params
            # reference smooth_1x1: v0 = (smu0+smu1)/2 * r;
            # v = beta/2 * r - smu0*smu1 * A r; then the recurrence
            v0 = (smu0 + smu1) * 0.5 * r
            v = beta * 0.5 * r - smu0 * smu1 * spmv(A, r)
            for _ in range(2, order + 1):
                sn = chi * (r - spmv(A, v)) + delta * delta * (v - v0)
                v0 = v
                v = v + sn
            return x + v

        return rstep


# ---------------------------------------------------------------------
# optimal-weight fourth-kind Chebyshev smoother (arxiv 2407.09848)

# Optimized accumulation weights beta_k for the degree-K fourth-kind
# Chebyshev smoother (Lottes, arxiv 2202.08830, Table 1 — the same
# table 2407.09848 builds its AMG smoothers on).  Minimizing the
# two-level W-cycle bound over the smoothed interval, they beat the
# unweighted (beta = 1) fourth-kind polynomial at every degree.
_OPT_FOURTH_KIND_WEIGHTS = {
    1: (1.12500000000000,),
    2: (1.02387287570313, 1.26408905371085),
    3: (1.00842544782028, 1.08867839208730, 1.33753125909618),
    4: (1.00391310427285, 1.04035811188593, 1.14863498546254,
        1.38268869241000),
    5: (1.00212930146164, 1.02173711549260, 1.07872433192603,
        1.19810065292663, 1.41322542791682),
    6: (1.00128517255940, 1.01304293035233, 1.04678215124113,
        1.11616489419675, 1.23829020218444, 1.43524297106744),
}


def opt_fourth_kind_weights(order: int):
    """Optimal beta weights for a degree-``order`` fourth-kind
    Chebyshev smoother; degrees beyond the published table fall back
    to the unweighted (beta = 1) fourth-kind polynomial — still a
    valid smoother, just without the last ~20% of the optimization."""
    w = _OPT_FOURTH_KIND_WEIGHTS.get(int(order))
    if w is None:
        return (1.0,) * int(order)
    return w


@register_solver("OPT_POLYNOMIAL")
class OptPolynomialSolver(ChebyshevSolver):
    """Optimal-weight fourth-kind Chebyshev smoother (module
    docstring).  Degree = ``chebyshev_polynomial_order``; subclassing
    :class:`ChebyshevSolver` reuses its power-iteration lmax estimate,
    the resetup spectral-bound cache (``reestimate_eigs`` /
    ``bound_staleness``), setup persistence, and the vmapped serve
    rebuild (``make_batch_params``).  Fourth-kind smoothing needs no
    lower bound: the polynomial targets [0, lmax], so the cheby_min
    ratio guess (the fragile half of first-kind tuning) drops out."""

    def make_residual_step(self):
        k = max(self.order, 1)
        betas = opt_fourth_kind_weights(k)
        rho = self.lmax
        M = self._make_M()

        def rstep(params, b, x, r):
            A, Mp = params
            # Lottes alg. 2/3: the auxiliary d/r recurrence is the
            # UNWEIGHTED fourth-kind iteration; the optimized betas
            # only reweight the corrections accumulated into x
            d = (4.0 / (3.0 * rho)) * M(Mp, r)
            for j in range(1, k + 1):
                x = x + betas[j - 1] * d
                if j == k:
                    break
                r = r - spmv(A, d)
                d = ((2.0 * j - 1.0) / (2.0 * j + 3.0)) * d + (
                    (8.0 * j + 4.0) / ((2.0 * j + 3.0) * rho)
                ) * M(Mp, r)
            return x

        return rstep

    # un-shadow ChebyshevSolver's first-kind make_step: the generic
    # residual-step wrapper is exactly right for the fourth-kind sweep
    make_step = Solver.make_step
