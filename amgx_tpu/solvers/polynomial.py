"""Polynomial smoothers (reference polynomial_solver.cu,
kpz_polynomial_solver.cu).

POLYNOMIAL: truncated Neumann-series smoother in the Jacobi-preconditioned
operator:  z = sum_{k<order} (I - D^{-1}A)^k D^{-1} r.
KPZ_POLYNOMIAL: same family with the KPZ order/mu parameters.
Both are gather-free chains of SpMV + AXPY — TPU-friendly.
"""

from __future__ import annotations

from amgx_tpu.ops.diagonal import invert_diag, scalarized
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import Solver
from amgx_tpu.solvers.registry import register_solver


@register_solver("POLYNOMIAL")
class PolynomialSolver(Solver):
    order_param = "kpz_order"

    def __init__(self, cfg, scope="default"):
        super().__init__(cfg, scope)
        self.order = max(int(cfg.get(self.order_param, scope)), 1)

    def _setup_impl(self, A):
        A = scalarized(A, "POLYNOMIAL")
        self._params = (A, invert_diag(A))

    def make_residual_step(self):
        order = self.order
        omega = self.relaxation_factor

        def rstep(params, b, x, r):
            A, dinv = params
            # z_m = sum_{k<=m} (I - Dinv A)^k Dinv r, built incrementally
            z = dinv * r
            for _ in range(order - 1):
                z = z - dinv * spmv(A, z) + dinv * r
            return x + omega * z

        return rstep


@register_solver("KPZ_POLYNOMIAL")
class KPZPolynomialSolver(PolynomialSolver):
    order_param = "kpz_order"
