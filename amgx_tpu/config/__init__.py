from amgx_tpu.config.amg_config import AMGConfig
from amgx_tpu.config.params import PARAMS, ParameterDescription

__all__ = ["AMGConfig", "PARAMS", "ParameterDescription"]
