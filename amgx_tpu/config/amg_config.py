"""AMGConfig — scoped configuration (reference AMG_Config, amg_config.h:126).

Supports the three reference input formats (amg_config.cu:60-250):

  * JSON config_version 2 with nested solver scopes — the shipped
    ``src/configs/*.json`` format.  A nested dict valued key like
    ``"preconditioner": {"solver": "AMG", "scope": "amg", ...}`` flattens
    to parameter ``preconditioner = "AMG"`` in the parent scope with the
    dict's remaining entries stored under scope ``"amg"``; looking the
    parameter up returns ``(value, new_scope)`` so nested solvers resolve
    their own parameters (amg_config.h:186-187).
  * legacy comma/semicolon ``k=v`` strings with ``scope:k=v`` and
    ``k(new_scope)=v`` scope declarations (config_version 2 strings).
  * plain ``k=v`` (config_version 1) — everything in the default scope.

Lookup order for get(name, scope): (scope, name) -> ("default", name) ->
registry default.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Tuple

from amgx_tpu.config import params as P


class ConfigError(ValueError):
    pass


class AMGConfig:
    def __init__(self):
        # (scope, name) -> value
        self._values: Dict[Tuple[str, str], Any] = {}
        # (scope, name) -> scope the named sub-solver reads its params from
        self._scope_links: Dict[Tuple[str, str], str] = {}
        self._auto_scope = 0

    # -- construction ------------------------------------------------------

    @classmethod
    def from_file(cls, path) -> "AMGConfig":
        with open(path) as f:
            text = f.read()
        return cls.from_string(text)

    @classmethod
    def from_string(cls, text: str) -> "AMGConfig":
        cfg = cls()
        cfg.parse(text)
        return cfg

    @classmethod
    def from_dict(cls, d: dict) -> "AMGConfig":
        cfg = cls()
        cfg._parse_json(d)
        return cfg

    def parse(self, text: str):
        text = text.strip()
        if text.startswith("{"):
            try:
                self._parse_json(json.loads(text))
            except json.JSONDecodeError as e:
                raise ConfigError(f"bad JSON config: {e}") from None
        else:
            self._parse_kv_string(text)

    # -- JSON config_version 2 (amg_config.cu:60-110) ----------------------

    def _parse_json(self, d: dict):
        ver = d.get("config_version", 1)
        if ver not in (1, 2):
            raise ConfigError(f"unsupported config_version {ver}")
        for key, val in d.items():
            if key == "config_version":
                continue
            self._ingest(key, val, scope="default")

    def _ingest(self, key: str, val: Any, scope: str):
        if isinstance(val, dict):
            # a nested solver dict without an explicit scope gets its own
            # auto scope — flattening into the parent would clobber the
            # parent's parameters (reference behavior: unnamed nested
            # scopes are unique)
            child_scope = val.get("scope")
            if child_scope is None:
                self._auto_scope += 1
                child_scope = f"_auto_scope_{self._auto_scope}"
            solver_name = val.get("solver")
            if solver_name is None:
                raise ConfigError(
                    f"nested config for {scope}:{key} lacks 'solver'"
                )
            self._set(scope, key, solver_name)
            self._scope_links[(scope, key)] = child_scope
            for k2, v2 in val.items():
                if k2 == "scope":
                    continue
                if k2 == "solver" and not isinstance(v2, dict):
                    self._set(child_scope, "solver", v2)
                    continue
                self._ingest(k2, v2, scope=child_scope)
        else:
            self._set(scope, key, val)

    # -- legacy k=v strings (amg_config.cu:147-250) ------------------------

    def _parse_kv_string(self, text: str):
        import re

        for item in re.split(r"[,;\n]+", text):
            item = item.strip()
            if not item or item.startswith("#") or item.startswith("%"):
                continue
            if "=" not in item:
                raise ConfigError(f"bad config entry {item!r}")
            lhs, rhs = (s.strip() for s in item.split("=", 1))
            scope = "default"
            new_scope = None
            if ":" in lhs:
                scope, lhs = (s.strip() for s in lhs.split(":", 1))
            if lhs == "config_version":
                if rhs not in ("1", "2"):
                    raise ConfigError(f"unsupported config_version {rhs}")
                continue
            m = re.match(r"^(\w+)\((\w+)\)$", lhs)
            if m:
                lhs, new_scope = m.group(1), m.group(2)
            self._set(scope, lhs, rhs, coerce=True)
            if new_scope is not None:
                self._scope_links[(scope, lhs)] = new_scope

    # -- storage -----------------------------------------------------------

    def _set(self, scope: str, name: str, value: Any, coerce: bool = False):
        desc = P.PARAMS.get(name)
        if desc is None:
            raise ConfigError(
                f"unknown parameter {name!r} (scope {scope!r})"
            )
        if coerce and isinstance(value, str):
            value = _coerce(value, desc.type)
        if desc.type is float and isinstance(value, int):
            value = float(value)
        if desc.type is int and isinstance(value, bool):
            value = int(value)
        if not isinstance(value, desc.type):
            raise ConfigError(
                f"parameter {name!r} expects {desc.type.__name__}, got "
                f"{value!r}"
            )
        if desc.allowed and value not in desc.allowed:
            raise ConfigError(
                f"parameter {name!r} value {value!r} not in {desc.allowed}"
            )
        P.warn_if_na(name)
        self._values[(scope, name)] = value

    def set(self, name: str, value: Any, scope: str = "default"):
        self._set(scope, name, value)

    # -- lookup ------------------------------------------------------------

    def get(self, name: str, scope: str = "default"):
        if (scope, name) in self._values:
            return self._values[(scope, name)]
        if ("default", name) in self._values:
            return self._values[("default", name)]
        return P.get_description(name).default

    def get_scoped(self, name: str, scope: str = "default"):
        """Returns (value, new_scope) like the reference getParameter
        (amg_config.h:186-187): new_scope is where the named sub-solver's
        own parameters live."""
        value = self.get(name, scope)
        if (scope, name) in self._scope_links:
            return value, self._scope_links[(scope, name)]
        if (scope, name) in self._values:
            return value, scope
        if ("default", name) in self._scope_links:
            return value, self._scope_links[("default", name)]
        return value, scope

    def has(self, name: str, scope: str = "default") -> bool:
        return (scope, name) in self._values or (
            "default",
            name,
        ) in self._values

    def items(self):
        return dict(self._values)

    def content_hash(self, digest_size: int = 12) -> str:
        """Stable content hash of the full config identity — the
        config half of every hierarchy-reuse key (serve cache entries,
        store manifests).  Process-independent: sorted items, repr'd
        values.  Scope LINKS are part of the hash: two configs with
        identical key/value maps but different sub-solver scope
        resolution build different hierarchies and must never share a
        persisted setup."""
        import hashlib

        items = sorted(
            (str(scope), str(name), repr(value))
            for (scope, name), value in self._values.items()
        )
        h = hashlib.blake2b(digest_size=digest_size)
        for scope, name, value in items:
            h.update(f"{scope}\0{name}\0{value}\1".encode())
        for (scope, name), child in sorted(self._scope_links.items()):
            h.update(f"L\0{scope}\0{name}\0{child}\1".encode())
        return h.hexdigest()

    # -- persistence (amgx_tpu.store manifests) ----------------------------

    def to_state(self) -> dict:
        """JSON-able snapshot of the full scoped key/value map (values
        are str/int/float/bool by construction — ``_set`` type-checks
        against the parameter registry)."""
        return {
            "values": [
                [scope, name, value]
                for (scope, name), value in sorted(self._values.items())
            ],
            "scope_links": [
                [scope, name, child]
                for (scope, name), child in sorted(
                    self._scope_links.items()
                )
            ],
            "auto_scope": self._auto_scope,
        }

    @classmethod
    def from_state(cls, state: dict) -> "AMGConfig":
        """Inverse of :meth:`to_state`.  Values re-enter through
        ``_set`` so an edited/corrupted manifest still gets the
        registry's type and allowed-value checks."""
        cfg = cls()
        for scope, name, value in state.get("values", ()):
            cfg._set(str(scope), str(name), value)
        for scope, name, child in state.get("scope_links", ()):
            cfg._scope_links[(str(scope), str(name))] = str(child)
        cfg._auto_scope = int(state.get("auto_scope", 0))
        return cfg

    def __repr__(self):
        return f"AMGConfig({len(self._values)} values)"


def _coerce(s: str, t: type):
    if t is str:
        return s
    try:
        if t is int:
            return int(s)
        if t is float:
            return float(s)
    except ValueError:
        pass
    raise ConfigError(f"cannot coerce {s!r} to {t.__name__}")
