"""Typed parameter registry.

Reference parity: the ~138 ``registerParameter<T>`` calls at init
(core.cu:307-520) and the ParameterDescription struct (amg_config.h:107).
Defaults and names are kept identical — the shipped solver JSON configs are
the public contract.  GPU-runtime-only knobs (memory pools, CUDA streams)
are registered for config-file compatibility but ignored by the TPU
runtime; XLA owns memory and scheduling.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ParameterDescription:
    name: str
    type: type
    default: Any
    doc: str = ""
    allowed: Optional[Tuple] = None


_REGISTRY: dict[str, ParameterDescription] = {}


def register(name, type_, default, doc="", allowed=None):
    _REGISTRY[name] = ParameterDescription(name, type_, default, doc, allowed)


S, I, F = str, int, float

# --- global / runtime (core.cu:307-345) -----------------------------------
register("determinism_flag", I, 0, "force deterministic coarsening/coloring")
register("exception_handling", I, 0, "internal exception processing")
register("fine_level_consolidation", I, 0, "consolidate fine level")
register("use_cuda_ipc_consolidation", I, 0, "ignored on TPU")
register("amg_consolidation_flag", I, 0, "AMG level consolidation")
register("matrix_consolidation_lower_threshold", I, 0,
         "avg rows below which partitions merge")
register("matrix_consolidation_upper_threshold", I, 1000,
         "avg rows merged partitions should have")
register("device_mem_pool_size", I, 256 * 1024 * 1024, "ignored on TPU")
register("device_consolidation_pool_size", I, 256 * 1024 * 1024, "ignored")
register("device_mem_pool_max_alloc_size", I, 20 * 1024 * 1024, "ignored")
register("device_alloc_scaling_factor", I, 10, "ignored on TPU")
register("device_alloc_scaling_threshold", I, 16 * 1024, "ignored on TPU")
register("device_mem_pool_size_limit", I, 0, "ignored on TPU")
register("num_streams", I, 0, "ignored on TPU (XLA schedules)")
register("serialize_threads", I, 0, "ignored on TPU")
register("high_priority_stream", I, 0, "ignored on TPU")
register("communicator", S, "MPI", "comm backend; TPU uses ICI collectives",
         ("MPI", "MPI_DIRECT", "ICI"))
register("separation_interior", S, "INTERIOR", "latency-hiding split view")
register("separation_exterior", S, "OWNED", "calc limit view")
register("min_rows_latency_hiding", I, -1, "disable overlap below this")
register("exact_coarse_solve", I, 0, "gather global coarse problem")
register("matrix_halo_exchange", I, 0, "halo exchange depth on lower levels")
register("boundary_coloring", S, "SYNC_COLORS", "ILU boundary coloring")
register("halo_coloring", S, "LAST", "ILU halo coloring")
register("use_sum_stopping_criteria", I, 0, "sum rows across ranks for stop")
register("dist_coarse_sparsify", F, 0.0,
         "communication-reduced coarse grids (TPU distributed path): "
         "drop cross-shard coarse-level Galerkin entries with "
         "|a_ij| < theta*sqrt(|a_ii a_jj|) diagonal-lumped, capping "
         "halo width on coarse levels (stencil sparsification, "
         "arxiv 1512.04629); 0 disables")
register("dist_sparsify_from_level", I, 1,
         "first hierarchy level dist_coarse_sparsify applies to: "
         "spare the strongest-coupled first coarse levels, trim the "
         "deep ones where per-exchange latency dominates")
register("rhs_from_a", I, 0, "reader: synthesize rhs from A")
register("complex_conversion", I, 0, "reader: convert complex system")
register("matrix_writer", S, "matrixmarket", "", ("matrixmarket", "binary"))
register("block_format", S, "ROW_MAJOR", "", ("ROW_MAJOR", "COL_MAJOR"))
register("block_convert", I, 0, "reader: scalar->block conversion")

# --- solver selection (core.cu:596-688 registry names) --------------------
register("solver", S, "AMG", "the solving algorithm")
register("preconditioner", S, "AMG", "the preconditioner algorithm")
register("coarse_solver", S, "DENSE_LU_SOLVER", "coarsest-level solver")
register("smoother", S, "BLOCK_JACOBI", "the smoothing algorithm")
register("smoother_amg_list", S, "BLOCK_JACOBI", "per-level smoother list")
register("fine_smoother", S, "BLOCK_JACOBI", "")
register("coarse_smoother", S, "BLOCK_JACOBI", "")

# --- krylov -----------------------------------------------------------------
register("gmres_n_restart", I, 20, "Krylov vectors in (F)GMRES")
register("gmres_krylov_dim", I, 0, "max Krylov dim (0: match restart)")
register("subspace_dim_s", I, 8, "IDR(s) shadow-space dimension")

# --- s-step / communication-avoiding Krylov (solvers/sstep.py) -------------
register("s_step", I, 4,
         "SSTEP_PCG block size: s SpMVs and one fused Gram reduction "
         "per outer iteration (= s PCG steps); 1 degenerates to "
         "classic PCG")
register("sstep_basis", S, "SCALED",
         "s-step Krylov basis conditioning: MONOMIAL keeps the raw "
         "M^-1 A powers, SCALED renormalizes basis columns by their "
         "A-norm (from the Gram diagonal — no extra reduction) for "
         "numerical stability at larger s",
         ("MONOMIAL", "SCALED"))
register("sstep_replace_every", I, 0,
         "residual-replacement guard for s-step drift: every N outer "
         "iterations the recurred residual is recomputed as b - A x "
         "(one extra SpMV, no extra reduction); 0: off")

# --- coarse / dense ---------------------------------------------------------
register("dense_lu_num_rows", I, 128, "densify when rows <= this")
register("dense_lu_max_rows", I, 0, "never densify above this (0: unused)")
register("inexact_coarse_solver", S, "OPT_POLYNOMIAL",
         "inner method of coarse_solver=INEXACT: fixed-sweep "
         "optimal-weight polynomial smoothing or a few unmonitored "
         "s-step PCG steps replace the DenseLU factorization "
         "(solvers/inexact.py)",
         ("OPT_POLYNOMIAL", "SSTEP_PCG", "CHEBYSHEV", "KPZ_POLYNOMIAL",
          "BLOCK_JACOBI", "JACOBI_L1"))
register("dense_lu_zero_pivot", S, "REGULARIZE",
         "zero/tiny-pivot handling in DENSE_LU factorization: "
         "REGULARIZE refactorizes with a scaled ridge (degraded but "
         "convergent coarse solve), RAISE raises SetupError",
         ("REGULARIZE", "RAISE"))

# --- guardrails (core/errors.py taxonomy, solvers/base.py hooks) -----------
register("solve_retries", I, 0,
         "retry a FAILED/DIVERGED solve up to N times with a fresh "
         "trace, halved relaxation_factor, and zero initial guess "
         "(recovery hook; 0: off)")
register("stagnation_window", I, 0,
         "report DIVERGED when the residual has not decreased over "
         "this many iterations (stagnation detection; 0: off)")
register("precision_fallback", I, 1,
         "ITERATIVE_REFINEMENT accuracy guardrail: when the inner "
         "solver runs a reduced-precision hierarchy "
         "(hierarchy_dtype != SAME) and the refined solve trips the "
         "guardrail (non-SUCCESS status, or more outer corrections "
         "than refine_iteration_guard), re-solve once with an "
         "hierarchy_dtype=SAME fallback solver (0: off)")
register("refine_iteration_guard", I, 0,
         "outer-iteration guardrail for the precision fallback: more "
         "than N outer refinement corrections trips the f64 re-solve "
         "(0: only a non-SUCCESS status trips)")

# --- smoother knobs ---------------------------------------------------------
register("relaxation_factor", F, 0.9, "solver relaxation factor")
register("ilu_sparsity_level", I, 0, "0:ILU0 1:ILU1")
register("symmetric_GS", I, 0, "symmetric GS sweeps")
register("jacobi_iters", I, 5, "inner iterations for GSINNER")
register("GS_L1_variant", I, 0, "L1 Gauss-Seidel variant")
register("kpz_mu", I, 4, "KPZ polynomial mu")
register("kpz_order", I, 3, "KPZ polynomial order")
register("chebyshev_polynomial_order", I, 5, "Chebyshev order")
register("chebyshev_lambda_estimate_mode", I, 0,
         "0-2: power-iteration estimate, 3: user cheby_min/max_lambda")
register("cheby_max_lambda", F, 1.0, "user max eigenvalue guess")
register("cheby_min_lambda", F, 0.125, "user min eigenvalue guess")
register("reestimate_eigs", I, 0,
         "Chebyshev/OPT_POLYNOMIAL spectral-bound refresh cadence on "
         "values-only resetup: 0 reuses the cached bounds (pattern "
         "unchanged, bump bound_staleness), N>0 re-runs the power "
         "iteration every Nth resetup")
register("kaczmarz_coloring_needed", I, 1, "")
register("cf_smoothing_mode", I, 0, "CF smoothing flavour")

# --- AMG hierarchy ----------------------------------------------------------
register("algorithm", S, "CLASSICAL", "",
         ("CLASSICAL", "AGGREGATION", "ENERGYMIN"))
register("hierarchy_dtype", S, "SAME",
         "reduced-precision hierarchy values (the cheap-preconditioner "
         "policy, amg/hierarchy.py): cast level operators, P/R, and "
         "smoother state to this dtype at _finalize_setup.  SAME keeps "
         "the input dtype; wrap reduced hierarchies in "
         "ITERATIVE_REFINEMENT (f64 outer correction) to keep the "
         "final tolerance unchanged (doc/PERFORMANCE.md)",
         ("SAME", "FLOAT64", "F64", "DOUBLE", "FLOAT32", "F32", "FLOAT",
          "BFLOAT16", "BF16"))
register("level_dtype_policy", S, "COARSE",
         "which levels hierarchy_dtype applies to: COARSE casts levels "
         ">= 1 plus every P/R (finest operator keeps the input dtype), "
         "ALL additionally casts the finest level so the whole cycle "
         "runs reduced",
         ("COARSE", "ALL"))
register("amg_host_levels_rows", I, -1, "host levels below this (ignored)")
register("cycle", S, "V", "", ("V", "W", "F", "CG", "CGF"))
register("max_levels", I, 100, "maximum number of levels")
register("min_fine_rows", I, 1, "min rows in a fine level")
register("min_coarse_rows", I, 2, "min block rows in a level")
register("max_coarse_iters", I, 100, "max coarsest-level solve iterations")
register("coarsen_threshold", F, 1.0, "coarsening-ratio threshold")
register("presweeps", I, 1, "presmooth iterations")
register("postsweeps", I, 1, "postsmooth iterations")
register("finest_sweeps", I, -1, "finest-level sweeps (-1: presweeps)")
register("coarsest_sweeps", I, 2, "coarsest-level smoothing iterations")
register("cycle_iters", I, 2, "CG-cycle inner iterations")
register("structure_reuse_levels", I, 0, "hierarchy structure reuse depth")
register("matrix_free", I, 0,
         "MATRIX_FREE accel format (ops/stencil.py): detect verified "
         "constant / axis-separable stencil operators at setup and "
         "replace their O(nnz) DIA value planes with O(1)/O(axis) "
         "coefficient state regenerated on the fly — the SpMV streams "
         "only x and y.  Detection is bitwise-verified against the CSR "
         "values; non-stencil operators keep their formats (0: off)")
register("fused_cycle", I, 1,
         "fuse the smoother->residual->restrict descent leg on "
         "MATRIX_FREE levels into ONE fine-grid pass (identical "
         "arithmetic; the trace-time pass counter and "
         "amgx_solver_cycle_passes_total prove the count).  No-op for "
         "levels without the MATRIX_FREE format; 0 = reference "
         "three-pass legs (parity gates)")
register("error_scaling", I, 0, "coarse-correction scaling mode")
register("reuse_scale", I, 0, "reuse correction scale for N iters")
register("scaling_smoother_steps", I, 2, "")
register("intensive_smoothing", I, 0, "drastically increase sweeps")
register("coarseAgenerator", S, "LOW_DEG", "Galerkin product method")
register("coarseAgenerator_coarse", S, "LOW_DEG", "")
register("interpolator", S, "D1", "", ("D1", "D2", "MULTIPASS", "EM"))
register("energymin_interpolator", S, "EM", "")
register("energymin_selector", S, "CR", "")
register("selector", S, "PMIS", "coarse-grid selector")
register("setup_location", S, "AUTO",
         "classical setup placement: AUTO = device pipeline when the "
         "config is covered (AHAT+PMIS+D1), HOST = scipy pipeline, "
         "DEVICE = require the device pipeline",
         ("AUTO", "HOST", "DEVICE"))
register("aggressive_levels", I, 0, "aggressive-coarsening levels")
register("aggressive_interpolator", S, "MULTIPASS", "")

# --- aggregation ------------------------------------------------------------
register("handshaking_phases", I, 1, "")
register("aggregation_edge_weight_component", I, 0, "")
register("max_matching_iterations", I, 15, "pairwise matching iterations")
register("max_unassigned_percentage", F, 0.05, "")
register("weight_formula", I, 0, "aggregation edge-weight formula")
register("aggregation_passes", I, 3, "MULTI_PAIRWISE passes")
register("structured_aggregation", I, 1,
         "aggregate stencil-structured matrices in geometric blocks so "
         "coarse operators stay banded (TPU DIA fast path); 0 forces "
         "matching-based aggregation")
register("filter_weights", I, 0, "")
register("filter_weights_alpha", F, 0.5, "")
register("full_ghost_level", I, 0, "")
register("notay_weights", I, 0, "")
register("ghost_offdiag_limit", I, 0, "")
register("merge_singletons", I, 1, "merge singletons into neighbors")
register("serial_matching", I, 0, "")
register("modified_handshake", I, 0, "")
register("aggregate_size", I, 2, "DUMMY selector aggregate size")

# --- classical strength/interp ---------------------------------------------
register("strength", S, "AHAT", "", ("AHAT", "ALL", "AFFINITY"))
register("strength_threshold", F, 0.25, "strength threshold")
register("max_row_sum", F, 1.1, "weaken deps when row sum exceeds")
register("interp_truncation_factor", F, 1.1, "interp truncation factor")
register("interp_max_elements", I, -1, "max interp elements per row")
register("affinity_iterations", I, 4, "")
register("affinity_vectors", I, 4, "")

# --- coloring ---------------------------------------------------------------
register("coloring_level", I, 1, "0:none 1:dist-1 2:dist-2 ...")
register("reorder_cols_by_color", I, 0, "")
register("insert_diag_while_reordering", I, 0, "")
register("matrix_coloring_scheme", S, "MIN_MAX", "coloring algorithm")
register("max_num_hash", I, 7, "")
register("num_colors", I, 10, "round-robin colors")
register("max_uncolored_percentage", F, 0.15, "")
register("initial_color", I, 0, "")
register("use_bsrxmv", I, 0, "ignored on TPU")
register("fine_levels", I, -1, "")
register("coloring_try_remove_last_colors", I, 0, "")
register("coloring_custom_arg", S, "", "")
register("print_coloring_info", I, 0, "")
register("weakness_bound", I, 2**31 - 1, "")
register("late_rejection", I, 0, "")
register("geometric_dim", I, 2, "")

# --- convergence / monitoring ----------------------------------------------
register("max_iters", I, 100, "maximum solve iterations")
register("monitor_residual", I, 0, "compute residual each iteration")
register("convergence", S, "ABSOLUTE", "",
         ("ABSOLUTE", "RELATIVE_MAX", "RELATIVE_INI", "RELATIVE_INI_CORE",
          "RELATIVE_MAX_CORE", "COMBINED_REL_INI_ABS"))
register("norm", S, "L2", "", ("L1", "L1_SCALED", "L2", "LMAX"))
register("use_scalar_norm", I, 0, "force scalar norm for block matrices")
register("tolerance", F, 1e-12, "convergence tolerance")
register("alt_rel_tolerance", F, 1e-12, "combined-criterion rel tol")
register("rel_div_tolerance", F, -1.0, "divergence check (-1: off)")
register("verbosity_level", I, 3, "")
register("solver_verbose", I, 0, "")
register("print_config", I, 0, "")
register("print_solve_stats", I, 0, "")
register("print_grid_stats", I, 0, "")
register("print_vis_data", I, 0, "")
register("print_aggregation_info", I, 0, "")
register("obtain_timings", I, 0, "")
register("store_res_history", I, 0, "")
register("convergence_analysis", I, 0, "")
register("scaling", S, "NONE", "",
         ("NONE", "BINORMALIZATION", "NBINORMALIZATION",
          "DIAGONAL_SYMMETRIC"))
register("matrix_reordering", S, "AUTO",
         "bandwidth-reducing unknown renumbering at solver setup "
         "(TPU: unlocks the windowed gather SpMV kernel). AUTO adopts "
         "the RCM ordering only when it yields a faster matrix format",
         ("NONE", "RCM", "AUTO"))

# --- eigensolvers (src/eigensolvers registrations) -------------------------
register("eig_solver", S, "POWER_ITERATION", "eigensolver algorithm")
register("eig_max_iters", I, 100, "")
register("eig_tolerance", F, 1e-6, "")
register("eig_shift", F, 0.0, "spectral shift sigma")
register("eig_damping_factor", F, 0.85, "pagerank damping")
register("eig_which", S, "largest", "which eigenpair",
         ("smallest", "largest", "pagerank", "shift"))
register("eig_wanted_count", I, 1, "number of eigenpairs")
register("eig_subspace_size", I, 8, "subspace/Lanczos dimension")
register("eig_convergence_check_freq", I, 1, "convergence check frequency")
register("eig_eigenvector", I, 0, "compute eigenvectors flag")
register("eig_eigenvector_solver", S, "", "inverse-iteration solver cfg")

# ---------------------------------------------------------------------------
# Consumption classification (round-5 contract: every registered param
# is honored by code, explicitly TPU-N/A, or dead in the reference too;
# tests/test_config.py asserts registry == consumed ∪ TPU_NA ∪
# REF_UNREAD and fails when a new param lands unwired).

# GPU-runtime machinery with no TPU analogue: XLA owns memory pools,
# streams, and kernel scheduling; ICI collectives replace MPI
# transports; coloring of halo updates guards CUDA scatter races that
# cannot occur under XLA's deterministic execution.  Setting one of
# these in a config warns once (the value is accepted and ignored).
TPU_NA = frozenset({
    "device_mem_pool_size", "device_mem_pool_max_alloc_size",
    "device_mem_pool_size_limit", "device_consolidation_pool_size",
    "device_alloc_scaling_factor", "device_alloc_scaling_threshold",
    "high_priority_stream", "num_streams", "serialize_threads",
    "use_cuda_ipc_consolidation", "use_bsrxmv", "exception_handling",
    "communicator", "matrix_halo_exchange", "handshaking_phases",
    "modified_handshake", "halo_coloring", "boundary_coloring",
    "full_ghost_level", "ghost_offdiag_limit",
    "separation_interior", "separation_exterior",
    "fine_level_consolidation", "amg_consolidation_flag",
    "reorder_cols_by_color", "insert_diag_while_reordering",
    "block_format", "block_convert", "amg_host_levels_rows",
    # reuse_scale caches the error-scaling lambda to skip GPU kernel
    # launches; under XLA the dots fuse into the cycle and recompute
    # is free, so the scale is always fresh (amg/hierarchy.py)
    "reuse_scale",
})

# Registered by the reference's core.cu but never read by any reference
# code path either (verified by grep over /root/reference/src+include):
# kept for config-file compatibility, silently accepted exactly like
# the reference.  fine_levels is read but its value discarded
# (agg_selector.cu:283).  max_coarse_iters left this set when
# coarse_solver=INEXACT made it the inexact coarse-sweep cap
# (solvers/inexact.py).
REF_UNREAD = frozenset({
    "GS_L1_variant", "coarseAgenerator_coarse", "coarse_smoother",
    "fine_smoother", "geometric_dim", "initial_color", "jacobi_iters",
    "smoother_amg_list", "fine_levels",
})

_warned_na: set = set()


def warn_if_na(name: str):
    """One-time warning when a config sets a TPU-N/A parameter."""
    if name in TPU_NA and name not in _warned_na:
        import warnings

        _warned_na.add(name)
        warnings.warn(
            f"config parameter {name!r} is accepted for AmgX config "
            "compatibility but has no TPU analogue (XLA owns "
            "memory/streams; ICI collectives replace MPI transports)"
        )


PARAMS = _REGISTRY


def get_description(name: str) -> ParameterDescription:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unregistered parameter {name!r}") from None


def write_parameters_description(path=None) -> str:
    """Dump the registry (reference AMGX_write_parameters_description,
    amgx_c.h:529-531)."""
    lines = []
    for p in sorted(_REGISTRY.values(), key=lambda p: p.name):
        allowed = f" allowed={list(p.allowed)}" if p.allowed else ""
        lines.append(
            f"{p.name} <{p.type.__name__}> default={p.default!r}{allowed}"
            + (f" — {p.doc}" if p.doc else "")
        )
    text = "\n".join(lines) + "\n"
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text
