"""Eigensolver implementations.

Reference parity: single_iteration_eigensolver.cu (power / inverse /
pagerank), subspace_iteration_eigensolver.cu, lanczos_eigensolver.cu,
arnoldi_eigensolver.cu, lobpcg_eigensolver.cu.  Hot kernels (SpMV, QR,
Rayleigh-Ritz) run on device; small dense eigenproblems (tridiagonal /
Hessenberg / Ritz) on host — the same split the reference makes with its
LAPACK bridge (amgx_lapack.cu).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from amgx_tpu.eigensolvers.base import (
    EigenResult,
    EigenSolver,
    register_eigensolver,
)
from amgx_tpu.ops.spmv import spmv


def _start_vector(n, dtype, seed=7):
    v = np.random.default_rng(seed).standard_normal(n).astype(dtype)
    return v / np.linalg.norm(v)


@register_eigensolver("POWER_ITERATION", "SINGLE_ITERATION", "PAGERANK",
                      "INVERSE_ITERATION")
class SingleIterationEigenSolver(EigenSolver):
    """Power iteration family (reference single_iteration_eigensolver.cu):
      * which=largest: power iteration on A (- shift I)
      * which=smallest / INVERSE_ITERATION: inverse iteration via an inner
        linear solver (configured by the 'solver' parameter scope)
      * which=pagerank: power iteration on the damped column-stochastic
        Google matrix d*P + (1-d)/n 11^T (reference pagerank_operator.h)
    """

    def _setup_impl(self, A):
        if self.requested_name == "PAGERANK":
            self.which = "pagerank"
        self._inner = None
        self.check_freq = max(
            int(self.cfg.get("eig_convergence_check_freq", self.scope)), 1
        )
        if (
            self.which == "smallest"
            or self.requested_name == "INVERSE_ITERATION"
        ):
            from amgx_tpu.core.matrix import SparseMatrix
            from amgx_tpu.solvers.registry import create_solver, make_nested

            solve_A = A
            if self.shift != 0.0:
                # shift-invert: iterate on (A - sigma I)^{-1} (reference
                # single_iteration_eigensolver.cu ShiftedOperator)
                import scipy.sparse as sps

                sp = A.to_scipy()
                solve_A = SparseMatrix.from_scipy(
                    (sp - self.shift * sps.eye_array(sp.shape[0])).tocsr()
                )
            self._inner = make_nested(create_solver(self.cfg, self.scope))
            self._inner.setup(solve_A)
        if self.which == "pagerank":
            # column-normalized |A| as the link matrix; dangling columns
            # (no out-links) redistribute their mass via the teleport
            # distribution (reference update_dangling_nodes)
            sp = A.to_scipy()
            colsum = np.asarray(np.abs(sp).sum(axis=0)).ravel()
            self._dangling = jnp.asarray(
                (colsum == 0).astype(np.float64)
            )
            colsum = np.where(colsum > 0, colsum, 1.0)
            import scipy.sparse as sps

            from amgx_tpu.core.matrix import SparseMatrix

            self._google = SparseMatrix.from_scipy(
                (abs(sp) @ sps.diags_array(1.0 / colsum)).tocsr()
            )
            # teleport distribution: personalization vector when supplied
            # (AMGX_eigensolver_pagerank_setup), else uniform
            pers = getattr(self, "personalization", None)
            if pers is not None:
                pers = np.abs(np.asarray(pers, dtype=np.float64))
                tot = pers.sum()
                pers = pers / (tot if tot > 0 else 1.0)
                self._teleport = jnp.asarray(pers)
            else:
                self._teleport = jnp.full(
                    (A.n_rows,), 1.0 / A.n_rows
                )

    def _solve_impl(self, x0=None) -> EigenResult:
        A = self.A
        n = A.n_rows
        dtype = np.dtype(A.values.dtype)
        v = jnp.asarray(
            x0 if x0 is not None else _start_vector(n, dtype)
        )
        shift = self.shift
        lam = 0.0
        res = np.inf
        it = 0

        if self.which == "pagerank":
            G = self._google
            d = self.damping
            dang = self._dangling.astype(dtype)
            tele = self._teleport.astype(dtype)
            # Perron vector: start from the teleport distribution
            v = tele

            @jax.jit
            def step(v):
                dangling_mass = jnp.dot(dang, v)
                w = d * (spmv(G, v) + dangling_mass * tele) + (
                    1.0 - d
                ) * jnp.sum(v) * tele
                return w / jnp.sum(jnp.abs(w))

            for it in range(1, self.max_iters + 1):
                w = step(v)
                if it % self.check_freq == 0:
                    res = float(jnp.max(jnp.abs(w - v)))
                    if res < self.tolerance:
                        v = w
                        break
                v = w
            return EigenResult(
                eigenvalues=np.array([1.0]),
                eigenvectors=np.asarray(v)[:, None],
                iterations=it,
                converged=res < self.tolerance,
                residual=res,
            )

        if self._inner is not None:
            # inverse iteration: v <- normalize(A^{-1} v)
            for it in range(1, self.max_iters + 1):
                w = self._inner.solve(np.asarray(v)).x
                nrm = float(jnp.linalg.norm(w))
                w = w / nrm
                lam_new = float(jnp.dot(w, spmv(A, w)))
                res = abs(lam_new - lam)
                lam = lam_new
                v = w
                if res < self.tolerance * max(abs(lam), 1.0):
                    break
            return EigenResult(
                eigenvalues=np.array([lam]),
                eigenvectors=np.asarray(v)[:, None],
                iterations=it,
                converged=res < self.tolerance * max(abs(lam), 1.0),
                residual=res,
            )

        @jax.jit
        def step(v):
            w = spmv(A, v)
            if shift != 0.0:
                w = w - shift * v
            lam = jnp.dot(v, w)
            rnorm = jnp.linalg.norm(w - lam * v)
            return w / jnp.linalg.norm(w), lam, rnorm

        for it in range(1, self.max_iters + 1):
            v, lam_j, rnorm_j = step(v)
            if it % self.check_freq == 0 or it == self.max_iters:
                lam = float(lam_j)
                res = float(rnorm_j) / max(abs(lam), 1e-30)
                if res < self.tolerance:
                    break
        return EigenResult(
            eigenvalues=np.array([lam + shift]),
            eigenvectors=np.asarray(v)[:, None],
            iterations=it,
            converged=res < self.tolerance,
            residual=res,
        )


@register_eigensolver("SUBSPACE_ITERATION")
class SubspaceIterationEigenSolver(EigenSolver):
    """Block power iteration with QR + Rayleigh-Ritz (reference
    subspace_iteration_eigensolver.cu)."""

    def _solve_impl(self, x0=None) -> EigenResult:
        A = self.A
        n = A.n_rows
        k = max(self.wanted_count, 1)
        m = max(self.subspace_size, k + 2)
        dtype = np.dtype(A.values.dtype)
        rng = np.random.default_rng(11)
        V = jnp.asarray(rng.standard_normal((n, m)).astype(dtype))
        V, _ = jnp.linalg.qr(V)

        @jax.jit
        def step(V):
            W = jax.vmap(lambda col: spmv(A, col), in_axes=1, out_axes=1)(V)
            Q, _ = jnp.linalg.qr(W)
            H = Q.T @ jax.vmap(
                lambda col: spmv(A, col), in_axes=1, out_axes=1
            )(Q)
            return Q, H

        res = np.inf
        lam = np.zeros(k)
        it = 0
        for it in range(1, self.max_iters + 1):
            V, H = step(V)
            evals, evecs = np.linalg.eigh(np.asarray((H + H.T) / 2.0))
            order = (
                np.argsort(evals)[::-1]
                if self.which == "largest"
                else np.argsort(evals)
            )
            lam = evals[order[:k]]
            # residual-based convergence: ||A x - lam x|| for the leading
            # Ritz pair (eigenvalue-change criteria converge prematurely)
            x1 = V @ jnp.asarray(evecs[:, order[0]])
            rvec = spmv(A, x1) - lam[0] * x1
            res = float(jnp.linalg.norm(rvec)) / max(abs(lam[0]), 1e-30)
            if res < self.tolerance:
                break
        X = np.asarray(V) @ np.asarray(evecs[:, order[:k]])
        return EigenResult(
            eigenvalues=lam,
            eigenvectors=X,
            iterations=it,
            converged=res < self.tolerance,
            residual=res,
        )


@register_eigensolver("LANCZOS")
class LanczosEigenSolver(EigenSolver):
    """Symmetric Lanczos with full reorthogonalization (reference
    lanczos_eigensolver.cu); tridiagonal Ritz problem on host."""

    def _solve_impl(self, x0=None) -> EigenResult:
        A = self.A
        n = A.n_rows
        dtype = np.dtype(A.values.dtype)
        m = min(self._krylov_dim(), n)
        v = jnp.asarray(
            x0 if x0 is not None else _start_vector(n, dtype)
        )
        V = [v]
        alphas, betas = [], []
        beta = 0.0
        for j in range(m):
            w = spmv(A, V[-1])
            if j > 0:
                w = w - beta * V[-2]
            alpha = float(jnp.dot(V[-1], w))
            w = w - alpha * V[-1]
            # full reorthogonalization (device matmul)
            Vm = jnp.stack(V)
            w = w - Vm.T @ (Vm @ w)
            beta = float(jnp.linalg.norm(w))
            alphas.append(alpha)
            if beta < 1e-14:
                break
            betas.append(beta)
            V.append(w / beta)
        import scipy.linalg as sla

        T_evals, T_evecs = sla.eigh_tridiagonal(
            np.array(alphas), np.array(betas[: len(alphas) - 1])
        )
        k = max(self.wanted_count, 1)
        order = (
            np.argsort(T_evals)[::-1]
            if self.which == "largest"
            else np.argsort(T_evals)
        )
        lam = T_evals[order[:k]]
        Vm = np.asarray(jnp.stack(V[: len(alphas)]))  # (m, n)
        X = Vm.T @ T_evecs[:, order[:k]]
        # residual of the leading pair
        x1 = X[:, 0] / np.linalg.norm(X[:, 0])
        r = np.asarray(spmv(A, x1)) - lam[0] * x1
        res = float(np.linalg.norm(r)) / max(abs(lam[0]), 1e-30)
        return EigenResult(
            eigenvalues=lam,
            eigenvectors=X,
            iterations=len(alphas),
            converged=res < self.tolerance,
            residual=res,
        )


@register_eigensolver("ARNOLDI")
class ArnoldiEigenSolver(EigenSolver):
    """Arnoldi for nonsymmetric spectra (reference arnoldi_eigensolver.cu);
    Hessenberg eigenproblem on host."""

    def _solve_impl(self, x0=None) -> EigenResult:
        A = self.A
        n = A.n_rows
        dtype = np.dtype(A.values.dtype)
        m = min(self._krylov_dim(), n)
        v = jnp.asarray(
            x0 if x0 is not None else _start_vector(n, dtype)
        )
        V = [v]
        H = np.zeros((m + 1, m))
        for j in range(m):
            w = spmv(A, V[j])
            for i in range(j + 1):
                H[i, j] = float(jnp.dot(V[i], w))
                w = w - H[i, j] * V[i]
            H[j + 1, j] = float(jnp.linalg.norm(w))
            if H[j + 1, j] < 1e-14:
                m = j + 1
                break
            V.append(w / H[j + 1, j])
        evals, evecs = np.linalg.eig(H[:m, :m])
        k = max(self.wanted_count, 1)
        order = np.argsort(np.abs(evals))
        order = order[::-1] if self.which == "largest" else order
        lam = evals[order[:k]]
        Vm = np.asarray(jnp.stack(V[:m]))
        X = Vm.T @ evecs[:, order[:k]]
        x1 = X[:, 0] / np.linalg.norm(X[:, 0])
        r = np.asarray(spmv(A, np.real(x1).astype(dtype))) - np.real(
            lam[0] * x1
        )
        res = float(np.linalg.norm(r)) / max(abs(lam[0]), 1e-30)
        return EigenResult(
            eigenvalues=lam,
            eigenvectors=X,
            iterations=m,
            converged=res < self.tolerance,
            residual=res,
        )


@register_eigensolver("LOBPCG")
class LOBPCGEigenSolver(EigenSolver):
    """LOBPCG for extreme eigenpairs of SPD matrices (reference
    lobpcg_eigensolver.cu); Rayleigh-Ritz on the [X R P] basis."""

    def _solve_impl(self, x0=None) -> EigenResult:
        A = self.A
        n = A.n_rows
        k = max(self.wanted_count, 1)
        dtype = np.dtype(A.values.dtype)
        rng = np.random.default_rng(13)
        X = np.linalg.qr(rng.standard_normal((n, k)).astype(dtype))[0]
        X = jnp.asarray(X)
        largest = self.which == "largest"

        Amul = jax.jit(
            jax.vmap(lambda col: spmv(A, col), in_axes=1, out_axes=1)
        )
        P = None
        lam = np.zeros(k)
        res = np.inf
        it = 0
        for it in range(1, self.max_iters + 1):
            AX = Amul(X)
            lam_m = np.asarray(jnp.diag(X.T @ AX))
            R = AX - X * jnp.asarray(lam_m)
            res = float(jnp.max(jnp.linalg.norm(R, axis=0))) / max(
                float(np.max(np.abs(lam_m))), 1e-30
            )
            if res < self.tolerance:
                lam = lam_m
                break
            basis = [X, R] + ([P] if P is not None else [])
            S = jnp.concatenate(basis, axis=1)
            # orthonormalize the trial basis
            S, _ = jnp.linalg.qr(S)
            AS = Amul(S)
            G = np.asarray(S.T @ AS)
            G = (G + G.T) / 2.0
            evals, evecs = np.linalg.eigh(G)
            order = np.argsort(evals)[::-1] if largest else np.argsort(
                evals
            )
            C = jnp.asarray(evecs[:, order[:k]])
            X_new = S @ C
            P = X_new - X @ (X.T @ X_new)
            X = X_new
            lam = evals[order[:k]]
        return EigenResult(
            eigenvalues=np.asarray(lam),
            eigenvectors=np.asarray(X),
            iterations=it,
            converged=res < self.tolerance,
            residual=res,
        )
