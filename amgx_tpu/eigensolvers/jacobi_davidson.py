"""Jacobi-Davidson eigensolver (reference jacobi_davidson_eigensolver.cu).

Symmetric JD for the extreme eigenpair: expand a search space V with
approximate solutions of the projected correction equation

    (I - u u^T)(A - theta I)(I - u u^T) t = -r,   t ⟂ u

solved by a few CG iterations; Rayleigh-Ritz on V gives the Ritz pair.
Restarts keep the best Ritz vectors when the space fills.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from amgx_tpu.eigensolvers.base import (
    EigenResult,
    EigenSolver,
    register_eigensolver,
)
from amgx_tpu.ops.spmv import spmv


def _correction_cg(A, theta, u, r, iters=8):
    """Approximately solve the projected correction equation with CG."""

    def proj(v):
        return v - jnp.dot(u, v) * u

    def op(v):
        return proj(spmv(A, proj(v)) - theta * proj(v))

    t = jnp.zeros_like(r)
    res = proj(-r)
    p = res
    rho = jnp.dot(res, res)
    for _ in range(iters):
        q = op(p)
        pq = jnp.dot(p, q)
        alpha = jnp.where(pq != 0, rho / pq, 0.0)
        t = t + alpha * p
        res = res - alpha * q
        rho_new = jnp.dot(res, res)
        beta = jnp.where(rho != 0, rho_new / rho, 0.0)
        p = res + beta * p
        rho = rho_new
    return t


@register_eigensolver("JACOBI_DAVIDSON")
class JacobiDavidsonEigenSolver(EigenSolver):
    def _solve_impl(self, x0=None) -> EigenResult:
        A = self.A
        n = A.n_rows
        dtype = np.dtype(A.values.dtype)
        m_max = max(self.subspace_size, 8)
        largest = self.which != "smallest"
        rng = np.random.default_rng(17)
        v = x0 if x0 is not None else rng.standard_normal(n).astype(dtype)
        v = jnp.asarray(v / np.linalg.norm(np.asarray(v)))
        V = [v]
        theta = 0.0
        u = v
        res = np.inf
        it = 0
        for it in range(1, self.max_iters + 1):
            Vm = jnp.stack(V)  # (m, n)
            AV = jax.vmap(lambda col: spmv(A, col))(Vm)
            H = np.asarray(Vm @ AV.T)
            H = (H + H.T) / 2.0
            evals, evecs = np.linalg.eigh(H)
            j = -1 if largest else 0
            theta = float(evals[j])
            u = Vm.T @ jnp.asarray(evecs[:, j])
            u = u / jnp.linalg.norm(u)
            r = spmv(A, u) - theta * u
            res = float(jnp.linalg.norm(r)) / max(abs(theta), 1e-30)
            if res < self.tolerance:
                break
            if len(V) >= m_max:  # thick restart with the best Ritz vector
                V = [u]
            t = _correction_cg(A, theta, u, r)
            # orthogonalize t against the space
            Vm = jnp.stack(V)
            t = t - Vm.T @ (Vm @ t)
            nrm = float(jnp.linalg.norm(t))
            if nrm < 1e-12:
                t = jnp.asarray(
                    rng.standard_normal(n).astype(dtype)
                )
                t = t - Vm.T @ (Vm @ t)
                nrm = float(jnp.linalg.norm(t))
            V.append(t / nrm)
        # return the k best Ritz pairs from the final subspace (siblings
        # honor eig_wanted_count the same way)
        k = max(self.wanted_count, 1)
        Vm = jnp.stack(V)
        AV = jax.vmap(lambda col: spmv(A, col))(Vm)
        H = np.asarray(Vm @ AV.T)
        H = (H + H.T) / 2.0
        evals, evecs = np.linalg.eigh(H)
        order = np.argsort(evals)[::-1] if largest else np.argsort(evals)
        k = min(k, len(evals))
        lam = evals[order[:k]]
        X = np.asarray(Vm.T @ jnp.asarray(evecs[:, order[:k]]))
        return EigenResult(
            eigenvalues=lam,
            eigenvectors=X,
            iterations=it,
            converged=res < self.tolerance,
            residual=res,
        )
