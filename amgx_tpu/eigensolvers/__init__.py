"""Eigensolvers (reference src/eigensolvers/: EigenSolver base
eigensolver.h:25-150; factories eigensolvers.cu:38-48; shipped configs
src/configs/eigen_configs/).

Registered: POWER_ITERATION, SINGLE_ITERATION, INVERSE_ITERATION,
PAGERANK, SUBSPACE_ITERATION, LANCZOS, ARNOLDI, LOBPCG,
JACOBI_DAVIDSON.
"""

from amgx_tpu.eigensolvers.base import (
    EigenResult,
    EigenSolver,
    EigenSolverRegistry,
    create_eigensolver,
)
from amgx_tpu.eigensolvers import algorithms  # noqa: F401  (registration)
from amgx_tpu.eigensolvers import jacobi_davidson  # noqa: F401

__all__ = [
    "EigenResult",
    "EigenSolver",
    "EigenSolverRegistry",
    "create_eigensolver",
]
