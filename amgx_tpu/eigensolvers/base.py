"""EigenSolver contract (reference eigensolver.h:25-150): configured by
eig_* parameters, setup(A) then solve() returning eigenpairs."""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

from amgx_tpu.core.matrix import SparseMatrix


@dataclasses.dataclass
class EigenResult:
    eigenvalues: np.ndarray  # (k,)
    eigenvectors: Optional[np.ndarray]  # (n, k) or None
    iterations: int
    converged: bool
    residual: float


_EIGENSOLVERS: Dict[str, type] = {}


class EigenSolverRegistry:
    @staticmethod
    def register(name, cls):
        _EIGENSOLVERS[name] = cls

    @staticmethod
    def get(name):
        try:
            return _EIGENSOLVERS[name]
        except KeyError:
            raise KeyError(
                f"unregistered eigensolver {name!r}; known: "
                f"{sorted(_EIGENSOLVERS)}"
            ) from None


def register_eigensolver(*names):
    def deco(cls):
        for n in names:
            EigenSolverRegistry.register(n, cls)
        cls.registry_name = names[0]
        return cls

    return deco


class EigenSolver:
    """Base: reads the eig_* parameter family (core registrations)."""

    registry_name = "?"

    def __init__(self, cfg, scope: str = "default"):
        self.cfg = cfg
        self.scope = scope
        g = lambda k: cfg.get(k, scope)
        self.max_iters = int(g("eig_max_iters"))
        self.tolerance = float(g("eig_tolerance"))
        self.shift = float(g("eig_shift"))
        self.which = str(g("eig_which")).lower()
        self.wanted_count = int(g("eig_wanted_count"))
        self.subspace_size = int(g("eig_subspace_size"))
        self.damping = float(g("eig_damping_factor"))
        self.want_vectors = bool(g("eig_eigenvector"))
        self.A: Optional[SparseMatrix] = None
        self.requested_name = type(self).registry_name

    def setup(self, A: SparseMatrix):
        self.A = A
        self._setup_impl(A)
        return self

    def _setup_impl(self, A):
        pass

    def _krylov_dim(self) -> int:
        """Krylov dimension for single-shot Lanczos/Arnoldi: the explicit
        eig_subspace_size when configured, else the iteration budget
        (the reference restarts; a long single sweep is equivalent here)."""
        if self.cfg.has("eig_subspace_size", self.scope):
            return max(self.subspace_size, 2 * self.wanted_count + 2)
        return max(self.max_iters, 2 * self.wanted_count + 2)

    def solve(self, x0=None) -> EigenResult:
        raise NotImplementedError


def create_eigensolver(cfg, scope: str = "default") -> EigenSolver:
    name = str(cfg.get("eig_solver", scope)).upper()
    inst = EigenSolverRegistry.get(name)(cfg, scope)
    # several registry names share a class (reference SINGLE_ITERATION
    # family); record which one was asked for so setup can specialize
    inst.requested_name = name
    return inst
