"""EigenSolver contract (reference eigensolver.h:25-150): configured by
eig_* parameters, setup(A) then solve() returning eigenpairs."""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

from amgx_tpu.core.matrix import SparseMatrix


@dataclasses.dataclass
class EigenResult:
    eigenvalues: np.ndarray  # (k,)
    eigenvectors: Optional[np.ndarray]  # (n, k) or None
    iterations: int
    converged: bool
    residual: float
    # per-vector convergence of the eigenvector post-pass (inverse
    # iteration): (k,) bool, or None when the algorithm produced the
    # vectors itself
    vector_converged: Optional[np.ndarray] = None


_EIGENSOLVERS: Dict[str, type] = {}


class EigenSolverRegistry:
    @staticmethod
    def register(name, cls):
        _EIGENSOLVERS[name] = cls

    @staticmethod
    def get(name):
        try:
            return _EIGENSOLVERS[name]
        except KeyError:
            raise KeyError(
                f"unregistered eigensolver {name!r}; known: "
                f"{sorted(_EIGENSOLVERS)}"
            ) from None


def register_eigensolver(*names):
    def deco(cls):
        for n in names:
            EigenSolverRegistry.register(n, cls)
        cls.registry_name = names[0]
        return cls

    return deco


class EigenSolver:
    """Base: reads the eig_* parameter family (core registrations)."""

    registry_name = "?"

    def __init__(self, cfg, scope: str = "default"):
        self.cfg = cfg
        self.scope = scope
        g = lambda k: cfg.get(k, scope)
        self.max_iters = int(g("eig_max_iters"))
        self.tolerance = float(g("eig_tolerance"))
        self.shift = float(g("eig_shift"))
        self.which = str(g("eig_which")).lower()
        self.wanted_count = int(g("eig_wanted_count"))
        self.subspace_size = int(g("eig_subspace_size"))
        self.damping = float(g("eig_damping_factor"))
        self.want_vectors = bool(g("eig_eigenvector"))
        self.A: Optional[SparseMatrix] = None
        self.requested_name = type(self).registry_name

    def setup(self, A: SparseMatrix):
        self.A = A
        self._setup_impl(A)
        return self

    def _setup_impl(self, A):
        pass

    def _krylov_dim(self) -> int:
        """Krylov dimension for single-shot Lanczos/Arnoldi: the explicit
        eig_subspace_size when configured, else the iteration budget
        (the reference restarts; a long single sweep is equivalent here)."""
        if self.cfg.has("eig_subspace_size", self.scope):
            return max(self.subspace_size, 2 * self.wanted_count + 2)
        return max(self.max_iters, 2 * self.wanted_count + 2)

    def solve(self, x0=None) -> EigenResult:
        """Run the algorithm, then the optional eigenvector post-pass
        (reference eigensolver.cu solve + eigenvector_solver)."""
        return self._maybe_extract_vectors(self._solve_impl(x0))

    def _solve_impl(self, x0=None) -> EigenResult:
        raise NotImplementedError

    # inverse-iteration post-pass bounds: iterate to the residual
    # tolerance below, at most this many steps per vector
    _VECTOR_MAX_STEPS = 32

    def _maybe_extract_vectors(self, res: EigenResult) -> EigenResult:
        """Post-pass eigenvector extraction (reference
        eigensolver.cu:271-276 + eigenvector_solver.cu): when
        ``eig_eigenvector_solver`` names a solver and the algorithm did
        not already produce vectors, run shift-inverted inverse
        iteration per converged eigenvalue — to the residual tolerance
        ``||A v - lam v|| <= eig_tolerance * ||A|| * ||v||`` with an
        iteration cap, a COMPLEX shift when the operator is complex
        (a real-part shift stalls on complex pairs), and per-vector
        convergence flags in ``vector_converged``."""
        name = str(self.cfg.get("eig_eigenvector_solver", self.scope))
        if (not self.want_vectors or res.eigenvectors is not None
                or not name or not res.eigenvalues.size):
            return res
        import dataclasses

        import numpy as np
        import scipy.sparse as sps

        from amgx_tpu.core.matrix import SparseMatrix
        from amgx_tpu.solvers.registry import SolverRegistry, make_nested

        sp = self.A.to_scipy().tocsr()
        n = sp.shape[0]
        is_complex = np.issubdtype(sp.dtype, np.complexfloating)
        # residual scale: lam and v are normalized against the operator
        # magnitude so the tolerance is meaningful for scaled matrices
        a_scale = max(float(abs(sp).sum(axis=1).max()), 1e-300)
        tol = max(self.tolerance, 1e-14)
        lams = np.atleast_1d(res.eigenvalues)
        vecs = np.zeros((n, len(lams)), dtype=sp.dtype)
        vec_ok = np.zeros(len(lams), dtype=bool)
        rng = np.random.default_rng(7)
        for k, lam in enumerate(lams):
            lam_c = complex(lam) if is_complex else float(np.real(lam))
            # relative shift offset, with an absolute floor scaled by
            # ||A|| so lam == 0 does not produce a near-exact-singular
            # shifted matrix (ADVICE r5: shift=1e-12 at lam=0)
            off = 1e-6 * max(abs(lam_c), 1e-4 * a_scale)
            shift = lam_c + off
            shifted = (sp - shift * sps.eye_array(n)).tocsr()
            inner = make_nested(
                SolverRegistry.get(name)(self.cfg, self.scope))
            inner.setup(SparseMatrix.from_scipy(shifted))
            v = rng.standard_normal(n)
            if is_complex:
                v = v + 1j * rng.standard_normal(n)
            v = v.astype(sp.dtype)
            v = v / max(np.linalg.norm(v), 1e-300)
            for _ in range(self._VECTOR_MAX_STEPS):
                v = np.asarray(inner.solve(v).x)
                v = v / max(np.linalg.norm(v), 1e-300)
                # residual against the vector's own Rayleigh quotient:
                # the algorithm's eigenvalue is only tol-accurate, so
                # ||A v - lam v|| would floor at the eigenvalue error
                Av = sp @ v
                rho = np.vdot(v, Av)
                resid = float(np.linalg.norm(Av - rho * v))
                if resid <= tol * a_scale:
                    vec_ok[k] = True
                    break
            vecs[:, k] = v
        return dataclasses.replace(
            res, eigenvectors=vecs, vector_converged=vec_ok
        )


def create_eigensolver(cfg, scope: str = "default") -> EigenSolver:
    name = str(cfg.get("eig_solver", scope)).upper()
    inst = EigenSolverRegistry.get(name)(cfg, scope)
    # several registry names share a class (reference SINGLE_ITERATION
    # family); record which one was asked for so setup can specialize
    inst.requested_name = name
    return inst
