"""Version info (reference parity: ReleaseVersion.txt, AMGX_get_api_version)."""

__version__ = "0.1.0"

# The reference API version this framework tracks feature-parity against
# (reference: ReleaseVersion.txt:1 -> 2.5.0).
REFERENCE_API_VERSION = (2, 5)


def get_api_version():
    """Returns (major, minor) like AMGX_get_api_version (amgx_c.h:160-163)."""
    return REFERENCE_API_VERSION
