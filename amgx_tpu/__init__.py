"""amgx_tpu — a TPU-native algebraic-multigrid / sparse-solver framework.

A from-scratch JAX/XLA rebuild of the capability surface of NVIDIA AmgX
(reference: /root/reference, C++/CUDA): algebraic multigrid (classical
Ruge-Stuben, aggregation), Krylov methods, smoothers/preconditioners,
eigensolvers, and multi-chip distribution via sharded halo exchange over a
``jax.sharding.Mesh`` (replacing the reference's MPI halo exchange,
src/distributed/).

Architecture stance (TPU-first, not a translation):
  * dtype polymorphism replaces the 16-way compile-time mode system
    (reference include/amgx_config.h:103-121); mode names survive only as
    aliases in :mod:`amgx_tpu.core.types`.
  * matrices are pytrees of static-shape arrays; solve paths are jitted
    end-to-end with ``lax.while_loop`` iteration; hierarchy setup is
    host-side (numpy/scipy) producing per-level static shapes.
  * distribution is SPMD ``shard_map`` over a device mesh with
    ``ppermute``/``psum`` collectives riding ICI.
"""

from amgx_tpu.core.types import (
    Mode,
    ViewType,
    mode_from_name,
)
from amgx_tpu.core.matrix import SparseMatrix
from amgx_tpu.config.amg_config import AMGConfig
from amgx_tpu.version import __version__

_initialized = False


def initialize():
    """Library init: register all factories (reference: core.cu:723 amgx::initialize).

    Idempotent. Factory registration in this rebuild happens at import time of
    the subpackages; this exists for API parity and future lazy registration.
    """
    global _initialized
    if _initialized:
        return
    # Honor an explicit JAX_PLATFORMS env pin.  Platform plugins (e.g.
    # a remote-TPU sitecustomize) may override jax_platforms via
    # jax.config at interpreter start, which silently defeats the user's
    # env selection — embedded-interpreter hosts (the native C API) have
    # no other way to choose the backend.
    import os

    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms:
        import jax

        if jax.config.jax_platforms != env_platforms:
            jax.config.update("jax_platforms", env_platforms)
    # Importing the registries triggers registration (reference core.cu:552-688).
    import amgx_tpu.solvers  # noqa: F401
    import amgx_tpu.amg  # noqa: F401
    _initialized = True


def finalize():
    """API-parity no-op (reference: core.cu:791 amgx::finalize)."""
    global _initialized
    _initialized = False


__all__ = [
    "Mode",
    "ViewType",
    "mode_from_name",
    "SparseMatrix",
    "AMGConfig",
    "initialize",
    "finalize",
    "__version__",
]
