"""File-based worker registry: how fleet processes find each other.

Each worker announces itself as one JSON file under the registry
root (``<root>/<worker_id>.json``), written with the same atomic
tmp-then-``os.replace`` discipline as the
:class:`~amgx_tpu.store.store.ArtifactStore` — a reader never sees a
half-written record, and a crashed writer leaves at worst a stale
``.tmp`` that is ignored.  No daemon, no lock server: liveness is
``os.kill(pid, 0)`` plus a heartbeat timestamp, which is exactly
enough for a single-host fleet (the target deployment: one worker
per TPU slice on the same VM).

Corrupt or stale records degrade to "worker not listed" — the fleet
twin of the store's digest-verified reads degrading to cache misses.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

_SUFFIX = ".json"


class WorkerRecord:
    """One announced worker: identity, wire address, capabilities."""

    __slots__ = (
        "worker_id", "host", "port", "pid", "slot", "dist_capable",
        "started_at", "heartbeat_at", "extra",
    )

    def __init__(self, worker_id: str, host: str, port: int, pid: int,
                 slot: int = 0, dist_capable: bool = False,
                 started_at: float = 0.0, heartbeat_at: float = 0.0,
                 extra: Optional[dict] = None):
        self.worker_id = str(worker_id)
        self.host = str(host)
        self.port = int(port)
        self.pid = int(pid)
        self.slot = int(slot)
        self.dist_capable = bool(dist_capable)
        self.started_at = float(started_at)
        self.heartbeat_at = float(heartbeat_at)
        self.extra = dict(extra or {})

    @property
    def address(self) -> tuple:
        return (self.host, self.port)

    def to_dict(self) -> dict:
        return {
            "worker_id": self.worker_id,
            "host": self.host,
            "port": self.port,
            "pid": self.pid,
            "slot": self.slot,
            "dist_capable": self.dist_capable,
            "started_at": self.started_at,
            "heartbeat_at": self.heartbeat_at,
            "extra": self.extra,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "WorkerRecord":
        return cls(
            d["worker_id"], d["host"], int(d["port"]), int(d["pid"]),
            slot=int(d.get("slot", 0)),
            dist_capable=bool(d.get("dist_capable", False)),
            started_at=float(d.get("started_at", 0.0)),
            heartbeat_at=float(d.get("heartbeat_at", 0.0)),
            extra=d.get("extra") or {},
        )

    def alive(self) -> bool:
        """Best-effort liveness: the announced pid still exists (and
        is signalable).  A same-host check — remote pids are assumed
        alive and left to wire-level breakers."""
        if self.pid <= 0:
            return False
        try:
            os.kill(self.pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            return True  # exists, owned by someone else
        except OSError:
            return True
        return True


class WorkerRegistry:
    """Directory of :class:`WorkerRecord` files.

    Writers call :meth:`announce` once and :meth:`heartbeat`
    periodically; :meth:`withdraw` removes the record on orderly
    shutdown.  Readers call :meth:`workers` (live records only) or
    :meth:`lookup`.  All reads tolerate concurrent writers and
    garbage files.
    """

    def __init__(self, root):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, worker_id: str) -> str:
        # worker ids become filenames: refuse separators outright
        wid = str(worker_id)
        if not wid or "/" in wid or "\\" in wid or wid.startswith("."):
            raise ValueError(f"invalid worker id {worker_id!r}")
        return os.path.join(self.root, wid + _SUFFIX)

    # -- writer side ---------------------------------------------------

    def announce(self, record: WorkerRecord) -> None:
        record.started_at = record.started_at or time.time()
        record.heartbeat_at = time.time()
        self._write(record)

    def heartbeat(self, record: WorkerRecord) -> None:
        record.heartbeat_at = time.time()
        self._write(record)

    def _write(self, record: WorkerRecord) -> None:
        path = self._path(record.worker_id)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(record.to_dict(), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def withdraw(self, worker_id: str) -> None:
        try:
            os.remove(self._path(worker_id))
        except FileNotFoundError:
            pass

    # -- reader side ---------------------------------------------------

    def lookup(self, worker_id: str) -> Optional[WorkerRecord]:
        """The record for ``worker_id``, or None when absent or
        unreadable (corrupt record == not announced)."""
        path = self._path(worker_id)  # id validation stays loud
        try:
            with open(path, encoding="utf-8") as f:
                return WorkerRecord.from_dict(json.load(f))
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def workers(self, live_only: bool = True) -> list:
        """All announced workers, sorted by slot then id; with
        ``live_only`` (the default) records whose pid is gone are
        skipped — a kill -9'd worker drops out of discovery without
        anyone withdrawing it."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in sorted(names):
            if not name.endswith(_SUFFIX):
                continue
            rec = self.lookup(name[: -len(_SUFFIX)])
            if rec is None:
                continue
            if live_only and not rec.alive():
                continue
            out.append(rec)
        out.sort(key=lambda r: (r.slot, r.worker_id))
        return out

    def wait_for(self, worker_id: str, timeout_s: float = 30.0,
                 poll_s: float = 0.05) -> WorkerRecord:
        """Block until ``worker_id`` announces (spawn rendezvous).
        Raises ``TimeoutError`` with the ids that DID announce, so a
        failed spawn is diagnosable from the exception alone."""
        deadline = time.monotonic() + float(timeout_s)
        while True:
            rec = self.lookup(worker_id)
            if rec is not None and rec.alive():
                return rec
            if time.monotonic() >= deadline:
                present = [r.worker_id for r in self.workers()]
                raise TimeoutError(
                    f"worker {worker_id!r} did not announce within "
                    f"{timeout_s}s (announced: {present})"
                )
            time.sleep(poll_s)
