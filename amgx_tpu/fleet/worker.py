"""FleetWorker: one process, one SolveGateway, one wire endpoint.

The worker wraps a :class:`~amgx_tpu.serve.gateway.SolveGateway`
(admission + batching + placement + sessions — the whole single-
process serving stack, unchanged) and serves the
:mod:`~amgx_tpu.fleet.wire` protocol over an asyncio socket:

* ``submit``   — rebuild the CSR system from the frame's arrays,
  ``await gateway.solve(...)``, reply the solution arrays; ANY
  taxonomy exception replies as a marshalled typed error (an
  ``AdmissionRejected`` shed on this worker is an
  ``AdmissionRejected`` at the client, ``retry_after_s`` intact).
* ``health``   — the gateway's health view plus worker identity and
  the warm-boot evidence (per-entry ``coarsen_calls``/``restored``)
  the rolling-restart gate asserts on.
* ``drain``    — the lossless handoff: ``gateway.drain()`` settles
  every admitted ticket and exports hierarchies + sessions to the
  SHARED ArtifactStore, the report crosses the wire, then the worker
  withdraws from the registry and exits.  Its replacement warm-boots
  from the same store and serves its first repeat fingerprint as a
  cache HIT with zero setups.
* ``metrics``  — the process's full Prometheus text exposition.
* ``session_open`` / ``session_step`` / ``session_close`` — the
  streaming-session face, pinned by client-side affinity to this
  worker.

Failure stance: garbage on a connection (bad magic, truncated
frames, unknown verbs) is answered with a typed error frame where a
reply is still possible and the CONNECTION is dropped — the worker
itself never dies from wire input.  Per-request handling runs in its
own asyncio task, so a slow solve never blocks the read loop or
health probes on the same connection.

Runnable as a module::

    python -m amgx_tpu.fleet.worker --registry /run/fleet \
        --store /var/amgx/store --worker-id w0 --slot 0
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal
import sys
import time
from typing import Optional

import numpy as np

from amgx_tpu.core.errors import AMGXTPUError
from amgx_tpu.fleet import wire
from amgx_tpu.fleet.registry import WorkerRecord, WorkerRegistry

_HEARTBEAT_S = 2.0


def _result_arrays(res) -> dict:
    """A SolveResult's fields as wire arrays (scalars included as
    0-d arrays, so the client rebuilds the dataclass verbatim)."""
    return {
        "x": np.asarray(res.x),
        "iters": np.asarray(res.iters),
        "status": np.asarray(res.status),
        "final_norm": np.asarray(res.final_norm),
        "initial_norm": np.asarray(res.initial_norm),
        "history": np.asarray(res.history),
    }


def _entry_setup_evidence(service) -> dict:
    """Warm-boot evidence aggregated over the hierarchy cache: how
    many coarsening calls each cached entry's AMG setup actually ran,
    and how many entries were restored from the store.  The rolling-
    restart gate asserts a replacement worker's repeat fingerprints
    show ``coarsen_calls == 0`` and ``restored > 0``."""
    total_coarsen = 0
    restored = 0
    entries = 0
    try:
        with service.cache._lock:
            solvers = [
                e.solver for e in service.cache._entries.values()
            ]
    except Exception:  # noqa: BLE001 — evidence, not control flow
        return {"entries": 0, "coarsen_calls": 0, "restored": 0}
    for solver in solvers:
        entries += 1
        # walk the preconditioner chain to the AMG solver (a plain
        # smoother preconditioner has no setup_stats — it contributes
        # zero coarsening by construction)
        node, stats = solver, None
        for _ in range(4):
            if node is None:
                break
            stats = getattr(node, "setup_stats", None)
            if isinstance(stats, dict):
                break
            stats = None
            node = getattr(node, "precond", None)
        if stats is None:
            continue
        total_coarsen += int(stats.get("coarsen_calls", 0) or 0)
        if stats.get("restored"):
            restored += 1
    return {
        "entries": entries,
        "coarsen_calls": total_coarsen,
        "restored": restored,
    }


class FleetWorker:
    """One wire-serving solve process.  Construct, then
    :meth:`run` (blocking; the CLI entry point) or ``await``
    :meth:`serve` inside an existing loop."""

    def __init__(self, worker_id: str, registry_dir: str, *,
                 store=None, host: str = "127.0.0.1", port: int = 0,
                 slot: int = 0, max_inflight: int = 256,
                 placement=None, gateway=None, flush_interval_s: float = 0.005,
                 warm_compile: bool = False, **gateway_kwargs):
        from amgx_tpu.serve.gateway import SolveGateway

        self.worker_id = str(worker_id)
        self.registry = WorkerRegistry(registry_dir)
        self.slot = int(slot)
        self._host = host
        self._port = int(port)
        self._placement_spec = placement
        if gateway is not None:
            self.gateway = gateway
        else:
            svc_kwargs = dict(gateway_kwargs)
            if placement is not None:
                svc_kwargs["placement"] = placement
            self.gateway = SolveGateway(
                store=store, max_inflight=max_inflight, **svc_kwargs
            )
        self._flush_interval_s = float(flush_interval_s)
        self._warm_compile = bool(warm_compile)
        self.warm_booted = 0
        self._server = None
        self._record: Optional[WorkerRecord] = None
        self._shutdown = asyncio.Event()
        self._draining = False
        self._sessions: dict = {}  # session_id -> SolveSession
        self.frames_in = 0
        self.frames_out = 0
        self.wire_errors = 0
        self.started_at = time.time()

    # -- identity ------------------------------------------------------

    def dist_capable(self) -> bool:
        """Whether this worker's placement shards oversized patterns
        (drives the frontend's dist-routing restriction)."""
        pol = self.gateway.service.placement
        return getattr(pol, "telemetry_kind", None) == "dist"

    @property
    def address(self) -> tuple:
        return (self._host, self._port)

    # -- lifecycle -----------------------------------------------------

    async def serve(self):
        """Boot, announce, serve until drained or cancelled."""
        if self.gateway.service.store is not None:
            self.warm_booted = self.gateway.service.warm_boot(
                wait=True, compile=self._warm_compile
            )
        self.gateway.start(self._flush_interval_s)
        self._server = await asyncio.start_server(
            self._handle_conn, self._host, self._port
        )
        self._port = self._server.sockets[0].getsockname()[1]
        self._record = WorkerRecord(
            self.worker_id, self._host, self._port, os.getpid(),
            slot=self.slot, dist_capable=self.dist_capable(),
            extra={"warm_booted": self.warm_booted},
        )
        self.registry.announce(self._record)
        hb = asyncio.ensure_future(self._heartbeat_loop())
        try:
            await self._shutdown.wait()
        finally:
            hb.cancel()
            self._server.close()
            await self._server.wait_closed()
            # connection handlers still parked on reads: cancel them
            # so loop teardown is quiet
            me = asyncio.current_task()
            others = [
                t for t in asyncio.all_tasks() if t is not me
            ]
            for t in others:
                t.cancel()
            await asyncio.gather(*others, return_exceptions=True)
            self.registry.withdraw(self.worker_id)
            if not self._draining:
                # cancelled without drain: stop the flusher anyway
                try:
                    self.gateway.stop()
                except Exception:  # noqa: BLE001
                    pass

    def run(self):
        """Blocking entry point (the spawned subprocess's main)."""
        asyncio.run(self.serve())

    async def _heartbeat_loop(self):
        while True:
            await asyncio.sleep(_HEARTBEAT_S)
            try:
                self.registry.heartbeat(self._record)
            except OSError:
                pass

    # -- connection handling -------------------------------------------

    async def _handle_conn(self, reader, writer):
        wlock = asyncio.Lock()
        tasks = set()
        try:
            while True:
                try:
                    header, arrays = await wire.read_frame_async(reader)
                except wire.WireClosed:
                    return
                except wire.WireError as e:
                    # garbage: answer typed (best effort), drop the
                    # CONNECTION, keep the worker
                    self.wire_errors += 1
                    await self._reply_error(writer, wlock, None, e)
                    return
                self.frames_in += 1
                t = asyncio.ensure_future(
                    self._dispatch(header, arrays, writer, wlock)
                )
                tasks.add(t)
                t.add_done_callback(tasks.discard)
        finally:
            for t in tasks:
                t.cancel()
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _send(self, writer, wlock, header, arrays=None):
        frame = wire.pack_frame(header, arrays)
        async with wlock:
            writer.write(frame)
            await writer.drain()
        self.frames_out += 1

    async def _reply_error(self, writer, wlock, rid, exc):
        try:
            await self._send(writer, wlock, {
                "verb": wire.VERB_RESULT,
                "rid": rid,
                "error": wire.marshal_error(exc),
            })
        except (OSError, wire.WireError):
            pass  # peer gone; nothing to tell it

    async def _dispatch(self, header, arrays, writer, wlock):
        rid = header.get("rid")
        verb = header.get("verb")
        try:
            if verb == wire.VERB_SUBMIT:
                await self._do_submit(header, arrays, writer, wlock)
            elif verb == wire.VERB_HEALTH:
                await self._send(writer, wlock, {
                    "verb": wire.VERB_RESULT, "rid": rid,
                    "health": self._health_view(),
                })
            elif verb == wire.VERB_PING:
                await self._send(writer, wlock, {
                    "verb": wire.VERB_RESULT, "rid": rid, "pong": True,
                })
            elif verb == wire.VERB_METRICS:
                await self._send(writer, wlock, {
                    "verb": wire.VERB_RESULT, "rid": rid,
                    "metrics_text": self._metrics_text(),
                })
            elif verb == wire.VERB_DRAIN:
                await self._do_drain(header, writer, wlock)
            elif verb == wire.VERB_SESSION_OPEN:
                await self._do_session_open(header, arrays, writer, wlock)
            elif verb == wire.VERB_SESSION_STEP:
                await self._do_session_step(header, arrays, writer, wlock)
            elif verb == wire.VERB_SESSION_CLOSE:
                await self._do_session_close(header, writer, wlock)
            else:
                self.wire_errors += 1
                await self._reply_error(
                    writer, wlock, rid,
                    wire.WireError(f"unknown verb {verb!r}"),
                )
        except asyncio.CancelledError:
            raise
        except AMGXTPUError as e:
            await self._reply_error(writer, wlock, rid, e)
        except Exception as e:  # noqa: BLE001 — cross the wire typed
            await self._reply_error(
                writer, wlock, rid,
                AMGXTPUError(f"{type(e).__name__}: {e}"),
            )

    # -- verb handlers -------------------------------------------------

    @staticmethod
    def _csr_from(header, arrays):
        import scipy.sparse as sp

        n = int(header["n"])
        A = sp.csr_matrix(
            (
                arrays["values"],
                arrays["col_indices"],
                arrays["row_offsets"],
            ),
            shape=(n, n),
        )
        fp = header.get("fp")
        if fp:
            # client already fingerprinted this structure; memoize so
            # _host_csr agrees without rehashing (affinity assertions
            # compare client- and worker-side fingerprints)
            A._amgx_tpu_fp = str(fp)
        return A

    async def _do_submit(self, header, arrays, writer, wlock):
        rid = header.get("rid")
        ctx = wire.trace_from_carrier(header.get("trace"))
        from amgx_tpu.telemetry import tracing

        t0 = time.perf_counter()
        A = self._csr_from(header, arrays)
        b = np.asarray(arrays["b"])
        x0 = arrays.get("x0")
        deadline_s = header.get("deadline_s")
        with tracing.use_context(ctx):
            res = await self.gateway.solve(
                A, b, x0,
                tenant=str(header.get("tenant", "default")),
                lane=str(header.get("lane", "interactive")),
                deadline_s=(
                    float(deadline_s) if deadline_s is not None else None
                ),
            )
            if ctx is not None:
                tracing.record_span(
                    "wire_serve", t0, time.perf_counter(), ctx,
                    args={"worker": self.worker_id},
                )
        await self._send(
            writer, wlock,
            {"verb": wire.VERB_RESULT, "rid": rid},
            _result_arrays(res),
        )

    def _health_view(self) -> dict:
        h = self.gateway.health()
        h["worker"] = {
            "worker_id": self.worker_id,
            "pid": os.getpid(),
            "slot": self.slot,
            "dist_capable": self.dist_capable(),
            "warm_booted": self.warm_booted,
            "uptime_s": time.time() - self.started_at,
            "frames_in": self.frames_in,
            "frames_out": self.frames_out,
            "wire_errors": self.wire_errors,
        }
        m = self.gateway.service.metrics
        h["serve"] = {
            k: m.get(k)
            for k in ("setups", "cache_hits", "cache_misses",
                      "compiles", "solves")
        }
        h["setup_evidence"] = _entry_setup_evidence(self.gateway.service)
        return h

    def _metrics_text(self) -> str:
        from amgx_tpu.telemetry import get_registry

        return get_registry().render_prometheus()

    async def _do_drain(self, header, writer, wlock):
        rid = header.get("rid")
        self._draining = True
        timeout_s = float(header.get("timeout_s", 30.0))
        loop = asyncio.get_event_loop()
        report = await loop.run_in_executor(
            None, lambda: self.gateway.drain(timeout_s=timeout_s)
        )
        await self._send(writer, wlock, {
            "verb": wire.VERB_RESULT, "rid": rid, "drain": report,
        })
        self._shutdown.set()

    # -- streaming sessions --------------------------------------------

    async def _do_session_open(self, header, arrays, writer, wlock):
        rid = header.get("rid")
        A = self._csr_from(header, arrays)
        deadline_s = header.get("deadline_s")
        loop = asyncio.get_event_loop()
        sess = await loop.run_in_executor(None, lambda: (
            self.gateway.restore_session(header["session_id"])
            if header.get("restore")
            else self.gateway.open_session(
                A,
                session_id=header.get("session_id"),
                tenant=str(header.get("tenant", "default")),
                lane=str(header.get("lane", "interactive")),
                deadline_s=(
                    float(deadline_s) if deadline_s is not None else None
                ),
            )
        ))
        self._sessions[sess.session_id] = sess
        await self._send(writer, wlock, {
            "verb": wire.VERB_RESULT, "rid": rid,
            "session_id": sess.session_id,
        })

    def _session(self, header):
        sid = str(header.get("session_id"))
        sess = self._sessions.get(sid)
        if sess is None:
            raise AMGXTPUError(f"unknown session {sid!r}")
        return sess

    async def _do_session_step(self, header, arrays, writer, wlock):
        rid = header.get("rid")
        sess = self._session(header)
        loop = asyncio.get_event_loop()
        values = arrays.get("values")
        ticket = sess.step(values, arrays["b"])
        self.gateway.flush()
        res = await loop.run_in_executor(None, ticket.result)
        await self._send(
            writer, wlock,
            {"verb": wire.VERB_RESULT, "rid": rid},
            _result_arrays(res),
        )

    async def _do_session_close(self, header, writer, wlock):
        rid = header.get("rid")
        sess = self._sessions.pop(str(header.get("session_id")), None)
        saved = False
        if sess is not None:
            loop = asyncio.get_event_loop()
            try:
                await loop.run_in_executor(None, sess.save)
                saved = True
            except Exception:  # noqa: BLE001 — close is best-effort
                saved = False
        await self._send(writer, wlock, {
            "verb": wire.VERB_RESULT, "rid": rid, "saved": saved,
        })


# ----------------------------------------------------------------------
# CLI


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="amgx_tpu fleet worker: serve one SolveGateway "
        "over the fleet wire protocol"
    )
    p.add_argument("--registry", required=True,
                   help="worker-registry directory (shared)")
    p.add_argument("--worker-id", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 binds an ephemeral port (announced in the "
                   "registry)")
    p.add_argument("--store", default=None,
                   help="shared ArtifactStore directory (warm-boot + "
                   "drain export)")
    p.add_argument("--slot", type=int, default=0)
    p.add_argument("--max-inflight", type=int, default=256)
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--warm-compile", action="store_true")
    args = p.parse_args(argv)

    import amgx_tpu

    amgx_tpu.initialize()

    store = None
    if args.store:
        from amgx_tpu.store import ArtifactStore

        store = ArtifactStore(args.store)

    worker = FleetWorker(
        args.worker_id, args.registry, store=store, host=args.host,
        port=args.port, slot=args.slot, max_inflight=args.max_inflight,
        max_batch=args.max_batch, warm_compile=args.warm_compile,
    )

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, worker._shutdown.set)
        except (NotImplementedError, RuntimeError):
            pass
    try:
        loop.run_until_complete(worker.serve())
    finally:
        loop.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
