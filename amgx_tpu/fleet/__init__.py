"""amgx_tpu.fleet — a multi-process solve fleet over RPC.

One process per TPU slice, each wrapping the full single-process
serving stack (:class:`~amgx_tpu.serve.gateway.SolveGateway`), wired
together by a stdlib-only length-prefixed wire protocol
(:mod:`~amgx_tpu.fleet.wire`), discovered through a file-based
registry (:mod:`~amgx_tpu.fleet.registry`), and fronted by a client
that routes on fingerprint affinity ACROSS processes with per-worker
circuit breakers (:mod:`~amgx_tpu.fleet.frontend` /
:mod:`~amgx_tpu.fleet.router`).  Rolling restarts drain through the
shared :class:`~amgx_tpu.store.store.ArtifactStore` so a replacement
worker's first repeat fingerprint is a cache HIT
(:mod:`~amgx_tpu.fleet.lifecycle`).

Heavy imports (jax, the serve stack) stay inside the modules that
need them — importing this package costs nothing, so the C API can
probe ``AMGX_TPU_FLEET`` cheaply.
"""

from amgx_tpu.fleet.wire import (  # noqa: F401
    WireClosed,
    WireError,
    marshal_error,
    pack_frame,
    read_frame,
    read_frame_async,
    unmarshal_error,
)
from amgx_tpu.fleet.registry import (  # noqa: F401
    WorkerRecord,
    WorkerRegistry,
)
from amgx_tpu.fleet.router import FleetRouter  # noqa: F401

__all__ = [
    "WireClosed", "WireError", "marshal_error", "pack_frame",
    "read_frame", "read_frame_async", "unmarshal_error",
    "WorkerRecord", "WorkerRegistry", "FleetRouter",
    "FleetFrontend", "FleetTicket", "FleetWorker",
    "FleetSupervisor", "launch_fleet",
]


def __getattr__(name):
    # lazy: frontend/worker/lifecycle pull in the serve stack
    if name in ("FleetFrontend", "FleetTicket"):
        from amgx_tpu.fleet import frontend

        return getattr(frontend, name)
    if name == "FleetWorker":
        from amgx_tpu.fleet.worker import FleetWorker

        return FleetWorker
    if name in ("FleetSupervisor", "launch_fleet"):
        from amgx_tpu.fleet import lifecycle

        return getattr(lifecycle, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
