"""FleetFrontend: the client face of the multi-process fleet.

One frontend holds one socket per attached worker, routes every
submit through the :class:`~amgx_tpu.fleet.router.FleetRouter`
(fingerprint affinity across PROCESSES — a repeat structure lands on
the worker whose hierarchy/compile caches are already warm), and
settles tickets from a per-connection reader thread that demuxes
replies by request id.

Failure semantics, tiered exactly like the in-process stack:

* A worker replies a TYPED error (an ``AdmissionRejected`` shed, a
  breaker-gated fingerprint, a deadline miss) — the worker is FINE:
  the slot's load releases normally and
  :meth:`FleetTicket.result` applies the
  :class:`~amgx_tpu.serve.retry.RetryPolicy` — retryable taxonomy
  members back off (honoring the shed's ``retry_after_s`` hint
  verbatim, since it round-tripped the wire) and re-submit through
  routing; everything else raises typed immediately.
* The CONNECTION dies (kill -9, mid-frame disconnect) — the slot's
  breaker trips (a dead process is a lost device one tier up), its
  warm set is forgotten, and every in-flight ticket on that socket is
  REQUEUED to a healthy worker exactly once; a second loss settles
  the ticket with a typed
  :class:`~amgx_tpu.core.errors.DeviceLostError`.  No ticket is ever
  silently lost.

The frontend is sync/threaded (not asyncio): its callers are the
C API batch face and benchmark closed loops, both thread-shaped.
"""

from __future__ import annotations

import itertools
import socket as socketlib
import threading
import time
import uuid
from typing import Optional

import numpy as np

from amgx_tpu.core.errors import (
    AMGXTPUError,
    DeviceLostError,
    Overloaded,
)
from amgx_tpu.core.profiling import LatencyReservoir
from amgx_tpu.fleet import wire
from amgx_tpu.fleet.registry import WorkerRegistry
from amgx_tpu.fleet.router import FleetRouter
from amgx_tpu.serve.retry import RetryPolicy


class _WorkerConn:
    """One attached worker: socket, reader thread, pending map."""

    def __init__(self, slot: int, worker_id: str, address,
                 dist_capable: bool, on_lost, on_reply,
                 connect_timeout_s: float):
        self.slot = int(slot)
        self.worker_id = str(worker_id)
        self.address = tuple(address)
        self.dist_capable = bool(dist_capable)
        self._on_lost = on_lost
        self._on_reply = on_reply
        self.sock = socketlib.create_connection(
            self.address, timeout=connect_timeout_s
        )
        self.sock.settimeout(None)
        self.rfile = self.sock.makefile("rb")
        self.wlock = threading.Lock()
        self.plock = threading.Lock()
        self.pending: dict = {}  # rid -> _Pending
        self.alive = True
        self.orderly = False  # set before an intentional close
        self._reader = threading.Thread(
            target=self._read_loop,
            name=f"fleet-read-{worker_id}",
            daemon=True,
        )
        self._reader.start()

    def send(self, frame: bytes) -> None:
        with self.wlock:
            self.sock.sendall(frame)

    def add_pending(self, rid: str, pending) -> None:
        with self.plock:
            self.pending[rid] = pending

    def pop_pending(self, rid):
        with self.plock:
            return self.pending.pop(rid, None)

    def drain_pending(self) -> list:
        with self.plock:
            out = list(self.pending.values())
            self.pending.clear()
            return out

    def close(self, orderly: bool = True) -> None:
        self.orderly = self.orderly or orderly
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass

    def _read_loop(self):
        err = None
        try:
            while True:
                header, arrays = wire.read_frame(self.rfile)
                rid = header.get("rid")
                pending = self.pop_pending(rid)
                if pending is not None:
                    self._on_reply(pending, header)
                    pending.settle_reply(header, arrays)
        except wire.WireClosed:
            pass
        except (wire.WireError, OSError, ValueError) as e:
            err = e
        finally:
            self.alive = False
            self._on_lost(self, err)


class _Pending:
    """One in-flight request: the resendable frame parts, the future
    its ticket waits on, and the requeue state."""

    __slots__ = (
        "header", "arrays", "fp", "n_rows", "slot", "rid",
        "requeued", "routed", "t_sent", "_outcome", "_event",
    )

    def __init__(self, header: dict, arrays: dict, fp, n_rows: int):
        self.header = header
        self.arrays = arrays
        self.fp = fp
        self.n_rows = int(n_rows)
        self.slot = -1
        self.rid = None
        self.requeued = False
        self.routed = False
        self.t_sent = 0.0
        self._outcome = None
        self._event = threading.Event()

    def settle_reply(self, header, arrays):
        self._outcome = ("reply", header, arrays)
        self._event.set()

    def settle_error(self, exc: BaseException):
        self._outcome = ("raise", exc, None)
        self._event.set()

    def rearm(self):
        self._outcome = None
        self._event.clear()

    def wait(self, timeout: Optional[float]):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"fleet request {self.rid!r} still in flight after "
                f"{timeout}s"
            )
        return self._outcome


def _rebuild_result(header, arrays):
    from amgx_tpu.solvers.base import SolveResult

    return SolveResult(
        x=arrays["x"],
        iters=arrays["iters"],
        status=arrays["status"],
        final_norm=arrays["final_norm"],
        initial_norm=arrays["initial_norm"],
        history=arrays["history"],
    )


class FleetTicket:
    """Settlement handle for one fleet submit — the wire twin of the
    gateway's GatewayTicket.  ``result()`` blocks for the reply and
    applies the frontend's RetryPolicy to retryable typed errors
    (sheds re-enter routing after the hinted backoff; the policy's
    ``max_attempts`` bounds the loop)."""

    def __init__(self, frontend: "FleetFrontend", pending: _Pending):
        self._frontend = frontend
        self._pending = pending
        self._done: Optional[tuple] = None

    def done(self) -> bool:
        return self._done is not None or self._pending._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if self._done is not None:
            kind, val = self._done
            if kind == "ok":
                return val
            raise val
        policy = self._frontend.retry_policy
        attempt = 0
        while True:
            outcome = self._pending.wait(timeout)
            kind, a, b = outcome
            if kind == "reply":
                header, arrays = a, b
                err = header.get("error")
                if err is None:
                    res = _rebuild_result(header, arrays)
                    self._done = ("ok", res)
                    return res
                exc = wire.unmarshal_error(err)
            else:
                exc = a
            if (
                isinstance(exc, policy.retryable)
                and attempt + 1 < policy.max_attempts
            ):
                attempt += 1
                policy.retries += 1
                self._frontend._count("retries")
                policy.sleep(policy.backoff_s(
                    attempt, getattr(exc, "retry_after_s", None)
                ))
                try:
                    self._frontend._resubmit(self._pending)
                except AMGXTPUError as resubmit_exc:
                    exc = resubmit_exc
                else:
                    continue
            if isinstance(exc, policy.retryable):
                policy.giveups += 1
            self._frontend._count("typed_errors")
            self._done = ("err", exc)
            raise exc


class FleetFrontend:
    """Routes submits across attached fleet workers.

    ``workers`` may be a :class:`~amgx_tpu.fleet.registry.
    WorkerRegistry` / registry directory (every live announced worker
    attaches) or an explicit iterable of records.  Telemetry
    registers as kind ``"fleet"`` (``amgx_fleet_*`` families).
    """

    def __init__(self, workers=None, *, capacity: int = 16,
                 retry_policy: Optional[RetryPolicy] = None,
                 dist_rows: Optional[int] = None,
                 trip_threshold: int = 1,
                 probe_every: Optional[int] = None,
                 connect_timeout_s: float = 10.0,
                 register_telemetry: bool = True):
        self.router = FleetRouter(
            capacity=capacity, dist_rows=dist_rows,
            trip_threshold=trip_threshold, probe_every=probe_every,
        )
        self.retry_policy = retry_policy or RetryPolicy()
        self.connect_timeout_s = float(connect_timeout_s)
        self._lock = threading.Lock()
        self._conns: dict = {}  # slot -> _WorkerConn
        self._rid_counter = itertools.count(1)
        self._rid_prefix = uuid.uuid4().hex[:8]
        self._counters = {
            "submitted": 0, "completed": 0, "typed_errors": 0,
            "retries": 0, "requeued": 0, "requeue_failures": 0,
            "conn_losses": 0,
        }
        self.wire_latency = LatencyReservoir()
        self.telemetry_name = None
        if register_telemetry:
            from amgx_tpu.telemetry import get_registry

            self.telemetry_name = get_registry().register("fleet", self)
        if workers is not None:
            if isinstance(workers, (str, WorkerRegistry)):
                self.attach_registry(workers)
            else:
                for rec in workers:
                    self.attach(rec)

    # -- membership ----------------------------------------------------

    def attach(self, record) -> int:
        """Attach an announced worker (a WorkerRecord): connect, add
        its slot to routing.  Returns the slot."""
        conn = _WorkerConn(
            record.slot, record.worker_id, record.address,
            record.dist_capable, self._conn_lost, self._on_reply,
            self.connect_timeout_s,
        )
        with self._lock:
            old = self._conns.get(conn.slot)
            self._conns[conn.slot] = conn
        if old is not None:
            old.close(orderly=True)
        self.router.add_worker(conn.slot, conn.dist_capable)
        return conn.slot

    def attach_registry(self, registry) -> list:
        reg = (
            registry if isinstance(registry, WorkerRegistry)
            else WorkerRegistry(registry)
        )
        return [self.attach(rec) for rec in reg.workers()]

    def detach(self, slot: int, close: bool = True) -> None:
        """Orderly removal: stop routing to the slot and drop its
        connection (no breaker trip)."""
        self.router.remove_worker(slot)
        with self._lock:
            conn = self._conns.pop(slot, None)
        if conn is not None and close:
            conn.close(orderly=True)

    def quiesce(self, slot: int) -> None:
        """Stop ROUTING to a slot but keep its connection — the
        rolling-restart window between "no new work" and "drain"."""
        self.router.remove_worker(slot)

    def attached_slots(self) -> list:
        with self._lock:
            return sorted(self._conns)

    # -- internals -----------------------------------------------------

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def _next_rid(self) -> str:
        return f"{self._rid_prefix}-{next(self._rid_counter)}"

    def _conn_for(self, slot: int):
        with self._lock:
            conn = self._conns.get(slot)
        if conn is None or not conn.alive:
            raise DeviceLostError(
                f"fleet slot {slot} has no live connection",
                device_label=f"worker:{slot}",
            )
        return conn

    def _send_pending(self, pending: _Pending, slot: int) -> None:
        conn = self._conn_for(slot)
        rid = self._next_rid()
        pending.rid = rid
        pending.slot = slot
        pending.t_sent = time.perf_counter()
        header = dict(pending.header)
        header["rid"] = rid
        frame = wire.pack_frame(header, pending.arrays)
        conn.add_pending(rid, pending)
        try:
            conn.send(frame)
        except OSError as e:
            conn.pop_pending(rid)
            raise DeviceLostError(
                f"send to worker slot {slot} failed: {e}",
                device_label=f"worker:{conn.worker_id}",
            ) from None

    def _on_reply(self, pending: _Pending, header: dict) -> None:
        """Reader-thread settlement: ANY reply — success or typed
        error — means the worker served; release its routing load,
        charge wire time, reset its breaker."""
        wire_s = time.perf_counter() - pending.t_sent
        if pending.routed:
            pending.routed = False
            self.router.settle(pending.slot, wire_s)
        self.wire_latency.add(wire_s)
        if (
            header.get("error") is None
            and pending.header.get("verb") == wire.VERB_SUBMIT
        ):
            self._count("completed")

    def _route_and_send(self, pending: _Pending) -> None:
        if not self.router.active_slots():
            raise Overloaded(
                "no fleet workers attached", retry_after_s=1.0,
                reason="no_workers",
            )
        slot, _warm = self.router.route(pending.fp, pending.n_rows)
        pending.routed = True
        try:
            self._send_pending(pending, slot)
        except DeviceLostError:
            pending.routed = False
            self.router.release(slot)
            self.router.failure(slot)
            raise

    def _resubmit(self, pending: _Pending) -> None:
        """Re-enter routing for a retryable typed error (the slot
        already settled — its load released when the reply landed)."""
        pending.rearm()
        self._route_and_send(pending)

    # -- connection-loss path ------------------------------------------

    def _conn_lost(self, conn: _WorkerConn, err) -> None:
        """Reader thread exit.  For an UNPLANNED loss: trip the
        slot's breaker, then requeue each in-flight request exactly
        once; a request already requeued settles typed."""
        with self._lock:
            current = self._conns.get(conn.slot) is conn
        stranded = conn.drain_pending()
        if conn.orderly and not stranded:
            return
        if current and not conn.orderly:
            self._count("conn_losses")
            self.router.failure(conn.slot)
            with self._lock:
                self._conns.pop(conn.slot, None)
            self.router.remove_worker(conn.slot)
        lost = DeviceLostError(
            f"fleet worker {conn.worker_id!r} (slot {conn.slot}) "
            f"connection lost" + (f": {err}" if err else ""),
            device_label=f"worker:{conn.worker_id}",
        )
        for pending in stranded:
            if pending.routed:
                pending.routed = False
                self.router.release(pending.slot)
            if pending.requeued:
                self._count("requeue_failures")
                pending.settle_error(lost)
                continue
            pending.requeued = True
            try:
                self._route_and_send(pending)
                self._count("requeued")
            except AMGXTPUError as e:
                pending.settle_error(e)

    # -- submission ----------------------------------------------------

    def submit(self, A, b, x0=None, *, tenant: str = "default",
               lane: str = "interactive",
               deadline_s: Optional[float] = None) -> FleetTicket:
        """Route one system to a fleet worker; returns a
        :class:`FleetTicket`.  Raises typed ``Overloaded`` when no
        workers are attached."""
        from amgx_tpu.serve.service import _host_csr

        row_offsets, col_indices, values, n, fp = _host_csr(A)
        header = {
            "verb": wire.VERB_SUBMIT,
            "tenant": str(tenant),
            "lane": str(lane),
            "deadline_s": deadline_s,
            "n": int(n),
            "fp": fp,
        }
        trace = wire.trace_carrier()
        if trace is not None:
            header["trace"] = trace
        arrays = {
            "row_offsets": np.asarray(row_offsets),
            "col_indices": np.asarray(col_indices),
            "values": np.asarray(values),
            "b": np.asarray(b),
        }
        if x0 is not None:
            arrays["x0"] = np.asarray(x0)
        pending = _Pending(header, arrays, fp, n)
        self._route_and_send(pending)
        self._count("submitted")
        return FleetTicket(self, pending)

    def solve(self, A, b, x0=None, *, tenant: str = "default",
              lane: str = "interactive",
              deadline_s: Optional[float] = None,
              timeout: Optional[float] = None):
        """Submit and wait — the one-call face."""
        return self.submit(
            A, b, x0, tenant=tenant, lane=lane, deadline_s=deadline_s
        ).result(timeout)

    def flush(self) -> None:
        """Face-compat no-op (workers flush on their own cadence)."""

    # -- control-plane verbs -------------------------------------------

    def _call(self, slot: int, header: dict, arrays=None,
              timeout: Optional[float] = 30.0) -> tuple:
        pending = _Pending(header, arrays or {}, None, 0)
        pending.requeued = True  # control verbs never re-route
        self._send_pending(pending, slot)
        kind, a, b = pending.wait(timeout)
        if kind == "raise":
            raise a
        err = a.get("error")
        if err is not None:
            raise wire.unmarshal_error(err)
        return a, b

    def health(self, slot: int, timeout: Optional[float] = 30.0) -> dict:
        header, _ = self._call(
            slot, {"verb": wire.VERB_HEALTH}, timeout=timeout
        )
        return header["health"]

    def ping(self, slot: int, timeout: Optional[float] = 10.0) -> bool:
        header, _ = self._call(
            slot, {"verb": wire.VERB_PING}, timeout=timeout
        )
        return bool(header.get("pong"))

    def metrics_text(self, slot: int,
                     timeout: Optional[float] = 30.0) -> str:
        header, _ = self._call(
            slot, {"verb": wire.VERB_METRICS}, timeout=timeout
        )
        return str(header.get("metrics_text", ""))

    def drain_worker(self, slot: int,
                     timeout: Optional[float] = 60.0) -> dict:
        """Drain a worker over the wire (it settles every admitted
        ticket, exports hierarchies + sessions to the shared store,
        replies its drain report and exits)."""
        with self._lock:
            conn = self._conns.get(slot)
        if conn is not None:
            conn.orderly = True  # its exit is planned, not a failure
        header, _ = self._call(
            slot,
            {"verb": wire.VERB_DRAIN, "timeout_s": timeout},
            timeout=(timeout or 0) + 30.0,
        )
        return header["drain"]

    # -- telemetry -----------------------------------------------------

    def telemetry_snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
        snap = {
            "counters": counters,
            "routing": self.router.snapshot(),
            "retry": {
                "retries": self.retry_policy.retries,
                "giveups": self.retry_policy.giveups,
            },
            "wire_latency": self.wire_latency.summary(),
        }
        return snap

    def close(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            conn.close(orderly=True)
