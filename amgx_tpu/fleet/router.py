"""Cross-process affinity routing: the AffinityRouter lifted one tier.

Inside one process, :class:`~amgx_tpu.serve.placement.router.
AffinityRouter` routes flushed groups to the CHIP whose caches hold
their fingerprint.  The fleet reuses the same host-pure state machine
one level up: slots are WORKER PROCESSES, warmth is a worker's
hierarchy/compile caches (and its warm-booted ArtifactStore state),
and the :class:`~amgx_tpu.serve.placement.health.DeviceHealthBoard`
becomes the per-worker breaker — a dead process is a lost device one
tier up, with the identical trip → half-open probe → close chain and
the same ``AMGX_TPU_BREAKER_PROBE_EVERY`` cadence knob.

The one fleet-specific decision layered on top: OVERSIZED patterns
(``n_rows`` at or above the distributed row threshold —
``AMGX_TPU_DIST_ROWS``, the same knob
:class:`~amgx_tpu.serve.placement.distributed.DistributedPlacement`
keys on) are restricted to workers that announced
``dist_capable=True``, so a pattern too big for one chip lands on the
worker that shards rows across its devices instead of a worker that
would fail the single-device setup.
"""

from __future__ import annotations

import threading
from typing import Optional

from amgx_tpu.serve.placement.health import DeviceHealthBoard
from amgx_tpu.serve.placement.router import AffinityRouter


def dist_row_threshold(value: Optional[int] = None) -> int:
    """Row count at which routing prefers a distributed-capable
    worker — ``AMGX_TPU_DIST_ROWS``, read through the same helper the
    DistributedPlacement eligibility check uses."""
    if value is not None:
        return int(value)
    import os

    from amgx_tpu.serve.placement.distributed import (
        DEFAULT_ROW_THRESHOLD,
        ENV_ROW_THRESHOLD,
    )

    try:
        return int(
            os.environ.get(ENV_ROW_THRESHOLD, str(DEFAULT_ROW_THRESHOLD))
        )
    except ValueError:
        return DEFAULT_ROW_THRESHOLD


class FleetRouter:
    """Routing + health for a bounded pool of worker slots.

    Slots (0..capacity-1) are stable identities across restarts: a
    replacement worker attaches at its predecessor's slot and inherits
    its breaker (the half-open probe against the NEW process is what
    closes it — the probe that proves the replacement serves).  The
    router is pure host state; the frontend owns sockets.
    """

    def __init__(self, capacity: int = 16, dist_rows: Optional[int] = None,
                 trip_threshold: int = 1, probe_every: Optional[int] = None):
        if capacity < 1:
            raise ValueError("FleetRouter needs capacity >= 1")
        self.capacity = int(capacity)
        self.router = AffinityRouter(self.capacity)
        self.board = DeviceHealthBoard(
            self.capacity, trip_threshold=trip_threshold,
            probe_every=probe_every,
        )
        self.dist_rows = dist_row_threshold(dist_rows)
        self._lock = threading.Lock()
        self._active: set = set()       # attached slots
        self._dist: set = set()         # dist-capable subset
        self.dist_routed = 0
        self.fallbacks = 0              # routed with every pool slot tripped

    # -- membership ----------------------------------------------------

    def add_worker(self, slot: int, dist_capable: bool = False) -> None:
        if not 0 <= slot < self.capacity:
            raise ValueError(
                f"slot {slot} outside router capacity {self.capacity}"
            )
        with self._lock:
            self._active.add(slot)
            if dist_capable:
                self._dist.add(slot)
            else:
                self._dist.discard(slot)

    def remove_worker(self, slot: int) -> None:
        """Detach a slot (orderly restart): its warm set is forgotten
        — the REPLACEMENT re-warms from the shared store — but its
        breaker state is left alone (an orderly drain is not a
        failure)."""
        with self._lock:
            self._active.discard(slot)
            self._dist.discard(slot)
        self.router.forget_device(slot)

    def active_slots(self) -> list:
        with self._lock:
            return sorted(self._active)

    # -- routing -------------------------------------------------------

    def _pool(self, n_rows: Optional[int]) -> set:
        with self._lock:
            pool = set(self._active)
            if (
                n_rows is not None
                and n_rows >= self.dist_rows
                and self._dist & pool
            ):
                pool = self._dist & pool
                self.dist_routed += 1
            return pool

    def route(self, fingerprint, n_rows: Optional[int] = None) -> tuple:
        """(slot, was_warm) for one request; reserves one load unit
        until :meth:`settle`/:meth:`release`.

        The degrade chain is the in-process one
        (AffinityPlacement._route_healthy) verbatim, over worker
        breakers: a tripped slot whose probe is due takes the request
        as its half-open probe; otherwise route among healthy pool
        slots; with the whole pool tripped, route anyway (counted
        ``fallbacks`` — the fleet must keep serving, and the request
        doubles as a probe)."""
        pool = self._pool(n_rows)
        if not pool:
            raise RuntimeError("no workers attached")
        tripped = [
            i for i in self.board.tripped_indices() if i in pool
        ]
        for i in tripped:
            if self.board.probe_due(i):
                return self.router.route_to(fingerprint, i)
        healthy = pool - set(tripped)
        if healthy:
            return self.router.route(fingerprint, allowed=healthy)
        with self._lock:
            self.fallbacks += 1
        return self.router.route(fingerprint, allowed=pool)

    def peek(self, fingerprint) -> Optional[int]:
        return self.router.peek(fingerprint)

    # -- settlement / health -------------------------------------------

    def settle(self, slot: int, wire_s: float) -> None:
        """Request completed (success OR typed application error —
        the worker is fine either way): release load, charge wire
        time, close/reset the slot's breaker."""
        self.router.settle(slot, wire_s)
        self.board.ok(slot)

    def release(self, slot: int) -> None:
        self.router.release(slot)

    def failure(self, slot: int) -> bool:
        """A worker-attributed failure (connection loss, mid-frame
        disconnect): trip the breaker and forget the slot's warm set —
        its process state is gone.  True when this call tripped."""
        tripped = self.board.failure(slot)
        self.router.forget_device(slot)
        return tripped

    def snapshot(self) -> dict:
        r = self.router.snapshot()
        with self._lock:
            r.update({
                "active": sorted(self._active),
                "dist_capable": sorted(self._dist),
                "dist_routed": self.dist_routed,
                "fallbacks": self.fallbacks,
                "dist_rows": self.dist_rows,
            })
        r["health"] = self.board.snapshot()
        return r
