"""The fleet wire protocol: length-prefixed JSON + binary frames.

One frame carries one request or one reply between a
:class:`~amgx_tpu.fleet.frontend.FleetFrontend` and a
:class:`~amgx_tpu.fleet.worker.FleetWorker`:

    +-------+---------+------------+-----------+------------------+
    | magic | version | header len | blob len  | header ... blob  |
    | AMGW  |   u8    |    u32     |    u64    | JSON      bytes  |
    +-------+---------+------------+-----------+------------------+

The JSON header holds the verb, the request id (the multiplexing key
— replies carry the id of the request they answer), per-request
deadlines, tenant/lane, trace context, and an ``arrays`` manifest
``[{name, dtype, shape, nbytes}, ...]`` describing the C-contiguous
numpy buffers concatenated into the blob.  Everything is stdlib +
numpy — no serialization dependency crosses the wire.

Failure stance (mirrors the PR 4 corrupt-artifact contract): garbage
on the wire is a **typed, counted** condition, never a hang or an
unhandled traceback.  Oversize prefixes, short reads, truncated
blobs, bad magic and malformed JSON all raise :class:`WireError`
(an :class:`~amgx_tpu.core.errors.AMGXTPUError`, RC_IO_ERROR); a
clean EOF at a frame boundary raises :class:`WireClosed` so callers
can tell "peer went away" from "peer sent garbage".

Typed error marshalling: :func:`marshal_error` /
:func:`unmarshal_error` round-trip the full ``core/errors.py``
taxonomy — an ``AdmissionRejected`` raised on a worker is an
``AdmissionRejected`` at the client, ``retry_after_s`` and ``reason``
intact, so ``serve/retry.py`` policies work unchanged across
processes.  Unknown exception types degrade to the base
:class:`~amgx_tpu.core.errors.AMGXTPUError` carrying the original
RC code and message.
"""

from __future__ import annotations

import asyncio
import json
import os
import struct
from typing import Optional

import numpy as np

from amgx_tpu.core.errors import (
    AMGXTPUError,
    AdmissionRejected,
    DeadlineExceededError,
    DeviceLostError,
    NonFiniteValuesError,
    Overloaded,
    PatternDegeneracyError,
    RC_IO_ERROR,
    RC_UNKNOWN,
    ResourceError,
    SetupError,
    SingularDiagonalError,
    SolveBreakdown,
    StoreError,
    rc_for_exception,
)

MAGIC = b"AMGW"
VERSION = 1
# magic, version, pad(3), header_len u32, blob_len u64
_PREFIX = struct.Struct("!4sB3xIQ")
PREFIX_LEN = _PREFIX.size

ENV_MAX_FRAME = "AMGX_TPU_FLEET_MAX_FRAME_MB"
MAX_HEADER_BYTES = 8 << 20


def max_blob_bytes() -> int:
    """Upper bound on one frame's binary payload (default 1 GiB,
    ``AMGX_TPU_FLEET_MAX_FRAME_MB`` overrides).  A length prefix past
    it is GARBAGE, refused typed before any allocation — a corrupt
    u64 must not become a 16-exabyte read()."""
    raw = os.environ.get(ENV_MAX_FRAME, "")
    try:
        mb = int(raw) if raw else 1024
    except ValueError:
        mb = 1024
    return max(mb, 1) << 20


# ----------------------------------------------------------------------
# verbs

VERB_SUBMIT = "submit"
VERB_RESULT = "result"  # reply verb for submit / session_step
VERB_HEALTH = "health"
VERB_DRAIN = "drain"
VERB_METRICS = "metrics"
VERB_PING = "ping"
VERB_SESSION_OPEN = "session_open"
VERB_SESSION_STEP = "session_step"
VERB_SESSION_CLOSE = "session_close"

REQUEST_VERBS = frozenset({
    VERB_SUBMIT, VERB_HEALTH, VERB_DRAIN, VERB_METRICS, VERB_PING,
    VERB_SESSION_OPEN, VERB_SESSION_STEP, VERB_SESSION_CLOSE,
})


# ----------------------------------------------------------------------
# typed wire failures


class WireError(AMGXTPUError):
    """Garbage on the wire: bad magic/version, oversize length
    prefix, truncated frame, malformed header, blob/manifest
    mismatch.  Typed (RC_IO_ERROR) so it settles tickets and crosses
    the C API boundary like every other taxonomy member."""

    rc = RC_IO_ERROR


class WireClosed(WireError):
    """The peer closed the connection at a clean frame boundary —
    orderly shutdown, not corruption.  Distinct class so accept loops
    can exit quietly while mid-frame disconnects stay loud."""


# ----------------------------------------------------------------------
# framing


def pack_frame(header: dict, arrays: Optional[dict] = None) -> bytes:
    """Serialize one frame.  ``arrays`` ({name: ndarray}) are made
    C-contiguous, described in the header's ``arrays`` manifest (in
    iteration order) and concatenated into the blob."""
    header = dict(header)
    blobs = []
    manifest = []
    for name, arr in (arrays or {}).items():
        a = np.asarray(arr)
        if not a.flags.c_contiguous:
            # (ascontiguousarray also promotes 0-d to 1-d, so only
            # copy when actually needed)
            a = np.ascontiguousarray(a)
        manifest.append({
            "name": str(name),
            "dtype": a.dtype.str,
            "shape": list(a.shape),
            "nbytes": int(a.nbytes),
        })
        blobs.append(a.tobytes())  # snapshot: caller may reuse buffers
    header["arrays"] = manifest
    hb = json.dumps(header, separators=(",", ":"),
                    allow_nan=True).encode("utf-8")
    if len(hb) > MAX_HEADER_BYTES:
        raise WireError(
            f"frame header {len(hb)} bytes exceeds the "
            f"{MAX_HEADER_BYTES}-byte bound"
        )
    blob = b"".join(blobs)
    if len(blob) > max_blob_bytes():
        raise WireError(
            f"frame blob {len(blob)} bytes exceeds the "
            f"{max_blob_bytes()}-byte bound "
            f"({ENV_MAX_FRAME} raises it)"
        )
    return _PREFIX.pack(MAGIC, VERSION, len(hb), len(blob)) + hb + blob


def _decode(prefix: bytes, hb: bytes, blob: bytes) -> tuple:
    magic, version, hlen, blen = _PREFIX.unpack(prefix)
    try:
        header = json.loads(hb.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"malformed frame header: {e}") from None
    if not isinstance(header, dict):
        raise WireError("frame header must be a JSON object")
    arrays = {}
    off = 0
    manifest = header.pop("arrays", [])
    if not isinstance(manifest, list):
        raise WireError("frame manifest must be a list")
    for ent in manifest:
        try:
            name = ent["name"]
            dt = np.dtype(ent["dtype"])
            shape = tuple(int(s) for s in ent["shape"])
            nbytes = int(ent["nbytes"])
        except (KeyError, TypeError, ValueError) as e:
            raise WireError(f"malformed array manifest: {e}") from None
        if nbytes < 0 or off + nbytes > len(blob):
            raise WireError(
                "array manifest overruns the frame blob"
            )
        try:
            count = nbytes // dt.itemsize if dt.itemsize else 0
            arrays[name] = np.frombuffer(
                blob, dtype=dt, count=count, offset=off,
            ).reshape(shape).copy()
        except ValueError as e:
            raise WireError(f"array decode failed: {e}") from None
        off += nbytes
    if off != len(blob):
        raise WireError(
            f"frame blob has {len(blob) - off} undeclared bytes"
        )
    return header, arrays


def _check_prefix(prefix: bytes) -> tuple:
    """Validate a 20-byte prefix BEFORE reading body bytes: bad magic
    or an oversize length is refused without allocating for it."""
    magic, version, hlen, blen = _PREFIX.unpack(prefix)
    if magic != MAGIC:
        raise WireError(f"bad frame magic {magic!r}")
    if version != VERSION:
        raise WireError(f"unsupported wire version {version}")
    if hlen > MAX_HEADER_BYTES:
        raise WireError(
            f"oversize header length prefix ({hlen} bytes)"
        )
    if blen > max_blob_bytes():
        raise WireError(
            f"oversize blob length prefix ({blen} bytes)"
        )
    return hlen, blen


async def read_frame_async(reader: asyncio.StreamReader) -> tuple:
    """Read one frame from an asyncio stream.  Clean EOF before any
    prefix byte raises :class:`WireClosed`; everything else short or
    malformed raises :class:`WireError`."""
    try:
        prefix = await reader.readexactly(PREFIX_LEN)
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            raise WireClosed("peer closed the wire") from None
        raise WireError(
            f"truncated frame prefix ({len(e.partial)} of "
            f"{PREFIX_LEN} bytes)"
        ) from None
    hlen, blen = _check_prefix(prefix)
    try:
        hb = await reader.readexactly(hlen)
        blob = await reader.readexactly(blen) if blen else b""
    except asyncio.IncompleteReadError as e:
        raise WireError(
            f"mid-frame disconnect ({len(e.partial)} bytes short)"
        ) from None
    return _decode(prefix, hb, blob)


def read_frame(fileobj) -> tuple:
    """Blocking twin of :func:`read_frame_async` over a file-like
    object (``socket.makefile('rb')``) — the synchronous client
    side's reader-thread entry point."""

    def _readexactly(n: int) -> bytes:
        chunks = []
        got = 0
        while got < n:
            chunk = fileobj.read(n - got)
            if not chunk:
                raise _Short(b"".join(chunks))
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    class _Short(Exception):
        def __init__(self, partial):
            self.partial = partial

    try:
        try:
            prefix = _readexactly(PREFIX_LEN)
        except _Short as e:
            if not e.partial:
                raise WireClosed("peer closed the wire") from None
            raise WireError(
                f"truncated frame prefix ({len(e.partial)} of "
                f"{PREFIX_LEN} bytes)"
            ) from None
        hlen, blen = _check_prefix(prefix)
        try:
            hb = _readexactly(hlen)
            blob = _readexactly(blen) if blen else b""
        except _Short as e:
            raise WireError(
                f"mid-frame disconnect ({len(e.partial)} bytes short)"
            ) from None
    except OSError as e:
        raise WireError(f"wire read failed: {e}") from None
    return _decode(prefix, hb, blob)


# ----------------------------------------------------------------------
# typed error marshalling

# the whole taxonomy by class name: what a worker raises is what the
# client re-raises (Overloaded before AdmissionRejected is irrelevant
# here — the name lookup is exact)
_TAXONOMY = {
    cls.__name__: cls
    for cls in (
        AMGXTPUError, SetupError, SingularDiagonalError,
        NonFiniteValuesError, PatternDegeneracyError, SolveBreakdown,
        ResourceError, DeviceLostError, DeadlineExceededError,
        AdmissionRejected, Overloaded, StoreError, WireError,
        WireClosed,
    )
}


def marshal_error(exc: BaseException) -> dict:
    """Wire form of any exception: class name, message, RC code, and
    the machine-actionable extras the taxonomy carries
    (``retry_after_s``/``reason``/``device_label``)."""
    d = {
        "etype": type(exc).__name__,
        "msg": str(exc),
        "rc": rc_for_exception(exc),
    }
    for k in ("retry_after_s", "reason", "device_label"):
        v = getattr(exc, k, None)
        if v is not None:
            d[k] = v
    return d


def unmarshal_error(d: dict) -> AMGXTPUError:
    """Reconstruct the typed exception a peer marshalled.  Taxonomy
    classes round-trip exactly (constructor extras included); unknown
    types degrade to :class:`AMGXTPUError` with the marshalled RC —
    a remote failure is ALWAYS typed client-side."""
    if not isinstance(d, dict):
        return AMGXTPUError("malformed error payload", rc=RC_UNKNOWN)
    msg = str(d.get("msg", ""))
    cls = _TAXONOMY.get(d.get("etype"))
    if cls is None:
        rc = d.get("rc")
        return AMGXTPUError(
            f"{d.get('etype', 'RemoteError')}: {msg}",
            rc=rc if isinstance(rc, int) else RC_UNKNOWN,
        )
    try:
        if issubclass(cls, AdmissionRejected):
            return cls(
                msg,
                retry_after_s=d.get("retry_after_s"),
                reason=str(d.get("reason", "rejected")),
            )
        if issubclass(cls, DeviceLostError):
            return cls(msg, device_label=d.get("device_label"))
        return cls(msg)
    except Exception:  # noqa: BLE001 — marshalling must not raise
        return AMGXTPUError(msg, rc=d.get("rc", RC_UNKNOWN))


# ----------------------------------------------------------------------
# trace-context propagation


def trace_carrier() -> Optional[dict]:
    """The ambient trace context as a wire-safe dict (None when this
    request is unsampled) — attached to submit/step headers so a
    worker's spans join the client's trace."""
    from amgx_tpu.telemetry import tracing

    ctx = tracing.ambient()
    if ctx is None:
        return None
    return {
        "trace_id": ctx.trace_id,
        "root_id": ctx.root_id,
        "tid": ctx.tid,
    }


def trace_from_carrier(carrier):
    """Rebuild a TraceContext from a wire carrier dict (None-safe,
    malformed-safe: propagation must never fail a solve)."""
    if not isinstance(carrier, dict):
        return None
    from amgx_tpu.telemetry import tracing

    try:
        return tracing.TraceContext(
            str(carrier["trace_id"]),
            int(carrier["root_id"]),
            int(carrier.get("tid", 0)),
        )
    except (KeyError, TypeError, ValueError):
        return None
