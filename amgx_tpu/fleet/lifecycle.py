"""Fleet lifecycle: spawn, rolling restart, teardown.

The :class:`FleetSupervisor` owns the worker SUBPROCESSES (the
frontend owns their sockets): it spawns ``python -m
amgx_tpu.fleet.worker`` with a shared registry directory and a shared
:class:`~amgx_tpu.store.store.ArtifactStore` directory, waits for the
registry announce, and implements the drain-then-warmboot rolling
restart the fleet bench gates:

    quiesce(slot)      — frontend stops routing new work to the slot
    drain over wire    — worker settles EVERY admitted ticket and
                         exports hierarchies + sessions to the store
    reap               — the drained process exits; supervisor joins it
    spawn replacement  — same slot; warm-boots from the same store
    attach             — frontend routes to it again; its FIRST group
                         for a persisted fingerprint is a hierarchy-
                         cache HIT (coarsen_calls == 0) — the restart
                         loses no tickets and pays no setups

``kill(slot, sig=SIGKILL)`` is the chaos face: the frontend's
connection-loss path (breaker trip + exactly-once requeue) is what
the fleet bench asserts against it.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Optional

from amgx_tpu.fleet.registry import WorkerRegistry


class FleetSupervisor:
    """Spawns and reaps fleet worker subprocesses on this host."""

    def __init__(self, registry_dir: str, store_dir: Optional[str] = None,
                 *, env: Optional[dict] = None,
                 spawn_timeout_s: float = 120.0,
                 worker_args: Optional[list] = None):
        self.registry = WorkerRegistry(registry_dir)
        self.store_dir = store_dir
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.worker_args = list(worker_args or [])
        self._env = dict(os.environ)
        self._env.update(env or {})
        self._procs: dict = {}  # worker_id -> Popen
        self._spawn_seq = 0

    # -- spawning ------------------------------------------------------

    def spawn(self, slot: int, *, worker_id: Optional[str] = None,
              placement: Optional[str] = None,
              env: Optional[dict] = None, extra_args: Optional[list] = None):
        """Start one worker and block until it announces.  Returns
        its WorkerRecord (address included).  ``placement`` overrides
        ``AMGX_TPU_PLACEMENT`` for the child — how a dist-capable
        worker joins the fleet."""
        self._spawn_seq += 1
        wid = worker_id or f"w{slot}-{self._spawn_seq}"
        cmd = [
            sys.executable, "-m", "amgx_tpu.fleet.worker",
            "--registry", self.registry.root,
            "--worker-id", wid,
            "--slot", str(slot),
        ]
        if self.store_dir:
            cmd += ["--store", str(self.store_dir)]
        cmd += self.worker_args + list(extra_args or [])
        child_env = dict(self._env)
        child_env.update(env or {})
        if placement is not None:
            child_env["AMGX_TPU_PLACEMENT"] = placement
        proc = subprocess.Popen(cmd, env=child_env)
        try:
            rec = self.registry.wait_for(
                wid, timeout_s=self.spawn_timeout_s
            )
        except TimeoutError:
            proc.kill()
            proc.wait()
            raise
        self._procs[wid] = proc
        return rec

    def launch(self, n: int, **spawn_kwargs) -> list:
        """Spawn ``n`` workers on slots 0..n-1."""
        return [self.spawn(slot, **spawn_kwargs) for slot in range(n)]

    # -- teardown ------------------------------------------------------

    def kill(self, worker_id: str, sig: int = signal.SIGKILL) -> bool:
        """Chaos face: signal a worker (default SIGKILL — no drain,
        no goodbye; the frontend's loss path takes it from there)."""
        proc = self._procs.get(worker_id)
        if proc is None or proc.poll() is not None:
            return False
        proc.send_signal(sig)
        return True

    def reap(self, worker_id: str,
             timeout_s: float = 60.0) -> Optional[int]:
        """Join a worker process; returns its exit code (None when it
        was never spawned here)."""
        proc = self._procs.pop(worker_id, None)
        if proc is None:
            return None
        try:
            return proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            return proc.wait()
        finally:
            self.registry.withdraw(worker_id)

    def terminate_all(self, timeout_s: float = 30.0) -> None:
        for wid, proc in list(self._procs.items()):
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + timeout_s
        for wid in list(self._procs):
            left = max(deadline - time.monotonic(), 0.1)
            self.reap(wid, timeout_s=left)

    def live_workers(self) -> list:
        return [
            wid for wid, p in self._procs.items() if p.poll() is None
        ]

    # -- the rolling restart -------------------------------------------

    def rolling_restart(self, worker_id: str, frontend, *,
                        timeout_s: float = 60.0,
                        placement: Optional[str] = None) -> dict:
        """Replace one worker with zero lost tickets and zero
        re-setups.  Returns ``{"drain": <worker's drain report>,
        "exit_code": ..., "replacement": <new WorkerRecord>}``."""
        rec = self.registry.lookup(worker_id)
        if rec is None:
            raise ValueError(f"unknown worker {worker_id!r}")
        slot = rec.slot
        # 1. no NEW work routes to the slot; in-flight work finishes
        frontend.quiesce(slot)
        # 2. lossless handoff: settle everything, export to the store
        report = frontend.drain_worker(slot, timeout=timeout_s)
        # 3. the drained process exits; join it
        exit_code = self.reap(worker_id, timeout_s=timeout_s)
        frontend.detach(slot)
        # 4. replacement at the SAME slot warm-boots from the store
        new_rec = self.spawn(slot, placement=placement)
        frontend.attach(new_rec)
        return {
            "drain": report,
            "exit_code": exit_code,
            "replacement": new_rec,
        }


def launch_fleet(n: int, registry_dir: str,
                 store_dir: Optional[str] = None, *,
                 env: Optional[dict] = None,
                 worker_args: Optional[list] = None,
                 frontend_kwargs: Optional[dict] = None,
                 **spawn_kwargs) -> tuple:
    """Convenience bring-up: spawn ``n`` workers and a connected
    frontend.  Returns ``(supervisor, frontend)``."""
    from amgx_tpu.fleet.frontend import FleetFrontend

    sup = FleetSupervisor(
        registry_dir, store_dir, env=env, worker_args=worker_args
    )
    records = sup.launch(n, **spawn_kwargs)
    front = FleetFrontend(**(frontend_kwargs or {}))
    for rec in records:
        front.attach(rec)
    return sup, front
