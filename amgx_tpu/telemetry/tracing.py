"""End-to-end request tracing: trace contexts, a bounded span ring,
and Chrome trace-event export (Perfetto-loadable).

A trace is minted per request at the serve front door
(:meth:`SolveGateway.submit` / :meth:`BatchedSolveService.submit`) and
threaded through admission -> staging -> flush-group formation ->
dispatch -> fetch; each stage records a *completed* span (name, start,
end) into one process-wide bounded ring.  Group-formation spans carry
the member tickets' trace ids in their args, so a Perfetto view shows
exactly which requests shared a batch and where a p99 ticket spent its
time.

Sampling (``AMGX_TPU_TRACE_SAMPLE``, default 0 = off) is
deterministic — every round(1/rate)-th minted trace is sampled, no
RNG — so test runs and incident reproductions see the same spans.
When tracing is off the hot-path surface is a single float compare:
:func:`new_trace` returns ``None`` without allocating, and every
``record_*`` helper early-outs on a ``None`` context.

Export is :func:`export_chrome`: the standard
``{"traceEvents": [...]}`` JSON with ``"ph": "X"`` complete events,
microsecond timestamps relative to process start, one ``tid`` row per
trace so a request's submit -> admission -> pad -> dispatch ->
device -> fetch chain renders as one nested lane.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Optional

# timestamps are perf_counter seconds; the exporter rebases onto this
# process epoch so Chrome ts values start near zero
_EPOCH = time.perf_counter()

_lock = threading.Lock()
_rate_override: Optional[float] = None
_mint_seq = itertools.count()
_id_seq = itertools.count(1)


def set_sample_rate(rate: Optional[float]) -> None:
    """Override the env sampling rate (tests/CI); ``None`` restores
    the ``AMGX_TPU_TRACE_SAMPLE`` environment value."""
    global _rate_override
    _rate_override = None if rate is None else float(rate)


_env_rate_cache = (None, 0.0)  # (raw env string, parsed rate)


def sample_rate() -> float:
    if _rate_override is not None:
        return _rate_override
    # memoize the parse on the raw string: this runs several times per
    # submit even with tracing off, so the steady state must be one
    # env lookup + one string compare, not a float() parse
    global _env_rate_cache
    raw = os.environ.get("AMGX_TPU_TRACE_SAMPLE")
    cached_raw, cached_val = _env_rate_cache
    if raw == cached_raw:
        return cached_val
    try:
        val = float(raw or 0.0)
    except ValueError:
        val = 0.0
    _env_rate_cache = (raw, val)
    return val


def tracing_enabled() -> bool:
    return sample_rate() > 0.0


class TraceContext:
    """Identity of one sampled request: ``trace_id`` names the
    request across every span; ``root_id`` is the root span's id
    (children parent onto it); ``tid`` is the Chrome row."""

    __slots__ = ("trace_id", "root_id", "tid")

    def __init__(self, trace_id: str, root_id: int, tid: int):
        self.trace_id = trace_id
        self.root_id = root_id
        self.tid = tid


def new_trace() -> Optional[TraceContext]:
    """Mint a sampled trace context, or None (not sampled / tracing
    off).  The off path is allocation-free."""
    rate = sample_rate()
    if rate <= 0.0:
        return None
    n = next(_mint_seq)
    if rate < 1.0:
        period = max(int(round(1.0 / rate)), 1)
        if n % period:
            return None
    sid = next(_id_seq)
    return TraceContext(f"t{os.getpid():x}-{n:x}", sid, sid)


# ----------------------------------------------------------------------
# span ring


def _buffer_cap() -> int:
    # clamp to >= 1: a 0/negative cap would make add() index an empty
    # ring on the solve hot path (same clamp as recorder._env_cap)
    try:
        return max(
            int(os.environ.get("AMGX_TPU_TRACE_BUFFER", "") or 16384), 1
        )
    except ValueError:
        return 16384


class SpanBuffer:
    """Bounded ring of completed spans (dicts).  A ring — recent
    behaviour is the question, memory must be bounded regardless of
    uptime; same stance as LatencyReservoir."""

    def __init__(self, cap: Optional[int] = None):
        self.cap = max(int(cap), 1) if cap is not None else _buffer_cap()
        self._lock = threading.Lock()
        self._spans: list = []
        self._next = 0
        self.total = 0  # lifetime spans, beyond the ring

    def add(self, span: dict) -> None:
        with self._lock:
            if len(self._spans) < self.cap:
                self._spans.append(span)
            else:
                self._spans[self._next] = span
                self._next = (self._next + 1) % self.cap
            self.total += 1

    def spans(self) -> list:
        """Chronological copy of the ring."""
        with self._lock:
            return self._spans[self._next:] + self._spans[: self._next]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._next = 0

    def __len__(self):
        with self._lock:
            return len(self._spans)


_BUFFER = SpanBuffer()


def span_buffer() -> SpanBuffer:
    return _BUFFER


def clear() -> None:
    _BUFFER.clear()


def telemetry_snapshot() -> dict:
    """Registry source for the ``tracing`` component."""
    return {
        "spans_total": _BUFFER.total,
        "buffer_len": len(_BUFFER),
        "sample_rate": sample_rate(),
    }


# ----------------------------------------------------------------------
# recording

# thread-local ambient context: profiling hooks (trace_range,
# setup_phase) attach their spans to the current request when one is
# active on this thread, and to the process lane otherwise
_tls = threading.local()


def ambient() -> Optional[TraceContext]:
    return getattr(_tls, "ctx", None)


class use_context:
    """``with use_context(ctx):`` — make ``ctx`` the thread's ambient
    trace for profiling hooks running inside the block."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


# the process-wide lane spans fall onto when no request context is
# ambient (solver setups, background compiles): still on the timeline,
# just not attributed to one request
_PROC_TID = 0


def record_span(name: str, t0: float, t1: float,
                ctx: Optional[TraceContext] = None,
                parent: Optional[int] = None,
                args: Optional[dict] = None,
                root: bool = False) -> Optional[int]:
    """Record one completed span.  ``ctx=None`` with tracing enabled
    records onto the process lane (setup/background work);
    ``root=True`` claims the context's pre-minted root span id (so
    children recorded before the root closes still parent onto it).
    Returns the span id (for parenting) or None when tracing is
    off."""
    if not tracing_enabled():
        return None
    sid = ctx.root_id if (root and ctx is not None) else next(_id_seq)
    span = {
        "name": name,
        "sid": sid,
        "t0": t0,
        "t1": t1,
        "tid": ctx.tid if ctx is not None else _PROC_TID,
        "trace_id": ctx.trace_id if ctx is not None else None,
    }
    if ctx is not None and not root:
        span["parent"] = ctx.root_id if parent is None else parent
    elif parent is not None:
        span["parent"] = parent
    if args:
        span["args"] = args
    _BUFFER.add(span)
    return sid


class span_scope:
    """``with span_scope("name"):`` — time a block into the span ring
    under the thread's ambient context.  Cheap no-op when tracing is
    off (one enabled check, no allocation beyond the scope object)."""

    __slots__ = ("_name", "_args", "_t0", "_on")

    def __init__(self, name: str, args: Optional[dict] = None):
        self._name = name
        self._args = args

    def __enter__(self):
        self._on = tracing_enabled()
        if self._on:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._on:
            record_span(
                self._name, self._t0, time.perf_counter(),
                ambient(), args=self._args,
            )
        return False


# ----------------------------------------------------------------------
# export


def export_chrome(path: Optional[str] = None) -> dict:
    """Spans -> Chrome trace-event JSON (Perfetto/chrome://tracing
    loadable).  Returns the event dict; also writes it to ``path``
    when given.  Span times rebase onto the process epoch in
    microseconds; args carry trace/span/parent ids so tooling can
    reconstruct request chains exactly."""
    pid = os.getpid()
    events = []
    for s in _BUFFER.spans():
        args = {"trace_id": s.get("trace_id"), "span_id": s["sid"]}
        if "parent" in s:
            args["parent_id"] = s["parent"]
        if "args" in s:
            args.update(s["args"])
        events.append({
            "name": s["name"],
            "cat": "amgx_tpu",
            "ph": "X",
            "ts": (s["t0"] - _EPOCH) * 1e6,
            "dur": max(s["t1"] - s["t0"], 0.0) * 1e6,
            "pid": pid,
            "tid": s["tid"],
            "args": args,
        })
    trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace
