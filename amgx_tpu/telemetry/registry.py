"""Process-wide telemetry registry: every observable component —
serve services, gateways, artifact stores, the solver-timing
aggregate, the trace buffer — registers a snapshot source here, and
one object answers "what is this process doing" in three shapes:

* :meth:`TelemetryRegistry.snapshot` — structured dict, per
  component;
* :meth:`TelemetryRegistry.render_prometheus` — text exposition
  (the ``/metrics`` payload for the future wire front-end, ROADMAP
  open item 2);
* :meth:`TelemetryRegistry.dump` — JSON to a path
  (``AMGX_TPU_TELEMETRY_DUMP=<path>`` dumps at interpreter exit; an
  operator can also call ``dump()`` on demand — the SIGUSR1 hook of a
  wire server).

Registration is weak: the registry holds ``weakref``s to sources, so
registering never extends a service's lifetime and dead components
silently drop out of the next snapshot (test suites create hundreds
of short-lived services).  Collection is *defensive*: one broken
source — including the ``telemetry_export`` injected fault — is
counted into ``telemetry_errors`` and skipped; telemetry can degrade
but can never fail a solve or take down the exposition page.

``telemetry_enabled()`` (``AMGX_TPU_TELEMETRY=0`` kills it) gates the
per-solve hot-path hooks (flight records, incident capture); the
registry itself always works when called explicitly.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import weakref
from typing import Callable, Optional

from amgx_tpu.core import faults
from amgx_tpu.telemetry import promtext, tracing

_enabled_override: Optional[bool] = None


def set_telemetry_enabled(on: Optional[bool]) -> None:
    """Override the ``AMGX_TPU_TELEMETRY`` master switch (tests and
    the CI overhead A/B); ``None`` restores the environment value."""
    global _enabled_override
    _enabled_override = on if on is None else bool(on)


def telemetry_enabled() -> bool:
    """Master switch for the hot-path telemetry hooks (flight
    records, incident capture, solver-timing re-emission).  Read per
    call so tests/benches can toggle mid-process."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get("AMGX_TPU_TELEMETRY", "1") != "0"


class TelemetryRegistry:
    """Weak component registry + the three export faces."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sources: dict = {}  # name -> (kind, weak/strong getter)
        self._seq = itertools.count()
        self.telemetry_errors = 0
        # built-in sources: the trace buffer and the solver-timing
        # aggregate are process-wide, not per-object
        self._solver_lock = threading.Lock()
        self._solver_stats: dict = {}
        self.register("tracing", tracing.telemetry_snapshot,
                      name="tracing")
        self.register("solvers", self._solver_snapshot, name="solvers")

    # -- registration --------------------------------------------------

    def register(self, kind: str, source, name: Optional[str] = None
                 ) -> str:
        """Register a snapshot source and return its component name.

        ``source`` is an object exposing ``telemetry_snapshot()`` (held
        by ``weakref.ref``), a bound method (``weakref.WeakMethod``),
        or a plain callable returning a dict (held strongly).  A
        repeated name replaces the previous source."""
        if name is None:
            name = f"{kind}{next(self._seq)}"
        if hasattr(source, "telemetry_snapshot"):
            ref = weakref.ref(source)

            def getter(_ref=ref):
                obj = _ref()
                return None if obj is None else obj.telemetry_snapshot()

        elif hasattr(source, "__self__"):
            wm = weakref.WeakMethod(source)

            def getter(_wm=wm):
                fn = _wm()
                return None if fn is None else fn()

        elif callable(source):
            getter = source
        else:
            raise TypeError(
                "telemetry source must expose telemetry_snapshot() "
                "or be callable"
            )
        with self._lock:
            self._sources[name] = (kind, getter)
        return name

    def unregister(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def components(self) -> list:
        with self._lock:
            return list(self._sources)

    # -- solver-timing aggregate (obtain_timings re-emission) ----------

    def record_solver(self, solver: str, setup_s: float = 0.0,
                      compile_s: float = 0.0, solve_s: float = 0.0,
                      iterations: int = 0, reductions: int = 0,
                      cycle_passes: int = 0,
                      setup_phases: Optional[dict] = None) -> None:
        """Fold one timed solve's ``obtain_timings`` lines into the
        per-solver-class aggregate (the registry's ``solvers``
        component).  ``reductions`` counts the solve's global
        dot/norm reductions (``amgx_solver_reductions_total`` — the
        communication-free-inner-loop observability of PR 8);
        ``cycle_passes`` counts fine-grid operator passes
        (``amgx_solver_cycle_passes_total`` — the fused matrix-free
        cycle-leg observability, ops/stencil.py; 0 for solvers
        without a cycle notion); ``iterations`` additionally feeds a
        per-solver iteration histogram
        (``promtext.ITERATION_BUCKETS``)."""
        with self._solver_lock:
            st = self._solver_stats.setdefault(solver, {
                "solves": 0, "iterations": 0, "reductions": 0,
                "cycle_passes": 0,
                "setup_s": 0.0, "compile_s": 0.0, "solve_s": 0.0,
                "setup_phases": {}, "iter_hist": {},
            })
            st["solves"] += 1
            st["iterations"] += int(iterations)
            st["reductions"] += int(reductions)
            st["cycle_passes"] += int(cycle_passes)
            hist = st["iter_hist"]
            for le in promtext.ITERATION_BUCKETS:
                if iterations <= le:
                    hist[le] = hist.get(le, 0) + 1
                    break
            else:
                hist["+Inf"] = hist.get("+Inf", 0) + 1
            st["setup_s"] += float(setup_s)
            st["compile_s"] += float(compile_s)
            st["solve_s"] += float(solve_s)
            if setup_phases:
                ph = st["setup_phases"]
                for k, v in setup_phases.items():
                    if isinstance(v, float):
                        ph[k] = ph.get(k, 0.0) + v

    def _solver_snapshot(self) -> dict:
        with self._solver_lock:
            return {
                name: {**st,
                       "setup_phases": dict(st["setup_phases"]),
                       "iter_hist": dict(st["iter_hist"])}
                for name, st in self._solver_stats.items()
            }

    # -- collection ----------------------------------------------------

    def _collect_one(self, getter: Callable):
        if faults.should_fire("telemetry_export"):
            raise RuntimeError(
                "injected telemetry export failure (fault site "
                "telemetry_export)"
            )
        return getter()

    def snapshot(self) -> dict:
        """``{component: {"kind": ..., "data": {...}}}`` across every
        live source.  Dead weakrefs are dropped; a source that raises
        is counted (``telemetry_errors``) and skipped — a snapshot
        never raises."""
        with self._lock:
            items = list(self._sources.items())
        out = {}
        dead = []
        errors = 0
        for name, (kind, getter) in items:
            try:
                data = self._collect_one(getter)
            except Exception:  # noqa: BLE001 — degrade, never fail
                errors += 1
                continue
            if data is None:
                dead.append(name)
                continue
            out[name] = {"kind": kind, "data": data}
        if dead:
            with self._lock:
                for name in dead:
                    self._sources.pop(name, None)
        if errors:
            with self._lock:
                self.telemetry_errors += errors
        return out

    # -- export faces --------------------------------------------------

    def render_prometheus(self) -> str:
        """Prometheus text exposition of everything registered.
        Collection and rendering errors degrade to the
        ``amgx_telemetry_errors_total`` counter on the page itself."""
        snap = self.snapshot()
        with self._lock:
            errors = self.telemetry_errors
        return promtext.render(snap, telemetry_errors=errors)

    def dump(self, path: Optional[str] = None) -> bool:
        """Write the JSON telemetry dump to ``path`` (default:
        ``AMGX_TPU_TELEMETRY_DUMP``).  Returns False — counted, never
        raising — on any failure; True on success."""
        try:
            if path is None:
                path = os.environ.get("AMGX_TPU_TELEMETRY_DUMP")
            if not path:
                return False
            if faults.should_fire("telemetry_export"):
                raise RuntimeError(
                    "injected telemetry dump failure (fault site "
                    "telemetry_export)"
                )
            payload = {
                "ts": time.time(),
                "pid": os.getpid(),
                "snapshot": self.snapshot(),
                "trace_spans": len(tracing.span_buffer()),
            }
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
            return True
        except Exception:  # noqa: BLE001 — export must never propagate
            with self._lock:
                self.telemetry_errors += 1
            return False


# ----------------------------------------------------------------------
# process-wide default flight recorder (direct-API solves; serve
# services own their own recorder, shared with their gateway)

_DEFAULT_RECORDER = None


def default_recorder():
    """Flight recorder for solves outside any serve service (the
    direct ``Solver.solve`` path); registered into the process
    registry on first use."""
    global _DEFAULT_RECORDER
    with _REGISTRY_LOCK:
        created = _DEFAULT_RECORDER is None
        if created:
            from amgx_tpu.telemetry.recorder import FlightRecorder

            _DEFAULT_RECORDER = FlightRecorder()
    if created:
        get_registry().register(
            "recorder", _DEFAULT_RECORDER.summary, name="flight"
        )
    return _DEFAULT_RECORDER


# ----------------------------------------------------------------------
# process-wide default registry

_REGISTRY: Optional[TelemetryRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def get_registry() -> TelemetryRegistry:
    """The process-wide registry (created on first use; installs the
    ``AMGX_TPU_TELEMETRY_DUMP`` exit hook once)."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        if _REGISTRY is None:
            _REGISTRY = TelemetryRegistry()
            import atexit

            def _exit_dump():
                if os.environ.get("AMGX_TPU_TELEMETRY_DUMP"):
                    _REGISTRY.dump()

            atexit.register(_exit_dump)
        return _REGISTRY
