"""Prometheus text-exposition rendering for registry snapshots.

One module owns the mapping from the library's internal snapshot
shapes (serve :class:`~amgx_tpu.serve.metrics.ServeMetrics` dicts,
gateway/admission state, :class:`~amgx_tpu.store.store.ArtifactStore`
counters, the aggregated solver timings) to the Prometheus
text-exposition format, so components never need to know metric
grammar and the full metric catalog lives in one place
(doc/OBSERVABILITY.md mirrors it).

The model is a *family* table: ``name -> {"type", "help", "samples"}``
where samples are ``(labels_dict, value)`` pairs.  ``render()`` emits
``# HELP`` / ``# TYPE`` headers once per family and one sample line
per (labels, value), with label values escaped per the exposition
grammar.  Families merge across components: every registered serve
service contributes samples to the same ``amgx_serve_*`` families,
distinguished by the ``component`` label.
"""

from __future__ import annotations

import re

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")

# per-config iteration histogram bucket upper bounds (inner-step
# equivalents; the registry's record_solver sorts each timed solve
# into the first bucket that covers it, "+Inf" past the last)
ITERATION_BUCKETS = (1, 2, 5, 10, 20, 50, 100, 200, 500)


def sanitize_name(name: str) -> str:
    """Coerce an internal counter key into a legal metric name."""
    name = _NAME_SANITIZE.sub("_", str(name))
    if not name or not _NAME_OK.match(name):
        name = "_" + name
    return name


def escape_label_value(v) -> str:
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f != f:  # NaN
        return "NaN"
    return repr(f)


class FamilyTable:
    """Accumulator for metric families; insertion-ordered."""

    def __init__(self):
        self._fams: dict = {}

    def add(self, name: str, mtype: str, help_text: str,
            labels: dict, value) -> None:
        if value is None:
            return
        name = sanitize_name(name)
        fam = self._fams.get(name)
        if fam is None:
            fam = self._fams[name] = {
                "type": mtype,
                "help": help_text,
                "samples": [],
            }
        fam["samples"].append((dict(labels), value))

    def names(self):
        return list(self._fams)

    def render(self) -> str:
        lines = []
        for name, fam in self._fams.items():
            lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['type']}")
            for labels, value in fam["samples"]:
                if labels:
                    lab = ",".join(
                        f'{sanitize_name(k)}="{escape_label_value(v)}"'
                        for k, v in sorted(labels.items())
                    )
                    lines.append(f"{name}{{{lab}}} {_fmt_value(value)}")
                else:
                    lines.append(f"{name} {_fmt_value(value)}")
        return "\n".join(lines) + "\n" if lines else ""


# ----------------------------------------------------------------------
# serve metrics (ServeMetrics.snapshot() shape)

# counters that are point-in-time levels, not monotone totals
_SERVE_GAUGES = {
    "queue_depth",
    "breakers_open",
    "gateway_draining",
}

# resilience_* keys that are levels, not totals
_RESILIENCE_GAUGES = {
    "resilience_devices_unhealthy",
}

# hierarchy/compile-cache counters get their own amgx_cache_* namespace
# (the catalog's "cache source"), the rest of the int counters land in
# amgx_serve_* / amgx_gateway_*
_CACHE_RENAME = {
    "cache_hits": "amgx_cache_hierarchy_hits_total",
    "cache_misses": "amgx_cache_hierarchy_misses_total",
    "cache_evictions": "amgx_cache_hierarchy_evictions_total",
    "setups": "amgx_cache_hierarchy_setups_total",
    "bucket_hits": "amgx_cache_compile_hits_total",
    "compiles": "amgx_cache_compiles_total",
    "compile_warmups": "amgx_cache_compile_warmups_total",
    "compile_evictions": "amgx_cache_compile_evictions_total",
    "aot_fallbacks": "amgx_cache_aot_fallbacks_total",
    "prewarms": "amgx_cache_prewarms_total",
    "prewarm_failures": "amgx_cache_prewarm_failures_total",
}

# snapshot keys that are derived/structured, rendered specially or not
# rendered as plain counters
_SERVE_SKIP = {
    "buckets", "latency", "lanes", "profile",
    "ticket_p50_s", "ticket_p99_s", "tenant_device_s",
    "hierarchy_bytes", "hierarchy_format_bytes",
}


def _quantile_samples(fams, name, help_text, comp, extra, summ):
    base = {"component": comp}
    base.update(extra)
    for q, key in (("0.5", "p50_s"), ("0.99", "p99_s")):
        fams.add(name, "gauge", help_text,
                 {**base, "quantile": q}, summ.get(key, 0.0))
    fams.add(name + "_count", "counter",
             help_text + " (lifetime sample count)", base,
             summ.get("count", 0))
    fams.add(name + "_max", "gauge",
             help_text + " (window max)", base, summ.get("max_s", 0.0))


def serve_families(fams: FamilyTable, comp: str, snap: dict) -> None:
    """ServeMetrics.snapshot() -> amgx_serve_* / amgx_gateway_* /
    amgx_cache_* / amgx_setup_phase_* families."""
    labels = {"component": comp}
    for k, v in snap.items():
        if k in _SERVE_SKIP or not isinstance(v, (int, float)):
            continue
        if isinstance(v, int) and not isinstance(v, bool):
            if k in _CACHE_RENAME:
                fams.add(_CACHE_RENAME[k], "counter",
                         f"serve cache counter {k}", labels, v)
            elif k in _SERVE_GAUGES:
                fams.add(f"amgx_serve_{k}", "gauge",
                         f"serve gauge {k}", labels, v)
            elif k.startswith("resilience_"):
                # failure-domain counters (device-loss failover,
                # watchdog fires, session checkpoints/restores) get
                # their own amgx_resilience_* namespace
                if k in _RESILIENCE_GAUGES:
                    fams.add(f"amgx_{k}", "gauge",
                             f"resilience gauge {k}", labels, v)
                else:
                    fams.add(f"amgx_{k}_total", "counter",
                             f"resilience counter {k}", labels, v)
            elif k.startswith("shed_"):
                fams.add("amgx_gateway_sheds_by_reason_total", "counter",
                         "typed gateway sheds by reason",
                         {**labels, "reason": k[len("shed_"):]}, v)
            elif k.startswith("gateway_"):
                fams.add(f"amgx_{k}_total", "counter",
                         f"gateway counter {k}", labels, v)
            elif k.startswith("tenant_"):
                continue  # structured separately by the gateway source
            else:
                fams.add(f"amgx_serve_{k}_total", "counter",
                         f"serve counter {k}", labels, v)
        else:
            # float accumulators / derived rates
            if k.endswith("_s"):
                fams.add(f"amgx_serve_{k[:-2]}_seconds_total", "counter",
                         f"serve seconds accumulator {k}", labels, v)
            else:
                fams.add(f"amgx_serve_{k}", "gauge",
                         f"serve derived gauge {k}", labels, v)
    for dt, nb in (snap.get("hierarchy_bytes") or {}).items():
        fams.add("amgx_cache_hierarchy_bytes", "gauge",
                 "resident hierarchy-cache bytes by array dtype "
                 "(mixed-precision policy observability: a "
                 "hierarchy_dtype=FLOAT32 hierarchy moves value "
                 "bytes from the float64 to the float32 family)",
                 {**labels, "dtype": dt}, nb)
    for fmt, nb in (snap.get("hierarchy_format_bytes") or {}).items():
        fams.add("amgx_cache_hierarchy_bytes", "gauge",
                 "resident hierarchy-cache bytes by accel format "
                 "(MATRIX_FREE levels hold O(1) coefficient state "
                 "where DIA holds O(nnz) value planes — this split "
                 "shows the compression landing)",
                 {**labels, "format": fmt}, nb)
    for stage, summ in (snap.get("latency") or {}).items():
        _quantile_samples(
            fams, "amgx_serve_ticket_latency_seconds",
            "per-ticket pipeline stage latency", comp,
            {"stage": stage}, summ,
        )
    for lane, summ in (snap.get("lanes") or {}).items():
        _quantile_samples(
            fams, "amgx_serve_lane_latency_seconds",
            "per-priority-lane end-to-end latency", comp,
            {"lane": lane}, summ,
        )
    for bk, st in (snap.get("buckets") or {}).items():
        bl = {**labels, "bucket": bk}
        fams.add("amgx_serve_bucket_calls_total", "counter",
                 "batched executions per (n, nnz, batch) bucket", bl,
                 st.get("calls", 0))
        fams.add("amgx_serve_bucket_seconds_total", "counter",
                 "device seconds per bucket", bl, st.get("total_s", 0.0))
        fams.add("amgx_serve_bucket_instances_total", "counter",
                 "real instances executed per bucket", bl,
                 st.get("instances", 0))
        fams.add("amgx_serve_bucket_pad_instances_total", "counter",
                 "padding instances executed per bucket", bl,
                 st.get("pad_instances", 0))
    prof = snap.get("profile") or {}
    for phase, secs in (prof.get("times") or {}).items():
        if phase.startswith("setup:"):
            fams.add("amgx_setup_phase_seconds_total", "counter",
                     "hierarchy-setup phase seconds "
                     "(cold-setup anatomy, PR 5)",
                     {**labels, "phase": phase[len("setup:"):]}, secs)
        else:
            fams.add("amgx_serve_phase_seconds_total", "counter",
                     "serve pipeline phase seconds",
                     {**labels, "phase": phase}, secs)
    for phase, calls in (prof.get("counts") or {}).items():
        if phase.startswith("setup:"):
            continue
        fams.add("amgx_serve_phase_calls_total", "counter",
                 "serve pipeline phase call counts",
                 {**labels, "phase": phase}, calls)


def gateway_families(fams: FamilyTable, comp: str, snap: dict) -> None:
    """Gateway telemetry_snapshot() -> amgx_gateway_* families (the
    admission/tenant view; the shared counter set is exported by the
    serve component)."""
    labels = {"component": comp}
    fams.add("amgx_gateway_inflight", "gauge",
             "admitted-but-unsettled tickets", labels,
             snap.get("inflight", 0))
    fams.add("amgx_gateway_max_inflight", "gauge",
             "global concurrency budget", labels,
             snap.get("max_inflight", 0))
    fams.add("amgx_gateway_up", "gauge",
             "1 while the gateway state is 'serving'",
             {**labels, "state": snap.get("state", "?")},
             1 if snap.get("state") == "serving" else 0)
    for tenant, counts in (snap.get("tenants") or {}).items():
        tl = {**labels, "tenant": tenant}
        fams.add("amgx_gateway_tenant_admitted_total", "counter",
                 "admitted submits per tenant", tl,
                 counts.get("admitted", 0))
        fams.add("amgx_gateway_tenant_sheds_total", "counter",
                 "typed sheds per tenant", tl, counts.get("sheds", 0))
        fams.add("amgx_gateway_tenant_completed_total", "counter",
                 "settled-success tickets per tenant", tl,
                 counts.get("completed", 0))
        if "tokens" in counts:
            fams.add("amgx_admission_tenant_tokens", "gauge",
                     "remaining token-bucket quota per tenant", tl,
                     counts["tokens"])
    for tenant, lanes in (snap.get("tenant_device_s") or {}).items():
        for lane, secs in lanes.items():
            fams.add("amgx_gateway_tenant_device_seconds_total",
                     "counter",
                     "device-execution seconds attributed per "
                     "tenant/lane (each ticket's even share of its "
                     "group's device time — fleet cost accounting)",
                     {**labels, "tenant": tenant, "lane": lane}, secs)
    for tenant, tokens in (snap.get("tenant_device_tokens") or {}
                           ).items():
        fams.add("amgx_admission_tenant_device_seconds", "gauge",
                 "remaining device-seconds budget per tenant "
                 "(negative = debt being refilled; admits shed typed "
                 "reason=device_budget while negative)",
                 {**labels, "tenant": tenant}, tokens)
    rec = snap.get("recorder") or {}
    fams.add("amgx_flight_records_total", "counter",
             "per-solve flight-recorder records", labels,
             rec.get("records_total"))
    fams.add("amgx_incident_log_size", "gauge",
             "incidents currently held in the ring", labels,
             rec.get("incident_log_size"))
    for kind, n in (rec.get("incidents_by_kind") or {}).items():
        fams.add("amgx_incidents_total", "counter",
                 "flight-recorder incidents by kind",
                 {**labels, "kind": kind}, n)


def store_families(fams: FamilyTable, comp: str, snap: dict) -> None:
    """ArtifactStore stats -> amgx_store_* families."""
    labels = {"component": comp}
    for k, v in (snap.get("counters") or {}).items():
        fams.add(f"amgx_store_{k}_total", "counter",
                 f"artifact-store counter {k}", labels, v)
    if "entries" in snap:
        fams.add("amgx_store_entries", "gauge",
                 "entries currently on disk", labels, snap["entries"])
    if "max_bytes" in snap:
        fams.add("amgx_store_budget_bytes", "gauge",
                 "configured store size budget", labels,
                 snap["max_bytes"])


def solver_families(fams: FamilyTable, comp: str, snap: dict) -> None:
    """Aggregated solver timings (obtain_timings re-emission) ->
    amgx_solver_* families, labeled by solver registry name."""
    for solver, st in snap.items():
        labels = {"component": comp, "solver": solver}
        fams.add("amgx_solver_solves_total", "counter",
                 "timed solves observed", labels, st.get("solves", 0))
        fams.add("amgx_solver_iterations_total", "counter",
                 "iterations across timed solves (inner-step "
                 "equivalents: one s-step outer = s CG steps)", labels,
                 st.get("iterations", 0))
        fams.add("amgx_solver_reductions_total", "counter",
                 "global dot/norm reductions across timed solves (the "
                 "cross-chip psum sync points; ~3/iter for monitored "
                 "PCG, ~2/s per iter for SSTEP_PCG)", labels,
                 st.get("reductions", 0))
        fams.add("amgx_solver_cycle_passes_total", "counter",
                 "fine-grid operator passes across timed solves "
                 "(trace-time op_pass counter; fused matrix-free "
                 "cycle legs drop this from 3(L-1)+1 to 2(L-1)+1 "
                 "per V-cycle)", labels,
                 st.get("cycle_passes", 0))
        hist = st.get("iter_hist") or {}
        if hist:
            # histogram-shaped per-config iteration distribution:
            # cumulative le-labelled buckets + _sum/_count
            cum = 0
            for le in ITERATION_BUCKETS:
                cum += hist.get(le, 0)
                fams.add("amgx_solver_iterations_bucket", "counter",
                         "timed solves by iteration count "
                         "(cumulative buckets)",
                         {**labels, "le": str(le)}, cum)
            fams.add("amgx_solver_iterations_bucket", "counter",
                     "timed solves by iteration count "
                     "(cumulative buckets)",
                     {**labels, "le": "+Inf"}, st.get("solves", 0))
            fams.add("amgx_solver_iterations_sum", "counter",
                     "iteration histogram sum", labels,
                     st.get("iterations", 0))
            fams.add("amgx_solver_iterations_count", "counter",
                     "iteration histogram count", labels,
                     st.get("solves", 0))
        fams.add("amgx_solver_setup_seconds_total", "counter",
                 "setup seconds across timed solves", labels,
                 st.get("setup_s", 0.0))
        fams.add("amgx_solver_compile_seconds_total", "counter",
                 "compile seconds across timed solves", labels,
                 st.get("compile_s", 0.0))
        fams.add("amgx_solver_solve_seconds_total", "counter",
                 "solve seconds across timed solves", labels,
                 st.get("solve_s", 0.0))
        for phase, secs in (st.get("setup_phases") or {}).items():
            fams.add("amgx_setup_phase_seconds_total", "counter",
                     "hierarchy-setup phase seconds "
                     "(cold-setup anatomy, PR 5)",
                     {"component": comp, "solver": solver,
                      "phase": phase}, secs)


def recorder_families(fams: FamilyTable, comp: str, snap: dict) -> None:
    """Standalone FlightRecorder summary (the direct-API default
    recorder) -> the same amgx_flight_* / amgx_incidents_* families
    the gateway source uses."""
    labels = {"component": comp}
    fams.add("amgx_flight_records_total", "counter",
             "per-solve flight-recorder records", labels,
             snap.get("records_total"))
    fams.add("amgx_incident_log_size", "gauge",
             "incidents currently held in the ring", labels,
             snap.get("incident_log_size"))
    for kind, n in (snap.get("incidents_by_kind") or {}).items():
        fams.add("amgx_incidents_total", "counter",
                 "flight-recorder incidents by kind",
                 {**labels, "kind": kind}, n)


def session_families(fams: FamilyTable, comp: str, snap: dict) -> None:
    """SessionManager.telemetry_snapshot() -> amgx_session_* families
    (the streaming transient-PDE workload: step/warm-start counts,
    resetup-under-solve overlap seconds, persistence outcomes)."""
    labels = {"component": comp}
    for k, v in snap.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        if k == "open":
            fams.add("amgx_session_open", "gauge",
                     "streaming sessions currently open", labels, v)
        elif isinstance(v, float):
            # float accumulators are seconds totals (resetup /
            # resetup-overlap)
            name = k if k.endswith("_seconds_total") \
                else f"{k}_seconds_total"
            fams.add(f"amgx_session_{name}", "counter",
                     f"session seconds accumulator {k}", labels, v)
        else:
            name = k if k.endswith("_total") else f"{k}_total"
            fams.add(f"amgx_session_{name}", "counter",
                     f"session counter {k}", labels, v)


def mesh_families(fams: FamilyTable, comp: str, snap: dict) -> None:
    """PlacementPolicy.telemetry_snapshot() (mesh/affinity, PR 10) ->
    amgx_mesh_* families: groups and busy seconds per device,
    convergence-mask psum totals, cache-affinity hit/miss counts."""
    labels = {"component": comp, "policy": snap.get("policy", "?")}
    fams.add("amgx_mesh_devices", "gauge",
             "devices visible to the placement policy", labels,
             snap.get("devices"))
    fams.add("amgx_mesh_groups_total", "counter",
             "groups placed by the policy", labels,
             snap.get("groups_total"))
    fams.add("amgx_mesh_sharded_groups_total", "counter",
             "groups whose batch axis was sharded over the mesh",
             labels, snap.get("sharded_groups_total"))
    fams.add("amgx_mesh_psums_total", "counter",
             "cross-chip convergence-mask psums executed (the ONLY "
             "collective of a batch-sharded group; one per group-loop "
             "iteration)", labels, snap.get("psums_total"))
    fams.add("amgx_mesh_psum_sites_per_iteration", "gauge",
             "psum call sites traced into the sharded group loop "
             "(gated == 1 by ci/mesh_bench.py)", labels,
             snap.get("psum_sites_per_iteration"))
    fams.add("amgx_mesh_compiles_total", "counter",
             "sharded executables compiled", labels,
             snap.get("mesh_compiles"))
    hits = snap.get("affinity_hits")
    misses = snap.get("affinity_misses")
    fams.add("amgx_mesh_affinity_hits_total", "counter",
             "groups routed to a device whose caches were already "
             "warm for their fingerprint", labels, hits)
    fams.add("amgx_mesh_affinity_misses_total", "counter",
             "groups routed cold (least-loaded fallback)", labels,
             misses)
    if hits is not None and misses is not None and (hits + misses):
        fams.add("amgx_mesh_affinity_hit_ratio", "gauge",
                 "warm-routing fraction of routed groups", labels,
                 hits / (hits + misses))
    for dev, n in (snap.get("groups_per_device") or {}).items():
        fams.add("amgx_mesh_device_groups_total", "counter",
                 "groups executed per device", {**labels, "device": dev},
                 n)
    for dev, secs in (snap.get("device_busy_s") or {}).items():
        fams.add("amgx_mesh_device_busy_seconds_total", "counter",
                 "device-execution seconds per device",
                 {**labels, "device": dev}, secs)


def dist_families(fams: FamilyTable, comp: str, snap: dict) -> None:
    """DistributedPlacement.telemetry_snapshot() (domain
    decomposition, PR 14) -> amgx_dist_* families: per-level halo
    bytes and ghost rows, setup counts, collective accounting, and
    the consolidation level index."""
    labels = {"component": comp, "policy": snap.get("policy", "?")}
    fams.add("amgx_dist_devices", "gauge",
             "mesh devices the row-sharding policy spans", labels,
             snap.get("devices"))
    fams.add("amgx_dist_row_threshold", "gauge",
             "minimum pattern rows for a group to row-shard", labels,
             snap.get("row_threshold"))
    fams.add("amgx_dist_sharded_groups_total", "counter",
             "groups solved row-sharded over the mesh", labels,
             snap.get("sharded_groups_total"))
    fams.add("amgx_dist_fallback_groups_total", "counter",
             "groups below the row threshold (fallback policy)",
             labels, snap.get("fallback_groups_total"))
    fams.add("amgx_dist_solves_total", "counter",
             "row-sharded instance solves", labels,
             snap.get("sharded_solves_total"))
    fams.add("amgx_dist_setups_total", "counter",
             "sharded hierarchy setups (fingerprint or values miss)",
             labels, snap.get("setups_total"))
    fams.add("amgx_dist_setup_seconds_total", "counter",
             "seconds spent in sharded hierarchy setup", labels,
             snap.get("setup_seconds_total"))
    fams.add("amgx_dist_iterations_total", "counter",
             "outer Krylov iterations retired by sharded solves",
             labels, snap.get("iterations_total"))
    fams.add("amgx_dist_psum_sites_per_solve", "gauge",
             "psum call sites traced into the sharded solve program "
             "(ci/halo_bench.py gates the reduction budget)", labels,
             snap.get("psum_sites_per_solve"))
    fams.add("amgx_dist_consolidation_level", "gauge",
             "hierarchy level index where graded consolidation onto "
             "fewer shards begins (= level count when never graded)",
             labels, snap.get("consolidation_level"))
    fams.add("amgx_dist_halo_exchange_bytes_per_cycle", "gauge",
             "analytic bytes one V-cycle's halo exchanges move "
             "(all levels + consolidation bridges)", labels,
             snap.get("halo_exchange_bytes_per_cycle"))
    fams.add("amgx_dist_sparsify_dropped_total", "counter",
             "cross-shard coarse Galerkin entries dropped by "
             "dist_coarse_sparsify (diagonal-lumped)", labels,
             snap.get("sparsify_dropped_total"))
    for lvl in (snap.get("levels") or ()):
        ll = {**labels, "level": str(lvl.get("level"))}
        fams.add("amgx_dist_level_halo_bytes", "gauge",
                 "bytes one halo exchange moves at this level", ll,
                 lvl.get("halo_bytes"))
        fams.add("amgx_dist_level_ghost_rows", "gauge",
                 "ghost (halo) rows per level, summed over shards",
                 ll, lvl.get("ghost_rows"))
        fams.add("amgx_dist_level_active_shards", "gauge",
                 "shards owning rows at this level (graded "
                 "consolidation shrinks the active tier)", ll,
                 lvl.get("active_shards"))


def fleet_families(fams: FamilyTable, comp: str, snap: dict) -> None:
    """FleetFrontend.telemetry_snapshot() (multi-process fleet tier)
    -> amgx_fleet_* families: submission/settlement counters,
    cross-process affinity routing, per-worker breaker state, and the
    wire round-trip latency summary."""
    labels = {"component": comp}
    counters = snap.get("counters") or {}
    fams.add("amgx_fleet_submitted_total", "counter",
             "solves submitted to fleet workers", labels,
             counters.get("submitted"))
    fams.add("amgx_fleet_completed_total", "counter",
             "solves settled successfully over the wire", labels,
             counters.get("completed"))
    fams.add("amgx_fleet_typed_errors_total", "counter",
             "tickets settled with a typed taxonomy error", labels,
             counters.get("typed_errors"))
    fams.add("amgx_fleet_retries_total", "counter",
             "retryable typed errors re-submitted through routing",
             labels, counters.get("retries"))
    fams.add("amgx_fleet_requeued_total", "counter",
             "in-flight tickets requeued to a healthy worker after a "
             "connection loss", labels, counters.get("requeued"))
    fams.add("amgx_fleet_requeue_failures_total", "counter",
             "tickets settled typed after losing their requeue too",
             labels, counters.get("requeue_failures"))
    fams.add("amgx_fleet_conn_losses_total", "counter",
             "worker connections lost unexpectedly", labels,
             counters.get("conn_losses"))
    routing = snap.get("routing") or {}
    hits = routing.get("hits")
    misses = routing.get("misses")
    fams.add("amgx_fleet_affinity_hits_total", "counter",
             "submits routed to a worker already warm for their "
             "fingerprint", labels, hits)
    fams.add("amgx_fleet_affinity_misses_total", "counter",
             "submits routed cold (least-loaded fallback)", labels,
             misses)
    if hits is not None and misses is not None and (hits + misses):
        fams.add("amgx_fleet_affinity_hit_ratio", "gauge",
                 "warm-routing fraction of fleet submits", labels,
                 hits / (hits + misses))
    fams.add("amgx_fleet_workers", "gauge",
             "workers currently attached and routable", labels,
             len(routing.get("active") or ()))
    fams.add("amgx_fleet_dist_routed_total", "counter",
             "oversized patterns restricted to distributed-capable "
             "workers", labels, routing.get("dist_routed"))
    fams.add("amgx_fleet_route_fallbacks_total", "counter",
             "submits routed with every pool worker's breaker open",
             labels, routing.get("fallbacks"))
    health = routing.get("health") or {}
    fams.add("amgx_fleet_workers_unhealthy", "gauge",
             "workers with an open breaker", labels,
             health.get("unhealthy"))
    fams.add("amgx_fleet_worker_trips_total", "counter",
             "worker breaker trips (dead process = lost device one "
             "tier up)", labels, health.get("trips"))
    fams.add("amgx_fleet_worker_probes_total", "counter",
             "half-open probes routed to tripped workers", labels,
             health.get("probes"))
    fams.add("amgx_fleet_worker_closes_total", "counter",
             "worker breakers closed by a successful probe", labels,
             health.get("closes"))
    retry = snap.get("retry") or {}
    fams.add("amgx_fleet_retry_giveups_total", "counter",
             "retryable errors surfaced after exhausting attempts",
             labels, retry.get("giveups"))
    lat = snap.get("wire_latency") or {}
    for stat in ("mean_s", "p50_s", "p99_s"):
        fams.add(f"amgx_fleet_wire_latency_{stat}", "gauge",
                 f"wire round-trip latency {stat.replace('_s', '')} "
                 "(submit to settle)", labels, lat.get(stat))
    fams.add("amgx_fleet_wire_requests", "gauge",
             "wire round-trips in the latency reservoir", labels,
             lat.get("count"))


def tracing_families(fams: FamilyTable, comp: str, snap: dict) -> None:
    labels = {"component": comp}
    fams.add("amgx_trace_spans_total", "counter",
             "spans recorded since process start", labels,
             snap.get("spans_total", 0))
    fams.add("amgx_trace_buffer_spans", "gauge",
             "spans currently held in the ring", labels,
             snap.get("buffer_len", 0))
    fams.add("amgx_trace_sample_rate", "gauge",
             "effective trace sampling rate", labels,
             snap.get("sample_rate", 0.0))


def generic_families(fams: FamilyTable, kind: str, comp: str,
                     snap: dict) -> None:
    """Fallback: flat numeric walk for unknown component kinds."""
    labels = {"component": comp}
    for k, v in snap.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        fams.add(f"amgx_{kind}_{k}", "gauge",
                 f"{kind} value {k}", labels, v)


_RENDERERS = {
    "serve": serve_families,
    "gateway": gateway_families,
    "store": store_families,
    "solvers": solver_families,
    "sessions": session_families,
    "mesh": mesh_families,
    "dist": dist_families,
    "fleet": fleet_families,
    "tracing": tracing_families,
    "recorder": recorder_families,
}


def render(components: dict, telemetry_errors: int = 0) -> str:
    """Registry snapshot ({name: {"kind", "data"}}) -> exposition
    text.  Unknown kinds degrade to a generic numeric walk; rendering
    of one component never fails the whole page (errors are counted
    into ``amgx_telemetry_errors_total`` by the caller)."""
    fams = FamilyTable()
    errors = telemetry_errors
    for comp, ent in components.items():
        kind = ent.get("kind", "component")
        data = ent.get("data")
        if not isinstance(data, dict):
            continue
        fn = _RENDERERS.get(kind, None)
        try:
            if fn is None:
                generic_families(fams, kind, comp, data)
            else:
                fn(fams, comp, data)
        except Exception:  # noqa: BLE001 — one bad component must not
            # take down the whole exposition page
            errors += 1
    fams.add("amgx_telemetry_errors_total", "counter",
             "telemetry collection/export failures (degraded, "
             "never propagated to a solve)", {}, errors)
    return fams.render()
