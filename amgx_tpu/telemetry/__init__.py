"""Unified telemetry: metrics exposition, request tracing, and the
solve flight recorder (reference analogues: amgx_timer/nvtx ranges,
print_solve_stats, convergence_analysis — generalized to a serving
fleet).

Three cooperating pieces, all bounded, all fail-degradable:

* :mod:`amgx_tpu.telemetry.registry` — the process-wide
  :class:`TelemetryRegistry` every gateway/service/store/solver
  registers into, with ``snapshot()`` (structured),
  ``render_prometheus()`` (text exposition), and ``dump()``
  (JSON; ``AMGX_TPU_TELEMETRY_DUMP=<path>`` dumps at exit);
* :mod:`amgx_tpu.telemetry.tracing` — per-request trace contexts
  threaded submit -> admission -> pad -> dispatch -> device -> fetch,
  recorded into a bounded span ring and exportable as Chrome
  trace-event JSON (``AMGX_TPU_TRACE_SAMPLE`` sampling, off by
  default with a no-op hot path);
* :mod:`amgx_tpu.telemetry.recorder` — the
  :class:`FlightRecorder`: a ring of per-solve records plus an
  incident log capturing what was in flight when a quarantine,
  breaker trip, shed, or deadline expiry fired.

Env knobs: ``AMGX_TPU_TELEMETRY=0`` (master off),
``AMGX_TPU_TRACE_SAMPLE`` (0..1), ``AMGX_TPU_TRACE_BUFFER``,
``AMGX_TPU_FLIGHT_RECORDS``, ``AMGX_TPU_INCIDENT_LOG``,
``AMGX_TPU_TELEMETRY_DUMP``.  See doc/OBSERVABILITY.md for the full
metric catalog and trace schema.
"""

from amgx_tpu.telemetry import tracing  # noqa: F401
from amgx_tpu.telemetry.recorder import FlightRecorder, SolveRecord
from amgx_tpu.telemetry.registry import (
    TelemetryRegistry,
    get_registry,
    set_telemetry_enabled,
    telemetry_enabled,
)

__all__ = [
    "TelemetryRegistry",
    "get_registry",
    "telemetry_enabled",
    "set_telemetry_enabled",
    "FlightRecorder",
    "SolveRecord",
    "tracing",
]
