"""Solve flight recorder: a bounded ring of per-solve records plus a
last-N incident log.

The reference's ``print_solve_stats``/``convergence_analysis`` answer
"how did THIS solve go" for one interactively-watched solve; a serving
fleet needs the same answer *retroactively* — what was the recent
solve population doing, and what exactly was in flight when something
tripped.  Two bounded rings:

* **records** — one :class:`SolveRecord` per completed solve
  (fingerprint, config hash, lane, tenant, iterations, final
  residual, status, per-stage timings, trace id), capacity
  ``AMGX_TPU_FLIGHT_RECORDS`` (default 256);
* **incidents** — whenever a quarantine, breaker trip, typed shed, or
  deadline expiry fires, the triggering detail plus a metrics
  snapshot is appended (capacity ``AMGX_TPU_INCIDENT_LOG``, default
  64).  Snapshot capture is throttled (one per
  ``snapshot_min_interval_s``) so an overload's shed storm cannot turn
  the observer into load; throttled incidents still log, just without
  the snapshot.

Failure stance: the ``telemetry_export`` fault site fires inside
:meth:`record`/:meth:`incident`, and every serve call site swallows
the raise into a counted ``telemetry_errors`` — telemetry must never
fail a solve (proved by ci/fault_smoke.py).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Optional

from amgx_tpu.core import faults


def _env_cap(name: str, default: int) -> int:
    try:
        return max(int(os.environ.get(name, "") or default), 1)
    except ValueError:
        return default


@dataclasses.dataclass(slots=True)
class SolveRecord:
    """One completed solve, as the flight recorder remembers it."""

    ts: float  # wall-clock unix time at record
    fingerprint: str
    config: str  # AMGConfig content hash
    lane: str
    tenant: str
    iterations: int
    final_residual: float
    status: int
    stages: dict  # stage name -> seconds
    path: str = "batched"  # batched | quarantine | fallback | direct
    trace_id: Optional[str] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class FlightRecorder:
    """Bounded solve-record ring + incident log (thread-safe)."""

    def __init__(
        self,
        cap: Optional[int] = None,
        incident_cap: Optional[int] = None,
        snapshot_fn: Optional[Callable[[], dict]] = None,
        snapshot_min_interval_s: float = 0.25,
    ):
        self.cap = (
            int(cap) if cap is not None
            else _env_cap("AMGX_TPU_FLIGHT_RECORDS", 256)
        )
        self.incident_cap = (
            int(incident_cap) if incident_cap is not None
            else _env_cap("AMGX_TPU_INCIDENT_LOG", 64)
        )
        self.snapshot_fn = snapshot_fn
        self.snapshot_min_interval_s = float(snapshot_min_interval_s)
        self._lock = threading.Lock()
        self._records: list = []
        self._next = 0
        self._incidents: list = []
        self._inext = 0
        self._last_snap = 0.0
        self.records_total = 0
        self.incidents_total = 0
        self.incidents_by_kind: dict = {}

    # -- records -------------------------------------------------------

    def record(self, **fields) -> SolveRecord:
        """Append one solve record.  Raises when the
        ``telemetry_export`` fault site is armed — call sites MUST
        swallow into a counted degrade (the fault contract)."""
        if faults.should_fire("telemetry_export"):
            raise RuntimeError(
                "injected flight-record failure (fault site "
                "telemetry_export)"
            )
        rec = SolveRecord(ts=time.time(), **fields)
        with self._lock:
            if len(self._records) < self.cap:
                self._records.append(rec)
            else:
                self._records[self._next] = rec
                self._next = (self._next + 1) % self.cap
            self.records_total += 1
        return rec

    def extend(self, recs: list) -> None:
        """Append pre-built :class:`SolveRecord`\\ s in ONE fault check
        and ONE lock acquisition — the serve fetch loop records a whole
        batch group this way, so the per-ticket hot-path cost is just
        the record construction (the ≤3% overhead ceiling in
        ci/telemetry_check.py is measured against this path)."""
        if faults.should_fire("telemetry_export"):
            raise RuntimeError(
                "injected flight-record failure (fault site "
                "telemetry_export)"
            )
        with self._lock:
            for rec in recs:
                if len(self._records) < self.cap:
                    self._records.append(rec)
                else:
                    self._records[self._next] = rec
                    self._next = (self._next + 1) % self.cap
            self.records_total += len(recs)

    def records(self) -> list:
        """Chronological copy of the record ring."""
        with self._lock:
            return self._records[self._next:] + self._records[: self._next]

    # -- incidents -----------------------------------------------------

    def incident(self, kind: str, detail: str = "",
                 record: Optional[SolveRecord] = None) -> dict:
        """Append one incident: the trigger (kind/detail/record) plus
        a throttled metrics snapshot.  Raises under the
        ``telemetry_export`` fault site (call sites swallow)."""
        if faults.should_fire("telemetry_export"):
            raise RuntimeError(
                "injected incident-capture failure (fault site "
                "telemetry_export)"
            )
        snap = None
        now = time.monotonic()
        take_snap = False
        with self._lock:
            if (
                self.snapshot_fn is not None
                and now - self._last_snap >= self.snapshot_min_interval_s
            ):
                self._last_snap = now
                take_snap = True
        if take_snap:
            try:
                snap = self.snapshot_fn()
            except Exception:  # noqa: BLE001 — the snapshot is garnish;
                # the incident itself must still land
                snap = None
        inc = {
            "ts": time.time(),
            "kind": kind,
            "detail": detail,
            "record": record.to_dict() if record is not None else None,
            "snapshot": snap,
        }
        with self._lock:
            if len(self._incidents) < self.incident_cap:
                self._incidents.append(inc)
            else:
                self._incidents[self._inext] = inc
                self._inext = (self._inext + 1) % self.incident_cap
            self.incidents_total += 1
            self.incidents_by_kind[kind] = (
                self.incidents_by_kind.get(kind, 0) + 1
            )
        return inc

    def incidents(self) -> list:
        """Chronological copy of the incident ring."""
        with self._lock:
            return (
                self._incidents[self._inext:]
                + self._incidents[: self._inext]
            )

    # -- export --------------------------------------------------------

    def summary(self) -> dict:
        """Bounded counts view (gateway.health(), prom export)."""
        with self._lock:
            return {
                "records_total": self.records_total,
                "record_ring_size": len(self._records),
                "incidents_total": self.incidents_total,
                "incident_log_size": len(self._incidents),
                "incidents_by_kind": dict(self.incidents_by_kind),
            }

    def to_dict(self) -> dict:
        """Full dump (gateway.debug_report(), capi telemetry JSON)."""
        return {
            "summary": self.summary(),
            "records": [r.to_dict() for r in self.records()],
            "incidents": self.incidents(),
        }
