from amgx_tpu.io.poisson import poisson_2d_5pt, poisson_3d_7pt, poisson_3d_27pt
from amgx_tpu.io.matrix_market import read_mtx, read_system, write_system

__all__ = [
    "poisson_2d_5pt",
    "poisson_3d_7pt",
    "poisson_3d_27pt",
    "read_mtx",
    "read_system",
    "write_system",
]
