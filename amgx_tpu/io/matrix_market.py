"""MatrixMarket IO with the reference's %%NVAMG extensions.

Reference parity: src/matrix_io.cu (readers/writers), AMGX_read_system /
AMGX_write_system (amgx_c.h:424-460).  Supported:

  * standard ``%%MatrixMarket matrix coordinate real|complex|integer|pattern
    general|symmetric|hermitian|skew-symmetric`` files;
  * the AmgX header extension line ``%%AMGX``/``%%NVAMG <flags>`` carrying
    tokens like ``sorted``, ``diagonal``, ``rhs``, ``solution``,
    ``block_dimx N``/``block_dimy N`` (matrix_io.cu:93-160): when ``rhs`` /
    ``solution`` appear, the vectors follow the matrix entries in the same
    file; ``diagonal`` means external diagonal blocks follow the entries.

Parsing is vectorized (numpy over the whole body) — the ingest path must
handle SuiteSparse-scale files (tens of millions of nnz).
Returns host numpy; callers build SparseMatrix from it.
"""

from __future__ import annotations

import numpy as np

from amgx_tpu.core.matrix import SparseMatrix


class MatrixIOError(ValueError):
    pass


def _parse_header(lines):
    header = lines[0].strip().split() if lines else []
    if not header or header[0] != "%%MatrixMarket":
        raise MatrixIOError(
            f"bad MatrixMarket header: {lines[0]!r}"
            if lines
            else "empty MatrixMarket file"
        )
    if len(header) < 5:
        raise MatrixIOError(
            f"short MatrixMarket header ({len(header)} tokens): "
            f"{lines[0]!r}"
        )
    field, sym = header[3].lower(), header[4].lower()
    flags = []
    i = 1
    while i < len(lines) and lines[i].lstrip().startswith("%"):
        if lines[i].startswith(("%%AMGX", "%%NVAMG")):
            tok = lines[i].strip("%").strip().split()
            flags = tok[1:] if tok and tok[0] in ("AMGX", "NVAMG") else tok
        i += 1
    return field, sym, flags, i


def _tokens_to_floats(body_lines):
    """One pass over whitespace-separated numeric tokens (C-level parse)."""
    blob = " ".join(body_lines)
    try:
        return np.array(blob.split(), dtype=np.float64)
    except ValueError as e:
        raise MatrixIOError(
            f"non-numeric token in MatrixMarket body: {e}"
        ) from None


_NVAMG_BIN_HEADER = b"%%NVAMGBinary\n"


def _read_system_binary(path):
    """%%NVAMGBinary reader (reference matrix_io.cu:286-334 writer
    layout): header + 9 uint32 system flags, then CSR int32 offsets and
    columns and f64 values (external diagonal appended), then optional
    f64 rhs/solution."""
    import os

    file_bytes = os.path.getsize(path)
    remaining = [file_bytes - len(_NVAMG_BIN_HEADER)]

    def _take(f, dtype, count, what):
        # size gate BEFORE np.fromfile: a garbled header can claim
        # billions of entries, and attempting the read would be a
        # multi-GB allocation instead of a clean typed error
        need = int(count) * np.dtype(dtype).itemsize
        if count < 0 or need > remaining[0]:
            raise MatrixIOError(
                f"truncated %%NVAMGBinary file: {what} "
                f"({need} bytes claimed, {remaining[0]} left)"
            )
        a = np.fromfile(f, dtype, count)
        if a.shape[0] != count:
            raise MatrixIOError(
                f"truncated %%NVAMGBinary file: {what} "
                f"({a.shape[0]}/{count} read)"
            )
        remaining[0] -= need
        return a

    with open(path, "rb") as f:
        hdr = f.read(len(_NVAMG_BIN_HEADER))
        if hdr != _NVAMG_BIN_HEADER:
            raise MatrixIOError("not a %%NVAMGBinary file")
        flags = _take(f, np.uint32, 9, "system flags")
        (is_mtx, is_rhs, is_soln, mfmt, has_diag, bdx, bdy, n, nnz) = (
            int(v) for v in flags
        )
        if not is_mtx:
            raise MatrixIOError("binary file carries no matrix")
        if mfmt != 0:
            raise MatrixIOError(
                f"unsupported binary matrix format {mfmt} "
                "(CSR real only, matching the reference writer)"
            )
        bsz = bdx * bdy
        row_offsets = _take(f, np.int32, n + 1, "row offsets")
        cols = _take(f, np.int32, nnz, "column indices")
        nval = bsz * (nnz + (n if has_diag else 0))
        vals = _take(f, np.float64, nval, "values")
        # vector lengths follow the reference writer's checks
        # (matrix_io.cu:363,381: rhs n*block_dimy, solution n*block_dimx)
        rhs = (
            _take(f, np.float64, n * bdy, "rhs") if is_rhs else None
        )
        sol = (
            _take(f, np.float64, n * bdx, "solution")
            if is_soln
            else None
        )
    row_lens = np.diff(row_offsets)
    # endpoint checks run even for n == 0 (a garbled header claiming
    # n=0 with nnz>0 must not slip through as an inconsistent system)
    if (
        int(row_offsets[0]) != 0
        or int(row_offsets[-1]) != nnz
        or (row_lens < 0).any()
    ):
        # garbled index section: decodes but is not a CSR (negative
        # row lengths / offsets not summing to nnz) — typed error, not
        # a downstream numpy crash
        raise MatrixIOError(
            "garbled %%NVAMGBinary file: row offsets are not a valid "
            "CSR pointer array"
        )
    rows = np.repeat(np.arange(n, dtype=np.int64), row_lens)
    cols = cols.astype(np.int64)
    vals = vals.reshape(-1, bsz) if bsz > 1 else vals
    if has_diag:
        # trailing n diagonal blocks follow the nnz entry values
        drows = np.arange(n, dtype=np.int64)
        rows = np.concatenate([rows, drows])
        cols = np.concatenate([cols, drows])
    A = dict(
        rows=rows,
        cols=cols,
        vals=vals,
        n_rows=n,
        n_cols=n,
        block_dims=(bdx, bdy),
    )
    return A, rhs, sol


def write_system_binary(path, A: SparseMatrix, rhs=None, sol=None):
    """%%NVAMGBinary writer (reference matrix_io.cu:286-334).  Real
    CSR only — the format encodes f64 values."""
    b = A.block_size
    if np.iscomplexobj(np.asarray(A.values)) or any(
        v is not None and np.iscomplexobj(np.asarray(v))
        for v in (rhs, sol)
    ):
        raise MatrixIOError(
            "%%NVAMGBinary encodes real values only; write complex "
            "systems as MatrixMarket text"
        )
    data = np.asarray(A.values, np.float64)
    flags = np.array(
        [
            1,
            int(rhs is not None),
            int(sol is not None),
            0,  # CSR
            0,  # no external diagonal (entries carry it)
            b,
            b,
            A.n_rows,
            A.nnz,
        ],
        dtype=np.uint32,
    )
    with open(path, "wb") as f:
        f.write(_NVAMG_BIN_HEADER)
        flags.tofile(f)
        np.asarray(A.row_offsets, np.int32).tofile(f)
        np.asarray(A.col_indices, np.int32).tofile(f)
        data.reshape(-1).tofile(f)
        if rhs is not None:
            np.asarray(rhs, np.float64).reshape(-1).tofile(f)
        if sol is not None:
            np.asarray(sol, np.float64).reshape(-1).tofile(f)


def read_system(path):
    """Read matrix (+ optional external diagonal / rhs / solution).

    Returns (A_dict, rhs, sol) where A_dict has keys rows, cols, vals,
    n_rows, n_cols, block_dims.  Complex fields keep full complex values
    everywhere (entries, diagonal, rhs, solution).  %%NVAMGBinary files
    are auto-detected.
    """
    with open(path, "rb") as fb:
        if fb.read(len(_NVAMG_BIN_HEADER)) == _NVAMG_BIN_HEADER:
            return _read_system_binary(path)
    with open(path) as f:
        lines = f.read().splitlines()
    field, sym, flags, i = _parse_header(lines)

    block_dimx = block_dimy = 1
    for j, tok in enumerate(flags):
        if tok == "block_dimx":
            block_dimx = int(flags[j + 1])
        if tok == "block_dimy":
            block_dimy = int(flags[j + 1])
    has_rhs = "rhs" in flags
    has_sol = "solution" in flags
    has_ext_diag = "diagonal" in flags

    try:
        sizes = lines[i].split()
        n_rows, n_cols, nnz = int(sizes[0]), int(sizes[1]), int(sizes[2])
    except (IndexError, ValueError):
        raise MatrixIOError(
            "missing or malformed MatrixMarket size line"
        ) from None
    i += 1

    body = [
        s
        for s in (ln.strip() for ln in lines[i:])
        if s and not s.startswith("%")
    ]
    bsz = block_dimx * block_dimy
    is_complex = field == "complex"
    vdt = np.complex128 if is_complex else np.float64
    # values per entry line after the two indices
    vtok = 0 if field == "pattern" else (2 * bsz if is_complex else bsz)

    # ---- matrix entries: one vectorized parse --------------------------
    toks = _tokens_to_floats(body[:nnz])
    per_line = 2 + vtok
    if toks.shape[0] != nnz * per_line:
        raise MatrixIOError(
            f"expected {nnz} entries x {per_line} tokens, got "
            f"{toks.shape[0]} tokens"
        )
    toks = toks.reshape(nnz, per_line)
    rows = toks[:, 0].astype(np.int64) - 1
    cols = toks[:, 1].astype(np.int64) - 1
    if field == "pattern":
        vals = np.ones((nnz, bsz) if bsz > 1 else nnz, vdt)
    elif is_complex:
        c = toks[:, 2::2] + 1j * toks[:, 3::2]
        vals = c if bsz > 1 else c[:, 0]
    else:
        vals = toks[:, 2:] if bsz > 1 else toks[:, 2]
    pos = nnz

    def _read_block_lines(count, width):
        t = _tokens_to_floats(body[pos : pos + count])
        w = 2 * width if is_complex else width
        if t.shape[0] != count * w:
            raise MatrixIOError("truncated auxiliary section")
        t = t.reshape(count, w)
        if is_complex:
            t = t[:, 0::2] + 1j * t[:, 1::2]
        return t if width > 1 else t[:, 0]

    if has_ext_diag:
        dvals = _read_block_lines(n_rows, bsz)
        pos += n_rows
        drows = np.arange(n_rows, dtype=np.int64)
        rows = np.concatenate([rows, drows])
        cols = np.concatenate([cols, drows])
        vals = np.concatenate([vals, dvals])

    if sym in ("symmetric", "hermitian", "skew-symmetric"):
        off = rows != cols
        mvals = vals[off]
        if bsz > 1:
            # mirrored block is the (conjugate-)transposed block
            mvals = (
                mvals.reshape(-1, block_dimx, block_dimy)
                .transpose(0, 2, 1)
                .reshape(-1, bsz)
            )
        if sym == "hermitian":
            mvals = np.conj(mvals)
        elif sym == "skew-symmetric":
            mvals = -mvals
        rows, cols = (
            np.concatenate([rows, cols[off]]),
            np.concatenate([cols, rows[off]]),
        )
        vals = np.concatenate([vals, mvals])

    rhs = sol = None
    nb = n_rows * block_dimx
    if has_rhs:
        rhs = _read_block_lines(nb, 1)
        pos += nb
    if has_sol:
        sol = _read_block_lines(nb, 1)
        pos += nb

    A = dict(
        rows=rows,
        cols=cols,
        vals=vals,
        n_rows=n_rows,
        n_cols=n_cols,
        block_dims=(block_dimx, block_dimy),
    )
    return A, rhs, sol


def read_mtx(path, dtype=None, build_ell=True) -> SparseMatrix:
    A, _, _ = read_system(path)
    bx, by = A["block_dims"]
    if bx != by:
        raise MatrixIOError(
            f"rectangular blocks {bx}x{by} are not supported"
        )
    vals = A["vals"]
    if dtype is not None:
        vals = vals.astype(dtype)
    return SparseMatrix.from_coo(
        A["rows"],
        A["cols"],
        vals,
        n_rows=A["n_rows"],
        n_cols=A["n_cols"],
        block_size=bx,
        build_ell=build_ell,
    )


def write_system(path, A: SparseMatrix, rhs=None, sol=None):
    """Write matrix (+rhs/solution) with the %%AMGX extension header."""
    flags = ["sorted"]
    if rhs is not None:
        flags.append("rhs")
    if sol is not None:
        flags.append("solution")
    b = A.block_size
    if b > 1:
        flags += ["block_dimx", str(b), "block_dimy", str(b)]
    indptr = np.asarray(A.row_offsets)
    indices = np.asarray(A.col_indices)
    data = np.asarray(A.values)
    field = "complex" if np.iscomplexobj(data) else "real"
    with open(path, "w") as f:
        f.write(f"%%MatrixMarket matrix coordinate {field} general\n")
        f.write("%%AMGX " + " ".join(flags) + "\n")
        f.write(f"{A.n_rows} {A.n_cols} {A.nnz}\n")
        for i in range(A.n_rows):
            for p in range(indptr[i], indptr[i + 1]):
                v = data[p].reshape(-1) if b > 1 else [data[p]]
                if field == "complex":
                    vtxt = " ".join(f"{c.real:.17g} {c.imag:.17g}" for c in v)
                else:
                    vtxt = " ".join(f"{c:.17g}" for c in v)
                f.write(f"{i + 1} {indices[p] + 1} {vtxt}\n")
        for vec in (rhs, sol):
            if vec is not None:
                for v in np.asarray(vec):
                    if np.iscomplexobj(vec):
                        f.write(f"{v.real:.17g} {v.imag:.17g}\n")
                    else:
                        f.write(f"{v:.17g}\n")


def complex_to_real_system(A_dict, rhs, sol, conversion_type: int):
    """Equivalent-real-formulation (ERF) conversion of a complex system
    (reference readers.cu:221-345 ReadAndConvert, ``complex_conversion``
    config param): K1..K4 produce the 2n x 2n real system

      K1: [[ Re, -Im], [Im,  Re]]   b = [Re b; Im b]  x = [Re x;  Im x]
      K2: [[ Re,  Im], [Im, -Re]]   b = [Re b; Im b]  x = [Re x; -Im x]
      K3: [[ Im,  Re], [Re, -Im]]   b = [Im b; Re b]  x = [Re x;  Im x]
      K4: [[ Im, -Re], [Re,  Im]]   b = [Im b; Re b]  x = [Re x; -Im x]
    """
    if conversion_type not in (1, 2, 3, 4):
        raise MatrixIOError(
            f"complex_conversion={conversion_type}: expected 1..4"
        )
    import scipy.sparse as sps

    n = A_dict["n_rows"]
    C = sps.csr_matrix(
        (np.asarray(A_dict["vals"]),
         (np.asarray(A_dict["rows"]), np.asarray(A_dict["cols"]))),
        shape=(n, A_dict["n_cols"]),
    )
    Re, Im = C.real.tocsr(), C.imag.tocsr()
    blocks = {
        1: [[Re, -Im], [Im, Re]],
        2: [[Re, Im], [Im, -Re]],
        3: [[Im, Re], [Re, -Im]],
        4: [[Im, -Re], [Re, Im]],
    }[conversion_type]
    K = sps.bmat(blocks, format="coo")
    out = dict(
        rows=K.row.astype(np.int64),
        cols=K.col.astype(np.int64),
        vals=K.data,
        n_rows=2 * n,
        n_cols=2 * A_dict["n_cols"],
        block_dims=(1, 1),
    )
    b2 = x2 = None
    if rhs is not None:
        rhs = np.asarray(rhs)
        b2 = (
            np.concatenate([rhs.real, rhs.imag])
            if conversion_type in (1, 2)
            else np.concatenate([rhs.imag, rhs.real])
        )
    if sol is not None:
        sol = np.asarray(sol)
        x2 = (
            np.concatenate([sol.real, sol.imag])
            if conversion_type in (1, 3)
            else np.concatenate([sol.real, -sol.imag])
        )
    return out, b2, x2
