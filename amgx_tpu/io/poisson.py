"""Poisson stencil generators.

Reference parity: AMGX_generate_distributed_poisson_7pt (amgx_c.h:510-522),
examples/generate_poisson.cu, and the 5-pt/7-pt/27-pt generators used across
src/tests.  Host-side numpy building scipy CSR, then converted to the device
pytree; the distributed variant slices rows per partition.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sps

from amgx_tpu.core.matrix import SparseMatrix


def _poisson_1d(n):
    return sps.diags_array(
        [-np.ones(n - 1), 2.0 * np.ones(n), -np.ones(n - 1)],
        offsets=[-1, 0, 1],
        format="csr",
    )


def poisson_scipy(shape, stencil="star"):
    """Kronecker-assembled Laplacian; shape is (nx,), (nx,ny) or (nx,ny,nz).

    stencil='star' gives the 5/7-point operator; '27pt' the dense 3D brick.
    """
    dims = [int(s) for s in shape]
    if stencil == "star":
        A = None
        for axis, n in enumerate(dims):
            term = None
            for j, m in enumerate(dims):
                f = _poisson_1d(m) if j == axis else sps.eye_array(m)
                term = f if term is None else sps.kron(term, f, format="csr")
            A = term if A is None else A + term
        return A.tocsr()
    if stencil == "27pt":
        assert len(dims) == 3
        return _poisson_27pt_direct(dims)
    raise ValueError(stencil)


def _poisson_27pt_direct(dims):
    nx, ny, nz = dims

    def adj(n):
        return sps.diags_array(
            [np.ones(n - 1), np.ones(n), np.ones(n - 1)],
            offsets=[-1, 0, 1],
            format="csr",
        )

    B = sps.kron(sps.kron(adj(nx), adj(ny)), adj(nz), format="csr")
    A = (-B + sps.eye_array(nx * ny * nz) * 27.0).tocsr()
    return A


def poisson_2d_5pt(nx, ny=None, dtype=np.float64, **kw) -> SparseMatrix:
    ny = nx if ny is None else ny
    A = poisson_scipy((nx, ny)).astype(dtype)
    return SparseMatrix.from_scipy(A, **kw)


def poisson_3d_7pt(nx, ny=None, nz=None, dtype=np.float64, **kw) -> SparseMatrix:
    ny = nx if ny is None else ny
    nz = nx if nz is None else nz
    A = poisson_scipy((nx, ny, nz)).astype(dtype)
    return SparseMatrix.from_scipy(A, **kw)


def poisson_3d_27pt(nx, ny=None, nz=None, dtype=np.float64, **kw) -> SparseMatrix:
    ny = nx if ny is None else ny
    nz = nx if nz is None else nz
    A = _poisson_27pt_direct((nx, ny, nz)).astype(dtype)
    return SparseMatrix.from_scipy(A, **kw)


def poisson_rhs(n, dtype=np.float64, seed=0):
    """Deterministic smooth-ish RHS used by tests/benchmarks."""
    rng = np.random.default_rng(seed)
    return rng.standard_normal(n).astype(dtype)


def jittered_poisson_family(shape, count, seed=0, jitter=0.08):
    """``count`` SPD scipy systems sharing the Poisson sparsity pattern
    with per-system coefficient jitter, plus random RHS — the
    replace_coefficients workload the serve tests and benchmarks both
    drive.  Returns a list of (csr_matrix, rhs) pairs."""
    rng = np.random.default_rng(seed)
    base = poisson_scipy(shape).tocsr()
    n = base.shape[0]
    out = []
    for _ in range(count):
        sp = base.copy()
        sp.data = sp.data * (1.0 + jitter * rng.standard_normal(sp.nnz))
        sp = (sp + sp.T) * 0.5 + sps.eye_array(n) * 0.5
        sp = sp.tocsr()
        sp.sort_indices()
        out.append((sp, rng.standard_normal(n)))
    return out
