"""Host-side matrix partitioner (reference DistributedManager +
DistributedArranger, src/distributed/distributed_manager.cu:1040-1345:
loadDistributedMatrix partition/renumber path).

Block-row partition of a CSR matrix into N shards with owned-first local
renumbering and appended halo columns — the same local index layout the
reference builds (owned rows first, halo appended, B2L boundary maps).
All per-shard arrays are padded to identical shapes and stacked along a
leading shard axis so the solve path runs under ``shard_map`` with one
static program (SPMD).

Halo exchange contract (executed on-device, see distributed/solve.py):
  send = x_loc[send_idx]                  # B2L gather, [max_send]
  pool = lax.all_gather(send, axis)       # [N, max_send] over ICI
  halo = pool[halo_src_part, halo_src_pos]  # [max_halo]
  x_full = concat([x_loc, halo])
"""

from __future__ import annotations

import dataclasses

import numpy as np
import scipy.sparse as sps


@dataclasses.dataclass
class DistributedMatrix:
    """Stacked padded per-shard arrays (host numpy; move to device by
    feeding into jitted/shard_mapped functions)."""

    n_global: int
    n_parts: int
    rows_per_part: int  # padded uniform local row count
    # ELL storage (local columns: 0..rows-1 owned, rows.. halo slots)
    ell_cols: np.ndarray  # [N, rows, w] int32
    ell_vals: np.ndarray  # [N, rows, w]
    diag: np.ndarray  # [N, rows]
    # halo machinery
    send_idx: np.ndarray  # [N, max_send] int32 local indices to send
    halo_src_part: np.ndarray  # [N, max_halo] int32
    halo_src_pos: np.ndarray  # [N, max_halo] int32
    max_send: int = 0
    max_halo: int = 0

    def pad_vector(self, v):
        """Global vector (n_global,) -> stacked padded [N, rows]."""
        out = np.zeros((self.n_parts, self.rows_per_part), dtype=v.dtype)
        flat = out.reshape(-1)
        flat[: self.n_global] = v
        return out.reshape(self.n_parts, self.rows_per_part)

    def unpad_vector(self, vp):
        return np.asarray(vp).reshape(-1)[: self.n_global]


def partition_matrix(Asp: sps.csr_matrix, n_parts: int) -> DistributedMatrix:
    """Contiguous block-row partition with halo renumbering."""
    n = Asp.shape[0]
    rows_pp = -(-n // n_parts)  # ceil
    n_pad = rows_pp * n_parts
    if n_pad > n:
        # pad with identity rows (affect nothing: b is zero-padded)
        Asp = sps.block_diag(
            [Asp, sps.eye_array(n_pad - n, format="csr")], format="csr"
        )
    Asp = Asp.tocsr()
    Asp.sort_indices()

    parts = []
    for p in range(n_parts):
        r0, r1 = p * rows_pp, (p + 1) * rows_pp
        local = Asp[r0:r1].tocsr()
        owned = (local.indices >= r0) & (local.indices < r1)
        halo_glob = np.unique(local.indices[~owned])
        g2l = {}
        for li, g in enumerate(halo_glob):
            g2l[g] = rows_pp + li
        # remap columns
        cols = local.indices.copy()
        cols[owned] = cols[owned] - r0
        if halo_glob.size:
            cols[~owned] = np.array(
                [g2l[g] for g in local.indices[~owned]], dtype=cols.dtype
            )
        parts.append(
            dict(
                indptr=local.indptr,
                cols=cols,
                vals=local.data,
                halo_glob=halo_glob,
                r0=r0,
                r1=r1,
            )
        )

    # who sends what: for each part, the sorted union of its rows needed
    # by others = boundary list (B2L, reference create_boundary_lists)
    send_lists = [[] for _ in range(n_parts)]
    for p, part in enumerate(parts):
        for g in part["halo_glob"]:
            owner = int(g // rows_pp)
            send_lists[owner].append(int(g))
    send_sorted = []
    for p in range(n_parts):
        s = np.unique(np.array(send_lists[p], dtype=np.int64))
        send_sorted.append(s)
    max_send = max((len(s) for s in send_sorted), default=0)
    max_send = max(max_send, 1)

    # per-part recv maps: halo slot -> (owner part, position in owner's
    # send buffer)
    max_halo = max((len(p["halo_glob"]) for p in parts), default=0)
    max_halo = max(max_halo, 1)
    send_idx = np.zeros((n_parts, max_send), dtype=np.int32)
    halo_src_part = np.zeros((n_parts, max_halo), dtype=np.int32)
    halo_src_pos = np.zeros((n_parts, max_halo), dtype=np.int32)
    for p in range(n_parts):
        s = send_sorted[p]
        send_idx[p, : len(s)] = (s - p * rows_pp).astype(np.int32)
        hg = parts[p]["halo_glob"]
        for li, g in enumerate(hg):
            owner = int(g // rows_pp)
            pos = int(np.searchsorted(send_sorted[owner], g))
            halo_src_part[p, li] = owner
            halo_src_pos[p, li] = pos

    # ELL with uniform width across shards
    w = 1
    for part in parts:
        lens = np.diff(part["indptr"])
        if lens.size:
            w = max(w, int(lens.max()))
    ell_cols = np.zeros((n_parts, rows_pp, w), dtype=np.int32)
    ell_vals = np.zeros((n_parts, rows_pp, w), dtype=Asp.dtype)
    diag = np.zeros((n_parts, rows_pp), dtype=Asp.dtype)
    for p, part in enumerate(parts):
        indptr, cols, vals = part["indptr"], part["cols"], part["vals"]
        lens = np.diff(indptr)
        row_ids = np.repeat(np.arange(rows_pp), lens)
        pos = np.arange(cols.shape[0]) - indptr[row_ids].astype(np.int64)
        ell_cols[p, row_ids, pos] = cols
        ell_vals[p, row_ids, pos] = vals
        dmask = cols == row_ids
        diag[p, row_ids[dmask]] = vals[dmask]

    return DistributedMatrix(
        n_global=n,
        n_parts=n_parts,
        rows_per_part=rows_pp,
        ell_cols=ell_cols,
        ell_vals=ell_vals,
        diag=diag,
        send_idx=send_idx,
        halo_src_part=halo_src_part,
        halo_src_pos=halo_src_pos,
        max_send=max_send,
        max_halo=max_halo,
    )
