"""Host-side matrix partitioner (reference DistributedManager +
DistributedArranger, src/distributed/distributed_manager.cu:1040-1345
loadDistributedMatrix partition/renumber path, distributed_arranger.h
create_B2L/create_neighbors/create_boundary_lists).

Partitions a CSR matrix into N shards with owned-first local renumbering
and appended halo columns — the reference's local index layout.  All
per-shard arrays are padded to identical shapes and stacked along a
leading shard axis so the solve path runs under ``shard_map`` as one
static SPMD program.

Two partition shapes:
  * contiguous block rows (the reference's default partition vector)
  * px×py×pz grid slabs when the matrix is stencil-structured
    (AMGX_generate_distributed_poisson_7pt semantics, amgx_c.h:510-522)
    — owned rows of a shard are a lexicographic sub-box, so boundary
    (halo) size is O(surface), not O(volume).

Halo exchange contract (on-device, distributed/solve.py): each shard
gathers its boundary values into per-NEIGHBOR send buffers and the
exchange is one ``lax.ppermute`` per direction over ICI — comm volume
O(boundary).  Partitions whose halo graph is not a small neighbor set
fall back to the all_gather pool (comm O(N·max_send)).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np
import scipy.sparse as sps

# Maximum distinct neighbor directions before falling back to the
# all_gather pool exchange (3D face-adjacency needs 6; diagonal-coupled
# 3D stencils on a 3D process grid need up to 26).
_MAX_DIRECTIONS = 26


@dataclasses.dataclass
class DistributedMatrix:
    """Stacked padded per-shard arrays (host numpy; moved to device by
    feeding into jitted/shard_mapped functions)."""

    n_global: int
    n_parts: int
    rows_per_part: int  # padded uniform local row count
    # ELL storage (local columns: 0..rows-1 owned, rows.. halo slots).
    # Block matrices (reference BSR, multiply.cu:49-71 bsrmv dispatch)
    # append the block dims: ell_vals [N, rows, w, b, b], diag
    # [N, rows, b, b], vectors [N, rows, b] — halo exchange and the
    # partition plan operate at BLOCK-row granularity (messages carry
    # b-vectors), as the reference's distributed manager does.
    ell_cols: np.ndarray  # [N, rows, w] int32
    ell_vals: np.ndarray  # [N, rows, w] or [N, rows, w, b, b]
    diag: np.ndarray  # [N, rows] or [N, rows, b, b]
    block_size: int = 1
    # --- neighbor (ppermute) exchange: per direction d ---
    # perms[d]: list[(src, dst)] device pairs; send_idx[d]: [N, ms_d]
    # local indices to pack; each shard's halo is filled from the
    # received buffers via (halo_dir, halo_pos).
    perms: Any = None  # tuple of tuples of (src, dst)
    send_idx_d: Any = None  # tuple of [N, ms_d] int32
    halo_dir: Optional[np.ndarray] = None  # [N, max_halo] int32 (dir id)
    halo_pos: Optional[np.ndarray] = None  # [N, max_halo] int32
    # --- all_gather fallback exchange ---
    send_idx: Optional[np.ndarray] = None  # [N, max_send] int32
    halo_src_part: Optional[np.ndarray] = None  # [N, max_halo] int32
    halo_src_pos: Optional[np.ndarray] = None  # [N, max_halo] int32
    max_send: int = 0
    max_halo: int = 0
    # interior/boundary split (latency hiding, reference
    # multiply.cu:95-110): interior rows reference no halo columns, so
    # their partial product depends only on x_loc and overlaps with the
    # in-flight halo exchange.  Row masks only — the SpMV applies them
    # to the shared ELL arrays (no second operator copy, no scatter).
    int_mask: Optional[np.ndarray] = None  # [N, rows] bool
    own_mask: Optional[np.ndarray] = None  # [N, rows] bool (non-pad)
    # compacted boundary row list [N, max_nb] (pad -> rows, the spill
    # slot): the boundary pass gathers/computes/scatter-adds ONLY these
    # O(surface) rows, which (a) avoids the masked full-size second
    # pass and (b) keeps the interior partial product in a fusion with
    # NO dependence on the halo permutes, so XLA's latency-hiding
    # scheduler can overlap it with the exchange
    # (ci/check_overlap_hlo.py asserts this on the compiled HLO)
    bnd_rows: Optional[np.ndarray] = None  # [N, max_nb] int32
    # windowed-tiled ELL arrays of the INTERIOR rows (ops.pallas_well
    # layout, stacked on the shard axis): the interior pass reads only
    # x_loc, so on TPU it rides the Pallas windowed kernel while the
    # halo exchange is in flight; boundary rows stay on the XLA path.
    ell_wcols: Optional[np.ndarray] = None  # [N, nt, 8, w*128] int32
    ell_wvals: Optional[np.ndarray] = None  # [N, nt, 8, w*128]
    ell_wbase: Optional[np.ndarray] = None  # [N, nt] int32
    ell_wwidth: Optional[int] = None  # window lanes (static)
    # row ownership: owner[i] = part owning global row i;
    # local_of[i] = its local slot — identity layout for contiguous
    # partitions (owner = i // rows_per_part).
    owner: Optional[np.ndarray] = None
    local_of: Optional[np.ndarray] = None
    # number of real (non-padding) owned rows per shard
    n_owned: Optional[np.ndarray] = None
    # process grid (px, py, pz) when the slab partition was used
    proc_grid: Any = None
    # per-shard sparsity keys: the LOCALIZED pattern of each shard
    # hashed through core.matrix.sparsity_fingerprint — the same
    # content hash the serve HierarchyCache/ArtifactStore key on, so a
    # sharded hierarchy is cache-addressable exactly like a
    # single-device one (no ad-hoc hash; stable across processes)
    shard_fps: Any = None

    @property
    def uses_ppermute(self) -> bool:
        return self.perms is not None

    @property
    def fingerprint(self) -> Optional[str]:
        """Content hash of the WHOLE partitioned pattern: the shard
        fingerprints plus the layout metadata that changes the traced
        program (part count, padded rows, block size).  Two uploads of
        the same global pattern under the same partition collide; a
        different shard count is a different program and keys apart."""
        if self.shard_fps is None:
            return None
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        h.update(
            np.asarray(
                [self.n_global, self.n_parts, self.rows_per_part,
                 self.block_size],
                dtype=np.int64,
            ).tobytes()
        )
        for fp in self.shard_fps:
            h.update(str(fp).encode())
        return h.hexdigest()

    def halo_stats(self) -> dict:
        """Halo-map anatomy for telemetry and the ci gates: per-shard
        ghost-row counts, the exchange mode, neighbor-direction count,
        and the analytic bytes one halo exchange moves (the same model
        DistributedAMG.collective_stats uses per level)."""
        item = np.dtype(
            self.ell_vals.dtype
            if hasattr(self.ell_vals, "dtype") else np.float64
        ).itemsize
        bvec = max(int(self.block_size), 1)
        ghost = None
        if isinstance(self.ell_cols, np.ndarray):
            rows_pp = self.rows_per_part
            ghost = [
                int(np.unique(
                    self.ell_cols[p][self.ell_cols[p] >= rows_pp]
                ).size)
                for p in range(self.n_parts)
            ]
        if self.uses_ppermute:
            mode = "ppermute"
            directions = len(self.perms)
            exchange_bytes = sum(
                len(self.perms[d]) * int(np.asarray(s).shape[-1])
                for d, s in enumerate(self.send_idx_d)
            ) * item * bvec
        else:
            mode = "allgather"
            directions = 0
            exchange_bytes = (
                self.n_parts * int(self.max_send) * item * bvec
            )
        return dict(
            mode=mode,
            directions=directions,
            ghost_rows=ghost,
            ghost_rows_total=(
                int(sum(ghost)) if ghost is not None else None
            ),
            max_halo=int(self.max_halo),
            exchange_bytes=int(exchange_bytes),
        )

    def pad_vector(self, v):
        """Global vector (n_global*b,) -> stacked padded [N, rows[, b]].

        ``owner is None`` means contiguous-by-offset ownership (the
        per-process layout): part p owns global rows
        [offs[p], offs[p+1]) with offs = cumsum(n_owned) — correct for
        non-uniform blocks too, unlike a flat reshape."""
        b = self.block_size
        v = np.asarray(v)
        if b > 1:
            v = v.reshape(-1, b)
            out = np.zeros(
                (self.n_parts, self.rows_per_part, b), dtype=v.dtype
            )
        else:
            out = np.zeros(
                (self.n_parts, self.rows_per_part), dtype=v.dtype
            )
        if self.owner is None:
            offs = np.concatenate(
                [[0], np.cumsum(self.n_owned)]
            ).astype(np.int64)
            for p in range(self.n_parts):
                out[p, : self.n_owned[p]] = v[offs[p]: offs[p + 1]]
        else:
            out[self.owner, self.local_of] = v
        return out

    def unpad_vector(self, vp):
        vp = np.asarray(vp)
        if self.owner is None:
            flat = np.concatenate(
                [vp[p, : self.n_owned[p]] for p in range(self.n_parts)]
            )
        else:
            flat = vp[self.owner, self.local_of]
        return flat.reshape(-1) if self.block_size > 1 else flat


def pack_boundary_rows(rows_by_part, rows_pp, max_nb=None):
    """Stack per-part boundary-row index lists as [N, max_nb] int32,
    padding with the spill slot ``rows_pp`` (the boundary scatter-add
    targets a length rows_pp+1 buffer whose last slot is discarded)."""
    if max_nb is None:
        max_nb = max((len(r) for r in rows_by_part), default=0)
    max_nb = max(int(max_nb), 1)
    out = np.full((len(rows_by_part), max_nb), rows_pp, dtype=np.int32)
    for p, r in enumerate(rows_by_part):
        out[p, : len(r)] = r
    return out


def part_interior_windowed(
    part, ell_cols_p, ell_vals_p, int_mask_p, rows_pp, count
):
    """Windowed tiling (ops.pallas_well layout) of ONE shard's interior
    rows, or None when its interior columns have no bounded window.
    Interior columns are all local (< rows_pp), so the kernel gathers
    from x_loc only — it runs while the halo exchange is in flight."""
    from amgx_tpu.ops.pallas_well import build_windowed_ell

    m = int_mask_p[:, None]
    cols_p = np.where(m, ell_cols_p, 0)
    vals_p = np.where(m, ell_vals_p, 0)
    lens = np.zeros(rows_pp, dtype=np.int64)
    lens[: int(count)] = np.diff(part["indptr"])
    lens[~int_mask_p] = 0  # boundary/padding rows: no real slots
    ro = np.concatenate([[0], np.cumsum(lens)])
    return build_windowed_ell(ro, cols_p, vals_p)


def _build_interior_windowed(
    parts, ell_cols, ell_vals, int_mask, rows_pp, counts
):
    """Per-shard windowed tiling stacked on the shard axis, or None
    when any shard's interior columns have no bounded window."""
    n_parts = ell_cols.shape[0]
    per = []
    wmax_lanes = 0
    for p in range(n_parts):
        built = part_interior_windowed(
            parts[p], ell_cols[p], ell_vals[p], int_mask[p], rows_pp,
            counts[p],
        )
        if built is None:
            return None
        per.append(built)
        wmax_lanes = max(wmax_lanes, built[3])
    wcols = np.stack([b[0] for b in per])
    wvals = np.stack([b[1] for b in per])
    wbase = np.stack([b[2] for b in per])
    return wcols, wvals, wbase, int(wmax_lanes)


def tiled_ell_wanted(dtype) -> bool:
    """Whether to build windowed-tiled ELL copies for this matrix
    dtype — judged on the EFFECTIVE device dtype (f64 host arrays land
    as f32 on device when x64 is disabled, the usual TPU setting).
    Single gate for BOTH assembly paths (global partitioner and the
    multi-host one), so they cannot diverge."""
    import jax as _jax

    from amgx_tpu.core.matrix import _want_tiled_ell

    eff = np.dtype(dtype)
    if eff == np.float64 and not _jax.config.jax_enable_x64:
        eff = np.dtype(np.float32)
    return _want_tiled_ell(eff)


def part_ell_arrays(part, rows_pp, w, dtype):
    """One shard's padded ELL block + diagonal — the per-shard slice of
    the stacked arrays (bit-parity-critical: both assembly paths, the
    global partitioner and the multi-host one, fill through here).
    Block parts (``vals`` of shape (nnzb, b, b)) produce block ELL
    arrays (rows, w, b, b) and block diagonals (rows, b, b)."""
    indptr, cols, vals = part["indptr"], part["cols"], part["vals"]
    vals = np.asarray(vals)
    nr = indptr.shape[0] - 1
    bshape = vals.shape[1:]  # () scalar, (b, b) block
    ell_cols = np.zeros((rows_pp, w), dtype=np.int32)
    ell_vals = np.zeros((rows_pp, w) + bshape, dtype=dtype)
    # padding rows get unit diagonal so smoothers stay finite there
    if bshape:
        diag = np.broadcast_to(
            np.eye(bshape[0], dtype=dtype), (rows_pp,) + bshape
        ).copy()
    else:
        diag = np.ones((rows_pp,), dtype=dtype)
    diag[:nr] = 0.0
    lens = np.diff(indptr)
    row_ids = np.repeat(np.arange(nr), lens)
    pos = np.arange(cols.shape[0]) - indptr[row_ids].astype(np.int64)
    ell_cols[row_ids, pos] = cols
    ell_vals[row_ids, pos] = vals
    dmask = cols == row_ids
    diag[row_ids[dmask]] = vals[dmask]
    return ell_cols, ell_vals, diag


def grid_partition_parts(grid, n_parts):
    """Choose a process grid px*py*pz == n_parts matching the domain
    aspect (largest domain axis gets the most parts)."""
    nx, ny, nz = grid

    def factorizations(n):
        out = []
        for px in range(1, n + 1):
            if n % px:
                continue
            m = n // px
            for py in range(1, m + 1):
                if m % py:
                    continue
                out.append((px, py, m // py))
        return out

    best, best_cost = None, None
    for px, py, pz in factorizations(n_parts):
        if px > nx or py > ny or pz > nz:
            continue
        # surface-to-volume proxy: total boundary area
        sx, sy, sz = nx / px, ny / py, nz / pz
        cost = (px > 1) * sy * sz + (py > 1) * sx * sz + (pz > 1) * sx * sy
        if best is None or cost < best_cost:
            best, best_cost = (px, py, pz), cost
    return best


def partition_rows(n, n_parts, grid=None, proc_grid=None):
    """owner[i] for each global row.  Contiguous blocks by default;
    grid slabs when (nx, ny, nz) geometry is provided."""
    if grid is None:
        rows_pp = -(-n // n_parts)
        return np.minimum(
            np.arange(n, dtype=np.int64) // rows_pp, n_parts - 1
        ).astype(np.int32), None
    nx, ny, nz = grid
    if proc_grid is None:
        proc_grid = grid_partition_parts(grid, n_parts)
    if proc_grid is None:
        rows_pp = -(-n // n_parts)
        return np.minimum(
            np.arange(n, dtype=np.int64) // rows_pp, n_parts - 1
        ).astype(np.int32), None
    px, py, pz = proc_grid
    i = np.arange(n, dtype=np.int64)
    ix, iy, iz = i % nx, (i // nx) % ny, i // (nx * ny)
    # balanced slab boundaries
    bx = np.minimum(ix * px // nx, px - 1)
    by = np.minimum(iy * py // ny, py - 1)
    bz = np.minimum(iz * pz // nz, pz - 1)
    return (bx + px * (by + py * bz)).astype(np.int32), proc_grid


def gather_row_entries(indptr, rsel):
    """Entry ids of CSR rows ``rsel``, vectorized (repeat/cumsum — no
    per-row Python loop; this sits on the setup hot path)."""
    indptr = np.asarray(indptr, dtype=np.int64)
    rsel = np.asarray(rsel, dtype=np.int64)
    lens = (indptr[rsel + 1] - indptr[rsel]).astype(np.int64)
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64), lens
    starts = np.repeat(indptr[rsel], lens)
    offs = np.arange(total, dtype=np.int64) - np.repeat(
        np.concatenate([[0], np.cumsum(lens)[:-1]]), lens
    )
    return starts + offs, lens


def block_csr_arrays(Asp, block_size):
    """Scalar CSR (n*b square) -> block-row CSR arrays
    (indptr, block col indices, values (nnzb, b, b)) — the host-side
    BSR view the block partition consumes (reference block-CSR
    matrix.h:65 layout)."""
    b = int(block_size)
    bsr = sps.bsr_matrix(Asp.tocsr(), blocksize=(b, b))
    bsr.sort_indices()
    return (
        bsr.indptr.astype(np.int64),
        bsr.indices.astype(np.int64),
        np.asarray(bsr.data),
    )


def partition_matrix(
    Asp: sps.csr_matrix,
    n_parts: int,
    grid=None,
    proc_grid=None,
    owner=None,
    block_size: int = 1,
) -> DistributedMatrix:
    """Partition + owned-first renumber + halo/exchange maps.

    ``grid``/``proc_grid`` opt into the px×py×pz slab partition;
    ``owner`` supplies an arbitrary precomputed partition vector
    (reference partition-vector upload path).  ``block_size`` b > 1
    partitions at BLOCK-row granularity (reference distributed block
    path): ``Asp`` is the scalar (n*b square) matrix, ``owner``/
    ``grid`` describe block rows, and the device arrays carry b×b
    blocks.
    """
    if block_size > 1:
        indptr, bcols, bvals = block_csr_arrays(Asp, block_size)
        n = indptr.shape[0] - 1
        if owner is None:
            owner, proc_grid = partition_rows(
                n, n_parts, grid, proc_grid
            )
        else:
            owner = np.asarray(owner, dtype=np.int32)
        local_of, counts, part_rows = local_numbering(owner, n_parts)
        rows_pp = max(int(counts.max()), 1)
        parts = []
        for p in range(n_parts):
            ent, lens = gather_row_entries(indptr, part_rows[p])
            lptr = np.concatenate([[0], np.cumsum(lens)]).astype(
                np.int64
            )
            parts.append(
                localize_columns(
                    lptr, bcols[ent], bvals[ent], owner,
                    local_of, p, rows_pp,
                )
            )
        return finalize_partition(
            parts, owner, local_of, counts, n, n_parts, proc_grid
        )
    n = Asp.shape[0]
    Asp = Asp.tocsr()
    Asp.sort_indices()
    if owner is None:
        owner, proc_grid = partition_rows(n, n_parts, grid, proc_grid)
    else:
        owner = np.asarray(owner, dtype=np.int32)

    local_of, counts, part_rows = local_numbering(owner, n_parts)
    rows_pp = max(int(counts.max()), 1)
    parts = []
    for p in range(n_parts):
        local = Asp[part_rows[p]].tocsr()
        parts.append(
            localize_columns(
                local.indptr, local.indices, local.data, owner,
                local_of, p, rows_pp,
            )
        )
    return finalize_partition(
        parts, owner, local_of, counts, n, n_parts, proc_grid
    )


class Ownership:
    """Analytic row-ownership with O(n_parts) state (the per-process
    memory contract: no global-length arrays).  ``owner_of``/
    ``local_of_ids`` map global-id arrays; ``global_rows(p)`` lists one
    part's owned global ids (O(local)); ``materialize()`` builds the
    O(n_global) arrays — only for boundary conveniences on SMALL levels
    (the consolidated tail)."""

    counts: np.ndarray

    @property
    def n_parts(self) -> int:
        return int(self.counts.shape[0])

    @property
    def n_global(self) -> int:
        return int(self.counts.sum())

    def owner_of(self, ids):
        raise NotImplementedError

    def local_of_ids(self, ids):
        raise NotImplementedError

    def global_rows(self, p):
        raise NotImplementedError

    def materialize(self):
        owner = np.empty(self.n_global, dtype=np.int32)
        local_of = np.empty(self.n_global, dtype=np.int32)
        for p in range(self.n_parts):
            g = self.global_rows(p)
            owner[g] = p
            local_of[g] = np.arange(len(g), dtype=np.int32)
        return owner, local_of

    @property
    def uniform_contiguous(self) -> bool:
        return False

    @property
    def offset_blocks(self) -> bool:
        """True when part p owns exactly global rows
        [cumsum(counts)[p], cumsum(counts)[p+1]) — the layout the
        owner=None pad/unpad convention assumes."""
        return False


class OffsetOwnership(Ownership):
    """Contiguous row blocks given by part offsets (the reference's
    partition-offsets upload path, sharded_partition's shape)."""

    def __init__(self, part_offsets):
        self.part_offsets = np.asarray(part_offsets, dtype=np.int64)
        self.counts = (
            self.part_offsets[1:] - self.part_offsets[:-1]
        ).astype(np.int64)

    def owner_of(self, ids):
        return (
            np.searchsorted(
                self.part_offsets, np.asarray(ids), side="right"
            )
            - 1
        ).astype(np.int32)

    def local_of_ids(self, ids):
        ids = np.asarray(ids, dtype=np.int64)
        return (
            ids - self.part_offsets[self.owner_of(ids)]
        ).astype(np.int32)

    def global_rows(self, p):
        return np.arange(
            self.part_offsets[p], self.part_offsets[p + 1],
            dtype=np.int64,
        )

    @property
    def uniform_contiguous(self) -> bool:
        rows_pp = int(self.counts.max()) if len(self.counts) else 1
        expect = np.minimum(
            np.arange(len(self.part_offsets)) * rows_pp,
            self.part_offsets[-1],
        )
        return bool(np.array_equal(self.part_offsets, expect))

    @property
    def offset_blocks(self) -> bool:
        return True


class GridOwnership(Ownership):
    """px*py*pz slab partition of an nx*ny*nz lexicographic grid —
    ownership is computed from coordinates (O(1) state), halo size is
    O(surface).  Matches partition_rows(grid=...) numbering."""

    def __init__(self, grid, proc_grid):
        self.grid = tuple(int(v) for v in grid)
        self.proc_grid = tuple(int(v) for v in proc_grid)
        nx, ny, nz = self.grid
        px, py, pz = self.proc_grid
        # slab boundaries identical to partition_rows
        self._xb = np.searchsorted(
            np.minimum(np.arange(nx) * px // nx, px - 1),
            np.arange(px + 1), side="left",
        )
        self._yb = np.searchsorted(
            np.minimum(np.arange(ny) * py // ny, py - 1),
            np.arange(py + 1), side="left",
        )
        self._zb = np.searchsorted(
            np.minimum(np.arange(nz) * pz // nz, pz - 1),
            np.arange(pz + 1), side="left",
        )
        cx = np.diff(self._xb)
        cy = np.diff(self._yb)
        cz = np.diff(self._zb)
        self.counts = (
            cx[None, None, :] * cy[None, :, None] * cz[:, None, None]
        ).reshape(-1).astype(np.int64)

    def _coords(self, ids):
        nx, ny, _ = self.grid
        ids = np.asarray(ids, dtype=np.int64)
        return ids % nx, (ids // nx) % ny, ids // (nx * ny)

    def owner_of(self, ids):
        nx, ny, nz = self.grid
        px, py, pz = self.proc_grid
        ix, iy, iz = self._coords(ids)
        bx = np.minimum(ix * px // nx, px - 1)
        by = np.minimum(iy * py // ny, py - 1)
        bz = np.minimum(iz * pz // nz, pz - 1)
        return (bx + px * (by + py * bz)).astype(np.int32)

    def local_of_ids(self, ids):
        nx, ny, nz = self.grid
        px, py, pz = self.proc_grid
        ix, iy, iz = self._coords(ids)
        bx = np.minimum(ix * px // nx, px - 1)
        by = np.minimum(iy * py // ny, py - 1)
        bz = np.minimum(iz * pz // nz, pz - 1)
        ox, oy, oz = self._xb[bx], self._yb[by], self._zb[bz]
        sx = self._xb[bx + 1] - ox
        sy = self._yb[by + 1] - oy
        # local slot = lexicographic index within the owned sub-box
        # (matches local_numbering's global-order-preserving numbering)
        return (
            (ix - ox) + sx * ((iy - oy) + sy * (iz - oz))
        ).astype(np.int32)

    def global_rows(self, p):
        nx, ny, _ = self.grid
        px, py, _ = self.proc_grid
        bx = p % px
        by = (p // px) % py
        bz = p // (px * py)
        xs = np.arange(self._xb[bx], self._xb[bx + 1], dtype=np.int64)
        ys = np.arange(self._yb[by], self._yb[by + 1], dtype=np.int64)
        zs = np.arange(self._zb[bz], self._zb[bz + 1], dtype=np.int64)
        return (
            xs[None, None, :]
            + nx * (ys[None, :, None] + self.grid[1] * zs[:, None, None])
        ).reshape(-1)


class ArrayOwnership(Ownership):
    """Ownership from explicit owner/local_of arrays (the reference's
    arbitrary partition-vector upload).  O(n_global) state — the
    single-process compatibility shape, not the multi-host one."""

    def __init__(self, owner, local_of=None, n_parts=None):
        self.owner = np.asarray(owner, dtype=np.int32)
        n_parts = (
            int(self.owner.max()) + 1 if n_parts is None else n_parts
        )
        self.counts = np.bincount(
            self.owner, minlength=n_parts
        ).astype(np.int64)
        if local_of is None:
            local_of, _, self._part_rows = local_numbering(
                self.owner, n_parts
            )
        else:
            self._part_rows = None
        self.local_arr = np.asarray(local_of, dtype=np.int32)

    def owner_of(self, ids):
        return self.owner[np.asarray(ids)]

    def local_of_ids(self, ids):
        return self.local_arr[np.asarray(ids)]

    def global_rows(self, p):
        if self._part_rows is not None:
            return self._part_rows[p]
        return np.nonzero(self.owner == p)[0]

    def materialize(self):
        return self.owner, self.local_arr


def local_numbering(owner, n_parts):
    """(local_of, counts, part_rows): slot of each global row within its
    part (global order preserved within a part), owned-row counts, and
    the global row list per part."""
    n = owner.shape[0]
    order = np.argsort(owner, kind="stable")
    counts = np.bincount(owner, minlength=n_parts)
    local_of = np.zeros(n, dtype=np.int32)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos_in_part = np.arange(n, dtype=np.int64) - starts[owner[order]]
    local_of[order] = pos_in_part.astype(np.int32)
    part_rows = [order[starts[p]: starts[p] + counts[p]]
                 for p in range(n_parts)]
    return local_of, counts, part_rows


def halo_localize(gcols, is_owned, owned_local, rows_pp):
    """Shared halo-slot numbering (bit-parity critical: the multi-host
    per-process path must reproduce this exactly): off-owned columns
    map to ``rows_pp + position in the SORTED unique halo-id list``."""
    halo_glob = np.unique(gcols[~is_owned])
    cols = np.empty(gcols.shape, dtype=np.int32)
    cols[is_owned] = owned_local
    if halo_glob.size:
        cols[~is_owned] = (
            rows_pp + np.searchsorted(halo_glob, gcols[~is_owned])
        ).astype(np.int32)
    return cols, halo_glob


def localize_columns(indptr, gcols, vals, owner, local_of, p, rows_pp):
    """Owned-first renumbering of one shard's rows: owned columns map to
    their local slot, off-shard columns to appended halo slots
    (reference loadDistributed_LocalToGlobal/InitLocalMatrix)."""
    is_owned = owner[gcols] == p
    cols, halo_glob = halo_localize(
        gcols, is_owned, local_of[gcols[is_owned]], rows_pp
    )
    return dict(indptr=indptr, cols=cols, vals=vals, halo_glob=halo_glob)


def build_exchange_plan(halo_globs, owner_fn, local_fn, n_parts):
    """Exchange plan from each part's sorted halo-id list alone.

    ``halo_globs[p]`` is part p's ``halo_glob`` (sorted global ids it
    needs); ``owner_fn``/``local_fn`` map global-id arrays to owning
    part / local slot.  Everything here is O(total boundary) — in a
    multi-host launch the lists ride one small allgather and every
    process builds the (replicated) plan independently
    (reference distributed_arranger.h create_B2L/create_boundary_lists).

    Returns ``(dm, fallback)``: the neighbor-ppermute plan dict (or
    None) and the all_gather fallback maps dict.
    """
    # boundary (B2L) lists: rows of p needed by q, sorted by global id
    send_sorted = {}  # (src_owner, dst) -> sorted global ids
    for p, hg in enumerate(halo_globs):
        hg = np.asarray(hg, dtype=np.int64)
        if hg.size == 0:
            continue
        owners = owner_fn(hg)
        for o in np.unique(owners):
            send_sorted[(int(o), p)] = hg[owners == o]

    max_halo = max((len(h) for h in halo_globs), default=0)
    max_halo = max(max_halo, 1)

    # ---- neighbor-direction (ppermute) plan -------------------------
    # direction = the shard-index displacement function; for grid slab
    # partitions every (src, dst) pair with traffic maps to one of a
    # small set of displacements, one ppermute each.
    pairs = sorted(send_sorted.keys())
    deltas = sorted({dst - src for (src, dst) in pairs})
    dm = None
    # no pairs at all (every column local on every part — e.g. a level
    # graded onto one shard) is a valid neighbor plan with zero
    # directions, NOT an all_gather fallback
    if len(deltas) <= _MAX_DIRECTIONS:
        perms, send_idx_d = [], []
        halo_dir = np.zeros((n_parts, max_halo), dtype=np.int32)
        halo_pos = np.zeros((n_parts, max_halo), dtype=np.int32)
        for d, delta in enumerate(deltas):
            dpairs = [(s, t) for (s, t) in pairs if t - s == delta]
            ms = max(len(send_sorted[k]) for k in dpairs)
            sidx = np.zeros((n_parts, ms), dtype=np.int32)
            for (s, t) in dpairs:
                ids = send_sorted[(s, t)]
                sidx[s, : len(ids)] = local_fn(ids)
            perms.append(tuple(dpairs))
            send_idx_d.append(sidx)
            for (s, t) in dpairs:
                ids = send_sorted[(s, t)]
                hg = np.asarray(halo_globs[t], dtype=np.int64)
                mine = np.isin(hg, ids)
                li = np.nonzero(mine)[0]
                halo_dir[t, li] = d
                halo_pos[t, li] = np.searchsorted(ids, hg[mine])
        dm = dict(
            perms=tuple(perms),
            send_idx_d=tuple(send_idx_d),
            halo_dir=halo_dir,
            halo_pos=halo_pos,
        )

    # ---- all_gather fallback maps (always built: small, and used by
    # setup-side consistency checks) ----------------------------------
    send_union = [np.array([], dtype=np.int64)] * n_parts
    for (s, t), ids in send_sorted.items():
        send_union[s] = np.union1d(send_union[s], ids)
    max_send = max(max((len(s) for s in send_union), default=0), 1)
    send_idx = np.zeros((n_parts, max_send), dtype=np.int32)
    halo_src_part = np.zeros((n_parts, max_halo), dtype=np.int32)
    halo_src_pos = np.zeros((n_parts, max_halo), dtype=np.int32)
    for p in range(n_parts):
        su = send_union[p]
        if len(su):
            send_idx[p, : len(su)] = local_fn(su)
        hg = np.asarray(halo_globs[p], dtype=np.int64)
        if hg.size:
            owners = owner_fn(hg)
            halo_src_part[p, : hg.size] = owners
            halo_src_pos[p, : hg.size] = [
                int(np.searchsorted(send_union[int(o)], g))
                for o, g in zip(owners, hg)
            ]
    fallback = dict(
        send_idx=send_idx,
        halo_src_part=halo_src_part,
        halo_src_pos=halo_src_pos,
        max_send=max_send,
        max_halo=max_halo,
    )
    return dm, fallback


def finalize_partition(
    parts, owner, local_of, counts, n, n_parts, proc_grid=None,
    split=True, owner_fn=None, local_fn=None,
):
    """Build the exchange plan + stacked device arrays from per-shard
    localized CSRs (the output of localize_columns).

    ``owner``/``local_of`` may be None when ``owner_fn``/``local_fn``
    provide analytic ownership (the per-process path: no global-length
    arrays; pad/unpad then require uniform contiguous blocks)."""
    rows_pp = max(int(counts.max()), 1)
    Adtype = parts[0]["vals"].dtype if parts else np.float64
    bshape = np.asarray(parts[0]["vals"]).shape[1:] if parts else ()
    block_size = bshape[0] if bshape else 1

    # per-shard pattern keys through the serve cache's content hash
    # (core.matrix.sparsity_fingerprint) — computed here, where the
    # localized CSR indices still exist, so sharded hierarchies key
    # the HierarchyCache/ArtifactStore without an ad-hoc hash
    from amgx_tpu.core.matrix import sparsity_fingerprint

    shard_fps = tuple(
        sparsity_fingerprint(
            part["indptr"],
            part["cols"],
            part["indptr"].shape[0] - 1,
            rows_pp + len(part["halo_glob"]),
            block_size,
        )
        for part in parts
    )

    if owner_fn is None:
        owner_fn = lambda ids: owner[ids]
    if local_fn is None:
        local_fn = lambda ids: local_of[ids]
    dm, fb = build_exchange_plan(
        [p["halo_glob"] for p in parts],
        owner_fn,
        local_fn,
        n_parts,
    )
    max_send, max_halo = fb["max_send"], fb["max_halo"]
    send_idx = fb["send_idx"]
    halo_src_part = fb["halo_src_part"]
    halo_src_pos = fb["halo_src_pos"]

    # ---- ELL with uniform width across shards -----------------------
    w = 1
    for part in parts:
        lens = np.diff(part["indptr"])
        if lens.size:
            w = max(w, int(lens.max()))
    ell_cols = np.zeros((n_parts, rows_pp, w), dtype=np.int32)
    ell_vals = np.zeros((n_parts, rows_pp, w) + bshape, dtype=Adtype)
    diag = np.zeros((n_parts, rows_pp) + bshape, dtype=Adtype)
    for p, part in enumerate(parts):
        ell_cols[p], ell_vals[p], diag[p] = part_ell_arrays(
            part, rows_pp, w, Adtype
        )

    # ---- interior/boundary split masks (latency hiding) -------------
    # rows whose every stored column is local (< rows_pp) are interior
    int_mask = own_mask = bnd_rows = None
    if split:
        is_bnd = (ell_cols >= rows_pp).any(axis=2)  # [N, rows]
        own_mask = np.zeros((n_parts, rows_pp), dtype=bool)
        for p in range(n_parts):
            own_mask[p, : counts[p]] = True
        int_mask = own_mask & ~is_bnd
        bnd_rows = pack_boundary_rows(
            [np.nonzero(own_mask[p] & is_bnd[p])[0]
             for p in range(n_parts)],
            rows_pp,
        )

    # ---- Pallas windowed tiling of the interior rows (TPU) ----------
    wcols = wvals = wbase = None
    wwidth = None
    if (
        int_mask is not None
        and block_size == 1
        and tiled_ell_wanted(Adtype)
    ):
        built = _build_interior_windowed(
            parts, ell_cols, ell_vals, int_mask, rows_pp, counts
        )
        if built is not None:
            wcols, wvals, wbase, wwidth = built

    return DistributedMatrix(
        n_global=n,
        n_parts=n_parts,
        rows_per_part=rows_pp,
        ell_cols=ell_cols,
        ell_vals=ell_vals,
        diag=diag,
        block_size=block_size,
        int_mask=int_mask,
        own_mask=own_mask,
        bnd_rows=bnd_rows,
        ell_wcols=wcols,
        ell_wvals=wvals,
        ell_wbase=wbase,
        ell_wwidth=wwidth,
        perms=None if dm is None else dm["perms"],
        send_idx_d=None if dm is None else dm["send_idx_d"],
        halo_dir=None if dm is None else dm["halo_dir"],
        halo_pos=None if dm is None else dm["halo_pos"],
        send_idx=send_idx,
        halo_src_part=halo_src_part,
        halo_src_pos=halo_src_pos,
        max_send=max_send,
        max_halo=max_halo,
        owner=owner,
        local_of=local_of,
        n_owned=counts.astype(np.int32),
        proc_grid=proc_grid,
        shard_fps=shard_fps,
    )
