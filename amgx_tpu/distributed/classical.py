"""Distributed classical (Ruge-Stuben) AMG setup.

Reference parity: the distributed classical path —
classical_amg_level.cu:297-318 (computeAOperator_distributed + RAP halo
renumber), distributed_arranger.h:58-210 (exchange_halo_rows_P,
exchange_RAP_ext, create_rings), selectors/pmis.cu (distributed PMIS
with boundary exchanges), interpolators/distance1.cu.

Per-process shape: each part holds its owned rows + one-ring halo ids
only; every cross-part byte rides the :mod:`amgx_tpu.distributed.comm`
fabric.  The reference's TWO-RING halo (B2L_rings=2,
distributed_manager.h:260-310) exists to give each rank the row
structure of its one-ring nodes; here the same information content
moves as three targeted exchanges instead of a second structural ring:

  * reverse strong edges: part q tells owner(i) about its strong
    entries S[j, i] into halo column i (one O(boundary) round) — this
    is what the transpose-degree PMIS weights and the symmetrized
    PMIS neighborhood need from ring 2;
  * per-round ghost state fetches: PMIS runs SYNCHRONOUSLY — each
    round fetches the (weight, state) of ghost nodes, updates owned
    states with the serial update rule, re-fetches, and marks F
    points; with deterministic hash weights on global ids the
    selection is IDENTICAL to the serial pmis_select;
  * halo P-rows: owners ship the interpolation rows of requested
    one-ring fine nodes with global coarse columns (reference
    exchange_halo_rows_P) for the Galerkin product.

Interpolation is D1 (row-local given ghost C/F flags and coarse ids)
or D2/standard (reference interpolators/distance2.cu — the halo F
rows' strong-C and sign-restricted F->C data ride one further
targeted exchange, `_d2_rows_payload`).  The partial RAP rows for
remote coarse points ship to
their owners and are sparse-added in part order (exchange_RAP_ext +
csr_RAP_sparse_add).  Unlike the aggregation path, P couples shards,
so the solve-side transfers communicate: prolongation does a coarse
halo exchange, restriction a reverse (accumulating) exchange — see
distributed/solve.py exchange_halo_reverse.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import scipy.sparse as sps

from amgx_tpu.amg.classical import (
    strength_ahat,
    strong_entry_flags,
    truncate_interp,
)
from amgx_tpu.distributed.comm import LoopbackComm, fetch_by_owner
from amgx_tpu.distributed.hierarchy import (
    _stop_rows,
    DistHierarchy,
    DistLevel,
    _finalize_level,
    _stack_level_blocks,
    finish_distributed_hierarchy,
    init_lvl_parts,
    lvl_parts_to_parts,
)
from amgx_tpu.distributed.partition import (
    OffsetOwnership,
    Ownership,
    halo_localize,
)

_PMIS_MAX_ROUNDS = 200  # serial pmis_select cap


def _hash_at(ids, seed: int = 0) -> np.ndarray:
    """The serial _hash_weights formula evaluated at specific global
    ids (O(len(ids)), not O(n_global)) — bit-identical to
    amg.classical._hash_weights(n, seed)[ids], which is what makes the
    distributed PMIS selection identical to the serial one."""
    idx = np.asarray(ids, dtype=np.uint64)
    z = (idx + np.uint64(seed)) * np.uint64(0xBF58476D1CE4E5B9)
    z ^= z >> np.uint64(31)
    z *= np.uint64(0x94D049BB133111EB)
    z ^= z >> np.uint64(29)
    return (z % np.uint64(1 << 30)).astype(np.float64) / float(1 << 30)


def _part_strength(A_local: sps.csr_matrix, counts_p: int, theta,
                   max_row_sum) -> sps.csr_matrix:
    """Strength mask of one part's owned rows (row-local computation:
    AHAT thresholds depend only on the row itself, so the per-part
    result equals the corresponding rows of the global mask)."""
    return strength_ahat(A_local, theta, max_row_sum)


def _pmis_distributed(
    lvl_parts, lvl_own: Ownership, comm, my_parts, S_parts,
    rows_pp: int, seed: int = 0,
):
    """Synchronous distributed PMIS — identical selection to the serial
    pmis_select (same weights, same update schedule).

    Returns ``cf[p]`` (int8 per owned row, 1=C) per part.
    """
    counts = lvl_own.counts

    # ---- reverse strong edges: tell owners about S[j, ghost] -------
    # outbox[(q, o)] = (targets_global, sources_global) for q's strong
    # entries into halo columns owned by o
    outbox = {}
    for p in my_parts:
        S = S_parts[p]
        hg = lvl_parts[p]["halo_glob"]
        if not len(hg):
            continue
        coo = S.tocoo()
        hal = coo.col >= rows_pp
        if not hal.any():
            continue
        tgt_glob = hg[coo.col[hal] - rows_pp]
        src_glob = lvl_own.global_rows(p)[coo.row[hal]]
        owners = lvl_own.owner_of(tgt_glob)
        for o in np.unique(owners):
            m = owners == o
            outbox[(p, int(o))] = (tgt_glob[m], src_glob[m])
    inbox = comm.alltoall(outbox, kind="rev-edges")
    # rev_edges[p]: (tgt_local, src_global) arrays
    rev_edges: Dict[int, tuple] = {}
    for (src_p, o), (tgt, src) in sorted(inbox.items()):
        tl = lvl_own.local_of_ids(tgt)
        if o in rev_edges:
            a, b = rev_edges[o]
            rev_edges[o] = (
                np.concatenate([a, tl]), np.concatenate([b, src])
            )
        else:
            rev_edges[o] = (tl, src)

    # ---- transpose-degree weights ----------------------------------
    # lam[i] = |S^T_i| = local strong rows into i + reverse edges
    lam = {}
    for p in my_parts:
        S = S_parts[p]
        nl = int(counts[p])
        loc = np.zeros(nl, dtype=np.int64)
        coo = S.tocoo()
        own_c = coo.col < nl
        np.add.at(loc, coo.col[own_c], 1)
        if p in rev_edges:
            np.add.at(loc, rev_edges[p][0], 1)
        lam[p] = loc
    # identical weights to serial pmis_select: lam + hash(global id)
    w = {
        p: lam[p] + _hash_at(lvl_own.global_rows(p), seed=seed)
        for p in my_parts
    }

    # ---- ghost lists: halo ids + reverse-edge sources --------------
    ghosts = {}
    for p in my_parts:
        ids = [np.asarray(lvl_parts[p]["halo_glob"], dtype=np.int64)]
        if p in rev_edges:
            ids.append(rev_edges[p][1])
        g = np.unique(np.concatenate(ids)) if ids else np.zeros(0, int)
        ghosts[p] = g

    # fetch ghost weights once (static)
    reqs = {}
    for p in my_parts:
        g = ghosts[p]
        if not len(g):
            continue
        owners = lvl_own.owner_of(g)
        reqs[p] = {int(o): g[owners == o] for o in np.unique(owners)}
    w_ans = fetch_by_owner(
        comm, reqs,
        lambda o, ids: w[o][lvl_own.local_of_ids(ids)],
        kind="pmis-w",
    )
    gw = {}
    for p in my_parts:
        g = ghosts[p]
        vals = np.zeros(len(g))
        owners = lvl_own.owner_of(g) if len(g) else np.zeros(0, int)
        for o, v in w_ans.get(p, {}).items():
            vals[owners == o] = v
        gw[p] = vals

    # ---- per-part neighbor tables (owned-row index, neighbor) ------
    # neighbor encoded as: >=0 owned local id; <0 -> ghost slot -1-g
    nbr = {}
    for p in my_parts:
        S = S_parts[p]
        nl = int(counts[p])
        hg = np.asarray(lvl_parts[p]["halo_glob"], dtype=np.int64)
        coo = S.tocoo()
        codes = coo.col.astype(np.int64).copy()
        hal = coo.col >= rows_pp
        if hal.any():
            gl = hg[coo.col[hal] - rows_pp]
            codes[hal] = -1 - np.searchsorted(ghosts[p], gl)
        rows = [coo.row.astype(np.int64)]
        cols = [codes]
        # intra-part transpose edges: serial PMIS runs on the
        # SYMMETRIZED graph (S + S^T), so an asymmetric strong entry
        # S[i, j] must also give owned j its edge back to i
        own_c = coo.col < nl
        if own_c.any():
            rows.append(coo.col[own_c].astype(np.int64))
            cols.append(coo.row[own_c].astype(np.int64))
        # reverse edges: sources are always remote rows -> ghost slots
        if p in rev_edges:
            tl, srcg = rev_edges[p]
            rows.append(tl.astype(np.int64))
            cols.append(-1 - np.searchsorted(ghosts[p], srcg))
        nbr[p] = (np.concatenate(rows), np.concatenate(cols))

    # ---- synchronous rounds ----------------------------------------
    state = {p: np.zeros(int(counts[p]), dtype=np.int8)
             for p in my_parts}
    # isolated (no strong neighbors either direction) -> C
    for p in my_parts:
        deg = np.zeros(int(counts[p]), dtype=np.int64)
        np.add.at(deg, nbr[p][0], 1)
        state[p][deg == 0] = 1

    def ghost_states(round_tag):
        ans = fetch_by_owner(
            comm, reqs,
            lambda o, ids: state[o][lvl_own.local_of_ids(ids)],
            kind=f"pmis-st{round_tag}",
        )
        gs = {}
        for p in my_parts:
            g = ghosts[p]
            vals = np.zeros(len(g), dtype=np.int8)
            owners = (
                lvl_own.owner_of(g) if len(g) else np.zeros(0, int)
            )
            for o, v in ans.get(p, {}).items():
                vals[owners == o] = v
            gs[p] = vals
        return gs

    for rnd in range(_PMIS_MAX_ROUNDS):
        # symmetric termination check — every process enters the round
        total_und = int(np.sum(comm.allgather(
            {p: int((state[p] == 0).sum()) for p in my_parts},
            kind="pmis-und",
        )))
        if total_und == 0:
            break
        gs = ghost_states(2 * rnd)
        for p in my_parts:
            rowi, code = nbr[p]
            st = state[p]
            und = st == 0
            wu_own = np.where(und, w[p], -1.0)
            wu_g = np.where(gs[p] == 0, gw[p], -1.0)
            isg = code < 0
            nb_w = np.empty(len(code))
            nb_w[isg] = wu_g[-1 - code[isg]]
            nb_w[~isg] = wu_own[code[~isg]]
            nbmax = np.full(int(counts[p]), -1.0)
            np.maximum.at(nbmax, rowi, nb_w)
            new_c = und & (wu_own > nbmax)
            st[new_c] = 1
        gs = ghost_states(2 * rnd + 1)
        for p in my_parts:
            rowi, code = nbr[p]
            st = state[p]
            isg = code < 0
            nb_c = np.empty(len(code), dtype=bool)
            nb_c[isg] = gs[p][-1 - code[isg]] == 1
            nb_c[~isg] = st[code[~isg]] == 1
            has_c = np.zeros(int(counts[p]), dtype=bool)
            np.logical_or.at(has_c, rowi, nb_c)
            st[(st == 0) & has_c] = -1
    for p in my_parts:
        state[p][state[p] == 0] = 1  # leftovers become C
    return {p: (state[p] == 1).astype(np.int8) for p in my_parts}



def _coarse_numbering_and_colinfo(
    cf, lvl_parts, lvl_own: Ownership, comm, my_parts, rows_pp: int,
    kind_prefix: str = "",
):
    """Shared coarse-numbering + halo-column-info stage (owners number
    their C points; ghost C/F flags + global coarse ids ride one
    targeted exchange).  Used by the main setup loop and the
    aggressive stage-2 refine — ONE copy of the numbering/halo
    assembly logic.

    Returns (ncs, coffsets, own_c, gcid, reqs, colinfo)."""
    counts = lvl_own.counts
    ncs = np.asarray(
        comm.allgather(
            {p: int(cf[p].sum()) for p in my_parts},
            kind=kind_prefix + "coarse-counts",
        ),
        dtype=np.int64,
    )
    coffsets = np.concatenate([[0], np.cumsum(ncs)])
    own_c = OffsetOwnership(coffsets)
    gcid = {}
    for p in my_parts:
        g = np.full(int(counts[p]), -1, dtype=np.int64)
        cm = np.cumsum(cf[p]) - 1
        sel = cf[p] == 1
        g[sel] = coffsets[p] + cm[sel]
        gcid[p] = g
    reqs = {}
    for p in my_parts:
        hg = lvl_parts[p]["halo_glob"]
        if not len(hg):
            continue
        owners = lvl_own.owner_of(hg)
        reqs[p] = {
            int(o): hg[owners == o] for o in np.unique(owners)
        }
    ans = fetch_by_owner(
        comm, reqs,
        lambda o, ids: np.stack([
            cf[o][lvl_own.local_of_ids(ids)].astype(np.int64),
            gcid[o][lvl_own.local_of_ids(ids)],
        ]),
        kind=kind_prefix + "halo-cf",
    )
    colinfo = {}
    for p in my_parts:
        nloc = lvl_parts[p]["A"].shape[1]
        cf_col = np.zeros(nloc, dtype=np.int8)
        gc_col = np.full(nloc, -1, dtype=np.int64)
        cf_col[: int(counts[p])] = cf[p]
        gc_col[: int(counts[p])] = gcid[p]
        hg = lvl_parts[p]["halo_glob"]
        if len(hg):
            owners = lvl_own.owner_of(hg)
            cfh = np.zeros(len(hg), dtype=np.int8)
            gch = np.full(len(hg), -1, dtype=np.int64)
            for o, v in ans.get(p, {}).items():
                m = owners == o
                cfh[m] = v[0].astype(np.int8)
                gch[m] = v[1]
            cf_col[rows_pp: rows_pp + len(hg)] = cfh
            gc_col[rows_pp: rows_pp + len(hg)] = gch
        colinfo[p] = (cf_col, gc_col)
    return ncs, coffsets, own_c, gcid, reqs, colinfo


def _aggressive_pmis_refine(
    lvl_parts, lvl_own: Ownership, comm, my_parts, S_parts, cf1,
    rows_pp: int,
):
    """Distributed two-stage aggressive coarsening, stage 2 (reference
    selectors AGGRESSIVE_PMIS; serial ``aggressive_pmis_select``):
    PMIS with seed 1 among the stage-1 C points on the distance-2
    strength graph S ∪ S·S, restricted to C x C with the diagonal
    dropped.  The C-subgraph is built per part — distance-2 paths
    through halo midpoints ride one targeted exchange that ships each
    halo node's strong->C(stage-1) targets in stage-1-compacted global
    coarse ids, so the stage-2 hash weights (and hence the selection)
    are identical to the serial refine on contiguous partitions.

    Returns cf_final[p] (int8 per owned row, 1 = C).
    """
    counts = lvl_own.counts
    # stage-1 compacted coarse numbering + ghost C/F info (shared
    # helper — one copy of the numbering/halo assembly logic)
    ncs1, coffsets1, own_c1, gcid1, reqs, colinfo = (
        _coarse_numbering_and_colinfo(
            cf1, lvl_parts, lvl_own, comm, my_parts, rows_pp,
            kind_prefix="agg2-",
        )
    )

    # strong->C(stage-1) targets of each owned row, as compacted gcids
    def strongC_row_targets(o, li):
        S = S_parts[o].tocsr()
        cf_col_o, gc_col_o = colinfo[o]
        sub = S[li].tocoo()
        m = cf_col_o[sub.col] == 1
        tgts = gc_col_o[sub.col[m]]
        iptr = np.concatenate(
            [[0], np.cumsum(np.bincount(sub.row[m], minlength=len(li)))]
        ).astype(np.int64)
        return iptr, tgts

    # fetch halo nodes' strong->C targets (the distance-2 midpoint ring)
    ans2 = fetch_by_owner(
        comm, reqs,
        lambda o, ids: strongC_row_targets(
            o, lvl_own.local_of_ids(ids)),
        kind="agg2-halo-s2",
    )

    # build the per-part C-subgraph (global coarse ids) with sparse
    # algebra: M maps every local slot to its strong->C target gcids
    # (owned rows from S, halo slots from the fetched payloads); then
    # Sc rows = M[C rows] ∪ (B[C rows] @ M) — the vectorized form of
    # the serial Sb + Sb@Sb restricted to C x C
    Sc_parts = {}
    pseudo_parts = {}
    rows_pp_c = max(int(ncs1.max()), 1)
    for p in my_parts:
        S = S_parts[p].tocsr()
        cf_col, gc_col = colinfo[p]
        hg = lvl_parts[p]["halo_glob"]
        nloc = S.shape[1]
        nr = int(counts[p])
        # M: (nloc x nc_global) strong->C map in gcid columns
        m_rows = [np.repeat(np.arange(nr, dtype=np.int64),
                            np.diff(S.indptr))]
        m_cols = [S.indices.astype(np.int64)]
        keep0 = cf_col[m_cols[0]] == 1
        m_rows[0] = m_rows[0][keep0]
        m_cols[0] = gc_col[m_cols[0][keep0]]
        if len(hg):
            owners = lvl_own.owner_of(hg)
            for o, (iptr, tgts) in ans2.get(p, {}).items():
                ids = reqs[p][o]
                slots = rows_pp + np.searchsorted(hg, ids)
                lens = np.diff(iptr)
                m_rows.append(np.repeat(
                    slots.astype(np.int64), lens))
                m_cols.append(np.asarray(tgts, dtype=np.int64))
        mr = np.concatenate(m_rows)
        mc = np.concatenate(m_cols)
        nc_glob = int(coffsets1[-1])
        M = sps.csr_matrix(
            (np.ones(len(mr), dtype=np.int8), (mr, mc)),
            shape=(nloc, max(nc_glob, 1)),
        )
        c_rows_loc = np.nonzero(cf1[p] == 1)[0]
        B = S[c_rows_loc].astype(bool).astype(np.int8)
        Sc_g = (M[c_rows_loc] + B @ M).tocsr()  # (nc_p x nc_global)
        Sc_g.sum_duplicates()
        # drop the diagonal (own coarse id)
        coo = Sc_g.tocoo()
        own_id = gcid1[p][c_rows_loc]
        keep = coo.col != own_id[coo.row]
        er = coo.row[keep].astype(np.int64)
        ec = coo.col[keep].astype(np.int64)
        is_owned = own_c1.owner_of(ec) == p if len(ec) else \
            np.zeros(0, bool)
        cols_loc, halo_c = halo_localize(
            ec, is_owned,
            own_c1.local_of_ids(ec[is_owned]) if len(ec) else
            np.zeros(0, np.int64),
            rows_pp_c,
        )
        nloc_c = rows_pp_c + len(halo_c)
        iptr = np.concatenate(
            [[0], np.cumsum(np.bincount(
                er, minlength=int(ncs1[p])))]
        ).astype(np.int64)
        order = np.argsort(er, kind="stable")
        Sc_parts[p] = sps.csr_matrix(
            (np.ones(len(ec), dtype=np.int8), cols_loc[order], iptr),
            shape=(int(ncs1[p]), nloc_c),
        )
        pseudo_parts[p] = dict(A=Sc_parts[p], halo_glob=halo_c)

    cf2 = _pmis_distributed(
        pseudo_parts, own_c1, comm, my_parts, Sc_parts, rows_pp_c,
        seed=1,
    )
    out = {}
    for p in my_parts:
        cf = np.zeros(int(counts[p]), dtype=np.int8)
        c_rows_loc = np.nonzero(cf1[p] == 1)[0]
        cf[c_rows_loc[cf2[p] == 1]] = 1
        out[p] = cf
    return out


def _direct_interpolation_local(
    A_local: sps.csr_matrix, S_local: sps.csr_matrix, counts_p: int,
    cf_row: np.ndarray, cf_col: np.ndarray, gc_col: np.ndarray,
) -> sps.csr_matrix:
    """D1 interpolation of one part's owned rows (reference
    interpolators/distance1.cu; the serial direct_interpolation with
    split row/column index spaces).

    ``cf_col``/``gc_col`` give C/F flag and GLOBAL coarse id per LOCAL
    column (owned slots + halo slots).  Returns csr (counts_p x
    nc_global-shaped columns as global coarse ids via gc_col).
    """
    indptr, indices, data = (
        A_local.indptr, A_local.indices, A_local.data,
    )
    nr = counts_p
    row_ids = np.repeat(np.arange(nr), np.diff(indptr))
    offd = indices != row_ids

    # strong flag per A entry: S shares A's row structure only where
    # entries survived (chunked searchsorted — strong_entry_flags)
    strong_flag = strong_entry_flags(A_local, S_local)

    is_C_col = cf_col[indices] == 1
    neg = data < 0
    pos = offd & (data > 0)

    sum_neg = np.zeros(nr)
    np.add.at(sum_neg, row_ids, np.where(offd & neg, data, 0.0))
    sum_pos = np.zeros(nr)
    np.add.at(sum_pos, row_ids, np.where(pos, data, 0.0))
    strongC = strong_flag & is_C_col
    sum_negC = np.zeros(nr)
    np.add.at(sum_negC, row_ids, np.where(strongC & neg, data, 0.0))
    sum_posC = np.zeros(nr)
    np.add.at(sum_posC, row_ids, np.where(strongC & pos, data, 0.0))

    diag = A_local.diagonal().astype(np.float64).copy()
    no_posC = sum_posC == 0
    diag = diag + np.where(no_posC, sum_pos, 0.0)
    with np.errstate(divide="ignore", invalid="ignore"):
        alpha = np.where(sum_negC != 0, sum_neg / sum_negC, 0.0)
        beta = np.where(sum_posC != 0, sum_pos / sum_posC, 0.0)
    diag = np.where(diag != 0, diag, 1.0)

    keep = strongC & (cf_row[row_ids] == 0)
    coef = np.where(data < 0, alpha[row_ids], beta[row_ids])
    pvals = -coef * data / diag[row_ids]
    rows_f = row_ids[keep]
    cols_f = gc_col[indices[keep]]
    vals_f = pvals[keep]

    rows_c = np.nonzero(cf_row == 1)[0]
    cols_c = gc_col[rows_c]
    vals_c = np.ones(rows_c.shape[0])

    rows = np.concatenate([rows_f, rows_c])
    gcols = np.concatenate([cols_f, cols_c]).astype(np.int64)
    vals = np.concatenate([vals_f, vals_c])
    # compact to the part's coarse-column set; caller re-expands
    ucols, inv = np.unique(gcols, return_inverse=True)
    P = sps.csr_matrix(
        (vals, (rows, inv)), shape=(nr, max(len(ucols), 1))
    )
    P.sum_duplicates()
    P.sort_indices()
    return P, ucols


def _d2_rows_payload(A_o, S_o, li, colinfo_o):
    """Owner-side D2 payload for requested local rows ``li``: each
    row's strong->C entries and sign-restricted (all) F->C entries,
    both in GLOBAL coarse ids (reference exchange of one-ring row
    structure feeding distance2.cu).  Rows are CSR-packed:
    (sc_indptr, sc_gc, sc_v, ng_indptr, ng_gc, ng_v)."""
    cf_col, gc_col = colinfo_o
    nli = len(li)
    A_sub = A_o[li].tocsr()
    S_sub = S_o[li].astype(bool)
    diag_sub = np.asarray(A_o.diagonal())[li]

    sc = A_sub.multiply(S_sub).tocoo()
    m = cf_col[sc.col] == 1
    sc_rows, sc_gc, sc_v = sc.row[m], gc_col[sc.col[m]], sc.data[m]
    sc_indptr = np.concatenate(
        [[0], np.cumsum(np.bincount(sc_rows, minlength=nli))]
    ).astype(np.int64)

    ac = A_sub.tocoo()
    mm = (cf_col[ac.col] == 1) & (ac.data * diag_sub[ac.row] < 0)
    ng_rows, ng_gc, ng_v = ac.row[mm], gc_col[ac.col[mm]], ac.data[mm]
    ng_indptr = np.concatenate(
        [[0], np.cumsum(np.bincount(ng_rows, minlength=nli))]
    ).astype(np.int64)
    return (sc_indptr, sc_gc, sc_v, ng_indptr, ng_gc, ng_v)


def _collect_d2_rows(halo_glob, cf_col, rows_pp, lvl_own, answers):
    """Requester-side reassembly of the D2 payloads: {halo slot ->
    (gc_ids, vals)} for strong-C rows (d2_sc) and sign-restricted
    F->C rows (d2_ng) of the part's F halo nodes."""
    d2_sc, d2_ng = {}, {}
    if not len(halo_glob):
        return d2_sc, d2_ng
    fh_mask = cf_col[rows_pp: rows_pp + len(halo_glob)] == 0
    fh = halo_glob[fh_mask]
    if not len(fh):
        return d2_sc, d2_ng
    owners = lvl_own.owner_of(fh)
    for o, (sc_ip, sc_gc, sc_v, ng_ip, ng_gc, ng_v) in (
        answers.items()
    ):
        ids = fh[owners == o]  # request order (fetch_by_owner aligns)
        for k, g in enumerate(ids):
            slot = rows_pp + int(
                np.searchsorted(halo_glob, g)
            )
            d2_sc[slot] = (
                sc_gc[sc_ip[k]: sc_ip[k + 1]],
                sc_v[sc_ip[k]: sc_ip[k + 1]],
            )
            d2_ng[slot] = (
                ng_gc[ng_ip[k]: ng_ip[k + 1]],
                ng_v[ng_ip[k]: ng_ip[k + 1]],
            )
    return d2_sc, d2_ng


def _multipass_interpolation_distributed(
    lvl_parts, lvl_own, comm, my_parts, S_parts, cf, colinfo,
    counts, rows_pp, max_passes=10,
):
    """Distributed MULTIPASS interpolation (reference
    interpolators/multipass.cu, 2557 LoC; replaces the round-4 D1
    fallback — VERDICT r4 #7).

    Pass-synchronized: in pass k every part's ready owned F rows
    (>= 1 strong neighbour already assigned, locally or in the halo)
    interpolate through their neighbours' P rows,

        P_i = -(1/atil_i) * sum_{j strong, assigned} a_ij P_j,
        atil_i = a_ii + (row_total_i - strong_assigned_sum_i),

    with halo assigned-flags and halo P rows riding one targeted
    exchange per pass (the ``_d2_rows_payload`` fabric).  The pass
    structure and arithmetic match the serial
    ``multipass_interpolation``, so the distributed Galerkin product
    equals the serial one to roundoff.  Every part executes the same
    number of comm rounds (ready-count consensus per pass — SPMD).

    Returns {p: (P csr compact, ucols)} like the D1/D2 builders.
    """
    # per-part constant data
    st = {}
    for p in my_parts:
        A_l = lvl_parts[p]["A"].tocsr()
        S_l = S_parts[p]
        nr = int(counts[p])
        ncol = A_l.shape[1]
        row_ids = np.repeat(np.arange(nr), np.diff(A_l.indptr))
        offd = A_l.indices != row_ids
        strong = strong_entry_flags(A_l, S_l) & offd
        diag = np.asarray(A_l.diagonal())[:nr]
        row_total = np.zeros(nr)
        np.add.at(row_total, row_ids,
                  np.where(offd, A_l.data, 0.0))
        st[p] = dict(
            A=A_l, nr=nr, ncol=ncol, row_ids=row_ids, strong=strong,
            diag=diag, row_total=row_total,
            assigned_col=np.zeros(ncol, dtype=bool),
            # owned P rows: global-coarse-id -> value lists per row
            P_rows={}, hcache={},
        )
        cf_col, gc_col = colinfo[p]
        st[p]["gc_col"] = gc_col
        st[p]["assigned_col"][:nr] = cf[p] == 1
        # halo C points are assigned identity rows, known locally
        hg = lvl_parts[p]["halo_glob"]
        for h in range(len(hg)):
            slot = rows_pp + h
            if cf_col[slot] == 1:
                st[p]["assigned_col"][
                    min(slot, ncol - 1)] = True
                st[p]["hcache"][slot] = (
                    np.asarray([gc_col[slot]], dtype=np.int64),
                    np.asarray([1.0]),
                )
        for i in np.nonzero(cf[p] == 1)[0]:
            st[p]["P_rows"][int(i)] = (
                np.asarray([st[p]["gc_col"][i]], dtype=np.int64),
                np.asarray([1.0]),
            )

    def p_row_payload(o, ids):
        """Owner-side: CSR-packed current P rows of owned fine ids."""
        li = lvl_own.local_of_ids(ids)
        lens = np.zeros(len(li) + 1, dtype=np.int64)
        gcs, vls = [], []
        for k, i in enumerate(li):
            row = st[o]["P_rows"].get(int(i))
            if row is not None:
                lens[k + 1] = len(row[0])
                gcs.append(row[0])
                vls.append(row[1])
        iptr = np.cumsum(lens)
        return (
            iptr,
            np.concatenate(gcs) if gcs else np.zeros(0, np.int64),
            np.concatenate(vls) if vls else np.zeros(0),
        )

    for _pass in range(max_passes):
        # 1. refresh halo assigned flags (assignments from last pass)
        reqs_f = {}
        for p in my_parts:
            hg = lvl_parts[p]["halo_glob"]
            if not len(hg):
                continue
            owners = lvl_own.owner_of(hg)
            reqs_f[p] = {
                int(o): hg[owners == o] for o in np.unique(owners)
            }
        own_assigned = {
            p: st[p]["assigned_col"][: st[p]["nr"]] for p in my_parts
        }
        ans_f = fetch_by_owner(
            comm, reqs_f,
            lambda o, ids: own_assigned[o][
                lvl_own.local_of_ids(ids)].astype(np.int8),
            kind=f"mp-assigned-{_pass}",
        )
        for p in my_parts:
            hg = lvl_parts[p]["halo_glob"]
            if not len(hg):
                continue
            owners = lvl_own.owner_of(hg)
            flags = np.zeros(len(hg), dtype=bool)
            for o, v in ans_f.get(p, {}).items():
                flags[owners == o] = v.astype(bool)
            sl = slice(rows_pp, rows_pp + len(hg))
            st[p]["assigned_col"][sl] = (
                st[p]["assigned_col"][sl] | flags
            )

        # 2. ready rows + consensus
        ready = {}
        for p in my_parts:
            d = st[p]
            un = ~d["assigned_col"][: d["nr"]]
            nb = np.zeros(d["nr"], dtype=bool)
            sel = d["strong"] & d["assigned_col"][d["A"].indices]
            nb[np.unique(d["row_ids"][sel])] = True
            ready[p] = np.nonzero(un & nb)[0]
        n_ready = int(np.sum(
            comm.allgather(
                {p: len(ready[p]) for p in my_parts},
                kind=f"mp-ready-{_pass}",
            )
        ))
        if n_ready == 0:
            break

        # 3. fetch P rows of strong-assigned halo neighbours of ready
        # rows (cache misses only)
        reqs_p = {}
        for p in my_parts:
            d = st[p]
            hg = lvl_parts[p]["halo_glob"]
            if not len(hg) or not len(ready[p]):
                reqs_p[p] = {}
                continue
            rmask = np.zeros(d["nr"], dtype=bool)
            rmask[ready[p]] = True
            sel = (
                d["strong"]
                & rmask[np.minimum(d["row_ids"], d["nr"] - 1)]
                & (d["A"].indices >= rows_pp)
                & d["assigned_col"][d["A"].indices]
            )
            slots = np.unique(d["A"].indices[sel])
            slots = slots[[s not in d["hcache"] for s in slots]]
            if not len(slots):
                reqs_p[p] = {}
                continue
            gids = hg[slots - rows_pp]
            owners = lvl_own.owner_of(gids)
            reqs_p[p] = {
                int(o): gids[owners == o] for o in np.unique(owners)
            }
        ans_p = fetch_by_owner(
            comm, reqs_p, p_row_payload, kind=f"mp-prows-{_pass}",
        )
        for p in my_parts:
            d = st[p]
            hg = lvl_parts[p]["halo_glob"]
            if not len(hg):
                continue
            for o, (iptr, gcs, vls) in ans_p.get(p, {}).items():
                ids = reqs_p[p][o]
                for k, g in enumerate(ids):
                    slot = rows_pp + int(np.searchsorted(hg, g))
                    d["hcache"][slot] = (
                        gcs[iptr[k]: iptr[k + 1]],
                        vls[iptr[k]: iptr[k + 1]],
                    )

        # 4. compute the ready rows (vectorized: the serial recurrence
        # W = diag(-1/atil) A_sa P as one scipy product per part)
        for p in my_parts:
            d = st[p]
            if not len(ready[p]):
                continue
            rmask = np.zeros(d["nr"], dtype=bool)
            rmask[ready[p]] = True
            sel = (
                d["strong"]
                & rmask[np.minimum(d["row_ids"], d["nr"] - 1)]
                & d["assigned_col"][d["A"].indices]
            )
            strong_sum = np.zeros(d["nr"])
            np.add.at(strong_sum, d["row_ids"][sel],
                      d["A"].data[sel])
            atil = d["diag"] + (d["row_total"] - strong_sum)
            atil = np.where(atil != 0, atil, 1.0)
            # P_all: every known P row (owned assigned + cached halo)
            # over local column slots x compact union-gcol space
            src_rows, src_gs, src_vs = [], [], []
            for i, (gs, vs) in d["P_rows"].items():
                src_rows.append(np.full(len(gs), i, dtype=np.int64))
                src_gs.append(gs)
                src_vs.append(vs)
            for slot, (gs, vs) in d["hcache"].items():
                src_rows.append(
                    np.full(len(gs), slot, dtype=np.int64))
                src_gs.append(gs)
                src_vs.append(vs)
            cat_g = (
                np.concatenate(src_gs) if src_gs
                else np.zeros(0, np.int64)
            )
            ug = np.unique(cat_g)
            P_all = sps.csr_matrix(
                (
                    np.concatenate(src_vs) if src_vs else np.zeros(0),
                    (
                        np.concatenate(src_rows) if src_rows
                        else np.zeros(0, np.int64),
                        np.searchsorted(ug, cat_g),
                    ),
                ),
                shape=(d["ncol"], max(len(ug), 1)),
            )
            scale = -1.0 / atil[d["row_ids"][sel]]
            A_sa = sps.csr_matrix(
                (d["A"].data[sel] * scale,
                 (d["row_ids"][sel], d["A"].indices[sel])),
                shape=(d["nr"], d["ncol"]),
            )
            W = (A_sa @ P_all).tocsr()
            W.sum_duplicates()
            W.sort_indices()
            for i in ready[p]:
                s0, s1 = W.indptr[i], W.indptr[i + 1]
                d["P_rows"][int(i)] = (
                    ug[W.indices[s0:s1]].astype(np.int64),
                    W.data[s0:s1].copy(),
                )
            d["assigned_col"][ready[p]] = True

    # assemble per-part compact CSR like the D1/D2 builders
    out = {}
    for p in my_parts:
        d = st[p]
        rows_l, gcols_l, vals_l = [], [], []
        for i, (gs, vs) in d["P_rows"].items():
            rows_l.append(np.full(len(gs), i, dtype=np.int64))
            gcols_l.append(gs)
            vals_l.append(vs)
        rows = (
            np.concatenate(rows_l) if rows_l
            else np.zeros(0, np.int64)
        )
        gcols = (
            np.concatenate(gcols_l) if gcols_l
            else np.zeros(0, np.int64)
        )
        vals = (
            np.concatenate(vals_l) if vals_l else np.zeros(0)
        )
        ucols, inv = np.unique(gcols, return_inverse=True)
        P = sps.csr_matrix(
            (vals, (rows, inv)),
            shape=(d["nr"], max(len(ucols), 1)),
        )
        P.sum_duplicates()
        P.sort_indices()
        out[p] = (P, ucols)
    return out


def _standard_interpolation_local(
    A_p, S_p, counts_p, cf_p, cf_col, gc_col, rows_pp,
    d2_sc, d2_ng, nc_global,
):
    """Distance-2 'standard' interpolation of one part's owned rows
    (reference interpolators/distance2.cu; serial twin
    amg.classical.standard_interpolation — same formulas, with the
    rows of off-part strong F neighbours supplied by ``d2_sc``/
    ``d2_ng`` in global coarse ids):

      w_ij = -( a_ij 1[j in C_i^s] +
                sum_{k in F_i^s} a_ik a_kj / d_ik ) / ã_ii
      d_ik = sum_{l in C_i^ext} (A_FC_neg)_kl

    Returns (P compact csr counts_p x len(ucols), ucols global ids).
    """
    Ab = A_p.tocsr()
    nloc = Ab.shape[1]
    A_str = Ab.multiply(S_p.astype(bool)).tocsr()
    cmask_col = cf_col == 1
    fmask = cf_p == 0
    fidx = np.nonzero(fmask)[0]
    cidx = np.nonzero(cf_p == 1)[0]
    nf = len(fidx)
    if nf == 0:
        ucols = gc_col[cidx]
        P = sps.csr_matrix(
            (np.ones(len(cidx)), (cidx, np.arange(len(cidx)))),
            shape=(counts_p, max(len(cidx), 1)),
        )
        return P, ucols
    diag = np.asarray(Ab.diagonal())  # owned slot i == owned row i

    # strong rows of F points, split C-slot / F-slot (self excluded)
    coo = A_str[fidx].tocoo()
    is_c = cmask_col[coo.col]
    is_self = coo.col == fidx[coo.row]
    ff = (~is_c) & (~is_self)
    fc = is_c
    SFF = sps.csr_matrix(
        (coo.data[ff], (coo.row[ff], coo.col[ff])), shape=(nf, nloc)
    )
    AsFC = sps.csr_matrix(
        (coo.data[fc], (coo.row[fc], gc_col[coo.col[fc]])),
        shape=(nf, nc_global),
    )
    AsFC.sum_duplicates()

    # NEG / SC rows per local slot: owned slots from the local block,
    # halo slots from the fetched payloads
    ac = Ab.tocoo()
    negm = cmask_col[ac.col] & (ac.data * diag[ac.row] < 0)
    neg_r = [ac.row[negm]]
    neg_c = [gc_col[ac.col[negm]]]
    neg_v = [ac.data[negm]]
    st = A_str.tocoo()
    scm = cmask_col[st.col]
    sc_r = [st.row[scm]]
    sc_c = [gc_col[st.col[scm]]]
    sc_v = [st.data[scm]]
    for slot, (g, v) in d2_ng.items():
        neg_r.append(np.full(len(g), slot, dtype=np.int64))
        neg_c.append(np.asarray(g, dtype=np.int64))
        neg_v.append(np.asarray(v))
    for slot, (g, v) in d2_sc.items():
        sc_r.append(np.full(len(g), slot, dtype=np.int64))
        sc_c.append(np.asarray(g, dtype=np.int64))
        sc_v.append(np.asarray(v))
    NEG = sps.csr_matrix(
        (
            np.concatenate(neg_v),
            (np.concatenate(neg_r), np.concatenate(neg_c)),
        ),
        shape=(nloc, nc_global),
    )
    SC = sps.csr_matrix(
        (
            np.concatenate(sc_v),
            (np.concatenate(sc_r), np.concatenate(sc_c)),
        ),
        shape=(nloc, nc_global),
    )

    # extended pattern T_i = C_i^s  ∪  ∪_{k in F_i^s} C_k^s
    SFFb = (SFF != 0).astype(np.float64)
    T = (
        ((AsFC != 0).astype(np.float64) + SFFb @ (SC != 0)) != 0
    ).astype(np.float64).tocsr()

    # denominators d_ik on the strong F-F edges
    E = (T @ NEG.T).tocsr()  # nf x nloc
    sff = SFF.tocoo()
    if sff.nnz:
        d_vals = np.asarray(E[sff.row, sff.col]).ravel()
        with np.errstate(divide="ignore", invalid="ignore"):
            b_vals = np.where(d_vals != 0, sff.data / d_vals, 0.0)
        B = sps.csr_matrix(
            (b_vals, (sff.row, sff.col)), shape=(nf, nloc)
        )
    else:
        d_vals = np.zeros(0)
        B = sps.csr_matrix((nf, nloc))

    Wnum = (AsFC + B @ NEG).multiply(T).tocsr()

    # modified diagonal ã_ii = a_ii + weak row sum + undistributable
    row_total = (
        np.asarray(Ab.sum(axis=1)).ravel()[fidx] - diag[fidx]
    )
    strong_sum = np.bincount(
        coo.row[ff | fc], weights=coo.data[ff | fc], minlength=nf
    )
    weak_sum = row_total - strong_sum
    undistributable = np.bincount(
        sff.row, weights=np.where(d_vals == 0, sff.data, 0.0),
        minlength=nf,
    ) if sff.nnz else np.zeros(nf)
    atil = diag[fidx] + weak_sum + undistributable
    atil = np.where(atil != 0, atil, 1.0)
    Wnum = sps.diags_array(-1.0 / atil) @ Wnum

    # assemble compact P over the union of used global coarse ids
    Wcoo = Wnum.tocoo()
    gcols_all = np.concatenate([Wcoo.col, gc_col[cidx]])
    ucols = np.unique(gcols_all)
    rows = np.concatenate([fidx[Wcoo.row], cidx])
    cols = np.searchsorted(ucols, gcols_all)
    vals = np.concatenate([Wcoo.data, np.ones(len(cidx))])
    P = sps.csr_matrix(
        (vals, (rows, cols)), shape=(counts_p, max(len(ucols), 1))
    )
    P.sum_duplicates()
    P.sort_indices()
    return P, ucols


def build_distributed_classical_hierarchy_local(
    local_parts: Dict[int, dict],
    ownership: Ownership,
    cfg,
    scope: str,
    comm: Optional[LoopbackComm] = None,
    max_levels: int = 20,
    consolidate_rows: int = 4096,
    proc_grid=None,
    mesh=None,
    stop_measure: str = "sum",
) -> DistHierarchy:
    """Distributed classical-AMG setup loop from per-process blocks
    (reference setup_v2 + classical_amg_level.cu distributed flow)."""
    if comm is None:
        from amgx_tpu.distributed.comm import default_comm

        comm = default_comm(ownership.n_parts)
    n_parts = ownership.n_parts
    my_parts = [p for p in comm.my_parts if p in local_parts]
    if sorted(local_parts) != sorted(my_parts):
        raise ValueError(
            f"local_parts {sorted(local_parts)} != comm.my_parts "
            f"{sorted(comm.my_parts)}"
        )

    theta = float(cfg.get("strength_threshold", scope))
    max_row_sum = float(cfg.get("max_row_sum", scope))
    trunc = float(cfg.get("interp_truncation_factor", scope))
    max_el = int(cfg.get("interp_max_elements", scope))
    interp = str(cfg.get("interpolator", scope)).upper()
    use_d2 = interp in ("D2", "STD", "STANDARD")
    use_mp = interp == "MULTIPASS"
    if interp not in ("D1",) and not use_d2 and not use_mp:
        import warnings

        warnings.warn(
            f"distributed classical interpolator {interp}: using D1 "
            "(D1, D2/standard and MULTIPASS are the distributed "
            "roster)"
        )
    selector = str(cfg.get("selector", scope)).upper()
    aggressive_levels = int(cfg.get("aggressive_levels", scope))
    aggressive_interp = str(
        cfg.get("aggressive_interpolator", scope)).upper()
    always_aggressive = selector in (
        "AGGRESSIVE_PMIS", "AGGRESSIVE_HMIS",
    )

    lvl_parts = init_lvl_parts(local_parts, ownership, my_parts)
    lvl_own: Ownership = ownership
    levels: List[DistLevel] = []
    max_part_nnz = 0
    max_part_rows = 0

    while (
        _stop_rows(lvl_own, stop_measure) > consolidate_rows
        and len(levels) < max_levels
    ):
        counts = lvl_own.counts
        rows_pp = max(int(counts.max()), 1)

        # ---- strength + PMIS (synchronous, serial-identical) -------
        S_parts = {
            p: _part_strength(
                lvl_parts[p]["A"], int(counts[p]), theta, max_row_sum
            )
            for p in my_parts
        }
        for p in my_parts:
            max_part_nnz = max(max_part_nnz, lvl_parts[p]["A"].nnz)
            max_part_rows = max(max_part_rows, int(counts[p]))
        cf = _pmis_distributed(
            lvl_parts, lvl_own, comm, my_parts, S_parts, rows_pp
        )
        # aggressive two-stage coarsening (reference AGGRESSIVE_PMIS /
        # aggressive_levels): refine stage-1 C points by PMIS on the
        # distance-2 C-subgraph, then interpolate with MULTIPASS
        lvl_aggressive = (
            len(levels) < aggressive_levels or always_aggressive
        )
        if lvl_aggressive:
            if aggressive_interp != "MULTIPASS":
                import warnings

                warnings.warn(
                    f"aggressive interpolator {aggressive_interp}: "
                    "using MULTIPASS"
                )
            cf = _aggressive_pmis_refine(
                lvl_parts, lvl_own, comm, my_parts, S_parts, cf,
                rows_pp,
            )
        lvl_use_mp = use_mp or lvl_aggressive

        # ---- coarse numbering + ghost C/F info (shared helper) -----
        ncs, coffsets, own_c, gcid, reqs, colinfo = (
            _coarse_numbering_and_colinfo(
                cf, lvl_parts, lvl_own, comm, my_parts, rows_pp,
            )
        )
        nc_global = int(ncs.sum())
        if nc_global >= lvl_own.n_global or nc_global == 0:
            break

        # ---- D2: fetch halo F rows' strong-C and sign-restricted
        # F->C data in GLOBAL coarse ids (the second-ring structural
        # content of reference distance2.cu, ridden as one targeted
        # exchange instead of a second halo ring) -------------------
        halo_d2 = {}
        if use_d2 and not lvl_use_mp:
            reqs2 = {}
            for p in my_parts:
                hg = lvl_parts[p]["halo_glob"]
                if not len(hg):
                    continue
                cf_col, _gc = colinfo[p]
                fh = hg[cf_col[rows_pp: rows_pp + len(hg)] == 0]
                if not len(fh):
                    continue
                owners = lvl_own.owner_of(fh)
                reqs2[p] = {
                    int(o): fh[owners == o] for o in np.unique(owners)
                }

            def d2_payload(o, ids):
                return _d2_rows_payload(
                    lvl_parts[o]["A"], S_parts[o],
                    lvl_own.local_of_ids(ids), colinfo[o],
                )

            halo_d2 = fetch_by_owner(
                comm, reqs2, d2_payload, kind="halo-d2rows"
            )

        # ---- interpolation of owned rows ---------------------------
        if lvl_use_mp:
            P_parts = _multipass_interpolation_distributed(
                lvl_parts, lvl_own, comm, my_parts, S_parts, cf,
                colinfo, counts, rows_pp,
            )
            if trunc < 1.0 or max_el >= 0:
                P_parts = {
                    p: (truncate_interp(P, trunc, max_el), uc)
                    for p, (P, uc) in P_parts.items()
                }
        else:
            P_parts = {}
        # p -> (P csr compact, global coarse col ids)
        for p in (() if lvl_use_mp else my_parts):
            cf_col, gc_col = colinfo[p]
            if use_d2:
                hg = lvl_parts[p]["halo_glob"]
                d2_sc, d2_ng = _collect_d2_rows(
                    hg, cf_col, rows_pp, lvl_own,
                    halo_d2.get(p, {}),
                )
                P, ucols = _standard_interpolation_local(
                    lvl_parts[p]["A"], S_parts[p], int(counts[p]),
                    cf[p], cf_col, gc_col, rows_pp,
                    d2_sc, d2_ng, nc_global,
                )
            else:
                P, ucols = _direct_interpolation_local(
                    lvl_parts[p]["A"], S_parts[p], int(counts[p]),
                    cf[p], cf_col, gc_col,
                )
            if trunc < 1.0 or max_el >= 0:
                P = truncate_interp(P, trunc, max_el)
            P_parts[p] = (P.tocsr(), ucols)

        # ---- halo P-rows (reference exchange_halo_rows_P) ----------
        def p_rows_payload(o, ids):
            P, ucols = P_parts[o]
            li = lvl_own.local_of_ids(ids)
            sub = P[li]
            return (
                sub.indptr.astype(np.int64),
                ucols[sub.indices],
                sub.data,
            )

        p_ans = fetch_by_owner(
            comm, reqs, p_rows_payload, kind="halo-P",
        )

        # ---- part-local Galerkin: Pext^T (A_p Pext) ----------------
        # extended coarse column space: owned coarse + ghost coarse
        rap_partial = {}  # p -> csr (nc_own x nc_global cols global)
        for p in my_parts:
            A_p = lvl_parts[p]["A"]
            nloc = A_p.shape[1]
            P_own, ucols_own = P_parts[p]
            hg = lvl_parts[p]["halo_glob"]
            # halo P rows in (lens, gcols, vals) per owner, re-ordered
            # to the halo list
            hp_indptr = np.zeros(len(hg) + 1, dtype=np.int64)
            hp_cols: list = []
            hp_vals: list = []
            if len(hg):
                owners = lvl_own.owner_of(hg)
                per_halo_rows = [None] * len(hg)
                for o, (iptr, gcols, vals) in p_ans.get(p, {}).items():
                    idx = np.nonzero(owners == o)[0]
                    for k, h in enumerate(idx):
                        per_halo_rows[h] = (
                            gcols[iptr[k]: iptr[k + 1]],
                            vals[iptr[k]: iptr[k + 1]],
                        )
                for h in range(len(hg)):
                    row = per_halo_rows[h]
                    ln = 0 if row is None else len(row[0])
                    hp_indptr[h + 1] = hp_indptr[h] + ln
                    if ln:
                        hp_cols.append(row[0])
                        hp_vals.append(row[1])
            hp_gcols = (
                np.concatenate(hp_cols) if hp_cols
                else np.zeros(0, dtype=np.int64)
            )
            hp_v = (
                np.concatenate(hp_vals) if hp_vals else np.zeros(0)
            )
            # extended coarse columns for this part
            cx = np.unique(np.concatenate([ucols_own, hp_gcols]))
            # P_ext over local fine slots (owned rows 0..counts,
            # halo rows at rows_pp..)
            Pcoo = P_own.tocoo()
            rows_ext = [Pcoo.row]
            cols_ext = [
                np.searchsorted(cx, ucols_own[Pcoo.col])
            ]
            vals_ext = [Pcoo.data]
            if len(hg):
                lens = np.diff(hp_indptr)
                rows_ext.append(
                    rows_pp + np.repeat(np.arange(len(hg)), lens)
                )
                cols_ext.append(np.searchsorted(cx, hp_gcols))
                vals_ext.append(hp_v)
            P_ext = sps.csr_matrix(
                (
                    np.concatenate(vals_ext),
                    (
                        np.concatenate(rows_ext),
                        np.concatenate(cols_ext),
                    ),
                ),
                shape=(nloc, max(len(cx), 1)),
            )
            AP = (A_p @ P_ext).tocsr()  # counts_p x ncx
            # P_owned^T in the same extended space
            P_ownx = sps.csr_matrix(
                (
                    Pcoo.data,
                    (Pcoo.row, np.searchsorted(cx, ucols_own[Pcoo.col])),
                ),
                shape=(int(counts[p]), max(len(cx), 1)),
            )
            part = (P_ownx.T @ AP).tocoo()  # ncx x ncx
            # back to global coarse ids
            rap_partial[p] = (
                cx[part.row], cx[part.col], part.data,
            )

        # ---- route partial rows to coarse owners -------------------
        outbox = {}
        local_keep = {}
        for p in my_parts:
            gr, gc, gv = rap_partial[p]
            owners = own_c.owner_of(gr)
            for o in np.unique(owners):
                m = owners == o
                if int(o) == p:
                    local_keep[p] = (gr[m], gc[m], gv[m])
                else:
                    outbox[(p, int(o))] = (gr[m], gc[m], gv[m])
        inbox = comm.alltoall(outbox, kind="rap-ext")
        rap_rows = {}
        for L in my_parts:
            trips = []
            if L in local_keep:
                trips.append((L, local_keep[L]))
            for (src, dst), t in inbox.items():
                if dst == L:
                    trips.append((src, t))
            acc = None
            nc_own = int(own_c.counts[L])
            for src, (gr, gc, gv) in sorted(trips):
                m = sps.csr_matrix(
                    (gv, (gr - coffsets[L], gc)),
                    shape=(nc_own, nc_global),
                )
                acc = m if acc is None else acc + m
            if acc is None:
                acc = sps.csr_matrix((nc_own, nc_global))
            acc.sum_duplicates()
            acc.sort_indices()
            rap_rows[L] = acc

        # ---- localize the coarse level -----------------------------
        rows_pp_c = max(int(own_c.counts.max()), 1)
        new_parts = {}
        p_halo_cache = {}
        for p in my_parts:
            m = rap_rows[p].tocsr()
            gcols = m.indices.astype(np.int64)
            # union halo: RAP columns + P ghost coarse ids (P columns
            # must resolve in the coarse level's halo numbering)
            _, ucols_own = P_parts[p]
            pg = ucols_own[
                (ucols_own < coffsets[p])
                | (ucols_own >= coffsets[p + 1])
            ]
            is_owned = own_c.owner_of(gcols) == p
            cols, halo_glob = halo_localize(
                gcols, is_owned,
                own_c.local_of_ids(gcols[is_owned]), rows_pp_c,
            )
            if len(pg):
                extra = np.setdiff1d(pg, halo_glob)
                if len(extra):
                    merged = np.union1d(halo_glob, extra)
                    # re-map halo slots into the merged list
                    remap = rows_pp_c + np.searchsorted(
                        merged, halo_glob
                    )
                    hal = cols >= rows_pp_c
                    cols = cols.copy()
                    cols[hal] = remap[cols[hal] - rows_pp_c].astype(
                        np.int32
                    )
                    halo_glob = merged
            nloc = rows_pp_c + len(halo_glob)
            new_parts[p] = dict(
                A=sps.csr_matrix(
                    (m.data, cols, m.indptr),
                    shape=(int(own_c.counts[p]), nloc),
                ),
                halo_glob=halo_glob,
            )
            p_halo_cache[p] = halo_glob

        # ---- device arrays: A + P in extended coarse numbering -----
        A_dev = _finalize_level(
            lvl_parts_to_parts(lvl_parts), lvl_own, comm,
            proc_grid=proc_grid if len(levels) == 0 else None,
            mesh=mesh,
        )
        P_local = {}
        for p in sorted(my_parts):
            P_own, ucols_own = P_parts[p]
            halo_c = p_halo_cache[p]
            # global coarse -> coarse-LOCAL extended slot
            owned_m = (
                (ucols_own >= coffsets[p])
                & (ucols_own < coffsets[p + 1])
            )
            slot = np.empty(len(ucols_own), dtype=np.int64)
            slot[owned_m] = ucols_own[owned_m] - coffsets[p]
            slot[~owned_m] = rows_pp_c + np.searchsorted(
                halo_c, ucols_own[~owned_m]
            )
            coo = P_own.tocoo()
            P_local[p] = sps.csr_matrix(
                (coo.data, (coo.row, slot[coo.col])),
                shape=(
                    int(counts[p]),
                    rows_pp_c + len(halo_c),
                ),
            )
        P_cols, P_vals = _stack_level_blocks(
            P_local, rows_pp, comm, mesh
        )
        levels.append(
            DistLevel(
                A=A_dev, P_cols=P_cols, P_vals=P_vals,
                R_cols=None, R_vals=None, bridge=None,
                classical=True,
            )
        )

        lvl_parts = new_parts
        lvl_own = own_c

    # deepest level + consolidated tail: shared finish with the
    # aggregation builder
    return finish_distributed_hierarchy(
        lvl_parts, lvl_own, comm, levels, proc_grid,
        max_part_nnz, max_part_rows, my_parts, mesh=mesh,
    )


def build_distributed_classical_hierarchy(
    Asp: sps.csr_matrix,
    n_parts: int,
    cfg,
    scope: str,
    grid=None,
    owner=None,
    max_levels: int = 20,
    consolidate_rows: int = 4096,
    stop_measure: str = "sum",
) -> DistHierarchy:
    """Single-process convenience wrapper (mirrors
    hierarchy.build_distributed_hierarchy): partition the global matrix
    into local parts, then run the per-process classical setup loop
    over a loopback fabric."""
    from amgx_tpu.amg.aggregation import infer_grid, stencil_offsets
    from amgx_tpu.distributed.partition import (
        ArrayOwnership,
        localize_columns,
        partition_rows,
    )

    n = Asp.shape[0]
    Asp = Asp.tocsr()
    Asp.sort_indices()
    proc_grid = None
    if owner is None:
        if grid is None:
            offs = stencil_offsets(Asp)
            grid = infer_grid(offs, n) if offs is not None else None
        owner, proc_grid = partition_rows(n, n_parts, grid)
    else:
        owner = np.asarray(owner, dtype=np.int32)
    ownership = ArrayOwnership(owner, n_parts=n_parts)

    rows_pp = max(int(ownership.counts.max()), 1)
    local_parts = {}
    for p in range(n_parts):
        local = Asp[ownership.global_rows(p)].tocsr()
        local_parts[p] = localize_columns(
            local.indptr, local.indices, local.data, owner,
            ownership.local_arr, p, rows_pp,
        )
    return build_distributed_classical_hierarchy_local(
        local_parts, ownership, cfg, scope,
        max_levels=max_levels,
        consolidate_rows=consolidate_rows,
        proc_grid=proc_grid,
        stop_measure=stop_measure,
    )
