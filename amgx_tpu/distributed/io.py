"""Distributed IO (reference src/distributed/distributed_io.cu:
DistributedRead::distributedRead, AMGX_read_system_distributed /
AMGX_write_system_distributed, amgx_c.h:439-460).

Single-process multi-partition reads — the pattern the reference's tests
use (generated_matrix_distributed_io.cu:35-83): a global MatrixMarket
file plus a partition vector produce per-partition local systems whose
union reproduces the global one.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sps

from amgx_tpu.io.matrix_market import read_system


def partition_vector_contiguous(n: int, n_parts: int) -> np.ndarray:
    """Default block partition vector (rank of each global row)."""
    rows_pp = -(-n // n_parts)
    return np.minimum(
        np.arange(n) // rows_pp, n_parts - 1
    ).astype(np.int32)


def read_system_distributed(path, n_parts: int, partition_vec=None):
    """Read a global system and split it into per-partition pieces.

    Returns (parts, rhs_parts, partition_vec) where parts[p] is a dict
    with the partition's global row ids and its local scipy CSR rows
    (global column space — the caller renumbers via
    :func:`amgx_tpu.distributed.partition.partition_matrix` or keeps
    global indexing).
    """
    Ad, rhs, _sol = read_system(path)
    if Ad["block_dims"] != (1, 1):
        raise NotImplementedError(
            "distributed reads of block matrices are not supported yet"
        )
    n = Ad["n_rows"]
    A = sps.csr_matrix(
        (Ad["vals"], (Ad["rows"], Ad["cols"])), shape=(n, Ad["n_cols"])
    )
    if partition_vec is None:
        partition_vec = partition_vector_contiguous(n, n_parts)
    partition_vec = np.asarray(partition_vec)
    parts = []
    rhs_parts = []
    for p in range(n_parts):
        rows = np.nonzero(partition_vec == p)[0]
        parts.append(dict(global_rows=rows, A_local=A[rows].tocsr()))
        rhs_parts.append(None if rhs is None else rhs[rows])
    return parts, rhs_parts, partition_vec


def union_equals_global(parts, A_global: sps.csr_matrix) -> bool:
    """The reference test's assertion: the union of partition rows
    reproduces the global matrix."""
    n = A_global.shape[0]
    rebuilt = sps.lil_matrix(A_global.shape)
    for part in parts:
        rebuilt[part["global_rows"]] = part["A_local"]
    diff = abs(rebuilt.tocsr() - A_global)
    return diff.nnz == 0 or float(diff.max()) == 0.0
