"""Distributed IO (reference src/distributed/distributed_io.cu:
DistributedRead::distributedRead, AMGX_read_system_distributed /
AMGX_write_system_distributed, amgx_c.h:439-460).

Single-process multi-partition reads — the pattern the reference's tests
use (generated_matrix_distributed_io.cu:35-83): a global MatrixMarket
file plus a partition vector produce per-partition local systems whose
union reproduces the global one.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sps

from amgx_tpu.io.matrix_market import read_system


def partition_vector_contiguous(n: int, n_parts: int) -> np.ndarray:
    """Default block partition vector (rank of each global row)."""
    rows_pp = -(-n // n_parts)
    return np.minimum(
        np.arange(n) // rows_pp, n_parts - 1
    ).astype(np.int32)


def read_system_distributed(path, n_parts: int, partition_vec=None):
    """Read a global system and split it into per-partition pieces.

    Returns (parts, rhs_parts, partition_vec).  Scalar matrices:
    parts[p] is {global_rows, A_local} with local scipy CSR rows in
    the global column space (the caller renumbers via
    :func:`amgx_tpu.distributed.partition.partition_matrix` or keeps
    global indexing).  Block matrices (reference distributed_io.cu
    block path): parts[p] is {global_rows, block_dims, indptr, cols,
    vals} — block CSR rows with (nnz, b, b) value blocks, the layout
    ``DistributedAMG.from_local_parts``-style consumers assemble from.
    """
    Ad, rhs, _sol = read_system(path)
    bx, by = Ad["block_dims"]
    n = Ad["n_rows"]
    if (bx, by) != (1, 1):
        if bx != by:
            raise NotImplementedError(
                "distributed reads of rectangular-block matrices are "
                "not supported"
            )
        # block matrices (reference distributed_io.cu block path): the
        # partition vector addresses BLOCK rows; per-part local pieces
        # keep the (nnz, b*b) block values alongside block csr indexing
        vals = np.asarray(Ad["vals"]).reshape(-1, bx * by)
        order = np.lexsort((Ad["cols"], Ad["rows"]))
        rows_s = np.asarray(Ad["rows"])[order]
        cols_s = np.asarray(Ad["cols"])[order]
        vals_s = vals[order]
        indptr = np.zeros(n + 1, np.int64)
        np.cumsum(np.bincount(rows_s, minlength=n), out=indptr[1:])
        if partition_vec is None:
            partition_vec = partition_vector_contiguous(n, n_parts)
        partition_vec = np.asarray(partition_vec)
        parts = []
        rhs_parts = []
        for p in range(n_parts):
            rows = np.nonzero(partition_vec == p)[0]
            sel = np.concatenate([
                np.arange(indptr[r], indptr[r + 1]) for r in rows
            ]) if len(rows) else np.zeros(0, np.int64)
            lens = (indptr[rows + 1] - indptr[rows]) if len(rows) \
                else np.zeros(0, np.int64)
            parts.append(dict(
                global_rows=rows,
                block_dims=(bx, by),
                indptr=np.concatenate([[0], np.cumsum(lens)]),
                cols=cols_s[sel],
                vals=vals_s[sel].reshape(-1, bx, by),
            ))
            if rhs is None:
                rhs_parts.append(None)
            else:
                sidx = (rows[:, None] * bx
                        + np.arange(bx)[None, :]).reshape(-1)
                rhs_parts.append(np.asarray(rhs)[sidx])
        return parts, rhs_parts, partition_vec
    A = sps.csr_matrix(
        (Ad["vals"], (Ad["rows"], Ad["cols"])), shape=(n, Ad["n_cols"])
    )
    if partition_vec is None:
        partition_vec = partition_vector_contiguous(n, n_parts)
    partition_vec = np.asarray(partition_vec)
    parts = []
    rhs_parts = []
    for p in range(n_parts):
        rows = np.nonzero(partition_vec == p)[0]
        parts.append(dict(global_rows=rows, A_local=A[rows].tocsr()))
        rhs_parts.append(None if rhs is None else rhs[rows])
    return parts, rhs_parts, partition_vec


def union_equals_global(parts, A_global: sps.csr_matrix) -> bool:
    """The reference test's assertion: the union of partition rows
    reproduces the global matrix.  ``A_global`` is the SCALAR matrix
    in both cases (block parts are expanded for the comparison)."""
    rebuilt = sps.lil_matrix(A_global.shape)
    for part in parts:
        if "A_local" in part:
            rebuilt[part["global_rows"]] = part["A_local"]
            continue
        bx, by = part["block_dims"]
        ip, cols, vals = part["indptr"], part["cols"], part["vals"]
        for li, g in enumerate(part["global_rows"]):
            for s in range(ip[li], ip[li + 1]):
                j = cols[s]
                rebuilt[g * bx:(g + 1) * bx,
                        j * by:(j + 1) * by] = vals[s]
    diff = abs(rebuilt.tocsr() - A_global)
    return diff.nnz == 0 or float(diff.max()) == 0.0
