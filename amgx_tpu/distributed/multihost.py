"""Multi-host launch plumbing and per-process partition construction.

Reference parity: the per-rank side of DistributedManager's upload path
(distributed_manager.cu loadDistributedMatrix*: each MPI rank holds its
own row block, renumbers to local with appended halo slots, and builds
B2L maps from neighbor metadata).  TPU shape:

  * :func:`initialize` wraps ``jax.distributed.initialize`` — after it,
    ``jax.devices()`` spans every host's chips and one ``Mesh`` over
    them drives the same shard_map code path as single-host.
  * :func:`local_part_from_rows` localizes ONE process's contiguous row
    block using only the block itself plus the global partition
    offsets — the global matrix is never materialized anywhere.
  * :func:`partition_from_local_parts` assembles the
    :class:`DistributedMatrix` from the per-part localized blocks in
    ONE process (stacked numpy arrays) — the single-host test shape.
  * :func:`sharded_partition` is the true multi-host assembly: each
    process materializes only its own parts' device arrays, the
    exchange plan rides an allgather of the O(boundary) halo-id
    lists, and the stacked arrays are ``jax.Array``s sharded one part
    per mesh device.  Tests validate bit-equality against the
    global-matrix path.
"""

from __future__ import annotations

import numpy as np

from amgx_tpu.distributed.partition import (
    DistributedMatrix,
    build_exchange_plan,
    finalize_partition,
)


def initialize(
    coordinator_address=None,
    num_processes=None,
    process_id=None,
    local_device_ids=None,
):
    """Join (or no-op) a multi-process JAX runtime.

    Explicit arguments always initialize.  With no arguments, the
    cluster autodetection of ``jax.distributed.initialize`` runs only
    when a recognized launcher environment is present (coordinator
    env vars, SLURM multi-task, TPU pod); otherwise this is a no-op so
    single-process use never touches the backend.  Call before any
    other JAX usage on every host.
    """
    import os

    import jax

    if coordinator_address is None and num_processes in (None, 1):
        markers = (
            "JAX_COORDINATOR_ADDRESS",
            "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS",
            "CLOUD_TPU_TASK_ID",
        )
        slurm_multi = int(os.environ.get("SLURM_NTASKS", "1") or 1) > 1
        if not (any(k in os.environ for k in markers) or slurm_multi):
            return  # single process
        try:
            jax.distributed.initialize()
        except RuntimeError:
            _reraise_unless_initialized(jax)
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    except RuntimeError:
        _reraise_unless_initialized(jax)


def _reraise_unless_initialized(jax):
    """Double-init is idempotent; anything else (wrong coordinator,
    connect/barrier timeout — jaxlib raises RuntimeError subclasses for
    those too) must propagate, or this process would silently continue
    on a single-process runtime and wedge the other hosts at the first
    collective."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        if not is_init():
            raise
        return
    state = getattr(jax.distributed, "global_state", None)  # older jax
    if state is None or getattr(state, "client", None) is None:
        raise


def local_part_from_rows(
    indptr, gcols, vals, part_offsets, my_part, rows_pp=None
):
    """Localize one process's contiguous row block.

    indptr/gcols/vals: CSR of rows [part_offsets[p], part_offsets[p+1])
    with GLOBAL column ids.  Returns the localized part dict
    (owned-first columns, halo slots appended) consumed by
    :func:`partition_from_local_parts` — the same shape
    ``localize_columns`` produces from the global matrix.
    """
    import scipy.sparse as sps

    part_offsets = np.asarray(part_offsets, dtype=np.int64)
    p = int(my_part)
    lo, hi = int(part_offsets[p]), int(part_offsets[p + 1])
    assert np.asarray(indptr).shape[0] - 1 == hi - lo, (
        "row block != partition size"
    )
    # canonicalize (the global-path partitioner sort_indices()es first;
    # bit-equality of the ELL slot order depends on it)
    blk = sps.csr_matrix(
        (np.asarray(vals), np.asarray(gcols, dtype=np.int64),
         np.asarray(indptr)),
        shape=(hi - lo, int(part_offsets[-1])),
    )
    blk.sort_indices()
    indptr = blk.indptr
    gcols = blk.indices.astype(np.int64)
    vals = blk.data
    if rows_pp is None:
        rows_pp = int((part_offsets[1:] - part_offsets[:-1]).max())
    own = (gcols >= lo) & (gcols < hi)
    from amgx_tpu.distributed.partition import halo_localize

    cols, halo_glob = halo_localize(
        gcols, own, (gcols[own] - lo).astype(np.int32), rows_pp
    )
    return dict(
        indptr=indptr, cols=cols, vals=vals, halo_glob=halo_glob,
        rows_pp=int(rows_pp),
    )


def partition_from_local_parts(
    parts, part_offsets, proc_grid=None
) -> DistributedMatrix:
    """Assemble the exchange plan from per-part localized blocks.

    ``parts[p]`` is :func:`local_part_from_rows`'s output for part p.
    This assembly is single-process (it stacks every part's localized
    CSR into the [N, rows, w] device arrays); the true multi-host
    assembly — per-process slices + the halo-id allgather — is
    :func:`sharded_partition`.
    """
    part_offsets = np.asarray(part_offsets, dtype=np.int64)
    n_parts = len(parts)
    assert part_offsets.shape[0] == n_parts + 1
    n = int(part_offsets[-1])
    counts = (part_offsets[1:] - part_offsets[:-1]).astype(np.int64)
    rows_pp = int(counts.max())
    for p, part in enumerate(parts):
        got = part.get("rows_pp", rows_pp)
        if got != rows_pp:
            raise ValueError(
                f"part {p} localized with rows_pp={got}, assembly "
                f"expects {rows_pp}: halo column ids would be wrong"
            )
    owner = np.repeat(
        np.arange(n_parts, dtype=np.int32), counts
    )
    local_of = (
        np.arange(n, dtype=np.int64) - part_offsets[owner]
    ).astype(np.int32)
    return finalize_partition(
        parts, owner, local_of, counts, n, n_parts, proc_grid
    )


def _offset_lookups(part_offsets):
    """(owner_fn, local_fn) computing ownership analytically from the
    partition offsets — O(1) state, no global-length arrays (the point
    of the multi-host path)."""
    part_offsets = np.asarray(part_offsets, dtype=np.int64)

    def owner_fn(ids):
        return (
            np.searchsorted(part_offsets, np.asarray(ids), side="right")
            - 1
        ).astype(np.int32)

    def local_fn(ids):
        ids = np.asarray(ids, dtype=np.int64)
        return (ids - part_offsets[owner_fn(ids)]).astype(np.int32)

    return owner_fn, local_fn


def sharded_partition(
    local_parts: dict,
    part_offsets,
    mesh,
    proc_grid=None,
) -> DistributedMatrix:
    """Multi-host assembly: each process materializes ONLY its own
    parts' device arrays; the exchange plan is built (replicated) from
    the allgathered O(boundary) halo-id lists.

    ``local_parts`` maps part index -> :func:`local_part_from_rows`
    output for the parts whose mesh device is addressable from this
    process (single-host: all of them).  The returned
    :class:`DistributedMatrix` carries stacked ``jax.Array``s sharded
    over ``mesh``'s first axis — drop-in for the shard_map solve path,
    with per-process memory O(n_global / n_hosts).

    Reference parity: the per-rank side of upload_all_global
    (distributed_manager.cu loadDistributedMatrix*) where each rank
    uploads only its block and halo plumbing is exchanged
    (distributed_arranger.h create_B2L et al.).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    part_offsets = np.asarray(part_offsets, dtype=np.int64)
    n_parts = part_offsets.shape[0] - 1
    n = int(part_offsets[-1])
    counts = (part_offsets[1:] - part_offsets[:-1]).astype(np.int64)
    rows_pp = int(counts.max())
    axis = mesh.axis_names[0]
    devices = mesh.devices.reshape(-1)
    if len(devices) != n_parts:
        raise ValueError(
            f"mesh has {len(devices)} devices, partition has "
            f"{n_parts} parts"
        )
    if not _uniform_blocks(part_offsets, rows_pp):
        raise ValueError(
            "sharded_partition needs uniform contiguous row blocks "
            "(rows_pp per part); got offsets "
            f"{part_offsets.tolist()}"
        )
    for p, part in local_parts.items():
        got = part.get("rows_pp", rows_pp)
        if got != rows_pp:
            raise ValueError(
                f"part {p} localized with rows_pp={got}, assembly "
                f"expects {rows_pp}: halo column ids would be wrong"
            )

    # ---- allgather the per-part metadata (halo ids, ELL width) ------
    # O(boundary) ints per part; everything downstream of this point is
    # process-replicated plan state.
    local_meta = {
        p: dict(
            halo_glob=np.asarray(part["halo_glob"], dtype=np.int64),
            w=int(np.diff(part["indptr"]).max(initial=0)),
            dtype=np.dtype(part["vals"].dtype).str,
        )
        for p, part in local_parts.items()
    }
    meta = _allgather_part_meta(local_meta, n_parts)

    owner_fn, local_fn = _offset_lookups(part_offsets)
    dm, fb = build_exchange_plan(
        [meta[p]["halo_glob"] for p in range(n_parts)],
        owner_fn,
        local_fn,
        n_parts,
    )

    # ---- per-part device arrays, stacked as sharded jax.Arrays ------
    from amgx_tpu.distributed.partition import (
        part_ell_arrays,
        part_interior_windowed,
        tiled_ell_wanted,
    )

    w = max(max(meta[p]["w"] for p in range(n_parts)), 1)
    # dtype from the gathered meta so a process owning no parts (all
    # its mesh devices remote) still agrees on array dtypes
    dtype = np.dtype(meta[0]["dtype"])

    per_dev = {}
    for p, part in local_parts.items():
        ec, ev, dg = part_ell_arrays(part, rows_pp, w, dtype)
        is_bnd = (ec >= rows_pp).any(axis=1)
        own = np.zeros(rows_pp, dtype=bool)
        own[: counts[p]] = True
        per_dev[p] = dict(
            ell_cols=ec, ell_vals=ev, diag=dg,
            own_mask=own, int_mask=own & ~is_bnd,
        )

    # ---- Pallas windowed tiling of the interior rows (TPU) ----------
    # built per local part; the static window width W must agree across
    # shards, so the per-part widths ride a second (scalar) allgather.
    wwidth = None
    if tiled_ell_wanted(dtype):
        for p, part in local_parts.items():
            built = part_interior_windowed(
                part, per_dev[p]["ell_cols"], per_dev[p]["ell_vals"],
                per_dev[p]["int_mask"], rows_pp, counts[p],
            )
            per_dev[p]["wtile"] = built
        wmeta = _allgather_part_meta(
            {
                p: dict(W=-1 if per_dev[p]["wtile"] is None
                        else per_dev[p]["wtile"][3])
                for p in local_parts
            },
            n_parts,
        )
        widths = [wmeta[p]["W"] for p in range(n_parts)]
        if all(W >= 0 for W in widths):
            wwidth = int(max(widths))
            for p in local_parts:
                tc, tv, bs, _ = per_dev[p]["wtile"]
                per_dev[p]["ell_wcols"] = tc
                per_dev[p]["ell_wvals"] = tv
                per_dev[p]["ell_wbase"] = bs

    # global shapes/dtypes derived WITHOUT local leaves: a process whose
    # addressable mesh devices own no parts passes an empty leaf list
    # (make_array_from_single_device_arrays accepts it with an explicit
    # dtype) and still constructs the same global arrays.
    from amgx_tpu.ops.pallas_well import _LANE, _ROW_TILE, _SUB

    nt = -(-rows_pp // _ROW_TILE)
    spec = {
        "ell_cols": ((rows_pp, w), np.int32),
        "ell_vals": ((rows_pp, w), dtype),
        "diag": ((rows_pp,), dtype),
        "own_mask": ((rows_pp,), np.bool_),
        "int_mask": ((rows_pp,), np.bool_),
        "ell_wcols": ((nt, _SUB, w * _LANE), np.int32),
        "ell_wvals": ((nt, _SUB, w * _LANE), dtype),
        "ell_wbase": ((nt,), np.int32),
    }

    def stack(key):
        shp, dt = spec[key]
        leaves = [
            jax.device_put(per_dev[p][key][None], devices[p])
            for p in sorted(per_dev)
        ]
        return jax.make_array_from_single_device_arrays(
            (n_parts,) + shp, NamedSharding(mesh, P(axis)), leaves,
            dtype=np.dtype(dt),
        )

    return DistributedMatrix(
        n_global=n,
        n_parts=n_parts,
        rows_per_part=rows_pp,
        ell_cols=stack("ell_cols"),
        ell_vals=stack("ell_vals"),
        diag=stack("diag"),
        int_mask=stack("int_mask"),
        own_mask=stack("own_mask"),
        ell_wcols=None if wwidth is None else stack("ell_wcols"),
        ell_wvals=None if wwidth is None else stack("ell_wvals"),
        ell_wbase=None if wwidth is None else stack("ell_wbase"),
        ell_wwidth=wwidth,
        perms=None if dm is None else dm["perms"],
        send_idx_d=None if dm is None else dm["send_idx_d"],
        halo_dir=None if dm is None else dm["halo_dir"],
        halo_pos=None if dm is None else dm["halo_pos"],
        send_idx=fb["send_idx"],
        halo_src_part=fb["halo_src_part"],
        halo_src_pos=fb["halo_src_pos"],
        max_send=fb["max_send"],
        max_halo=fb["max_halo"],
        # owner/local_of stay None (the owner=None pad/unpad layout
        # assumes uniform contiguous blocks — validated here; carrying
        # the O(N) arrays would defeat the per-process memory bound)
        owner=None,
        local_of=None,
        n_owned=counts.astype(np.int32),
        proc_grid=proc_grid,
    )


def _uniform_blocks(part_offsets, rows_pp) -> bool:
    """True when every part (except possibly the last) owns exactly
    rows_pp contiguous rows — then pad/unpad work without the O(N)
    owner/local_of arrays (DistributedMatrix's owner=None layout)."""
    po = np.asarray(part_offsets, dtype=np.int64)
    expect = np.minimum(np.arange(len(po)) * rows_pp, po[-1])
    return bool(np.array_equal(po, expect))


def _allgather_part_meta(local_meta: dict, n_parts: int) -> list:
    """Exchange per-part metadata dicts across processes.

    Single-process (all parts local): a passthrough.  Multi-process:
    rides ``jax.experimental.multihost_utils.broadcast_one_to_all``-
    style process allgather of the pickled lists — O(boundary) bytes.
    """
    import jax

    if jax.process_count() == 1:
        missing = [p for p in range(n_parts) if p not in local_meta]
        if missing:
            raise ValueError(
                f"single-process assembly needs all {n_parts} parts; "
                f"missing {missing}"
            )
        return [local_meta[p] for p in range(n_parts)]
    # multi-process: EVERY process enters the collective, parts or not
    # (a process whose addressable mesh devices own no parts still
    # participates with an empty payload)
    import pickle

    from jax.experimental import multihost_utils

    payload = np.frombuffer(
        pickle.dumps({p: m for p, m in local_meta.items()}),
        dtype=np.uint8,
    )
    # pad to the max payload size (allgather needs uniform shapes)
    sizes = multihost_utils.process_allgather(
        np.array([payload.size], dtype=np.int64)
    ).reshape(-1)
    buf = np.zeros(int(sizes.max()), dtype=np.uint8)
    buf[: payload.size] = payload
    gathered = multihost_utils.process_allgather(buf)
    meta: dict = {}
    for row, size in zip(np.asarray(gathered), sizes):
        meta.update(pickle.loads(np.asarray(row)[: int(size)].tobytes()))
    missing = [p for p in range(n_parts) if p not in meta]
    if missing:
        raise ValueError(f"no process supplied parts {missing}")
    return [meta[p] for p in range(n_parts)]
