"""Multi-host launch plumbing and per-process partition construction.

Reference parity: the per-rank side of DistributedManager's upload path
(distributed_manager.cu loadDistributedMatrix*: each MPI rank holds its
own row block, renumbers to local with appended halo slots, and builds
B2L maps from neighbor metadata).  TPU shape:

  * :func:`initialize` wraps ``jax.distributed.initialize`` — after it,
    ``jax.devices()`` spans every host's chips and one ``Mesh`` over
    them drives the same shard_map code path as single-host.
  * :func:`local_part_from_rows` localizes ONE process's contiguous row
    block using only the block itself plus the global partition
    offsets — the global matrix is never materialized anywhere.
  * :func:`partition_from_local_parts` assembles the
    :class:`DistributedMatrix` from the per-part localized blocks.
    The EXCHANGE PLAN needs only each part's halo-id list
    (O(boundary) ints per part); the stacked device arrays are
    assembled in one process here — a true multi-host launch would
    keep each host's slice local and all_gather just the halo-id
    lists (round-3).  Tests validate bit-equality against the
    global-matrix path.
"""

from __future__ import annotations

import numpy as np

from amgx_tpu.distributed.partition import (
    DistributedMatrix,
    finalize_partition,
)


def initialize(
    coordinator_address=None,
    num_processes=None,
    process_id=None,
    local_device_ids=None,
):
    """Join (or no-op) a multi-process JAX runtime.

    Explicit arguments always initialize.  With no arguments, the
    cluster autodetection of ``jax.distributed.initialize`` runs only
    when a recognized launcher environment is present (coordinator
    env vars, SLURM multi-task, TPU pod); otherwise this is a no-op so
    single-process use never touches the backend.  Call before any
    other JAX usage on every host.
    """
    import os

    import jax

    if coordinator_address is None and num_processes in (None, 1):
        markers = (
            "JAX_COORDINATOR_ADDRESS",
            "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS",
            "CLOUD_TPU_TASK_ID",
        )
        slurm_multi = int(os.environ.get("SLURM_NTASKS", "1") or 1) > 1
        if not (any(k in os.environ for k in markers) or slurm_multi):
            return  # single process
        try:
            jax.distributed.initialize()
        except RuntimeError:
            pass  # launcher already initialized the runtime: idempotent
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    except RuntimeError:
        pass  # already initialized: idempotent


def local_part_from_rows(
    indptr, gcols, vals, part_offsets, my_part, rows_pp=None
):
    """Localize one process's contiguous row block.

    indptr/gcols/vals: CSR of rows [part_offsets[p], part_offsets[p+1])
    with GLOBAL column ids.  Returns the localized part dict
    (owned-first columns, halo slots appended) consumed by
    :func:`partition_from_local_parts` — the same shape
    ``localize_columns`` produces from the global matrix.
    """
    import scipy.sparse as sps

    part_offsets = np.asarray(part_offsets, dtype=np.int64)
    p = int(my_part)
    lo, hi = int(part_offsets[p]), int(part_offsets[p + 1])
    assert np.asarray(indptr).shape[0] - 1 == hi - lo, (
        "row block != partition size"
    )
    # canonicalize (the global-path partitioner sort_indices()es first;
    # bit-equality of the ELL slot order depends on it)
    blk = sps.csr_matrix(
        (np.asarray(vals), np.asarray(gcols, dtype=np.int64),
         np.asarray(indptr)),
        shape=(hi - lo, int(part_offsets[-1])),
    )
    blk.sort_indices()
    indptr = blk.indptr
    gcols = blk.indices.astype(np.int64)
    vals = blk.data
    if rows_pp is None:
        rows_pp = int((part_offsets[1:] - part_offsets[:-1]).max())
    own = (gcols >= lo) & (gcols < hi)
    from amgx_tpu.distributed.partition import halo_localize

    cols, halo_glob = halo_localize(
        gcols, own, (gcols[own] - lo).astype(np.int32), rows_pp
    )
    return dict(
        indptr=indptr, cols=cols, vals=vals, halo_glob=halo_glob,
        rows_pp=int(rows_pp),
    )


def partition_from_local_parts(
    parts, part_offsets, proc_grid=None
) -> DistributedMatrix:
    """Assemble the exchange plan from per-part localized blocks.

    ``parts[p]`` is :func:`local_part_from_rows`'s output for part p.
    This assembly is single-process (it stacks every part's localized
    CSR into the [N, rows, w] device arrays); in a true multi-host
    launch each host would keep only its own slice and the EXCHANGE
    PLAN inputs (each part's O(boundary) ``halo_glob`` list) would
    ride one small all_gather — that collective leg is round-3 work.
    """
    part_offsets = np.asarray(part_offsets, dtype=np.int64)
    n_parts = len(parts)
    assert part_offsets.shape[0] == n_parts + 1
    n = int(part_offsets[-1])
    counts = (part_offsets[1:] - part_offsets[:-1]).astype(np.int64)
    rows_pp = int(counts.max())
    for p, part in enumerate(parts):
        got = part.get("rows_pp", rows_pp)
        if got != rows_pp:
            raise ValueError(
                f"part {p} localized with rows_pp={got}, assembly "
                f"expects {rows_pp}: halo column ids would be wrong"
            )
    owner = np.repeat(
        np.arange(n_parts, dtype=np.int32), counts
    )
    local_of = (
        np.arange(n, dtype=np.int64) - part_offsets[owner]
    ).astype(np.int32)
    return finalize_partition(
        parts, owner, local_of, counts, n, n_parts, proc_grid
    )
