"""Multi-host launch plumbing and per-process partition construction.

Reference parity: the per-rank side of DistributedManager's upload path
(distributed_manager.cu loadDistributedMatrix*: each MPI rank holds its
own row block, renumbers to local with appended halo slots, and builds
B2L maps from neighbor metadata).  TPU shape:

  * :func:`initialize` wraps ``jax.distributed.initialize`` — after it,
    ``jax.devices()`` spans every host's chips and one ``Mesh`` over
    them drives the same shard_map code path as single-host.
  * :func:`local_part_from_rows` localizes ONE process's contiguous row
    block using only the block itself plus the global partition
    offsets — the global matrix is never materialized anywhere.
  * :func:`partition_from_local_parts` assembles the
    :class:`DistributedMatrix` from the per-part localized blocks in
    ONE process (stacked numpy arrays) — the single-host test shape.
  * :func:`sharded_partition` is the true multi-host assembly: each
    process materializes only its own parts' device arrays, the
    exchange plan rides an allgather of the O(boundary) halo-id
    lists, and the stacked arrays are ``jax.Array``s sharded one part
    per mesh device.  Tests validate bit-equality against the
    global-matrix path.
"""

from __future__ import annotations

import numpy as np

from amgx_tpu.distributed.partition import (
    DistributedMatrix,
    build_exchange_plan,
    finalize_partition,
)


def initialize(
    coordinator_address=None,
    num_processes=None,
    process_id=None,
    local_device_ids=None,
):
    """Join (or no-op) a multi-process JAX runtime.

    Explicit arguments always initialize.  With no arguments, the
    cluster autodetection of ``jax.distributed.initialize`` runs only
    when a recognized launcher environment is present (coordinator
    env vars, SLURM multi-task, TPU pod); otherwise this is a no-op so
    single-process use never touches the backend.  Call before any
    other JAX usage on every host.
    """
    import os

    import jax

    if coordinator_address is None and num_processes in (None, 1):
        markers = (
            "JAX_COORDINATOR_ADDRESS",
            "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS",
            "CLOUD_TPU_TASK_ID",
        )
        slurm_multi = int(os.environ.get("SLURM_NTASKS", "1") or 1) > 1
        if not (any(k in os.environ for k in markers) or slurm_multi):
            return  # single process
        try:
            jax.distributed.initialize()
        except RuntimeError:
            _reraise_unless_initialized(jax)
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    except RuntimeError:
        _reraise_unless_initialized(jax)


def _reraise_unless_initialized(jax):
    """Double-init is idempotent; anything else (wrong coordinator,
    connect/barrier timeout — jaxlib raises RuntimeError subclasses for
    those too) must propagate, or this process would silently continue
    on a single-process runtime and wedge the other hosts at the first
    collective."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        if not is_init():
            raise
        return
    state = getattr(jax.distributed, "global_state", None)  # older jax
    if state is None or getattr(state, "client", None) is None:
        raise


def local_part_from_rows(
    indptr, gcols, vals, part_offsets, my_part, rows_pp=None
):
    """Localize one process's contiguous row block.

    indptr/gcols/vals: CSR of rows [part_offsets[p], part_offsets[p+1])
    with GLOBAL column ids.  Returns the localized part dict
    (owned-first columns, halo slots appended) consumed by
    :func:`partition_from_local_parts` — the same shape
    ``localize_columns`` produces from the global matrix.
    """
    import scipy.sparse as sps

    part_offsets = np.asarray(part_offsets, dtype=np.int64)
    p = int(my_part)
    lo, hi = int(part_offsets[p]), int(part_offsets[p + 1])
    assert np.asarray(indptr).shape[0] - 1 == hi - lo, (
        "row block != partition size"
    )
    # canonicalize (the global-path partitioner sort_indices()es first;
    # bit-equality of the ELL slot order depends on it)
    blk = sps.csr_matrix(
        (np.asarray(vals), np.asarray(gcols, dtype=np.int64),
         np.asarray(indptr)),
        shape=(hi - lo, int(part_offsets[-1])),
    )
    blk.sort_indices()
    indptr = blk.indptr
    gcols = blk.indices.astype(np.int64)
    vals = blk.data
    if rows_pp is None:
        rows_pp = int((part_offsets[1:] - part_offsets[:-1]).max())
    own = (gcols >= lo) & (gcols < hi)
    from amgx_tpu.distributed.partition import halo_localize

    cols, halo_glob = halo_localize(
        gcols, own, (gcols[own] - lo).astype(np.int32), rows_pp
    )
    return dict(
        indptr=indptr, cols=cols, vals=vals, halo_glob=halo_glob,
        rows_pp=int(rows_pp),
    )


def partition_from_local_parts(
    parts, part_offsets, proc_grid=None
) -> DistributedMatrix:
    """Assemble the exchange plan from per-part localized blocks.

    ``parts[p]`` is :func:`local_part_from_rows`'s output for part p.
    This assembly is single-process (it stacks every part's localized
    CSR into the [N, rows, w] device arrays); the true multi-host
    assembly — per-process slices + the halo-id allgather — is
    :func:`sharded_partition`.
    """
    part_offsets = np.asarray(part_offsets, dtype=np.int64)
    n_parts = len(parts)
    assert part_offsets.shape[0] == n_parts + 1
    n = int(part_offsets[-1])
    counts = (part_offsets[1:] - part_offsets[:-1]).astype(np.int64)
    rows_pp = int(counts.max())
    for p, part in enumerate(parts):
        got = part.get("rows_pp", rows_pp)
        if got != rows_pp:
            raise ValueError(
                f"part {p} localized with rows_pp={got}, assembly "
                f"expects {rows_pp}: halo column ids would be wrong"
            )
    owner = np.repeat(
        np.arange(n_parts, dtype=np.int32), counts
    )
    local_of = (
        np.arange(n, dtype=np.int64) - part_offsets[owner]
    ).astype(np.int32)
    return finalize_partition(
        parts, owner, local_of, counts, n, n_parts, proc_grid
    )



def sharded_partition(
    local_parts: dict,
    part_offsets,
    mesh,
    proc_grid=None,
) -> DistributedMatrix:
    """Multi-host assembly: each process materializes ONLY its own
    parts' device arrays; the exchange plan is built (replicated) from
    the allgathered O(boundary) halo-id lists.

    ``local_parts`` maps part index -> :func:`local_part_from_rows`
    output for the parts whose mesh device is addressable from this
    process (single-host: all of them).  The returned
    :class:`DistributedMatrix` carries stacked ``jax.Array``s sharded
    over ``mesh``'s first axis — drop-in for the shard_map solve path,
    with per-process memory O(n_global / n_hosts).

    Reference parity: the per-rank side of upload_all_global
    (distributed_manager.cu loadDistributedMatrix*) where each rank
    uploads only its block and halo plumbing is exchanged
    (distributed_arranger.h create_B2L et al.).

    Thin wrapper over :func:`assemble_level_sharded` (the same
    assembly serves every hierarchy level): validates the fine-level
    contract (uniform contiguous blocks, consistent rows_pp) and
    builds the comm fabric matching the mesh placement.
    """
    import jax

    from amgx_tpu.distributed.comm import AllgatherComm, LoopbackComm
    from amgx_tpu.distributed.partition import OffsetOwnership

    part_offsets = np.asarray(part_offsets, dtype=np.int64)
    n_parts = part_offsets.shape[0] - 1
    counts = (part_offsets[1:] - part_offsets[:-1]).astype(np.int64)
    rows_pp = int(counts.max())
    devices = mesh.devices.reshape(-1)
    if len(devices) != n_parts:
        raise ValueError(
            f"mesh has {len(devices)} devices, partition has "
            f"{n_parts} parts"
        )
    if not _uniform_blocks(part_offsets, rows_pp):
        raise ValueError(
            "sharded_partition needs uniform contiguous row blocks "
            "(rows_pp per part); got offsets "
            f"{part_offsets.tolist()}"
        )
    for p, part in local_parts.items():
        got = part.get("rows_pp", rows_pp)
        if got != rows_pp:
            raise ValueError(
                f"part {p} localized with rows_pp={got}, assembly "
                f"expects {rows_pp}: halo column ids would be wrong"
            )
    if jax.process_count() > 1:
        comm = AllgatherComm(n_parts, sorted(local_parts))
    else:
        comm = LoopbackComm(n_parts)
    return assemble_level_sharded(
        local_parts, OffsetOwnership(part_offsets), comm, mesh,
        proc_grid=proc_grid,
    )


def _part_boundary_count(part, count_p, rows_pp) -> int:
    """Number of owned rows referencing halo columns (>= rows_pp) in
    one localized part dict."""
    indptr = np.asarray(part["indptr"])
    cols = np.asarray(part["cols"])
    if cols.size == 0:
        return 0
    lens = np.diff(indptr)[:count_p]
    rid = np.repeat(np.arange(count_p), lens)
    hal = cols[: int(indptr[count_p])] >= rows_pp
    return int(np.unique(rid[hal]).size)


def stack_parts_sharded(
    per_part: dict, mesh, n_parts, dtype=None, shape=None
):
    """Stack per-part arrays into one [n_parts, ...] ``jax.Array``
    sharded one part per device of ``mesh``'s flattened device list.

    ``per_part[p]`` must be supplied for exactly the parts whose mesh
    device is addressable from this process (jax.Array invariant:
    every addressable shard needs a leaf).  All parts must share one
    shape+dtype; a process never materializes another part's data —
    the per-process memory stays O(global / n_processes).  A process
    addressing no parts passes an empty dict with explicit
    ``shape``+``dtype`` (the global array metadata must still agree).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    devices = mesh.devices.reshape(-1)
    axis = mesh.axis_names[0]
    if per_part:
        some = np.asarray(next(iter(per_part.values())))
        if shape is None:
            shape = some.shape
        if dtype is None:
            dtype = some.dtype
    elif shape is None or dtype is None:
        raise ValueError(
            "a process holding no parts must pass explicit shape and "
            "dtype so the global array metadata agrees across processes"
        )
    leaves = [
        jax.device_put(
            np.ascontiguousarray(np.asarray(per_part[p]))[None],
            devices[p],
        )
        for p in sorted(per_part)
    ]
    from amgx_tpu.core.sharding import make_stacked_array

    return make_stacked_array(
        (n_parts,) + tuple(shape),
        NamedSharding(mesh, P(axis)),
        leaves,
        np.dtype(dtype),
    )


def addressable_parts(mesh) -> list:
    """Part indices whose mesh device is addressable by this process
    (part p <-> flattened mesh device p — the assembly convention)."""
    import jax

    pid = jax.process_index()
    return [
        p
        for p, d in enumerate(mesh.devices.reshape(-1))
        if d.process_index == pid
    ]


def assemble_level_sharded(
    parts_by_p: dict, own, comm, mesh, proc_grid=None
):
    """Multi-process device assembly of ONE hierarchy level.

    The per-rank analogue of ``finalize_partition`` (reference: each
    rank assembles only its own level-matrix block, amg.cu:425-660
    setup_v2 + distributed_manager.cu reorder/B2L plumbing): this
    process materializes device arrays for ``parts_by_p``'s parts
    only; the exchange plan is built replicated from the allgathered
    O(boundary) halo-id lists riding the setup ``comm`` fabric.  Every
    stacked field of the returned :class:`DistributedMatrix` is a
    ``jax.Array`` sharded one part per mesh device — drop-in for the
    shard_map solve path.

    Bit-parity contract: per-part blocks are produced by the same
    ``part_ell_arrays`` / ``part_interior_windowed`` helpers as the
    single-process path, so shard p's slice equals the Loopback
    build's ``ell_*[p]`` exactly (asserted by the multiprocess test).
    """
    import jax

    from amgx_tpu.distributed.partition import (
        part_ell_arrays,
        part_interior_windowed,
        tiled_ell_wanted,
    )

    if not own.offset_blocks:
        raise ValueError(
            "sharded level assembly needs analytic offset-block "
            "ownership (OffsetOwnership); arbitrary partition vectors "
            "must assemble single-process"
        )
    n_parts = own.n_parts
    counts = np.asarray(own.counts, dtype=np.int64)
    rows_pp = max(int(counts.max()), 1)
    mine = addressable_parts(mesh)
    if sorted(parts_by_p) != mine:
        raise ValueError(
            f"process drives parts {sorted(parts_by_p)} but addresses "
            f"mesh devices of parts {mine}: the comm striping must "
            "match the mesh placement (one part per mesh device)"
        )

    # ---- replicated plan from allgathered O(boundary) metadata ------
    from amgx_tpu.core.matrix import sparsity_fingerprint

    local_meta = {
        p: dict(
            halo_glob=np.asarray(part["halo_glob"], dtype=np.int64),
            w=int(np.diff(part["indptr"]).max(initial=0)),
            dtype=np.dtype(part["vals"].dtype).str,
            nb=int(_part_boundary_count(part, counts[p], rows_pp)),
            # per-shard pattern key (core.matrix.sparsity_fingerprint,
            # the serve cache's content hash) — O(local) to compute,
            # O(1) to gather; every process then holds the full tuple
            # so DistributedMatrix.fingerprint agrees replicated.
            # block_size is literally 1: this assembly path is
            # scalar-only (from_local_parts raises for blocks), which
            # keeps the key identical to finalize_partition's for any
            # pattern both paths can actually build
            fp=sparsity_fingerprint(
                np.asarray(part["indptr"]),
                np.asarray(part["cols"]),
                np.asarray(part["indptr"]).shape[0] - 1,
                rows_pp + len(part["halo_glob"]),
                1,
            ),
        )
        for p, part in parts_by_p.items()
    }
    meta = comm.allgather(local_meta, kind="level-meta")
    dm_plan, fb = build_exchange_plan(
        [meta[p]["halo_glob"] for p in range(n_parts)],
        own.owner_of,
        own.local_of_ids,
        n_parts,
    )
    w = max(max(meta[p]["w"] for p in range(n_parts)), 1)
    max_nb = max(meta[p]["nb"] for p in range(n_parts))
    dtype = np.dtype(meta[0]["dtype"])

    from amgx_tpu.distributed.partition import pack_boundary_rows

    # ---- per-part device blocks (same helpers as single-process) ----
    per_dev = {}
    for p, part in parts_by_p.items():
        ec, ev, dg = part_ell_arrays(part, rows_pp, w, dtype)
        is_bnd = (ec >= rows_pp).any(axis=1)
        own_m = np.zeros(rows_pp, dtype=bool)
        own_m[: counts[p]] = True
        per_dev[p] = dict(
            ell_cols=ec, ell_vals=ev, diag=dg,
            own_mask=own_m, int_mask=own_m & ~is_bnd,
            bnd_rows=pack_boundary_rows(
                [np.nonzero(own_m & is_bnd)[0]], rows_pp, max_nb
            )[0],
        )

    # windowed interior tiling: static width W must agree across
    # shards, so the local widths ride one scalar allgather
    wwidth = None
    if tiled_ell_wanted(dtype):
        for p, part in parts_by_p.items():
            per_dev[p]["wtile"] = part_interior_windowed(
                part, per_dev[p]["ell_cols"], per_dev[p]["ell_vals"],
                per_dev[p]["int_mask"], rows_pp, counts[p],
            )
        widths = comm.allgather(
            {
                p: (-1 if per_dev[p]["wtile"] is None
                    else int(per_dev[p]["wtile"][3]))
                for p in parts_by_p
            },
            kind="wtile-width",
        )
        if all(W >= 0 for W in widths):
            wwidth = int(max(widths))
            for p in parts_by_p:
                tc, tv, bs, _ = per_dev[p]["wtile"]
                per_dev[p]["ell_wcols"] = tc
                per_dev[p]["ell_wvals"] = tv
                per_dev[p]["ell_wbase"] = bs

    # explicit shapes/dtypes so a process holding no parts (its mesh
    # devices all remote) still constructs agreeing global arrays
    from amgx_tpu.ops.pallas_well import _LANE, _ROW_TILE, _SUB

    nt = -(-rows_pp // _ROW_TILE)
    spec = {
        "ell_cols": ((rows_pp, w), np.int32),
        "ell_vals": ((rows_pp, w), dtype),
        "diag": ((rows_pp,), dtype),
        "own_mask": ((rows_pp,), np.bool_),
        "int_mask": ((rows_pp,), np.bool_),
        "bnd_rows": ((max(max_nb, 1),), np.int32),
        "ell_wcols": ((nt, _SUB, w * _LANE), np.int32),
        "ell_wvals": ((nt, _SUB, w * _LANE), dtype),
        "ell_wbase": ((nt,), np.int32),
    }

    def stack(key):
        shp_dt = spec.get(key)
        return stack_parts_sharded(
            {p: per_dev[p][key] for p in per_dev}, mesh, n_parts,
            shape=None if shp_dt is None else shp_dt[0],
            dtype=None if shp_dt is None else shp_dt[1],
        )

    # plan arrays are replicated numpy [N, ...]; ship each part's row
    # to its device so the traced lps pytree is fully sharded
    def stack_plan(arr):
        arr = np.asarray(arr)
        return stack_parts_sharded(
            {p: arr[p] for p in per_dev}, mesh, n_parts,
            shape=arr.shape[1:], dtype=arr.dtype,
        )

    return DistributedMatrix(
        n_global=int(own.n_global),
        n_parts=n_parts,
        rows_per_part=rows_pp,
        ell_cols=stack("ell_cols"),
        ell_vals=stack("ell_vals"),
        diag=stack("diag"),
        int_mask=stack("int_mask"),
        own_mask=stack("own_mask"),
        bnd_rows=stack("bnd_rows"),
        ell_wcols=None if wwidth is None else stack("ell_wcols"),
        ell_wvals=None if wwidth is None else stack("ell_wvals"),
        ell_wbase=None if wwidth is None else stack("ell_wbase"),
        ell_wwidth=wwidth,
        perms=None if dm_plan is None else dm_plan["perms"],
        send_idx_d=(
            None if dm_plan is None
            else tuple(stack_plan(s) for s in dm_plan["send_idx_d"])
        ),
        halo_dir=(
            None if dm_plan is None else stack_plan(dm_plan["halo_dir"])
        ),
        halo_pos=(
            None if dm_plan is None else stack_plan(dm_plan["halo_pos"])
        ),
        send_idx=stack_plan(fb["send_idx"]),
        halo_src_part=stack_plan(fb["halo_src_part"]),
        halo_src_pos=stack_plan(fb["halo_src_pos"]),
        max_send=fb["max_send"],
        max_halo=fb["max_halo"],
        owner=None,
        local_of=None,
        n_owned=counts.astype(np.int32),
        proc_grid=proc_grid,
        shard_fps=tuple(meta[p]["fp"] for p in range(n_parts)),
    )


def _uniform_blocks(part_offsets, rows_pp) -> bool:
    """True when every part (except possibly the last) owns exactly
    rows_pp contiguous rows — then pad/unpad work without the O(N)
    owner/local_of arrays (DistributedMatrix's owner=None layout)."""
    po = np.asarray(part_offsets, dtype=np.int64)
    expect = np.minimum(np.arange(len(po)) * rows_pp, po[-1])
    return bool(np.array_equal(po, expect))


