"""Setup-time communication fabric for per-process distributed setup.

Reference parity: the MPI exchanges inside DistributedArranger /
DistributedManager's setup flow (distributed_arranger.h:58-210
create_B2L / exchange_halo_rows_P / exchange_RAP_ext;
comms_mpi_hostbuffer_stream.cu).  The AMG *setup* phase is host-side
numpy here (as the reference's arranger is substantially host thrust),
so its cross-shard traffic is not ICI collectives but process-level
exchanges; the *solve* phase traffic is ppermute/psum on device.

Every cross-shard byte of the per-process setup flows through one of
these objects — shard-local setup code never indexes another shard's
arrays.  Two implementations:

  * :class:`LoopbackComm` — single-process: this process drives all
    parts (the virtual-mesh test shape and the reference's
    single-process multi-partition tests, SURVEY §4); routing is a
    dict re-key, but the interface still bounds what setup MAY
    exchange, and the byte accounting proves the per-process memory
    contract (max message size << global size).
  * :class:`AllgatherComm` — multi-process: payloads ride
    ``jax.experimental.multihost_utils.process_allgather`` (the
    pickled-buffer pattern of multihost._allgather_part_meta).  Every
    process must enter every round with the same sequence of calls.

Both record per-round traffic in ``stats`` so tests can assert the
O(global/N) + O(boundary) bound.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, List, Tuple

import numpy as np


def _nbytes(obj) -> int:
    """Approximate payload size in bytes (numpy-aware)."""
    if isinstance(obj, np.ndarray):
        return obj.nbytes
    if isinstance(obj, (list, tuple)):
        return sum(_nbytes(o) for o in obj)
    if isinstance(obj, dict):
        return sum(_nbytes(v) for v in obj.values())
    if isinstance(obj, (bytes, bytearray)):
        return len(obj)
    return 32  # scalars / small metadata


class CommStats:
    """Per-round traffic accounting (the evidence for the per-process
    memory contract)."""

    def __init__(self):
        self.rounds: List[Dict[str, Any]] = []

    def record(self, kind: str, sent_bytes: int, max_msg_bytes: int):
        self.rounds.append(
            dict(kind=kind, sent_bytes=sent_bytes,
                 max_msg_bytes=max_msg_bytes)
        )

    @property
    def total_bytes(self) -> int:
        return sum(r["sent_bytes"] for r in self.rounds)

    @property
    def max_msg_bytes(self) -> int:
        return max((r["max_msg_bytes"] for r in self.rounds), default=0)


class LoopbackComm:
    """Single-process fabric: this process owns every part.

    ``my_parts`` lists the part indices driven locally (all of them in
    single-process mode).
    """

    def __init__(self, n_parts: int):
        self.n_parts = int(n_parts)
        self.my_parts = list(range(self.n_parts))
        self.stats = CommStats()

    # -- point-to-point round -----------------------------------------
    def alltoall(
        self, outbox: Dict[Tuple[int, int], Any], kind: str = "p2p"
    ) -> Dict[Tuple[int, int], Any]:
        """Route ``{(src, dst): payload}`` -> the same dict viewed by
        receivers.  Single-process: identity plus accounting."""
        sent = sum(_nbytes(v) for v in outbox.values())
        mx = max((_nbytes(v) for v in outbox.values()), default=0)
        self.stats.record(kind, sent, mx)
        return dict(outbox)

    # -- small replicated metadata ------------------------------------
    def allgather(
        self, per_part: Dict[int, Any], kind: str = "meta"
    ) -> List[Any]:
        """Gather one small object per part -> list indexed by part.
        Every part must be supplied by exactly one process."""
        missing = [p for p in range(self.n_parts) if p not in per_part]
        if missing:
            raise ValueError(f"allgather missing parts {missing}")
        sent = sum(_nbytes(v) for v in per_part.values())
        mx = max((_nbytes(v) for v in per_part.values()), default=0)
        self.stats.record(kind, sent, mx)
        return [per_part[p] for p in range(self.n_parts)]


class AllgatherComm(LoopbackComm):
    """Multi-process fabric over ``process_allgather`` (pickled
    payloads, the multihost._allgather_part_meta pattern).  Each
    process drives ``my_parts``; rounds are collective — every process
    must call the same sequence."""

    def __init__(self, n_parts: int, my_parts):
        super().__init__(n_parts)
        self.my_parts = sorted(int(p) for p in my_parts)

    def _exchange_blob(self, obj) -> list:
        """Allgather one pickled python object per process."""
        import jax
        from jax.experimental import multihost_utils

        if jax.process_count() == 1:
            return [obj]
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        sizes = multihost_utils.process_allgather(
            np.array([payload.size], dtype=np.int64)
        ).reshape(-1)
        buf = np.zeros(int(sizes.max()), dtype=np.uint8)
        buf[: payload.size] = payload
        rows = multihost_utils.process_allgather(buf)
        return [
            pickle.loads(np.asarray(r)[: int(s)].tobytes())
            for r, s in zip(np.asarray(rows), sizes)
        ]

    def alltoall(self, outbox, kind="p2p"):
        sent = sum(_nbytes(v) for v in outbox.values())
        mx = max((_nbytes(v) for v in outbox.values()), default=0)
        self.stats.record(kind, sent, mx)
        merged: Dict[Tuple[int, int], Any] = {}
        for blob in self._exchange_blob(outbox):
            merged.update(blob)
        # keep only messages addressed to parts this process drives
        mine = set(self.my_parts)
        return {
            (s, d): v for (s, d), v in merged.items() if d in mine
        }

    def allgather(self, per_part, kind="meta"):
        sent = sum(_nbytes(v) for v in per_part.values())
        mx = max((_nbytes(v) for v in per_part.values()), default=0)
        self.stats.record(kind, sent, mx)
        merged: Dict[int, Any] = {}
        for blob in self._exchange_blob(per_part):
            merged.update(blob)
        missing = [p for p in range(self.n_parts) if p not in merged]
        if missing:
            raise ValueError(f"allgather missing parts {missing}")
        return [merged[p] for p in range(self.n_parts)]


def default_comm(n_parts: int) -> LoopbackComm:
    """LoopbackComm single-process; AllgatherComm under a multi-process
    runtime (parts striped across processes by index)."""
    import jax

    nproc = jax.process_count()
    if nproc == 1:
        return LoopbackComm(n_parts)
    pid = jax.process_index()
    mine = [p for p in range(n_parts) if p % nproc == pid]
    return AllgatherComm(n_parts, mine)


def fetch_by_owner(
    comm: LoopbackComm,
    requests: Dict[int, Dict[int, np.ndarray]],
    answer_fn,
    kind: str = "fetch",
) -> Dict[int, Dict[int, np.ndarray]]:
    """Two-round owner lookup: part p requests values for global ids it
    needs from each owner; owners answer (reference
    exchange_halo_rows_P shape: requests are O(boundary) id lists).

    ``requests[p][o]`` = global ids part p needs from owner o (p in
    comm.my_parts).  ``answer_fn(o, ids)`` computes the answer on the
    process driving part o.  Returns ``answers[p][o]`` aligned with the
    request order.
    """
    out = {
        (p, o): ids
        for p, by_o in requests.items()
        for o, ids in by_o.items()
    }
    inbox = comm.alltoall(out, kind=f"{kind}-req")
    replies = {
        (o, p): answer_fn(o, ids) for (p, o), ids in inbox.items()
    }
    back = comm.alltoall(replies, kind=f"{kind}-ans")
    answers: Dict[int, Dict[int, np.ndarray]] = {}
    for (o, p), vals in back.items():
        answers.setdefault(p, {})[o] = vals
    return answers
