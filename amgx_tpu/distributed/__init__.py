"""Distributed layer: SPMD domain decomposition over a jax.sharding.Mesh.

Replaces the reference's MPI machinery (src/distributed/: DistributedManager
B2L maps, CommsMPIHostBufferStream halo exchange, global_reduce) with ICI
collectives: the boundary->local (B2L) gather + ``all_gather`` pool +
halo gather replaces point-to-point halo exchange; ``psum`` replaces
MPI_Allreduce for dots/norms.  One SPMD code path for 1..N chips.
"""

from amgx_tpu.distributed.partition import DistributedMatrix, partition_matrix
from amgx_tpu.distributed.solve import (
    dist_cg,
    dist_pcg_jacobi,
    dist_spmv_replicated_check,
    halo_site_counter,
)
from amgx_tpu.distributed.eigen import (
    dist_inverse_iteration,
    dist_lanczos,
    dist_power_iteration,
)

__all__ = [
    "DistributedMatrix",
    "partition_matrix",
    "halo_site_counter",
    "dist_cg",
    "dist_pcg_jacobi",
    "dist_spmv_replicated_check",
    "dist_power_iteration",
    "dist_lanczos",
    "dist_inverse_iteration",
]
