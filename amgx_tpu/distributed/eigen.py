"""Distributed eigensolvers over sharded operators (VERDICT r3
missing #6; reference src/eigensolvers/eigensolver.cu +
amg_eigensolver.h:43-121 — the eigensolver framework operates on
distributed operators through the same Operator::apply / halo-exchange
machinery as the linear solvers).

TPU shape: every matrix application is the sharded SpMV
(``make_local_spmv`` — ppermute halo exchange, interior/boundary
overlap) and every dot/norm is a ``psum``, inside one ``shard_map``
program per algorithm:

  * :func:`dist_power_iteration` — largest |lambda| pair
    (single_iteration_eigensolver.cu), whole loop jitted with a
    ``while_loop`` on the psum'd residual.
  * :func:`dist_lanczos` — symmetric Lanczos with full
    reorthogonalization; the m-step basis stays shard-local
    ([m, rows] per shard), alpha/beta ride psums, and the tridiagonal
    Ritz problem solves replicated on host (lanczos_eigensolver.cu).
  * :func:`dist_inverse_iteration` — smallest pair via the
    distributed Jacobi-PCG inner solve (inverse-iteration flavor of
    single_iteration_eigensolver.cu).

All three accept the :class:`DistributedMatrix` + mesh pair used by
the distributed linear solvers, so they run unchanged on the
multi-process sharded assembly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from amgx_tpu.core.sharding import shard_map
from amgx_tpu.distributed.partition import DistributedMatrix
from amgx_tpu.distributed.solve import (
    _pdot,
    _shard_params,
    make_local_spmv,
)


def _start_local(A: DistributedMatrix, seed=7):
    """Deterministic start vector in stacked padded layout (padding
    slots zero so they never pollute norms)."""
    n = A.n_global * max(A.block_size, 1)
    v = np.random.default_rng(seed).standard_normal(n)
    v = v / np.linalg.norm(v)
    return jnp.asarray(A.pad_vector(v))


def dist_power_iteration(
    A: DistributedMatrix, mesh: Mesh, max_iters: int = 200,
    tol: float = 1e-6,
):
    """Largest-|lambda| eigenpair of the sharded operator.

    Returns (eigenvalue, eigenvector (n_global,), iterations,
    residual)."""
    axis = mesh.axis_names[0]
    shard = _shard_params(A)
    spmv = make_local_spmv(A, axis)
    v0 = _start_local(A)
    in_shard = jax.tree.map(lambda _: P(axis), shard)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(in_shard, P(axis)),
        out_specs=(P(axis), P(), P(), P()),
    )
    def run(shard_stk, v_stk):
        sh = jax.tree.map(lambda s: s[0], shard_stk)
        v = v_stk[0]

        def cond(c):
            it, v, lam, res = c
            return (it < max_iters) & (res >= tol)

        def body(c):
            it, v, lam, _ = c
            w = spmv(sh, v)
            lam_new = _pdot(v, w, axis)  # Rayleigh (v normalized)
            r = w - lam_new * v
            res = jnp.sqrt(_pdot(r, r, axis)) / jnp.maximum(
                jnp.abs(lam_new), 1e-30
            )
            nrm = jnp.sqrt(_pdot(w, w, axis))
            v = w / jnp.maximum(nrm, 1e-300)
            return (it + 1, v, lam_new, res)

        it, v, lam, res = jax.lax.while_loop(
            cond, body, (jnp.int32(0), v, jnp.asarray(0.0, v.dtype),
                         jnp.asarray(jnp.inf, v.dtype))
        )
        return v[None], lam, res, it

    v, lam, res, it = jax.jit(run)(shard, v0)
    return (
        float(lam),
        A.unpad_vector(jax.device_get(v)),
        int(it),
        float(res),
    )


def dist_lanczos(
    A: DistributedMatrix, mesh: Mesh, m: int = 30, k: int = 1,
    which: str = "largest",
):
    """Symmetric Lanczos (full reorthogonalization) on the sharded
    operator; Ritz values/vectors of the host tridiagonal problem.

    Returns (eigenvalues (k,), eigenvectors (n_global, k), steps,
    residual-of-leading-pair)."""
    axis = mesh.axis_names[0]
    shard = _shard_params(A)
    spmv = make_local_spmv(A, axis)
    v0 = _start_local(A)
    in_shard = jax.tree.map(lambda _: P(axis), shard)
    m = int(m)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(in_shard, P(axis)),
        out_specs=(P(None, axis), P(), P()),
    )
    def run(shard_stk, v_stk):
        sh = jax.tree.map(lambda s: s[0], shard_stk)
        v = v_stk[0]
        V = jnp.zeros((m + 1,) + v.shape, v.dtype)
        V = V.at[0].set(v)
        alphas = jnp.zeros((m,), v.dtype)
        betas = jnp.zeros((m,), v.dtype)

        def body(j, carry):
            V, alphas, betas = carry
            vj = V[j]
            w = spmv(sh, vj)
            alpha = _pdot(vj, w, axis)
            w = w - alpha * vj - jnp.where(
                j > 0, betas[jnp.maximum(j - 1, 0)], 0.0
            ) * V[jnp.maximum(j - 1, 0)]
            # full reorthogonalization: distributed V V^T w
            coeffs = jax.lax.psum(
                jnp.einsum("i...,...->i", V, w), axis
            )
            # only the first j+1 basis vectors are valid
            mask = jnp.arange(m + 1) <= j
            coeffs = jnp.where(mask, coeffs, 0.0)
            w = w - jnp.einsum("i,i...->...", coeffs, V)
            beta = jnp.sqrt(_pdot(w, w, axis))
            V = V.at[j + 1].set(
                jnp.where(beta > 1e-14, w / jnp.maximum(beta, 1e-300),
                          0.0)
            )
            alphas = alphas.at[j].set(alpha)
            betas = betas.at[j].set(beta)
            return (V, alphas, betas)

        V, alphas, betas = jax.lax.fori_loop(
            0, m, body, (V, alphas, betas)
        )
        # shard axis explicit on dim 1 -> global [m+1, N, rows(, b)]
        return V[:, None], alphas, betas

    V, alphas, betas = jax.jit(run)(shard, v0)
    alphas = np.asarray(jax.device_get(alphas))
    betas = np.asarray(jax.device_get(betas))
    # effective Krylov size: stop at the first tiny beta
    steps = m
    for j in range(m):
        if betas[j] < 1e-14:
            steps = j + 1
            break
    import scipy.linalg as sla

    T_evals, T_evecs = sla.eigh_tridiagonal(
        alphas[:steps], betas[: steps - 1]
    )
    order = (
        np.argsort(T_evals)[::-1] if which == "largest"
        else np.argsort(T_evals)
    )
    lam = T_evals[order[:k]]
    # assemble Ritz vectors from the shard-stacked basis
    Vh = np.asarray(jax.device_get(V))  # [m+1, N, rows(, b)]
    Vg = np.stack(
        [A.unpad_vector(Vh[j]) for j in range(steps)]
    )  # (steps, n)
    X = Vg.T @ T_evecs[:, order[:k]]
    x1 = X[:, 0] / np.linalg.norm(X[:, 0])
    # residual via one more distributed application
    from amgx_tpu.distributed.solve import dist_spmv_replicated_check

    r = dist_spmv_replicated_check(A, x1, mesh) - lam[0] * x1
    res = float(np.linalg.norm(r)) / max(abs(lam[0]), 1e-30)
    return lam, X, steps, res


def dist_inverse_iteration(
    A: DistributedMatrix, mesh: Mesh, max_iters: int = 50,
    tol: float = 1e-8, inner_iters: int = 200, inner_tol: float = 1e-10,
):
    """Smallest-|lambda| eigenpair via inverse iteration with the
    distributed Jacobi-PCG inner solve.

    Returns (eigenvalue, eigenvector (n_global,), iterations,
    residual)."""
    from amgx_tpu.distributed.solve import (
        dist_pcg_jacobi,
        dist_spmv_replicated_check,
    )

    n = A.n_global * max(A.block_size, 1)
    v = np.random.default_rng(7).standard_normal(n)
    v = v / np.linalg.norm(v)
    lam = 0.0
    res = np.inf
    it = 0
    for it in range(1, max_iters + 1):
        w, _, _ = dist_pcg_jacobi(
            A, v, mesh, max_iters=inner_iters, tol=inner_tol
        )
        w = w / np.linalg.norm(w)
        Aw = dist_spmv_replicated_check(A, w, mesh)
        lam = float(w @ Aw)
        res = float(np.linalg.norm(Aw - lam * w)) / max(
            abs(lam), 1e-30
        )
        v = w
        if res < tol:
            break
    return lam, v, it, res
