"""Multi-level distributed AMG setup (reference distributed setup loop
src/amg.cu:425-660 setup_v2, distributed Galerkin with halo-row P/RAP
exchange classical_amg_level.cu:297-318 + distributed_arranger.cu
exchange_RAP_ext, consolidation glue.h:200).

TPU-first structure
-------------------
Setup runs on host per *shard*: every coarsening step consumes only a
shard's owned rows plus one-ring halo data, so on a multi-host
deployment each process holds ~global/N of every level.  The steps per
level, mirroring the reference flow:

  1. shard-local aggregation on the owned submatrix (geometric blocks
     when the local box is stencil-structured, matching handshake
     otherwise) — aggregates never span shards, so P and R are block-
     diagonal across shards and restriction/prolongation need NO
     communication in the solve;
  2. halo P-row exchange: a shard fetches the P rows of its fine halo
     nodes from their owners (reference exchange_halo_rows_P);
  3. shard-local Galerkin rows: Ac_p = P_pᵀ (A_p P_ext) — the coarse
     rows owned by p, with columns in global coarse numbering
     (reference exchange_RAP_ext + csr_RAP_sparse_add);
  4. owned-first renumber of the coarse level (halo appended) and a new
     neighbor-exchange plan.

Coarsening continues until the global coarse size drops below the
consolidation threshold; the remaining hierarchy is *consolidated*
(gathered and replicated on every chip — reference glue_matrices) where
coarse work is too small to shard profitably.  The solve-side cycle
runs the distributed levels with ppermute halo exchange and damped
Jacobi smoothing, then the replicated tail as a standard AMG cycle.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

import numpy as np
import scipy.sparse as sps

from amgx_tpu.distributed.partition import (
    DistributedMatrix,
    finalize_partition,
    local_numbering,
    localize_columns,
    partition_rows,
)

# Stop sharding below this global size: coarse grids this small cannot
# feed N chips and the replicated tail costs zero communication
# (reference matrix_consolidation_lower_threshold semantics).
_CONSOLIDATE_ROWS = 4096

# Graded consolidation (reference glue_matrices, amg.cu:302-360): when
# the AVERAGE owned rows per active shard drops below _GRADE_LOWER,
# group shards (progressive power-of-two halving) and give each
# group's coarse rows to its leader until the average recovers — the
# sub-mesh tier between fully sharded and fully replicated.  Grading
# moves OWNERSHIP only; aggregation already ran per original shard, so
# the preconditioner is algorithmically unchanged at the graded level
# itself.  0 disables.
_GRADE_LOWER = 1024


@dataclasses.dataclass
class DistLevel:
    """One distributed level: sharded operator + grid-transfer blocks."""

    A: DistributedMatrix
    # P block of shard p: owned fine rows x owned coarse cols (local
    # numbering both sides); stacked padded ELL [N, rows_pp, wp].
    P_cols: Optional[np.ndarray] = None
    P_vals: Optional[np.ndarray] = None
    # R = P^T block: owned coarse rows x owned fine cols.
    R_cols: Optional[np.ndarray] = None
    R_vals: Optional[np.ndarray] = None
    # graded-consolidation bridge into THIS level's coarse grid:
    # (perms_down, is_leader) — perms_down[j] is step j of a stride-2^j
    # ppermute REDUCTION TREE toward each group leader (the reference's
    # glue_vector): the consumer must accumulate (rc += ppermute(rc))
    # between steps, so forwarded values are subtree sums; prolongation
    # replays the inverted steps in reverse order.  None when the
    # coarse grid keeps one part per shard.
    bridge: Any = None


@dataclasses.dataclass
class DistHierarchy:
    levels: List[DistLevel]
    # consolidated (replicated) tail: a host scipy matrix in the LOCAL
    # row order of the deepest distributed level's coarse numbering
    tail_matrix: Any = None
    # mapping: stacked coarse vector [N, rows_pp] <-> tail global rows
    tail_owner: Optional[np.ndarray] = None
    tail_local_of: Optional[np.ndarray] = None


def _local_aggregate(A_pp: sps.csr_matrix, cfg, scope) -> np.ndarray:
    """Aggregate one shard's owned submatrix — the same selector
    decision as the serial path (shared helper)."""
    from amgx_tpu.amg.aggregation import select_aggregates

    return select_aggregates(A_pp, cfg, scope)[0]


class _ShardedLevelCSR:
    """Host-side per-shard CSR state of one level (the arranger's view:
    owned rows, local columns owned-first + halo, global halo ids)."""

    def __init__(self, shards, halo_globs, g_rows, owner, local_of,
                 counts):
        self.shards = shards  # list[sps.csr_matrix] local cols
        self.halo_globs = halo_globs  # list[np.ndarray] global ids
        self.g_rows = g_rows  # list[np.ndarray] owned global row ids
        self.owner = owner
        self.local_of = local_of
        self.counts = counts

    @property
    def n_parts(self):
        return len(self.shards)

    @property
    def n_global(self):
        return int(self.counts.sum())


def _shard_the_matrix(Asp, owner, n_parts) -> _ShardedLevelCSR:
    """Initial sharding of the (fine) matrix — the stand-in for the
    reference's distributed upload; each entry of `shards` is what one
    rank would hold."""
    local_of, counts, part_rows = local_numbering(owner, n_parts)
    rows_pp = max(int(counts.max()), 1)
    shards, halo_globs = [], []
    for p in range(n_parts):
        local = Asp[part_rows[p]].tocsr()
        d = localize_columns(
            local.indptr, local.indices, local.data, owner, local_of,
            p, rows_pp,
        )
        nloc = rows_pp + len(d["halo_glob"])
        shards.append(
            sps.csr_matrix(
                (d["vals"], d["cols"], d["indptr"]),
                shape=(counts[p], nloc),
            )
        )
        halo_globs.append(d["halo_glob"])
    return _ShardedLevelCSR(
        shards, halo_globs, part_rows, owner, local_of, counts
    )


def _level_device_arrays(lvl: _ShardedLevelCSR) -> DistributedMatrix:
    """Exchange plan + stacked arrays for one level's sharded operator."""
    rows_pp = max(int(lvl.counts.max()), 1)
    parts = []
    for p in range(lvl.n_parts):
        s = lvl.shards[p]
        parts.append(
            dict(
                indptr=s.indptr,
                cols=s.indices.astype(np.int32),
                vals=s.data,
                halo_glob=lvl.halo_globs[p],
            )
        )
    return finalize_partition(
        parts, lvl.owner, lvl.local_of, lvl.counts, lvl.n_global,
        lvl.n_parts,
    )


def _pad_ell_blocks(mats, rows_pad):
    """Stack per-shard CSR blocks as padded ELL [N, rows_pad, w]."""
    n_parts = len(mats)
    w = 1
    for m in mats:
        lens = np.diff(m.indptr)
        if lens.size:
            w = max(w, int(lens.max()))
    dtype = mats[0].dtype if mats else np.float64
    cols = np.zeros((n_parts, rows_pad, w), dtype=np.int32)
    vals = np.zeros((n_parts, rows_pad, w), dtype=dtype)
    for p, m in enumerate(mats):
        lens = np.diff(m.indptr)
        rid = np.repeat(np.arange(m.shape[0]), lens)
        pos = np.arange(m.indices.shape[0]) - m.indptr[rid].astype(
            np.int64
        )
        cols[p, rid, pos] = m.indices
        vals[p, rid, pos] = m.data
    return cols, vals


def _grade_groups(ncs, grade_lower):
    """Grouping of active shards for graded consolidation.

    Returns ``(lead_of, moff, perms_down, is_leader)`` or None when no
    grading applies.  ``lead_of[p]``/``moff[p]`` place shard p's coarse
    block inside its leader's row range; ``perms_down[j]`` is step j of
    a stride-2^j reduction tree toward the leaders — consumers MUST
    accumulate between steps (see DistLevel.bridge).
    """
    ncs = np.asarray(ncs, dtype=np.int64)
    n_parts = ncs.shape[0]
    active = np.nonzero(ncs > 0)[0]
    na = len(active)
    if na <= 1 or grade_lower <= 0:
        return None
    nc_global = int(ncs.sum())
    if nc_global / na >= grade_lower:
        return None
    # smallest power-of-two grouping restoring avg >= grade_lower —
    # progressive halving, so successive levels step through sub-mesh
    # tiers rather than collapsing to one shard at once
    g = 1
    while (na // g) > 1 and nc_global / (na // g) < grade_lower:
        g *= 2
    if g == 1:
        return None
    lead_of = np.arange(n_parts, dtype=np.int32)
    moff = np.zeros(n_parts, dtype=np.int64)
    is_leader = np.zeros(n_parts, dtype=bool)
    groups = []
    for i in range(0, na, g):
        members = active[i: i + g]
        leader = int(members[0])
        is_leader[leader] = True
        groups.append(members)
        off = 0
        for p in members:
            lead_of[p] = leader
            moff[p] = off
            off += int(ncs[p])
    # log-depth reduction tree: step s sends relative position j+s ->
    # j for j % 2s == 0, so glue/unglue cost log2(g) collective steps
    # (the cycle ACCUMULATES between steps — subtree sums ride up)
    perms_down = []
    s = 1
    while s < g:
        step = []
        for members in groups:
            for j in range(0, len(members) - s, 2 * s):
                step.append((int(members[j + s]), int(members[j])))
        if step:
            perms_down.append(tuple(step))
        s *= 2
    return lead_of, moff, tuple(perms_down), is_leader


def build_distributed_hierarchy(
    Asp: sps.csr_matrix,
    n_parts: int,
    cfg,
    scope: str,
    grid=None,
    owner=None,
    max_levels: int = 20,
    consolidate_rows: int = _CONSOLIDATE_ROWS,
    grade_lower: int = _GRADE_LOWER,
) -> DistHierarchy:
    """The distributed setup loop (reference amg.cu:425-660)."""
    from amgx_tpu.amg.aggregation import infer_grid, stencil_offsets

    n = Asp.shape[0]
    Asp = Asp.tocsr()
    Asp.sort_indices()
    if owner is None:
        if grid is None:
            offs = stencil_offsets(Asp)
            grid = infer_grid(offs, n) if offs is not None else None
        owner, _ = partition_rows(n, n_parts, grid)
    else:
        owner = np.asarray(owner, dtype=np.int32)

    lvl = _shard_the_matrix(Asp, owner, n_parts)
    levels: List[DistLevel] = []

    while (
        lvl.n_global > consolidate_rows and len(levels) < max_levels
    ):
        rows_pp = max(int(lvl.counts.max()), 1)
        # 1. shard-local aggregation on the owned submatrix
        aggs, ncs = [], []
        for p in range(lvl.n_parts):
            A_pp = lvl.shards[p][:, : lvl.counts[p]]
            # owned cols use local slots 0..counts-1 (padding-free view)
            A_pp = A_pp.tocsr()
            agg = _local_aggregate(A_pp, cfg, scope)
            aggs.append(agg)
            ncs.append(int(agg.max()) + 1 if agg.size else 0)
        nc_global = int(np.sum(ncs))
        if nc_global >= lvl.n_global or nc_global == 0:
            break  # coarsening stalled

        # graded consolidation (sub-mesh tier): leaders own their whole
        # group's coarse block; members' restricted partials ride the
        # bridge ppermutes (reference glue_vector/unglue_vector)
        graded = _grade_groups(ncs, grade_lower)
        if graded is not None:
            lead_of, moff, perms_down, is_leader = graded
            bridge = (perms_down, is_leader)
        else:
            lead_of = np.arange(lvl.n_parts, dtype=np.int32)
            moff = np.zeros(lvl.n_parts, dtype=np.int64)
            bridge = None

        # coarse global numbering: leader L owns one contiguous block
        # holding its members' aggregates back to back (no grading:
        # leader = shard, the per-shard blocks of before)
        nc_lead = np.zeros(lvl.n_parts, dtype=np.int64)
        for p in range(lvl.n_parts):
            nc_lead[lead_of[p]] += ncs[p]
        goffs = np.concatenate([[0], np.cumsum(nc_lead)[:-1]])
        # base coarse id of shard p's aggregates
        cbase = goffs[lead_of] + moff
        owner_c = np.empty(nc_global, dtype=np.int32)
        for p in range(lvl.n_parts):
            if ncs[p]:
                owner_c[cbase[p]: cbase[p] + ncs[p]] = lead_of[p]

        # per-shard P (owned fine x LEADER-local coarse slots)
        P_blocks = [
            sps.csr_matrix(
                (
                    np.ones(lvl.counts[p], dtype=lvl.shards[p].dtype),
                    (np.arange(lvl.counts[p]), moff[p] + aggs[p]),
                ),
                shape=(lvl.counts[p], int(nc_lead[lead_of[p]])),
            )
            for p in range(lvl.n_parts)
        ]

        # 2+3. halo P-row exchange and shard-local Galerkin rows:
        # P_ext maps every LOCAL column of A_p (owned + halo) to global
        # coarse ids; halo rows come from the owning shard's aggregate
        # map — the single-process arranger reads them directly (a real
        # multi-host build ships them point-to-point).
        # global fine id -> global coarse id (the union of all shards'
        # aggregate maps; each entry is produced by exactly one owner)
        gagg = np.empty(lvl.n_global, dtype=np.int64)
        for p in range(lvl.n_parts):
            gagg[lvl.g_rows[p]] = cbase[p] + aggs[p]

        # per-LEADER RAP: members' partial products land on leader-local
        # rows and are sparse-added (reference csr_RAP_sparse_add /
        # exchange_RAP_ext — here the single-process arranger sums them
        # directly)
        rap = {}
        for p in range(lvl.n_parts):
            A_p = lvl.shards[p]
            nloc = A_p.shape[1]
            # local col -> global coarse id
            col_to_gc = np.empty(nloc, dtype=np.int64)
            col_to_gc[: lvl.counts[p]] = cbase[p] + aggs[p]
            if rows_pp > lvl.counts[p]:
                col_to_gc[lvl.counts[p]: rows_pp] = 0  # padding, no nnz
            hg = lvl.halo_globs[p]
            if len(hg):
                col_to_gc[rows_pp: rows_pp + len(hg)] = gagg[hg]
            # AP with global coarse columns
            coo = A_p.tocoo()
            AP = sps.csr_matrix(
                (coo.data, (coo.row, col_to_gc[coo.col])),
                shape=(lvl.counts[p], nc_global),
            )
            AP.sum_duplicates()
            Ac_p = (P_blocks[p].T @ AP).tocsr()  # (nc_lead, nc_global)
            L = int(lead_of[p])
            rap[L] = Ac_p if L not in rap else rap[L] + Ac_p

        # 4. owned-first renumber of the coarse level
        local_of_c, counts_c, g_rows_c = local_numbering(
            owner_c, lvl.n_parts
        )
        rows_pp_c = max(int(counts_c.max()), 1)
        new_shards, new_halos = [], []
        empty = sps.csr_matrix(
            (0, nc_global), dtype=Asp.dtype
        )
        for p in range(lvl.n_parts):
            m = rap.get(p, empty).tocsr()
            m.sum_duplicates()
            m.sort_indices()
            d = localize_columns(
                m.indptr, m.indices, m.data, owner_c, local_of_c, p,
                rows_pp_c,
            )
            nloc = rows_pp_c + len(d["halo_glob"])
            new_shards.append(
                sps.csr_matrix(
                    (d["vals"], d["cols"], d["indptr"]),
                    shape=(counts_c[p], nloc),
                )
            )
            new_halos.append(d["halo_glob"])

        # device arrays for this level (A + P/R stacked blocks)
        A_dev = _level_device_arrays(lvl)
        P_cols, P_vals = _pad_ell_blocks(P_blocks, rows_pp)
        R_blocks = [P_blocks[p].T.tocsr() for p in range(lvl.n_parts)]
        R_cols, R_vals = _pad_ell_blocks(R_blocks, rows_pp_c)
        levels.append(
            DistLevel(
                A=A_dev, P_cols=P_cols, P_vals=P_vals,
                R_cols=R_cols, R_vals=R_vals, bridge=bridge,
            )
        )

        lvl = _ShardedLevelCSR(
            new_shards, new_halos, g_rows_c, owner_c, local_of_c,
            counts_c,
        )

    # deepest distributed level (operator only; smoothed, no transfer)
    levels.append(DistLevel(A=_level_device_arrays(lvl)))

    # consolidated tail: gather the last level's rows into one host
    # matrix in GLOBAL coarse numbering (reference glue_matrices)
    rows, cols, vals = [], [], []
    for p in range(lvl.n_parts):
        m = lvl.shards[p].tocoo()
        rows_pp_l = max(int(lvl.counts.max()), 1)
        hg = lvl.halo_globs[p]
        col_to_g = np.empty(m.shape[1], dtype=np.int64)
        col_to_g[: lvl.counts[p]] = lvl.g_rows[p]
        if rows_pp_l > lvl.counts[p]:
            col_to_g[lvl.counts[p]: rows_pp_l] = 0
        if len(hg):
            col_to_g[rows_pp_l: rows_pp_l + len(hg)] = hg
        rows.append(lvl.g_rows[p][m.row])
        cols.append(col_to_g[m.col])
        vals.append(m.data)
    tail = sps.csr_matrix(
        (
            np.concatenate(vals),
            (np.concatenate(rows), np.concatenate(cols)),
        ),
        shape=(lvl.n_global, lvl.n_global),
    )
    tail.sum_duplicates()
    tail.sort_indices()

    return DistHierarchy(
        levels=levels,
        tail_matrix=tail,
        tail_owner=lvl.owner,
        tail_local_of=lvl.local_of,
    )
