"""Multi-level distributed AMG setup (reference distributed setup loop
src/amg.cu:425-660 setup_v2, distributed Galerkin with halo-row P/RAP
exchange classical_amg_level.cu:297-318 + distributed_arranger.cu
exchange_RAP_ext, consolidation glue.h:200).

TPU-first structure
-------------------
Setup runs on host **per part**: every coarsening step consumes only a
part's owned rows plus one-ring halo data, and every cross-part byte
flows through the :mod:`amgx_tpu.distributed.comm` fabric — a part's
setup never indexes another part's arrays, so on a multi-host
deployment each process holds ~global/N of every level (the reference's
per-rank setup_v2 shape).  The steps per level:

  1. part-local aggregation on the owned submatrix (geometric blocks
     when the local box is stencil-structured, matching handshake
     otherwise) — aggregates never span parts, so P and R are block-
     diagonal across parts and restriction/prolongation need NO
     communication in the solve;
  2. halo coarse-id fetch: a part requests the coarse assignment of its
     fine halo nodes from their owners (reference exchange_halo_rows_P)
     — one O(boundary) request/answer round on the comm fabric;
  3. part-local Galerkin rows: Ac_p = P_pᵀ (A_p P_ext) — the coarse
     rows owned by p, with columns in global coarse numbering
     (reference exchange_RAP_ext + csr_RAP_sparse_add); under graded
     consolidation the partial rows ride the fabric to their group
     leader, which sparse-adds them in part order;
  4. owned-first renumber of the coarse level against ANALYTIC coarse
     ownership (leaders own contiguous id blocks — O(n_parts) offsets,
     no global-length arrays) and a new neighbor-exchange plan built
     from allgathered O(boundary) halo-id lists.

Coarsening continues until the global coarse size drops below the
consolidation threshold; the remaining hierarchy is *consolidated*
(gathered and replicated on every chip — reference glue_matrices) where
coarse work is too small to shard profitably.  The solve-side cycle
runs the distributed levels with ppermute halo exchange, then the
replicated tail as a standard AMG cycle.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np
import scipy.sparse as sps

from amgx_tpu.distributed.comm import (
    LoopbackComm,
    fetch_by_owner,
)
from amgx_tpu.distributed.partition import (
    ArrayOwnership,
    DistributedMatrix,
    OffsetOwnership,
    Ownership,
    finalize_partition,
    halo_localize,
    localize_columns,
    partition_rows,
)

# Stop sharding below this global size: coarse grids this small cannot
# feed N chips and the replicated tail costs zero communication
# (reference matrix_consolidation_lower_threshold semantics).
_CONSOLIDATE_ROWS = 4096

# Graded consolidation (reference glue_matrices, amg.cu:302-360): when
# the AVERAGE owned rows per active shard drops below _GRADE_LOWER,
# group shards (progressive power-of-two halving) and give each
# group's coarse rows to its leader until the average recovers — the
# sub-mesh tier between fully sharded and fully replicated.  Grading
# moves OWNERSHIP only; aggregation already ran per original shard, so
# the preconditioner is algorithmically unchanged at the graded level
# itself.  0 disables.
_GRADE_LOWER = 1024


def _stop_rows(own, stop_measure: str) -> int:
    """Coarsening-stop row measure (reference amg.cu:333-360): the sum
    of partition rows by default, or the worst (smallest) partition
    scaled to the part count with stop_measure="min"
    (use_sum_stopping_criteria=0 semantics)."""
    if stop_measure == "min":
        return int(np.asarray(own.counts).min()) * len(own.counts)
    return own.n_global


@dataclasses.dataclass
class DistLevel:
    """One distributed level: sharded operator + grid-transfer blocks."""

    A: DistributedMatrix
    # P block of shard p: owned fine rows x owned coarse cols (local
    # numbering both sides); stacked padded ELL [N, rows_pp, wp].
    P_cols: Optional[np.ndarray] = None
    P_vals: Optional[np.ndarray] = None
    # R = P^T block: owned coarse rows x owned fine cols.
    R_cols: Optional[np.ndarray] = None
    R_vals: Optional[np.ndarray] = None
    # graded-consolidation bridge into THIS level's coarse grid:
    # (perms_down, is_leader) — perms_down[j] is step j of a stride-2^j
    # ppermute REDUCTION TREE toward each group leader (the reference's
    # glue_vector): the consumer must accumulate (rc += ppermute(rc))
    # between steps, so forwarded values are subtree sums; prolongation
    # replays the inverted steps in reverse order.  None when the
    # coarse grid keeps one part per shard.
    bridge: Any = None
    # classical levels: P couples shards — P_cols index the COARSE
    # level's extended local numbering (owned slots + coarse halo), so
    # prolongation needs a coarse halo exchange and restriction a
    # reverse (accumulating) exchange; R_cols/R_vals are unused
    # (R = P^T is applied by scatter-add + reverse exchange).
    classical: bool = False


@dataclasses.dataclass
class DistHierarchy:
    levels: List[DistLevel]
    # consolidated (replicated) tail: a host scipy matrix in the LOCAL
    # row order of the deepest distributed level's coarse numbering
    tail_matrix: Any = None
    # mapping: stacked coarse vector [N, rows_pp] <-> tail global rows
    tail_owner: Optional[np.ndarray] = None
    tail_local_of: Optional[np.ndarray] = None
    # per-process setup accounting: comm traffic + peak per-part sizes
    # (the O(global/N) memory-contract evidence)
    setup_stats: Optional[dict] = None
    # the setup comm fabric, kept for post-setup collective rounds
    # (smoother-metadata consensus, solution gather) — every process
    # must keep issuing matched rounds (SPMD)
    comm: Any = None


def _local_aggregate(A_pp: sps.csr_matrix, cfg, scope) -> np.ndarray:
    """Aggregate one shard's owned submatrix — the same selector
    decision as the serial path (shared helper)."""
    from amgx_tpu.amg.aggregation import select_aggregates

    return select_aggregates(A_pp, cfg, scope)[0]


def _stack_level_blocks(blocks_by_p, rows_pad, comm, mesh=None):
    """Stack per-part CSR transfer blocks (P/R) as padded ELL
    [N, rows_pad, w].

    The ELL width w is a comm-wide consensus (one scalar allgather) so
    every process traces identical static shapes.  All parts local ->
    stacked numpy; subset of parts -> per-part ``jax.Array``s sharded
    over ``mesh`` (the per-rank assembly shape).
    """
    n_parts = comm.n_parts
    meta = comm.allgather(
        {
            p: (int(np.diff(m.indptr).max(initial=0)),
                np.dtype(m.dtype).str)
            for p, m in blocks_by_p.items()
        },
        kind="transfer-width",
    )
    w = max(max(m[0] for m in meta), 1)
    dtype = np.dtype(meta[0][1])
    per = {}
    for p, m in blocks_by_p.items():
        cols = np.zeros((rows_pad, w), dtype=np.int32)
        vals = np.zeros((rows_pad, w), dtype=dtype)
        lens = np.diff(m.indptr)
        rid = np.repeat(np.arange(m.shape[0]), lens)
        pos = np.arange(m.indices.shape[0]) - m.indptr[rid].astype(
            np.int64
        )
        cols[rid, pos] = m.indices
        vals[rid, pos] = m.data
        per[p] = (cols, vals)
    if len(blocks_by_p) == n_parts:
        return (
            np.stack([per[p][0] for p in range(n_parts)]),
            np.stack([per[p][1] for p in range(n_parts)]),
        )
    from amgx_tpu.distributed.multihost import stack_parts_sharded

    return (
        stack_parts_sharded(
            {p: c for p, (c, _) in per.items()}, mesh, n_parts
        ),
        stack_parts_sharded(
            {p: v for p, (_, v) in per.items()}, mesh, n_parts
        ),
    )


def _grade_groups(ncs, grade_lower):
    """Grouping of active shards for graded consolidation.

    Returns ``(lead_of, moff, perms_down, is_leader)`` or None when no
    grading applies.  ``lead_of[p]``/``moff[p]`` place shard p's coarse
    block inside its leader's row range; ``perms_down[j]`` is step j of
    a stride-2^j reduction tree toward the leaders — consumers MUST
    accumulate between steps (see DistLevel.bridge).
    """
    ncs = np.asarray(ncs, dtype=np.int64)
    n_parts = ncs.shape[0]
    active = np.nonzero(ncs > 0)[0]
    na = len(active)
    if na <= 1 or grade_lower <= 0:
        return None
    nc_global = int(ncs.sum())
    if nc_global / na >= grade_lower:
        return None
    # smallest power-of-two grouping restoring avg >= grade_lower —
    # progressive halving, so successive levels step through sub-mesh
    # tiers rather than collapsing to one shard at once
    g = 1
    while (na // g) > 1 and nc_global / (na // g) < grade_lower:
        g *= 2
    if g == 1:
        return None
    lead_of = np.arange(n_parts, dtype=np.int32)
    moff = np.zeros(n_parts, dtype=np.int64)
    is_leader = np.zeros(n_parts, dtype=bool)
    groups = []
    for i in range(0, na, g):
        members = active[i: i + g]
        leader = int(members[0])
        is_leader[leader] = True
        groups.append(members)
        off = 0
        for p in members:
            lead_of[p] = leader
            moff[p] = off
            off += int(ncs[p])
    # log-depth reduction tree: step s sends relative position j+s ->
    # j for j % 2s == 0, so glue/unglue cost log2(g) collective steps
    # (the cycle ACCUMULATES between steps — subtree sums ride up)
    perms_down = []
    s = 1
    while s < g:
        step = []
        for members in groups:
            for j in range(0, len(members) - s, 2 * s):
                step.append((int(members[j + s]), int(members[j])))
        if step:
            perms_down.append(tuple(step))
        s *= 2
    return lead_of, moff, tuple(perms_down), is_leader


def _sparsify_offpart_rows(m, own_c, p, theta, d_own, offcols,
                           answers_by_o):
    """Communication-reduced coarse rows (the stencil-sparsification
    idea of arxiv 1512.04629 / SParSH-AMG's halo trimming): drop WEAK
    off-part entries of one part's summed Galerkin rows and lump the
    dropped mass onto the row diagonal.

    The drop test is the strength-of-connection criterion
    ``|a_ij| < theta * sqrt(|a_ii a_jj|)`` — symmetric by construction
    (both sides of a cross-part edge evaluate the same quantity, so an
    entry and its transpose are dropped together and a symmetric
    operator stays symmetric), and boundary-consistent (the remote
    diagonal ``a_jj`` was fetched from its owner, not estimated).
    Diagonal lumping preserves row sums, so the action on the
    aggregation near-kernel (constants) is exact; only smoothing of
    oscillatory cross-boundary error weakens, which the outer Krylov
    absorbs (iteration-parity gated by tests/ci).

    ``m`` is the (owned coarse rows x global coarse cols) CSR;
    ``offcols`` its sorted unique off-part columns; ``answers_by_o``
    the fetched diagonals aligned with the per-owner request order.
    Returns ``(sparsified m, entries dropped)``.
    """
    coo = m.tocoo()
    owners_col = own_c.owner_of(coo.col)
    offp = owners_col != p
    if not offp.any():
        return m, 0
    g_rows = own_c.global_rows(p)
    # diagonal magnitude per entry: own rows from d_own; off-part
    # columns from the owner-fetched map
    dmap = np.empty(len(offcols), dtype=np.float64)
    owners_u = own_c.owner_of(offcols)
    for o, vals in answers_by_o.items():
        dmap[owners_u == o] = np.abs(np.asarray(vals, dtype=np.float64))
    dcol = np.empty(coo.col.shape[0], dtype=np.float64)
    ow = ~offp
    dcol[ow] = np.abs(
        np.asarray(d_own, dtype=np.float64)[
            own_c.local_of_ids(coo.col[ow])
        ]
    )
    dcol[offp] = dmap[np.searchsorted(offcols, coo.col[offp])]
    drow = np.abs(np.asarray(d_own, dtype=np.float64))[coo.row]
    weak = offp & (
        np.abs(coo.data) < theta * np.sqrt(drow * dcol)
    )
    n_drop = int(weak.sum())
    if n_drop == 0:
        return m, 0
    lump = np.zeros(m.shape[0], dtype=coo.data.dtype)
    np.add.at(lump, coo.row[weak], coo.data[weak])
    keep = ~weak
    rows = np.concatenate([coo.row[keep], np.arange(m.shape[0])])
    cols = np.concatenate([coo.col[keep], g_rows])
    data = np.concatenate([coo.data[keep], lump])
    m2 = sps.csr_matrix((data, (rows, cols)), shape=m.shape)
    m2.sum_duplicates()
    m2.sort_indices()
    return m2, n_drop


def _sparsify_coarse_level(rap, own_c, comm, my_parts, theta):
    """One comm round + per-part sparsification of the freshly summed
    coarse Galerkin rows: each part extracts its OWNED coarse diagonal,
    off-part column diagonals ride an O(boundary) fetch_by_owner round
    (the same fabric shape as the halo coarse-id fetch), then weak
    cross-part entries are dropped diagonal-lumped.  Returns
    ``(total entries dropped, off-part columns before, after)`` — the
    halo-width evidence for setup_stats/telemetry.

    MUST be called on every process of a multi-process launch even
    when theta <= 0 is handled by the caller — the fetch round is
    collective (SPMD round matching).
    """
    # owned coarse diagonals (complete: leaders already summed RAP)
    diag_own = {}
    for p in my_parts:
        d = np.zeros(int(own_c.counts[p]), dtype=np.float64)
        m = rap.get(p)
        if m is not None:
            coo = m.tocoo()
            hit = coo.col == own_c.global_rows(p)[coo.row]
            np.add.at(d, coo.row[hit], coo.data[hit].real
                      if np.iscomplexobj(coo.data) else coo.data[hit])
        diag_own[p] = d
    requests = {}
    offcols = {}
    halo_before = 0
    for p in my_parts:
        m = rap.get(p)
        if m is None:
            continue
        cols = m.tocoo().col
        oc = np.unique(cols[own_c.owner_of(cols) != p])
        if oc.size == 0:
            continue
        offcols[p] = oc
        halo_before += int(oc.size)
        owners = own_c.owner_of(oc)
        requests[p] = {
            int(o): oc[owners == o] for o in np.unique(owners)
        }
    answers = fetch_by_owner(
        comm,
        requests,
        lambda o, ids: diag_own[o][own_c.local_of_ids(ids)],
        kind="sparsify-diag",
    )
    dropped = 0
    halo_after = 0
    for p in my_parts:
        if p not in offcols:
            continue
        rap[p], nd = _sparsify_offpart_rows(
            rap[p], own_c, p, theta, diag_own[p], offcols[p],
            answers.get(p, {}),
        )
        dropped += nd
        cols = rap[p].tocoo().col
        halo_after += int(
            np.unique(cols[own_c.owner_of(cols) != p]).size
        )
    return dropped, halo_before, halo_after


def _finalize_level(
    parts_by_p: Dict[int, dict],
    own: Ownership,
    comm: LoopbackComm,
    proc_grid=None,
    mesh=None,
) -> DistributedMatrix:
    """Exchange plan + stacked device arrays for one level.

    Single-process (Loopback): every part is local, so the stacked
    [N, rows, w] numpy arrays are assembled directly.  Multi-process
    (this process drives a subset of parts): each process assembles
    per-part ``jax.Array``s for its own parts only, sharded one part
    per device of ``mesh`` — the reference's per-rank level assembly
    (amg.cu:425-660 setup_v2 builds every coarse level per rank).
    """
    n_parts = own.n_parts
    if len(parts_by_p) != n_parts:
        if mesh is None:
            raise ValueError(
                "process drives a subset of parts but no mesh was "
                "supplied for sharded device assembly (pass mesh= "
                "through the builder / DistributedAMG.from_local_parts)"
            )
        from amgx_tpu.distributed.multihost import (
            assemble_level_sharded,
        )

        return assemble_level_sharded(
            parts_by_p, own, comm, mesh, proc_grid=proc_grid
        )
    parts = [parts_by_p[p] for p in range(n_parts)]
    dm = finalize_partition(
        parts, None, None, own.counts, own.n_global, n_parts,
        proc_grid=proc_grid,
        owner_fn=own.owner_of, local_fn=own.local_of_ids,
    )
    if not own.offset_blocks:
        # owner=None pad/unpad assumes contiguous-by-offset blocks;
        # other ownerships (grid slabs, arbitrary vectors) attach the
        # materialized maps — single-process conveniences that hold the
        # global matrix anyway
        dm.owner, dm.local_of = own.materialize()
    return dm


def init_lvl_parts(local_parts, ownership: Ownership, my_parts):
    """Localized part dicts -> the per-part csr level state both
    builders (aggregation and classical) iterate on."""
    rows_pp0 = max(int(ownership.counts.max()), 1)

    def as_csr(part, counts_p):
        nloc = rows_pp0 + len(part["halo_glob"])
        return sps.csr_matrix(
            (part["vals"], part["cols"], part["indptr"]),
            shape=(counts_p, nloc),
        )

    return {
        p: dict(
            A=as_csr(local_parts[p], int(ownership.counts[p])),
            halo_glob=np.asarray(
                local_parts[p]["halo_glob"], dtype=np.int64
            ),
        )
        for p in my_parts
    }


def finish_distributed_hierarchy(
    lvl_parts, lvl_own: Ownership, comm, levels, proc_grid,
    max_part_nnz: int, max_part_rows: int, my_parts, mesh=None,
) -> DistHierarchy:
    """Shared tail of both distributed builders: finalize the deepest
    level (materializing its small owner maps for the cycle's
    consolidation gather), allgather the consolidated tail matrix
    (reference glue_matrices — O(tail nnz) per part, bounded by the
    consolidation threshold), and package the traffic stats."""
    counts_L = lvl_own.counts
    rows_pp_L = max(int(counts_L.max()), 1)
    A_last = _finalize_level(
        lvl_parts_to_parts(lvl_parts), lvl_own, comm,
        proc_grid=proc_grid if not levels else None,
        mesh=mesh,
    )
    owner_L, local_L = lvl_own.materialize()
    A_last.owner = owner_L
    A_last.local_of = local_L
    levels.append(DistLevel(A=A_last))

    tail_local = {}
    for p in my_parts:
        m = lvl_parts[p]["A"].tocoo()
        hg = lvl_parts[p]["halo_glob"]
        col_to_g = np.zeros(m.shape[1], dtype=np.int64)
        g_rows = lvl_own.global_rows(p)
        col_to_g[: counts_L[p]] = g_rows
        if len(hg):
            col_to_g[rows_pp_L: rows_pp_L + len(hg)] = hg
        tail_local[p] = (g_rows[m.row], col_to_g[m.col], m.data)
    gathered = comm.allgather(tail_local, kind="tail-glue")
    rows = [t[0] for t in gathered]
    cols = [t[1] for t in gathered]
    vals = [t[2] for t in gathered]
    ng_L = lvl_own.n_global
    tail = sps.csr_matrix(
        (
            np.concatenate(vals) if vals else np.zeros(0),
            (
                np.concatenate(rows) if rows else np.zeros(0, int),
                np.concatenate(cols) if cols else np.zeros(0, int),
            ),
        ),
        shape=(ng_L, ng_L),
    )
    tail.sum_duplicates()
    tail.sort_indices()

    stats = dict(
        comm_total_bytes=comm.stats.total_bytes,
        comm_max_msg_bytes=comm.stats.max_msg_bytes,
        comm_rounds=len(comm.stats.rounds),
        max_part_nnz=int(max_part_nnz),
        max_part_rows=int(max_part_rows),
        n_parts=comm.n_parts,
    )
    return DistHierarchy(
        levels=levels,
        tail_matrix=tail,
        tail_owner=owner_L,
        tail_local_of=local_L,
        setup_stats=stats,
        comm=comm,
    )


def build_distributed_hierarchy_local(
    local_parts: Dict[int, dict],
    ownership: Ownership,
    cfg,
    scope: str,
    comm: Optional[LoopbackComm] = None,
    max_levels: int = 20,
    consolidate_rows: int = _CONSOLIDATE_ROWS,
    grade_lower: int = _GRADE_LOWER,
    proc_grid=None,
    mesh=None,
    stop_measure: str = "sum",
    sparsify_theta: float = 0.0,
    sparsify_from_level: int = 1,
) -> DistHierarchy:
    """The distributed setup loop from per-process local blocks
    (reference per-rank setup_v2, amg.cu:425-660).

    ``sparsify_theta`` > 0 enables communication-reduced coarse grids
    (``dist_coarse_sparsify``): after each level's Galerkin rows are
    summed, weak CROSS-PART entries (|a_ij| < theta sqrt|a_ii a_jj|,
    remote diagonals owner-fetched) are dropped diagonal-lumped before
    the coarse halo is built — capping the halo width growth that
    otherwise makes coarse-level exchanges latency-dominated
    (arxiv 1512.04629's stencil sparsification, SParSH-AMG's halo
    trimming).  Per-level drop/halo counts land in
    ``setup_stats["sparsify"]``.

    ``local_parts[p]`` is the localized CSR dict of part p
    (``localize_columns``/``local_part_from_rows`` output: owned-first
    columns, appended halo slots, sorted ``halo_glob``) for each part
    this process drives (``comm.my_parts``).  ``ownership`` supplies
    analytic owner/local lookups (O(n_parts) state).  No step consumes
    a global-length array; cross-part data rides ``comm``.
    """
    if comm is None:
        from amgx_tpu.distributed.comm import default_comm

        comm = default_comm(ownership.n_parts)
    n_parts = ownership.n_parts
    my_parts = [p for p in comm.my_parts if p in local_parts]
    if sorted(local_parts) != sorted(my_parts):
        raise ValueError(
            f"local_parts {sorted(local_parts)} != comm.my_parts "
            f"{sorted(comm.my_parts)}"
        )
    max_part_nnz = 0
    max_part_rows = 0

    lvl_parts = init_lvl_parts(local_parts, ownership, my_parts)
    lvl_own: Ownership = ownership
    levels: List[DistLevel] = []
    sparsify_stats: List[dict] = []

    while (
        _stop_rows(lvl_own, stop_measure) > consolidate_rows
        and len(levels) < max_levels
    ):
        counts = lvl_own.counts
        rows_pp = max(int(counts.max()), 1)
        # 1. part-local aggregation on the owned submatrix
        aggs: Dict[int, np.ndarray] = {}
        ncs_local: Dict[int, int] = {}
        for p in my_parts:
            A_pp = lvl_parts[p]["A"][:, : counts[p]].tocsr()
            agg = _local_aggregate(A_pp, cfg, scope)
            aggs[p] = agg
            ncs_local[p] = int(agg.max()) + 1 if agg.size else 0
            max_part_nnz = max(max_part_nnz, lvl_parts[p]["A"].nnz)
            max_part_rows = max(max_part_rows, int(counts[p]))
        # replicate the per-part coarse counts (N ints) — every part
        # then derives the SAME grading + coarse numbering
        ncs = np.asarray(
            comm.allgather(ncs_local, kind="coarse-counts"),
            dtype=np.int64,
        )
        nc_global = int(ncs.sum())
        if nc_global >= lvl_own.n_global or nc_global == 0:
            break  # coarsening stalled

        # graded consolidation (sub-mesh tier): leaders own their whole
        # group's coarse block; members' restricted partials ride the
        # bridge ppermutes (reference glue_vector/unglue_vector)
        graded = _grade_groups(ncs, grade_lower)
        if graded is not None:
            lead_of, moff, perms_down, is_leader = graded
            bridge = (perms_down, is_leader)
        else:
            lead_of = np.arange(n_parts, dtype=np.int32)
            moff = np.zeros(n_parts, dtype=np.int64)
            bridge = None

        # coarse global numbering: leader L owns one contiguous block
        # holding its members' aggregates back to back -> coarse
        # ownership is ANALYTIC (offsets, O(n_parts) state)
        nc_lead = np.zeros(n_parts, dtype=np.int64)
        for p in range(n_parts):
            nc_lead[lead_of[p]] += ncs[p]
        coffsets = np.concatenate([[0], np.cumsum(nc_lead)])
        own_c = OffsetOwnership(coffsets)
        # base coarse id of part p's aggregates
        cbase = coffsets[lead_of] + moff

        # per-part P (owned fine x LEADER-local coarse slots)
        P_blocks = {
            p: sps.csr_matrix(
                (
                    np.ones(counts[p], dtype=lvl_parts[p]["A"].dtype),
                    (np.arange(counts[p]), moff[p] + aggs[p]),
                ),
                shape=(int(counts[p]), int(nc_lead[lead_of[p]])),
            )
            for p in my_parts
        }

        # 2. halo coarse-id fetch: each part requests gagg[h] =
        # cbase[owner(h)] + agg_owner[local(h)] for its halo ids from
        # their owners — O(boundary) ids each way on the fabric
        # (reference exchange_halo_rows_P; no global gagg array exists)
        requests: Dict[int, Dict[int, np.ndarray]] = {}
        for p in my_parts:
            hg = lvl_parts[p]["halo_glob"]
            if not len(hg):
                continue
            owners = lvl_own.owner_of(hg)
            requests[p] = {
                int(o): hg[owners == o] for o in np.unique(owners)
            }
        answers = fetch_by_owner(
            comm,
            requests,
            lambda o, ids: (
                cbase[o] + aggs[o][lvl_own.local_of_ids(ids)]
            ).astype(np.int64),
            kind="halo-agg",
        )

        # 3. part-local Galerkin rows with global coarse columns
        partial_rap: Dict[int, Dict[int, sps.csr_matrix]] = {}
        for p in my_parts:
            A_p = lvl_parts[p]["A"]
            nloc = A_p.shape[1]
            col_to_gc = np.zeros(nloc, dtype=np.int64)
            col_to_gc[: counts[p]] = cbase[p] + aggs[p]
            hg = lvl_parts[p]["halo_glob"]
            if len(hg):
                hvals = np.empty(len(hg), dtype=np.int64)
                owners = lvl_own.owner_of(hg)
                for o, vals in answers.get(p, {}).items():
                    hvals[owners == o] = vals
                col_to_gc[rows_pp: rows_pp + len(hg)] = hvals
            coo = A_p.tocoo()
            AP = sps.csr_matrix(
                (coo.data, (coo.row, col_to_gc[coo.col])),
                shape=(int(counts[p]), nc_global),
            )
            AP.sum_duplicates()
            Ac_p = (P_blocks[p].T @ AP).tocsr()  # (nc_lead, nc_global)
            partial_rap.setdefault(int(lead_of[p]), {})[p] = Ac_p

        # route members' partials to their leaders (reference
        # exchange_RAP_ext / csr_RAP_sparse_add); leaders sum in part
        # order so the result is independent of the transport
        outbox = {}
        for L, by_src in partial_rap.items():
            for src, Ac_p in by_src.items():
                if L in my_parts:
                    continue  # stays local
                c = Ac_p.tocoo()
                outbox[(src, L)] = (
                    c.row.astype(np.int64), c.col.astype(np.int64),
                    c.data, Ac_p.shape,
                )
        inbox = comm.alltoall(outbox, kind="rap-ext")
        rap: Dict[int, sps.csr_matrix] = {}
        for L in my_parts:
            if nc_lead[L] == 0:
                continue
            by_src = dict(partial_rap.get(L, {}))
            for (src, dst), (r, c, v, shp) in inbox.items():
                if dst == L:
                    by_src[src] = sps.csr_matrix(
                        (v, (r, c)), shape=shp
                    )
            acc = None
            for src in sorted(by_src):
                acc = (
                    by_src[src] if acc is None else acc + by_src[src]
                )
            if acc is not None:
                rap[L] = acc

        # 3b. communication-reduced coarse grid: sparsify weak
        # cross-part couplings of the summed Galerkin rows BEFORE the
        # coarse halo is derived from them (one O(boundary) diagonal
        # fetch round — SPMD-matched: theta and the level gate are
        # replicated config).  ``sparsify_from_level`` spares the
        # first coarse levels (still bandwidth-dominated, and the
        # levels where dropped couplings cost convergence most) and
        # trims the DEEP levels, where per-exchange latency dominates
        # the tiny payloads — the coarse-level latency wall.
        if (
            sparsify_theta > 0.0
            and len(levels) + 1 >= max(int(sparsify_from_level), 1)
        ):
            dropped, hb, ha = _sparsify_coarse_level(
                rap, own_c, comm, my_parts, float(sparsify_theta)
            )
            sparsify_stats.append(
                dict(level=len(levels) + 1, dropped=int(dropped),
                     offpart_cols_before=int(hb),
                     offpart_cols_after=int(ha))
            )

        # 4. owned-first renumber of the coarse level (analytic coarse
        # ownership; halo slots appended per part)
        rows_pp_c = max(int(own_c.counts.max()), 1)
        new_parts = {}
        for p in my_parts:
            m = rap.get(p)
            if m is None:
                m = sps.csr_matrix(
                    (0, nc_global), dtype=lvl_parts[p]["A"].dtype
                )
            m = m.tocsr()
            m.sum_duplicates()
            m.sort_indices()
            gcols = m.indices.astype(np.int64)
            is_owned = own_c.owner_of(gcols) == p
            cols, halo_glob = halo_localize(
                gcols, is_owned,
                own_c.local_of_ids(gcols[is_owned]), rows_pp_c,
            )
            nloc = rows_pp_c + len(halo_glob)
            new_parts[p] = dict(
                A=sps.csr_matrix(
                    (m.data, cols, m.indptr),
                    shape=(int(own_c.counts[p]), nloc),
                ),
                halo_glob=halo_glob,
            )

        # device arrays for this level (A + P/R stacked blocks)
        A_dev = _finalize_level(
            lvl_parts_to_parts(lvl_parts), lvl_own, comm,
            proc_grid=proc_grid if len(levels) == 0 else None,
            mesh=mesh,
        )
        P_cols, P_vals = _stack_level_blocks(
            P_blocks, rows_pp, comm, mesh
        )
        R_blocks = {p: P_blocks[p].T.tocsr() for p in P_blocks}
        R_cols, R_vals = _stack_level_blocks(
            R_blocks, rows_pp_c, comm, mesh
        )
        levels.append(
            DistLevel(
                A=A_dev, P_cols=P_cols, P_vals=P_vals,
                R_cols=R_cols, R_vals=R_vals, bridge=bridge,
            )
        )

        lvl_parts = new_parts
        lvl_own = own_c

    h = finish_distributed_hierarchy(
        lvl_parts, lvl_own, comm, levels, proc_grid,
        max_part_nnz, max_part_rows, my_parts, mesh=mesh,
    )
    if sparsify_stats:
        h.setup_stats["sparsify"] = sparsify_stats
    return h


def lvl_parts_to_parts(lvl_parts):
    """Per-part csr state -> the localized dicts finalize expects."""
    return {
        p: dict(
            indptr=d["A"].indptr,
            cols=d["A"].indices.astype(np.int32),
            vals=d["A"].data,
            halo_glob=d["halo_glob"],
        )
        for p, d in lvl_parts.items()
    }


def _block_coo_reduce(rows, cols, blocks, dtype=None):
    """Canonicalize a block COO triple: lexsort by (row, col), sum
    duplicate blocks (np.add.reduceat in stable key order — the
    deterministic part-order sum the scalar path gets from csr adds).
    Returns (rows, cols, blocks) with unique sorted keys."""
    if len(rows) == 0:
        b = blocks.shape[1] if blocks.ndim == 3 else 1
        return (
            np.zeros(0, np.int64), np.zeros(0, np.int64),
            np.zeros((0, b, b), dtype or np.float64),
        )
    order = np.lexsort((cols, rows))
    rows, cols, blocks = rows[order], cols[order], blocks[order]
    key_new = np.empty(len(rows), dtype=bool)
    key_new[0] = True
    key_new[1:] = (rows[1:] != rows[:-1]) | (cols[1:] != cols[:-1])
    starts = np.nonzero(key_new)[0]
    out = np.add.reduceat(blocks, starts, axis=0)
    return rows[starts], cols[starts], out


def _block_parts_to_parts(lvl_parts):
    """Block level state -> the localized dicts _finalize_level expects
    (vals carry the (nnzb, b, b) blocks; part_ell_arrays is
    block-aware)."""
    return {
        p: dict(
            indptr=d["indptr"],
            cols=np.asarray(d["cols"], dtype=np.int32),
            vals=d["vals"],
            halo_glob=d["halo_glob"],
        )
        for p, d in lvl_parts.items()
    }


def build_distributed_hierarchy_block(
    Asp: sps.csr_matrix,
    n_parts: int,
    block_size: int,
    cfg,
    scope: str,
    grid=None,
    owner=None,
    comm: Optional[LoopbackComm] = None,
    max_levels: int = 20,
    consolidate_rows: int = _CONSOLIDATE_ROWS,
    grade_lower: int = _GRADE_LOWER,
    stop_measure: str = "sum",
) -> DistHierarchy:
    """Distributed aggregation AMG on a BLOCK matrix (reference
    distributed block path: aggregation treats block rows as graph
    nodes, aggregation_amg_level.cu; transfers are aggregate maps ⊗
    I_b, so the coarse operator blocks are member-block sums).

    Same per-part structure as the scalar builder: aggregation runs on
    the part's condensed (Frobenius-norm) graph, halo coarse ids ride
    the comm fabric, partial coarse BLOCK rows route to their graded
    leaders and reduce in deterministic key order.  Device levels are
    block ELL ([N, rows, w, b, b]); the consolidated tail expands to
    scalar (the replicated tail AMG scalarizes block operators, like
    the serial hierarchy).

    MAINTENANCE NOTE: the grading / coarse-numbering / halo-fetch /
    RAP-routing protocol below mirrors build_distributed_hierarchy_local
    step for step (only the value-combine differs: _block_coo_reduce
    vs scipy csr sums) — a change to the collective protocol in either
    builder must be applied to BOTH until the loop is parametrized on
    a value-combine callback."""
    from amgx_tpu.distributed.partition import block_csr_arrays

    b = int(block_size)
    if comm is None:
        from amgx_tpu.distributed.comm import default_comm

        comm = default_comm(n_parts)
    indptr_g, bcols_g, bvals_g = block_csr_arrays(Asp, b)
    n = indptr_g.shape[0] - 1
    if owner is None:
        # grid/owner describe BLOCK rows (reference block partition
        # vectors are block-row granular)
        owner, proc_grid = partition_rows(n, n_parts, grid)
    else:
        owner = np.asarray(owner, dtype=np.int32)
        proc_grid = None
    ownership = ArrayOwnership(owner, n_parts=n_parts)
    rows_pp0 = max(int(ownership.counts.max()), 1)
    my_parts = list(comm.my_parts)

    from amgx_tpu.distributed.partition import gather_row_entries

    lvl_parts = {}
    for p in my_parts:
        ent, lens = gather_row_entries(
            indptr_g, ownership.global_rows(p)
        )
        lptr = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
        loc = localize_columns(
            lptr, bcols_g[ent], bvals_g[ent], owner,
            ownership.local_arr, p, rows_pp0,
        )
        lvl_parts[p] = dict(
            indptr=loc["indptr"], cols=loc["cols"], vals=loc["vals"],
            halo_glob=loc["halo_glob"],
        )
    lvl_own: Ownership = ownership
    levels: List[DistLevel] = []
    max_part_nnz = 0
    max_part_rows = 0

    # reference computeEdgeWeightsBlockDiaCsr_V2 (size2_selector.cu:770):
    # aggregation_edge_weight_component picks the block component the
    # edge weights condense on.  When the config does not set it, the
    # TPU default is the Frobenius condense (uses the whole block; at
    # least as informative as any single component)
    ew_comp = (
        int(cfg.get("aggregation_edge_weight_component", scope))
        if cfg.has("aggregation_edge_weight_component", scope)
        else -1
    )

    def cond_csr(d, counts_p):
        """Condensed scalar csr of one block part (component or
        Frobenius weights)."""
        nloc = rows_pp_cur + len(d["halo_glob"])
        if 0 <= ew_comp < d["vals"].shape[1] * d["vals"].shape[2]:
            bi, bj = divmod(ew_comp, d["vals"].shape[2])
            w = np.abs(d["vals"][:, bi, bj])
            # component-(0,0)-only condensation can drop block edges
            # whose (0,0) entry is zero; keep the graph connected with
            # a small Frobenius floor
            fro = np.sqrt((d["vals"] ** 2).sum(axis=(1, 2)))
            w = np.where(w > 0, w, 1e-12 * fro)
        else:
            w = np.sqrt((d["vals"] ** 2).sum(axis=(1, 2)))
        return sps.csr_matrix(
            (w, d["cols"], d["indptr"]), shape=(counts_p, nloc)
        )

    while (
        _stop_rows(lvl_own, stop_measure) > consolidate_rows
        and len(levels) < max_levels
    ):
        counts = lvl_own.counts
        rows_pp_cur = max(int(counts.max()), 1)
        aggs: Dict[int, np.ndarray] = {}
        ncs_local: Dict[int, int] = {}
        for p in my_parts:
            A_pp = cond_csr(lvl_parts[p], int(counts[p]))[
                :, : counts[p]
            ].tocsr()
            agg = _local_aggregate(A_pp, cfg, scope)
            aggs[p] = agg
            ncs_local[p] = int(agg.max()) + 1 if agg.size else 0
            max_part_nnz = max(
                max_part_nnz, lvl_parts[p]["vals"].shape[0]
            )
            max_part_rows = max(max_part_rows, int(counts[p]))
        ncs = np.asarray(
            comm.allgather(ncs_local, kind="coarse-counts"),
            dtype=np.int64,
        )
        nc_global = int(ncs.sum())
        if nc_global >= lvl_own.n_global or nc_global == 0:
            break

        graded = _grade_groups(ncs, grade_lower)
        if graded is not None:
            lead_of, moff, perms_down, is_leader = graded
            bridge = (perms_down, is_leader)
        else:
            lead_of = np.arange(n_parts, dtype=np.int32)
            moff = np.zeros(n_parts, dtype=np.int64)
            bridge = None
        nc_lead = np.zeros(n_parts, dtype=np.int64)
        for p in range(n_parts):
            nc_lead[lead_of[p]] += ncs[p]
        coffsets = np.concatenate([[0], np.cumsum(nc_lead)])
        own_c = OffsetOwnership(coffsets)
        cbase = coffsets[lead_of] + moff

        P_blocks = {
            p: sps.csr_matrix(
                (
                    np.ones(counts[p], dtype=bvals_g.dtype),
                    (np.arange(counts[p]), moff[p] + aggs[p]),
                ),
                shape=(int(counts[p]), int(nc_lead[lead_of[p]])),
            )
            for p in my_parts
        }

        # halo coarse ids from their owners (O(boundary))
        requests: Dict[int, Dict[int, np.ndarray]] = {}
        for p in my_parts:
            hg = lvl_parts[p]["halo_glob"]
            if not len(hg):
                continue
            owners = lvl_own.owner_of(hg)
            requests[p] = {
                int(o): hg[owners == o] for o in np.unique(owners)
            }
        answers = fetch_by_owner(
            comm,
            requests,
            lambda o, ids: (
                cbase[o] + aggs[o][lvl_own.local_of_ids(ids)]
            ).astype(np.int64),
            kind="halo-agg",
        )

        # partial coarse BLOCK rows: Ac_IJ = sum of member blocks
        partial_rap: Dict[int, Dict[int, tuple]] = {}
        for p in my_parts:
            d = lvl_parts[p]
            nloc = rows_pp_cur + len(d["halo_glob"])
            col_to_gc = np.zeros(nloc, dtype=np.int64)
            col_to_gc[: counts[p]] = cbase[p] + aggs[p]
            hg = d["halo_glob"]
            if len(hg):
                hvals = np.empty(len(hg), dtype=np.int64)
                owners = lvl_own.owner_of(hg)
                for o, vals in answers.get(p, {}).items():
                    hvals[owners == o] = vals
                col_to_gc[rows_pp_cur: rows_pp_cur + len(hg)] = hvals
            lens = np.diff(d["indptr"])
            rid = np.repeat(
                np.arange(int(counts[p]), dtype=np.int64), lens
            )
            crow = moff[p] + aggs[p][rid]  # leader-local coarse row
            ccol = col_to_gc[d["cols"]]
            r2, c2, blk = _block_coo_reduce(
                crow, ccol, d["vals"], bvals_g.dtype
            )
            partial_rap.setdefault(int(lead_of[p]), {})[p] = (
                r2, c2, blk
            )

        outbox = {}
        for L, by_src in partial_rap.items():
            for src, trip in by_src.items():
                if L in my_parts:
                    continue
                outbox[(src, L)] = trip
        inbox = comm.alltoall(outbox, kind="rap-ext")
        rap: Dict[int, tuple] = {}
        for L in my_parts:
            if nc_lead[L] == 0:
                continue
            by_src = dict(partial_rap.get(L, {}))
            for (src, dst), trip in inbox.items():
                if dst == L:
                    by_src[src] = trip
            if not by_src:
                continue
            rr = np.concatenate(
                [by_src[s][0] for s in sorted(by_src)]
            )
            cc = np.concatenate(
                [by_src[s][1] for s in sorted(by_src)]
            )
            bb = np.concatenate(
                [by_src[s][2] for s in sorted(by_src)]
            )
            rap[L] = _block_coo_reduce(rr, cc, bb, bvals_g.dtype)

        # owned-first renumber of the coarse block level
        rows_pp_c = max(int(own_c.counts.max()), 1)
        new_parts = {}
        for p in my_parts:
            trip = rap.get(p)
            if trip is None:
                trip = (
                    np.zeros(0, np.int64), np.zeros(0, np.int64),
                    np.zeros((0, b, b), bvals_g.dtype),
                )
            gr, gc, blk = trip
            is_owned = own_c.owner_of(gc) == p
            cols, halo_glob = halo_localize(
                gc, is_owned,
                own_c.local_of_ids(gc[is_owned]), rows_pp_c,
            )
            nr_c = int(own_c.counts[p])
            indptr_c = np.concatenate(
                [[0], np.cumsum(np.bincount(
                    gr, minlength=nr_c
                ))]
            ).astype(np.int64)
            new_parts[p] = dict(
                indptr=indptr_c, cols=cols, vals=blk,
                halo_glob=halo_glob,
            )

        A_dev = _finalize_level(
            _block_parts_to_parts(lvl_parts), lvl_own, comm,
            proc_grid=proc_grid if len(levels) == 0 else None,
        )
        P_cols, P_vals = _stack_level_blocks(
            P_blocks, rows_pp_cur, comm, None
        )
        R_blocks = {p: P_blocks[p].T.tocsr() for p in P_blocks}
        R_cols, R_vals = _stack_level_blocks(
            R_blocks, rows_pp_c, comm, None
        )
        levels.append(
            DistLevel(
                A=A_dev, P_cols=P_cols, P_vals=P_vals,
                R_cols=R_cols, R_vals=R_vals, bridge=bridge,
            )
        )
        lvl_parts = new_parts
        lvl_own = own_c

    # deepest level + scalar-expanded consolidated tail
    counts_L = lvl_own.counts
    rows_pp_L = max(int(counts_L.max()), 1)
    A_last = _finalize_level(
        _block_parts_to_parts(lvl_parts), lvl_own, comm,
        proc_grid=proc_grid if not levels else None,
    )
    owner_L, local_L = lvl_own.materialize()
    A_last.owner = owner_L
    A_last.local_of = local_L
    levels.append(DistLevel(A=A_last))

    tail_local = {}
    for p in my_parts:
        d = lvl_parts[p]
        hg = d["halo_glob"]
        col_to_g = np.zeros(
            rows_pp_L + len(hg), dtype=np.int64
        )
        g_rows = lvl_own.global_rows(p)
        col_to_g[: counts_L[p]] = g_rows
        if len(hg):
            col_to_g[rows_pp_L: rows_pp_L + len(hg)] = hg
        lens = np.diff(d["indptr"])
        rid = np.repeat(np.arange(int(counts_L[p])), lens)
        # expand blocks to scalar entries
        gi = g_rows[rid]
        gj = col_to_g[d["cols"]]
        bi, bj = np.meshgrid(np.arange(b), np.arange(b), indexing="ij")
        srow = (gi[:, None, None] * b + bi[None]).ravel()
        scol = (gj[:, None, None] * b + bj[None]).ravel()
        sval = d["vals"].ravel()
        tail_local[p] = (srow, scol, sval)
    gathered = comm.allgather(tail_local, kind="tail-glue")
    ng_L = lvl_own.n_global
    tail = sps.csr_matrix(
        (
            np.concatenate([t[2] for t in gathered]),
            (
                np.concatenate([t[0] for t in gathered]),
                np.concatenate([t[1] for t in gathered]),
            ),
        ),
        shape=(ng_L * b, ng_L * b),
    )
    tail.sum_duplicates()
    tail.sort_indices()
    tail.eliminate_zeros()

    stats = dict(
        comm_total_bytes=comm.stats.total_bytes,
        comm_max_msg_bytes=comm.stats.max_msg_bytes,
        comm_rounds=len(comm.stats.rounds),
        max_part_nnz=int(max_part_nnz),
        max_part_rows=int(max_part_rows),
        n_parts=comm.n_parts,
    )
    return DistHierarchy(
        levels=levels,
        tail_matrix=tail,
        tail_owner=owner_L,
        tail_local_of=local_L,
        setup_stats=stats,
        comm=comm,
    )


def build_distributed_hierarchy(
    Asp: sps.csr_matrix,
    n_parts: int,
    cfg,
    scope: str,
    grid=None,
    owner=None,
    max_levels: int = 20,
    consolidate_rows: int = _CONSOLIDATE_ROWS,
    grade_lower: int = _GRADE_LOWER,
    stop_measure: str = "sum",
    sparsify_theta: float = 0.0,
    sparsify_from_level: int = 1,
) -> DistHierarchy:
    """Single-process convenience wrapper: partition the global matrix
    into local parts, then run the per-process setup loop
    (:func:`build_distributed_hierarchy_local`) over a loopback fabric.
    The reference analogue is upload_all_global followed by setup_v2;
    per-rank uploads enter the local builder directly."""
    from amgx_tpu.amg.aggregation import infer_grid, stencil_offsets

    n = Asp.shape[0]
    Asp = Asp.tocsr()
    Asp.sort_indices()
    proc_grid = None
    if owner is None:
        if grid is None:
            offs = stencil_offsets(Asp)
            grid = infer_grid(offs, n) if offs is not None else None
        owner, proc_grid = partition_rows(n, n_parts, grid)
    else:
        owner = np.asarray(owner, dtype=np.int32)
    ownership = ArrayOwnership(owner, n_parts=n_parts)

    rows_pp = max(int(ownership.counts.max()), 1)
    local_parts = {}
    for p in range(n_parts):
        local = Asp[ownership.global_rows(p)].tocsr()
        local_parts[p] = localize_columns(
            local.indptr, local.indices, local.data, owner,
            ownership.local_arr, p, rows_pp,
        )
    h = build_distributed_hierarchy_local(
        local_parts, ownership, cfg, scope,
        max_levels=max_levels,
        consolidate_rows=consolidate_rows,
        grade_lower=grade_lower,
        proc_grid=proc_grid,
        stop_measure=stop_measure,
        sparsify_theta=sparsify_theta,
        sparsify_from_level=sparsify_from_level,
    )
    # fine-level pad/unpad convenience for non-contiguous partitions
    # (grid slabs / arbitrary partition vectors): the global-matrix
    # entry point has the O(n_global) arrays anyway
    h.levels[0].A.owner = owner
    h.levels[0].A.local_of = ownership.local_arr
    return h
