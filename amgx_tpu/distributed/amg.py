"""Distributed AMG: fine level sharded over the mesh, coarse hierarchy
consolidated.

Reference mapping (SURVEY §2.6/§5.8): the reference shrinks the active
rank set on coarse levels (consolidation/"glue", glue.h) because coarse
work cannot saturate the machine.  Taken to its TPU-native limit: the
FINE level — where nearly all memory traffic lives — is block-row
sharded with B2L halo exchange over ICI; every coarser level is
replicated on all chips (full consolidation), so the coarse V-cycle
runs redundantly-but-identically everywhere with zero communication.
Restriction ends with a ``psum`` (the consolidation gather);
prolongation needs no communication at all (P rows are owned rows).

Solve = distributed PCG preconditioned by this two-tier cycle — one
shard_map program (acceptance config 5: distributed aggregation AMG on
partitioned Poisson).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import scipy.sparse as sps

from amgx_tpu.distributed.partition import (
    DistributedMatrix,
    partition_matrix,
)
from amgx_tpu.distributed.solve import _local_spmv, _pdot, _shard_params


def _pad_csr_rows(sp: sps.csr_matrix, n_parts: int, rows_pp: int):
    """Split sp (n_rows x m) into row blocks, pad each to uniform ELL and
    stack [N, rows_pp, w] (+ cols).  Column space untouched."""
    blocks = []
    w = 1
    for p in range(n_parts):
        blk = sp[p * rows_pp : (p + 1) * rows_pp].tocsr()
        blocks.append(blk)
        lens = np.diff(blk.indptr)
        if lens.size:
            w = max(w, int(lens.max()))
    cols = np.zeros((n_parts, rows_pp, w), dtype=np.int32)
    vals = np.zeros((n_parts, rows_pp, w), dtype=sp.dtype)
    for p, blk in enumerate(blocks):
        lens = np.diff(blk.indptr)
        nrows = blk.shape[0]
        row_ids = np.repeat(np.arange(nrows), lens)
        pos = np.arange(blk.indices.shape[0]) - blk.indptr[
            row_ids
        ].astype(np.int64)
        cols[p, row_ids, pos] = blk.indices
        vals[p, row_ids, pos] = blk.data
    return cols, vals


class DistributedAMG:
    """Two-tier distributed AMG-PCG solver."""

    def __init__(self, Asp: sps.csr_matrix, mesh: Mesh, cfg=None,
                 scope: str = "default"):
        from amgx_tpu.config.amg_config import AMGConfig

        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_parts = mesh.devices.size
        if cfg is None:
            cfg = AMGConfig.from_string(
                '{"config_version": 2, "solver": {"scope": "amg",'
                ' "solver": "AMG", "algorithm": "AGGREGATION",'
                ' "selector": "SIZE_2", "smoother": {"scope": "jac",'
                ' "solver": "BLOCK_JACOBI", "relaxation_factor": 0.8,'
                ' "monitor_residual": 0}, "presweeps": 1,'
                ' "postsweeps": 1, "max_iters": 1, "cycle": "V",'
                ' "coarse_solver": "DENSE_LU_SOLVER",'
                ' "monitor_residual": 0}}'
            )
            scope = "amg"
        self.cfg = cfg
        self.scope = scope
        self._setup(Asp)

    def _setup(self, Asp):
        n = Asp.shape[0]
        # fine level: sharded (B2L halo machinery)
        self.fine = partition_matrix(Asp, self.n_parts)
        rows_pp = self.fine.rows_per_part

        # fine-level smoothing honors the config (Jacobi-type only for
        # now: pointwise damped sweeps distribute trivially)
        sname, sscope = self.cfg.get_scoped("smoother", self.scope)
        if sname not in ("BLOCK_JACOBI", "JACOBI_L1"):
            import warnings

            warnings.warn(
                f"distributed fine-level smoother {sname}: using damped "
                "Jacobi (colored smoothers on the sharded level TBD)"
            )
        self.omega = float(self.cfg.get("relaxation_factor", sscope))
        self.presweeps = max(int(self.cfg.get("presweeps", self.scope)), 0)
        self.postsweeps = max(
            int(self.cfg.get("postsweeps", self.scope)), 0
        )
        self._solve_cache = {}

        # one coarsening step on the host builds P/R and the coarse
        # operator; the coarse hierarchy below it is a standard
        # (replicated) AMG solver
        from amgx_tpu.amg.hierarchy import AMGSolver
        from amgx_tpu.core.matrix import SparseMatrix

        amg = AMGSolver(self.cfg, self.scope)
        P_, R_, Ac = amg._build_coarse(Asp, 0)
        # pad the global operators to the padded row space
        n_pad = rows_pp * self.n_parts
        if n_pad > n:
            P_ = sps.vstack(
                [P_, sps.csr_matrix((n_pad - n, P_.shape[1]))]
            ).tocsr()
            R_ = sps.hstack(
                [R_, sps.csr_matrix((R_.shape[0], n_pad - n))]
            ).tocsr()
        self.nc = Ac.shape[0]
        # R columns partitioned by owner shard: rc = psum_p R_p r_p
        Rl = R_.tocsc()
        r_cols, r_vals = [], []
        for p in range(self.n_parts):
            blk = Rl[:, p * rows_pp : (p + 1) * rows_pp].tocsr()
            r_cols.append(blk)
        w = max(
            max((int(np.diff(b.indptr).max()) if b.nnz else 1)
                for b in r_cols), 1
        )
        R_cols = np.zeros((self.n_parts, self.nc, w), dtype=np.int32)
        R_vals = np.zeros((self.n_parts, self.nc, w), dtype=Asp.dtype)
        for p, blk in enumerate(r_cols):
            lens = np.diff(blk.indptr)
            rid = np.repeat(np.arange(self.nc), lens)
            pos = np.arange(blk.indices.shape[0]) - blk.indptr[
                rid
            ].astype(np.int64)
            R_cols[p, rid, pos] = blk.indices
            R_vals[p, rid, pos] = blk.data
        self.R_cols, self.R_vals = R_cols, R_vals

        # P rows partitioned by owner shard: x_loc += P_p e
        self.P_cols, self.P_vals = _pad_csr_rows(
            P_.tocsr(), self.n_parts, rows_pp
        )

        # coarse hierarchy: a standard replicated AMG on Ac
        coarse_amg = AMGSolver(self.cfg, self.scope)
        coarse_amg.setup(SparseMatrix.from_scipy(Ac.tocsr()))
        self.coarse_amg = coarse_amg
        self._coarse_cycle = coarse_amg.make_cycle()
        self._coarse_params = coarse_amg.apply_params()

    # ------------------------------------------------------------------

    def _local_cycle(self, shard, Rc, Rv, Pc, Pv, coarse_params, r_loc):
        """One two-tier cycle applied to a local residual (zero guess)."""
        ell_cols, ell_vals, diag, *_ = shard
        dinv = jnp.where(diag != 0, 1.0 / diag, 1.0)
        omega = jnp.asarray(self.omega, r_loc.dtype)
        # pre-smooth (damped Jacobi, zero guess)
        z = jnp.zeros_like(r_loc)
        for i in range(max(self.presweeps, 1)):
            rr = r_loc if i == 0 else (
                r_loc - _local_spmv(shard, z, self.axis)
            )
            z = z + omega * dinv * rr
        rr = r_loc - _local_spmv(shard, z, self.axis)
        # restrict: rc = psum_p R_p rr_p  (consolidation gather)
        rc_part = jnp.sum(Rv * rr[Rc], axis=1)
        rc = jax.lax.psum(rc_part, self.axis)
        # replicated coarse solve (identical on every shard)
        ec = self._coarse_cycle(
            coarse_params, rc, jnp.zeros_like(rc)
        )
        # prolongate: z += P_p ec   (no communication)
        z = z + jnp.sum(Pv * ec[Pc], axis=1)
        # post-smooth
        for _ in range(max(self.postsweeps, 1)):
            rr = r_loc - _local_spmv(shard, z, self.axis)
            z = z + omega * dinv * rr
        return z

    def _build_solve(self, max_iters, tol):
        axis = self.axis
        n_shard_arrays = len(_shard_params(self.fine))
        in_specs = (
            tuple(P(axis) for _ in range(n_shard_arrays)),
            P(axis), P(axis), P(axis), P(axis),  # R/P blocks
            None,  # coarse params replicated
            P(axis),  # b
        )

        @functools.partial(
            jax.shard_map,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(P(axis), P(), P()),
        )
        def solve_sm(shard_stk, Rc_, Rv_, Pc_, Pv_, coarse, b_stk):
            sh = tuple(s[0] for s in shard_stk)
            b_loc = b_stk[0]
            M = lambda r: self._local_cycle(
                sh, Rc_[0], Rv_[0], Pc_[0], Pv_[0], coarse, r
            )
            x = jnp.zeros_like(b_loc)
            r = b_loc
            z = M(r)
            p = z
            rho = _pdot(r, z, axis)
            nrm0 = jnp.sqrt(_pdot(b_loc, b_loc, axis))

            def cond(c):
                it, x, r, p, rho, nrm = c
                return (it < max_iters) & (nrm >= tol * nrm0) & (nrm0 > 0)

            def body(c):
                it, x, r, p, rho, nrm = c
                q = _local_spmv(sh, p, axis)
                alpha = rho / _pdot(p, q, axis)
                x = x + alpha * p
                r = r - alpha * q
                z = M(r)
                rho_new = _pdot(r, z, axis)
                p = z + (rho_new / rho) * p
                nrm = jnp.sqrt(_pdot(r, r, axis))
                return (it + 1, x, r, p, rho_new, nrm)

            it, x, r, p, rho, nrm = jax.lax.while_loop(
                cond, body, (jnp.int32(0), x, r, p, rho, nrm0)
            )
            return x[None], it, nrm

        return jax.jit(solve_sm)

    def solve(self, b, max_iters=200, tol=1e-8):
        """Distributed AMG-preconditioned CG. Returns (x, iters, nrm).
        The jitted program is cached per (max_iters, tol) — repeated
        solves dispatch without recompiling."""
        key = (max_iters, float(tol))
        fn = self._solve_cache.get(key)
        if fn is None:
            fn = self._build_solve(max_iters, tol)
            self._solve_cache[key] = fn
        shard = _shard_params(self.fine)
        bp = jnp.asarray(self.fine.pad_vector(np.asarray(b)))
        x, it, nrm = fn(
            shard,
            jnp.asarray(self.R_cols),
            jnp.asarray(self.R_vals),
            jnp.asarray(self.P_cols),
            jnp.asarray(self.P_vals),
            self._coarse_params,
            bp,
        )
        return (
            self.fine.unpad_vector(jax.device_get(x)),
            int(it),
            float(nrm),
        )
