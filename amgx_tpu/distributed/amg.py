"""Distributed AMG solve path: multi-level sharded V-cycle + PCG.

Reference mapping (SURVEY §2.6/§5.8): the sharded levels come from
:mod:`amgx_tpu.distributed.hierarchy` (the distributed setup loop,
amg.cu:425-660); each distributed level smooths with damped Jacobi,
L1-Jacobi, Chebyshev polynomials, multicolor GS, or multicolor DILU
(reference block_jacobi/jacobi_l1/cheb/multicolor_gauss_seidel/
multicolor_dilu solvers) and
exchanges halos via neighbor ppermute; restriction/prolongation are
communication-free (shard-local aggregates).  Below the consolidation
threshold the remaining hierarchy is replicated on every chip
(reference glue_matrices/glue_vector, glue.h:200,525) and runs as a
standard AMG cycle with zero communication; entry/exit are one
all_gather / local slice per outer cycle.

Solve = distributed PCG preconditioned by this cycle, one shard_map
program (acceptance config 5: distributed aggregation AMG).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import scipy.sparse as sps

from amgx_tpu.distributed.hierarchy import (
    DistHierarchy,
    build_distributed_hierarchy,
)
from amgx_tpu.distributed.solve import (
    _pdot,
    _pgram,
    _safe_block_inv,
    _shard_params,
    exchange_halo,
    exchange_halo_reverse,
    make_local_spmv,
)
from amgx_tpu.core.profiling import named_scope, trace_range
from amgx_tpu.core.sharding import pvary, shard_map


def _level_is_sharded(A) -> bool:
    """True when the level's stacked arrays are multi-process sharded
    ``jax.Array``s (per-rank assembly) rather than host numpy."""
    return not isinstance(A.ell_cols, np.ndarray)


def _host_part_blocks(A):
    """{p: (ell_cols_p, ell_vals_p, diag_p, n_owned_p)} host views of
    the parts this process holds: every part for numpy-stacked levels,
    the addressable shards for multi-process sharded levels — smoother
    metadata stays O(global / n_processes) per process."""
    if not _level_is_sharded(A):
        return {
            p: (
                A.ell_cols[p], A.ell_vals[p], A.diag[p],
                int(A.n_owned[p]) if A.n_owned is not None
                else A.ell_cols.shape[1],
            )
            for p in range(A.n_parts)
        }
    by_field = []
    for arr in (A.ell_cols, A.ell_vals, A.diag):
        by_field.append(
            {
                s.index[0].start: np.asarray(s.data)[0]
                for s in arr.addressable_shards
            }
        )
    cols_by, vals_by, diag_by = by_field
    return {
        p: (
            cols_by[p], vals_by[p], diag_by[p],
            int(A.n_owned[p]) if A.n_owned is not None
            else cols_by[p].shape[0],
        )
        for p in cols_by
    }


def _vals_nonzero_mask(vals_p):
    """(rows, w) structural-nonzero mask for scalar or block
    (rows, w, b, b) ELL values."""
    if vals_p.ndim == 2:
        return vals_p != 0
    return (vals_p != 0).any(axis=(-2, -1))


def _part_colors(cols_p, vals_p, nr):
    """Distance-1 greedy coloring of ONE shard's LOCAL coupling graph
    (halo columns excluded); padding rows -1.  Returns (colors, nc).
    Block levels color the BLOCK-row graph (any-nonzero blocks)."""
    from amgx_tpu.ops.coloring import greedy_coloring

    rows, w = cols_p.shape
    out = np.full(rows, -1, dtype=np.int32)
    nc = 1
    rid = np.broadcast_to(
        np.arange(rows, dtype=np.int64)[:, None], (rows, w)
    )
    em = _vals_nonzero_mask(vals_p) & (cols_p < rows) & (cols_p != rid)
    counts = em[:nr].sum(axis=1)
    indptr = np.concatenate([[0], np.cumsum(counts)])
    indices = cols_p[:nr][em[:nr]].astype(np.int64)
    if nr:
        c = greedy_coloring(indptr, indices, nr)
        out[:nr] = c
        nc = int(c.max()) + 1
    return out, nc


def _local_colors(A, comm=None, mesh=None, blocks=None,
                  build_stacked=True):
    """Per-shard local colorings stacked [N, rows] (numpy, or sharded
    ``jax.Array``s in the per-rank assembly); num_colors is a
    comm-wide consensus so every process traces the same sweep
    structure.  Returns (colors, num_colors, host_colors_by_part);
    ``build_stacked=False`` skips the stacked array (DILU needs only
    the host colorings — the factor slices encode the sweep)."""
    if blocks is None:
        blocks = _host_part_blocks(A)
    per = {}
    nc_local = {}
    for p, (cols_p, vals_p, _d, nr) in blocks.items():
        per[p], nc_local[p] = _part_colors(cols_p, vals_p, nr)
    if not _level_is_sharded(A):
        stacked = (
            np.stack([per[p] for p in range(A.n_parts)])
            if build_stacked else None
        )
        return stacked, max(max(nc_local.values(), default=1), 1), per
    nc = max(max(comm.allgather(nc_local, kind="colors-nc")), 1)
    if not build_stacked:
        return None, nc, per
    from amgx_tpu.distributed.multihost import stack_parts_sharded

    return (
        stack_parts_sharded(
            per, mesh, A.n_parts,
            shape=(A.rows_per_part,), dtype=np.int32,
        ),
        nc,
        per,
    )


def _part_dilu(cols_p, vals_p, nr, cp, nc, rows_pp):
    """One shard's DILU factor split per color:
    [c] -> dict(rows, einv, L=(row,col,val), U=(row,col,val))."""
    w = cols_p.shape[1]
    rid = np.repeat(np.arange(rows_pp), w).reshape(rows_pp, w)
    keep = (vals_p != 0) & (cols_p < nr) & (rid < nr)
    Al = sps.csr_matrix(
        (vals_p[keep], (rid[keep], cols_p[keep])),
        shape=(nr, nr),
    )
    d = np.asarray(Al.diagonal())
    # pairwise products p_ij = a_ij * a_ji on the symmetric-
    # intersection pattern (Hadamard with the transpose)
    Pm = Al.multiply(Al.T.tocsr()).tocsr()
    E = d.copy()
    for c in range(1, nc):
        rows_c = np.nonzero(cp[:nr] == c)[0]
        if not len(rows_c):
            continue
        lower = (cp[:nr] >= 0) & (cp[:nr] < c)
        invE = np.where(
            lower & (E != 0), 1.0 / np.where(E != 0, E, 1.0), 0.0
        )
        E[rows_c] = d[rows_c] - Pm[rows_c] @ invE
    einv = np.where(E != 0, 1.0 / np.where(E != 0, E, 1.0), 0.0)

    Alc = Al.tocoo()
    row_color = cp[:nr][Alc.row]
    col_color = cp[:nr][Alc.col]
    shard_cols = []
    for c in range(nc):
        rows_c = np.nonzero(cp[:nr] == c)[0]
        sel = row_color == c
        r_of = np.full(nr, -1, dtype=np.int64)
        r_of[rows_c] = np.arange(len(rows_c))
        ent_r = r_of[Alc.row[sel]]
        ent_c = Alc.col[sel]
        ent_v = Alc.data[sel]
        low = col_color[sel] < c  # rows here all have color c
        off = ent_c != Alc.row[sel]  # strictly off-diagonal
        shard_cols.append(
            dict(
                rows=rows_c,
                einv=einv[rows_c],
                L=(ent_r[off & low], ent_c[off & low],
                   ent_v[off & low]),
                U=(ent_r[off & ~low], ent_c[off & ~low],
                   ent_v[off & ~low]),
            )
        )
    return shard_cols


def _part_dilu_block(cols_p, vals_p, nr, cp, nc, rows_pp):
    """Block (b > 1) variant of :func:`_part_dilu` (reference
    multicolor_dilu_solver.cu block specializations b=2..10): the
    factor diagonal is a b x b block per block row,

        E_i = a_ii - sum_{j: color(j) < color(i)} a_ij Einv_j a_ji

    computed per color with batched block products; L/U slices carry
    b x b blocks.  Same restricted-additive-Schwarz locality as the
    scalar factor (owned couplings only)."""
    w = cols_p.shape[1]
    b = vals_p.shape[-1]
    rid = np.broadcast_to(
        np.arange(rows_pp, dtype=np.int64)[:, None], (rows_pp, w)
    )
    keep = _vals_nonzero_mask(vals_p) & (cols_p < nr) & (rid < nr)
    er_all = rid[keep]
    ec_all = cols_p[keep]
    ev_all = vals_p[keep]  # (nnz, b, b)
    # transpose lookup: slot of (j, i) for each entry (i, j)
    order = np.lexsort((ec_all, er_all))
    er_s, ec_s = er_all[order], ec_all[order]
    key_s = er_s * np.int64(nr + 1) + ec_s
    tkey = ec_all * np.int64(nr + 1) + er_all
    pos = np.searchsorted(key_s, tkey)
    ok = (pos < key_s.shape[0]) & (
        key_s[np.minimum(pos, len(key_s) - 1)] == tkey
    )
    trans_slot = np.where(ok, order[np.minimum(pos, len(order) - 1)], -1)

    diag = np.zeros((nr, b, b), dtype=vals_p.dtype)
    on_diag = er_all == ec_all
    diag[er_all[on_diag]] = ev_all[on_diag]
    eye = np.eye(b, dtype=vals_p.dtype)
    Einv = np.zeros((nr, b, b), dtype=vals_p.dtype)
    colors_r = cp[:nr]

    def _inv_rows(rows_c, E_rows):
        ok_d = np.abs(np.linalg.det(E_rows)) > 1e-300
        safe = np.where(ok_d[:, None, None], E_rows, eye)
        Einv[rows_c] = np.linalg.inv(safe)

    for c in range(nc):
        rows_c = np.nonzero(colors_r == c)[0]
        if not len(rows_c):
            continue
        E_rows = diag[rows_c].copy()
        if c > 0:
            # batched correction: entries of color-c rows whose column
            # color is lower AND whose transpose entry exists
            in_c = (colors_r[er_all] == c) & (
                colors_r[ec_all] < c) & (colors_r[ec_all] >= 0) & (
                trans_slot >= 0) & ~on_diag
            if in_c.any():
                ei = er_all[in_c]
                prod = np.einsum(
                    "nij,njk,nkl->nil",
                    ev_all[in_c],
                    Einv[ec_all[in_c]],
                    ev_all[np.maximum(trans_slot[in_c], 0)],
                )
                r_of = np.full(nr, -1, dtype=np.int64)
                r_of[rows_c] = np.arange(len(rows_c))
                np.add.at(E_rows, r_of[ei], -prod)
        _inv_rows(rows_c, E_rows)

    row_color = colors_r[er_all]
    col_color = colors_r[ec_all]
    shard_cols = []
    for c in range(nc):
        rows_c = np.nonzero(colors_r == c)[0]
        sel = row_color == c
        r_of = np.full(nr, -1, dtype=np.int64)
        r_of[rows_c] = np.arange(len(rows_c))
        ent_r = r_of[er_all[sel]]
        ent_c = ec_all[sel]
        ent_v = ev_all[sel]
        low = col_color[sel] < c
        off = ec_all[sel] != er_all[sel]
        shard_cols.append(
            dict(
                rows=rows_c,
                einv=Einv[rows_c],
                L=(ent_r[off & low], ent_c[off & low],
                   ent_v[off & low]),
                U=(ent_r[off & ~low], ent_c[off & ~low],
                   ent_v[off & ~low]),
            )
        )
    return shard_cols


def _pack_dilu_color(e, rc_max, wl, wu, rows_pp, dtype, b=1):
    """Pack one shard's color slice into fixed-shape arrays
    (ridx, Lc, Lv, Uc, Uv, einv); pads point at the spill slot
    ``rows_pp`` with zero values/Einv.  Block (b > 1) slices carry
    b x b value/Einv blocks."""
    extra = () if b == 1 else (b, b)

    def pack(trip, n_rows_c, width):
        er, ec, ev = trip
        cols = np.full((n_rows_c, width), rows_pp, dtype=np.int32)
        vals = np.zeros((n_rows_c, width, *extra), dtype=dtype)
        if len(er):
            order = np.argsort(er, kind="stable")
            er, ec, ev = er[order], ec[order], np.asarray(ev)[order]
            pos = np.arange(len(er)) - np.searchsorted(er, er)
            cols[er, pos] = ec
            vals[er, pos] = ev
        return cols, vals

    k = len(e["rows"])
    ridx = np.full((rc_max,), rows_pp, dtype=np.int32)
    einv = np.zeros((rc_max, *extra), dtype=dtype)
    Lc = np.full((rc_max, wl), rows_pp, dtype=np.int32)
    Lv = np.zeros((rc_max, wl, *extra), dtype=dtype)
    Uc = np.full((rc_max, wu), rows_pp, dtype=np.int32)
    Uv = np.zeros((rc_max, wu, *extra), dtype=dtype)
    ridx[:k] = e["rows"]
    einv[:k] = e["einv"]
    lc, lv = pack(e["L"], max(k, 1), wl)
    uc, uv = pack(e["U"], max(k, 1), wu)
    Lc[:k], Lv[:k] = lc[:k], lv[:k]
    Uc[:k], Uv[:k] = uc[:k], uv[:k]
    return ridx, Lc, Lv, Uc, Uv, einv


def _local_dilu(A, colors_by_p, nc, comm=None, mesh=None, blocks=None):
    """Per-shard DILU factor + per-color compact L/U ELL slices
    (reference multicolor_dilu_solver.cu, the workhorse smoother).

    The factor uses each shard's LOCAL owned couplings only (restricted
    additive-Schwarz flavor — cross-shard coupling enters through the
    outer residual, like the reference's per-rank factor):

        E_i = a_ii - sum_{j: color(j) < color(i)} a_ij a_ji / E_j

    Apply = forward color sweep (E+L) y = r, backward (E+U) z = E y.
    Rows are sliced per color into compact stacked arrays, so one
    application costs O(nnz) total — each stored entry is touched by
    exactly one forward and one backward stage.

    ``colors_by_p`` holds this process's parts' host colorings; the
    per-color slice shapes (rc_max, wl, wu) are a comm-wide consensus
    so every process traces identical sweeps.  Returns a tuple (one
    entry per color) of stacked (numpy or mesh-sharded)
    ``(ridx, Lc, Lv, Uc, Uv, Einv)`` arrays.
    """
    if blocks is None:
        blocks = _host_part_blocks(A)
    rows_pp = A.rows_per_part
    n_parts = A.n_parts
    per = {}
    dtype = np.dtype(A.ell_vals.dtype)
    b = A.block_size
    for p, (cols_p, vals_p, _d, nr) in blocks.items():
        part_fn = _part_dilu if b == 1 else _part_dilu_block
        per[p] = part_fn(
            cols_p, vals_p, nr, colors_by_p[p], nc, rows_pp
        )

    def widths_of(shard_cols):
        out = []
        for c in range(nc):
            e = shard_cols[c]
            wl = (
                int(np.bincount(e["L"][0]).max())
                if len(e["L"][0]) else 0
            )
            wu = (
                int(np.bincount(e["U"][0]).max())
                if len(e["U"][0]) else 0
            )
            out.append((len(e["rows"]), wl, wu))
        return out

    wloc = {p: widths_of(per[p]) for p in per}
    if _level_is_sharded(A):
        gathered = comm.allgather(wloc, kind="dilu-widths")
    else:
        gathered = [wloc[p] for p in range(n_parts)]
    meta = []
    for c in range(nc):
        rc_max = max(max(g[c][0] for g in gathered), 1)
        wl = max(max(g[c][1] for g in gathered), 1)
        wu = max(max(g[c][2] for g in gathered), 1)
        packed = {
            p: _pack_dilu_color(
                per[p][c], rc_max, wl, wu, rows_pp, dtype, b=b
            )
            for p in per
        }
        if not _level_is_sharded(A):
            meta.append(
                tuple(
                    np.stack([packed[p][i] for p in range(n_parts)])
                    for i in range(6)
                )
            )
        else:
            from amgx_tpu.distributed.multihost import (
                stack_parts_sharded,
            )

            ex = () if b == 1 else (b, b)
            shapes = (
                ((rc_max,), np.int32),            # ridx
                ((rc_max, wl), np.int32),         # Lc
                ((rc_max, wl, *ex), dtype),       # Lv
                ((rc_max, wu), np.int32),         # Uc
                ((rc_max, wu, *ex), dtype),       # Uv
                ((rc_max, *ex), dtype),           # einv
            )
            meta.append(
                tuple(
                    stack_parts_sharded(
                        {p: packed[p][i] for p in packed},
                        mesh, n_parts,
                        shape=shapes[i][0], dtype=shapes[i][1],
                    )
                    for i in range(6)
                )
            )
    return tuple(meta)


class DistributedAMG:
    """Multi-level distributed AMG-PCG solver."""

    def __init__(self, Asp: sps.csr_matrix, mesh: Mesh, cfg=None,
                 scope: str = "default",
                 consolidate_rows: int | None = None,
                 owner=None, grid=None,
                 grade_lower: int | None = None,
                 block_size: int = 1,
                 sparsify_theta: float | None = None,
                 sparsify_from_level: int | None = None,
                 _local=None):
        from amgx_tpu.config.amg_config import AMGConfig

        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_parts = int(mesh.devices.size)
        if cfg is None:
            cfg = AMGConfig.from_string(
                '{"config_version": 2, "solver": {"scope": "amg",'
                ' "solver": "AMG", "algorithm": "AGGREGATION",'
                ' "selector": "SIZE_2", "smoother": {"scope": "jac",'
                ' "solver": "BLOCK_JACOBI", "relaxation_factor": 0.8,'
                ' "monitor_residual": 0}, "presweeps": 1,'
                ' "postsweeps": 1, "max_iters": 1, "cycle": "V",'
                ' "coarse_solver": "DENSE_LU_SOLVER",'
                ' "monitor_residual": 0}}'
            )
            scope = "amg"
        from amgx_tpu.distributed.hierarchy import _CONSOLIDATE_ROWS

        self.cfg = cfg
        self.scope = scope
        if consolidate_rows is None:
            # reference matrix_consolidation_lower_threshold semantics:
            # levels whose AVERAGE rows/shard drop below the threshold
            # consolidate; 0 keeps the built-in default global cap
            lower = int(
                cfg.get("matrix_consolidation_lower_threshold", scope)
            )
            upper = int(
                cfg.get("matrix_consolidation_upper_threshold", scope)
            )
            if lower > 0 and upper <= lower:
                # reference amg.cu:57-60 configuration validation
                raise ValueError(
                    "matrix_consolidation_lower_threshold must be "
                    "smaller than matrix_consolidation_upper_threshold"
                )
            consolidate_rows = (
                lower * self.n_parts if lower > 0 else _CONSOLIDATE_ROWS
            )
        # reference amg.cu:333-360: the setup-loop stop measure is the
        # MIN of per-partition rows by default, their SUM with
        # use_sum_stopping_criteria=1.  The builder's global threshold
        # is a sum test, so the min criterion tightens it by the
        # worst-case imbalance factor when the flag is explicitly 0.
        self.sum_stopping = (
            bool(cfg.get("use_sum_stopping_criteria", scope))
            if cfg.has("use_sum_stopping_criteria", scope) else None
        )
        self.consolidate_rows = consolidate_rows
        from amgx_tpu.distributed.hierarchy import _GRADE_LOWER

        self.grade_lower = (
            _GRADE_LOWER if grade_lower is None else grade_lower
        )

        self._owner = owner
        self._grid = grid
        self._local = _local
        self.block_size = int(block_size)
        # explicit kwargs override the cfg knobs (callers like the
        # serve placement thread the sparsification settings directly
        # instead of cloning a config blob)
        self._sparsify_override = sparsify_theta
        self._sparsify_from_override = sparsify_from_level
        self._setup(Asp)

    def _stop_measure(self) -> str:
        """Setup-loop stop measure: "min" when
        use_sum_stopping_criteria is explicitly 0 (reference amg.cu:333
        default), "sum" otherwise (the builder's global threshold;
        also what an explicit 1 requests)."""
        return "min" if self.sum_stopping is False else "sum"

    @classmethod
    def from_local_parts(
        cls, local_parts, part_offsets, mesh: Mesh, cfg=None,
        scope: str = "default", consolidate_rows: int | None = None,
        grade_lower: int | None = None, comm=None,
    ):
        """Per-process entry (reference per-rank upload + setup_v2):
        ``local_parts[p]`` is multihost.local_part_from_rows output for
        the parts this process drives; the global matrix is never
        materialized.  Setup traffic rides the comm fabric; when
        ``comm`` is None under a multi-process runtime the fabric's
        part striping follows the MESH placement (part p lives on
        flattened mesh device p — the device-assembly invariant)."""
        from amgx_tpu.distributed.partition import OffsetOwnership

        if comm is None:
            import jax as _jax

            n_parts = int(mesh.devices.size)
            if _jax.process_count() > 1:
                from amgx_tpu.distributed.comm import AllgatherComm
                from amgx_tpu.distributed.multihost import (
                    addressable_parts,
                )

                comm = AllgatherComm(n_parts, addressable_parts(mesh))
            else:
                from amgx_tpu.distributed.comm import LoopbackComm

                comm = LoopbackComm(n_parts)
        return cls(
            None, mesh, cfg=cfg, scope=scope,
            consolidate_rows=consolidate_rows,
            grade_lower=grade_lower,
            _local=(local_parts, OffsetOwnership(part_offsets), comm),
        )

    # ------------------------------------------------------------------

    _SMOOTHERS = {
        "BLOCK_JACOBI": "jacobi",
        "JACOBI_L1": "l1",
        "CHEBYSHEV": "cheby",
        "CHEBYSHEV_POLY": "cheby",
        "MULTICOLOR_GS": "mcgs",
        "GS": "mcgs",
        "FIXCOLOR_GS": "mcgs",
        "MULTICOLOR_DILU": "dilu",
    }

    def _setup(self, Asp):
        sname, sscope = self.cfg.get_scoped("smoother", self.scope)
        self.smoother_kind = self._SMOOTHERS.get(sname)
        if self.smoother_kind is None:
            import warnings

            warnings.warn(
                f"distributed smoother {sname}: using damped Jacobi "
                "(Jacobi/L1/Chebyshev/multicolor-GS/DILU are the "
                "sharded-level roster)"
            )
            self.smoother_kind = "jacobi"
        if self.block_size > 1 and self.smoother_kind not in (
            "jacobi", "mcgs", "dilu",
        ):
            import warnings

            warnings.warn(
                f"distributed block smoother {sname}: using block "
                "Jacobi (block multicolor GS/DILU and Jacobi are the "
                "block sharded-level roster)"
            )
            self.smoother_kind = "jacobi"
        # effective smoother after any downgrade (ADVICE r4 #4:
        # callers can detect substitutions programmatically)
        self.effective_smoother = self.smoother_kind
        if self.smoother_kind == "cheby":
            self.cheby_order = max(
                int(self.cfg.get("chebyshev_polynomial_order", sscope)),
                1,
            )
            self.cheby_mode = int(
                self.cfg.get("chebyshev_lambda_estimate_mode", sscope)
            )
            self.cheby_user_max = float(
                self.cfg.get("cheby_max_lambda", sscope)
            )
            self.cheby_user_min = float(
                self.cfg.get("cheby_min_lambda", sscope)
            )
        self.omega = float(self.cfg.get("relaxation_factor", sscope))
        self.presweeps = max(int(self.cfg.get("presweeps", self.scope)), 0)
        self.postsweeps = max(
            int(self.cfg.get("postsweeps", self.scope)), 0
        )
        self.cycle_type = str(
            self.cfg.get("cycle", self.scope)
        ).upper()
        self.cycle_iters = int(self.cfg.get("cycle_iters", self.scope))
        # communication-reduced coarse grids (dist_coarse_sparsify):
        # theta for the cross-shard Galerkin drop; 0 keeps exact RAP
        self.sparsify_theta = float(
            self.cfg.get("dist_coarse_sparsify", self.scope)
            if self._sparsify_override is None
            else self._sparsify_override
        )
        self.sparsify_from_level = int(
            self.cfg.get("dist_sparsify_from_level", self.scope)
            if self._sparsify_from_override is None
            else self._sparsify_from_override
        )
        self._solve_cache = {}

        algorithm = str(
            self.cfg.get("algorithm", self.scope)
        ).upper()
        if self.block_size > 1:
            # block path (reference distributed block matrices):
            # block-row aggregation, block ELL levels, block smoothers
            from amgx_tpu.distributed.hierarchy import (
                build_distributed_hierarchy_block,
            )

            if self._local is not None:
                raise NotImplementedError(
                    "from_local_parts with block_size > 1: upload the "
                    "scalar-expanded blocks per rank or use the "
                    "global-matrix block entry"
                )
            if algorithm == "CLASSICAL":
                import warnings

                warnings.warn(
                    "distributed classical AMG is scalar-only; "
                    "block systems use aggregation (block-row graph)"
                )
            self.h = build_distributed_hierarchy_block(
                Asp, self.n_parts, self.block_size, self.cfg,
                self.scope,
                grid=self._grid, owner=self._owner,
                consolidate_rows=self.consolidate_rows,
                grade_lower=self.grade_lower,
                stop_measure=self._stop_measure(),
            )
        elif self._local is not None:
            local_parts, ownership, comm = self._local
            if algorithm == "CLASSICAL":
                from amgx_tpu.distributed.classical import (
                    build_distributed_classical_hierarchy_local,
                )

                self.h: DistHierarchy = (
                    build_distributed_classical_hierarchy_local(
                        local_parts, ownership, self.cfg, self.scope,
                        comm=comm,
                        consolidate_rows=self.consolidate_rows,
                        mesh=self.mesh,
                        stop_measure=self._stop_measure(),
                    )
                )
            else:
                from amgx_tpu.distributed.hierarchy import (
                    build_distributed_hierarchy_local,
                )

                self.h = build_distributed_hierarchy_local(
                    local_parts, ownership, self.cfg, self.scope,
                    comm=comm,
                    consolidate_rows=self.consolidate_rows,
                    grade_lower=self.grade_lower,
                    mesh=self.mesh,
                    stop_measure=self._stop_measure(),
                    sparsify_theta=self.sparsify_theta,
                    sparsify_from_level=self.sparsify_from_level,
                )
        elif algorithm == "CLASSICAL":
            from amgx_tpu.distributed.classical import (
                build_distributed_classical_hierarchy,
            )

            self.h = build_distributed_classical_hierarchy(
                Asp, self.n_parts, self.cfg, self.scope,
                grid=self._grid, owner=self._owner,
                consolidate_rows=self.consolidate_rows,
                stop_measure=self._stop_measure(),
            )
        else:
            self.h = build_distributed_hierarchy(
                Asp, self.n_parts, self.cfg, self.scope,
                grid=self._grid, owner=self._owner,
                consolidate_rows=self.consolidate_rows,
                grade_lower=self.grade_lower,
                stop_measure=self._stop_measure(),
                sparsify_theta=self.sparsify_theta,
                sparsify_from_level=self.sparsify_from_level,
            )
        self.fine = self.h.levels[0].A
        self._setup_level_smoothers()

        # replicated tail: standard AMG on the consolidated matrix
        from amgx_tpu.amg.hierarchy import AMGSolver
        from amgx_tpu.core.matrix import SparseMatrix

        from amgx_tpu.solvers.registry import make_nested

        # nested: the distributed cycle feeds residuals in the
        # consolidated ordering directly into make_cycle(), bypassing
        # solve()'s permute/unpermute — the tail must never reorder
        # reference dense_lu_solver.cu:669 exact_coarse_solve: solve
        # the (already-consolidated) global coarse problem exactly —
        # force a dense-LU coarsest solve on the replicated tail even
        # when the config asked for NOSOLVER/iterative
        tail_cfg = self.cfg
        if bool(self.cfg.get("exact_coarse_solve", self.scope)):
            import copy

            tail_cfg = copy.deepcopy(self.cfg)
            tail_cfg.set("coarse_solver", "DENSE_LU_SOLVER", self.scope)
        tail_amg = make_nested(AMGSolver(tail_cfg, self.scope))
        tail_amg.setup(SparseMatrix.from_scipy(self.h.tail_matrix))
        self.tail_amg = tail_amg
        self._tail_cycle = tail_amg.make_cycle()
        self._tail_params = tail_amg.apply_params()
        if _level_is_sharded(self.fine):
            # replicated device copies for the multi-process jit (host
            # numpy can't be auto-committed across processes)
            from jax.sharding import NamedSharding

            repl = NamedSharding(self.mesh, P())
            self._tail_params_dev = jax.tree.map(
                lambda a: jax.device_put(np.asarray(a), repl),
                self._tail_params,
            )

        # stacked [N, rows_pp_L] global ids of the deepest level's owned
        # slots (consolidation gather/scatter maps; padding -> id 0 with
        # zero mask).  Single source of truth: closed over by the cycle
        # as replicated constants, indexed per shard via axis_index.
        last = self.h.levels[-1].A
        ng = last.n_global
        gids = np.zeros((last.n_parts, last.rows_per_part), np.int64)
        msk = np.zeros((last.n_parts, last.rows_per_part), bool)
        gids[last.owner, last.local_of] = np.arange(ng, dtype=np.int64)
        msk[last.owner, last.local_of] = True
        self._tail_gids = gids
        self._tail_mask = msk

    # ------------------------------------------------------------------

    def _setup_level_smoothers(self):
        """Per-sharded-level smoother metadata.

        CHEBYSHEV: spectral interval of D^-1 A per level — the
        Gershgorin row-sum bound max_i sum_j |a_ij|/|a_ii| is a true
        upper bound on lambda_max (no estimation randomness, no
        collectives at setup; reference cheb_solver.cu power-iterates
        instead), lambda_min = cheby_min_lambda * lambda_max (ratio
        semantics as in solvers/chebyshev.py).

        MULTICOLOR_GS: distance-1 greedy coloring of each shard's LOCAL
        coupling graph (halo columns excluded — cross-shard coupling
        relaxes Jacobi-style with the sweep-stale halo, the reference's
        per-rank coloring semantics); padding rows get color -1.
        """
        ship = (
            self.h.levels
            if len(self.h.levels) == 1
            else self.h.levels[:-1]
        )
        comm = self.h.comm
        mesh = self.mesh
        self._level_smooth = []
        self._level_colors = []
        for lvl in ship:
            A = lvl.A
            colors = None
            if A.block_size > 1:
                # block levels (round 5, VERDICT r4 #5): multicolor
                # GS and DILU now run block-native on sharded levels
                # (RAS flavor, like scalar); everything else smooths
                # with block Jacobi — batched b×b diagonal-block
                # inverses computed ONCE here (inside the cycle they
                # would be re-factorized every smooth)
                dinv_b = np.asarray(
                    _safe_block_inv(jnp.asarray(np.asarray(A.diag)))
                )
                if self.smoother_kind == "mcgs":
                    cstack, ncolors, _ = _local_colors(A, comm, mesh)
                    self._level_smooth.append(("mcgs", ncolors))
                    self._level_colors.append((cstack, dinv_b))
                    continue
                if self.smoother_kind == "dilu":
                    blocks = _host_part_blocks(A)
                    _, ncolors, host_colors = _local_colors(
                        A, comm, mesh, blocks=blocks,
                        build_stacked=False,
                    )
                    colors = _local_dilu(
                        A, host_colors, ncolors, comm, mesh,
                        blocks=blocks,
                    )
                    self._level_smooth.append(("dilu", ncolors))
                    self._level_colors.append(colors)
                    continue
                self._level_smooth.append(("jacobi", None))
                self._level_colors.append(dinv_b)
                continue
            if self.smoother_kind == "cheby":
                # Gershgorin bound per part; the level-wide max is a
                # comm consensus in the per-rank assembly
                lmax_loc = {}
                for p, (_c, vals_p, diag_p, _nr) in (
                    _host_part_blocks(A).items()
                ):
                    ev = np.abs(vals_p).sum(axis=-1)
                    d = np.abs(diag_p)
                    ratio = np.where(
                        d > 0, ev / np.maximum(d, 1e-300), 0.0
                    )
                    lmax_loc[p] = float(ratio.max()) if ratio.size else 0.0
                if self.cheby_mode == 3:
                    lmax, lmin = self.cheby_user_max, self.cheby_user_min
                else:
                    if _level_is_sharded(A):
                        lmax = max(
                            comm.allgather(lmax_loc, kind="cheby-lmax")
                        )
                    else:
                        lmax = max(lmax_loc.values(), default=0.0)
                    lmax = max(float(lmax), 1e-12)
                    lmin = self.cheby_user_min * lmax
                self._level_smooth.append(
                    ("cheby", (float(lmax), float(lmin)))
                )
            elif self.smoother_kind == "mcgs":
                colors, ncolors, _ = _local_colors(A, comm, mesh)
                self._level_smooth.append(("mcgs", ncolors))
            elif self.smoother_kind == "dilu":
                blocks = _host_part_blocks(A)
                _, ncolors, host_colors = _local_colors(
                    A, comm, mesh, blocks=blocks, build_stacked=False
                )
                colors = _local_dilu(
                    A, host_colors, ncolors, comm, mesh, blocks=blocks
                )
                self._level_smooth.append(("dilu", ncolors))
            else:
                self._level_smooth.append((self.smoother_kind, None))
            self._level_colors.append(colors)

    def _traced_level_params(self):
        """Per-level traced arrays: (shard_params(A), P, R) stacks.
        The deepest level is the consolidation bridge — its operator
        lives in the replicated tail, so no arrays are shipped for it
        (unless it is also the fine level, whose operator drives the
        outer PCG)."""
        out = []
        ship = (
            self.h.levels
            if len(self.h.levels) == 1
            else self.h.levels[:-1]
        )
        for i, lvl in enumerate(ship):
            entry = [_shard_params(lvl.A, self.cfg, self.scope)]
            for a in (lvl.P_cols, lvl.P_vals, lvl.R_cols, lvl.R_vals):
                entry.append(None if a is None else jnp.asarray(a))
            sdata = self._level_colors[i]
            entry.append(
                None
                if sdata is None
                else jax.tree.map(jnp.asarray, sdata)
            )
            out.append(tuple(entry))
        if len(self.h.levels) > 1:
            # deepest level: ship ONLY its exchange maps — classical
            # restriction/prolongation at the level above need the
            # coarse plan for the reverse/forward halo exchanges; the
            # operator itself lives in the replicated tail
            sp = _shard_params(self.h.levels[-1].A, self.cfg, self.scope)
            out.append(({"ex": sp["ex"]},))
        return tuple(out)

    def _make_cycle(self):
        """Shard-local multi-level V-cycle closure (zero initial guess).

        Returns fn(level_params_local, tail_params, tail_gids, tail_msk,
        r_loc) -> z_loc, traced inside shard_map.
        """
        axis = self.axis
        levels = self.h.levels
        spmvs = [make_local_spmv(l.A, axis) for l in levels]
        omega = self.omega
        pre, post = max(self.presweeps, 1), max(self.postsweeps, 1)
        tail_cycle = self._tail_cycle

        level_smooth = self._level_smooth

        def smooth(l, lp, r_l, z, sweeps, tag):
            with named_scope(f"damg_l{l}_{tag}"):
                return _smooth_body(l, lp, r_l, z, sweeps)

        def _smooth_body(l, lp, r_l, z, sweeps):
            sh = lp[0]
            d = sh["diag"]
            kind, meta = level_smooth[l]
            if kind == "cheby":
                # Chebyshev polynomial on [lmin, lmax] of D^-1 A
                # (reference cheb_solver.cu three-term recurrence);
                # every step is one distributed SpMV — no coloring, no
                # extra exchanges: the TPU-preferred smoother
                lmax, lmin = meta
                theta = (lmax + lmin) / 2.0
                delta = max((lmax - lmin) / 2.0, 1e-30)
                sigma = theta / delta
                dinv = jnp.where(d != 0, 1.0 / d, 1.0)
                for _ in range(sweeps):
                    rho_old = 1.0 / sigma
                    rr = r_l if z is None else r_l - spmvs[l](sh, z)
                    dd = dinv * rr / theta
                    z = dd if z is None else z + dd
                    for _k in range(self.cheby_order - 1):
                        rho = 1.0 / (2.0 * sigma - rho_old)
                        rr = r_l - spmvs[l](sh, z)
                        dd = (
                            rho * rho_old * dd
                            + (2.0 * rho / delta) * dinv * rr
                        )
                        z = z + dd
                        rho_old = rho
                return z
            if kind == "mcgs":
                # multicolor GS: one halo exchange per sweep (halo is
                # sweep-stale, the reference's per-rank semantics);
                # same-color local rows update together.  Block levels
                # (round 5) run the same sweep with b×b einsums and
                # block-diagonal inverses.
                ncolors = meta
                om = jnp.asarray(omega, r_l.dtype)
                ell_cols, ell_vals = sh["ell"]
                if levels[l].A.block_size > 1:
                    colors, dinv_b = lp[5]
                    dinv_b = jnp.asarray(dinv_b)
                    if z is None:
                        z = jnp.zeros_like(r_l)
                    for _s in range(sweeps):
                        halo = exchange_halo(levels[l].A, sh, z, axis)
                        for c in range(ncolors):
                            xf = jnp.concatenate([z, halo])
                            y = jnp.einsum(
                                "rwij,rwj->ri", ell_vals, xf[ell_cols]
                            )
                            upd = jnp.einsum(
                                "rij,rj->ri", dinv_b, r_l - y
                            )
                            z = jnp.where(
                                (colors == c)[:, None],
                                z + om * upd,
                                z,
                            )
                    return z
                colors = lp[5]
                dinv = jnp.where(d != 0, 1.0 / d, 1.0)
                if z is None:
                    z = jnp.zeros_like(r_l)
                for _s in range(sweeps):
                    halo = exchange_halo(levels[l].A, sh, z, axis)
                    for c in range(ncolors):
                        xf = jnp.concatenate([z, halo])
                        y = jnp.sum(ell_vals * xf[ell_cols], axis=-1)
                        z = jnp.where(
                            colors == c,
                            z + om * dinv * (r_l - y),
                            z,
                        )
                return z
            if kind == "dilu":
                # per-shard DILU (restricted additive Schwarz): forward
                # color sweep (E+L) y = rr, backward (E+U) z' = E y —
                # compact per-color slices, O(nnz) per application;
                # cross-shard coupling enters through the outer
                # residual (one distributed SpMV per sweep)
                ncolors = meta
                slices = lp[5]
                om = jnp.asarray(omega, r_l.dtype)
                nloc = r_l.shape[0]
                blocked = levels[l].A.block_size > 1

                def minv(rr):
                    pad = (
                        jnp.zeros((1, rr.shape[1]), rr.dtype)
                        if blocked else jnp.zeros((1,), rr.dtype)
                    )
                    rx = jnp.concatenate([rr, pad])
                    y = jnp.zeros_like(rx)
                    for c in range(ncolors):
                        ridx, Lc, Lv, _, _, einv = slices[c]
                        if blocked:
                            ly = jnp.einsum("nwij,nwj->ni", Lv, y[Lc])
                            y = y.at[ridx].set(jnp.einsum(
                                "nij,nj->ni", einv, rx[ridx] - ly))
                        else:
                            ly = jnp.sum(Lv * y[Lc], axis=-1)
                            y = y.at[ridx].set(
                                einv * (rx[ridx] - ly))
                    zz = jnp.zeros_like(rx)
                    for c in range(ncolors - 1, -1, -1):
                        ridx, _, _, Uc, Uv, einv = slices[c]
                        if blocked:
                            uz = jnp.einsum("nwij,nwj->ni", Uv, zz[Uc])
                            corr = jnp.einsum(
                                "nij,nj->ni", einv, uz)
                            zz = zz.at[ridx].set(y[ridx] - corr)
                        else:
                            uz = jnp.sum(Uv * zz[Uc], axis=-1)
                            zz = zz.at[ridx].set(y[ridx] - einv * uz)
                    return zz[:nloc]

                for i in range(sweeps):
                    rr = r_l if (i == 0 and z is None) else (
                        r_l - spmvs[l](sh, z)
                    )
                    upd = om * minv(rr)
                    z = upd if z is None else z + upd
                return z
            om = jnp.asarray(omega, r_l.dtype)
            if levels[l].A.block_size > 1:
                # block Jacobi (reference block_jacobi_solver.cu):
                # the batched b×b diagonal-block inverses were
                # factorized once at setup (_setup_level_smoothers)
                # and ship as this level's smoother data
                dinv_b = lp[5]
                for i in range(sweeps):
                    rr = r_l if (i == 0 and z is None) else (
                        r_l - spmvs[l](sh, z)
                    )
                    upd = om * jnp.einsum("rij,rj->ri", dinv_b, rr)
                    z = upd if z is None else z + upd
                return z
            if kind == "l1":
                # L1 diagonal: a_ii + sum_{j!=i} |a_ij| (reference
                # jacobi_l1_solver.cu) — computed from the shard's ELL
                # values, one cheap reduction per sweep set
                av = jnp.sum(jnp.abs(sh["ell"][1]), axis=-1)
                d = d + (av - jnp.abs(d))
            dinv = jnp.where(d != 0, 1.0 / d, 1.0)
            for i in range(sweeps):
                rr = r_l if (i == 0 and z is None) else (
                    r_l - spmvs[l](sh, z)
                )
                z = om * dinv * rr if z is None else z + om * dinv * rr
            return z

        # consolidation gather/scatter maps (replicated closure
        # constants; per-shard rows selected via axis_index)
        gids = jnp.asarray(self._tail_gids)  # [N, rows_pp_L]
        msk = jnp.asarray(self._tail_mask)
        ng = self.h.tail_matrix.shape[0]

        blk = self.block_size

        def descend(l, lps, tail_params, r_l, branching=True):
            lp = lps[l]
            if l == len(levels) - 1:
                # consolidation bridge: each shard scatters its OWNED
                # slots into the (small) tail vector and one psum
                # replicates it — O(ng) bytes per shard, proportional
                # to the ACTIVE tier (reference glue_vector via
                # sub-communicators, glue.h:525; an all_gather of the
                # padded [N, rows_pp_L] stack would cost
                # O(N·rows_pp_L) regardless of how many shards still
                # own rows).  Block levels expand to the scalar tail
                # operator (block gid g covers scalar ids g*b..).
                me = jax.lax.axis_index(axis)
                with named_scope(f"damg_l{l}_tail_glue"):
                    rg = jnp.zeros((ng,), r_l.dtype)
                    # .add, not .set: padding slots alias id 0
                    # (masked to 0)
                    if blk > 1:
                        ids2 = (
                            gids[me][:, None] * blk + jnp.arange(blk)
                        )
                        rg = rg.at[ids2.reshape(-1)].add(
                            jnp.where(
                                msk[me][:, None], r_l, 0.0
                            ).reshape(-1)
                        )
                    else:
                        rg = rg.at[gids[me]].add(
                            jnp.where(msk[me], r_l, 0.0)
                        )
                    rg = jax.lax.psum(rg, axis)
                with named_scope("damg_tail_cycle"):
                    eg = tail_cycle(tail_params, rg, jnp.zeros_like(rg))
                if blk > 1:
                    egb = eg.reshape(-1, blk)
                    return jnp.where(
                        msk[me][:, None], egb[gids[me]], 0.0
                    )
                return jnp.where(msk[me], eg[gids[me]], 0.0)
            sh = lp[0]
            z = smooth(l, lp, r_l, None, pre, "presmooth")
            with named_scope(f"damg_l{l}_restrict"):
                rr = r_l - spmvs[l](sh, z)
                Pc, Pv, Rc, Rv = lp[1], lp[2], lp[3], lp[4]
                if levels[l].classical:
                    # R = P^T with shard-coupling P: scatter-add the
                    # partials into extended coarse slots (owned +
                    # coarse halo), then fold halo partials back to
                    # their owners (reference add_from_halo)
                    A_next = levels[l + 1].A
                    sh_next = lps[l + 1][0]
                    rows_c = A_next.rows_per_part
                    y = jnp.zeros(
                        (rows_c + A_next.max_halo,), rr.dtype
                    )
                    y = y.at[Pc].add(Pv * rr[:, None])
                    rc = exchange_halo_reverse(
                        A_next, sh_next, y[:rows_c], y[rows_c:], axis
                    )
                elif blk > 1:
                    # aggregate map ⊗ I_b: whole b-vectors restrict
                    rc = jnp.sum(Rv[..., None] * rr[Rc], axis=1)
                else:
                    rc = jnp.sum(Rv * rr[Rc], axis=1)
            # graded-consolidation bridge (reference glue_vector):
            # members' restricted partials ppermute onto their group
            # leader; non-leaders continue with a zero coarse system
            bridge = levels[l].bridge
            if bridge is not None:
                perms_down, is_leader = bridge
                lead_m = jnp.asarray(is_leader)
                me = jax.lax.axis_index(axis)
                # log-depth reduction: each step forwards the
                # ACCUMULATED subtree partials (see _grade_groups)
                with named_scope(f"damg_l{l}_glue"):
                    for perm in perms_down:
                        rc = rc + jax.lax.ppermute(
                            rc, axis, perm=list(perm)
                        )
                    rc = jnp.where(lead_m[me], rc, 0.0)
            # gamma/K-cycles visit the coarse level more than once
            # (reference fixed_cycle.cu / cg_[flex_]cycle.cu); branch
            # only on the top levels to bound the unrolled trace, like
            # the serial hierarchy's W_MAX_BRANCH_LEVELS.  F's second
            # visit is a plain V walk.
            from amgx_tpu.amg.hierarchy import W_MAX_BRANCH_LEVELS

            branch = (
                branching
                and self.cycle_type in ("W", "F", "CG", "CGF")
                and l < min(len(levels) - 2, W_MAX_BRANCH_LEVELS)
            )
            if branch and self.cycle_type in ("CG", "CGF"):
                ec = kcycle(l + 1, lps, tail_params, rc)
            else:
                ec = descend(l + 1, lps, tail_params, rc, branching)
                if branch:
                    zc_lp = lps[l + 1]
                    rc2 = rc - spmvs[l + 1](zc_lp[0], ec)
                    ec = ec + descend(
                        l + 1, lps, tail_params, rc2,
                        branching=(self.cycle_type == "W"),
                    )
            if bridge is not None:
                # unglue: tree-broadcast the leader's correction back to
                # its group members (reference unglue_vector) — the
                # reduction steps inverted and replayed in reverse
                with named_scope(f"damg_l{l}_unglue"):
                    ec = jnp.where(lead_m[me], ec, 0.0)
                    for perm in reversed(perms_down):
                        inv = [(dst, src) for (src, dst) in perm]
                        ec = ec + jax.lax.ppermute(ec, axis, perm=inv)
            with named_scope(f"damg_l{l}_prolong"):
                if levels[l].classical:
                    # P gathers from owned coarse + coarse halo: one
                    # forward halo exchange of the correction
                    A_next = levels[l + 1].A
                    sh_next = lps[l + 1][0]
                    halo_e = exchange_halo(A_next, sh_next, ec, axis)
                    e_ext = jnp.concatenate([ec, halo_e])
                    z = z + jnp.sum(Pv * e_ext[Pc], axis=1)
                elif blk > 1:
                    z = z + jnp.sum(Pv[..., None] * ec[Pc], axis=1)
                else:
                    z = z + jnp.sum(Pv * ec[Pc], axis=1)
            z = smooth(l, lp, r_l, z, post, "postsmooth")
            return z

        def kcycle(l, lps, tail_params, b_c):
            """K-cycle coarse solve (reference cg_[flex_]cycle.cu,
            Notay): cycle_iters (F)CG iterations on the sharded coarse
            system, preconditioned by the non-branching cycle; dots
            are psum'd over the mesh axis."""
            sh = lps[l][0]
            flexible = self.cycle_type == "CGF"
            x = jnp.zeros_like(b_c)
            r = b_c
            z = descend(l, lps, tail_params, r, branching=False)
            p = z
            rho = _pdot(r, z, axis)
            for j in range(max(self.cycle_iters, 1)):
                q = spmvs[l](sh, p)
                pq = _pdot(p, q, axis)
                alpha = jnp.where(pq != 0, rho / pq, 0.0)
                x = x + alpha * p
                r_new = r - alpha * q
                if j + 1 == max(self.cycle_iters, 1):
                    break
                z = descend(
                    l, lps, tail_params, r_new, branching=False
                )
                rho_new = _pdot(r_new, z, axis)
                denom = jnp.where(rho != 0, rho, 1.0)
                if flexible:
                    beta = _pdot(z, r_new - r, axis) / denom
                else:
                    beta = rho_new / denom
                p = z + beta * p
                r, rho = r_new, rho_new
            return x

        def cycle(lps, tail_params, r0):
            return descend(0, lps, tail_params, r0)

        return cycle

    def _build_solve(self, max_iters, tol):
        axis = self.axis
        lps = self._traced_level_params()
        in_lps = jax.tree.map(lambda _: P(axis), lps)
        cycle = self._make_cycle()
        fine_spmv = make_local_spmv(self.fine, axis)

        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(in_lps, None, P(axis)),
            out_specs=(P(axis), P(), P()),
        )
        def solve_sm(lps_stk, tail_params, b_stk):
            lps_loc = jax.tree.map(lambda s: s[0], lps_stk)
            b_loc = b_stk[0]
            sh0 = lps_loc[0][0]
            M = lambda r: cycle(lps_loc, tail_params, r)
            x = jnp.zeros_like(b_loc)
            r = b_loc
            z = M(r)
            p = z
            rho = _pdot(r, z, axis)
            nrm0 = jnp.sqrt(_pdot(b_loc, b_loc, axis))

            def cond(c):
                it, x, r, p, rho, nrm = c
                return (it < max_iters) & (nrm >= tol * nrm0) & (
                    nrm0 > 0
                )

            def body(c):
                it, x, r, p, rho, nrm = c
                q = fine_spmv(sh0, p)
                alpha = rho / _pdot(p, q, axis)
                x = x + alpha * p
                r = r - alpha * q
                z = M(r)
                rho_new = _pdot(r, z, axis)
                p = z + (rho_new / rho) * p
                nrm = jnp.sqrt(_pdot(r, r, axis))
                return (it + 1, x, r, p, rho_new, nrm)

            it, x, r, p, rho, nrm = jax.lax.while_loop(
                cond, body, (jnp.int32(0), x, r, p, rho, nrm0)
            )
            return x[None], it, nrm

        return jax.jit(solve_sm), lps

    def _build_solve_sstep(self, max_iters, tol, s):
        """Distributed s-step PCG outer (reference SSTEP_PCG economics
        on the row-sharded mesh): s cycle applications and s halo-
        exchanged SpMVs per outer iteration, but only TWO cross-shard
        collectives per s steps — ONE psum'd fused Gram block
        (:func:`amgx_tpu.distributed.solve._pgram`, every inner
        product of the outer iteration) plus the monitor norm —
        versus 3 psums per step for monitored PCG.  The scalar
        recurrences are the serial SSTEP_PCG's (solvers/sstep.py),
        operating on the replicated Gram matrix, with the SCALED-basis
        column normalization read off the Gram diagonal (no extra
        reduction).  ``max_iters`` bounds OUTER iterations (one outer
        = s inner steps)."""
        from amgx_tpu.solvers.sstep import _guarded_solve

        axis = self.axis
        lps = self._traced_level_params()
        in_lps = jax.tree.map(lambda _: P(axis), lps)
        cycle = self._make_cycle()
        fine_spmv = make_local_spmv(self.fine, axis)

        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(in_lps, None, P(axis)),
            out_specs=(P(axis), P(), P()),
        )
        def solve_sm(lps_stk, tail_params, b_stk):
            lps_loc = jax.tree.map(lambda st: st[0], lps_stk)
            b_loc = b_stk[0]
            sh0 = lps_loc[0][0]
            M = lambda r: cycle(lps_loc, tail_params, r)
            dt = b_loc.dtype
            nrm0 = jnp.sqrt(_pdot(b_loc, b_loc, axis))
            x = jnp.zeros_like(b_loc)
            r = b_loc
            # previous direction block and its A-image: zero on entry
            # makes the first A-orthogonalization a no-op exactly;
            # pvary marks them device-varying (shard-local basis) for
            # the new shard_map's while_loop carry typing
            Pr = pvary(jnp.zeros((s,) + b_loc.shape, dt), (axis,))
            APr = jnp.zeros_like(Pr)

            def cond(c):
                it, x, r, Pr, APr, nrm = c
                return (
                    (it < max_iters) & (nrm >= tol * nrm0) & (nrm0 > 0)
                )

            def body(c):
                it, x, r, Pr, APr, nrm = c
                # -- s-step Krylov block: s SpMVs + s cycle applies --
                z = M(r)
                z_rows, az_rows = [z], []
                for _ in range(s - 1):
                    az = fine_spmv(sh0, z_rows[-1])
                    az_rows.append(az)
                    z_rows.append(M(az))
                az_rows.append(fine_spmv(sh0, z_rows[-1]))
                Z = jnp.stack(z_rows)
                AZ = jnp.stack(az_rows)

                # -- collective 1 of 2: the psum'd fused Gram block --
                L = jnp.concatenate([Z, Pr, r[None]], axis=0)
                Rt = jnp.concatenate([AZ, APr, r[None]], axis=0)
                G = _pgram(L, Rt, axis)  # (2s+1, 2s+1) replicated

                # SCALED basis: normalize columns by their A-norms
                # from the Gram diagonal — no extra reduction
                rdt = jnp.zeros((), G.dtype).real.dtype
                d = jnp.sqrt(jnp.maximum(
                    jnp.abs(jnp.diagonal(G)[:s].real),
                    jnp.finfo(rdt).tiny,
                )).astype(rdt)
                inv = (1.0 / d).astype(G.dtype)
                sl = jnp.concatenate(
                    [inv, jnp.ones((s + 1,), G.dtype)]
                )
                G = G * sl[:, None] * sl[None, :]
                Z = Z * inv[:, None]
                AZ = AZ * inv[:, None]

                G_ZAZ = G[:s, :s]
                G_ZAP = G[:s, s:2 * s]
                G_Zr = G[:s, -1]
                G_PAZ = G[s:2 * s, :s]
                W_prev = G[s:2 * s, s:2 * s]
                G_Pr = G[s:2 * s, -1]

                # scalar recurrences off the replicated Gram matrix
                # (identical on every shard — SPMD)
                C = -_guarded_solve(W_prev, G_PAZ).T
                P_new = Z + C @ Pr
                AP_new = AZ + C @ APr
                Cc = jnp.conj(C)
                W_new = (
                    G_ZAZ
                    + G_ZAP @ C.T
                    + Cc @ (G_PAZ + W_prev @ C.T)
                )
                g = G_Zr + Cc @ G_Pr
                a = _guarded_solve(W_new, g)

                x = x + jnp.tensordot(a, P_new, axes=1)
                r = r - jnp.tensordot(a, AP_new, axes=1)
                # -- collective 2 of 2: the monitor norm -------------
                nrm = jnp.sqrt(_pdot(r, r, axis))
                return (it + 1, x, r, P_new, AP_new, nrm)

            it, x, r, Pr, APr, nrm = jax.lax.while_loop(
                cond, body, (jnp.int32(0), x, r, Pr, APr, nrm0)
            )
            return x[None], it, nrm

        return jax.jit(solve_sm), lps

    def _build_solve_fgmres(self, max_iters, tol, restart):
        """Distributed FGMRES(restart) preconditioned by the AMG cycle
        (reference fgmres_solver.cu; the north-star outer solver).

        Same Arnoldi/Givens machinery as the serial FGMRES — H, g, cs,
        sn are replicated scalars identical on every shard because all
        dots ride psum — with the basis vectors V/Z stored shard-local.
        """
        axis = self.axis
        lps = self._traced_level_params()
        in_lps = jax.tree.map(lambda _: P(axis), lps)
        cycle = self._make_cycle()
        fine_spmv = make_local_spmv(self.fine, axis)
        m = restart

        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(in_lps, None, P(axis)),
            out_specs=(P(axis), P(), P()),
        )
        def solve_sm(lps_stk, tail_params, b_stk):
            lps_loc = jax.tree.map(lambda s: s[0], lps_stk)
            b_loc = b_stk[0]
            sh0 = lps_loc[0][0]
            M = lambda r: cycle(lps_loc, tail_params, r)
            n = b_loc.shape[0]
            dt = b_loc.dtype
            nrm0 = jnp.sqrt(_pdot(b_loc, b_loc, axis))

            def arnoldi_step(c):
                (j, V, Z, H, g, cs, sn, it, res) = c
                v = V[j]
                z = M(v)
                w = fine_spmv(sh0, z)
                Z = Z.at[j].set(z)
                hcol = jnp.zeros(m + 1, dt)

                def mgs(i, wc):
                    w, hcol = wc
                    h = jnp.where(
                        i <= j, _pdot(V[i], w, axis), 0.0
                    )
                    w = w - h * V[i]
                    return (w, hcol.at[i].set(h))

                w, hcol = jax.lax.fori_loop(0, m, mgs, (w, hcol))
                hlast = jnp.sqrt(_pdot(w, w, axis))
                hcol = hcol.at[j + 1].set(hlast)
                V = V.at[j + 1].set(
                    w / jnp.where(hlast > 0, hlast, 1.0)
                )

                def rot(i, hc):
                    t = cs[i] * hc[i] + sn[i] * hc[i + 1]
                    u = -sn[i] * hc[i] + cs[i] * hc[i + 1]
                    do = i < j
                    return hc.at[i].set(
                        jnp.where(do, t, hc[i])
                    ).at[i + 1].set(jnp.where(do, u, hc[i + 1]))

                hcol = jax.lax.fori_loop(0, m, rot, hcol)
                hj, hj1 = hcol[j], hcol[j + 1]
                denom = jnp.sqrt(hj * hj + hj1 * hj1)
                denom = jnp.where(denom > 0, denom, 1.0)
                c_new, s_new = hj / denom, hj1 / denom
                hcol = hcol.at[j].set(denom).at[j + 1].set(0.0)
                cs = cs.at[j].set(c_new)
                sn = sn.at[j].set(s_new)
                gj = g[j]
                g = g.at[j].set(c_new * gj).at[j + 1].set(
                    -s_new * gj
                )
                H = H.at[:, j].set(hcol)
                return (
                    j + 1, V, Z, H, g, cs, sn, it + 1,
                    jnp.abs(g[j + 1]),
                )

            def arnoldi_cond(c):
                j, it, res = c[0], c[7], c[8]
                return (
                    (j < m) & (res >= tol * nrm0) & (it < max_iters)
                )

            def restart_body(c):
                x, it, res = c
                r = b_loc - fine_spmv(sh0, x)
                beta = jnp.sqrt(_pdot(r, r, axis))
                # pvary: V/Z hold shard-local basis vectors — mark the
                # zero initializers as device-varying so the while_loop
                # carry types match (shard_map vma typing).  Shapes
                # follow b_loc so block residuals [rows, b] work.
                V = pvary(
                    jnp.zeros((m + 1,) + b_loc.shape, dt), (axis,)
                )
                V = V.at[0].set(
                    r / jnp.where(beta > 0, beta, 1.0)
                )
                Z = pvary(
                    jnp.zeros((m,) + b_loc.shape, dt), (axis,)
                )
                H = jnp.zeros((m + 1, m), dt)
                g = jnp.zeros(m + 1, dt).at[0].set(beta)
                cs = jnp.ones(m, dt)
                sn = jnp.zeros(m, dt)
                (j, V, Z, H, g, cs, sn, it, res) = jax.lax.while_loop(
                    arnoldi_cond, arnoldi_step,
                    (jnp.int32(0), V, Z, H, g, cs, sn, it, beta),
                )
                idx = jnp.arange(m)
                diag_fix = jnp.where(idx >= j, 1.0, 0.0)
                R = H[:m, :m] + jnp.diag(diag_fix)
                gm = jnp.where(idx < j, g[:m], 0.0)
                y = jax.scipy.linalg.solve_triangular(
                    R, gm, lower=False
                )
                x = x + jnp.tensordot(y, Z, axes=1)
                return (x, it, res)

            def outer_cond(c):
                it, res = c[1], c[2]
                return (res >= tol * nrm0) & (it < max_iters) & (
                    nrm0 > 0
                )

            x, it, res = jax.lax.while_loop(
                outer_cond, restart_body,
                (jnp.zeros_like(b_loc), jnp.int32(0), nrm0),
            )
            return x[None], it, res

        return jax.jit(solve_sm), lps

    def collective_stats(self):
        """Analytic solve-side collective byte model, one cycle visit
        per level (VERDICT r3 #7: collective scope on graded tiers).

        Halo-exchange bytes count only the LISTED ppermute pairs — a
        shard with no owned rows at a graded level appears in no
        (src, dst) pair, so per-level bytes scale with the ACTIVE
        tier (the TPU analogue of the reference's sub-communicator
        scope, glue.h:114,200).  The consolidation bridge counts its
        reduction-tree pairs, and the tail glue is one O(ng) psum per
        shard (NOT an O(N·rows_pp) all_gather).  Returns
        {"levels": [...], "tail_bytes_per_shard": int}.
        """
        item = np.dtype(np.asarray(self.h.tail_matrix.data).dtype
                        ).itemsize
        bvec = max(self.block_size, 1)
        out = []
        levels = self.h.levels
        for l, lvl in enumerate(levels):
            A = lvl.A
            active = int(np.count_nonzero(np.asarray(A.n_owned)))
            deepest = l == len(levels) - 1 and len(levels) > 1
            if deepest and not levels[l - 1].classical:
                # the cycle performs NO halo exchange on the
                # consolidated deepest level (tail glue only); its
                # exchange plan is exercised only by classical
                # transfer operators at the level above
                halo = 0
            elif A.uses_ppermute:
                halo = sum(
                    len(A.perms[d]) * int(s.shape[-1])
                    for d, s in enumerate(A.send_idx_d)
                ) * item * bvec
            else:
                halo = (
                    A.n_parts * int(A.max_send) * item * bvec
                )
            bridge = 0
            if lvl.bridge is not None and l + 1 < len(levels):
                perms_down, _ = lvl.bridge
                rows_c = levels[l + 1].A.rows_per_part
                bridge = sum(
                    len(step) for step in perms_down
                ) * rows_c * item * bvec
            out.append(
                dict(level=l, active_shards=active,
                     halo_bytes=int(halo), bridge_bytes=int(bridge))
            )
        return dict(
            levels=out,
            tail_bytes_per_shard=int(
                self.h.tail_matrix.shape[0] * item
            ),
        )

    def _pad_vector_sharded(self, b):
        """Replicated host b -> stacked [N, rows] sharded one part per
        mesh device (the per-rank analogue of pad_vector: each process
        materializes only its parts' slices)."""
        from amgx_tpu.distributed.multihost import (
            addressable_parts,
            stack_parts_sharded,
        )

        A = self.fine
        offs = np.concatenate([[0], np.cumsum(A.n_owned)]).astype(
            np.int64
        )
        per = {}
        for p in addressable_parts(self.mesh):
            buf = np.zeros((A.rows_per_part,), dtype=b.dtype)
            buf[: A.n_owned[p]] = b[offs[p]: offs[p + 1]]
            per[p] = buf
        return stack_parts_sharded(per, self.mesh, A.n_parts)

    def _unpad_vector_sharded(self, x):
        """Sharded stacked x -> global host vector: each process reads
        its addressable shards; the parts ride one comm allgather
        (matched SPMD round on every process)."""
        A = self.fine
        loc = {}
        for s in x.addressable_shards:
            p = s.index[0].start
            loc[p] = np.asarray(s.data)[0][: A.n_owned[p]]
        parts = self.h.comm.allgather(loc, kind="solve-x")
        return np.concatenate(parts)

    def _resolve_program(self, outer, max_iters, tol, restart,
                         s_step=None):
        """The jitted sharded program + traced level params for one
        (outer, max_iters, tol, restart/s) key, building on miss."""
        if outer == "sstep":
            s = int(
                self.cfg.get("s_step", self.scope)
                if s_step is None else s_step
            )
            s = max(s, 1)
            key = (outer, max_iters, float(tol), s)
        else:
            key = (outer, max_iters, float(tol), restart)
        hit = self._solve_cache.get(key)
        if hit is None:
            if outer == "fgmres":
                hit = self._build_solve_fgmres(max_iters, tol, restart)
            elif outer == "sstep":
                hit = self._build_solve_sstep(max_iters, tol, key[3])
            else:
                hit = self._build_solve(max_iters, tol)
            self._solve_cache[key] = hit
        return hit

    def solve_device(self, b, max_iters=200, tol=1e-8, outer="pcg",
                     restart=32, s_step=None):
        """Async face of :meth:`solve` (the serve placement path):
        launches the sharded program and returns the DEVICE results
        ``(x_stacked [N, rows], iters, nrm)`` with NO host sync — the
        caller (a serve group's lazy ``SolveResult``) owns the one
        fetch.  Single-process stacked-numpy hierarchies only (the
        multi-process per-rank path syncs in its gather anyway)."""
        fn, lps = self._resolve_program(
            outer, max_iters, tol, restart, s_step
        )
        if _level_is_sharded(self.fine):
            raise NotImplementedError(
                "solve_device: per-rank sharded assembly gathers at "
                "unpad; use solve()"
            )
        bp = jnp.asarray(self.fine.pad_vector(np.asarray(b)))
        return fn(lps, self._tail_params, bp)

    def solve(self, b, max_iters=200, tol=1e-8, outer="pcg",
              restart=32, s_step=None):
        """Distributed AMG-preconditioned solve -> (x, iters, nrm).
        ``outer``: 'pcg' (default), 'fgmres' (the north-star outer,
        reference FGMRES_AGGREGATION), or 'sstep' (communication-
        avoiding s-step PCG: two collectives per s inner steps via the
        psum'd fused Gram block; ``s_step`` defaults to the config's
        ``s_step``, and the returned iteration count is OUTER
        iterations — multiply by s for inner-step parity).  Jitted
        programs are cached per (outer, max_iters, tol, restart/s)."""
        fn, lps = self._resolve_program(
            outer, max_iters, tol, restart, s_step
        )
        if _level_is_sharded(self.fine):
            bp = self._pad_vector_sharded(np.asarray(b))
            x, it, nrm = fn(lps, self._tail_params_dev, bp)
            return (self._unpad_vector_sharded(x), int(it), float(nrm))
        bp = jnp.asarray(self.fine.pad_vector(np.asarray(b)))
        x, it, nrm = fn(lps, self._tail_params, bp)
        return (
            self.fine.unpad_vector(jax.device_get(x)),
            int(it),
            float(nrm),
        )
