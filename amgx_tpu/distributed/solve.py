"""Distributed SpMV and Krylov solves under shard_map.

The solve loop runs entirely inside ``shard_map`` over a device mesh.
Halo exchange (reference exchange_halo, comms_mpi_hostbuffer_stream.cu)
is one ``lax.ppermute`` per neighbor direction — B2L gather into a
per-direction send buffer, neighbor permute over ICI, halo scatter —
with comm volume O(boundary).  Partitions without a small neighbor-
direction set fall back to the all_gather pool (O(N·max_send)).
Reductions are ``psum`` (reference global_reduce).  The while_loop
condition uses the psum'd scalar, identical on every shard — SPMD.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from amgx_tpu.core.sharding import shard_map
from amgx_tpu.distributed.partition import DistributedMatrix
from amgx_tpu.ops import blas as blas_mod

# Collective-site accounting (trace-time, the PR 8 machinery):
#   * every cross-shard reduction (psum) records into BOTH the PR 8
#     reduction slot (ops/blas.reduction_counter — one psum IS one
#     global reduction) and the "psum_sites" slot serve/batched's
#     psum_site_counter reads, so the serve-side collective gates see
#     distributed solves with no extra plumbing;
#   * every halo exchange records into its own "halo_sites" slot —
#     ci/halo_bench.py gates the fine-level SpMV to <= 1 exchange per
#     apply (forward; the reverse exchange records the same site).
_record_psum_site, _psum_sites = blas_mod.make_site_counter(
    "psum_sites"
)
record_halo_exchange, halo_site_counter = blas_mod.make_site_counter(
    "halo_sites"
)


def _shard_params(A: DistributedMatrix, cfg=None, scope="default"):
    """Traced per-shard arrays, stacked on the shard axis: the local
    operator (interior/boundary split when built) plus halo-exchange
    maps, as a dict pytree.

    min_rows_latency_hiding (reference core.cu:346): when the config
    sets it explicitly, levels below the row threshold drop the
    interior/boundary overlap split (a negative explicit value drops
    it everywhere).  Unset, the TPU default keeps the overlap at every
    level — the split costs nothing under XLA's scheduler."""
    overlap_ok = True
    if cfg is not None and cfg.has("min_rows_latency_hiding", scope):
        thresh = int(cfg.get("min_rows_latency_hiding", scope))
        rows = int(A.ell_cols.shape[1]) if hasattr(
            A.ell_cols, "shape") else 0
        overlap_ok = thresh >= 0 and rows >= thresh
    out = {
        "diag": jnp.asarray(A.diag),
        "ell": (jnp.asarray(A.ell_cols), jnp.asarray(A.ell_vals)),
    }
    if A.int_mask is not None and overlap_ok:
        out["split"] = (
            jnp.asarray(A.int_mask),
            jnp.asarray(A.own_mask),
            None if A.bnd_rows is None else jnp.asarray(A.bnd_rows),
        )
    if A.ell_wcols is not None:
        from amgx_tpu.ops.pallas_well import pallas_well_supported

        # ship the tiled copies only where the kernel actually runs —
        # they duplicate the ELL footprint in HBM
        if pallas_well_supported():
            out["wtile"] = (
                jnp.asarray(A.ell_wcols),
                jnp.asarray(A.ell_wvals),
                jnp.asarray(A.ell_wbase),
            )
    if A.uses_ppermute:
        out["ex"] = (
            tuple(jnp.asarray(s) for s in A.send_idx_d),
            jnp.asarray(A.halo_dir),
            jnp.asarray(A.halo_pos),
        )
    else:
        out["ex"] = (
            jnp.asarray(A.send_idx),
            jnp.asarray(A.halo_src_part),
            jnp.asarray(A.halo_src_pos),
        )
    return out


def exchange_halo(A: DistributedMatrix, shard, x_loc, axis):
    """halo values for x (reference exchange_halo_v2).  Runs inside
    shard_map; `shard` is the _shard_params dict with the leading
    shard axis dropped.  Block vectors ([rows, b]) exchange whole
    b-vectors per halo slot (reference block halo buffers)."""
    record_halo_exchange()
    blk = x_loc.ndim == 2
    if A.uses_ppermute:
        send_idx_d, halo_dir, halo_pos = shard["ex"]
        halo = jnp.zeros(
            (halo_pos.shape[0],) + x_loc.shape[1:], x_loc.dtype
        )
        for d, perm in enumerate(A.perms):
            buf = x_loc[send_idx_d[d]]
            recv = jax.lax.ppermute(buf, axis, perm=list(perm))
            sel = halo_dir == d
            if blk:
                sel = sel[:, None]
            halo = jnp.where(sel, recv[halo_pos], halo)
        return halo
    send_idx, hsp, hpos = shard["ex"]
    send = x_loc[send_idx]  # B2L gather
    pool = jax.lax.all_gather(send, axis)  # [N, max_send(, b)]
    return pool[hsp, hpos]


def exchange_halo_reverse(A: DistributedMatrix, shard, y_own, y_halo,
                          axis):
    """Accumulating reverse exchange (reference add_from_halo,
    distributed_comms.h:138): each shard's HALO-slot partials are sent
    back to the owning shard and ADDED into its owned slots.  This is
    the transpose of exchange_halo — classical restriction R = P^T
    scatters partial coarse sums into halo slots, which must fold back
    into their owners' rows.

    ``y_own``: [rows] owned partials; ``y_halo``: [max_halo] halo-slot
    partials.  Returns y_own with remote contributions added.
    """
    record_halo_exchange()
    if A.uses_ppermute:
        send_idx_d, halo_dir, halo_pos = shard["ex"]
        for d, perm in enumerate(A.perms):
            ms = send_idx_d[d].shape[0]
            # pack: this shard's halo partials for direction d land at
            # their position in the (src, dst) id list; others drop
            # into a spill slot
            buf = jnp.zeros((ms + 1,), y_own.dtype)
            idx = jnp.where(halo_dir == d, halo_pos, ms)
            buf = buf.at[idx].add(y_halo)
            inv = [(dst, src) for (src, dst) in perm]
            recv = jax.lax.ppermute(buf[:ms], axis, perm=inv)
            # unpack: the owner adds received partials at the same
            # B2L gather indices the forward exchange packs from.
            # INVARIANT: padding positions of send_idx_d are 0 and the
            # matching recv slots are provably 0 (y_halo padding only
            # ever receives zero-valued scatter contributions, and buf
            # slots beyond a pair's id count are never written), so
            # row 0 accumulates only zeros from padding.
            y_own = y_own.at[send_idx_d[d]].add(recv)
        return y_own
    send_idx, hsp, hpos = shard["ex"]
    pool = jax.lax.all_gather(y_halo, axis)  # [N, max_halo]
    hsp_all = jax.lax.all_gather(hsp, axis)  # [N, max_halo]
    hpos_all = jax.lax.all_gather(hpos, axis)
    me = jax.lax.axis_index(axis)
    ms = send_idx.shape[0]
    contrib = jnp.zeros((ms + 1,), y_own.dtype)
    idx = jnp.where(hsp_all == me, hpos_all, ms)
    contrib = contrib.at[idx.reshape(-1)].add(pool.reshape(-1))
    return y_own.at[send_idx].add(contrib[:ms])


def make_local_spmv(A: DistributedMatrix, axis):
    """Shard-local y = (A x)_loc with halo exchange over `axis`.

    Latency hiding (reference multiply.cu:95-110
    exchange_halo_split_gather -> interior -> boundary): the interior
    partial product reads only x_loc, so it carries no data dependence
    on the permute results — XLA's latency-hiding scheduler overlaps
    it with the in-flight exchange."""

    use_wtile = False
    if A.ell_wcols is not None:
        from amgx_tpu.ops.pallas_well import pallas_well_supported

        use_wtile = pallas_well_supported()  # matches _shard_params

    def spmv(shard, x_loc):
        ell_cols, ell_vals = shard["ell"]
        if A.block_size > 1:
            # block SpMV (reference bsrmv, multiply.cu:49-71): one
            # einsum contracts the b×b blocks — MXU-batched on TPU.
            # Same interior/boundary overlap structure as scalar.
            halo = exchange_halo(A, shard, x_loc, axis)
            if "split" in shard:
                int_mask, own_mask, bnd_rows = shard["split"]
                nloc = x_loc.shape[0]
                lc = jnp.minimum(ell_cols, nloc - 1)
                yi = jnp.where(
                    int_mask[:, None],
                    jnp.einsum("rwij,rwj->ri", ell_vals, x_loc[lc]),
                    0.0,
                )
                xf = jnp.concatenate([x_loc, halo])
                if bnd_rows is not None:
                    yb = jnp.einsum(
                        "rwij,rwj->ri",
                        ell_vals[bnd_rows],
                        xf[ell_cols[bnd_rows]],
                    )
                    y = jnp.concatenate(
                        [yi, jnp.zeros((1, yi.shape[1]), yi.dtype)]
                    )
                    y = y.at[bnd_rows].add(yb)
                    return y[:nloc]
                yb = jnp.where(
                    (own_mask & ~int_mask)[:, None],
                    jnp.einsum("rwij,rwj->ri", ell_vals, xf[ell_cols]),
                    0.0,
                )
                return yi + yb
            xf = jnp.concatenate([x_loc, halo])
            return jnp.einsum("rwij,rwj->ri", ell_vals, xf[ell_cols])
        if "split" in shard:
            int_mask, own_mask, bnd_rows = shard["split"]
            halo = exchange_halo(A, shard, x_loc, axis)
            if use_wtile:
                # interior pass on the Pallas windowed kernel: interior
                # columns are all local, so the gather reads only x_loc
                # — overlaps with the in-flight exchange.  Boundary/
                # padding rows carry zero values in the tiled arrays,
                # so the output needs no mask.
                from amgx_tpu.ops.pallas_well import _pallas_well_spmv

                wc, wv, wb = shard["wtile"]
                yi = _pallas_well_spmv(
                    wc, wv, wb, x_loc, x_loc.shape[0], A.ell_wwidth
                )
            else:
                # XLA interior pass: columns clamped into the local
                # range (the clamp only touches boundary rows, whose
                # contribution comes from the compact pass below) — no
                # dependence on the permute results, so it lands in a
                # fusion XLA can schedule DURING the exchange
                # (ci/check_overlap_hlo.py asserts the dataflow)
                nloc = x_loc.shape[0]
                lc = jnp.minimum(ell_cols, nloc - 1)
                yi = jnp.where(
                    int_mask, jnp.sum(ell_vals * x_loc[lc], axis=-1), 0
                )
            if bnd_rows is not None:
                # compact boundary pass (reference multiply.cu:95-110
                # boundary-rows kernel): gather the O(surface) boundary
                # rows, compute against [x_loc, halo], scatter-add into
                # a spill-padded copy of yi.  Structurally unfusable
                # with the interior reduce -> overlap-safe, and the
                # second pass costs O(nb*w) instead of O(rows*w).
                xf = jnp.concatenate([x_loc, halo])
                yb = jnp.sum(
                    ell_vals[bnd_rows] * xf[ell_cols[bnd_rows]],
                    axis=-1,
                )
                y = jnp.concatenate(
                    [yi, jnp.zeros((1,), yi.dtype)]
                )
                y = y.at[bnd_rows].add(yb)
                return y[: x_loc.shape[0]]
            xf = jnp.concatenate([x_loc, halo])
            yb = jnp.where(
                own_mask & ~int_mask,
                jnp.sum(ell_vals * xf[ell_cols], axis=-1),
                0,
            )
            return yi + yb
        halo = exchange_halo(A, shard, x_loc, axis)
        xf = jnp.concatenate([x_loc, halo])
        return jnp.sum(ell_vals * xf[ell_cols], axis=1)

    return spmv


def _pdot(a, b, axis):
    # vdot flattens, so block vectors [rows, b] reduce correctly.
    # One psum = one global reduction: counted into both the PR 8
    # reduction slot and the serve psum-site slot at trace time.
    blas_mod.record_reduction()
    _record_psum_site()
    return jax.lax.psum(jnp.vdot(a, b), axis)


def _pgram(L, Rt, axis):
    """Distributed fused Gram block: the shard-local
    :func:`amgx_tpu.ops.blas.gram_block` matmul followed by ONE psum —
    ALL inner products of an s-step outer iteration in a single
    collective (gram_block already records the reduction site; only
    the psum site is added here)."""
    from amgx_tpu.ops.blas import gram_block

    _record_psum_site()
    return jax.lax.psum(gram_block(L, Rt), axis)


def _safe_block_inv(d):
    """Batched b×b diagonal-block inverse with the scalar path's
    zero-diagonal protection: singular blocks (inv -> inf/nan) fall
    back to identity instead of poisoning the solve."""
    inv = jnp.linalg.inv(d)
    ok = jnp.isfinite(inv).all(axis=(-2, -1), keepdims=True)
    eye = jnp.eye(d.shape[-1], dtype=d.dtype)
    return jnp.where(ok, inv, eye)


def _run_dist_solve(A, b_global, mesh, max_iters, tol, preconditioned):
    axis = mesh.axis_names[0]
    shard = _shard_params(A)
    bp = jnp.asarray(A.pad_vector(b_global))
    local_spmv = make_local_spmv(A, axis)

    def local_solve(sh, b_loc):
        diag = sh["diag"]
        if A.block_size > 1:
            # block-Jacobi: batched b×b diagonal-block inverses
            # (reference block_jacobi_solver.cu setup); padding rows
            # carry identity blocks, and singular blocks fall back to
            # identity (the scalar d==0 guard's block analogue)
            dinv = _safe_block_inv(diag)
            prec = lambda rr: jnp.einsum("rij,rj->ri", dinv, rr)
        else:
            dinv = jnp.where(diag != 0, 1.0 / diag, 1.0)
            prec = lambda rr: dinv * rr
        x = jnp.zeros_like(b_loc)
        r = b_loc  # x0 = 0
        z = prec(r) if preconditioned else r
        p = z
        rho = _pdot(r, z, axis)
        nrm0 = jnp.sqrt(_pdot(b_loc, b_loc, axis))

        def cond(c):
            it, x, r, p, rho, nrm = c
            return (it < max_iters) & (nrm >= tol * nrm0) & (nrm0 > 0)

        def body(c):
            it, x, r, p, rho, nrm = c
            q = local_spmv(sh, p)
            alpha = rho / _pdot(p, q, axis)
            x = x + alpha * p
            r = r - alpha * q
            z = prec(r) if preconditioned else r
            rho_new = _pdot(r, z, axis)
            p = z + (rho_new / rho) * p
            nrm = jnp.sqrt(_pdot(r, r, axis))
            return (it + 1, x, r, p, rho_new, nrm)

        it, x, r, p, rho, nrm = jax.lax.while_loop(
            cond, body, (jnp.int32(0), x, r, p, rho, nrm0)
        )
        return x, it, nrm

    in_shard = jax.tree.map(lambda _: P(axis), shard)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(in_shard, P(axis)),
        out_specs=(P(axis), P(), P()),
    )
    def solve_sm(shard_stk, b_stk):
        sh = jax.tree.map(lambda s: s[0], shard_stk)
        x, it, nrm = local_solve(sh, b_stk[0])
        return x[None], it, nrm

    x, it, nrm = jax.jit(solve_sm)(shard, bp)
    return A.unpad_vector(jax.device_get(x)), int(it), float(nrm)


def dist_pcg_jacobi(A: DistributedMatrix, b, mesh: Mesh, max_iters=200,
                    tol=1e-8):
    """Distributed Jacobi-PCG: returns (x, iters, final_norm)."""
    return _run_dist_solve(A, b, mesh, max_iters, tol, True)


def dist_cg(A: DistributedMatrix, b, mesh: Mesh, max_iters=200, tol=1e-8):
    return _run_dist_solve(A, b, mesh, max_iters, tol, False)


def dist_spmv_replicated_check(A: DistributedMatrix, x, mesh: Mesh):
    """y = A x through the distributed path (validation against the
    single-device SpMV — the distributed_io test pattern, SURVEY §4)."""
    axis = mesh.axis_names[0]
    shard = _shard_params(A)
    xp = jnp.asarray(A.pad_vector(x))
    local_spmv = make_local_spmv(A, axis)
    in_shard = jax.tree.map(lambda _: P(axis), shard)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(in_shard, P(axis)),
        out_specs=P(axis),
    )
    def spmv_sm(shard_stk, x_stk):
        sh = jax.tree.map(lambda s: s[0], shard_stk)
        return local_spmv(sh, x_stk[0])[None]

    y = jax.jit(spmv_sm)(shard, xp)
    return A.unpad_vector(jax.device_get(y))
