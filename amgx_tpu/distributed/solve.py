"""Distributed SpMV and Krylov solves under shard_map.

The solve loop runs entirely inside ``shard_map`` over a 1-axis device
mesh: halo exchange is B2L-gather -> ``all_gather`` -> halo-gather
(reference exchange_halo, comms_mpi_hostbuffer_stream.cu), reductions are
``psum`` (reference global_reduce).  The while_loop condition uses the
psum'd scalar, identical on every shard — standard SPMD.

This is the distributed minimum slice (Krylov + Jacobi); the distributed
AMG hierarchy (coarse-level RAP exchange, consolidation onto sub-meshes)
builds on the same primitives in a later milestone.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from amgx_tpu.distributed.partition import DistributedMatrix


def _shard_params(A: DistributedMatrix):
    """The traced per-shard arrays, stacked on the shard axis."""
    return (
        jnp.asarray(A.ell_cols),
        jnp.asarray(A.ell_vals),
        jnp.asarray(A.diag),
        jnp.asarray(A.send_idx),
        jnp.asarray(A.halo_src_part),
        jnp.asarray(A.halo_src_pos),
    )


def _local_spmv(shard, x_loc, axis):
    """y_loc = (A x)_loc with halo exchange over `axis`."""
    ell_cols, ell_vals, diag, send_idx, hsp, hpos = shard
    send = x_loc[send_idx]  # B2L gather
    pool = jax.lax.all_gather(send, axis)  # [N, max_send] over ICI
    halo = pool[hsp, hpos]  # [max_halo]
    xf = jnp.concatenate([x_loc, halo])
    return jnp.sum(ell_vals * xf[ell_cols], axis=1)


def _pdot(a, b, axis):
    return jax.lax.psum(jnp.dot(a, b), axis)


def _make_dist_solver(preconditioned: bool):
    """Builds the shard-local PCG body (Jacobi-preconditioned or plain)."""

    def local_solve(shard, b_loc, max_iters, tol, axis):
        ell_cols, ell_vals, diag, *_ = shard
        dinv = jnp.where(diag != 0, 1.0 / diag, 1.0)
        x = jnp.zeros_like(b_loc)
        r = b_loc  # x0 = 0
        z = dinv * r if preconditioned else r
        p = z
        rho = _pdot(r, z, axis)
        nrm0 = jnp.sqrt(_pdot(b_loc, b_loc, axis))

        def cond(c):
            it, x, r, p, rho, nrm = c
            return (it < max_iters) & (nrm >= tol * nrm0) & (nrm0 > 0)

        def body(c):
            it, x, r, p, rho, nrm = c
            q = _local_spmv(shard, p, axis)
            alpha = rho / _pdot(p, q, axis)
            x = x + alpha * p
            r = r - alpha * q
            z = dinv * r if preconditioned else r
            rho_new = _pdot(r, z, axis)
            p = z + (rho_new / rho) * p
            nrm = jnp.sqrt(_pdot(r, r, axis))
            return (it + 1, x, r, p, rho_new, nrm)

        it, x, r, p, rho, nrm = jax.lax.while_loop(
            cond, body, (jnp.int32(0), x, r, p, rho, nrm0)
        )
        return x, it, nrm

    return local_solve


def _run_dist_solve(A, b_global, mesh, max_iters, tol, preconditioned):
    axis = mesh.axis_names[0]
    shard = _shard_params(A)
    bp = jnp.asarray(A.pad_vector(b_global))
    local = _make_dist_solver(preconditioned)

    in_shard = tuple(P(axis) for _ in shard)

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(in_shard, P(axis)),
        out_specs=(P(axis), P(), P()),
    )
    def solve_sm(shard_stk, b_stk):
        shard_loc = tuple(s[0] for s in shard_stk)  # drop unit shard axis
        x, it, nrm = local(shard_loc, b_stk[0], max_iters, tol, axis)
        return x[None], it, nrm

    x, it, nrm = jax.jit(solve_sm)(shard, bp)
    return A.unpad_vector(jax.device_get(x)), int(it), float(nrm)


def dist_pcg_jacobi(A: DistributedMatrix, b, mesh: Mesh, max_iters=200,
                    tol=1e-8):
    """Distributed Jacobi-PCG: returns (x, iters, final_norm)."""
    return _run_dist_solve(A, b, mesh, max_iters, tol, True)


def dist_cg(A: DistributedMatrix, b, mesh: Mesh, max_iters=200, tol=1e-8):
    return _run_dist_solve(A, b, mesh, max_iters, tol, False)


def dist_spmv_replicated_check(A: DistributedMatrix, x, mesh: Mesh):
    """y = A x through the distributed path (for validation against the
    single-device SpMV — the distributed_io test pattern, SURVEY §4)."""
    axis = mesh.axis_names[0]
    shard = _shard_params(A)
    xp = jnp.asarray(A.pad_vector(x))
    in_shard = tuple(P(axis) for _ in shard)

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(in_shard, P(axis)),
        out_specs=P(axis),
    )
    def spmv_sm(shard_stk, x_stk):
        shard_loc = tuple(s[0] for s in shard_stk)
        return _local_spmv(shard_loc, x_stk[0], axis)[None]

    y = jax.jit(spmv_sm)(shard, xp)
    return A.unpad_vector(jax.device_get(y))
