"""Batched solve service (serve-scale layer).

Many independent sparse solves -> a few vmapped device calls:

  * :func:`amgx_tpu.core.matrix.sparsity_fingerprint` groups requests
    that share a sparsity pattern;
  * :mod:`amgx_tpu.serve.bucketing` pads groups to a small set of
    (n, nnz, batch) buckets so XLA compile-cache hits dominate;
  * :mod:`amgx_tpu.serve.batched` runs the vmapped masked-convergence
    solve (early-converged instances freeze);
  * :mod:`amgx_tpu.serve.cache` reuses one hierarchy setup per
    (fingerprint, config) across all later coefficient sets;
  * :mod:`amgx_tpu.serve.metrics` exports the serving counters.

Entry point::

    from amgx_tpu.serve import BatchedSolveService
    svc = BatchedSolveService()           # Jacobi-PCG default config
    results = svc.solve_many([(A0, b0), (A1, b1), ...])
"""

from amgx_tpu.serve.bucketing import pad_pattern, bucket_batch
from amgx_tpu.serve.batched import make_batched_solve
from amgx_tpu.serve.cache import HierarchyCache, config_hash
from amgx_tpu.serve.metrics import ServeMetrics
from amgx_tpu.serve.service import (
    DEFAULT_CONFIG,
    BatchedSolveService,
    SolveTicket,
)

# serving-stack alias: the docs/issues call the frontend "the solve
# service"; the class name keeps its descriptive form
SolveService = BatchedSolveService

__all__ = [
    "BatchedSolveService",
    "SolveService",
    "DEFAULT_CONFIG",
    "SolveTicket",
    "HierarchyCache",
    "ServeMetrics",
    "make_batched_solve",
    "pad_pattern",
    "bucket_batch",
    "config_hash",
]
