"""Batched solve service (serve-scale layer).

Many independent sparse solves -> a few vmapped device calls:

  * :func:`amgx_tpu.core.matrix.sparsity_fingerprint` groups requests
    that share a sparsity pattern;
  * :mod:`amgx_tpu.serve.bucketing` pads groups to a small set of
    (n, nnz, batch) buckets so XLA compile-cache hits dominate;
  * :mod:`amgx_tpu.serve.batched` runs the vmapped masked-convergence
    solve (early-converged instances freeze);
  * :mod:`amgx_tpu.serve.cache` reuses one hierarchy setup per
    (fingerprint, config) across all later coefficient sets;
  * :mod:`amgx_tpu.serve.metrics` exports the serving counters.

The fleet front-end (:mod:`amgx_tpu.serve.gateway`) is the
multi-tenant door in front of the service: per-tenant token-bucket
quotas, a global concurrency budget with priority lanes
(interactive / batch), deadline-aware load shedding, and a graceful
``drain()`` that exports hot hierarchies to the artifact store —
every overload answer is a typed ``AdmissionRejected``/``Overloaded``
carrying ``retry_after_s``.

Entry points::

    from amgx_tpu.serve import BatchedSolveService
    svc = BatchedSolveService()           # Jacobi-PCG default config
    results = svc.solve_many([(A0, b0), (A1, b1), ...])

    from amgx_tpu.serve import SolveGateway
    gw = SolveGateway(max_inflight=128).start()
    t = gw.submit(A, b, tenant="web", lane="interactive",
                  deadline_s=0.5)
    x = t.result().x
"""

from amgx_tpu.serve.bucketing import pad_pattern, bucket_batch
from amgx_tpu.serve.batched import make_batched_solve
from amgx_tpu.serve.cache import HierarchyCache, config_hash
from amgx_tpu.serve.metrics import ServeMetrics
from amgx_tpu.serve.service import (
    CHEAP_PRECONDITIONER_CONFIG,
    COMM_AVOIDING_CONFIG,
    DEFAULT_CONFIG,
    BatchedSolveService,
    SolveTicket,
)
from amgx_tpu.serve.admission import (
    AdmissionController,
    TenantQuota,
    TokenBucket,
)
from amgx_tpu.serve.gateway import GatewayTicket, SolveGateway
from amgx_tpu.serve.placement import (
    AffinityPlacement,
    AffinityRouter,
    DeviceHealthBoard,
    MeshPlacement,
    PlacementPolicy,
    SingleDevicePolicy,
    breaker_probe_every,
    placement_from_env,
)
from amgx_tpu.serve.retry import DEFAULT_RETRYABLE, RetryPolicy

# serving-stack alias: the docs/issues call the frontend "the solve
# service"; the class name keeps its descriptive form
SolveService = BatchedSolveService

__all__ = [
    "BatchedSolveService",
    "SolveService",
    "DEFAULT_CONFIG",
    "COMM_AVOIDING_CONFIG",
    "CHEAP_PRECONDITIONER_CONFIG",
    "SolveTicket",
    "SolveGateway",
    "GatewayTicket",
    "AdmissionController",
    "TenantQuota",
    "TokenBucket",
    "PlacementPolicy",
    "SingleDevicePolicy",
    "MeshPlacement",
    "AffinityPlacement",
    "AffinityRouter",
    "DeviceHealthBoard",
    "breaker_probe_every",
    "RetryPolicy",
    "DEFAULT_RETRYABLE",
    "placement_from_env",
    "HierarchyCache",
    "ServeMetrics",
    "make_batched_solve",
    "pad_pattern",
    "bucket_batch",
    "config_hash",
]
