"""Batched solve execution: vmap a solver's iteration over a leading
batch axis with masked per-instance convergence.

Why not ``vmap(solver.make_solve())``: vmapping a ``lax.while_loop``
runs the body on EVERY instance until the LAST one converges, so
early-converged instances keep iterating — their x drifts past the
converged answer and their iteration counts are lost.  This module
instead builds ONE while_loop at the batch level whose body applies the
vmapped per-instance iteration and then commits updates only where the
instance is still active (residual-masked updates): converged instances
freeze bit-exactly at their convergence iterate, and per-instance
status/iteration counts match the sequential solves.

The compiled program takes the solver's *batch template* (pattern data:
index arrays, transfer operators, Galerkin plans — see
``Solver.make_batch_params``) as an ARGUMENT, so every pattern in the
same (n, nnz, batch) shape bucket reuses one XLA executable.

Shared-structure batching: naively vmapping over fully-batched params
replicates pattern leaves (index arrays, transfer operators) B times
AND — worse — turns every SpMV gather into a batched-*indices* gather,
which XLA lowers to a slow general gather (measured ~10x on CPU).  The
loop instead splits params leaves into value-dependent (batched,
``in_axes=0``) and structural (shared, ``in_axes=None``) by a
dependence walk over the params-rebuild jaxpr — syntactic dependence,
so a leaf is only ever shared when it provably cannot vary with the
coefficients.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from amgx_tpu.core.profiling import named_scope
from amgx_tpu.ops import blas as blas_mod
from amgx_tpu.ops.spmv import spmv
from amgx_tpu.solvers.base import (
    FAILED,
    NOT_CONVERGED,
    SUCCESS,
    DIVERGED,
    SolveResult,
)


# ----------------------------------------------------------------------
# cross-chip collective accounting (mesh placement, serve/placement)
#
# When the batch axis is sharded over a jax.sharding.Mesh the group
# loop's convergence check becomes the ONE cross-chip sync point per
# iteration: every shard must agree whether any instance anywhere is
# still active, or their while_loops would diverge around the psums a
# sharded solver would run inside the body.  The counter reuses
# ops/blas.make_site_counter (the PR 8 reduction-site machinery) on
# its own slot: it counts psum SITES at trace time, so the mesh bench
# can assert the compiled group loop carries exactly one collective
# per iteration (ci/mesh_bench.py).

_record_psum, psum_site_counter = blas_mod.make_site_counter(
    "psum_sites"
)


def _instance_protocol(solver):
    """Resolve the per-instance iteration protocol of a solver into
    (init_one, iter_one, norm_one) pure functions:

      init_one(params, b, x0)        -> extra
      iter_one(params, b, x, extra)  -> (x, extra)
      norm_one(params, b, x, extra)  -> (ncomp,) residual norm

    Returns None when the solver exposes no step/iterate protocol
    (GMRES/IDR override make_solve wholesale).
    """
    norm_of = solver.make_norm()

    if hasattr(solver, "_make_init"):
        try:
            init_fn, iter_fn = solver._make_init(), solver._make_iter()
        except NotImplementedError:
            init_fn = None
        if init_fn is not None:
            return (
                init_fn,
                iter_fn,
                lambda params, b, x, extra: norm_of(extra[0]),
            )

    try:
        rstep = solver.make_residual_step()
    except NotImplementedError:
        rstep = None
    if rstep is not None:
        op = solver.operator_of

        def init_r(params, b, x0):
            return (b - spmv(op(params), x0),)

        def iter_r(params, b, x, extra):
            x = rstep(params, b, x, extra[0])
            return x, (b - spmv(op(params), x),)

        return init_r, iter_r, lambda params, b, x, extra: norm_of(
            extra[0]
        )

    try:
        step = solver.make_step()
    except NotImplementedError:
        return None
    op = solver.operator_of

    def init_s(params, b, x0):
        return ()

    def iter_s(params, b, x, extra):
        return step(params, b, x), ()

    def norm_s(params, b, x, extra):
        return norm_of(b - spmv(op(params), x))

    return init_s, iter_s, norm_s


def _value_dependent_flags(params_of, template, values_spec):
    """Per-leaf booleans for ``params_of(template, values)``: True when
    the leaf can depend on ``values`` (syntactic dependence over the
    rebuild jaxpr).  Conservative fallback: everything depends."""
    fn = lambda v: params_of(template, v)  # noqa: E731
    out_shape = jax.eval_shape(fn, values_spec)
    leaves, treedef = jax.tree_util.tree_flatten(out_shape)
    try:
        from jax import core

        closed = jax.make_jaxpr(fn)(values_spec)
        jaxpr = closed.jaxpr
        dep = set(jaxpr.invars)

        def is_dep(atom):
            return not isinstance(atom, core.Literal) and atom in dep

        for eqn in jaxpr.eqns:
            hit = any(is_dep(v) for v in eqn.invars)
            if not hit:
                # conservative recursion stand-in: sub-jaxprs (scan,
                # cond, pjit) are treated atomically above
                continue
            dep.update(eqn.outvars)
        flags = [is_dep(v) for v in jaxpr.outvars]
        if len(flags) != len(leaves):
            raise ValueError("outvar/leaf count mismatch")
        return flags, treedef
    except Exception:  # jax internals moved: batch everything
        return [True] * len(leaves), treedef


def make_batched_solve(solver, axis_name=None):
    """Pure ``fn(template, values_B, b_B, x0_B) -> SolveResult`` with
    batched leaves (x (B, n), iters/status (B,), norms (B, ncomp),
    history (B, max_iters+1, ncomp)), or None when the solver supports
    neither a traced values-only params rebuild nor an iteration
    protocol.  Jit the result once per shape bucket.

    ``axis_name`` (mesh placement): the function will run under a
    ``shard_map`` whose batch axis carries this name — the group
    loop's convergence check then psums the shard-local active mask
    over the axis so every shard runs the SAME trip count as the
    unsharded loop (per-instance results stay bitwise: converged
    instances freeze under the commit mask either way).  ``None``
    (default) emits the plain single-device loop, unchanged.
    """
    bp = solver.make_batch_params()
    if bp is None:
        return None
    template0, params_of = bp
    proto = _instance_protocol(solver)
    if proto is None:
        return None
    init_one, iter_one, norm_one = proto

    vdt = solver.A.values.dtype
    v_spec = jax.ShapeDtypeStruct(solver.A.values.shape, vdt)
    dep_flags, params_treedef = _value_dependent_flags(
        params_of, template0, v_spec
    )

    def _merge(shared, batched):
        """Rebuild the params pytree from split leaf lists."""
        flat = []
        si = bi = 0
        for d in dep_flags:
            if d:
                flat.append(batched[bi])
                bi += 1
            else:
                flat.append(shared[si])
                si += 1
        return jax.tree_util.tree_unflatten(params_treedef, flat)

    def _wrap(fn):
        """Per-instance fn(params, ...) -> vmapped over split params
        with structural leaves shared (in_axes=None)."""

        def inner(shared, batched, *args):
            return fn(_merge(shared, batched), *args)

        def vmapped(shared, batched, *args):
            return jax.vmap(
                inner,
                in_axes=(None, 0) + (0,) * len(args),
            )(shared, batched, *args)

        return vmapped

    init_v = _wrap(init_one)
    iter_v = _wrap(iter_one)
    norm_v = _wrap(norm_one)

    conv = solver._conv_check
    max_iters = solver.max_iters
    rel_div = solver.rel_div_tolerance
    ncomp = solver.norm_components
    monitored = solver.monitor_residual

    def _split_params(template, values_B):
        """(shared_leaves, batched_leaves): structural leaves come from
        ONE unbatched rebuild, value-dependent leaves from the vmapped
        rebuild (XLA dead-code-eliminates each side's unused half)."""
        with named_scope("serve_batch_params"):
            flat0 = jax.tree_util.tree_leaves(
                params_of(template, values_B[0])
            )
            flatB = jax.tree_util.tree_leaves(
                jax.vmap(lambda v: params_of(template, v))(values_B)
            )
        shared = [l for l, d in zip(flat0, dep_flags) if not d]
        batched = [l for l, d in zip(flatB, dep_flags) if d]
        return shared, batched

    def solve_plain(template, values_B, b_B, x0_B):
        """Unmonitored: fixed max_iters sweeps for every instance."""
        shared, batched = _split_params(template, values_B)
        extra_B = init_v(shared, batched, b_B, x0_B)

        def fori_body(i, c):
            x, extra = c
            return iter_v(shared, batched, b_B, x, extra)

        x, _ = jax.lax.fori_loop(
            0, max_iters, fori_body, (x0_B, extra_B)
        )
        B = b_B.shape[0]
        rdt = jnp.real(b_B).dtype
        zero = jnp.zeros((B, ncomp), rdt)
        return SolveResult(
            x=x,
            iters=jnp.full((B,), max_iters, jnp.int32),
            status=jnp.full((B,), SUCCESS, jnp.int32),
            final_norm=zero,
            initial_norm=zero,
            history=jnp.full((B, max_iters + 1, ncomp), jnp.nan, rdt),
        )

    if not monitored:
        return solve_plain

    def solve(template, values_B, b_B, x0_B):
        shared, batched = _split_params(template, values_B)
        B = b_B.shape[0]
        rdt = jnp.real(b_B).dtype
        extra_B = init_v(shared, batched, b_B, x0_B)
        nrm0 = norm_v(shared, batched, b_B, x0_B, extra_B)
        hist = jnp.full((B, max_iters + 1, ncomp), jnp.nan, rdt)
        hist = hist.at[:, 0].set(nrm0)
        done0 = jax.vmap(conv)(nrm0, nrm0, nrm0)
        status0 = jnp.where(
            done0, jnp.int32(SUCCESS), jnp.int32(NOT_CONVERGED)
        )
        iters0 = jnp.zeros((B,), jnp.int32)

        def cond(c):
            it, status = c[0], c[7]
            active = jnp.any(status == NOT_CONVERGED)
            if axis_name is not None:
                # shared convergence mask: THE cross-chip collective of
                # a batch-sharded group (everything else in the body is
                # instance-local, hence shard-local) — one psum per
                # group iteration, counted at trace time
                _record_psum()
                active = (
                    jax.lax.psum(active.astype(jnp.int32), axis_name)
                    > 0
                )
            return active & (it < max_iters)

        def body(c):
            it, x, extra, nrm, ini, mx, hist, status, iters = c
            active = status == NOT_CONVERGED  # (B,)
            x_n, extra_n = iter_v(shared, batched, b_B, x, extra)
            nrm_n = norm_v(shared, batched, b_B, x_n, extra_n)
            it = it + 1

            def commit(new, old):
                m = active.reshape((-1,) + (1,) * (new.ndim - 1))
                return jnp.where(m, new, old)

            x = commit(x_n, x)
            extra = jax.tree_util.tree_map(commit, extra_n, extra)
            mx_n = jnp.maximum(mx, nrm_n)
            hist = hist.at[:, it].set(
                jnp.where(active[:, None], nrm_n, jnp.nan)
            )
            done_ok = jax.vmap(conv)(nrm_n, ini, mx_n)
            bad = ~jnp.all(jnp.isfinite(nrm_n), axis=-1)
            st_n = jnp.where(
                done_ok, jnp.int32(SUCCESS), jnp.int32(NOT_CONVERGED)
            )
            if rel_div > 0:
                div = jnp.any(nrm_n > rel_div * ini, axis=-1)
                st_n = jnp.where(div, jnp.int32(DIVERGED), st_n)
            st_n = jnp.where(bad, jnp.int32(FAILED), st_n)
            nrm = commit(nrm_n, nrm)
            mx = commit(mx_n, mx)
            iters = jnp.where(active, it, iters)
            status = jnp.where(active, st_n, status)
            return (it, x, extra, nrm, ini, mx, hist, status, iters)

        c0 = (
            jnp.int32(0),
            x0_B,
            extra_B,
            nrm0,
            nrm0,
            nrm0,
            hist,
            status0,
            iters0,
        )
        _, x, _, nrm, ini, mx, hist, status, iters = jax.lax.while_loop(
            cond, body, c0
        )
        return SolveResult(
            x=x,
            iters=iters,
            status=status,
            final_norm=nrm,
            initial_norm=ini,
            history=hist,
        )

    return solve
