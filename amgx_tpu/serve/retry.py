"""Client-side retry policy: jittered exponential backoff that honors
typed shed hints.

Every rejection the fleet front-end raises is TYPED
(:class:`~amgx_tpu.core.errors.AdmissionRejected` /
:class:`~amgx_tpu.core.errors.Overloaded`) and carries
``retry_after_s`` — the machine-actionable backoff hint sized to the
actual recovery event (a token-bucket refill, the breaker probe
cadence, a drain handoff).  A well-behaved client should sleep THAT
long, not a guessed constant; this module is the reference
implementation the chaos soak harness (ci/chaos_soak.py) and external
clients use:

    policy = RetryPolicy(max_attempts=5, base_s=0.05)
    res = policy.call(lambda: gw.submit(A, b, tenant="web").result())

Semantics:

* retryable errors are the RECOVERABLE taxonomy classes — admission
  sheds, deadline misses, device loss (the serve layer already
  requeued once; a client retry lands after failover settled) — plus
  any extra classes the caller lists;
* the backoff for attempt k is ``base_s * factor**k`` with a
  deterministic-seedable jitter fraction, CAPPED by ``max_s`` — but a
  typed ``retry_after_s`` hint REPLACES the exponential term (the
  server knows when capacity returns; the jitter still applies so a
  thundering herd of identical clients decorrelates);
* non-retryable errors (setup errors, validation rejects — retrying
  identical bad input cannot help) propagate immediately.

Deterministic under a seed: the jitter stream is a private
``numpy.random.Generator``, so tests and the chaos harness replay
byte-identical schedules.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import numpy as np

from amgx_tpu.core.errors import (
    AdmissionRejected,
    DeadlineExceededError,
    DeviceLostError,
)

# recoverable-by-waiting taxonomy classes: retrying later can succeed
DEFAULT_RETRYABLE = (
    AdmissionRejected,  # includes Overloaded
    DeadlineExceededError,
    DeviceLostError,
)


@dataclasses.dataclass
class RetryPolicy:
    """Jittered exponential backoff honoring typed shed hints.

    Parameters: ``max_attempts`` total tries (the first call counts);
    ``base_s``/``factor`` the exponential schedule; ``jitter_frac``
    the uniform jitter applied multiplicatively in
    ``[1 - j, 1 + j]``; ``max_s`` the per-sleep cap;
    ``retryable`` the exception classes worth retrying; ``seed``
    makes the jitter stream reproducible; ``sleep`` is injectable for
    tests (defaults to ``time.sleep``)."""

    max_attempts: int = 4
    base_s: float = 0.05
    factor: float = 2.0
    jitter_frac: float = 0.25
    max_s: float = 5.0
    retryable: tuple = DEFAULT_RETRYABLE
    seed: Optional[int] = None
    sleep: Callable[[float], None] = time.sleep

    def __post_init__(self):
        self._rng = np.random.default_rng(self.seed)
        self.retries = 0
        self.giveups = 0

    def backoff_s(self, attempt: int,
                  retry_after_s: Optional[float] = None) -> float:
        """The sleep before retry ``attempt`` (0-based): the server's
        ``retry_after_s`` hint when present, else
        ``base_s * factor**attempt`` — jittered, capped at
        ``max_s``, never negative."""
        base = (
            float(retry_after_s)
            if retry_after_s is not None
            else self.base_s * self.factor ** attempt
        )
        if self.jitter_frac > 0:
            base *= 1.0 + self.jitter_frac * float(
                self._rng.uniform(-1.0, 1.0)
            )
        return float(min(max(base, 0.0), self.max_s))

    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` with retries.  Returns its result; re-raises
        the last error after ``max_attempts`` (counted in
        ``giveups``) or immediately for non-retryable classes."""
        attempts = max(int(self.max_attempts), 1)
        for attempt in range(attempts):
            try:
                return fn(*args, **kwargs)
            except self.retryable as e:
                if attempt + 1 >= attempts:
                    self.giveups += 1
                    raise
                self.retries += 1
                self.sleep(self.backoff_s(
                    attempt, getattr(e, "retry_after_s", None)
                ))
        raise AssertionError("unreachable")  # pragma: no cover
