"""Batched solve service: accept many independent solve requests,
execute them as a small number of vmapped device calls.

Shape of the system (an inference-server-style continuous batcher):

  submit(A, b) ──┐   group by (padded-pattern fingerprint, dtype)
  submit(A, b) ──┼─> bounded queue ──flush──> pad to (n, nnz, B) bucket
  submit(A, b) ──┘   (max_batch / max-wait)    │
                                               ▼
                             hierarchy cache (fingerprint + config):
                             one solver setup per pattern, reused for
                             every later coefficient set
                                               │
                                               ▼
                             compile cache (shape bucket + config):
                             one jitted batched solve per bucket
                                               │
                                               ▼
                             vmapped masked-convergence solve
                             (serve.batched), results unpadded

Solvers without a traced batch path (GMRES, multicolor GS, ...) fall
back to sequential resetup+solve per request — correct, just not
amortized; the ``fallback_solves`` counter exposes it.

Fault isolation (guardrails): non-finite uploads are rejected at
submit() with a typed SetupError; a group that fails as a unit is
QUARANTINED — every member retries in per-request isolation so only
the actually-poisoned requests fail; a per-fingerprint circuit breaker
bypasses batching for patterns that keep failing; optional per-ticket
deadlines fail late tickets without touching their group.  All of it
is counted in serve/metrics.py.

Scalar (block_size == 1) systems only for now: block coefficient
layouts don't survive the nnz-padding embedding.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

import numpy as np

from amgx_tpu.config.amg_config import AMGConfig
from amgx_tpu.core.matrix import SparseMatrix
from amgx_tpu.core.profiling import trace_range
from amgx_tpu.serve.batched import make_batched_solve
from amgx_tpu.serve.bucketing import (
    PaddedPattern,
    bucket_batch,
    pad_pattern,
)
from amgx_tpu.serve.cache import (
    HierarchyCache,
    HierarchyEntry,
    config_hash,
    template_signature,
)
from amgx_tpu.serve.metrics import ServeMetrics

def _host_csr(A):
    """(row_offsets, col_indices, values, n, raw_fingerprint) host
    arrays from a SparseMatrix or scipy sparse matrix; scalar matrices
    only.  The fingerprint keys the padded-pattern cache (SparseMatrix
    memoizes its own, so repeat submissions skip the hash too)."""
    from amgx_tpu.core.matrix import sparsity_fingerprint

    if isinstance(A, SparseMatrix):
        if A.block_size != 1:
            raise ValueError(
                "BatchedSolveService: scalar (block_size == 1) "
                "systems only"
            )
        return (
            np.asarray(A.row_offsets),
            np.asarray(A.col_indices),
            np.asarray(A.values),
            A.n_rows,
            A.fingerprint(),
        )
    try:
        sp = A.tocsr()
    except AttributeError:
        raise TypeError(
            f"expected SparseMatrix or scipy sparse matrix, got "
            f"{type(A).__name__}"
        ) from None
    sp.sort_indices()
    fp = sparsity_fingerprint(
        sp.indptr, sp.indices, sp.shape[0], sp.shape[1], 1
    )
    return sp.indptr, sp.indices, sp.data, sp.shape[0], fp


# the service's stock configuration — also the workload ci/serve_bench.py
# and tests/test_serve.py measure
DEFAULT_CONFIG = (
    '{"config_version": 2, "solver": {"scope": "main", "solver": "PCG",'
    ' "max_iters": 200, "tolerance": 1e-8,'
    ' "monitor_residual": 1, "convergence": "RELATIVE_INI",'
    ' "preconditioner": {"scope": "jac", "solver": "BLOCK_JACOBI",'
    ' "relaxation_factor": 0.9, "max_iters": 2,'
    ' "monitor_residual": 0}}}'
)


@dataclasses.dataclass
class SolveTicket:
    """Handle returned by submit(); result() blocks (flushing the
    owning group if needed) and returns a per-request SolveResult."""

    _service: "BatchedSolveService"
    _group_key: tuple
    _result: object = None
    _done: bool = False
    _error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._done

    def result(self):
        if not self._done:
            self._service._flush_group_of(self)
        if self._error is not None:
            raise self._error
        return self._result


@dataclasses.dataclass
class _Request:
    pattern: PaddedPattern
    values: np.ndarray  # padded (nnzb,)
    b: np.ndarray  # padded (nb,)
    x0: np.ndarray  # padded (nb,)
    ticket: SolveTicket
    # optional absolute monotonic deadline; the flusher fails the
    # ticket with ResourceError when execution starts after it
    deadline: Optional[float] = None


@dataclasses.dataclass
class _Group:
    key: tuple  # (padded fingerprint, dtype str)
    pattern: PaddedPattern
    dtype: np.dtype
    requests: list
    deadline: float


class BatchedSolveService:
    """Shape-bucketed, vmapped multi-system solver frontend.

    Parameters
    ----------
    config: AMGConfig | JSON/kv string | None — solver configuration
        shared by every request (the service IS one config; run several
        services for several configs).  Default: Jacobi-PCG.
    max_batch: flush a group when it reaches this many requests.
    max_wait_s: flush a group this long after its first request
        (enforced by poll()/flush(); start() runs a background poller).
    queue_limit: bound on total queued requests; reaching it flushes
        everything (backpressure, never unbounded memory).
    validate: reject non-finite uploads at submit() with a typed
        SetupError instead of letting one poisoned request fail (or
        quarantine) its whole batch group later (``validation_rejects``
        counter).
    breaker_threshold: per-fingerprint circuit breaker — after this
        many consecutive group failures for one pattern, batching is
        bypassed for that pattern and its requests run in per-request
        isolation (``breaker_trips`` / ``breaker_bypasses`` counters;
        a successful batched group resets the count).
    """

    def __init__(
        self,
        config=None,
        max_batch: int = 32,
        max_wait_s: float = 0.02,
        queue_limit: int = 1024,
        cache_entries: int = 64,
        validate: bool = True,
        breaker_threshold: int = 3,
    ):
        if config is None:
            config = DEFAULT_CONFIG
        if isinstance(config, str):
            config = AMGConfig.from_string(config)
        self.cfg = config
        self.cfg_key = config_hash(config)
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.queue_limit = int(queue_limit)
        self.metrics = ServeMetrics()
        self.cache = HierarchyCache(
            max_entries=cache_entries, metrics=self.metrics
        )
        self._lock = threading.RLock()
        self._groups: dict = {}
        self._queued = 0
        self._compiled: dict = {}
        self._patterns: dict = {}
        self._poller: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.validate = bool(validate)
        self.breaker_threshold = int(breaker_threshold)
        # circuit breaker: padded fingerprint -> consecutive group
        # failures; fingerprints in _broken bypass batching (with a
        # periodic half-open probe so transient failures don't cost a
        # pattern its batching forever)
        self._fail_counts: dict = {}
        self._broken: set = set()
        self._bypass_counts: dict = {}

    # ------------------------------------------------------------------
    # submission

    def submit(self, A, b, x0=None, deadline_s=None) -> SolveTicket:
        """Queue one system; returns a ticket.  ``A`` is a SparseMatrix
        or scipy sparse matrix (scalar block size).  ``deadline_s``
        (optional, seconds from now): if the group executes after the
        deadline, THIS ticket fails with ResourceError while the rest
        of the group proceeds."""
        ro, ci, vals, n, raw_fp = _host_csr(A)
        if self.validate:
            # typed rejection at the door: one poisoned request must
            # never reach a batch group (guardrails acceptance)
            from amgx_tpu.core.errors import NonFiniteValuesError

            bad = not np.all(np.isfinite(vals))
            bad = bad or (b is not None
                          and not np.all(np.isfinite(np.asarray(b))))
            bad = bad or (x0 is not None
                          and not np.all(np.isfinite(np.asarray(x0))))
            if bad:
                self.metrics.inc("validation_rejects")
                raise NonFiniteValuesError(
                    "BatchedSolveService.submit: system contains "
                    "NaN/Inf (validation reject)"
                )
        pattern = self._pattern_for(ro, ci, n, raw_fp)
        dtype = np.dtype(vals.dtype)
        if not np.issubdtype(dtype, np.inexact):
            # integer uploads promote; complex dtypes pass through
            dtype = np.dtype(np.float64)
        with trace_range("serve_submit"), self.metrics.profile.phase(
            "pad"
        ):
            req_vals = pattern.embed_values(vals, dtype=dtype)
            req_b = pattern.embed_vector(b, dtype)
            req_x0 = pattern.embed_vector(x0, dtype)
        key = (pattern.fingerprint, str(dtype))
        flush_now = []
        with self._lock:
            grp = self._groups.get(key)
            if grp is None:
                grp = _Group(
                    key=key,
                    pattern=pattern,
                    dtype=dtype,
                    requests=[],
                    deadline=time.monotonic() + self.max_wait_s,
                )
                self._groups[key] = grp
            ticket = SolveTicket(_service=self, _group_key=key)
            grp.requests.append(
                _Request(
                    pattern=pattern,
                    values=req_vals,
                    b=req_b,
                    x0=req_x0,
                    ticket=ticket,
                    deadline=(
                        None
                        if deadline_s is None
                        else time.monotonic() + float(deadline_s)
                    ),
                )
            )
            self._queued += 1
            self.metrics.inc("submitted")
            self.metrics.set_gauge("queue_depth", self._queued)
            if len(grp.requests) >= self.max_batch:
                flush_now.append(self._take_group(key))
            elif self._queued >= self.queue_limit:
                flush_now.extend(
                    self._take_group(k) for k in list(self._groups)
                )
        for grp in flush_now:
            self._execute_group(grp)
        return ticket

    def solve_many(self, systems):
        """Synchronous convenience: submit every (A, b[, x0]) tuple,
        flush, and return the per-system SolveResults in order."""
        tickets = [self.submit(*sys) for sys in systems]
        self.flush()
        return [t.result() for t in tickets]

    # ------------------------------------------------------------------
    # flushing

    def flush(self):
        """Execute every queued group now."""
        with self._lock:
            groups = [self._take_group(k) for k in list(self._groups)]
        for grp in groups:
            self._execute_group(grp)

    def poll(self):
        """Execute groups whose max-wait deadline has passed."""
        now = time.monotonic()
        with self._lock:
            due = [
                self._take_group(k)
                for k, g in list(self._groups.items())
                if g.deadline <= now
            ]
        for grp in due:
            self._execute_group(grp)

    def start(self, interval_s: float = 0.005):
        """Run a daemon poller enforcing max_wait_s in the background."""
        if self._poller is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                self.poll()

        self._poller = threading.Thread(
            target=loop, name="serve-poller", daemon=True
        )
        self._poller.start()

    def stop(self):
        if self._poller is None:
            return
        self._stop.set()
        self._poller.join()
        self._poller = None
        self.flush()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------------
    # internals

    _PATTERN_CACHE_MAX = 512

    def _pattern_for(self, ro, ci, n, raw_fp) -> PaddedPattern:
        """Padded pattern for a raw fingerprint, cached: re-padding on
        every submission would cost O(nnz log nnz) host work per
        request — more than the batched solve itself for small
        systems."""
        with self._lock:
            pat = self._patterns.get(raw_fp)
        if pat is not None:
            return pat
        pat = pad_pattern(ro, ci, n)
        with self._lock:
            if len(self._patterns) >= self._PATTERN_CACHE_MAX:
                self._patterns.clear()
            self._patterns[raw_fp] = pat
        return pat

    # total bytes the batched dense copies may occupy (B x nb x nb);
    # above it a non-ELL bucket stays CSR (segment-sum SpMV)
    _DENSE_BUDGET_MB = 256
    # padded max row length up to which the ELL structure is used
    _ELL_MAX_WIDTH = 64

    def _accel_for(self, pat: PaddedPattern) -> tuple:
        """Bucket-safe acceleration formats for a padded pattern.

        Preference order mirrors ops.spmv: DIA for stencil-shaped
        patterns (slice + FMA, no gathers — gathers and scatters are
        the slow ops on both CPU XLA and TPU), then ELL (gather + FMA,
        nnz-proportional work), then dense (batched GEMV, n^2 work,
        small buckets within the byte budget), then CSR segment-sum.
        DIA's offsets are static metadata, so DIA entries share a
        compiled program only with matching-offset patterns; the
        same-fingerprint compile-reuse guarantee is unaffected."""
        import os

        from amgx_tpu.core.matrix import dia_gate

        if dia_gate(pat.num_diagonals, pat.nb, pat.nnzb):
            return ("dia",)
        w = pat.max_row_len
        if 0 < w <= self._ELL_MAX_WIDTH and w * pat.nb <= 4 * pat.nnzb:
            return ("ell",)
        budget = (
            int(
                os.environ.get(
                    "AMGX_TPU_SERVE_DENSE_MB", self._DENSE_BUDGET_MB
                )
            )
            * 2**20
        )
        bb = bucket_batch(self.max_batch)
        if bb * pat.nb * pat.nb * 8 <= budget:
            return ("dense",)
        return ()

    def _take_group(self, key) -> _Group:
        """Remove a group from the queue (caller holds the lock)."""
        grp = self._groups.pop(key)
        self._queued -= len(grp.requests)
        self.metrics.set_gauge("queue_depth", self._queued)
        return grp

    def _flush_group_of(self, ticket: SolveTicket):
        with self._lock:
            grp = self._groups.get(ticket._group_key)
            if grp is None or ticket not in [
                r.ticket for r in grp.requests
            ]:
                grp = None
            else:
                grp = self._take_group(ticket._group_key)
        if grp is not None:
            self._execute_group(grp)
        elif not ticket._done:
            # another thread is executing the group right now
            while not ticket._done:
                time.sleep(0.001)

    def _build_entry(self, grp: _Group) -> HierarchyEntry:
        """One solver setup for this padded pattern (hierarchy-cache
        miss path), using the group's first coefficient set."""
        import amgx_tpu.solvers  # noqa: F401 — registry side effects
        import amgx_tpu.amg  # noqa: F401 — registers "AMG"
        from amgx_tpu.solvers.registry import create_solver, make_nested

        with self.metrics.profile.phase("setup"):
            A = grp.pattern.template_matrix(
                grp.pattern.extract_values(grp.requests[0].values),
                grp.dtype,
                accel_formats=self._accel_for(grp.pattern),
            )
            # make_nested: the service owns the solve boundary — no
            # per-solver rescaling/renumbering of padded systems
            solver = make_nested(create_solver(self.cfg, "default"))
            solver.setup(A)
            bp = solver.make_batch_params()
            batch_fn = make_batched_solve(solver)
            template = bp[0] if bp is not None else None
            sig = (
                template_signature(template)
                if batch_fn is not None
                else None
            )
        return HierarchyEntry(
            solver=solver,
            template=template,
            batch_fn=batch_fn,
            signature=sig,
            pattern=grp.pattern,
        )

    def _compiled_fn(self, entry: HierarchyEntry, Bb: int):
        """Jitted batched solve shared across every hierarchy entry
        with the same template signature (= shape bucket) and batch
        bucket — a bucket hit is an XLA compile-cache hit."""
        import jax

        from amgx_tpu.core import faults
        from amgx_tpu.core.errors import ResourceError

        key = (entry.signature, Bb)
        with self._lock:
            fn = self._compiled.get(key)
            if fn is not None:
                self.metrics.inc("bucket_hits")
                return fn
            if faults.should_fire("serve_compile"):
                raise ResourceError(
                    "injected serve compile failure (fault site "
                    "serve_compile)"
                )
            self.metrics.inc("compiles")
            fn = jax.jit(entry.batch_fn)
            self._compiled[key] = fn
            return fn

    def _expire_deadlines(self, grp: _Group):
        """Fail (only) the tickets whose deadline already passed; the
        rest of the group executes normally."""
        from amgx_tpu.core.errors import ResourceError

        now = time.monotonic()
        live = []
        for r in grp.requests:
            if r.deadline is not None and now > r.deadline:
                r.ticket._error = ResourceError(
                    "serve deadline exceeded before execution"
                )
                r.ticket._done = True
                self.metrics.inc("deadline_expired")
            else:
                live.append(r)
        grp.requests = live

    def _breaker_failure(self, fp: str):
        """Count a group failure; trip the breaker at the threshold."""
        if self.breaker_threshold <= 0 or fp in self._broken:
            return
        with self._lock:
            n = self._fail_counts.get(fp, 0) + 1
            self._fail_counts[fp] = n
            if n >= self.breaker_threshold:
                self._broken.add(fp)
                self.metrics.inc("breaker_trips")
                self.metrics.set_gauge(
                    "breakers_open", len(self._broken)
                )

    def _breaker_success(self, fp: str):
        """A batched group completed: reset the failure count and — if
        this was a half-open probe — close the breaker."""
        with self._lock:
            self._fail_counts.pop(fp, None)
            if fp in self._broken:
                self._broken.discard(fp)
                self._bypass_counts.pop(fp, None)
                self.metrics.inc("breaker_closes")
                self.metrics.set_gauge(
                    "breakers_open", len(self._broken)
                )

    # every Nth group for an open-breaker pattern retries batching
    # (half-open probe): success closes the breaker, failure keeps it
    # open and recounts toward nothing (already open)
    _BREAKER_PROBE_EVERY = 8

    def _execute_group(self, grp: _Group):
        if not grp.requests:
            return
        self._expire_deadlines(grp)
        if not grp.requests:
            return
        fp = grp.pattern.fingerprint
        if fp in self._broken:
            with self._lock:
                probes = self._bypass_counts.get(fp, 0) + 1
                self._bypass_counts[fp] = probes
            if probes % self._BREAKER_PROBE_EVERY != 0:
                # breaker open: this pattern keeps poisoning its batch
                # groups — serve its requests in per-request isolation
                # without attempting a batched execution
                self.metrics.inc("breaker_bypasses")
                self._execute_quarantined(grp)
                return
            # fall through: half-open probe attempts one batched group
        try:
            entry = self.cache.get_or_build(
                grp.pattern,
                self.cfg_key,
                grp.dtype,
                lambda: self._build_entry(grp),
            )
            if entry.batch_fn is None:
                self._execute_sequential(entry, grp)
            else:
                self._execute_batched(entry, grp)
        except BaseException:  # noqa: BLE001 — failures must reach the
            # tickets, not kill the poller thread (tickets already
            # completed — e.g. earlier fallback solves — keep their
            # results).  Quarantine: the group failed as a unit (a
            # poisoned member sabotaged shared setup, or compile/
            # execute died) — retry every member in isolation so only
            # the actually-poisoned requests fail.
            self.metrics.inc("failed_groups")
            self._breaker_failure(fp)
            self.metrics.inc("quarantines")
            self._execute_quarantined(grp)
        else:
            self._breaker_success(fp)

    def _execute_quarantined(self, grp: _Group):
        """Per-request isolation: each request gets its own solver
        setup on its OWN coefficients (the cached group entry may have
        been built from a poisoned member), so exactly the poisoned
        requests fail — with typed errors — and the rest complete."""
        import amgx_tpu.solvers  # noqa: F401 — registry side effects
        import amgx_tpu.amg  # noqa: F401 — registers "AMG"
        from amgx_tpu.solvers.registry import create_solver, make_nested

        pat = grp.pattern
        for r in grp.requests:
            if r.ticket._done:
                continue
            try:
                with self.metrics.profile.phase("quarantine"):
                    A = pat.template_matrix(
                        pat.extract_values(r.values),
                        grp.dtype,
                        accel_formats=self._accel_for(pat),
                    )
                    solver = make_nested(
                        create_solver(self.cfg, "default")
                    )
                    solver.setup(A)
                    res = solver.solve(r.b, x0=r.x0)
            except BaseException as e:  # noqa: BLE001 — per-request
                r.ticket._error = e
                r.ticket._done = True
                self.metrics.inc("poisoned_requests")
            else:
                r.ticket._result = dataclasses.replace(
                    res, x=res.x[: pat.n]
                )
                r.ticket._done = True
                self.metrics.inc("quarantined_solves")
                self.metrics.inc("solved")

    def _execute_batched(self, entry: HierarchyEntry, grp: _Group):
        import jax.numpy as jnp

        # submit() flushes a group at max_batch, so one batch bucket
        # always covers the whole group
        chunk = grp.requests
        Bb = bucket_batch(len(chunk))
        n_pad = Bb - len(chunk)
        self.metrics.inc("batches")
        pat = grp.pattern
        with self.metrics.profile.phase("stack"):
            # batch padding: clones of the first system with b=0
            # converge at iteration 0 and freeze immediately
            vals = np.stack(
                [r.values for r in chunk] + [chunk[0].values] * n_pad
            )
            bs = np.stack(
                [r.b for r in chunk]
                + [np.zeros_like(chunk[0].b)] * n_pad
            )
            x0s = np.stack(
                [r.x0 for r in chunk]
                + [np.zeros_like(chunk[0].x0)] * n_pad
            )
        fn = self._compiled_fn(entry, Bb)
        t0 = time.perf_counter()
        with trace_range("serve_batch_execute"), \
                self.metrics.profile.phase("execute"):
            res = fn(
                entry.template,
                jnp.asarray(vals),
                jnp.asarray(bs),
                jnp.asarray(x0s),
            )
            res.x.block_until_ready()
        dt = time.perf_counter() - t0
        bucket_key = (pat.nb, pat.nnzb, Bb)
        self.metrics.record_batch(bucket_key, dt, len(chunk), n_pad)
        self.metrics.inc("solved", len(chunk))
        self.metrics.inc("padded_elems", Bb * pat.nb)
        self.metrics.inc(
            "real_elems", sum(r.pattern.n for r in chunk)
        )
        with self.metrics.profile.phase("unpack"):
            # one device->host transfer per field, then numpy
            # slicing (per-request device slices would cost ~6
            # dispatches each and dominate small-system batches)
            x_h = np.asarray(res.x)
            iters_h = np.asarray(res.iters)
            status_h = np.asarray(res.status)
            fin_h = np.asarray(res.final_norm)
            ini_h = np.asarray(res.initial_norm)
            hist_h = np.asarray(res.history)
            for i, r in enumerate(chunk):
                r.ticket._result = dataclasses.replace(
                    res,
                    x=x_h[i, : r.pattern.n],
                    iters=iters_h[i],
                    status=status_h[i],
                    final_norm=fin_h[i],
                    initial_norm=ini_h[i],
                    history=hist_h[i],
                )
                r.ticket._done = True

    def _execute_sequential(self, entry: HierarchyEntry, grp: _Group):
        """Fallback for solvers without a traced batch path."""
        pat = grp.pattern
        for r in grp.requests:
            with self.metrics.profile.phase("fallback"):
                A = pat.template_matrix(
                    pat.extract_values(r.values),
                    grp.dtype,
                    accel_formats=self._accel_for(pat),
                )
                entry.solver.resetup(A)
                res = entry.solver.solve(r.b, x0=r.x0)
            r.ticket._result = dataclasses.replace(
                res, x=res.x[: pat.n]
            )
            r.ticket._done = True
            self.metrics.inc("fallback_solves")
            self.metrics.inc("solved")
