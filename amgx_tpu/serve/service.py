"""Batched solve service: accept many independent solve requests,
execute them as a small number of vmapped device calls.

Shape of the system (an inference-server-style continuous batcher):

  submit(A, b) ──┐   group by (padded-pattern fingerprint, dtype)
  submit(A, b) ──┼─> bounded queue ──flush──> resident staging slot
  submit(A, b) ──┘   (max_batch / max-wait)    │ (padded rows written
                                               │  in place at submit)
                                               ▼
                             hierarchy cache (fingerprint + config):
                             one solver setup per pattern, reused for
                             every later coefficient set
                                               │
                                               ▼
                             compile cache (shape bucket + config):
                             one AOT-compiled batched solve per
                             bucket, warmed in the background
                                               │
                                               ▼
                             single-worker dispatch stage: ship the
                             staging slot, launch the vmapped solve
                             (x0 donated), return WITHOUT blocking
                                               │
                                               ▼
                             SolveTicket.result(): ONE blocking fetch
                             per group, results unpadded lazily

Async pipeline (PR 3): ``submit`` pads straight into a persistent,
double-buffered staging slot; the flusher splits into a host stage
(deadlines, hierarchy/compile resolution — caller thread) and a device
stage (ship + launch — single-worker executor), so padding of group
N+1 overlaps device execution of group N.  Nothing in the steady-state
path blocks on the device: the ONLY host sync is the shared per-group
fetch inside ``SolveTicket.result()`` (counted by the ``host_syncs``
metric and asserted by tests/test_serve.py).

Solvers without a traced batch path (GMRES, multicolor GS, ...) fall
back to sequential resetup+solve per request — correct, just not
amortized; the ``fallback_solves`` counter exposes it.

Fault isolation (guardrails): non-finite uploads are rejected at
submit() with a typed SetupError; a group that fails as a unit is
QUARANTINED — every member retries in per-request isolation (reusing
the pattern's cached hierarchy when one exists) so only the
actually-poisoned requests fail; a per-fingerprint circuit breaker
bypasses batching for patterns that keep failing; optional per-ticket
deadlines fail late tickets without touching their group.  All of it
is counted in serve/metrics.py.

Scalar (block_size == 1) systems only for now: block coefficient
layouts don't survive the nnz-padding embedding.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import threading
import time
from typing import Optional

import numpy as np

from amgx_tpu.config.amg_config import AMGConfig
from amgx_tpu.core.matrix import SparseMatrix
from amgx_tpu.core.profiling import trace_range
from amgx_tpu.serve.batched import make_batched_solve
from amgx_tpu.serve.bucketing import (
    PaddedPattern,
    StagingSlot,
    bucket_batch,
    pad_pattern,
)
from amgx_tpu.serve.cache import (
    CompileCache,
    HierarchyCache,
    HierarchyEntry,
    _compile_pool,
    config_hash,
    template_signature,
)
from amgx_tpu.serve.metrics import ServeMetrics
from amgx_tpu.solvers.base import SolveResult
from amgx_tpu.telemetry import (
    FlightRecorder,
    SolveRecord,
    get_registry,
    telemetry_enabled,
    tracing,
)


def _host_csr(A):
    """(row_offsets, col_indices, values, n, raw_fingerprint) host
    arrays from a SparseMatrix or scipy sparse matrix; scalar matrices
    only.  The fingerprint keys the padded-pattern cache; it is
    memoized on the object (SparseMatrix has its own memo; for scipy
    CSR inputs it is stashed as an attribute) so repeat submissions of
    one matrix skip the pattern hash — callers that mutate a CSR's
    index arrays IN PLACE after a submit must pass a fresh matrix."""
    from amgx_tpu.core.matrix import sparsity_fingerprint

    if isinstance(A, SparseMatrix):
        if A.block_size != 1:
            raise ValueError(
                "BatchedSolveService: scalar (block_size == 1) "
                "systems only"
            )
        return (
            np.asarray(A.row_offsets),
            np.asarray(A.col_indices),
            np.asarray(A.values),
            A.n_rows,
            A.fingerprint(),
        )
    try:
        sp = A.tocsr()
    except AttributeError:
        raise TypeError(
            f"expected SparseMatrix or scipy sparse matrix, got "
            f"{type(A).__name__}"
        ) from None
    sp.sort_indices()
    fp = getattr(sp, "_amgx_tpu_fp", None)
    if fp is None:
        fp = sparsity_fingerprint(
            sp.indptr, sp.indices, sp.shape[0], sp.shape[1], 1
        )
        try:
            sp._amgx_tpu_fp = fp
        except AttributeError:
            pass
    return sp.indptr, sp.indices, sp.data, sp.shape[0], fp


def _env_float(name: str, default: float) -> float:
    import os

    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


_DTYPE_MEMO: dict = {}


def _resolve_dtype(dt):
    """(resolved np.dtype, str) for an upload dtype — integer uploads
    promote to f64, complex passes through.  Memoized: dtype object
    construction and str() are measurable at submit rates."""
    ent = _DTYPE_MEMO.get(dt)
    if ent is None:
        rdt = np.dtype(dt)
        if not np.issubdtype(rdt, np.inexact):
            rdt = np.dtype(np.float64)
        ent = (rdt, str(rdt))
        _DTYPE_MEMO[dt] = ent
    return ent


# the service's stock configuration — also the workload ci/serve_bench.py
# and tests/test_serve.py measure
DEFAULT_CONFIG = (
    '{"config_version": 2, "solver": {"scope": "main", "solver": "PCG",'
    ' "max_iters": 200, "tolerance": 1e-8,'
    ' "monitor_residual": 1, "convergence": "RELATIVE_INI",'
    ' "preconditioner": {"scope": "jac", "solver": "BLOCK_JACOBI",'
    ' "relaxation_factor": 0.9, "max_iters": 2,'
    ' "monitor_residual": 0}}}'
)

# the recommended serve configuration (PR 8, doc/PERFORMANCE.md
# "Communication-free inner loops"): s-step PCG (s=4 CG steps per
# fused Gram reduction) over an aggregation AMG V-cycle smoothed by
# the optimal-weight fourth-kind Chebyshev polynomial — no colorings,
# no triangular solves, no per-step scalar dots; every inner-loop
# global reduction a future mesh shard would psum over is amortized
# s-fold.  ci/smoother_bench.py gates its iteration parity against
# the PCG+Jacobi baseline; ci/serve_bench.py gates its per-iteration
# time at B=16.
COMM_AVOIDING_CONFIG = (
    '{"config_version": 2, "solver": {"scope": "main",'
    ' "solver": "SSTEP_PCG", "s_step": 4, "max_iters": 200,'
    ' "tolerance": 1e-8, "monitor_residual": 1,'
    ' "convergence": "RELATIVE_INI",'
    ' "preconditioner": {"scope": "amg", "solver": "AMG",'
    ' "algorithm": "AGGREGATION", "selector": "SIZE_8",'
    ' "smoother": {"scope": "sm", "solver": "OPT_POLYNOMIAL",'
    ' "chebyshev_polynomial_order": 2, "monitor_residual": 0},'
    ' "presweeps": 1, "postsweeps": 1, "max_iters": 1,'
    ' "min_coarse_rows": 32, "max_levels": 10,'
    ' "structure_reuse_levels": -1,'
    ' "coarse_solver": "DENSE_LU_SOLVER", "cycle": "V",'
    ' "monitor_residual": 0}}}'
)

# the cheap-preconditioner configuration (doc/PERFORMANCE.md "Run the
# preconditioner cheap"): the whole AMG hierarchy runs in f32
# (hierarchy_dtype=FLOAT32, level_dtype_policy=ALL — half the
# bandwidth-bound HBM bytes per cycle) and bottoms out in an INEXACT
# iterative coarse solve (no O(n^3) DenseLU factorization, no dense
# factors in the store), wrapped in ITERATIVE_REFINEMENT's f64 outer
# residual correction so the FINAL tolerance is unchanged.  The
# precision_fallback guardrail re-solves once at full precision if
# the cheap path fails to converge.  ci/precision_bench.py gates
# retired-iteration parity (+10% inner-step equivalents) against the
# f64/DenseLU baseline.
CHEAP_PRECONDITIONER_CONFIG = (
    '{"config_version": 2, "solver": {"scope": "main",'
    ' "solver": "ITERATIVE_REFINEMENT", "max_iters": 40,'
    ' "tolerance": 1e-8, "monitor_residual": 1,'
    ' "convergence": "RELATIVE_INI", "precision_fallback": 1,'
    ' "preconditioner": {"scope": "inner", "solver": "PCG",'
    ' "max_iters": 8, "monitor_residual": 0,'
    ' "preconditioner": {"scope": "amg", "solver": "AMG",'
    ' "algorithm": "AGGREGATION", "selector": "SIZE_8",'
    ' "hierarchy_dtype": "FLOAT32", "level_dtype_policy": "ALL",'
    ' "smoother": {"scope": "sm", "solver": "OPT_POLYNOMIAL",'
    ' "chebyshev_polynomial_order": 2, "monitor_residual": 0},'
    ' "presweeps": 1, "postsweeps": 1, "max_iters": 1,'
    ' "min_coarse_rows": 32, "max_levels": 10,'
    ' "structure_reuse_levels": -1,'
    ' "coarse_solver": "INEXACT",'
    ' "inexact_coarse_solver": "OPT_POLYNOMIAL", "cycle": "V",'
    ' "monitor_residual": 0}}}}'
)


# process-wide single-worker device-dispatch stage: ship-and-launch of
# batched groups serializes here (device_put + async XLA dispatch, no
# blocking), which keeps the flusher's caller free to pad the next
# group while the device executes the current one
_DISPATCH_POOL: Optional[concurrent.futures.ThreadPoolExecutor] = None
_DISPATCH_LOCK = threading.Lock()


def _dispatch_pool() -> concurrent.futures.ThreadPoolExecutor:
    global _DISPATCH_POOL
    with _DISPATCH_LOCK:
        if _DISPATCH_POOL is None:
            _DISPATCH_POOL = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="serve-dispatch"
            )
        return _DISPATCH_POOL


def _block_ready(x):
    """THE steady-state device sync: wait for a dispatched group's
    solution.  Kept as a module hook so tests can count that it runs
    exactly once per batched group."""
    import jax

    return jax.block_until_ready(x)


class _DaemonFetchPool:
    """Daemon-thread work pool for watchdogged fetches: the group's
    blocking device sync runs here so the fetching caller can time out
    (a hung chip must settle typed, not block result()/drain()
    forever).  NOT a ThreadPoolExecutor — its workers are non-daemon
    on Python >= 3.9 and joined at interpreter shutdown, so one truly
    hung ``block_until_ready`` would wedge process EXIT, exactly the
    hang the watchdog exists to eliminate.  These workers are daemon
    threads: a stuck one is simply abandoned and the pool grows
    around it up to the cap (tasks queued past a fully-stuck pool
    still time out typed at the watchdog).  Workers are reused, so
    the steady state pays a queue hop, not a thread spawn."""

    def __init__(self, max_workers: int = 32):
        import queue

        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._workers = 0
        self._idle = 0
        self._max = int(max_workers)

    def submit(self, fn) -> concurrent.futures.Future:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._q.put((fn, fut))
        with self._lock:
            if self._idle == 0 and self._workers < self._max:
                self._workers += 1
                threading.Thread(
                    target=self._loop,
                    name=f"serve-fetch-{self._workers}",
                    daemon=True,
                ).start()
        return fut

    def _loop(self):
        while True:
            with self._lock:
                self._idle += 1
            fn, fut = self._q.get()
            with self._lock:
                self._idle -= 1
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — delivered to
                # the watchdogged waiter via the future
                fut.set_exception(e)


_FETCH_POOL: Optional[_DaemonFetchPool] = None
_FETCH_POOL_LOCK = threading.Lock()


def _fetch_pool() -> _DaemonFetchPool:
    global _FETCH_POOL
    with _FETCH_POOL_LOCK:
        if _FETCH_POOL is None:
            _FETCH_POOL = _DaemonFetchPool(max_workers=32)
        return _FETCH_POOL


def _fetch_host(tree):
    """Device→host copy of a (ready) batched result pytree — the
    second half of the per-group sync, also test-countable."""
    import jax

    return jax.device_get(tree)


@dataclasses.dataclass
class SolveTicket:
    """Handle returned by submit().

    ``done()`` is non-blocking: True once the ticket has settled
    (result or error) OR its group has been dispatched to the device —
    the result itself may still be in flight.  ``result()`` flushes
    the owning group if needed, then performs the pipeline's single
    per-group blocking fetch (shared by every groupmate, whichever
    ticket asks first) and returns this request's SolveResult."""

    _service: "BatchedSolveService"
    _group_key: tuple
    _row: int = 0
    _pattern: object = None
    _result: object = None
    _done: bool = False
    _error: Optional[BaseException] = None
    _batch: object = None  # _BatchResult after dispatch
    _t_submit: float = 0.0
    _pad_s: float = 0.0
    _lane: str = "interactive"
    _tenant: str = "default"  # set by the gateway; "default" direct
    _deadline: Optional[float] = None  # absolute monotonic, or None
    # telemetry trace context (tracing.TraceContext) when this ticket
    # is sampled, else None — spans recorded at pad/dispatch/fetch
    _trace: object = None
    # settle-path lock: concurrent result() calls on ONE ticket are a
    # designed pattern (a gateway drain's settle loop races the client
    # thread), so the deadline short-circuit's _batch/_error handoff
    # must be atomic — both callers get the result, or both get the
    # sticky typed error, never an AttributeError or a silent None
    _rlock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def done(self) -> bool:
        return self._done

    def result(self):
        if not self._done:
            self._service._flush_group_of(self)
        with self._rlock:
            if self._error is not None:
                raise self._error
            if self._result is None and self._batch is not None:
                # deadline short-circuit at the fetch boundary: a late
                # fetch whose group nobody has synced yet returns a
                # typed deadline failure instead of blocking on the
                # device (an already-fetched group's result is free —
                # return it).  The failure is STICKY (cached like
                # every other terminal error) so retries raise
                # consistently and the metric counts tickets, not
                # calls.
                if (
                    self._deadline is not None
                    and not self._batch.fetched()
                    and time.monotonic() > self._deadline
                ):
                    from amgx_tpu.core.errors import (
                        DeadlineExceededError,
                    )

                    self._service.metrics.inc("deadline_expired_fetch")
                    self._error = DeadlineExceededError(
                        "serve deadline exceeded before the result "
                        "was fetched"
                    )
                    self._batch = None  # final: release the group ref
                    self._service._flight_incident(
                        "deadline_expired",
                        detail="fetch-boundary short-circuit",
                    )
                    raise self._error
                self._result = self._batch.result_for(self)
            return self._result


@dataclasses.dataclass
class _Request:
    ticket: SolveTicket
    row: int  # staging-slot row owned by this request
    # optional absolute monotonic deadline; the flusher fails the
    # ticket with ResourceError when execution starts after it
    deadline: Optional[float] = None
    # row write finished (writes happen outside the service lock; the
    # flusher's host stage waits on this)
    ready: bool = False


@dataclasses.dataclass
class _Group:
    key: tuple  # (padded fingerprint, dtype str, lane)
    pattern: PaddedPattern
    dtype: np.dtype
    requests: list
    deadline: float
    slot: StagingSlot
    lane: str = "interactive"
    created: float = 0.0  # monotonic group-creation time (aging)
    promoted: bool = False  # batch aging credit consumed (sticky)


class _BatchResult:
    """One dispatched batched group: the device-resident SolveResult
    plus the bookkeeping to distribute per-request results lazily.

    ``fetch()`` performs the pipeline's ONLY steady-state host sync —
    once per group, whichever ticket asks first — then records the
    queue→pad→dispatch→device→fetch breakdown for every groupmate.

    Timing semantics: the ``device`` stage is measured dispatch→ready
    AT FETCH TIME, so it is exact when the consumer fetches promptly
    (solve_many, the serve bench) and an UPPER BOUND including consumer
    idle when results are collected late — measuring true completion
    would need a watcher thread performing a second per-group sync,
    which the one-sync-per-group contract deliberately forbids."""

    __slots__ = (
        "_service", "res", "pattern", "tickets", "Bb",
        "t_flush", "t_dispatch", "_lock", "_host", "_error", "plan",
        "entry", "retry", "requeued",
    )

    def __init__(self, service, res, pattern, tickets, Bb,
                 t_flush, t_dispatch, plan=None, entry=None,
                 retry=None):
        self._service = service
        self.res = res
        self.pattern = pattern
        self.tickets = tickets
        self.Bb = Bb
        self.t_flush = t_flush
        self.t_dispatch = t_dispatch
        self.plan = plan  # placement GroupPlan (fetch-time accounting)
        # failover state: the hierarchy entry and the retained host
        # payload (batched vals/b/x0 copies) a device-lost group
        # re-dispatches from, one-shot (serve/service failover)
        self.entry = entry
        self.retry = retry
        self.requeued = False
        self._lock = threading.Lock()
        self._host = None
        self._error = None

    def fetched(self) -> bool:
        """Has the group's one host sync already happened?  Used by
        the deadline short-circuit: once fetched, handing a late
        ticket its result is free — only an UNfetched group may
        convert lateness into a typed deadline failure."""
        with self._lock:
            return self._host is not None

    def _sync_once(self):
        """One attempt at the group's blocking sync + host copy:
        the ``device_lost_fetch`` fault site, then the watchdogged
        ``block_until_ready``, then the device→host copy.  Returns
        ``(host_tree, t_done)``; raises typed ``DeviceLostError`` on
        injected loss or watchdog expiry (the caller's failover
        hook)."""
        from amgx_tpu.core import faults

        label = (
            self.plan.device_label if self.plan is not None else None
        )
        if faults.should_fire("device_lost_fetch"):
            from amgx_tpu.core.errors import DeviceLostError

            raise DeviceLostError(
                "injected device loss at fetch (fault site "
                "device_lost_fetch)",
                device_label=label,
            )
        self._service._watched_block(self.res.x, label)
        t_done = time.perf_counter()
        return _fetch_host(self.res), t_done

    def __del__(self):
        # a group nobody ever fetched (every ticket deadline-expired
        # or was abandoned) must still release its placement
        # reservation — abandon() is idempotent, so a fetched group's
        # finalizer is a no-op
        plan = getattr(self, "plan", None)
        if plan is not None:
            try:
                plan.abandon()
            except Exception:  # noqa: BLE001 — finalizer must not raise
                pass

    def fetch(self):
        with self._lock:
            if self._host is not None:
                return self._host
            if self._error is not None:
                raise self._error
            m = self._service.metrics
            try:
                host, t_done = self._sync_once()
            except BaseException as e:  # noqa: BLE001 — async runtime
                # failure (OOM, XLA runtime error, device loss)
                # surfacing at the fetch, after the staging rows are
                # gone: a DEVICE loss first attempts the one-shot
                # failover requeue from the retained host payload —
                # the groupmates then see a normal (late) success;
                # anything else (or a failed requeue) converts to a
                # typed error for EVERY groupmate (the C API maps it
                # to per-system FAILED statuses)
                from amgx_tpu.core.errors import (
                    AMGXTPUError,
                    DeviceLostError,
                    ResourceError,
                )

                host = None
                label = (
                    self.plan.device_label
                    if self.plan is not None else None
                )
                # real hardware surfaces a lost chip as a jaxlib
                # XlaRuntimeError, not our typed class — classify it
                # here (fetch boundary only) so failover is not an
                # injected-faults-only feature
                dl = self._service._classify_device_loss(e, label)
                if dl is not None:
                    e = dl
                    try:
                        host, t_done = (
                            self._service._failover_refetch(self, e)
                        )
                    except BaseException as e2:  # noqa: BLE001
                        if not isinstance(e2, Exception):
                            # Ctrl-C / SystemExit mid-requeue must
                            # propagate, never demote to a typed
                            # settlement (the PR 9 contract)
                            raise
                        if isinstance(e2, AMGXTPUError):
                            e = e2
                        elif e.__cause__ is None:
                            e.__cause__ = e2
                        else:
                            # keep the ROOT device failure as the
                            # cause chain (the classified runtime
                            # error is what started the incident);
                            # the secondary requeue error rides along
                            # for diagnostics without erasing it
                            e.requeue_error = e2
                if host is None:
                    if isinstance(e, AMGXTPUError):
                        err = e
                    else:
                        err = ResourceError(
                            "batched group execution failed after "
                            f"dispatch: {type(e).__name__}: {e}"
                        )
                        err.__cause__ = e
                    self._error = err
                    self.res = None  # drop the poisoned buffers
                    self.retry = None  # terminal: no further requeue
                    self.entry = None
                    m.inc("failed_groups")
                    if self.plan is not None:
                        try:
                            self.plan.abandon()  # release the slot
                        except Exception:  # noqa: BLE001 — placement
                            # telemetry must not mask the failure
                            m.inc("telemetry_errors")
                    if (
                        not isinstance(err, DeviceLostError)
                        or getattr(err, "inferred", False)
                    ):
                        # a CERTAIN chip loss (injected, watchdog) is
                        # not the pattern's fault — only the device
                        # breaker trips.  An INFERRED loss (classified
                        # runtime error) charges both breakers: if the
                        # pattern itself is the poison, its own
                        # breaker must still open.
                        self._service._breaker_failure(
                            self.pattern.fingerprint
                        )
                    raise err
            t_fetch = time.perf_counter()
            self._host = host
            self.res = None  # host copy cached: free the device batch
            # the group settled: the failover payload (full batched
            # host copies) and the entry ref are dead weight — tickets
            # keep this _BatchResult alive until they are collected
            self.retry = None
            self.entry = None
            device_s = max(t_done - self.t_dispatch, 0.0)
            fetch_s = t_fetch - t_done
            dispatch_s = self.t_dispatch - self.t_flush
            pat = self.pattern
            m.inc("host_syncs")
            if self.plan is not None:
                try:
                    # placement accounting (per-device busy seconds,
                    # mesh psum totals) — degrade, never fail a fetch
                    self.plan.on_fetch(host, device_s)
                except Exception:  # noqa: BLE001
                    m.inc("telemetry_errors")
            m.add_time("device_busy_s", device_s)
            m.add_time("host_busy_s", fetch_s)
            m.record_batch(
                (pat.nb, pat.nnzb, self.Bb),
                device_s,
                len(self.tickets),
                self.Bb - len(self.tickets),
            )
            m.inc("solved", len(self.tickets))
            m.inc("padded_elems", self.Bb * pat.nb)
            m.inc("real_elems", len(self.tickets) * pat.n)
            # per-tenant device-seconds (fleet cost accounting, first
            # slice of ROADMAP item 2): the group's device time splits
            # evenly across its live tickets, accumulated per
            # (tenant, lane) — folded locally so the whole group costs
            # ONE metrics-lock acquisition
            share = device_s / len(self.tickets)
            tenant_shares: dict = {}
            rec_on = telemetry_enabled()
            if rec_on:
                # hoist everything shared or vectorizable out of the
                # per-ticket loop: one wall clock, list-ified status /
                # iteration arrays, and one vectorized residual max —
                # the loop body then only CONSTRUCTS records (batched
                # into the recorder under one lock by extend(); this
                # is the path the ci/telemetry_check.py ≤3% overhead
                # ceiling measures)
                ts_now = time.time()
                iters_l = np.asarray(host.iters).tolist()
                status_l = np.asarray(host.status).tolist()
                fn = np.asarray(host.final_norm)
                fn_max = fn.reshape(fn.shape[0], -1).max(axis=1)
                recs = []
            for t in self.tickets:
                total = max(t_fetch - t._t_submit, 0.0)
                stages = {
                    "queue": max(
                        self.t_flush - t._t_submit - t._pad_s, 0.0
                    ),
                    "pad": t._pad_s,
                    "dispatch": dispatch_s,
                    "device": device_s,
                    "fetch": fetch_s,
                    "total": total,
                }
                m.record_ticket(stages)
                m.record_lane(t._lane, total)
                tk = (t._tenant, t._lane)
                tenant_shares[tk] = tenant_shares.get(tk, 0.0) + share
                ctx = t._trace
                if ctx is not None:
                    # the ticket's tail spans only materialize at the
                    # group's one fetch — device is dispatch->ready,
                    # fetch is the host copy (both shared groupwide)
                    tracing.record_span(
                        "queue", t._t_submit + t._pad_s, self.t_flush,
                        ctx,
                    )
                    tracing.record_span(
                        "device", self.t_dispatch, t_done, ctx
                    )
                    tracing.record_span("fetch", t_done, t_fetch, ctx)
                if rec_on:
                    i = t._row
                    recs.append(SolveRecord(
                        ts=ts_now,
                        fingerprint=pat.fingerprint,
                        config=self._service.cfg_key,
                        lane=t._lane,
                        tenant=t._tenant,
                        iterations=iters_l[i],
                        final_residual=float(fn_max[i]),
                        status=status_l[i],
                        stages=stages,
                        path="batched",
                        trace_id=(
                            ctx.trace_id if ctx is not None else None
                        ),
                    ))
            for (tn, ln), s in tenant_shares.items():
                m.record_tenant_device(tn, ln, s)
            if rec_on and recs:
                self._service._flight_record_many(recs)
            return self._host

    def result_for(self, ticket: SolveTicket) -> SolveResult:
        host = self.fetch()
        i = ticket._row
        n = self.pattern.n
        return SolveResult(
            x=host.x[i, :n],
            iters=host.iters[i],
            status=host.status[i],
            final_norm=host.final_norm[i],
            initial_norm=host.initial_norm[i],
            history=host.history[i],
        )


class BatchedSolveService:
    """Shape-bucketed, vmapped multi-system solver frontend.

    Parameters
    ----------
    config: AMGConfig | JSON/kv string | None — solver configuration
        shared by every request (the service IS one config; run several
        services for several configs).  Default: Jacobi-PCG.
    max_batch: flush a group when it reaches this many requests.
    max_wait_s: flush a group this long after its first request
        (enforced by poll()/flush(); start() runs a background poller).
    queue_limit: bound on total queued requests; reaching it flushes
        everything (backpressure, never unbounded memory).
    validate: reject non-finite uploads at submit() with a typed
        SetupError instead of letting one poisoned request fail (or
        quarantine) its whole batch group later (``validation_rejects``
        counter).
    breaker_threshold: per-fingerprint circuit breaker — after this
        many consecutive group failures for one pattern, batching is
        bypassed for that pattern and its requests run in per-request
        isolation (``breaker_trips`` / ``breaker_bypasses`` counters;
        a successful batched group resets the count).
    breaker_probe_every: half-open probe cadence shared by the
        fingerprint breaker AND the placement device breakers — every
        Nth attempt against an open breaker is admitted as the probe
        whose success closes it.  None resolves
        ``AMGX_TPU_BREAKER_PROBE_EVERY`` (default 8).
    fetch_watchdog_s: wall-clock bound on a dispatched group's one
        blocking fetch (failure domains, doc/ROBUSTNESS.md): past it
        the fetch settles with a typed ``DeviceLostError`` (and the
        group requeues through the placement degrade chain) instead
        of blocking ``result()``/``drain()`` on a hung chip forever.
        None resolves ``AMGX_TPU_FETCH_WATCHDOG_S`` (default 120);
        <= 0 disables (the sync runs inline, the pre-watchdog path).
    failover: retain a host copy of each dispatched group's batched
        arrays so a device lost AFTER dispatch can requeue once
        (affinity → least-loaded healthy chip → smaller mesh layout →
        single-device retry); without it a post-dispatch device loss
        settles every groupmate typed.  Costs one host memcpy of the
        batched vals/b(+x0) per flush, freed at the group's fetch —
        turn it off for huge groups where typed settlement on loss is
        acceptable.  None resolves ``AMGX_TPU_FAILOVER`` (default
        on).
    store: setup-artifact store for warm-boot serving (PR 4): a
        :class:`~amgx_tpu.store.store.ArtifactStore` or a directory
        path.  Every hierarchy entry this service builds is exported
        to the store in the background; :meth:`warm_boot` repopulates
        the hierarchy cache from it at startup so previously-seen
        fingerprints serve their first group without a rebuild.  When
        set, JAX's persistent compilation cache is pointed at
        ``<root>/xla_cache`` (``AMGX_TPU_XLA_CACHE=0`` opts out) so
        restored buckets can skip XLA compiles too.
    donate: donate the batched x0 buffer to the compiled solve
        (``donate_argnums``) so XLA writes the solution in place
        instead of allocating a fresh (B, n) output per flush.  The
        service always owns that buffer, so donation is always SAFE;
        the default (None) follows the platform
        (:func:`amgx_tpu.solvers.base.donation_enabled`: accelerators
        donate, CPU doesn't — donation serializes CPU dispatch and
        would defeat the async pipeline).  True/False force it, e.g.
        for the bitwise donation-on/off A/B test in
        tests/test_serve.py.
    placement: device-placement policy (:mod:`amgx_tpu.serve.placement`)
        — a :class:`~amgx_tpu.serve.placement.PlacementPolicy`
        instance, a spec string (``"single"`` / ``"mesh[:N]"`` /
        ``"affinity"``), or None to resolve ``AMGX_TPU_PLACEMENT``
        (unset = single-device, bitwise the pre-placement behavior).
        ``MeshPlacement`` shards each group's batch axis over the
        visible chips via ``shard_map``; ``AffinityPlacement`` routes
        whole groups to the chip whose caches are warm for their
        fingerprint.  See doc/MESH.md.
    """

    def __init__(
        self,
        config=None,
        max_batch: int = 32,
        max_wait_s: float = 0.02,
        queue_limit: int = 1024,
        cache_entries: int = 64,
        validate: bool = True,
        breaker_threshold: int = 3,
        breaker_probe_every: Optional[int] = None,
        donate: Optional[bool] = None,
        store=None,
        placement=None,
        fetch_watchdog_s: Optional[float] = None,
        failover: Optional[bool] = None,
    ):
        if config is None:
            config = DEFAULT_CONFIG
        if isinstance(config, str):
            config = AMGConfig.from_string(config)
        self.cfg = config
        self.cfg_key = config_hash(config)
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.queue_limit = int(queue_limit)
        self.metrics = ServeMetrics()
        self.cache = HierarchyCache(
            max_entries=cache_entries, metrics=self.metrics,
            on_evict=self._on_hierarchy_evict,
        )
        self.store = None
        self._store_futures: list = []
        if store is not None:
            import os

            from amgx_tpu.store.store import ArtifactStore

            self.store = (
                store
                if isinstance(store, ArtifactStore)
                else ArtifactStore(store)
            )
            if os.environ.get("AMGX_TPU_XLA_CACHE", "1") != "0":
                from amgx_tpu.store.warmboot import (
                    enable_persistent_compile_cache,
                )

                enable_persistent_compile_cache(
                    os.path.join(self.store.root, "xla_cache")
                )
        self.donate = donate
        self.compile_cache = CompileCache(
            metrics=self.metrics, donate=donate
        )
        self._lock = threading.RLock()
        self._groups: dict = {}
        self._queued = 0
        self._patterns: dict = {}
        self._staging: dict = {}
        # device-resident zero warm-start blocks, shared across flushes
        # (and across same-shape patterns) when no request warm-starts
        # and donation is off — one device_put saved per flush
        self._zeros_x0: dict = {}
        # signature -> batch bucket of its last flush (warm-up target)
        self._last_bucket: dict = {}
        self._poller: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.validate = bool(validate)
        self.breaker_threshold = int(breaker_threshold)
        # half-open probe cadence shared by the per-fingerprint breaker
        # and the placement device breakers: param wins, then the
        # AMGX_TPU_BREAKER_PROBE_EVERY env knob, then the default 8
        # (instance attribute shadows the class-constant fallback)
        from amgx_tpu.serve.placement.health import (
            breaker_probe_every as _probe_cadence,
        )

        self._BREAKER_PROBE_EVERY = _probe_cadence(breaker_probe_every)
        # failure-domain resilience (doc/ROBUSTNESS.md "Failure
        # domains"): fetch_watchdog_s bounds the wall-clock wait of a
        # group's one host sync (a hung chip settles typed and
        # requeues; <=0 disables and the sync runs inline, the
        # pre-watchdog path); failover keeps a host copy of each
        # dispatched group's batched arrays so a device lost AFTER
        # dispatch can requeue through the placement degrade chain
        self.fetch_watchdog_s = (
            _env_float("AMGX_TPU_FETCH_WATCHDOG_S", 120.0)
            if fetch_watchdog_s is None
            else float(fetch_watchdog_s)
        )
        import os as _os

        self.failover = (
            _os.environ.get("AMGX_TPU_FAILOVER", "1") != "0"
            if failover is None
            else bool(failover)
        )
        # circuit breaker: padded fingerprint -> consecutive group
        # failures; fingerprints in _broken bypass batching (with a
        # periodic half-open probe so transient failures don't cost a
        # pattern its batching forever)
        self._fail_counts: dict = {}
        self._broken: set = set()
        self._bypass_counts: dict = {}
        # solve flight recorder + registry registration (telemetry
        # tentpole): the recorder's incident snapshots read this
        # service's own metrics; the registry holds only a weakref,
        # so registration never extends the service's lifetime
        self.recorder = FlightRecorder(
            snapshot_fn=self.metrics.snapshot
        )
        # device placement (serve/placement): WHERE a flushed group
        # runs — None resolves AMGX_TPU_PLACEMENT (unset = the
        # behavior-identical single-device default); stateful policies
        # (mesh/affinity) register their per-device telemetry source
        from amgx_tpu.serve.placement import resolve_placement

        self.placement = resolve_placement(placement)
        if (
            breaker_probe_every is not None
            and getattr(self.placement, "health", None) is not None
        ):
            # the documented "one cadence knob for both breaker
            # families" contract: an EXPLICIT service param overrides
            # the policy board's env/default resolution (a policy
            # constructed with its own explicit probe_every and no
            # service param keeps its setting)
            self.placement.health.probe_every = (
                self._BREAKER_PROBE_EVERY
            )
        if self.placement.telemetry_kind is not None:
            self.placement.telemetry_name = get_registry().register(
                self.placement.telemetry_kind, self.placement
            )
        self.telemetry_name = get_registry().register("serve", self)

    # ------------------------------------------------------------------
    # telemetry

    def telemetry_snapshot(self) -> dict:
        """Registry source (kind="serve"): the full metrics snapshot —
        counters, caches, latency/lane reservoirs, phase profile —
        plus the hierarchy cache's resident bytes by dtype (the
        mixed-precision halved-bytes observability)."""
        snap = self.metrics.snapshot()
        try:
            snap["hierarchy_bytes"] = self.cache.bytes_by_dtype()
        except Exception:  # noqa: BLE001 — telemetry never fails
            pass
        try:
            snap["hierarchy_format_bytes"] = self.cache.bytes_by_format()
        except Exception:  # noqa: BLE001 — telemetry never fails
            pass
        return snap

    def _flight_record(self, **fields):
        """Record one solve into the flight recorder, degrading any
        failure (including the ``telemetry_export`` fault) to a
        counted ``telemetry_errors`` — telemetry never fails a
        solve."""
        try:
            self.recorder.record(**fields)
        except BaseException:  # noqa: BLE001 — degrade, never raise
            self.metrics.inc("telemetry_errors")

    def _flight_record_many(self, recs):
        """Batched flight-record append (one lock for a whole fetch
        group); a failure counts one ``telemetry_errors`` PER lost
        record, preserving the per-solve error accounting."""
        try:
            self.recorder.extend(recs)
        except BaseException:  # noqa: BLE001 — degrade, never raise
            self.metrics.inc("telemetry_errors", len(recs))

    def _flight_incident(self, kind: str, detail: str = "",
                         record=None):
        """Capture one incident (quarantine / breaker trip / shed /
        deadline expiry), same degrade contract as _flight_record."""
        if not telemetry_enabled():
            return
        try:
            self.recorder.incident(kind, detail=detail, record=record)
        except BaseException:  # noqa: BLE001 — degrade, never raise
            self.metrics.inc("telemetry_errors")

    # ------------------------------------------------------------------
    # submission

    # sentinel: distinguishes "no front-end minted a trace — mint one
    # here if sampling says so" from "the gateway already made the
    # sampling decision (possibly None)"
    _TRACE_UNSET = object()

    def submit(self, A, b, x0=None, deadline_s=None,
               lane: str = "interactive", tenant: str = "default",
               _host=None, _trace=_TRACE_UNSET) -> SolveTicket:
        """Queue one system; returns a ticket.  ``A`` is a SparseMatrix
        or scipy sparse matrix (scalar block size).

        ``deadline_s`` (optional, seconds from now) is enforced
        END-TO-END: an already-expired deadline is rejected right here
        with a typed :class:`DeadlineExceededError`; a deadline that
        passes while queued fails THIS ticket at flush while the rest
        of the group proceeds; and a deadline that passes before the
        result is fetched short-circuits ``ticket.result()`` instead
        of blocking on the device.

        ``lane`` ("interactive" | "batch") is the priority lane:
        groups never mix lanes, and at flush-group formation
        interactive groups preempt batch groups (batch is
        starvation-protected by an aging credit —
        ``_BATCH_AGING_FACTOR`` × max_wait_s promotes a passed-over
        batch group to interactive rank, counted by
        ``batch_promotions``)."""
        t_submit = time.perf_counter()
        # trace context: the gateway mints and passes one (or None);
        # direct service callers sample here.  new_trace() is a float
        # compare when tracing is off.
        ctx = (
            tracing.new_trace()
            if _trace is self._TRACE_UNSET
            else _trace
        )
        if deadline_s is not None and float(deadline_s) <= 0.0:
            from amgx_tpu.core.errors import DeadlineExceededError

            self.metrics.inc("deadline_expired")
            self._flight_incident(
                "deadline_expired", detail="dead on arrival at submit"
            )
            raise DeadlineExceededError(
                f"deadline_s={float(deadline_s):g} already expired at "
                "submit"
            )
        # _host: pre-extracted (ro, ci, vals, n, raw_fp) from a
        # front-end that already ran _host_csr for its own admission
        # gates (the gateway's breaker shed) — don't extract twice
        ro, ci, vals, n, raw_fp = (
            _host if _host is not None else _host_csr(A)
        )
        if self.validate:
            # typed rejection at the door: one poisoned request must
            # never reach a batch group (guardrails acceptance)
            from amgx_tpu.core.errors import NonFiniteValuesError

            bad = not np.all(np.isfinite(vals))
            bad = bad or (b is not None
                          and not np.all(np.isfinite(np.asarray(b))))
            bad = bad or (x0 is not None
                          and not np.all(np.isfinite(np.asarray(x0))))
            if bad:
                self.metrics.inc("validation_rejects")
                raise NonFiniteValuesError(
                    "BatchedSolveService.submit: system contains "
                    "NaN/Inf (validation reject)"
                )
        pattern = self._pattern_for(ro, ci, n, raw_fp)
        dtype, dtype_s = _resolve_dtype(vals.dtype)
        key = (pattern.fingerprint, dtype_s, lane)
        flush_now = []
        new_group = False
        with self._lock:
            now_mono = time.monotonic()
            grp = self._groups.get(key)
            if grp is None:
                grp = _Group(
                    key=key,
                    pattern=pattern,
                    dtype=dtype,
                    requests=[],
                    deadline=now_mono + self.max_wait_s,
                    slot=self._acquire_slot(key, pattern, dtype),
                    lane=lane,
                    created=now_mono,
                )
                self._groups[key] = grp
                new_group = True
            ticket = SolveTicket(
                _service=self,
                _group_key=key,
                _row=len(grp.requests),
                _pattern=pattern,
            )
            ticket._t_submit = t_submit
            ticket._lane = lane
            ticket._tenant = tenant
            ticket._trace = ctx
            if deadline_s is not None:
                ticket._deadline = now_mono + float(deadline_s)
            req = _Request(
                ticket=ticket,
                row=ticket._row,
                deadline=ticket._deadline,
            )
            grp.requests.append(req)
            self._queued += 1
            self.metrics.inc("submitted")
            self.metrics.set_gauge("queue_depth", self._queued)
            if len(grp.requests) >= self.max_batch:
                flush_now.append(self._take_group(key))
            elif self._queued >= self.queue_limit:
                # backpressure flush-all: interactive groups still go
                # first (priority holds under pressure too)
                flush_now.extend(
                    self._take_group(k)
                    for k in self._ordered_keys(now_mono)
                )
        # pad: write the request into its staging row — OUTSIDE the
        # lock (the row is exclusively this thread's until the group
        # flushes; the flusher waits on req.ready)
        t0 = time.perf_counter()
        try:
            # ambient ctx: trace_range/setup_phase spans fired inside
            # this block attribute to THIS request's trace
            with tracing.use_context(ctx), trace_range("serve_submit"):
                grp.slot.write_row(req.row, vals, b, x0)
        except BaseException as e:
            # malformed request (wrong length, bad dtype): fail ONLY
            # this ticket; its garbage row rides along inert.  Any
            # groups already taken for flushing MUST still execute —
            # their tickets would otherwise spin forever.
            ticket._error = e
            ticket._done = True
            req.ready = True
            for g in flush_now:
                self._execute_group(g)
            raise
        req.ready = True
        ticket._pad_s = time.perf_counter() - t0
        # locked accumulate: submit threads, the flusher, and the
        # dispatch worker all write this profile concurrently
        self.metrics.profile.add("pad", ticket._pad_s)
        if ctx is not None:
            tracing.record_span(
                "pad", t0, t0 + ticket._pad_s, ctx
            )
            if _trace is self._TRACE_UNSET:
                # direct service use: this call is the trace root
                tracing.record_span(
                    "submit", t_submit, time.perf_counter(), ctx,
                    args={"lane": lane, "tenant": tenant},
                    root=True,
                )
        if new_group:
            self._maybe_warm(pattern, dtype)
        for g in flush_now:
            self._execute_group(g)
        return ticket

    def solve_many(self, systems):
        """Synchronous convenience: submit every (A, b[, x0]) tuple,
        flush, and return the per-system SolveResults in order."""
        tickets = [self.submit(*sys) for sys in systems]
        self.flush()
        return [t.result() for t in tickets]

    def prewarm(self, A, batch: Optional[int] = None):
        """Eliminate a pattern's cold start in the background: build
        (or fetch) the hierarchy entry for ``A``'s sparsity and
        AOT-compile its batched solve for the ``batch`` bucket
        (default: this service's max_batch), all on the shared compile
        worker — no flush ever head-of-line-blocks behind it."""
        ro, ci, vals, n, raw_fp = _host_csr(A)
        pattern = self._pattern_for(ro, ci, n, raw_fp)
        dtype = _resolve_dtype(vals.dtype)[0]
        Bb = bucket_batch(self.max_batch if batch is None else batch)
        vals = np.asarray(vals).copy()

        def job():
            try:
                entry = self.cache.get_or_build(
                    pattern,
                    self.cfg_key,
                    dtype,
                    lambda: self._build_entry(pattern, vals, dtype),
                )
                if entry.batch_fn is not None:
                    self.placement.warm(self, entry, Bb)
                self.metrics.inc("prewarms")
            except BaseException:  # noqa: BLE001 — warm-up best-effort
                self.metrics.inc("prewarm_failures")

        _compile_pool().submit(job)

    # ------------------------------------------------------------------
    # flushing

    # a batch-lane group passed over this long (x max_wait_s) gains
    # its aging credit and sorts with interactive rank — starvation
    # protection for the low-priority lane
    _BATCH_AGING_FACTOR = 8

    def _lane_rank(self, grp: _Group, now: float) -> int:
        """0 = flush first (interactive, or an aged batch group whose
        starvation credit promotes it), 1 = batch."""
        if grp.lane != "batch":
            return 0
        if grp.promoted:
            return 0
        if (
            now - grp.created
            >= self.max_wait_s * self._BATCH_AGING_FACTOR
        ):
            grp.promoted = True
            self.metrics.inc("batch_promotions")
            return 0
        return 1

    def _ordered_keys(self, now: float) -> list:
        """Group keys in flush order (caller holds the lock):
        interactive preempts batch at flush-group formation; within a
        rank, oldest max-wait deadline first."""
        return sorted(
            self._groups,
            key=lambda k: (
                self._lane_rank(self._groups[k], now),
                self._groups[k].deadline,
            ),
        )

    def flush(self):
        """Execute every queued group now (dispatch completes before
        return; results are fetched lazily by the tickets).
        Interactive-lane groups dispatch before batch-lane groups."""
        now = time.monotonic()
        with self._lock:
            groups = [
                self._take_group(k) for k in self._ordered_keys(now)
            ]
        for grp in groups:
            self._execute_group(grp)

    def poll(self):
        """Execute groups whose max-wait deadline has passed, in lane
        order.  Interactive preemption is REAL here, not just
        ordering: while any interactive group is due, due batch
        groups are deferred to a later poll (``batch_deferrals``) so
        the single-worker dispatch stage serves the interactive lane
        first — bounded by the aging credit, which promotes a batch
        group after ``_BATCH_AGING_FACTOR x max_wait_s`` so sustained
        interactive pressure can never starve it.  Poller flushes
        don't wait for the dispatch stage — padding of the next group
        proceeds while the worker ships this one."""
        now = time.monotonic()
        with self._lock:
            due_keys = [
                k for k in self._ordered_keys(now)
                if self._groups[k].deadline <= now
            ]
            interactive_pressure = any(
                self._groups[k].lane != "batch" for k in due_keys
            )
            due = []
            for k in due_keys:
                g = self._groups[k]
                if (
                    interactive_pressure
                    and g.lane == "batch"
                    and self._lane_rank(g, now) != 0
                ):
                    self.metrics.inc("batch_deferrals")
                    continue
                due.append(self._take_group(k))
        for grp in due:
            self._execute_group(grp, wait_dispatch=False)

    def start(self, interval_s: float = 0.005):
        """Run a daemon poller enforcing max_wait_s in the background."""
        if self._poller is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                self.poll()

        self._poller = threading.Thread(
            target=loop, name="serve-poller", daemon=True
        )
        self._poller.start()

    def stop(self):
        if self._poller is None:
            return
        self._stop.set()
        self._poller.join()
        self._poller = None
        self.flush()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------------
    # internals

    _PATTERN_CACHE_MAX = 512
    # double-buffered staging: two resident slots per group key so the
    # next group pads while the previous one ships
    _STAGING_SLOTS_PER_KEY = 2

    def _pattern_for(self, ro, ci, n, raw_fp) -> PaddedPattern:
        """Padded pattern for a raw fingerprint, cached: re-padding on
        every submission would cost O(nnz log nnz) host work per
        request — more than the batched solve itself for small
        systems."""
        with self._lock:
            pat = self._patterns.get(raw_fp)
        if pat is not None:
            return pat
        pat = pad_pattern(ro, ci, n)
        with self._lock:
            if len(self._patterns) >= self._PATTERN_CACHE_MAX:
                self._patterns.clear()
            self._patterns[raw_fp] = pat
        return pat

    def _acquire_slot(self, key, pattern, dtype) -> StagingSlot:
        """Resident staging slot for a new group (caller holds the
        lock).  Reuses a free pooled slot; allocates (and pools, up to
        the double-buffer depth) otherwise."""
        pool = self._staging.setdefault(key, [])
        for s in pool:
            if not s.in_use:
                s.in_use = True
                s.x0_used = False
                self.metrics.inc("staging_reuses")
                return s
        s = StagingSlot(pattern, dtype, bucket_batch(self.max_batch))
        s.in_use = True
        if len(pool) < self._STAGING_SLOTS_PER_KEY:
            pool.append(s)
        else:
            self.metrics.inc("staging_overflows")
        if len(self._staging) > self._PATTERN_CACHE_MAX:
            for k in list(self._staging):
                if k != key and not any(
                    x.in_use for x in self._staging[k]
                ):
                    del self._staging[k]
        return s

    def _release_slot(self, slot: StagingSlot):
        with self._lock:
            slot.in_use = False

    def _release_group_slot(self, grp: "_Group"):
        """Release a group's slot exactly once: `grp.slot` is the
        ownership token — whoever nulls it did the release, so a
        failure path running after a success-path release can't free
        (or read) a slot a newer group already owns."""
        slot, grp.slot = grp.slot, None
        if slot is not None:
            self._release_slot(slot)

    def _maybe_warm(self, pattern: PaddedPattern, dtype):
        """Background AOT warm-up at group creation: if this pattern's
        hierarchy is already cached, schedule the compile for its
        last-seen batch bucket now, so it overlaps the group's queue
        wait instead of blocking its flush."""
        entry = self.cache.peek(pattern.fingerprint, self.cfg_key, dtype)
        if entry is None or entry.batch_fn is None:
            return
        bb = self._last_bucket.get(entry.signature)
        if bb:
            self.placement.warm(self, entry, bb)

    # total bytes the batched dense copies may occupy (B x nb x nb);
    # above it a non-ELL bucket stays CSR (segment-sum SpMV)
    _DENSE_BUDGET_MB = 256
    # padded max row length up to which the ELL structure is used
    _ELL_MAX_WIDTH = 64

    def _accel_for(self, pat: PaddedPattern) -> tuple:
        """Bucket-safe acceleration formats for a padded pattern.

        Preference order mirrors ops.spmv: DIA for stencil-shaped
        patterns (slice + FMA, no gathers — gathers and scatters are
        the slow ops on both CPU XLA and TPU), then ELL (gather + FMA,
        nnz-proportional work), then dense (batched GEMV, n^2 work,
        small buckets within the byte budget), then CSR segment-sum.
        DIA's offsets are static metadata, so DIA entries share a
        compiled program only with matching-offset patterns; the
        same-fingerprint compile-reuse guarantee is unaffected."""
        import os

        from amgx_tpu.core.matrix import dia_gate

        if dia_gate(pat.num_diagonals, pat.nb, pat.nnzb):
            return ("dia",)
        w = pat.max_row_len
        if 0 < w <= self._ELL_MAX_WIDTH and w * pat.nb <= 4 * pat.nnzb:
            return ("ell",)
        budget = (
            int(
                os.environ.get(
                    "AMGX_TPU_SERVE_DENSE_MB", self._DENSE_BUDGET_MB
                )
            )
            * 2**20
        )
        bb = bucket_batch(self.max_batch)
        if bb * pat.nb * pat.nb * 8 <= budget:
            return ("dense",)
        return ()

    def _take_group(self, key) -> _Group:
        """Remove a group from the queue (caller holds the lock)."""
        grp = self._groups.pop(key)
        self._queued -= len(grp.requests)
        self.metrics.set_gauge("queue_depth", self._queued)
        return grp

    def _flush_group_of(self, ticket: SolveTicket):
        with self._lock:
            grp = self._groups.get(ticket._group_key)
            if grp is None or ticket not in [
                r.ticket for r in grp.requests
            ]:
                grp = None
            else:
                grp = self._take_group(ticket._group_key)
        if grp is not None:
            self._execute_group(grp)
        elif not ticket._done:
            # another thread is executing the group right now
            while not ticket._done:
                time.sleep(0.001)

    @staticmethod
    def _wait_ready(grp: _Group):
        """Staging-row writes happen outside the service lock; the
        flusher's host stage waits (µs-scale) until every submitter in
        the group has finished its write."""
        for r in grp.requests:
            while not r.ready:
                time.sleep(0.0001)

    def _build_entry(
        self, pattern: PaddedPattern, values, dtype
    ) -> HierarchyEntry:
        """One solver setup for this padded pattern (hierarchy-cache
        miss path) from a representative coefficient set ``values``
        (original (nnz,) layout)."""
        import amgx_tpu.solvers  # noqa: F401 — registry side effects
        import amgx_tpu.amg  # noqa: F401 — registers "AMG"
        from amgx_tpu.solvers.registry import create_solver, make_nested

        with self.metrics.profile.phase("setup"):
            A = pattern.template_matrix(
                values,
                dtype,
                accel_formats=self._accel_for(pattern),
            )
            # make_nested: the service owns the solve boundary — no
            # per-solver rescaling/renumbering of padded systems
            solver = make_nested(create_solver(self.cfg, "default"))
            solver.setup(A)
            bp = solver.make_batch_params()
            batch_fn = make_batched_solve(solver)
            template = bp[0] if bp is not None else None
            sig = (
                template_signature(template)
                if batch_fn is not None
                else None
            )
        # cold-miss setup anatomy: fold the solver's per-phase setup
        # profile (strength/aggregation/interp/rap/transfer/finalize,
        # PR 5) into the service profile so serve metrics show WHERE a
        # cold group's setup time went, not just that it happened
        for k, v in solver.collect_setup_profile().items():
            # floats only: the profile also carries integer COUNTERS
            # (syncs, transfer_batches/arrays) that must not land in a
            # seconds-denominated phase table
            if isinstance(v, float):
                self.metrics.profile.add(f"setup:{k}", v)
        entry = HierarchyEntry(
            solver=solver,
            template=template,
            batch_fn=batch_fn,
            signature=sig,
            pattern=pattern,
        )
        self._export_entry(entry, dtype)
        return entry

    def resetup_entry(self, fingerprint: str, values, dtype=None,
                      *, b=None, x0=None):
        """Public values-only resetup of a CACHED hierarchy entry —
        the serve-level ``AMGX_solver_resetup``: re-embeds ``values``
        (original ``(nnz,)`` layout) into the pattern's padded
        template, then refreshes the cached template solver in place
        (``replace_values`` gather maps + RAP-plan re-execution +
        the PR 8 spectral-bound cache with its ``reestimate_eigs``
        cadence).  Streaming sessions (:mod:`amgx_tpu.sessions`) call
        this on their resetup cadence, and the quarantine path's
        entry reuse is the same helper — one code path for "refresh
        the shared hierarchy with new coefficients".

        ``fingerprint`` is either the RAW sparsity fingerprint of a
        submitted matrix or the PADDED pattern fingerprint (the
        hierarchy-cache key); raw fingerprints resolve through the
        pattern cache.  Raises ``KeyError`` when no entry is cached
        for it under this service's config.

        With ``b`` (padded or original length), the refreshed solver
        also runs one isolated solve INSIDE the same critical section
        (resetup+solve must not interleave with another caller's
        resetup) and returns its SolveResult; otherwise returns None.
        """
        dtype = (
            _resolve_dtype(np.asarray(values).dtype)[0]
            if dtype is None else np.dtype(dtype)
        )
        with self._lock:
            pat = self._patterns.get(fingerprint)
        fp = pat.fingerprint if pat is not None else fingerprint
        entry = self.cache.peek(fp, self.cfg_key, dtype)
        if entry is None:
            raise KeyError(
                f"no cached hierarchy entry for fingerprint "
                f"{str(fingerprint)[:16]}... under this service's "
                "config/dtype"
            )
        pat = entry.pattern
        values = np.asarray(values).reshape(-1)
        old = entry.solver.A
        if (
            old is not None
            and getattr(old, "nnz", None) == pat.nnzb
            and np.dtype(old.values.dtype) == dtype
        ):
            # the true values-only path: one scatter embed + the
            # replace_values gather maps of the EXISTING template —
            # no host-side acceleration-structure rebuild (from_csr
            # re-derives ELL/DIA/dense metadata, which costs more
            # than the refreshed solve itself at streaming rates)
            A = old.replace_values(pat.embed_values(values, dtype))
        else:
            A = pat.template_matrix(
                values, dtype, accel_formats=self._accel_for(pat),
            )
        if b is not None:
            bb = np.asarray(b).reshape(-1)
            if bb.shape[0] == pat.n:
                bb = pat.embed_vector(bb, dtype)
            if x0 is not None:
                x0 = np.asarray(x0).reshape(-1)
                if x0.shape[0] == pat.n:
                    x0 = pat.embed_vector(x0, dtype)
        # the cached template solver is shared mutable state: the
        # sequential fallback and concurrent quarantine retries
        # resetup it too — one critical section per refresh(+solve)
        with entry.solver_lock:
            entry.solver.resetup(A)
            res = None if b is None else entry.solver.solve(bb, x0=x0)
        self.metrics.inc("entry_resetups")
        return res

    # ------------------------------------------------------------------
    # setup-artifact store (warm-boot serving, amgx_tpu.store)

    def _export_entry(self, entry: HierarchyEntry, dtype):
        """Persist a freshly-built hierarchy entry in the background
        (shared compile worker — never on a flush path).  Best-effort:
        failures count, nothing raises."""
        if self.store is None:
            return

        def job():
            try:
                from amgx_tpu.store.warmboot import export_entry

                ok = export_entry(self, entry, dtype)
                self.metrics.inc(
                    "store_exports" if ok else "store_export_failures"
                )
            except BaseException:  # noqa: BLE001 — persistence is
                # an optimization, never a serve-path liability
                self.metrics.inc("store_export_failures")

        with self._lock:
            self._store_futures = [
                f for f in self._store_futures if not f.done()
            ]
            self._store_futures.append(_compile_pool().submit(job))

    def flush_store(self):
        """Block until every scheduled store export has settled (tests
        and orderly shutdown; the serve path never calls this)."""
        with self._lock:
            futures, self._store_futures = self._store_futures, []
        for f in futures:
            f.result()

    def export_all_entries(self) -> int:
        """Synchronously export EVERY cached hierarchy entry to the
        store (the gateway's drain protocol: hot fingerprints must be
        on disk before the replacement worker boots).  Settles the
        background build-time exports FIRST so entries they already
        persisted are skipped, not re-serialized.  Returns the number
        on disk; without a store, 0."""
        if self.store is None:
            return 0
        from amgx_tpu.store.warmboot import export_all

        self.flush_store()  # settle scheduled background exports
        return export_all(self)

    def warm_boot(self, wait: bool = True, compile: bool = True) -> int:
        """Repopulate the hierarchy cache from the store (see
        :func:`amgx_tpu.store.warmboot.warm_boot`): previously
        persisted fingerprints serve their first group as cache HITS —
        no hierarchy rebuild, and with ``compile=True`` their batched
        solves AOT-warm in the background too."""
        from amgx_tpu.store.warmboot import warm_boot

        return warm_boot(self, wait=wait, compile=compile)

    def _on_hierarchy_evict(self, key, entry: HierarchyEntry):
        """Hierarchy-cache eviction hook: drop the entry's AOT
        executables from the compile cache unless another live entry
        shares the template signature (equal signatures share
        programs)."""
        sig = entry.signature
        try:
            # placement-resident ENTRY state (routed/replicated
            # templates, router warm sets): drop unconditionally
            self.placement.evicted(entry)
        except Exception:  # noqa: BLE001 — eviction housekeeping
            pass
        if sig is None or self.cache.any_with_signature(sig):
            return
        self.compile_cache.evict_signature(sig)
        try:
            # signature-keyed placement executables are shared across
            # equal-signature entries (like the compile cache's), so
            # they fall only with the signature's LAST entry
            self.placement.evict_signature(sig)
        except Exception:  # noqa: BLE001 — eviction housekeeping
            pass
        with self._lock:
            self._last_bucket.pop(sig, None)

    def _expire_deadlines(self, grp: _Group):
        """Fail (only) the tickets whose deadline already passed; their
        staged rows ride along inert while the rest of the group
        executes normally."""
        from amgx_tpu.core.errors import DeadlineExceededError

        now = time.monotonic()
        for r in grp.requests:
            if (
                r.deadline is not None
                and now > r.deadline
                and not r.ticket._done
            ):
                r.ticket._error = DeadlineExceededError(
                    "serve deadline exceeded before execution"
                )
                r.ticket._done = True
                self.metrics.inc("deadline_expired")
                self._flight_incident(
                    "deadline_expired",
                    detail=f"expired while queued (lane {grp.lane})",
                )

    def _breaker_failure(self, fp: str):
        """Count a group failure; trip the breaker at the threshold.
        The already-open check runs UNDER the lock: two concurrent
        group failures crossing the threshold together must produce
        exactly one trip (breaker metrics stay consistent under
        multi-threaded submit — asserted by test_robustness.py)."""
        if self.breaker_threshold <= 0:
            return
        with self._lock:
            if fp in self._broken:
                return
            n = self._fail_counts.get(fp, 0) + 1
            self._fail_counts[fp] = n
            if n >= self.breaker_threshold:
                self._broken.add(fp)
                self.metrics.inc("breaker_trips")
                self.metrics.set_gauge(
                    "breakers_open", len(self._broken)
                )
                tripped = True
            else:
                tripped = False
        if tripped:
            # outside the service lock: incident capture snapshots the
            # metrics (which take their own lock)
            self._flight_incident(
                "breaker_trip", detail=f"fingerprint {fp[:16]}..."
            )

    def _breaker_success(self, fp: str):
        """A batched group completed: reset the failure count and — if
        this was a half-open probe — close the breaker."""
        with self._lock:
            self._fail_counts.pop(fp, None)
            if fp in self._broken:
                self._broken.discard(fp)
                self._bypass_counts.pop(fp, None)
                self.metrics.inc("breaker_closes")
                self.metrics.set_gauge(
                    "breakers_open", len(self._broken)
                )

    # every Nth group for an open-breaker pattern retries batching
    # (half-open probe): success closes the breaker, failure keeps it
    # open and recounts toward nothing (already open).  Class-constant
    # FALLBACK only: __init__ sets the instance attribute from the
    # breaker_probe_every param / AMGX_TPU_BREAKER_PROBE_EVERY env
    # knob, shared with the placement device breakers.
    _BREAKER_PROBE_EVERY = 8

    # ------------------------------------------------------------------
    # failure domains: watchdog + device-loss failover

    # the effective fetch watchdog never undercuts this multiple of
    # the observed p99 device time (legitimately long groups must not
    # be typed-failed by a fixed global bound)
    _WATCHDOG_P99_FACTOR = 25.0

    def _watched_block(self, x, device_label=None):
        """The group's one blocking device sync, under the in-flight
        watchdog: with ``fetch_watchdog_s > 0`` the sync runs on a
        pooled worker and a wall-clock expiry raises a typed
        :class:`DeviceLostError` (the hung worker is abandoned — the
        caller's thread, and with it ``result()``/``drain()``, never
        blocks past the watchdog).  Disabled (<= 0), the sync runs
        inline — the exact pre-watchdog path.  The ``fetch_hang``
        fault site simulates the hung chip with a bounded sleep
        (:func:`amgx_tpu.core.faults.hang_seconds`)."""
        from amgx_tpu.core import faults

        hang = faults.should_fire("fetch_hang")
        wd = self.fetch_watchdog_s
        if not wd or wd <= 0:
            if hang:
                time.sleep(faults.hang_seconds())
            return _block_ready(x)
        # adaptive floor: a service whose groups legitimately run long
        # (big hierarchies, saturated chip) must not have healthy
        # fetches typed-failed by a fixed global bound — once device-
        # time history exists, the effective watchdog is at least
        # _WATCHDOG_P99_FACTOR x the observed p99.  (A COLD service
        # has no history: size AMGX_TPU_FETCH_WATCHDOG_S above the
        # largest legitimate first group.)
        p99 = self.metrics.latency_percentile("device", 99.0)
        if p99:
            wd = max(wd, self._WATCHDOG_P99_FACTOR * p99)

        def work():
            if hang:
                time.sleep(faults.hang_seconds())
            return _block_ready(x)

        fut = _fetch_pool().submit(work)
        try:
            return fut.result(timeout=wd)
        except concurrent.futures.TimeoutError:
            from amgx_tpu.core.errors import DeviceLostError

            self.metrics.inc("resilience_watchdog_fires")
            self._flight_incident(
                "watchdog_fire",
                detail=(
                    f"fetch exceeded the {wd:g}s watchdog on device "
                    f"{device_label!r}"
                ),
            )
            raise DeviceLostError(
                f"group fetch exceeded the {wd:g}s in-flight "
                "watchdog (device presumed hung)",
                device_label=device_label,
            ) from None

    @staticmethod
    def _classify_device_loss(e, device_label=None):
        """Map a post-dispatch runtime failure to a typed
        :class:`DeviceLostError` when it plausibly means the DEVICE
        (not the program) failed — the hook that makes failover work
        on real hardware, where a lost chip surfaces as a jaxlib
        ``XlaRuntimeError`` at the fetch, never as our own typed
        class.  Classification runs at the FETCH boundary only: by
        then the executable compiled and launched, so a runtime error
        is device-side by construction (dispatch-time errors may be
        compile/trace problems and are NOT classified — a program bug
        must not trip chip breakers).  Returns the typed error, or
        None to keep the generic typed-ResourceError conversion."""
        from amgx_tpu.core.errors import DeviceLostError

        if isinstance(e, DeviceLostError):
            return e
        name = type(e).__name__
        mod = type(e).__module__ or ""
        if (
            name in ("XlaRuntimeError", "JaxRuntimeError")
            or mod.startswith("jaxlib")
        ):
            msg = str(e)
            # device-OOM is the one common PROGRAM-level runtime
            # failure at this boundary: the group is too big, not the
            # chip dead — requeuing it onto the next chip would OOM
            # there too and serially trip every breaker in the fleet.
            # Keep it on the generic typed path (fingerprint breaker,
            # quarantine isolation).
            if (
                "RESOURCE_EXHAUSTED" in msg
                or "Out of memory" in msg
                or "out of memory" in msg
            ):
                return None
            err = DeviceLostError(
                f"device runtime failure at fetch: {name}: {e}",
                device_label=device_label,
            )
            err.__cause__ = e
            # inferred (not certain) device loss: the failover caller
            # ALSO charges the fingerprint breaker, so a poisonous
            # pattern whose every group dies at runtime still trips
            # its own breaker instead of eating the fleet chip by chip
            err.inferred = True
            return err
        return None

    def _device_loss_attributed(self, plan, exc):
        """Common device-loss bookkeeping: trip the plan's device
        breaker (routing forgets the chip), release its reservation,
        and log the incident.  Degrade-never-raise."""
        if plan is not None:
            try:
                plan.device_failure(exc)
            except Exception:  # noqa: BLE001 — health accounting must
                self.metrics.inc("telemetry_errors")
            try:
                plan.abandon()
            except Exception:  # noqa: BLE001
                self.metrics.inc("telemetry_errors")
        self._flight_incident(
            "device_failover",
            detail=(
                f"device "
                f"{getattr(plan, 'device_label', None)!r} lost: "
                f"{type(exc).__name__}: {exc}"
            ),
        )

    def _failover_replan(self, plan, exc, entry, Bb):
        """Dispatch-side failover: the launch lost its device — trip
        it and resolve a fresh plan through the placement degrade
        chain (affinity re-routes to the least-loaded healthy chip; a
        mesh shrinks to its healthy prefix; single-device retries in
        place).  The caller re-ships the still-staged group through
        the new plan exactly once."""
        self._device_loss_attributed(plan, exc)
        self.metrics.inc("resilience_failovers")
        return self.placement.plan(self, entry, Bb)

    def _failover_refetch(self, batch, exc):
        """Fetch-side failover: the device died (or hung past the
        watchdog) AFTER dispatch, with the staging slot long released
        — re-dispatch the group from its retained host payload on a
        fresh plan and perform the replacement fetch inline (the
        caller is already inside the group's one blocking fetch).
        One-shot: a second loss, or a group dispatched without a
        retained payload (``failover=False``), re-raises typed."""
        from amgx_tpu.core.errors import DeviceLostError

        self._device_loss_attributed(batch.plan, exc)
        retry = batch.retry
        if retry is None or batch.requeued or batch.entry is None:
            raise exc
        batch.requeued = True
        batch.res = None  # the lost device's handles are dead weight
        self.metrics.inc("resilience_failovers")
        entry, Bb, pat = batch.entry, batch.Bb, batch.pattern
        nplan = None
        try:
            # inside the try: a failing replan (compile error on the
            # shrunk layout, routing failure) must count as a requeue
            # failure like every other second-failure path
            nplan = self.placement.plan(self, entry, Bb)
            vals_d = nplan.put(retry["vals"])
            bs_d = nplan.put(retry["bs"])
            x0 = retry["x0"]
            if x0 is None:
                x0 = np.zeros(
                    (Bb, pat.nb), dtype=retry["bs"].dtype
                )
            x0_d = nplan.put(x0)
            t_redispatch = time.perf_counter()
            res = nplan.fn(entry.template, vals_d, bs_d, x0_d)
            self.metrics.inc("batches")
            self._watched_block(res.x, nplan.device_label)
            t_done = time.perf_counter()
            host = _fetch_host(res)
        except BaseException as e2:  # noqa: BLE001 — the requeue is
            # one-shot: ANY second failure settles the group typed
            if isinstance(e2, DeviceLostError):
                self._device_loss_attributed(nplan, e2)
            elif nplan is not None:
                try:
                    nplan.abandon()
                except Exception:  # noqa: BLE001
                    self.metrics.inc("telemetry_errors")
            self.metrics.inc("resilience_requeue_failures")
            raise
        # the replacement plan owns the group now: its on_fetch does
        # the settle/health accounting, its timings are the real ones
        batch.plan = nplan
        batch.t_dispatch = t_redispatch
        return host, t_done

    def _execute_group(self, grp: _Group, wait_dispatch: bool = True):
        """Host stage of the flusher: deadlines, hierarchy/compile
        resolution, then hand-off to the single-worker dispatch stage.
        ``wait_dispatch`` waits for the DISPATCH (not the device) so
        tickets read done() immediately after a synchronous flush; the
        poller passes False and pipelines."""
        if not grp.requests:
            self._release_group_slot(grp)
            return
        self._wait_ready(grp)
        t_flush = time.perf_counter()
        self._expire_deadlines(grp)
        live = [r for r in grp.requests if not r.ticket._done]
        if not live:
            self._release_group_slot(grp)
            return
        fp = grp.pattern.fingerprint
        if fp in self._broken:
            with self._lock:
                probes = self._bypass_counts.get(fp, 0) + 1
                self._bypass_counts[fp] = probes
            if probes % self._BREAKER_PROBE_EVERY != 0:
                # breaker open: this pattern keeps poisoning its batch
                # groups — serve its requests in per-request isolation
                # without attempting a batched execution
                self.metrics.inc("breaker_bypasses")
                self._execute_quarantined(grp)
                return
            # fall through: half-open probe attempts one batched group
        try:
            # oversized-pattern bypass: a policy that executes the
            # pattern without any single-device hierarchy (distributed
            # row-sharding above AMGX_TPU_DIST_ROWS) supplies its own
            # lightweight entry BEFORE the cache resolves — the
            # single-device setup for a too-big pattern never runs
            entry = self.placement.entry_for(
                self, grp.pattern, grp.dtype
            )
            if entry is None:
                vals0 = grp.pattern.extract_values(
                    grp.slot.vals[live[0].row]
                )
                entry = self.cache.get_or_build(
                    grp.pattern,
                    self.cfg_key,
                    grp.dtype,
                    lambda: self._build_entry(
                        grp.pattern, vals0, grp.dtype
                    ),
                )
            if entry.batch_fn is None:
                self._execute_sequential(entry, grp, live)
                self._breaker_success(fp)
                return
            from amgx_tpu.core import faults
            from amgx_tpu.core.errors import ResourceError

            if faults.should_fire("serve_compile"):
                raise ResourceError(
                    "injected serve compile failure (fault site "
                    "serve_compile)"
                )
            Bb = bucket_batch(len(grp.requests))
            # placement: the policy resolves WHERE this group runs and
            # with WHICH executable (single-device: the shared compile
            # cache, unchanged; mesh: the shard_map program; affinity:
            # the fingerprint's routed device)
            plan = self.placement.plan(self, entry, Bb)
            with self._lock:
                if len(self._last_bucket) >= self._PATTERN_CACHE_MAX:
                    self._last_bucket.clear()
                self._last_bucket[entry.signature] = Bb
        except BaseException:  # noqa: BLE001 — failures must reach the
            # tickets, not kill the poller thread.  Quarantine: the
            # group failed as a unit (a poisoned member sabotaged
            # shared setup, or the compile died) — retry every member
            # in isolation so only the actually-poisoned requests fail.
            self._group_failed(grp, fp)
            return
        if wait_dispatch:
            # synchronous flush (submit()-triggered, flush()): the
            # caller would wait for the dispatch anyway — run the
            # device stage inline and skip the worker hop.  The launch
            # itself is non-blocking, so padding of the NEXT group
            # still overlaps this group's device execution.
            self._dispatch_batched(entry, plan, grp, live, t_flush)
        else:
            # pipelined flush (poller/server mode): the device stage
            # runs on the single-worker executor; this thread returns
            # to padding immediately
            _dispatch_pool().submit(
                self._dispatch_batched, entry, plan, grp, live, t_flush
            )

    def _group_failed(self, grp: _Group, fp: str,
                      device_loss: bool = False):
        self.metrics.inc("failed_groups")
        if not device_loss:
            # a lost CHIP is not the pattern's fault: only non-device
            # failures count toward the fingerprint breaker (the
            # device breaker already tripped via the placement hook)
            self._breaker_failure(fp)
        self.metrics.inc("quarantines")
        self._flight_incident(
            "quarantine",
            detail=(
                f"group of {len(grp.requests)} (lane {grp.lane}) "
                f"fingerprint {fp[:16]}..."
            ),
        )
        self._execute_quarantined(grp)

    def _dispatch_batched(self, entry, plan, grp, live, t_flush):
        """Device stage (single-worker executor): ship the staging
        slot (through the placement plan's transfers), launch the
        plan's compiled batched solve, attach the lazy result.
        Returns at DISPATCH — the only block_until_ready in steady
        state is inside SolveTicket.result().  Never raises: failures
        quarantine the group right here in the worker."""
        from amgx_tpu.core import faults
        from amgx_tpu.core.errors import DeviceLostError

        fp = grp.pattern.fingerprint
        try:
            pat = grp.pattern
            slot = grp.slot
            nreq = len(grp.requests)
            Bb = bucket_batch(nreq)
            with trace_range("serve_batch_dispatch"), \
                    self.metrics.profile.phase("dispatch"):
                # batch padding: clones of a live system with b = 0
                # converge at iteration 0 and freeze immediately
                slot.fill_batch_padding(nreq, Bb)
                if live[0].row != 0:
                    slot.vals[nreq:Bb] = slot.vals[live[0].row]

                def _ship(p):
                    """Transfer + launch through one plan (run again,
                    on a replacement plan, when the first plan's
                    device is lost at dispatch)."""
                    vals_d = p.put(slot.vals[:Bb])
                    bs_d = p.put(slot.bs[:Bb])
                    if slot.x0_used or p.donate:
                        # warm starts (or a donated buffer, which the
                        # compiled call consumes) need a fresh
                        # transfer
                        x0_d = p.put(slot.x0s[:Bb])
                    else:
                        # all-zero initial guesses: reuse one resident
                        # device block instead of shipping zeros per
                        # flush (keyed per placement target: a routed
                        # device's zeros live on that device)
                        zk = (
                            (Bb, pat.nb, str(grp.dtype)) + p.zeros_key
                        )
                        with self._lock:
                            x0_d = self._zeros_x0.get(zk)
                        if x0_d is None:
                            x0_d = p.zeros(Bb, pat.nb, grp.dtype)
                            with self._lock:
                                if len(self._zeros_x0) >= 64:
                                    self._zeros_x0.clear()
                                self._zeros_x0[zk] = x0_d
                    if faults.should_fire("device_lost_dispatch"):
                        raise DeviceLostError(
                            "injected device loss at dispatch (fault "
                            "site device_lost_dispatch)",
                            device_label=p.device_label,
                        )
                    return p.fn(entry.template, vals_d, bs_d, x0_d)

                try:
                    res = _ship(plan)
                except DeviceLostError as e:
                    # one-shot dispatch-side failover: trip the lost
                    # device, resolve a replacement plan through the
                    # degrade chain (the rows are still staged), and
                    # re-ship; a SECOND loss escapes to the outer
                    # handler and the group quarantines per-request
                    plan = self._failover_replan(plan, e, entry, Bb)
                    res = _ship(plan)
                self.metrics.inc("batches")
                # failover payload: host copies of the batched arrays
                # so a device lost AFTER this release can re-dispatch
                # the group (the slot itself is reused by the next
                # group and must not be retained)
                retry = None
                if self.failover:
                    retry = {
                        "vals": np.array(slot.vals[:Bb]),
                        "bs": np.array(slot.bs[:Bb]),
                        "x0": (
                            np.array(slot.x0s[:Bb])
                            if (slot.x0_used or plan.donate)
                            else None
                        ),
                    }
                # host buffers were copied to the device and the solve
                # is launched: release ONLY now, so a pre-launch
                # failure still leaves the rows intact for quarantine
                self._release_group_slot(grp)
            t_dispatch = time.perf_counter()
            self.metrics.add_time(
                "host_busy_s",
                (t_dispatch - t_flush)
                + sum(r.ticket._pad_s for r in live),
            )
            if tracing.tracing_enabled():
                sampled = [
                    r.ticket._trace for r in live
                    if r.ticket._trace is not None
                ]
                for c in sampled:
                    tracing.record_span(
                        "dispatch", t_flush, t_dispatch, c
                    )
                # group-formation span: one per batched group with at
                # least one SAMPLED member (at fractional rates a
                # memberless span per group would flood the ring and
                # evict the sampled chains), linking the member
                # tickets' trace ids so a Perfetto view shows exactly
                # which requests shared this batch
                if sampled:
                    tracing.record_span(
                        "flush_group", t_flush, t_dispatch, None,
                        args={
                            "members": [c.trace_id for c in sampled],
                            "batch": Bb,
                            "real": nreq,
                            "lane": grp.lane,
                            "fingerprint": fp[:16],
                        },
                    )
            br = _BatchResult(
                self, res, pat, [r.ticket for r in live], Bb,
                t_flush, t_dispatch, plan=plan, entry=entry,
                retry=retry,
            )
            for r in live:
                r.ticket._batch = br
                r.ticket._done = True
            self._breaker_success(fp)
        except BaseException as e:  # noqa: BLE001 — worker must not die
            device_loss = isinstance(e, DeviceLostError)
            if device_loss:
                # the REQUEUE's device died too: attribute the loss
                # before quarantining (abandon rides along inside)
                self._device_loss_attributed(plan, e)
            else:
                try:
                    plan.abandon()  # release any routing reservation
                except Exception:  # noqa: BLE001 — placement telemetry
                    self.metrics.inc("telemetry_errors")
            self._group_failed(grp, fp, device_loss=device_loss)

    def _execute_quarantined(self, grp: _Group):
        """Per-request isolation: each request re-solves on its OWN
        coefficients so exactly the poisoned requests fail — with
        typed errors — and the rest complete.  When the pattern's
        hierarchy entry is already cached (the group failure happened
        AFTER a healthy build), the re-solve reuses it via a
        values-only resetup instead of re-deriving the whole setup per
        request; a fresh isolated setup remains the fallback."""
        import amgx_tpu.solvers  # noqa: F401 — registry side effects
        import amgx_tpu.amg  # noqa: F401 — registers "AMG"
        from amgx_tpu.solvers.registry import create_solver, make_nested

        pat = grp.pattern
        accel = self._accel_for(pat)
        entry = self.cache.peek(
            pat.fingerprint, self.cfg_key, grp.dtype
        )
        if grp.slot is None:
            # the slot was already handed back (failure after a
            # successful dispatch release): the staged coefficients
            # are gone, so the requests cannot be re-solved
            from amgx_tpu.core.errors import ResourceError

            for r in grp.requests:
                if not r.ticket._done:
                    r.ticket._error = ResourceError(
                        "serve group failed after its staging was "
                        "released; request not recoverable"
                    )
                    r.ticket._done = True
                    self.metrics.inc("poisoned_requests")
            return
        try:
            for r in grp.requests:
                if r.ticket._done:
                    continue
                vals = pat.extract_values(grp.slot.vals[r.row])
                b = grp.slot.bs[r.row]
                x0 = grp.slot.x0s[r.row]
                try:
                    with self.metrics.profile.phase("quarantine"):
                        res = None
                        if entry is not None:
                            try:
                                # same helper sessions use: values-only
                                # refresh of the cached entry + one
                                # isolated solve under its lock
                                res = self.resetup_entry(
                                    pat.fingerprint, vals, grp.dtype,
                                    b=b, x0=x0,
                                )
                                self.metrics.inc(
                                    "quarantine_entry_reuses"
                                )
                            except Exception:  # noqa: BLE001 —
                                # isolated setup decides; Ctrl-C must
                                # not be absorbed into the SLOWEST
                                # recovery path
                                res = None
                        if res is None:
                            A = pat.template_matrix(
                                vals, grp.dtype, accel_formats=accel
                            )
                            solver = make_nested(
                                create_solver(self.cfg, "default")
                            )
                            solver.setup(A)
                            res = solver.solve(b, x0=x0)
                except BaseException as e:  # noqa: BLE001 — per-request
                    r.ticket._error = e
                    r.ticket._done = True
                    self.metrics.inc("poisoned_requests")
                else:
                    r.ticket._result = dataclasses.replace(
                        res, x=res.x[: pat.n]
                    )
                    r.ticket._done = True
                    self.metrics.inc("quarantined_solves")
                    self.metrics.inc("solved")
                    if telemetry_enabled():
                        t = r.ticket
                        self._flight_record(
                            fingerprint=pat.fingerprint,
                            config=self.cfg_key,
                            lane=t._lane,
                            tenant=t._tenant,
                            iterations=int(res.iters),
                            final_residual=float(
                                np.max(np.asarray(res.final_norm))
                            ),
                            status=int(res.status),
                            stages={},
                            path="quarantine",
                            trace_id=(
                                t._trace.trace_id
                                if t._trace is not None else None
                            ),
                        )
        finally:
            self._release_group_slot(grp)

    def _execute_sequential(self, entry: HierarchyEntry, grp: _Group,
                            live: list):
        """Fallback for solvers without a traced batch path.  The slot
        is released only on full success — a mid-loop failure keeps the
        rows staged so the quarantine path can re-solve them (it owns
        the release then)."""
        pat = grp.pattern
        for r in live:
            with self.metrics.profile.phase("fallback"):
                vals = pat.extract_values(grp.slot.vals[r.row])
                A = pat.template_matrix(
                    vals,
                    grp.dtype,
                    accel_formats=self._accel_for(pat),
                )
                with entry.solver_lock:
                    entry.solver.resetup(A)
                    res = entry.solver.solve(
                        grp.slot.bs[r.row],
                        x0=grp.slot.x0s[r.row],
                        block=False,
                    )
            r.ticket._result = dataclasses.replace(
                res, x=res.x[: pat.n]
            )
            r.ticket._done = True
            self.metrics.inc("fallback_solves")
            self.metrics.inc("solved")
            if telemetry_enabled():
                # block=False above: reading iters/status here would
                # force a per-request device sync and serialize the
                # fallback loop — record the solve without the
                # device-resident scalars (-1 = not synced)
                t = r.ticket
                self._flight_record(
                    fingerprint=pat.fingerprint,
                    config=self.cfg_key,
                    lane=t._lane,
                    tenant=t._tenant,
                    iterations=-1,
                    final_residual=float("nan"),
                    status=-1,
                    stages={},
                    path="fallback",
                    trace_id=(
                        t._trace.trace_id
                        if t._trace is not None else None
                    ),
                )
        self._release_group_slot(grp)
