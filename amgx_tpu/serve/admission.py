"""Multi-tenant admission control for the fleet front-end.

The primitives the gateway (:mod:`amgx_tpu.serve.gateway`) makes its
admit/shed decision from, kept separate so they are unit-testable with
an injected clock and reusable by other frontends:

* :class:`TokenBucket` — the per-tenant rate quota.  Continuous
  refill at ``rate`` tokens/s up to ``burst``; ``try_take`` either
  admits (returns 0.0) or returns the seconds until the requested
  tokens would be available — which IS the ``retry_after_s`` hint the
  typed rejection carries.
* :class:`AdmissionController` — the composed decision: tenant quota,
  then the global concurrency budget (priority-aware: the batch lane
  sheds at ``(1 - interactive_reserve_frac)`` of the budget so a
  burst of batch work can never starve interactive admission), then
  the deadline-shed predictor.

Everything here is *load-independent state*: the controller never
looks at the service directly.  The gateway feeds it the one live
signal it needs — the serve pipeline's end-to-end p99 from the
PR 3 latency reservoirs — as ``predicted_s``.  A missing percentile
(``None``: empty reservoir, cold service) always ADMITS: shedding on
absent data would deadlock a cold worker, and the first tickets are
exactly what fills the reservoir.

Admission failures are the typed, recoverable vocabulary of
:mod:`amgx_tpu.core.errors`: :class:`~amgx_tpu.core.errors.Overloaded`
for budget/drain sheds, its base
:class:`~amgx_tpu.core.errors.AdmissionRejected` for quota / deadline
/ breaker sheds — both carrying ``retry_after_s``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

from amgx_tpu.core.errors import AdmissionRejected, Overloaded


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Token-bucket parameters for one tenant: sustained ``rate``
    requests/s with bursts up to ``burst``.

    ``device_seconds_rate`` (optional) adds a DEVICE-SECONDS budget on
    top of the request quota — the enforcement half of the PR 9 cost
    accounting (``amgx_gateway_tenant_device_seconds_total`` counted;
    this charges).  The budget refills continuously at
    ``device_seconds_rate`` device-seconds per wall second up to
    ``device_seconds_burst`` (default: 10x the rate, i.e. ~10 s of
    standing credit); every settled ticket's measured share of its
    group's device time is charged POST-PAID, so the balance can go
    negative (debt) and the next admit sheds — typed
    :class:`AdmissionRejected`, ``reason="device_budget"``, with
    ``retry_after_s`` = the refill time back to zero balance — until
    the refill clears it.  A big-n tenant therefore pays for its
    actual device time, not one token per request.  ``None`` (default)
    means no device budget, the pre-PR behavior."""

    rate: float = 1000.0
    burst: float = 100.0
    device_seconds_rate: Optional[float] = None
    device_seconds_burst: Optional[float] = None


class TokenBucket:
    """Continuous-refill token bucket (thread safety is the
    controller's job — it holds its lock around ``try_take``).

    The clock is injectable so quota arithmetic is unit-testable
    without sleeping; production uses ``time.monotonic``.
    """

    __slots__ = ("rate", "burst", "tokens", "_t_last", "_clock")

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._clock = clock
        self._t_last = clock()

    def try_take(self, n: float = 1.0) -> float:
        """Take ``n`` tokens if available: returns 0.0 (admitted) or
        the seconds until ``n`` tokens will have refilled — the
        retry-after hint.  A zero-rate bucket that is out of burst
        returns ``inf`` (the caller caps the hint)."""
        now = self._clock()
        if self.rate > 0:
            self.tokens = min(
                self.burst,
                self.tokens + (now - self._t_last) * self.rate,
            )
        self._t_last = now
        if self.tokens >= n:
            self.tokens -= n
            return 0.0
        if self.rate <= 0:
            return float("inf")
        return (n - self.tokens) / self.rate


def can_meet_deadline(deadline_s, predicted_s,
                      headroom: float = 1.0) -> bool:
    """The shed predictor: can a request with ``deadline_s`` seconds
    of slack plausibly complete, given the pipeline's current
    end-to-end tail estimate ``predicted_s`` (p99 of the serve
    latency reservoirs)?

    MISSING data admits: ``predicted_s is None`` (empty reservoir —
    cold service) or no deadline at all is always True.  Only a
    deadline strictly tighter than ``headroom * predicted_s`` is
    provably unmeetable and sheds."""
    if deadline_s is None or predicted_s is None:
        return True
    return float(deadline_s) >= headroom * float(predicted_s)


class AdmissionController:
    """Composed admission decision + in-flight accounting.

    ``admit()`` either reserves one unit of the concurrency budget
    (caller MUST pair it with ``release()`` when the request settles)
    or raises the typed rejection.  Decision order — cheapest and
    most client-actionable first:

    1. injected ``admission_quota`` fault / tenant token bucket
       (:class:`AdmissionRejected`, ``reason="quota"``);
    2. tenant device-seconds budget, when its quota carries one —
       post-paid balance, debited by :meth:`charge_device_seconds` at
       each ticket's settle (:class:`AdmissionRejected`,
       ``reason="device_budget"``);
    3. global concurrency budget; the batch lane sheds at
       ``(1 - interactive_reserve_frac) * max_inflight`` so
       interactive admission always has headroom
       (:class:`Overloaded`, ``reason="overloaded"``);
    4. deadline-shed predictor (:class:`AdmissionRejected`,
       ``reason="deadline_unmeetable"``) — *after* the budget check so
       an overloaded service answers with the backoff hint, not a
       misleading deadline verdict.
    """

    def __init__(
        self,
        max_inflight: int = 256,
        interactive_reserve_frac: float = 0.25,
        default_quota: Optional[TenantQuota] = None,
        quotas: Optional[dict] = None,
        deadline_headroom: float = 1.0,
        retry_after_cap_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.max_inflight = int(max_inflight)
        self.interactive_reserve_frac = float(interactive_reserve_frac)
        self.default_quota = default_quota  # None = unlimited
        self.quota_spec = dict(quotas or {})
        self.deadline_headroom = float(deadline_headroom)
        self.retry_after_cap_s = float(retry_after_cap_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: dict = {}
        # device-seconds budgets (tokens denominated in device time);
        # charged post-paid by charge_device_seconds, gated in admit()
        self._device_buckets: dict = {}
        self.inflight = 0

    # -- quota ---------------------------------------------------------

    def _bucket_for(self, tenant: str) -> Optional[TokenBucket]:
        """Tenant's token bucket, created lazily from its quota spec
        (caller holds the lock).  No spec and no default = unlimited."""
        b = self._buckets.get(tenant)
        if b is not None:
            return b
        spec = self.quota_spec.get(tenant, self.default_quota)
        if spec is None:
            return None
        b = TokenBucket(spec.rate, spec.burst, clock=self._clock)
        self._buckets[tenant] = b
        return b

    def _device_bucket_for(self, tenant: str) -> Optional[TokenBucket]:
        """Tenant's device-seconds budget bucket, created lazily
        (caller holds the lock); None when its quota spec carries no
        device budget."""
        b = self._device_buckets.get(tenant)
        if b is not None:
            return b
        spec = self.quota_spec.get(tenant, self.default_quota)
        if spec is None or spec.device_seconds_rate is None:
            return None
        burst = (
            spec.device_seconds_burst
            if spec.device_seconds_burst is not None
            else 10.0 * spec.device_seconds_rate
        )
        b = TokenBucket(
            spec.device_seconds_rate, burst, clock=self._clock
        )
        self._device_buckets[tenant] = b
        return b

    def charge_device_seconds(self, tenant: str, seconds: float,
                              lane: str = None) -> None:
        """Post-paid device-time charge: a settled ticket's measured
        share of its group's device time debits the tenant's budget
        (wired by the gateway through
        ``ServeMetrics.on_tenant_device``).  The balance may go
        negative — debt — which :meth:`admit` sheds on until the
        continuous refill clears it."""
        with self._lock:
            b = self._device_bucket_for(tenant)
            if b is None:
                return
            b.try_take(0.0)  # refill to now before debiting
            b.tokens -= float(seconds)

    def _cap(self, retry_after: float) -> float:
        return min(retry_after, self.retry_after_cap_s)

    @property
    def batch_budget(self) -> int:
        """In-flight ceiling for the batch lane: the interactive
        reserve stays admittable even when batch has filled its
        share."""
        return max(
            int(self.max_inflight
                * (1.0 - self.interactive_reserve_frac)),
            1,
        )

    # -- the decision --------------------------------------------------

    def admit(self, tenant: str = "default",
              lane: str = "interactive",
              deadline_s: Optional[float] = None,
              predicted_s=None) -> None:
        """Admit (reserving one in-flight unit) or raise typed.

        ``predicted_s`` is the pipeline tail estimate: a float, None,
        or a zero-arg callable resolved at most once, always OUTSIDE
        the controller lock so the reservoir copy+sort behind the
        gateway's p99 never serializes concurrent admissions.  A
        no-deadline submit resolves it only if the budget gate sheds
        (the backoff hint) — the hot under-budget path skips it
        entirely; a deadline-carrying submit resolves it up front,
        before the quota gate, because the deadline check needs the
        value inside the lock."""
        from amgx_tpu.core import faults

        def resolve():
            return (
                predicted_s() if callable(predicted_s) else predicted_s
            )

        # the deadline gate's input is a pure function of the
        # arguments: resolve it before taking the lock
        pred = resolve() if deadline_s is not None else None
        over = None
        with self._lock:
            bucket = self._bucket_for(tenant)
            if faults.should_fire("admission_quota"):
                raise AdmissionRejected(
                    f"tenant {tenant!r} quota exhausted (injected "
                    "fault site admission_quota)",
                    retry_after_s=self._cap(1.0),
                    reason="quota",
                )
            token_taken = False
            if bucket is not None:
                wait = bucket.try_take(1.0)
                if wait > 0.0:
                    raise AdmissionRejected(
                        f"tenant {tenant!r} over its request quota "
                        f"({bucket.rate:g}/s, burst {bucket.burst:g})",
                        retry_after_s=self._cap(wait),
                        reason="quota",
                    )
                token_taken = True

            def refund():
                # a request shed by a LATER gate was never served:
                # charging its quota token anyway would quota-starve
                # the tenant exactly when it retries after the
                # overload clears (double punishment)
                if token_taken:
                    bucket.tokens = min(
                        bucket.burst, bucket.tokens + 1.0
                    )

            dbucket = self._device_bucket_for(tenant)
            if dbucket is not None:
                # device-seconds ENFORCEMENT: post-paid, so the gate
                # admits while the balance is non-negative;
                # try_take(0) refills to now and, when the tenant is
                # in debt, returns the seconds until the balance is
                # back at zero — exactly the retry hint
                wait = dbucket.try_take(0.0)
                if wait > 0.0:
                    refund()
                    raise AdmissionRejected(
                        f"tenant {tenant!r} device-seconds budget "
                        f"exhausted ({dbucket.rate:g} dev-s/s refill, "
                        f"balance {dbucket.tokens:g}s)",
                        retry_after_s=self._cap(wait),
                        reason="device_budget",
                    )
            limit = (
                self.max_inflight
                if lane == "interactive"
                else self.batch_budget
            )
            if self.inflight >= limit:
                # budget shed outranks the deadline verdict (see the
                # class docstring), but its hint may need a reservoir
                # sort — record the decision and raise OUTSIDE the
                # lock so a shed storm cannot serialize admissions
                refund()
                over = (self.inflight, limit)
            elif not can_meet_deadline(
                deadline_s, pred, self.deadline_headroom
            ):
                refund()
                raise AdmissionRejected(
                    f"deadline_s={float(deadline_s):g} cannot be met "
                    f"(current p99 {float(pred):g}s)",
                    retry_after_s=self._cap(float(pred)),
                    reason="deadline_unmeetable",
                )
            else:
                self.inflight += 1
        if over is not None:
            inflight, limit = over
            # backoff hint: one pipeline tail-latency's worth of
            # draining, when known; a small fixed nudge otherwise
            hint = (pred if deadline_s is not None else resolve())
            raise Overloaded(
                f"concurrency budget exhausted ({inflight} "
                f"in flight, {lane} lane limit {limit})",
                retry_after_s=self._cap(float(hint or 0.05)),
                reason="overloaded",
            )

    def release(self, n: int = 1) -> None:
        """Return ``n`` in-flight units (the paired ticket settled)."""
        with self._lock:
            self.inflight = max(self.inflight - n, 0)

    def snapshot(self) -> dict:
        """Telemetry view: budget occupancy and per-tenant remaining
        tokens (the quota gauge the exposition page exports as
        ``amgx_admission_tenant_tokens``)."""
        with self._lock:
            return {
                "inflight": self.inflight,
                "max_inflight": self.max_inflight,
                "batch_budget": self.batch_budget,
                "tenant_tokens": {
                    t: b.tokens for t, b in self._buckets.items()
                },
                # refill-to-now view (read-only): an indebted tenant
                # that stopped sending never calls try_take again, so
                # exporting the raw balance would show cleared debt
                # forever
                "tenant_device_tokens": {
                    t: min(
                        b.burst,
                        b.tokens
                        + max(self._clock() - b._t_last, 0.0) * b.rate,
                    )
                    for t, b in self._device_buckets.items()
                },
            }
