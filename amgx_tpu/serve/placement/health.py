"""Per-device failure breakers: the failure-domain layer under the
placement policies.

PR 2 gave *sparsity patterns* circuit breakers (a fingerprint that
keeps poisoning its batch groups bypasses batching); this module gives
*devices* the same semantics.  A chip that loses a dispatch or a fetch
(typed :class:`~amgx_tpu.core.errors.DeviceLostError`, or the in-flight
watchdog expiring on a hung fetch) trips its breaker:

  healthy ──failure×threshold──> tripped ──every Nth plan──> half-open
     ▲                               │                          probe
     └──────────── probe group succeeds ────────────────────────┘

While tripped, a device receives NO new groups — the affinity router
routes around it (its warm-fingerprint set is forgotten, so sessions
re-pin elsewhere), and a mesh shrinks its shard layout to the healthy
device prefix.  Every Nth placement attempt that WOULD have used the
tripped device is admitted as the half-open probe; its group's
successful fetch closes the breaker (``resilience_device_closes``) and
the device rejoins routing.  The probe cadence is the SAME knob the
fingerprint breaker uses (:func:`breaker_probe_every` —
``AMGX_TPU_BREAKER_PROBE_EVERY``, default 8), so one configuration
governs both breaker families.

Pure host state, no jax imports: unit-testable without devices and
reusable by a multi-process fleet tier (worker health instead of chip
health).  Counters land in the owning service's shared
:class:`~amgx_tpu.serve.metrics.ServeMetrics` under the
``resilience_*`` prefix, exported as ``amgx_resilience_*`` families.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

_PROBE_DEFAULT = 8
ENV_PROBE = "AMGX_TPU_BREAKER_PROBE_EVERY"


def breaker_probe_every(value: Optional[int] = None) -> int:
    """The half-open probe cadence shared by the per-fingerprint and
    per-device breakers: every Nth attempt against an open breaker is
    admitted as the probe.  ``value`` (a config param) wins; else the
    ``AMGX_TPU_BREAKER_PROBE_EVERY`` env knob; else 8.  Clamped to
    >= 1 (a cadence of 1 probes every attempt — breakers effectively
    log-only; 0/negative/malformed fall back to the default so a config
    typo can never disable probing and strand a breaker open)."""
    if value is None:
        raw = os.environ.get(ENV_PROBE, "")
        try:
            value = int(raw) if raw else _PROBE_DEFAULT
        except ValueError:
            value = _PROBE_DEFAULT
    value = int(value)
    return value if value >= 1 else _PROBE_DEFAULT


class DeviceHealthBoard:
    """Failure breakers for ``n`` placement devices.

    ``failure(i)`` counts a device-attributed failure and trips the
    breaker at ``trip_threshold`` (default 1: device loss is severe —
    one lost dispatch/fetch quarantines the chip).  ``ok(i)`` closes
    the breaker (a successful fetch on the device — in particular the
    half-open probe's).  ``probe_due(i)`` implements the cadence: for
    a tripped device, every ``probe_every``-th call returns True and
    the caller routes ONE group there as the probe.

    Thread-safe; ``metrics`` (a ServeMetrics, attached lazily by the
    owning policy's first ``plan``) receives the ``resilience_*``
    counters — trips, probes, closes — and the
    ``resilience_devices_unhealthy`` gauge."""

    def __init__(self, n_devices: int, trip_threshold: int = 1,
                 probe_every: Optional[int] = None, metrics=None):
        if n_devices < 1:
            raise ValueError("DeviceHealthBoard needs >= 1 device")
        self.n = int(n_devices)
        self.trip_threshold = max(int(trip_threshold), 1)
        self.probe_every = breaker_probe_every(probe_every)
        self.metrics = metrics
        self._lock = threading.Lock()
        self._fails = [0] * self.n
        self._tripped = [False] * self.n
        self._probe_counts = [0] * self.n
        self.trips = 0
        self.probes = 0
        self.closes = 0

    # -- metrics (degrade, never raise) --------------------------------

    def _inc(self, name: str):
        m = self.metrics
        if m is not None:
            try:
                m.inc(name)
            except Exception:  # noqa: BLE001 — health accounting must
                pass  # never fail a placement decision

    def _gauge_unhealthy(self):
        m = self.metrics
        if m is not None:
            try:
                m.set_gauge(
                    "resilience_devices_unhealthy",
                    sum(self._tripped),
                )
            except Exception:  # noqa: BLE001
                pass

    # -- state transitions ---------------------------------------------

    def failure(self, index: int) -> bool:
        """One device-attributed failure; True when this call TRIPPED
        the breaker (open→open recounts toward nothing)."""
        if not 0 <= index < self.n:
            return False
        with self._lock:
            if self._tripped[index]:
                return False
            self._fails[index] += 1
            if self._fails[index] < self.trip_threshold:
                return False
            self._tripped[index] = True
            self._probe_counts[index] = 0
            self.trips += 1
            self._inc("resilience_device_trips")
            self._gauge_unhealthy()
            return True

    def ok(self, index: int) -> None:
        """A group's fetch succeeded on the device: reset its failure
        count and — when tripped (the half-open probe) — close the
        breaker."""
        if not 0 <= index < self.n:
            return
        with self._lock:
            self._fails[index] = 0
            if self._tripped[index]:
                self._tripped[index] = False
                self.closes += 1
                self._inc("resilience_device_closes")
                self._gauge_unhealthy()

    def probe_due(self, index: int) -> bool:
        """For a TRIPPED device: consume one probe-cadence tick; True
        on the cadence multiple (the caller routes one group there as
        the half-open probe).  Healthy devices always return False —
        they need no probe."""
        if not 0 <= index < self.n:
            return False
        with self._lock:
            if not self._tripped[index]:
                return False
            self._probe_counts[index] += 1
            if self._probe_counts[index] % self.probe_every:
                return False
            self.probes += 1
            self._inc("resilience_device_probes")
            return True

    # -- views ---------------------------------------------------------

    def healthy(self, index: int) -> bool:
        with self._lock:
            return 0 <= index < self.n and not self._tripped[index]

    def healthy_indices(self) -> list:
        with self._lock:
            return [i for i in range(self.n) if not self._tripped[i]]

    def tripped_indices(self) -> list:
        with self._lock:
            return [i for i in range(self.n) if self._tripped[i]]

    def healthy_prefix(self) -> int:
        """Length of the longest all-healthy prefix of the device
        list — the mesh degrade chain: a tripped shard device shrinks
        the layout to the devices before it (a mesh is a device
        PREFIX, so one bad chip caps, not punctures, the mesh)."""
        with self._lock:
            for i in range(self.n):
                if self._tripped[i]:
                    return i
            return self.n

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "devices": self.n,
                "unhealthy": sum(self._tripped),
                "tripped": [
                    i for i in range(self.n) if self._tripped[i]
                ],
                "trips": self.trips,
                "probes": self.probes,
                "closes": self.closes,
                "probe_every": self.probe_every,
            }
