"""Policy-driven device placement for the serve tier.

Splits the batched service's host-side queueing from the decision of
WHERE a flushed group executes (ROADMAP item 1):

* :class:`SingleDevicePolicy` — the default; bitwise the pre-placement
  behavior (everything on the process-default device).
* :class:`MeshPlacement` — shard the batch axis of each group across a
  ``jax.sharding.Mesh`` via ``shard_map``: each chip solves its slice,
  hierarchies replicate through partition-rule pytree specs, the only
  cross-chip collective is the psum'd shared convergence mask.
* :class:`AffinityPlacement` — route each whole group to the device
  whose hierarchy/compile caches are already warm for its fingerprint
  (:class:`AffinityRouter`), falling back to least-loaded.
* :class:`DistributedPlacement` — row-shard ONE big system over the
  mesh (domain decomposition, AmgX L3): patterns crossing
  ``row_threshold`` rows are partitioned with halo maps, solved by
  the shard-aware distributed AMG hierarchy, and settled through the
  normal group pipeline (see doc/DISTRIBUTED.md).

Select with the service's ``placement=`` argument or
``AMGX_TPU_PLACEMENT=single|mesh[:N]|affinity|distributed[:N]``
(see doc/MESH.md, doc/DISTRIBUTED.md).

Failure domains (doc/ROBUSTNESS.md "Failure domains"): every policy
carries a :class:`DeviceHealthBoard` of per-device breakers — a lost
dispatch/fetch trips the device, routing forgets it, the mesh shrinks
to the healthy prefix, and every Nth attempt is the half-open probe
whose success re-admits the chip.
"""

from amgx_tpu.serve.placement.health import (
    DeviceHealthBoard,
    breaker_probe_every,
)
from amgx_tpu.serve.placement.policy import (
    ENV_VAR,
    GroupPlan,
    PlacementPolicy,
    SingleDevicePolicy,
    parse_placement,
    placement_from_env,
    resolve_placement,
)
from amgx_tpu.serve.placement.mesh import (
    MeshPlacement,
    template_partition_specs,
)
from amgx_tpu.serve.placement.router import (
    AffinityPlacement,
    AffinityRouter,
)
from amgx_tpu.serve.placement.distributed import DistributedPlacement

__all__ = [
    "ENV_VAR",
    "DeviceHealthBoard",
    "breaker_probe_every",
    "GroupPlan",
    "PlacementPolicy",
    "SingleDevicePolicy",
    "MeshPlacement",
    "AffinityPlacement",
    "AffinityRouter",
    "DistributedPlacement",
    "template_partition_specs",
    "parse_placement",
    "placement_from_env",
    "resolve_placement",
]
