"""DistributedPlacement: row-shard ONE big system over the mesh.

Where :class:`~amgx_tpu.serve.placement.mesh.MeshPlacement` shards the
BATCH axis of many small systems, this policy shards the ROW axis of a
single large one (domain decomposition, AmgX L3): a flushed group
whose pattern crosses ``row_threshold`` rows is partitioned over the
mesh (:class:`~amgx_tpu.core.rowsharded.RowShardedMatrix`), solved by
the shard-aware AMG hierarchy
(:class:`~amgx_tpu.distributed.amg.DistributedAMG` — per-rank host
coarsening, ghost-row Galerkin, optional ``dist_coarse_sparsify`` halo
capping, consolidated tail), and settled through the NORMAL serve
pipeline: the ticket is submitted, traced, flight-recorded, and
drained like any other group — ``plan.fn`` returns a lazy
``SolveResult`` pytree and the group's single fetch stays the only
host sync.

Eligibility: ``pattern.n >= row_threshold``, a real (non-complex)
dtype, and >= 2 mesh devices; everything else takes the ``fallback``
policy's plan (single-device by default) bit-identically.  The
sharded hierarchy is cached per pattern ``fingerprint`` + values hash
— the per-shard keys reuse ``core.matrix.sparsity_fingerprint``
(``DistributedMatrix.fingerprint``), so repeat fingerprints skip
setup exactly like the service's ``HierarchyCache``.

Oversized-pattern bypass: the service consults
:meth:`DistributedPlacement.entry_for` BEFORE resolving its
single-device hierarchy entry, so a pattern above ``row_threshold``
never pays (or even attempts) a single-device setup — the policy
hands the flusher a lightweight entry stub carrying only what the
sharded plan reads (pattern, solver tolerance/max_iters, dtype) and
the hierarchy work happens exclusively in the sharded
``_solver_for`` path.  The only remaining single-device exposure for
a bypassed pattern is the quarantine fallback after a FAILED sharded
group (per-request isolation re-derives a fresh setup).

Outer loops: ``outer="pcg"`` (default) or ``"sstep"`` (s-step PCG —
two collectives per s steps through the psum'd fused Gram block).
Convergence is relative-residual at the entry solver's tolerance.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Optional

import numpy as np

from amgx_tpu.serve.placement.policy import (
    GroupPlan,
    PlacementPolicy,
    SingleDevicePolicy,
)

DEFAULT_ROW_THRESHOLD = 65536
ENV_ROW_THRESHOLD = "AMGX_TPU_DIST_ROWS"


def _orig_csr(pat):
    """Recover the ORIGINAL (unpadded) CSR pattern from a
    PaddedPattern: ``scatter`` maps original entries into the padded
    arrays, so the original columns/indptr fall out of two gathers."""
    ro = np.asarray(pat.row_offsets)
    ci = np.asarray(pat.col_indices)
    rows = (
        np.searchsorted(ro, pat.scatter, side="right") - 1
    ).astype(np.int64)
    indptr = np.concatenate(
        [[0], np.cumsum(np.bincount(rows, minlength=pat.n))]
    ).astype(np.int64)
    return indptr, ci[pat.scatter].astype(np.int64)


class _BypassOperator:
    """The ``entry.solver.A`` face of a bypass entry: carries only
    the dtype the eligibility check reads."""

    __slots__ = ("values",)

    def __init__(self, dtype):
        self.values = np.empty(0, dtype)


class _BypassSolverParams:
    """The ``entry.solver`` face of a bypass entry: the outer-loop
    parameters ``plan`` reads (tolerance / max_iters, resolved from
    the service config WITHOUT running any setup) plus the dtype
    probe."""

    __slots__ = ("A", "tolerance", "max_iters")

    def __init__(self, dtype, tolerance, max_iters):
        self.A = _BypassOperator(dtype)
        self.tolerance = float(tolerance)
        self.max_iters = int(max_iters)


def _bypass_batch_fn(*_a, **_k):  # pragma: no cover — never invoked
    raise RuntimeError(
        "distributed-bypass entry has no single-device executable; "
        "its groups dispatch through DistributedPlacement.plan"
    )


class _ShardedSolver:
    """One fingerprint's sharded state: the RowShardedMatrix, its
    DistributedAMG hierarchy (rebuilt when values change), and the
    jit-side unpad metadata."""

    __slots__ = (
        "rs", "amg", "vals_hash", "setup_s", "n", "uniform",
    )

    def __init__(self, rs, amg, vals_hash, setup_s):
        self.rs = rs
        self.amg = amg
        self.vals_hash = vals_hash
        self.setup_s = setup_s
        self.n = rs.dm.n_global
        n_owned = np.asarray(rs.dm.n_owned)
        # reshape-unpad is valid only for uniform contiguous blocks
        # (every part except the last owns exactly rows_per_part rows)
        self.uniform = bool(
            (n_owned[:-1] == rs.dm.rows_per_part).all()
        )


class DistributedPlacement(PlacementPolicy):
    """Row-shard big-pattern groups over the mesh; delegate the rest.

    Parameters
    ----------
    devices: chips to mesh over (default all ``jax.devices()``).
    axis_name: mesh axis name ("rows").
    max_shards: cap on the shard count
        (``AMGX_TPU_PLACEMENT=distributed:N``).
    row_threshold: minimum pattern rows to shard; smaller groups take
        the fallback plan.  None resolves ``AMGX_TPU_DIST_ROWS``
        (default 65536).
    outer: "pcg" | "sstep" — the distributed outer Krylov loop.
    sparsify_theta: ``dist_coarse_sparsify`` for the sharded
        hierarchy (0 = exact Galerkin).
    consolidate_rows / grade_lower: the hierarchy's consolidation
        knobs (None = DistributedAMG defaults).
    fallback: policy for ineligible groups (default
        :class:`SingleDevicePolicy` — bitwise the pre-placement
        behavior).
    """

    name = "distributed"
    telemetry_kind = "dist"

    def __init__(self, devices=None, axis_name: str = "rows",
                 max_shards: Optional[int] = None,
                 row_threshold: Optional[int] = None,
                 outer: str = "pcg",
                 sparsify_theta: float = 0.0,
                 consolidate_rows: Optional[int] = None,
                 grade_lower: Optional[int] = None,
                 fallback: Optional[PlacementPolicy] = None):
        import jax
        import os

        if outer not in ("pcg", "sstep"):
            raise ValueError(
                f"DistributedPlacement outer must be 'pcg' or "
                f"'sstep', got {outer!r}"
            )
        self.devices = (
            list(devices) if devices is not None
            else list(jax.devices())
        )
        if max_shards:
            self.devices = self.devices[:max_shards]
        self.axis_name = axis_name
        self.max_shards = max_shards
        if row_threshold is None:
            row_threshold = int(
                os.environ.get(
                    ENV_ROW_THRESHOLD, str(DEFAULT_ROW_THRESHOLD)
                )
            )
        self.row_threshold = int(row_threshold)
        self.outer = outer
        self.sparsify_theta = float(sparsify_theta)
        self.consolidate_rows = consolidate_rows
        self.grade_lower = grade_lower
        self._fallback = fallback or SingleDevicePolicy()
        self.health = self._fallback.health
        self._lock = threading.Lock()
        self._mesh = None
        self._solvers: dict = {}  # pattern fingerprint -> _ShardedSolver
        # (fingerprint, dtype str) -> bypass HierarchyEntry stub
        self._bypass_entries: dict = {}
        self._bypass_builds = 0
        # telemetry (guarded by _lock)
        self._sharded_groups = 0
        self._fallback_groups = 0
        self._solves = 0
        self._setups = 0
        self._setup_s = 0.0
        self._iters_total = 0
        self._level_stats: list = []
        self._sparsify_stats: list = []
        self._consolidation_level = -1
        self._halo_bytes_cycle = 0
        self.psum_sites: Optional[int] = None
        self._dist_fp: Optional[str] = None

    # -- mesh -----------------------------------------------------------

    def _mesh_for(self):
        from jax.sharding import Mesh

        with self._lock:
            if self._mesh is None:
                self._mesh = Mesh(
                    np.array(self.devices), (self.axis_name,)
                )
            return self._mesh

    def _eligible(self, entry, Bb: int) -> bool:
        pat = entry.pattern
        dt = np.dtype(entry.solver.A.values.dtype)
        return (
            len(self.devices) >= 2
            and pat.n >= self.row_threshold
            and dt.kind == "f"
        )

    # -- sharded state --------------------------------------------------

    def _solver_for(self, entry, values: np.ndarray) -> _ShardedSolver:
        """The fingerprint's sharded hierarchy, rebuilt when the
        coefficient content changes (hash of the value bytes)."""
        from amgx_tpu.core.rowsharded import RowShardedMatrix

        pat = entry.pattern
        vh = hashlib.blake2b(
            np.ascontiguousarray(values).tobytes(), digest_size=16
        ).hexdigest()
        with self._lock:
            ss = self._solvers.get(pat.fingerprint)
        if ss is not None and ss.vals_hash == vh:
            return ss
        t0 = time.perf_counter()
        indptr, cols = _orig_csr(pat)
        mesh = self._mesh_for()
        rs = RowShardedMatrix.from_csr(
            indptr, cols, values, pat.n, mesh=mesh
        )
        kw = {}
        if self.consolidate_rows is not None:
            kw["consolidate_rows"] = self.consolidate_rows
        if self.grade_lower is not None:
            kw["grade_lower"] = self.grade_lower
        if self.sparsify_theta > 0.0:
            kw["sparsify_theta"] = self.sparsify_theta
        amg = rs.solver(**kw)
        setup_s = time.perf_counter() - t0
        ss = _ShardedSolver(rs, amg, vh, setup_s)
        if not ss.uniform:
            # the lazy jit-side unpad (flatten + slice) requires the
            # uniform contiguous layout from_csr builds; anything else
            # must not silently misorder rows
            raise ValueError(
                "DistributedPlacement requires a uniform contiguous "
                "row partition for the jit-side unpad"
            )
        cs = amg.collective_stats()
        cons = next(
            (
                l for l, lvl in enumerate(amg.h.levels)
                if lvl.bridge is not None
            ),
            len(amg.h.levels),
        )
        with self._lock:
            self._solvers[pat.fingerprint] = ss
            self._setups += 1
            self._setup_s += setup_s
            self._dist_fp = rs.fingerprint
            self._level_stats = [
                dict(
                    level=l["level"],
                    halo_bytes=l["halo_bytes"],
                    active_shards=l["active_shards"],
                    ghost_rows=g,
                )
                for l, g in zip(
                    cs["levels"],
                    [
                        (lvl.A.halo_stats()["ghost_rows_total"]
                         if isinstance(lvl.A.ell_cols, np.ndarray)
                         else None)
                        for lvl in amg.h.levels
                    ],
                )
            ]
            self._sparsify_stats = list(
                (amg.h.setup_stats or {}).get("sparsify", [])
            )
            self._consolidation_level = cons
            self._halo_bytes_cycle = sum(
                l["halo_bytes"] + l["bridge_bytes"]
                for l in cs["levels"]
            )
        return ss

    # -- PlacementPolicy ------------------------------------------------

    def entry_for(self, service, pattern, dtype):
        """Serve-tier oversized-pattern bypass: for a pattern this
        policy WILL shard (rows >= ``row_threshold``, real dtype,
        >= 2 devices), hand the flusher a lightweight entry — the
        single-device ``cache.get_or_build`` (and its whole hierarchy
        setup) never runs.  The stub quacks exactly like the entry
        fields the dispatch path touches: ``pattern``,
        ``solver.tolerance`` / ``solver.max_iters`` (resolved from
        the service config without setup), a truthy ``batch_fn`` (so
        the sequential fallback is not taken), ``template=None``
        (ignored by the sharded executable) and a distinct
        ``signature`` for the bucket-warmup map.  Ineligible patterns
        return None and resolve the cache unchanged."""
        dt = np.dtype(dtype)
        if not (
            len(self.devices) >= 2
            and pattern.n >= self.row_threshold
            and dt.kind == "f"
        ):
            return None
        key = (pattern.fingerprint, str(dt))
        with self._lock:
            entry = self._bypass_entries.get(key)
        if entry is not None:
            return entry
        import amgx_tpu.solvers  # noqa: F401 — registry side effects
        import amgx_tpu.amg  # noqa: F401 — registers "AMG"
        from amgx_tpu.serve.cache import HierarchyEntry
        from amgx_tpu.solvers.registry import create_solver, make_nested

        proto = make_nested(create_solver(service.cfg, "default"))
        entry = HierarchyEntry(
            solver=_BypassSolverParams(
                dt, proto.tolerance, proto.max_iters
            ),
            template=None,
            batch_fn=_bypass_batch_fn,
            signature=("dist-bypass", pattern.fingerprint, str(dt)),
            pattern=pattern,
        )
        with self._lock:
            if len(self._bypass_entries) >= 64:
                self._bypass_entries.clear()
            entry = self._bypass_entries.setdefault(key, entry)
            self._bypass_builds += 1
        return entry

    def plan(self, service, entry, Bb: int) -> GroupPlan:
        if not self._eligible(entry, Bb):
            with self._lock:
                self._fallback_groups += 1
            return self._fallback.plan(service, entry, Bb)

        import jax.numpy as jnp

        from amgx_tpu.serve.batched import psum_site_counter
        from amgx_tpu.solvers.base import (
            NOT_CONVERGED,
            SUCCESS,
            SolveResult,
        )

        pat = entry.pattern
        tol = float(entry.solver.tolerance)
        max_iters = int(entry.solver.max_iters)
        outer = self.outer
        policy = self

        def fn(_template, vals_B, bs_B, x0_B):
            """Host-staged sharded dispatch: per live instance, one
            shard_map solve launched async; the returned SolveResult
            leaves are lazy device arrays — the group's single fetch
            stays the only host sync."""
            vals_B = np.asarray(vals_B)
            bs_B = np.asarray(bs_B)
            x0_B = np.asarray(x0_B)
            Bb_ = vals_B.shape[0]
            hist = np.full(
                (max_iters + 1, 1), np.nan, dtype=np.float64
            )
            xs, its, sts, fns, ins, hs = [], [], [], [], [], []
            prev_vals = None
            ss = None
            solved = 0
            for i in range(Bb_):
                b_i = bs_B[i, : pat.n]
                if not np.any(b_i):
                    # batch-padding clone (b = 0): converged at 0
                    xs.append(jnp.zeros((pat.nb,), vals_B.dtype))
                    its.append(jnp.asarray(np.int32(0)))
                    sts.append(jnp.asarray(np.int32(SUCCESS)))
                    fns.append(jnp.zeros((1,), np.float64))
                    ins.append(jnp.zeros((1,), np.float64))
                    hs.append(jnp.asarray(hist))
                    continue
                v_i = pat.extract_values(vals_B[i])
                if ss is None or (
                    prev_vals is not None
                    and not np.array_equal(prev_vals, v_i)
                ):
                    ss = policy._solver_for(entry, v_i)
                    prev_vals = v_i
                x0_i = x0_B[i, : pat.n]
                # warm starts: solve the shifted system A d = b - A x0
                # (one host SpMV off the cached pattern), x = x0 + d
                shift = np.any(x0_i)
                rhs = (
                    b_i - ss.rs._scipy @ x0_i if shift else b_i
                )
                nrm0 = float(np.linalg.norm(rhs))
                with psum_site_counter() as c:
                    x_d, it_d, nrm_d = ss.amg.solve_device(
                        rhs, max_iters=max_iters, tol=tol,
                        outer=outer,
                    )
                if c.count and policy.psum_sites is None:
                    with policy._lock:
                        policy.psum_sites = c.count
                # jit-side unpad (uniform contiguous blocks): flatten
                # the stacked [N, rows] shards and slice the real rows
                # — an async device op, no host sync
                x_flat = jnp.reshape(x_d, (-1,))[: pat.n]
                if shift:
                    x_flat = x_flat + jnp.asarray(x0_i)
                x_full = jnp.pad(x_flat, (0, pat.nb - pat.n))
                ok = nrm_d <= tol * max(nrm0, 1e-300)
                xs.append(x_full)
                its.append(it_d.astype(jnp.int32))
                sts.append(
                    jnp.where(
                        ok,
                        jnp.int32(SUCCESS),
                        jnp.int32(NOT_CONVERGED),
                    )
                )
                fns.append(jnp.reshape(nrm_d, (1,)).astype(np.float64))
                ins.append(jnp.asarray([nrm0], dtype=np.float64))
                hs.append(jnp.asarray(hist))
                solved += 1
            with policy._lock:
                policy._sharded_groups += 1
                policy._solves += solved
            return SolveResult(
                x=jnp.stack(xs),
                iters=jnp.stack(its),
                status=jnp.stack(sts),
                final_norm=jnp.stack(fns),
                initial_norm=jnp.stack(ins),
                history=jnp.stack(hs),
            )

        def on_fetch(host, device_s):
            with policy._lock:
                policy._iters_total += int(
                    np.asarray(host.iters).sum()
                )

        return GroupPlan(
            fn=fn,
            put=np.asarray,  # host staging: fn partitions per shard
            zeros=lambda bb, nb, dtype: np.zeros((bb, nb), dtype),
            zeros_key=("dist", len(self.devices)),
            donate=False,
            device_label=f"dist{len(self.devices)}",
            on_fetch=on_fetch,
        )

    def warm(self, service, entry, Bb: int) -> None:
        if not self._eligible(entry, Bb):
            self._fallback.warm(service, entry, Bb)

    def evicted(self, entry) -> None:
        with self._lock:
            self._solvers.pop(entry.pattern.fingerprint, None)
        self._fallback.evicted(entry)

    def evict_signature(self, signature) -> None:
        self._fallback.evict_signature(signature)

    def describe(self) -> dict:
        return {
            "policy": self.name,
            "devices": len(self.devices),
            "axis": self.axis_name,
            "row_threshold": self.row_threshold,
            "outer": self.outer,
            "sparsify_theta": self.sparsify_theta,
        }

    def telemetry_snapshot(self) -> dict:
        """Registry source (kind="dist") -> the ``amgx_dist_*``
        families: per-level halo bytes and ghost rows, setup counts,
        collective accounting, consolidation level index."""
        with self._lock:
            return {
                "policy": self.name,
                "devices": len(self.devices),
                "row_threshold": self.row_threshold,
                "outer": self.outer,
                "sharded_groups_total": self._sharded_groups,
                "fallback_groups_total": self._fallback_groups,
                "sharded_solves_total": self._solves,
                "bypassed_builds_total": self._bypass_builds,
                "setups_total": self._setups,
                "setup_seconds_total": self._setup_s,
                "iterations_total": self._iters_total,
                "psum_sites_per_solve": self.psum_sites or 0,
                "consolidation_level": self._consolidation_level,
                "halo_exchange_bytes_per_cycle":
                    self._halo_bytes_cycle,
                "sparsify_dropped_total": sum(
                    s["dropped"] for s in self._sparsify_stats
                ),
                "levels": list(self._level_stats),
                "fingerprint": self._dist_fp,
            }
